"""Example 06 — SERVE a Llama-3-8B-class model on ONE 16 GB chip.

The deploy pipeline the reference never had (it has no inference path
at all, SURVEY.md §2), now behind the real serving layer: prune 25 % of
every block's FFN channels by weight-norm, quantize the matmul weights
to int4 (two values per byte, fused-unpack Pallas kernel on the decode
path), and serve the artifact through ``torchpruner_tpu.serve`` — a
continuous-batching engine (request scheduler + lane-aligned bucketed
KV allocator + prefill/decode disaggregation) decoding with a bf16 KV
cache.  Open-loop staggered arrivals exercise mid-run admission and
slot recycling; per-request TTFT and token gaps come back on the
request objects.

At the full 8B config the bf16 weights alone (~15 GB) do not fit one
chip's HBM; the int4 tree (~3.8 GB + bf16 embedding) does —
`experiments/llama8b_decode.py` measures that configuration on real
hardware; this example walks the same pipeline end-to-end at a small
scale so it runs anywhere in seconds.

Run: ``python examples/06_serve_8b_on_one_chip.py [--full]``
(``--full`` builds the real 8B config — needs a TPU-sized device).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--full", action="store_true",
                    help="the real 8B config (needs ~6 GB of HBM)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (like examples 01-03)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp

    from torchpruner_tpu.attributions import WeightNormAttributionMetric
    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.core.pruner import prune_by_scores
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.experiments.llama8b_decode import (
        logical_params,
        quantized_random_params,
        weight_bytes,
    )
    from torchpruner_tpu.models import llama
    from torchpruner_tpu.ops.quant import quantize_params
    from torchpruner_tpu.serve import (
        OpenLoopTraffic,
        ServeEngine,
        staggered_arrivals,
        synthetic_requests,
        vocab_of,
    )
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    if args.full:
        # the BASELINE Llama-3-8B: params built DIRECTLY at int4 (no
        # bf16 master is ever materialized) — prune composes at the
        # spec level for the throughput story; a trained checkpoint
        # would instead flow import -> prune -> fine-tune -> quantize
        model = llama(seq_len=256, ffn_dim=10752)  # 25% FFN pruned
        params, _ = quantized_random_params(model, bits=4)
        print(f"8B config (25% FFN pruned), int4: "
              f"{logical_params(params) / 1e9:.2f}B logical params, "
              f"{weight_bytes(params) / 1e9:.2f} GB weight bytes/step")
    else:
        # small scale, REAL pipeline: init -> score -> prune -> quantize
        model = llama(vocab_size=512, dim=64, depth=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, ffn_dim=128,
                      seq_len=64)
        params, _ = init_model(model, seed=0)
        for g in pruning_graph(model):
            if not g.target.endswith("/gate"):
                continue
            scores = WeightNormAttributionMetric(
                model, params, [], lm_cross_entropy_loss).run(g.target)
            res = prune_by_scores(model, params, g.target, scores,
                                  policy="fraction", fraction=0.25)
            model, params = res.model, res.params
        params = quantize_params(model, params, bits=4)
        params = jax.tree_util.tree_map(
            lambda a: (a.astype(jnp.bfloat16)
                       if hasattr(a, "dtype")
                       and jnp.issubdtype(a.dtype, jnp.floating) else a),
            params,
            is_leaf=lambda x: not isinstance(x, dict),
        )
        print(f"pruned 25% FFN + int4: "
              f"{logical_params(params):,} logical params, "
              f"{weight_bytes(params):,} weight bytes/step")

    # -- serve the pruned+quantized artifact -------------------------------
    # continuous batching: more requests than slots, staggered open-loop
    # arrivals -> mid-run admits and slot recycling; bf16 KV cache (half
    # the cache HBM — the serving config)
    slots, max_len = (8, 192) if args.full else (2, 64)
    n_req = slots * 3
    engine = ServeEngine(model, params, n_slots=slots, max_len=max_len,
                         cache_dtype=jnp.bfloat16)
    vocab = vocab_of(model)
    requests = synthetic_requests(
        n_req, vocab=vocab,
        prompt_lens=[8, 16, 12] if args.full else [4, 8, 6],
        max_new=[48, 64] if args.full else [12, 16], seed=0)
    traffic = OpenLoopTraffic(
        requests, staggered_arrivals(n_req, every_steps=4), by_step=True)

    t0 = time.perf_counter()
    summary = engine.run(traffic)
    wall = time.perf_counter() - t0
    print(f"served {summary['requests_completed']} requests "
          f"({summary['gen_tokens']} tokens) on {slots} slots in "
          f"{wall:.1f}s (incl. compile): "
          f"{summary['sustained_gen_tok_s']} gen tok/s steady, "
          f"TTFT p50 {summary['ttft_p50_ms']} ms / "
          f"p99 {summary['ttft_p99_ms']} ms, per-token p50 "
          f"{summary['token_p50_ms']} ms on "
          f"{jax.devices()[0].platform}")
    print(f"admits {summary['admits']}, evictions/slot-reuse "
          f"{summary['evictions']}")
    first = requests[0]
    print("request0 tokens[:8] =",
          np.asarray(first.tokens[:8], np.int32).tolist())


if __name__ == "__main__":
    main()

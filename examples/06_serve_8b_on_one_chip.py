"""Example 06 — serve a Llama-3-8B-class model on ONE 16 GB chip.

The deploy pipeline the reference never had (it has no inference path
at all, SURVEY.md §2): prune 25 % of every block's FFN channels by
weight-norm, quantize the matmul weights to int4 (two values per byte,
fused-unpack Pallas kernel on the decode path), and decode with a bf16
KV cache.  At the full 8B config the bf16 weights alone (~15 GB) do
not fit one chip's HBM; the int4 tree (~3.8 GB + bf16 embedding) does
— `experiments/llama8b_decode.py` measures that configuration on real
hardware; this example walks the same pipeline end-to-end at a small
scale so it runs anywhere in seconds.

Run: ``python examples/06_serve_8b_on_one_chip.py [--full]``
(``--full`` builds the real 8B config — needs a TPU-sized device).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--full", action="store_true",
                    help="the real 8B config (needs ~6 GB of HBM)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (like examples 01-03)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp

    import torchpruner_tpu as tp
    from torchpruner_tpu.attributions import WeightNormAttributionMetric
    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.core.pruner import prune_by_scores
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.experiments.llama8b_decode import (
        logical_params,
        quantized_random_params,
        weight_bytes,
    )
    from torchpruner_tpu.generate import generate
    from torchpruner_tpu.models import llama
    from torchpruner_tpu.ops.quant import quantize_params
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    if args.full:
        # the BASELINE Llama-3-8B: params built DIRECTLY at int4 (no
        # bf16 master is ever materialized) — prune composes at the
        # spec level for the throughput story; a trained checkpoint
        # would instead flow import -> prune -> fine-tune -> quantize
        model = llama(seq_len=256, ffn_dim=10752)  # 25% FFN pruned
        params, _ = quantized_random_params(model, bits=4)
        print(f"8B config (25% FFN pruned), int4: "
              f"{logical_params(params) / 1e9:.2f}B logical params, "
              f"{weight_bytes(params) / 1e9:.2f} GB weight bytes/step")
    else:
        # small scale, REAL pipeline: init -> score -> prune -> quantize
        model = llama(vocab_size=512, dim=64, depth=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, ffn_dim=128,
                      seq_len=64)
        params, _ = init_model(model, seed=0)
        for g in pruning_graph(model):
            if not g.target.endswith("/gate"):
                continue
            scores = WeightNormAttributionMetric(
                model, params, [], lm_cross_entropy_loss).run(g.target)
            res = prune_by_scores(model, params, g.target, scores,
                                  policy="fraction", fraction=0.25)
            model, params = res.model, res.params
        params = quantize_params(model, params, bits=4)
        params = jax.tree_util.tree_map(
            lambda a: (a.astype(jnp.bfloat16)
                       if hasattr(a, "dtype")
                       and jnp.issubdtype(a.dtype, jnp.floating) else a),
            params,
            is_leaf=lambda x: not isinstance(x, dict),
        )
        print(f"pruned 25% FFN + int4: "
              f"{logical_params(params):,} logical params, "
              f"{weight_bytes(params):,} weight bytes/step")

    B, S, n_new = (8, 64, 64) if args.full else (2, 8, 16)
    prompt = jnp.zeros((B, S), jnp.int32)
    t0 = time.perf_counter()
    toks = generate(model, params, prompt, n_new,
                    cache_dtype=jnp.bfloat16)
    jax.block_until_ready(toks)
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    toks = generate(model, params, prompt, n_new,
                    cache_dtype=jnp.bfloat16)
    jax.block_until_ready(toks)
    steady = time.perf_counter() - t0
    print(f"decoded {B}×{n_new} tokens: first call {first:.1f}s "
          f"(compile), steady {steady:.3f}s "
          f"({B * n_new / steady:.0f} gen tok/s) on "
          f"{jax.devices()[0].platform}")
    print("tokens[0,:8] =", np.asarray(toks)[0, :8].tolist())


if __name__ == "__main__":
    main()

"""Example 5 — beyond the reference: the distributed prune-train loop.

The reference runs everything on one GPU in one process; this framework's
north star (SURVEY.md §2.11, BASELINE.json) is the same loop on TPU pods.
This script demonstrates the full scale path on a virtual 8-device CPU
mesh — the exact code that runs on real chips, exercised the same way
``__graft_entry__.dryrun_multichip`` validates it every round:

1. a ``{data: 2, model: 2}`` mesh: Llama decoder trained with the batch
   sharded over ``data`` and params column/row-split over ``model``
   (tensor parallelism derived from the pruning graph),
2. distributed attribution scoring (per-example score rows psum-reduced
   across the mesh), followed by a structured FFN prune + reshard +
   continued training at the new shapes,
3. the same architecture (fresh params) pipelined over a ``{pp: 4}``
   axis with the collective-based SPMD formulation
   (``parallel/pp_spmd.py``) — stacked blocks, ``lax.ppermute`` between
   stages,
4. a ``{pp: 2, data: 2}`` 2-D mesh: pipeline and data parallelism
   composed in one program — the first-step loss must equal step 3's
   (same params, same batch, different mesh layout), asserted.

Runs in a couple of minutes on CPU.  On a pod, replace the virtual
devices with ``initialize_distributed()`` + the real mesh — nothing else
changes (tests/test_multiprocess.py proves the 2-process wiring).

Run::

    python examples/05_distributed_prune_train.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    argparse.ArgumentParser(description=__doc__.splitlines()[0]).parse_args()
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import optax

    import torchpruner_tpu as tp
    from torchpruner_tpu.core.pruner import prune_by_scores
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.parallel import (
        DistributedScorer,
        ShardedTrainer,
        make_mesh,
        pp_spmd_train_step,
    )
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    devices = jax.devices()
    print(f"devices: {len(devices)} × {devices[0].platform}")

    # -- 1) DP×TP training ------------------------------------------------
    model = llama_tiny(depth=4)
    mesh = make_mesh({"data": 2, "model": 2}, devices=devices[:4])
    trainer = ShardedTrainer.create(
        model, optax.adam(1e-3), lm_cross_entropy_loss, mesh,
        seed=0, min_shard_size=0, partition="tp",
    )
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 256, size=(8, 16)).astype(np.int32)
    for step in range(3):
        loss = float(trainer.step(toks, toks))
    print(f"1) DP×TP train ok (loss {loss:.4f} on mesh "
          f"{dict(zip(mesh.axis_names, mesh.devices.shape))})")

    # -- 2) score → prune → reshard → keep training -----------------------
    metric = tp.TaylorAttributionMetric(
        trainer.model, trainer.params, [(toks, toks)],
        lm_cross_entropy_loss, state=trainer.state,
    )
    # score rows computed SPMD over the mesh's data axis (psum-reduced)
    scores = DistributedScorer(metric, mesh).run("block1_ffn/gate")
    res = prune_by_scores(
        trainer.model, trainer.params, "block1_ffn/gate", scores,
        policy="fraction", fraction=0.25,
        state=trainer.state, opt_state=trainer.opt_state,
    )
    trainer = trainer.rebuild(res.model, res.params, res.state,
                              res.opt_state)
    loss_pruned = float(trainer.step(toks, toks))
    print(f"2) scored + pruned 25% of block1 FFN, resharded, trained "
          f"(loss {loss_pruned:.4f}, widths {res.model.layer('block1_ffn/gate').features})")

    # -- 3) SPMD pipeline over 4 stages -----------------------------------
    pp_mesh = make_mesh({"pp": 4}, devices=devices[:4])
    step_pp = pp_spmd_train_step(
        model, optax.adam(1e-3), lm_cross_entropy_loss,
        mesh=pp_mesh, n_microbatches=4,
    )
    params, _ = tp.init_model(model, seed=0)
    opt_state = optax.adam(1e-3).init(params)
    params, opt_state, loss_spmd = step_pp(params, opt_state, toks)
    print(f"3) SPMD pipeline (4 stages, ppermute streaming) train ok "
          f"(loss {float(loss_spmd):.4f})")

    # -- 4) PP × DP on a 2-D mesh -----------------------------------------
    mesh2d = make_mesh({"pp": 2, "data": 2}, devices=devices[:4])
    step_2d = pp_spmd_train_step(
        model, optax.adam(1e-3), lm_cross_entropy_loss,
        mesh=mesh2d, n_microbatches=2, data_axis="data",
    )
    params, _ = tp.init_model(model, seed=0)
    params, _, loss_2d = step_2d(params, optax.adam(1e-3).init(params), toks)
    assert abs(float(loss_2d) - float(loss_spmd)) < 1e-4, (loss_2d, loss_spmd)
    print(f"4) PP×DP composed on a 2-D mesh ok (loss {float(loss_2d):.4f} "
          f"== step 3's, asserted)")

    # -- 5) interleaved schedule (V=2 chunks per stage) -------------------
    step_il = pp_spmd_train_step(
        model, optax.adam(1e-3), lm_cross_entropy_loss,
        mesh=mesh2d, n_microbatches=2, data_axis="data", interleave=2,
    )
    params, _ = tp.init_model(model, seed=0)
    params, _, loss_il = step_il(params, optax.adam(1e-3).init(params), toks)
    assert abs(float(loss_il) - float(loss_spmd)) < 1e-4, (loss_il, loss_spmd)
    print(f"5) Megatron interleaved schedule (V=2, wrap-around ppermute) "
          f"ok (loss {float(loss_il):.4f} == step 3's, asserted)")


if __name__ == "__main__":
    main()

"""Example 1 — the reference's "Attributions comparison (Max model)"
notebook, as a script.

A hand-weighted 2->4->1 ReLU net computes ``max(x1, x2)``; the ground-truth
relevance of each hidden unit is known analytically, so the attribution
methods can be compared against truth (reference notebook 1 / paper Fig. 1).

Run::

    python examples/01_attributions_comparison.py [--cpu]
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

from torchpruner_tpu.experiments.max_comparison import run_max_comparison

if __name__ == "__main__":
    results = run_max_comparison(verbose=True)
    print()
    print(f"{'method':<14} {'A':>8} {'B':>8} {'C':>8} {'D':>8}")
    for method, scores in results.items():
        vals = " ".join(f"{v:8.3f}" for v in scores)
        print(f"{method:<14} {vals}")
    print(
        "\nGround truth: units A/B carry max's two arms, C carries the "
        "shared baseline, D is dead — Shapley attributes "
        "[0.37, 0.37, 1.7, 0.0] (reference tests/test_attributions.py)."
    )

"""Example 3 — the reference's "CIFAR-10 - VGG16 - Layerwise robustness"
notebook, as a script.

Train a model with the reference's recipe (or restore a checkpoint), then
for every prunable layer x all 8 attribution methods simulate pruning by
zeroing units in ascending-score order, logging test loss per removal; the
per-method AUC summary ranks the methods (reference: SV variants best,
signed Taylor worst; 6.5 h on a 2020 GPU for VGG16 — minutes here at
digits scale, and `--preset vgg16_layerwise` for the full-size recipe).

Run::

    python examples/03_layerwise_robustness.py [--cpu] [model:dataset]
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

from torchpruner_tpu.experiments.parity import run_trained_robustness_parity

if __name__ == "__main__":
    spec = next(
        (a for a in sys.argv[1:] if ":" in a), "digits_convnet:digits"
    )
    model_name, dataset = spec.split(":")
    # one seed for the demo (the parity suite's PARITY.md rows use 3)
    out = run_trained_robustness_parity(model_name, dataset, seeds=(0,),
                                        verbose=True)
    print(f"\ntrained {model_name} test acc {out['test_acc']:.2%}")
    print(f"{'method':<14} AUC (loss increase per removed unit)")
    for m, v in sorted(out["aucs"].items(), key=lambda kv: kv[1]):
        print(f"{m:<14} {v:.4f}")

"""Example 4 — beyond the reference: prune a causal LM and serve it.

The reference is vision-only; this framework extends the same
attribution→prune loop to the LM families (BASELINE configs 3-5) and adds
the serving path the reference never had.  This script:

1. trains a miniature Llama (GQA + RoPE + SwiGLU) briefly on token data,
2. scores one block's FFN channels with Taylor attribution on the LM loss,
3. prunes the lowest-scoring fraction (optimizer state sliced too),
4. fine-tunes a few steps at the new shapes (one recompile), and
5. generates from BOTH models with the KV-cache decoder — same prompt,
   pruned model decoding at its pruned shapes.

Runs in about a minute on CPU.

Run::

    python examples/04_prune_llm_and_generate.py [--cpu]
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np
import optax

import torchpruner_tpu as tp
from torchpruner_tpu.data import load_dataset
from torchpruner_tpu.models import llama_tiny
from torchpruner_tpu.train.loop import Trainer
from torchpruner_tpu.utils.flops import param_count
from torchpruner_tpu.utils.losses import lm_cross_entropy_loss


def main():
    model = llama_tiny()
    data = load_dataset("lm_tiny", "train", n=512)
    batches = data.batches(64)

    trainer = Trainer.create(
        model, optax.adam(1e-3), lm_cross_entropy_loss, seed=0
    )
    for epoch in range(3):
        for x, _ in batches:
            loss = trainer.step(x, x)
    print(f"trained: loss {float(loss):.4f}, "
          f"params {param_count(trainer.params):,}")

    # score one block's FFN gate channels on the LM loss (per-example
    # rows first, mean reduction — the reference's attribution contract)
    target = "block1_ffn/gate"
    metric = tp.TaylorAttributionMetric(
        trainer.model, trainer.params, [(x, x) for x, _ in batches[:4]],
        lm_cross_entropy_loss, state=trainer.state,
    )
    scores = metric.run(target)
    # COPY the trained dense params: pruning shares buffers for untouched
    # layers, and the fine-tune step donates its inputs — generating from
    # a plain reference after fine-tuning would hit deleted arrays
    import jax
    import jax.numpy as jnp

    dense_model = trainer.model
    dense_params = jax.tree.map(jnp.copy, trainer.params)
    res = tp.prune_by_scores(
        trainer.model, trainer.params, target, scores,
        policy="fraction", fraction=0.25,
        state=trainer.state, opt_state=trainer.opt_state,
    )
    print(f"pruned {target}: {len(scores)} -> "
          f"{res.model.widths()[target]} channels, "
          f"params {param_count(res.params):,}")

    # fine-tune at the new shapes (ONE recompile — the XLA-honest
    # equivalent of the reference's in-place surgery)
    trainer = trainer.rebuild(res.model, res.params, res.state,
                              res.opt_state)
    for x, _ in batches:
        loss = trainer.step(x, x)
    print(f"fine-tuned: loss {float(loss):.4f}")

    # serve both: one-shot prefill + KV-cache decode; the pruned model
    # decodes at its pruned shapes, next to the trained dense model it
    # was cut from
    prompt = np.asarray(data.x[:2, :8], np.int32)
    out_pruned = tp.generate(trainer.model, trainer.params, prompt, 16)
    out_dense = tp.generate(dense_model, dense_params, prompt, 16)
    print(f"prompt:       {prompt[0].tolist()}")
    print(f"pruned model: {np.asarray(out_pruned)[0].tolist()}")
    print(f"dense model:  {np.asarray(out_dense)[0].tolist()}")

    # deploy step: int8 weight-only quantization (decode reads every
    # param per generated token — on TPU the weight bytes are the
    # bottleneck, and int8 halves them vs bf16).  Quantize AFTER
    # pruning; generation runs directly on the QTensor params.
    qparams = tp.quantize_params(trainer.model, trainer.params)
    out_q = tp.generate(trainer.model, qparams, prompt, 16)
    print(f"pruned+int8:  {np.asarray(out_q)[0].tolist()}")


if __name__ == "__main__":
    main()

"""Example 2 — the reference's "Pruning Untrained Networks" notebook, as a
script.

An UNTRAINED FC net is scored with Monte-Carlo Shapley on validation data;
removing every negative-attribution unit (outermost layer first) lifts test
accuracy far above chance with no training at all (reference: MNIST
7.16% -> 50.94%).  Runs on the bundled real sklearn digits by default;
point it at MNIST once ``data/prepare.py`` has ingested the distribution
files.

Run::

    python examples/02_prune_untrained_network.py [--cpu] [model:dataset]
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

from torchpruner_tpu.experiments.parity import run_untrained_prune_parity

if __name__ == "__main__":
    spec = next(
        (a for a in sys.argv[1:] if ":" in a), "digits_fc:digits_flat"
    )
    model_name, dataset = spec.split(":")
    out = run_untrained_prune_parity(model_name, dataset, verbose=True)
    print(
        f"\n{dataset}: accuracy {out['acc_before']:.2%} -> "
        f"{out['acc_after']:.2%}, params {out['params_before']:,} -> "
        f"{out['params_after']:,} in {out['prune_seconds']:.1f}s"
    )

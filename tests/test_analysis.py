"""tpu-lint tests: golden corruptions of known-good plans (each distinct
failure mode must fire its exact finding, across MLP / conv / llama
families), sharding hazards on an abstract mesh, jaxpr hazards, the
``apply_plan`` pre-flight, the ``shard_params`` warning, the CLI exit
codes, and the all-presets sweep."""

import dataclasses
import json
import logging

import pytest

import jax
import jax.numpy as jnp

from torchpruner_tpu.analysis import (
    abstract_trees,
    lint_jaxpr,
    lint_model_plans,
    lint_plan,
    lint_preset,
    lint_sharding,
    lint_step,
    severity_config,
)
from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.graph import group_for
from torchpruner_tpu.core.plan import (
    PlanError,
    apply_plan,
    plan_from_dict,
    plan_to_dict,
)
from torchpruner_tpu.core.pruner import plan_for_group
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.experiments.presets import preset_names
from torchpruner_tpu.models import digits_convnet, digits_fc, llama_tiny


def checks(findings):
    return [f.check for f in findings]


#: (model ctor, a prunable target with a consumer) per family
FAMILIES = [
    (digits_fc, "fc1"),                      # MLP
    (digits_convnet, "conv1"),               # conv (+BN, flatten fan-out)
    (llama_tiny, "block1_ffn/gate"),         # llama (GLU + down consumer)
]


@pytest.mark.parametrize("ctor,target", FAMILIES, ids=["mlp", "conv", "llama"])
def test_known_good_plans_lint_clean(ctor, target):
    model = ctor()
    assert lint_model_plans(model) == []
    # and the specific target's plan too
    params, state = abstract_trees(model)
    plan = plan_for_group(model, group_for(model, target))
    assert lint_plan(plan, params, state) == []


def _corrupt(plan, i, **changes):
    """Replace slice ``i`` of a plan with a mutated copy."""
    slices = list(plan.slices)
    slices[i] = dataclasses.replace(slices[i], **changes)
    return dataclasses.replace(plan, slices=tuple(slices))


@pytest.mark.parametrize("ctor,target", FAMILIES, ids=["mlp", "conv", "llama"])
def test_golden_corruptions_fire_exact_findings(ctor, target):
    """Each distinct corruption of a known-good plan fires exactly its
    finding — the golden contract of the plan-lint pass."""
    model = ctor()
    params, state = abstract_trees(model)
    plan = plan_for_group(model, group_for(model, target))

    # bad pytree path
    bad = _corrupt(plan, 0, path=("definitely", "missing"))
    assert checks(lint_plan(bad, params, state)) == ["plan/missing-path"]

    # axis out of range
    bad = _corrupt(plan, 0, axis=9)
    assert checks(lint_plan(bad, params, state)) == ["plan/axis-out-of-range"]

    # fan_out that does not divide the axis
    bad = _corrupt(plan, 0, fan_out=7)
    assert checks(lint_plan(bad, params, state)) == ["plan/fanout-indivisible"]

    # consumer unit count disagreeing with the producer's
    consumer_i = len(plan.slices) - 1  # consumers are appended last
    bad = dataclasses.replace(plan, n_units=plan.n_units - 1)
    got = checks(lint_plan(bad, params, state))
    assert got and set(got) == {"plan/unit-count-mismatch"}
    assert consumer_i < len(plan.slices)

    # two slices overlapping on the same (path, axis)
    bad = dataclasses.replace(
        plan, slices=plan.slices + (plan.slices[0],)
    )
    assert checks(lint_plan(bad, params, state)) == [
        "plan/overlapping-slices"
    ]


def test_missing_state_collection_is_an_error_only_when_required():
    model = digits_convnet()
    params, state = abstract_trees(model)
    plan = plan_for_group(model, group_for(model, "conv1"))
    # conv1's group drags BatchNorm running stats along -> state required
    got = checks(lint_plan(plan, params, None))
    assert got and set(got) == {"plan/missing-collection"}
    assert lint_plan(plan, params, state) == []


# ---------------------------------------------------------------------------
# sharding lint
# ---------------------------------------------------------------------------


def test_gqa_breaking_head_prune_is_an_error():
    """llama_tiny: 4 query heads on 2 KV heads.  Dropping both heads of
    KV group 1 leaves KV head 1 with zero query heads — head-axis TP
    sharding would misalign; the analyzer must say so."""
    model = llama_tiny()
    fs = lint_sharding(
        model, {"data": 1, "model": 2}, partition="tp",
        targets=["block1_attn/attn"],
        drops={"block1_attn/attn": [2, 3]}, min_size=4,
    )
    assert "sharding/gqa-indivisible" in checks(fs)
    [f] = [x for x in fs if x.check == "sharding/gqa-indivisible"]
    assert f.severity == "error" and f.path == "block1_attn/attn"


def test_even_gqa_head_prune_is_clean():
    """Dropping one head per KV group keeps the grouping even — no
    error."""
    model = llama_tiny()
    fs = lint_sharding(
        model, {"data": 1, "model": 2}, partition="tp",
        targets=["block1_attn/attn"],
        drops={"block1_attn/attn": [0, 3]}, min_size=4,
    )
    assert "sharding/gqa-indivisible" not in checks(fs)


def test_replication_fallback_reported_after_prune():
    """A Dense whose width stops dividing the mesh silently replicates —
    the analyzer names the arrays."""
    model = SegmentedModel(
        layers=(
            L.Dense("fc1", 128, use_bias=False),
            L.Activation("act", "relu"),
            L.Dense("fc2", 4, use_bias=False),
        ),
        input_shape=(17,),
    )
    fs = lint_sharding(
        model, {"model": 2}, partition="fsdp", targets=["fc1"],
        drops={"fc1": [0]}, min_size=4,
    )
    found = [f for f in fs if f.check == "sharding/replicated-fallback"]
    # fc1/w (17, 127): no dim divides 2 any more; fc2/w (127, 4) -> 4 ok
    assert [f.path for f in found] == ["fc1/w"]
    assert found[0].severity == "warning"
    # pre-prune everything was fine
    clean = lint_sharding(
        model, {"model": 2}, partition="fsdp", targets=["fc1"],
        drops={"fc1": []}, min_size=4,
    )
    assert "sharding/replicated-fallback" not in checks(clean)


def test_hbm_delta_info_present_and_shrinks():
    model = llama_tiny()
    fs = lint_sharding(
        model, {"model": 2}, targets=["block1_ffn/gate"],
        drops={"block1_ffn/gate": list(range(32))}, min_size=4,
    )
    [f] = [x for x in fs if x.check == "sharding/hbm-delta"]
    assert f.severity == "info" and "-" in f.message  # negative delta


# ---------------------------------------------------------------------------
# jaxpr lint
# ---------------------------------------------------------------------------


def test_clean_bf16_train_step_has_no_findings():
    import optax

    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    fs = lint_step(
        llama_tiny(), lm_cross_entropy_loss, tx=optax.adam(1e-3),
        compute_dtype=jnp.bfloat16,
    )
    assert fs == []


def test_float64_in_trace_is_an_error():
    import jax.experimental

    with jax.experimental.enable_x64():
        cj = jax.make_jaxpr(lambda x: x.astype(jnp.float64) * 2.0)(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        )
    assert "jaxpr/float64" in checks(lint_jaxpr(cj))


def test_promoted_matmul_under_bf16_policy_is_flagged():
    # an f32 weight leaks into a program whose policy says bf16
    mixed = jax.make_jaxpr(lambda x, w: x @ w)(
        jax.ShapeDtypeStruct((2, 8), jnp.bfloat16),
        jax.ShapeDtypeStruct((8, 4), jnp.float32),
    )
    assert "jaxpr/mixed-precision-matmul" in checks(
        lint_jaxpr(mixed, compute_dtype=jnp.bfloat16)
    )
    # a fully-promoted (all-f32) contraction under a bf16 policy
    promoted = jax.make_jaxpr(lambda x, w: x @ w)(
        jax.ShapeDtypeStruct((2, 8), jnp.float32),
        jax.ShapeDtypeStruct((8, 4), jnp.float32),
    )
    assert "jaxpr/promoted-matmul" in checks(
        lint_jaxpr(promoted, compute_dtype=jnp.bfloat16)
    )
    # the same programs under an f32 policy are what was asked for
    assert lint_jaxpr(mixed, compute_dtype=jnp.float32) == []
    assert lint_jaxpr(promoted, compute_dtype=jnp.float32) == []


def test_quant_dtype_drift_is_flagged():
    def serve(x, q):
        return x @ q.astype(jnp.float32)  # dequantize to the WRONG dtype

    cj = jax.make_jaxpr(serve)(
        jax.ShapeDtypeStruct((2, 8), jnp.bfloat16),
        jax.ShapeDtypeStruct((8, 4), jnp.int8),
    )
    assert "jaxpr/quant-dtype-drift" in checks(
        lint_jaxpr(cj, compute_dtype=jnp.bfloat16)
    )


def test_closed_over_concrete_array_is_flagged():
    big = jnp.ones((64, 64))
    cj = jax.make_jaxpr(lambda x: x @ big)(
        jax.ShapeDtypeStruct((2, 64), jnp.float32)
    )
    assert "jaxpr/const-capture" in checks(lint_jaxpr(cj))
    # small scalars (eps constants etc.) stay silent
    small = jnp.float32(1e-5)
    cj2 = jax.make_jaxpr(lambda x: x + small)(
        jax.ShapeDtypeStruct((4,), jnp.float32)
    )
    assert lint_jaxpr(cj2) == []


# ---------------------------------------------------------------------------
# integration points
# ---------------------------------------------------------------------------


def test_apply_plan_preflight_raises_descriptive_plan_error():
    model = digits_fc()
    params, state = init_model(model)
    plan = plan_for_group(model, group_for(model, "fc1"))
    bad = _corrupt(plan, 0, path=("fc9", "w"))
    with pytest.raises(PlanError) as ei:
        apply_plan(bad, [0], params, state=state)
    msg = str(ei.value)
    assert "fc9/w" in msg and "plan/missing-path" in msg
    assert isinstance(ei.value, ValueError)  # catchable as before

    # bad axis names the axis and the shape
    bad = _corrupt(plan, 0, axis=6)
    with pytest.raises(PlanError, match="axis 6"):
        apply_plan(bad, [0], params, state=state)

    # the good plan still applies
    p2, s2, _ = apply_plan(plan, [0], params, state=state)
    assert p2["fc1"]["w"].shape[1] == params["fc1"]["w"].shape[1] - 1


def test_shard_params_warns_once_on_replication_fallback(caplog):
    from torchpruner_tpu.parallel.mesh import make_mesh
    from torchpruner_tpu.parallel.sharding import shard_params

    mesh = make_mesh({"model": len(jax.devices())})
    tree = {"w": jnp.ones((33, 513)), "small": jnp.ones((2,))}
    with caplog.at_level(logging.INFO, logger="torchpruner_tpu"):
        shard_params(tree, mesh, min_size=4)
    msgs = [r.message for r in caplog.records]
    assert any(
        "sharding/replicated-fallback" in m and "w (33, 513)" in m
        for m in msgs
    )
    assert not any("small" in m for m in msgs)

    # downgradeable through the analyzer's severity config
    caplog.clear()
    severity_config.overrides["sharding/replicated-fallback"] = "ignore"
    try:
        with caplog.at_level(logging.DEBUG, logger="torchpruner_tpu"):
            shard_params(tree, mesh, min_size=4)
        assert not caplog.records
    finally:
        severity_config.overrides.pop("sharding/replicated-fallback")


def test_severity_override_also_silences_apply_plan_preflight():
    """One knob for both halves: a check downgraded below error in the
    severity config must stop the inline pre-flight from raising too."""
    model = digits_convnet()
    params, state = init_model(model)
    plan = plan_for_group(model, group_for(model, "conv1"))
    with pytest.raises(PlanError):  # state required but not given
        apply_plan(plan, [0], params, state=None)
    severity_config.overrides["plan/missing-collection"] = "warning"
    try:
        p2, _, _ = apply_plan(plan, [0], params, state=None)
        assert p2["conv1"]["w"].shape[3] == params["conv1"]["w"].shape[3] - 1
    finally:
        severity_config.overrides.pop("plan/missing-collection")


def test_lint_config_with_broken_plan_reports_instead_of_crashing():
    """A mesh config whose plan lint finds errors must still produce a
    report (the sharding simulation of a broken plan is skipped, not
    attempted and crashed)."""
    from torchpruner_tpu.analysis import lint_config
    from torchpruner_tpu.utils.config import ExperimentConfig

    model = digits_fc()
    plan = plan_for_group(model, group_for(model, "fc1"))
    bad = _corrupt(plan, 0, path=("fc9", "w"))
    cfg = ExperimentConfig(name="broken", model="digits_fc",
                           mesh={"model": 2})
    report = lint_config(cfg, model=model, plans=[bad], jaxpr=False)
    assert not report.ok
    assert [f.check for f in report.errors] == ["plan/missing-path"]
    # and no sharding findings: the pass was skipped, not crashed
    assert not any(f.lint == "sharding" for f in report.findings)


def test_cli_lint_plan_without_lint_is_rejected():
    from torchpruner_tpu.__main__ import main

    with pytest.raises(SystemExit):
        main(["--preset", "vgg16_digits32_layerwise", "--smoke",
              "--lint-plan", "whatever.json"])


def test_severity_overrides_regrade_report_findings():
    model = llama_tiny()
    severity_config.overrides["sharding/gqa-indivisible"] = "warning"
    try:
        from torchpruner_tpu.analysis.findings import merge_reports

        fs = lint_sharding(
            model, {"data": 1, "model": 2}, partition="tp",
            targets=["block1_attn/attn"],
            drops={"block1_attn/attn": [2, 3]}, min_size=4,
        )
        report = merge_reports("t", fs)
        assert report.ok  # the error was regraded to warning
        assert any(
            f.check == "sharding/gqa-indivisible" for f in report.warnings
        )
    finally:
        severity_config.overrides.pop("sharding/gqa-indivisible")


# ---------------------------------------------------------------------------
# CLI + preset sweep
# ---------------------------------------------------------------------------


def test_cli_lint_clean_preset_exits_zero(capsys):
    from torchpruner_tpu.__main__ import main

    assert main(["--lint", "vgg16_digits32_layerwise", "--smoke"]) == 0
    out = capsys.readouterr().out
    assert "tpu-lint" in out and "0 error(s)" in out


def test_cli_lint_corrupted_plan_exits_nonzero(tmp_path, capsys):
    from torchpruner_tpu.__main__ import main

    model = digits_fc()
    plan = plan_for_group(model, group_for(model, "fc1"))
    d = plan_to_dict(_corrupt(plan, 0, path=("fc9", "w")))
    path = tmp_path / "bad_plan.json"
    path.write_text(json.dumps(d))
    assert main([
        "--lint", "vgg16_digits32_layerwise", "--smoke",
        "--lint-plan", str(path),
    ]) == 1
    out = capsys.readouterr().out
    assert "plan/missing-path" in out and "fc9/w" in out
    # round-trip sanity: the uncorrupted plan comes back equal
    assert plan_from_dict(plan_to_dict(plan)) == plan


def test_lint_sweep_all_presets_smoke():
    """Every shipped preset (smoke variants) must lint with zero
    error-severity findings — the CI gate of the analyzer."""
    for name in preset_names():
        report = lint_preset(name, smoke=True)
        assert report.ok, f"{name}: {report.format()}"


def test_lint_sweep_all_presets_full():
    """Full-size presets (8B llama on its 64-chip mesh included) lint
    clean too — entirely abstract, no devices (slow lane)."""
    for name in preset_names():
        report = lint_preset(name, smoke=False)
        assert report.ok, f"{name}: {report.format()}"

"""Mask-based simulated pruning: the plan-derived masks must reproduce a
real structural prune's forward exactly (eval mode), stay pinned at zero
through training via the optax transform, and materialize into the same
model with one final prune()."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.masking import apply_masks, drop_masks, masked_update
from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.models import digits_convnet
from torchpruner_tpu.utils.losses import cross_entropy_loss


def fc():
    return SegmentedModel(
        (L.Dense("fc1", 16), L.Activation("r1", "relu"),
         L.Dense("fc2", 12), L.Activation("r2", "relu"),
         L.Dense("out", 4)),
        (8,),
    )


def test_masked_forward_equals_pruned_forward_fc():
    model = fc()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
    drops = {"fc1": [0, 5, 9], "fc2": [3]}

    pm, _ = drop_masks(model, params, drops)
    y_masked, _ = model.apply(apply_masks(params, pm), x)

    res_model, res_params = model, params
    res_state = state
    for layer, d in drops.items():
        r = prune(res_model, res_params, layer, d, state=res_state)
        res_model, res_params, res_state = r.model, r.params, r.state
    y_pruned, _ = res_model.apply(res_params, x, state=res_state)
    np.testing.assert_allclose(
        np.asarray(y_masked), np.asarray(y_pruned), atol=1e-5
    )


def test_masked_forward_equals_pruned_forward_conv_bn_flatten():
    """Conv channel masks must null the BN scale/bias/stats AND the strided
    flatten fan-out rows of the dense consumer — the full plan."""
    model = digits_convnet()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 8, 1))
    drops = {"conv2": [1, 7, 30]}

    pm, sm = drop_masks(model, params, drops, state=state)
    y_masked, _ = model.apply(
        apply_masks(params, pm), x, state=apply_masks(state, sm)
    )
    r = prune(model, params, "conv2", drops["conv2"], state=state)
    y_pruned, _ = r.model.apply(r.params, x, state=r.state)
    np.testing.assert_allclose(
        np.asarray(y_masked), np.asarray(y_pruned), atol=1e-5
    )


def test_masked_training_pins_zeros_and_materializes():
    """Chained after adam, masked entries stay exactly zero across steps
    (no recompile between sparsity experiments); the final structural
    prune of the masked model matches pruning + the same training."""
    model = fc()
    params, _ = init_model(model, seed=0)
    drops = {"fc1": [2, 11]}
    pm, _ = drop_masks(model, params, drops)
    tx = optax.chain(optax.adam(1e-2), masked_update(pm))
    params = apply_masks(params, pm)
    opt_state = tx.init(params)

    x = jax.random.normal(jax.random.PRNGKey(3), (8, 8))
    y = jnp.arange(8) % 4

    @jax.jit
    def step(p, o):
        def loss(p_):
            out, _ = model.apply(p_, x)
            return jnp.mean(cross_entropy_loss(out, y))

        g = jax.grad(loss)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o

    for _ in range(5):
        params, opt_state = step(params, opt_state)

    w = np.asarray(params["fc1"]["w"])
    b = np.asarray(params["fc1"]["b"])
    assert np.all(w[:, [2, 11]] == 0.0) and np.all(b[[2, 11]] == 0.0)
    assert np.all(np.asarray(params["fc2"]["w"])[[2, 11], :] == 0.0)
    # surviving entries DID train
    assert np.any(w[:, [0, 1]] != 0.0)

    # materialize: prune away the masked units; forward unchanged
    xt = jax.random.normal(jax.random.PRNGKey(4), (4, 8))
    y_masked, _ = model.apply(params, xt)
    r = prune(model, params, "fc1", drops["fc1"])
    y_final, _ = r.model.apply(r.params, xt)
    np.testing.assert_allclose(
        np.asarray(y_masked), np.asarray(y_final), atol=1e-5
    )


def test_simulated_prune_retrain_matches_structural_accuracy():
    """cfg.simulate runs the same prune loop with masks — the per-step
    post-prune test accuracy must equal the structural run's (same
    policy, same plan), with no shape change anywhere."""
    from torchpruner_tpu.data import synthetic_dataset
    from torchpruner_tpu.experiments.prune_retrain import run_prune_retrain
    from torchpruner_tpu.utils.config import ExperimentConfig

    datasets = tuple(
        synthetic_dataset((16,), 4, 96, seed=s) for s in (0, 1, 2)
    )
    model = SegmentedModel(
        (L.Dense("fc1", 16), L.Activation("r1", "relu"),
         L.Dense("fc2", 12), L.Activation("r2", "relu"),
         L.Dense("out", 4)),
        (16,),
    )
    import os

    kw = dict(
        name="sim", dataset="synthetic", method="weight_norm",
        policy="fraction", fraction=0.25, score_examples=64,
        eval_batch_size=32, log_path=os.devnull,
    )
    hist_real = run_prune_retrain(
        ExperimentConfig(**kw), model=model, datasets=datasets,
        verbose=False,
    )
    hist_sim = run_prune_retrain(
        ExperimentConfig(**kw, simulate=True), model=model,
        datasets=datasets, verbose=False,
    )
    assert len(hist_real) == len(hist_sim) == 2
    for r, s in zip(hist_real, hist_sim):
        assert r.layer == s.layer and r.n_dropped == s.n_dropped
        np.testing.assert_allclose(r.post_acc, s.post_acc, atol=1e-6)
        np.testing.assert_allclose(r.post_loss, s.post_loss, atol=1e-5)

    # simulate + finetune is a config error (masks would regrow)
    with pytest.raises(ValueError, match="masked_update"):
        ExperimentConfig(**kw, simulate=True, finetune_epochs=1)


def test_simulated_prune_over_mesh_runs():
    """simulate composes with the SPMD loop: masked (sharded) params keep
    their shardings, so the compiled step is reused across the sweep."""
    from torchpruner_tpu.data import synthetic_dataset
    from torchpruner_tpu.experiments.prune_retrain import run_prune_retrain
    from torchpruner_tpu.utils.config import ExperimentConfig

    datasets = tuple(
        synthetic_dataset((16,), 4, 64, seed=s) for s in (0, 1, 2)
    )
    model = SegmentedModel(
        (L.Dense("fc1", 16), L.Activation("r1", "relu"),
         L.Dense("out", 4)),
        (16,),
    )
    import os

    hist = run_prune_retrain(
        ExperimentConfig(
            name="sim_mesh", dataset="synthetic", method="weight_norm",
            policy="fraction", fraction=0.25, score_examples=32,
            eval_batch_size=32, simulate=True,
            mesh={"data": 2, "model": 4}, log_path=os.devnull,
        ),
        model=model, datasets=datasets, verbose=False,
    )
    assert len(hist) == 1 and hist[0].n_dropped == 4
    assert np.isfinite(hist[0].post_acc)


def test_drop_masks_rejects_unknown_layer():
    model = fc()
    params, _ = init_model(model, seed=0)
    with pytest.raises(KeyError):
        drop_masks(model, params, {"nope": [0]})

"""Native data-pipeline tests: the C++ library builds on this toolchain,
its shuffle matches the pure-Python splitmix64 Fisher-Yates bit for bit,
gather matches numpy fancy indexing, and the prefetch iterator reproduces
the synchronous batch stream."""

import numpy as np
import pytest

from torchpruner_tpu.data import Dataset
from torchpruner_tpu.data import native


@pytest.fixture(scope="module")
def lib():
    lib = native._load_library()
    if lib is None:
        pytest.skip("native library unavailable (no toolchain)")
    return lib


def test_native_builds_and_loads(lib):
    assert native.native_available()


def test_shuffle_native_matches_python(lib):
    for n, seed in ((1, 0), (7, 3), (1000, 42), (1000, 43)):
        got = native.shuffled_indices(n, seed)
        want = native._py_shuffle(n, seed)
        np.testing.assert_array_equal(got, want)
        assert sorted(got.tolist()) == list(range(n))  # a real permutation


def test_shuffle_differs_across_seeds(lib):
    a = native.shuffled_indices(500, 1)
    b = native.shuffled_indices(500, 2)
    assert not np.array_equal(a, b)


def test_gather_matches_numpy(lib):
    rng = np.random.default_rng(0)
    for shape, dtype in (((100, 17), np.float32), ((64, 8, 8, 3), np.uint8),
                         ((50,), np.int32)):
        src = rng.integers(0, 100, size=shape).astype(dtype)
        idx = rng.integers(0, shape[0], size=32).astype(np.int64)
        np.testing.assert_array_equal(
            native.gather_rows(src, idx), src[idx]
        )


def test_prefetch_matches_synchronous_batches(lib):
    rng = np.random.default_rng(1)
    ds = Dataset(
        rng.normal(size=(103, 5)).astype(np.float32),
        rng.integers(0, 10, size=103).astype(np.int32),
    )
    got = list(native.prefetch_batches(ds, 16, shuffle=True, seed=9))
    idx = native.shuffled_indices(103, 9)
    want = [
        (ds.x[idx[i:i + 16]], ds.y[idx[i:i + 16]])
        for i in range(0, 103, 16)
    ]
    assert len(got) == len(want)
    for (gx, gy), (wx, wy) in zip(got, want):
        np.testing.assert_array_equal(gx, wx)
        np.testing.assert_array_equal(gy, wy)


def test_prefetch_drop_remainder(lib):
    ds = Dataset(np.zeros((10, 2), np.float32), np.zeros((10,), np.int32))
    batches = list(native.prefetch_batches(ds, 4, drop_remainder=True))
    assert [len(b[0]) for b in batches] == [4, 4]


def test_gather_rejects_out_of_range_on_both_paths():
    src = np.arange(20, dtype=np.float32).reshape(10, 2)
    for bad in ([0, 10], [-1, 3], [11]):
        idx = np.asarray(bad, dtype=np.int64)
        with pytest.raises(IndexError):
            native.gather_rows(src, idx)


def test_prefetch_propagates_worker_errors():
    class Broken:
        """Dataset whose second row gather explodes."""
        x = np.zeros((8, 2), np.float32)
        y = np.zeros((8,), np.int32)

        def __len__(self):
            return 12  # lies: indices 8..11 are out of range

    with pytest.raises(IndexError):
        list(native.prefetch_batches(Broken(), 4))


def test_augment_native_matches_python_bitwise(lib):
    rng = np.random.default_rng(7)
    x = rng.normal(size=(33, 16, 16, 3)).astype(np.float32)
    for seed in (0, 1, 12345):
        got = native.augment_batch(x, seed)
        np.testing.assert_array_equal(
            got, native._augment_numpy(x, seed, pad=4))
    # single- vs multi-threaded native: per-example streams make the
    # result independent of thread count
    np.testing.assert_array_equal(
        native.augment_batch(x, 5, n_threads=1),
        native.augment_batch(x, 5, n_threads=4),
    )


def test_augment_fill_native_matches_python_and_reference_border(lib):
    """fill=-mean/std reproduces the reference's pad-raw-then-Normalize
    border pixels (its cifar10.py:105-110: RandomCrop(padding=4) runs on
    the raw image, Normalize after — borders land at -mean/std)."""
    from torchpruner_tpu.data.datasets import norm_zero

    fill = norm_zero("cifar10")
    np.testing.assert_allclose(
        fill, -np.array([0.485, 0.456, 0.406]) / [0.229, 0.224, 0.225],
        rtol=1e-6)
    rng = np.random.default_rng(11)
    x = rng.normal(size=(40, 12, 12, 3)).astype(np.float32)
    for seed in (0, 77):
        got = native.augment_batch(x, seed, fill=fill)
        np.testing.assert_array_equal(
            got, native._augment_numpy(x, seed, pad=4, fill=fill))
    # pad-then-normalize commutes with normalize-then-pad-with(-mean/std):
    # augmenting raw data then normalizing == normalizing then augmenting
    # with the norm_zero fill, for the same seed (bit-exact draws)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)
    raw = rng.random(size=(16, 8, 8, 3)).astype(np.float32)
    a = (native.augment_batch(raw, seed=3) - mean) / std
    b = native.augment_batch((raw - mean) / std, seed=3, fill=fill)
    np.testing.assert_allclose(a, b, atol=1e-5)
    # scalar fill broadcasts; wrong channel count raises
    one = native.augment_batch(x, 1, fill=0.5)
    assert one.shape == x.shape
    with pytest.raises(ValueError):
        native.augment_batch(x, 1, fill=[1.0, 2.0])


def test_augment_semantics():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(64, 8, 8, 1)).astype(np.float32)
    out = native.augment_batch(x, seed=9)
    assert out.shape == x.shape and out.dtype == np.float32
    # every output image is a shifted (possibly flipped) window of its
    # source: the multiset of nonzero pixel values is a subset
    for i in range(8):
        src_vals = set(np.round(x[i].ravel(), 5).tolist())
        out_vals = [v for v in np.round(out[i].ravel(), 5).tolist()
                    if v != 0.0]
        assert all(v in src_vals for v in out_vals)
    # deterministic per seed, different across seeds
    np.testing.assert_array_equal(out, native.augment_batch(x, seed=9))
    assert not np.array_equal(out, native.augment_batch(x, seed=10))
    # non-image input passes through
    flat = rng.normal(size=(4, 10)).astype(np.float32)
    np.testing.assert_array_equal(native.augment_batch(flat, 0), flat)

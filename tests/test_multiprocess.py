"""Cross-host (multi-PROCESS) execution of the distributed path.

``dryrun_multichip`` and the mesh tests prove multi-device SPMD inside one
process; this proves the wiring a pod actually needs (SURVEY.md §2.11):
two OS processes join one JAX runtime through
``initialize_distributed`` (Gloo collectives on CPU), build one global
mesh, feed disjoint ``Dataset.host_shard`` slices, and produce the exact
single-process DP trajectory.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import optax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: generous wall per attempt: two gloo workers + cold SPMD compiles on a
#: box that may be running other suites.  The old 420 s budget was the
#: slow-lane flake (PR-4/7/8 postmortems): under load the second worker's
#: backend init starved past the deadline and communicate() raised.
WORKER_TIMEOUT_S = 900


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(extra_args=(), n=2, tries=2):
    """Spawn ``n`` workers joined through one distributed runtime and
    return their parsed JSON results sorted by pid.

    Retries once on the two LOAD-dependent failure modes — a timeout
    (backend init starved) and a distributed-init/connect error on the
    shared port — with a fresh port, so the tests pin the parity
    invariant instead of the box's scheduler.  Real assertion failures
    (bad exit with output, wrong math) are never retried."""
    worker = os.path.join(REPO, "tests", "_mp_worker.py")
    env = os.environ.copy()
    # each worker gets 2 virtual CPU devices -> a 2n-device global mesh
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # python puts the SCRIPT's dir on sys.path, not the cwd — the worker
    # needs the repo root to import torchpruner_tpu
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    last_err = None
    for attempt in range(tries):
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(i), str(n), str(port),
                 *extra_args],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                cwd=REPO, env=env,
            )
            for i in range(n)
        ]
        outs, timed_out, init_err = [], False, False
        try:
            for p in procs:
                try:
                    out, err = p.communicate(timeout=WORKER_TIMEOUT_S)
                except subprocess.TimeoutExpired:
                    # kill the whole gang at the FIRST timeout: peers
                    # blocked on the hung worker's collective would each
                    # burn a full WORKER_TIMEOUT_S of their own otherwise
                    timed_out = True
                    for q in procs:
                        q.kill()
                    out, err = p.communicate()
                    err = (err or "") + "\n[worker timeout]"
                outs.append((p.returncode, out, err))
        finally:
            for p in procs:
                p.kill()
        if not timed_out and all(rc == 0 for rc, _, _ in outs):
            results = []
            for _, out, err in outs:
                lines = [ln for ln in out.splitlines()
                         if ln.startswith("{")]
                assert lines, f"no JSON from worker:\n{out}\n{err[-1000:]}"
                results.append(json.loads(lines[-1]))
            results.sort(key=lambda r: r["pid"])
            return results
        if any("Multiprocess computations aren't implemented"
               in (err or "") for _, _, err in outs):
            # this jaxlib's CPU client was built WITHOUT cross-process
            # collectives: every multiprocess CPU computation is
            # impossible here, regardless of our code.  Skip — loudly,
            # so the slow lane reads as environment-limited rather than
            # red — while CI's jax[cpu] (gloo collectives) still runs
            # the full parity assertion.  (This was the "load-flaky"
            # slow-lane failure of the PR-4/7/8 postmortems: a constant
            # environment limitation, not a race.)
            import pytest

            pytest.skip("jaxlib CPU backend lacks cross-process "
                        "collectives on this machine")
        init_err = any(
            ("distributed" in err.lower() or "connect" in err.lower()
             or "barrier" in err.lower() or "timed out" in err.lower())
            for rc, _, err in outs if rc not in (0, None)
        )
        last_err = "\n---\n".join(
            f"rc={rc}:\n{err[-2000:]}" for rc, _, err in outs)
        if not (timed_out or init_err) or attempt + 1 == tries:
            raise AssertionError(f"workers failed:\n{last_err}")
    raise AssertionError(f"workers failed after {tries} tries:\n{last_err}")


def test_two_process_dp_matches_single_process():
    results = _run_workers()

    # one runtime: every process sees all 4 devices but addresses only 2
    for r in results:
        assert r["process_count"] == 2
        assert r["global_devices"] == 4
        assert r["local_devices"] == 2
    # both processes ran the same SPMD program: identical trajectories
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["w_abs_sum"],
                               results[1]["w_abs_sum"], rtol=1e-6)

    # ...and the distributed trajectory equals single-process DP on the
    # same global batches (host i contributes examples i::2, so a global
    # batch is the concatenation of the per-host slices)
    from torchpruner_tpu.data import synthetic_dataset
    from torchpruner_tpu.models.mlp import fc_net
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    data = synthetic_dataset((16,), 4, 64, seed=0)
    shards = [data.host_shard(i, 2) for i in range(2)]
    trainer = Trainer.create(fc_net(16, hidden=(32, 32)), optax.sgd(0.05),
                             cross_entropy_loss, seed=0)
    ref = []
    for (x0, y0), (x1, y1) in zip(
        shards[0].iter_batches(16, drop_remainder=True),
        shards[1].iter_batches(16, drop_remainder=True),
    ):
        ref.append(float(trainer.step(np.concatenate([x0, x1]),
                                      np.concatenate([y0, y1]))))
    assert len(ref) == len(results[0]["losses"]) == 2
    np.testing.assert_allclose(results[0]["losses"], ref, rtol=1e-4)

    # the multiprocess padded+masked evaluation counts exactly the real
    # examples: compare against single-process eval on the same batches
    ref_eval = trainer.evaluate([
        (np.concatenate([x0, x1]), np.concatenate([y0, y1]))
        for (x0, y0), (x1, y1) in zip(shards[0].batches(15),
                                      shards[1].batches(15))
    ])
    np.testing.assert_allclose(results[0]["eval_loss"], ref_eval[0],
                               rtol=1e-4)
    np.testing.assert_allclose(results[0]["eval_acc"], ref_eval[1],
                               rtol=1e-6)


def test_two_process_obs_metric_shards_merge(tmp_path):
    """Cross-host metric aggregation (obs.aggregate) under a real
    two-process runtime: every process writes a ``metrics.shard<i>.json``
    at close, and process 0's merged export sums counters / maxes gauges
    across hosts — the fix for non-zero processes' metrics vanishing."""
    obs_dir = str(tmp_path / "obs")
    results = _run_workers(("obs", obs_dir))
    assert [r["is_emitter"] for r in results] == [True, False]

    # every process left its shard; only process 0 emitted the stream
    assert os.path.exists(os.path.join(obs_dir, "metrics.shard0.json"))
    assert os.path.exists(os.path.join(obs_dir, "metrics.shard1.json"))
    assert os.path.exists(os.path.join(obs_dir, "events.jsonl"))

    # the exported textfile carries the MERGED totals: counters summed
    # (10 + 20), steps summed (1 + 2 recorded intervals... process i
    # records i+1 steps -> 3 total), gauges maxed with a _min companion
    prom = open(os.path.join(obs_dir, "metrics.prom")).read()
    import re

    def series(name):
        m = re.search(rf"^{name} (\S+)$", prom, re.M)
        return float(m.group(1)) if m else None

    assert series("mp_examples_total") == 30.0
    assert series("mp_hbm_gauge") == 200.0
    assert series("mp_hbm_gauge_min") == 100.0
    assert series("step_time_seconds_count") == 3.0
    assert series("examples_total") == 24.0

    # merged registry re-derivable offline from the shards alone
    from torchpruner_tpu.obs.aggregate import load_shards, merge_shards

    shards = load_shards(obs_dir)
    assert [s["process_index"] for s in shards] == [0, 1]
    snap = merge_shards(shards).snapshot()
    assert snap["mp_examples_total"] == 30.0
    assert snap["step_time_seconds_count"] == 3


def test_two_process_spmd_pipeline_matches_single_process():
    """The collective-based PP path (parallel/pp_spmd.py) across two
    processes: a 4-stage pp mesh axis spanning 2 hosts x 2 devices, so
    the stage-to-stage ppermute crosses the process boundary.  The loss
    trajectory must equal the plain single-device gradient step."""
    results = _run_workers(("pp",))
    for r in results:
        assert r["process_count"] == 2
        assert r["global_devices"] == 4
        assert r["local_devices"] == 2
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)

    # single-device reference trajectory (same seeds, same data)
    import jax
    import optax

    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    model = llama_tiny(depth=4)
    params, _ = init_model(model, seed=0)
    tokens = model.example_input(8, seed=0)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def loss_fn(p):
        logits, _ = model.apply(p, tokens)
        return lm_cross_entropy_loss(logits, tokens).mean()

    ref = []
    for _ in range(3):
        l, g = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(g, opt_state, params)
        params = jax.tree_util.tree_map(lambda a, u: a + u, params, updates)
        ref.append(float(l))
    np.testing.assert_allclose(results[0]["losses"], ref,
                               rtol=1e-4, atol=1e-6)

    # interleaved (V=2) trajectory: cross-process equality + the
    # single-device depth-8 reference
    np.testing.assert_allclose(results[0]["losses_interleaved"],
                               results[1]["losses_interleaved"], rtol=1e-6)
    model8 = llama_tiny(depth=8)
    params8, _ = init_model(model8, seed=0)
    opt_state8 = opt.init(params8)

    def loss8(p):
        logits, _ = model8.apply(p, tokens)
        return lm_cross_entropy_loss(logits, tokens).mean()

    ref8 = []
    for _ in range(2):
        l, g = jax.value_and_grad(loss8)(params8)
        updates, opt_state8 = opt.update(g, opt_state8, params8)
        params8 = jax.tree_util.tree_map(lambda a, u: a + u, params8,
                                         updates)
        ref8.append(float(l))
    np.testing.assert_allclose(results[0]["losses_interleaved"], ref8,
                               rtol=1e-4, atol=1e-6)

"""Preset / CLI / token-dataset tests: the named preset configs
resolve, round-trip through JSON, and the transformer prune-retrain path
runs end to end on miniature variants."""

import json
import os

import numpy as np
import pytest

from torchpruner_tpu.data import load_dataset
from torchpruner_tpu.data.datasets import synthetic_token_dataset
from torchpruner_tpu.experiments.presets import PRESETS, get_preset
from torchpruner_tpu.experiments.prune_retrain import (
    LOSS_REGISTRY,
    MODEL_REGISTRY,
    run_prune_retrain,
)
from torchpruner_tpu.utils.config import ExperimentConfig


def test_all_presets_resolve_and_roundtrip(tmp_path):
    # the five BASELINE.json configs + the runnable-here digits32 variant
    # + the reference MNIST MLP recipe (the obs smoke target)
    assert len(PRESETS) == 7
    for name in PRESETS:
        for smoke in (False, True):
            cfg = get_preset(name, smoke=smoke)
            assert cfg.model in MODEL_REGISTRY, cfg.model
            assert cfg.loss in LOSS_REGISTRY
            p = tmp_path / f"{name}_{smoke}.json"
            cfg.to_json(str(p))
            back = ExperimentConfig.from_json(str(p))
            assert back == cfg


def test_unknown_preset_raises():
    with pytest.raises(KeyError):
        get_preset("nope")


def test_token_classification_dataset_is_learnable_structure():
    ds = synthetic_token_dataset(16, 64, 2, 200, seed=0)
    assert ds.x.shape == (200, 16) and ds.x.dtype == np.int32
    assert set(np.unique(ds.y)) <= {0, 1}
    # the two classes must differ in token statistics (signal exists)
    h0 = np.bincount(ds.x[ds.y == 0].ravel(), minlength=64)
    h1 = np.bincount(ds.x[ds.y == 1].ravel(), minlength=64)
    assert np.abs(h0 / h0.sum() - h1 / h1.sum()).max() > 0.01


def test_lm_dataset_targets_are_inputs():
    ds = load_dataset("lm_tiny", "val", n=32)
    assert ds.x.shape == (32, 16)
    np.testing.assert_array_equal(ds.x, ds.y)


def test_prune_retrain_on_llama_tiny_ffn():
    """Config-5 recipe end to end at miniature scale: Taylor on LM loss,
    FFN channels only, fraction policy."""
    cfg = get_preset("llama3_ffn_taylor", smoke=True)
    cfg.score_examples = 16
    cfg.eval_batch_size = 16
    cfg.log_path = os.devnull
    history = run_prune_retrain(cfg, verbose=False)
    assert len(history) == 2  # one FFN group per block, heads untouched
    assert all(r.layer.endswith("_ffn/gate") for r in history)
    assert all(r.n_dropped == 16 for r in history)  # 25% of 64


def test_cli_list_and_dump(tmp_path, capsys):
    from torchpruner_tpu.__main__ import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in PRESETS:
        assert name in out
    dump = tmp_path / "cfg.json"
    assert main([
        "--preset", "bert_glue_sensitivity", "--smoke",
        "--dump-config", str(dump),
    ]) == 0
    cfg = json.loads(dump.read_text())
    assert cfg["model"] == "bert_tiny"


def test_cli_runs_config_with_profile_and_cache(tmp_path, monkeypatch):
    """End-to-end CLI run: config file in, JSON summary out, profiler
    trace written, compilation cache pointed at the configured dir."""
    from torchpruner_tpu.__main__ import main
    from torchpruner_tpu.utils.config import ExperimentConfig

    monkeypatch.setenv(
        "TORCHPRUNER_TPU_COMPILATION_CACHE", str(tmp_path / "xla")
    )
    cfg = ExperimentConfig(
        name="cli_e2e", model="digits_fc", dataset="digits_flat",
        experiment="robustness", method="weight_norm", score_examples=32,
        eval_batch_size=32, target_filter=("fc2",),
        log_path=str(tmp_path / "log.csv"),
    )
    path = tmp_path / "cfg.json"
    cfg.to_json(str(path))
    trace_dir = tmp_path / "trace"
    assert main([
        "--config", str(path), "--profile", str(trace_dir),
    ]) == 0
    assert any(trace_dir.rglob("*.pb")), "no profiler trace written"
    assert (tmp_path / "xla").exists()


def test_optimizer_config_dispatch():
    from torchpruner_tpu.experiments.prune_retrain import make_optimizer
    from torchpruner_tpu.utils.config import ExperimentConfig

    import jax.numpy as jnp

    params = {"w": jnp.ones((3,))}
    grads = {"w": jnp.ones((3,))}
    for opt in ("sgd", "adam", "adamw"):
        wd = 0.01 if opt != "adam" else 0.0  # adam+decay rejected
        cfg = ExperimentConfig(name="o", optimizer=opt, lr=0.1,
                               weight_decay=wd)
        tx = make_optimizer(cfg)
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        assert jnp.isfinite(updates["w"]).all()
    # adam's state carries moments; sgd without momentum does not
    cfg_adam = ExperimentConfig(name="a", optimizer="adam")
    assert "ScaleByAdamState" in str(
        type(make_optimizer(cfg_adam).init(params)[0]))
    with pytest.raises(ValueError, match="optimizer"):
        ExperimentConfig(name="bad", optimizer="lion")
    with pytest.raises(ValueError, match="momentum"):
        ExperimentConfig(name="bad", optimizer="adam", momentum=0.9)
    with pytest.raises(ValueError, match="adamw"):
        ExperimentConfig(name="bad", optimizer="adam", weight_decay=1e-4)


def test_train_robustness_experiment_end_to_end(tmp_path):
    """The one-command two-phase protocol: training runs first and the
    sweep scores the TRAINED weights (sanity: a trained digits model
    gives weight_norm a finite, non-degenerate AUC and the training
    history shows learning)."""
    from torchpruner_tpu.experiments.robustness import run_train_robustness
    from torchpruner_tpu.utils.config import ExperimentConfig

    cfg = ExperimentConfig(
        name="tr_e2e", model="digits_fc", dataset="digits_flat",
        experiment="train_robustness", epochs=2, batch_size=64,
        optimizer="adam", lr=1e-3, method="weight_norm",
        score_examples=64, eval_batch_size=64, target_filter=("fc2",),
        log_path=str(tmp_path / "log.csv"),
    )
    summary = run_train_robustness(cfg, verbose=False)
    assert set(summary) == {"weight_norm"}
    assert np.isfinite(summary["weight_norm"])

"""Training loop, logger, FLOPs, config, data pipeline."""

import csv
import os

import numpy as np
import optax
import pytest

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.core.segment import SegmentedModel
from torchpruner_tpu.data import load_dataset, synthetic_dataset
from torchpruner_tpu.train import CSVLogger, Trainer, evaluate, train_epoch
from torchpruner_tpu.utils.config import ExperimentConfig
from torchpruner_tpu.utils.flops import model_cost, param_count
from torchpruner_tpu.utils.losses import cross_entropy_loss


def tiny_model():
    return SegmentedModel(
        (L.Dense("fc1", 32), L.Activation("r1", "relu"), L.Dense("out", 4)),
        (8,),
    )


def tiny_data(n=256, seed=0):
    return synthetic_dataset((8,), 4, n, seed=seed)


def test_training_reduces_loss():
    ds = tiny_data()
    trainer = Trainer.create(tiny_model(), optax.adam(1e-2),
                             cross_entropy_loss, seed=0)
    batches = ds.batches(32)
    l0, a0 = trainer.evaluate(batches)
    for epoch in range(3):
        train_epoch(trainer, ds.batches(32, shuffle=True, seed=epoch),
                    verbose=False)
    l1, a1 = trainer.evaluate(batches)
    assert l1 < l0
    assert a1 > a0


def test_gradient_accumulation_matches_full_batch():
    """accum_steps=4 (scanned microbatches, one update) must produce the
    same parameters and loss as the full-batch step — equal-size
    microbatches of a mean loss sum to the full-batch gradient."""
    import jax

    ds = tiny_data(n=64)
    x, y = next(iter(ds.batches(64)))

    def run(accum):
        t = Trainer.create(tiny_model(), optax.sgd(1e-2, momentum=0.9),
                           cross_entropy_loss, seed=0, accum_steps=accum)
        losses = [float(t.step(x, y)) for _ in range(3)]
        return t.params, losses

    p1, l1 = run(1)
    p4, l4 = run(4)
    np.testing.assert_allclose(l1, l4, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    # non-dividing batch size is rejected at trace time
    t = Trainer.create(tiny_model(), optax.sgd(1e-2), cross_entropy_loss,
                       seed=0, accum_steps=3)
    with pytest.raises(ValueError, match="divisible"):
        t.step(x, y)


def test_train_prune_train():
    # the reference's behavioral optimizer test, end to end through Trainer
    # (reference tests/test_pruner.py:180-228)
    ds = tiny_data()
    trainer = Trainer.create(tiny_model(), optax.sgd(1e-2, momentum=0.9),
                             cross_entropy_loss, seed=0)
    train_epoch(trainer, ds.batches(32), verbose=False)
    res = prune(trainer.model, trainer.params, "fc1", [0, 1, 2, 3],
                state=trainer.state, opt_state=trainer.opt_state)
    trainer = trainer.rebuild(res.model, res.params, res.state, res.opt_state)
    l = train_epoch(trainer, ds.batches(32), verbose=False)
    assert np.isfinite(l)
    assert trainer.model.layer("fc1").features == 28


def test_param_count_and_flops():
    m = tiny_model()
    trainer = Trainer.create(m, optax.sgd(1e-2), cross_entropy_loss)
    n, flops = model_cost(m, trainer.params, trainer.state)
    assert n == 8 * 32 + 32 + 32 * 4 + 4
    if flops is not None:  # cost analysis is best-effort per backend
        assert flops > 0


def test_csv_logger_schema(tmp_path):
    path = str(tmp_path / "log.csv")
    logger = CSVLogger(path, experiment="t")
    logger.log_prune_step(
        layer="fc1", method="shapley", test_loss=1.0, test_acc=0.5,
        test_loss_pp=1.1, test_acc_pp=0.45, n_params=123, flops=456.0,
        widths={"fc1": 28, "out": 4}, prune_time=0.5, prune_ratio=0.1,
    )
    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 1
    assert rows[0]["widths"] == "28-4"
    assert rows[0]["test_loss_pp"] == "1.100000"
    assert os.path.exists(path + ".jsonl")


def test_config_roundtrip(tmp_path):
    cfg = ExperimentConfig(name="x", method="taylor", mesh={"data": 4})
    p = str(tmp_path / "cfg.json")
    cfg.to_json(p)
    cfg2 = ExperimentConfig.from_json(p)
    assert cfg2 == cfg
    # unknown keys rejected
    import json
    with open(p) as f:
        raw = json.load(f)
    raw["bogus"] = 1
    with open(p, "w") as f:
        json.dump(raw, f)
    with pytest.raises(ValueError):
        ExperimentConfig.from_json(p)


def test_load_dataset_shapes_and_split_consistency():
    tr = load_dataset("mnist_flat", "train", n=64)
    te = load_dataset("mnist_flat", "test", n=64)
    assert tr.x.shape == (64, 784) and tr.y.dtype == np.int32
    # same class centers across splits: a model trained on train should do
    # better than chance on test — proxy: class-conditional means correlate
    for c in range(3):
        a = tr.x[tr.y == c].mean(0)
        b = te.x[te.y == c].mean(0)
        corr = np.corrcoef(a, b)[0, 1]
        assert corr > 0.3, f"class {c} centers inconsistent across splits"


def test_dataset_batching():
    ds = tiny_data(100)
    bs = ds.batches(32)
    assert [len(b[0]) for b in bs] == [32, 32, 32, 4]
    bs2 = ds.batches(32, drop_remainder=True)
    assert [len(b[0]) for b in bs2] == [32, 32, 32]


def test_host_shard_partitions_disjointly():
    """host_shard(i, n) slices are disjoint, cover the dataset, and are
    deterministic — the multi-host DP data contract; single-process
    defaults are the identity."""
    ds = tiny_data(n=64)
    shards = [ds.host_shard(i, 4) for i in range(4)]
    assert sum(len(s) for s in shards) == 64
    seen = np.concatenate([s.x for s in shards])
    np.testing.assert_array_equal(
        np.sort(seen.ravel()), np.sort(ds.x.ravel())
    )
    # identity without multi-process config
    assert ds.host_shard() is ds
    with pytest.raises(ValueError, match="host index"):
        ds.host_shard(4, 4)


def test_mixed_precision_training_keeps_f32_master_state():
    """bf16 compute: params/opt-state/BN stats stay f32, loss decreases,
    and one step tracks the f32 step closely."""
    import jax
    import jax.numpy as jnp
    import optax

    from torchpruner_tpu.core import layers as L
    from torchpruner_tpu.core.segment import SegmentedModel, init_model
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    model = SegmentedModel(
        (
            L.Conv("conv1", 8, kernel_size=(3, 3), padding="SAME"),
            L.BatchNorm("bn1"),
            L.Activation("act1", "relu"),
            L.Flatten("flatten"),
            L.Dense("out", 5),
        ),
        (8, 8, 2),
    )
    params, state = init_model(model, seed=0)
    x = np.asarray(
        jax.random.normal(jax.random.PRNGKey(1), (16, 8, 8, 2)), np.float32
    )
    y = np.asarray(np.arange(16) % 5, np.int32)
    tx = optax.sgd(0.05, momentum=0.9)
    def copy(tree):
        # each trainer donates its buffers — they can't share arrays
        return jax.tree_util.tree_map(lambda a: jnp.array(a), tree)

    mp = Trainer.create(model, tx, cross_entropy_loss, params=copy(params),
                        state=copy(state), compute_dtype=jnp.bfloat16)
    fp = Trainer.create(model, tx, cross_entropy_loss, params=copy(params),
                        state=copy(state))
    losses_mp = [float(mp.step(x, y)) for _ in range(5)]
    losses_fp = [float(fp.step(x, y)) for _ in range(5)]
    assert losses_mp[-1] < losses_mp[0]
    assert abs(losses_mp[0] - losses_fp[0]) < 0.05
    for tree in (mp.params, mp.state, mp.opt_state):
        for leaf in jax.tree_util.tree_leaves(tree):
            if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
                assert jnp.result_type(leaf) == jnp.float32
    # BN running stats track the f32 run closely — the EMA arithmetic is
    # f32 (norm rules compute in f32), not bf16-rounded
    np.testing.assert_allclose(
        np.asarray(mp.state["bn1"]["mean"]),
        np.asarray(fp.state["bn1"]["mean"]), atol=5e-3,
    )
    # a tiny EMA increment below bf16 resolution must not round away
    from torchpruner_tpu.core import layers as L

    spec = [l for l in model.layers if l.name == "bn1"][0]
    st = {"mean": jnp.full((8,), 1.0), "var": jnp.ones((8,))}
    # 1 + 2^-7 is exactly representable in bf16; the EMA increment
    # (1-decay) * 2^-7 lands between bf16 steps around 1.0 and would
    # round away under bf16 arithmetic
    tiny = jnp.full((16, 8, 8, 8), 1.0 + 2.0**-7, jnp.bfloat16)
    _, ns = L.apply_layer(
        spec,
        {k: v.astype(jnp.bfloat16) for k, v in mp.params["bn1"].items()},
        st, tiny, train=True,
    )
    expected = 1.0 + (1.0 - spec.decay) * 2.0**-7
    np.testing.assert_allclose(float(ns["mean"][0]), expected, rtol=1e-5)


def test_remat_training_matches_exact():
    """jax.checkpoint blocks recompute the forward — same program
    SEMANTICS as the non-remat step.  The real invariant is pinned in
    two parts: (1) the trajectories agree to float tolerance — NOT
    bitwise, because the remat and non-remat programs fuse and schedule
    their reductions differently, and on the multithreaded XLA CPU
    backend the summation partitioning can additionally shift with
    machine load (this test was load-flaky at rtol 1e-6 / atol 1e-6:
    PR-4/7/8 slow-lane postmortems) — and (2) the checkpoint primitive
    structurally engages, asserted on the jaxpr below."""
    import jax
    import jax.numpy as jnp
    import optax

    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    model = llama_tiny(depth=2)
    x = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 256), np.int32
    )

    def run(remat):
        t = Trainer.create(model, optax.adam(1e-3), lm_cross_entropy_loss,
                           seed=0, remat=remat)
        losses = [float(t.step(x, x)) for _ in range(3)]
        return losses, t.params

    l0, p0 = run(False)
    l1, p1 = run(True)
    np.testing.assert_allclose(l0, l1, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p0),
                    jax.tree_util.tree_leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)

    # the checkpoint primitive actually engages (per composite block)
    params, state = __import__(
        "torchpruner_tpu.core.segment", fromlist=["init_model"]
    ).init_model(model, seed=0)

    def loss(p, remat):
        out, _ = model.apply(p, x, state=state, train=True, remat=remat)
        return jnp.mean(lm_cross_entropy_loss(out, x))

    j_no = str(jax.make_jaxpr(
        lambda p: jax.grad(lambda q: loss(q, False))(p))(params))
    j_yes = str(jax.make_jaxpr(
        lambda p: jax.grad(lambda q: loss(q, True))(p))(params))
    assert "remat" not in j_no
    assert "remat" in j_yes


def test_sharded_trainer_bf16_remat_step():
    """The SPMD step composes with mixed precision + remat: masters stay
    f32, loss decreases, prune->reshard->step still works."""
    import jax
    import jax.numpy as jnp
    import optax

    from torchpruner_tpu.core.pruner import prune
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.parallel import ShardedTrainer, make_mesh
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    mesh = make_mesh({"data": 2, "model": 4})
    t = ShardedTrainer.create(
        llama_tiny(depth=2), optax.adam(1e-2), lm_cross_entropy_loss, mesh,
        seed=0, min_shard_size=0, partition="fsdp",
        compute_dtype=jnp.bfloat16, remat=True,
    )
    x = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 256), np.int32
    )
    l0 = float(t.step(x, x))
    l1 = float(t.step(x, x))
    assert np.isfinite(l0) and l1 < l0
    for leaf in jax.tree_util.tree_leaves(t.params):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
            assert jnp.result_type(leaf) == jnp.float32
    r = prune(t.model, t.params, "block1_ffn/gate", [0, 1],
              state=t.state, opt_state=t.opt_state)
    t = t.rebuild(r.model, r.params, r.state, r.opt_state)
    assert np.isfinite(float(t.step(x, x)))


def test_multi_step_matches_sequential_steps():
    """K steps scanned inside one program (Trainer.multi_step) must
    produce exactly the params, rng chain, state and losses of K
    sequential Trainer.step calls on the same batches — the dispatch
    amortization is free of semantic drift (incl. BN state threading
    and per-step rng splits)."""
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.core import layers as L

    def bn_model():  # BN exercises mutable-state threading
        return SegmentedModel(
            (L.Dense("fc1", 16), L.BatchNorm("bn1"),
             L.Activation("r1", "relu"), L.Dense("out", 4)),
            (8,),
        )

    ds = tiny_data(n=96)
    batches = list(ds.batches(32))[:3]
    xs = jnp.stack([b[0] for b in batches])
    ys = jnp.stack([b[1] for b in batches])

    seq = Trainer.create(bn_model(), optax.adam(1e-2), cross_entropy_loss,
                         seed=0)
    seq_losses = [float(seq.step(x, y)) for x, y in batches]

    multi = Trainer.create(bn_model(), optax.adam(1e-2), cross_entropy_loss,
                           seed=0)
    losses = multi.multi_step(xs, ys)

    np.testing.assert_allclose(np.asarray(losses), seq_losses, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(seq.params),
                    jax.tree_util.tree_leaves(multi.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(seq.state),
                    jax.tree_util.tree_leaves(multi.state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(seq.rng), np.asarray(multi.rng))
    assert multi.step_count == 3

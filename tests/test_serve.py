"""Serving-engine tests: lane-aligned KV allocation, continuous-batching
scheduling, engine-vs-solo token parity (greedy AND seeded sampling),
SIGTERM-style drain, checkpoint hot-swap, obs/report surfacing, and the
HTTP front end."""

import json
import os
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.core.segment import init_model
from torchpruner_tpu.generate import generate
from torchpruner_tpu.models import llama_moe_tiny, llama_tiny
from torchpruner_tpu.serve import (
    KVCacheAllocator,
    OpenLoopTraffic,
    Request,
    Sampling,
    ServeEngine,
    aligned_len,
    bucket_for,
    poisson_arrivals,
    prefill_buckets,
    staggered_arrivals,
    synthetic_requests,
)
from torchpruner_tpu.serve.request import DONE, DRAINED


@pytest.fixture
def tiny_engine():
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    return model, params, ServeEngine(model, params, n_slots=2, max_len=64)


# -- allocator ---------------------------------------------------------------


def test_aligned_len_follows_lane_ladder():
    assert aligned_len(1) == 8
    assert aligned_len(8) == 8
    assert aligned_len(9) == 16
    assert aligned_len(128) == 128
    assert aligned_len(129) == 256
    # the LAST bucket is capped at max_len itself (possibly unaligned):
    # a bucket larger than the physical slot could never insert
    assert prefill_buckets(20) == [8, 16, 20]
    assert prefill_buckets(160) == [8, 16, 24, 32, 40, 48, 56, 64, 72,
                                    80, 88, 96, 104, 112, 120, 128, 160]
    assert max(prefill_buckets(100)) == 100
    assert bucket_for(13, [8, 16, 24]) == 16
    with pytest.raises(ValueError, match="exceeds"):
        bucket_for(100, [8, 16, 24])


def test_allocator_pages_and_recycling():
    a = KVCacheAllocator(n_slots=2, max_len=64, page_len=16)
    assert a.pages_per_slot == 4
    l1 = a.allocate(1, 30)  # 2 pages
    l2 = a.allocate(2, 64)  # 4 pages
    assert l1.pages == 2 and l2.pages == 4
    assert a.pages_in_use == 6 and a.active_slots == 2
    assert a.allocate(3, 8) is None  # no slot free
    a.release(l1.slot)
    assert a.pages_in_use == 4 and a.total_evictions == 1
    l3 = a.allocate(3, 8)
    assert l3 is not None and l3.slot == l1.slot  # slot recycled
    assert a.allocate(4, 65) is None  # longer than a slot


def test_allocator_page_budget_caps_residency():
    a = KVCacheAllocator(n_slots=4, max_len=64, page_len=16,
                         page_budget=6)
    assert a.allocate(1, 64) is not None  # 4 pages
    assert a.allocate(2, 64) is None      # would need 4 > 2 remaining
    assert a.allocate(3, 30) is not None  # 2 pages fits the budget


# -- engine: continuous batching ----------------------------------------------


def test_continuous_batching_tokens_match_solo_decode(tiny_engine):
    """More requests than slots with staggered open-loop arrivals —
    mid-run admissions and slot recycling — and every request's tokens
    bit-identical to its static solo generate() decode."""
    model, params, eng = tiny_engine
    # the gauge must agree with the ONE dispatch predicate — max_len=64
    # blocks cleanly, so the decode-shaped kernel serves this engine and
    # the parity below exercises it (not the einsum fallback)
    from torchpruner_tpu.generate import _attn_layers
    from torchpruner_tpu.ops import decode_attention as _da

    head_dim = next(spec.head_dim for _, spec in _attn_layers(model.layers))
    assert eng.decode_kernel
    assert eng.decode_kernel == _da.kernel_active(
        eng.max_len, head_dim, jnp.float32)
    reqs = synthetic_requests(6, vocab=64, prompt_lens=[4, 7, 5],
                              max_new=[6, 3, 9], seed=1)
    traffic = OpenLoopTraffic(reqs, staggered_arrivals(6, every_steps=2),
                              by_step=True)
    summary = eng.run(traffic)
    assert summary["requests_completed"] == 6
    assert summary["evictions"] == 6  # every slot recycled at least once
    assert eng.scheduler.allocator.active_slots == 0
    for r in reqs:
        assert r.state == DONE and len(r.tokens) == r.max_new
        # replay at the ENGINE's cache length: the decode kernel's block
        # partition is a function of max_len (ops/decode_attention.py),
        # so bit-identity pins the replay to the serving geometry
        want = np.asarray(generate(model, params, r.prompt_ids[None],
                                   r.max_new, max_len=eng.max_len))[0]
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      want)
        assert r.ttft_s is not None and r.ttft_s >= 0
        assert len(r.token_gaps_s) == r.max_new - 1


def test_sampled_requests_match_seeded_generate(tiny_engine):
    """Per-request temperature / top_k / top_p sampling reproduces the
    solo generate() stream from the same seed — the replayability
    contract (a served request can be re-decoded offline).  The
    COMBINED top_k+top_p case pins the truncation ORDER: the nucleus
    must be measured over the top-k-renormalized distribution, exactly
    as generate._truncate_logits does."""
    model, params, eng = tiny_engine
    cases = [Sampling(temperature=0.8, seed=7),
             Sampling(temperature=1.2, top_k=5, seed=11),
             Sampling(temperature=0.9, top_p=0.8, seed=13),
             Sampling(temperature=1.0, top_k=2, top_p=0.6, seed=17),
             Sampling(temperature=0.7, top_k=7, top_p=0.5, seed=19)]
    rng = np.random.default_rng(0)
    reqs = [eng.submit(Request(
        prompt_ids=rng.integers(0, 64, size=5).astype(np.int32),
        max_new=8, sampling=s)) for s in cases]
    eng.run()
    for r in reqs:
        s = r.sampling
        want = np.asarray(generate(
            model, params, r.prompt_ids[None], r.max_new,
            temperature=s.temperature, top_k=s.top_k, top_p=s.top_p,
            rng=jax.random.PRNGKey(s.seed), max_len=eng.max_len))[0]
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      want)


def test_moe_and_bf16_cache_serving():
    """The engine rides the MoE decode path and a bf16 KV cache (the
    serving config) — parity against generate() at the SAME cache
    dtype."""
    model = llama_moe_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=48,
                      cache_dtype=jnp.bfloat16)
    reqs = synthetic_requests(3, vocab=64, prompt_lens=[4, 6],
                              max_new=[5], seed=2)
    for r in reqs:
        eng.submit(r)
    eng.run()
    for r in reqs:
        want = np.asarray(generate(model, params, r.prompt_ids[None],
                                   r.max_new, cache_dtype=jnp.bfloat16,
                                   max_len=eng.max_len))[0]
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      want)


def test_eos_stops_early_and_recycles_slot(tiny_engine):
    """An eos_id hit ends the request before max_new and frees the slot
    (early eviction — the other slot-reuse trigger)."""
    model, params, eng = tiny_engine
    probe = Request(prompt_ids=np.asarray([5, 9, 2], np.int32), max_new=8)
    eng.submit(probe)
    eng.run()
    eos = probe.tokens[2]  # third greedy token
    r = Request(prompt_ids=np.asarray([5, 9, 2], np.int32), max_new=8,
                eos_id=int(eos))
    eng.submit(r)
    eng.run()
    assert r.state == DONE
    assert len(r.tokens) == 3 and r.tokens[-1] == eos
    assert eng.scheduler.allocator.active_slots == 0


def test_retain_results_false_keeps_memory_bounded():
    """The HTTP-server configuration: completed requests are NOT
    accumulated on the engine (each response lives with its waiter), so
    a long-running server — and, across a hot-swap, the old program set
    pinned by served_by — can be garbage-collected."""
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=64,
                      retain_results=False)
    reqs = [eng.submit(r) for r in synthetic_requests(
        3, vocab=64, prompt_lens=[4], max_new=[4], seed=9)]
    summary = eng.run()
    assert eng.results() == []
    assert summary["requests_completed"] == 3
    assert all(len(r.tokens) == 4 for r in reqs)  # waiters still served
    assert summary["ttft_p50_ms"] is None  # read the obs histograms


def test_summary_throughput_window_is_per_run():
    """A warmup run must not dilute the next run's sustained tok/s:
    summary()'s gen_tokens/wall cover the most recent run() only, while
    request counts stay lifetime."""
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=64)
    for r in synthetic_requests(2, vocab=64, prompt_lens=[4],
                                max_new=[6], seed=10):
        eng.submit(r)
    eng.run()  # warmup window: 12 tokens
    for r in synthetic_requests(1, vocab=64, prompt_lens=[4],
                                max_new=[5], seed=11):
        eng.submit(r)
    summary = eng.run()
    assert summary["gen_tokens"] == 5  # this window, not lifetime 17
    assert summary["requests_completed"] == 3  # lifetime count
    assert eng.gen_tokens == 17


def test_submit_rejects_oversized_and_bad_sampling(tiny_engine):
    _model, _params, eng = tiny_engine
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(Request(prompt_ids=np.arange(4, dtype=np.int32),
                           max_new=100))
    with pytest.raises(ValueError, match="top_k"):
        eng.submit(Request(prompt_ids=np.arange(4, dtype=np.int32),
                           max_new=2, sampling=Sampling(top_k=0)))
    with pytest.raises(ValueError, match="empty"):
        Request(prompt_ids=np.asarray([], np.int32), max_new=2)


# -- drain -------------------------------------------------------------------


def test_preemption_drains_in_flight_and_snapshots_queue(tmp_path):
    """Preemption mid-run: in-flight requests FINISH (never truncated),
    queued + unsubmitted ones land in the atomic snapshot, and the
    snapshot round-trips back into submittable requests."""
    from torchpruner_tpu.resilience.guards import PreemptionHandler
    from torchpruner_tpu.serve.engine import SNAPSHOT_FILENAME

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=96,
                      run_dir=str(tmp_path))
    reqs = synthetic_requests(6, vocab=64, prompt_lens=[4],
                              max_new=[20], seed=5)
    traffic = OpenLoopTraffic(reqs, staggered_arrivals(6, every_steps=1),
                              by_step=True)
    pre = PreemptionHandler()

    class FireAt:
        def __init__(self, inner):
            self.inner = inner

        @property
        def exhausted(self):
            return self.inner.exhausted

        def drain(self):
            return self.inner.drain()

        def pump(self, engine):
            n = self.inner.pump(engine)
            if engine.steps == 6:
                pre.request()  # the SIGTERM handler path, in-process
            return n

    summary = eng.run(FireAt(traffic), preemption=pre)
    done = [r for r in reqs if r.state == DONE]
    drained = [r for r in reqs if r.state == DRAINED]
    assert len(done) >= 1 and len(drained) >= 1
    assert len(done) + len(drained) == 6
    for r in done:
        assert len(r.tokens) == r.max_new  # finished, not truncated
    snap = json.load(open(tmp_path / SNAPSHOT_FILENAME))
    assert len(snap["requests"]) == len(drained)
    assert summary["requests_drained"] == len(drained)
    revived = [Request.from_snapshot(d) for d in snap["requests"]]
    assert [r.max_new for r in revived] == [r.max_new for r in drained]
    np.testing.assert_array_equal(revived[0].prompt_ids,
                                  drained[0].prompt_ids)
    # a submission racing the drain (e.g. an HTTP client after SIGTERM)
    # bounces immediately instead of queueing into a loop that will
    # never admit it
    late = eng.submit(Request(prompt_ids=np.asarray([1, 2], np.int32),
                              max_new=4))
    assert late.state == DRAINED and late._event.is_set()


# -- hot-swap ----------------------------------------------------------------


def test_hot_swap_switches_at_boundary_after_drain(tmp_path):
    """A staged pruned checkpoint compiles on a background thread (the
    engine keeps serving meanwhile) and takes over only once in-flight
    requests finish; requests stamped ``served_by`` the old programs
    match the OLD weights' solo decode, later ones the NEW (pruned)
    weights'."""
    from torchpruner_tpu.checkpoint import save_checkpoint

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    r = prune(model, params, "block1_ffn/gate", [0, 3, 17])
    pm, pp = r.model, r.params
    ck = os.path.join(tmp_path, "ckpt-pruned")
    save_checkpoint(ck, pm, pp)

    eng = ServeEngine(model, params, n_slots=2, max_len=64)
    old_programs = eng.programs
    reqs = synthetic_requests(6, vocab=64, prompt_lens=[4, 6],
                              max_new=[5, 7], seed=3)

    class SwapTraffic:
        """3 requests up front (served by the old weights), swap staged
        at step 2, the last 3 released only AFTER the swap lands."""

        def __init__(self):
            self.early, self.late = reqs[:3], list(reqs[3:])
            self.fired = False

        @property
        def exhausted(self):
            return not self.early and not self.late

        def drain(self):
            out = list(self.early) + list(self.late)
            self.early, self.late = [], []
            return out

        def pump(self, engine):
            n = 0
            while self.early:
                engine.submit(self.early.pop(0))
                n += 1
            if not self.fired and engine.steps >= 2:
                engine.request_swap(ck)
                self.fired = True
            if self.fired and engine.swaps_total >= 1:
                while self.late:
                    engine.submit(self.late.pop(0))
                    n += 1
            return n

    summary = eng.run(SwapTraffic())
    assert summary["swaps"] == 1
    assert summary["requests_completed"] == 6
    assert eng.model.widths() == pm.widths()  # serving the pruned spec
    for q in reqs:
        served_new = q.served_by is not old_programs
        m_, p_ = (pm, pp) if served_new else (model, params)
        want = np.asarray(generate(m_, p_, q.prompt_ids[None],
                                   q.max_new, max_len=eng.max_len))[0]
        np.testing.assert_array_equal(np.asarray(q.tokens, np.int32),
                                      want)
    assert sum(q.served_by is not old_programs for q in reqs) == 3


def test_failed_swap_keeps_serving(tmp_path, capsys):
    """A corrupt/missing swap checkpoint must be reported and dropped —
    the engine keeps serving the current weights and still terminates."""
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=64)
    reqs = synthetic_requests(3, vocab=64, prompt_lens=[4],
                              max_new=[5], seed=6)

    class BadSwap:
        def __init__(self):
            self.inner = OpenLoopTraffic(
                reqs, staggered_arrivals(3, every_steps=1), by_step=True)
            self.fired = False

        @property
        def exhausted(self):
            return self.inner.exhausted

        def drain(self):
            return self.inner.drain()

        def pump(self, engine):
            n = self.inner.pump(engine)
            if not self.fired and engine.steps >= 1:
                engine.request_swap(str(tmp_path / "no-such-ckpt"))
                self.fired = True
            return n

    summary = eng.run(BadSwap())
    assert summary["swaps"] == 0
    assert summary["requests_completed"] == 3
    assert eng._pending_swap is None  # staging failure cleared
    assert "hot-swap failed" in capsys.readouterr().err


def test_by_step_schedule_survives_idle_gaps():
    """A step-indexed arrival far beyond the previous request's
    completion must still be served: the open-loop clock is the
    engine's loop TICKS, which advance while the slot array idles
    (engine.steps would freeze and stall the schedule forever)."""
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=64)
    reqs = synthetic_requests(2, vocab=64, prompt_lens=[4],
                              max_new=[4], seed=8)
    traffic = OpenLoopTraffic(reqs, [0, 60], by_step=True)
    summary = eng.run(traffic)
    assert summary["requests_completed"] == 2
    assert all(len(r.tokens) == 4 for r in reqs)


def test_prefill_bucket_never_exceeds_slot_length():
    """A prompt landing in the top (unaligned) bucket of a non-ladder
    max_len must prefill and insert cleanly — the last bucket is capped
    at max_len, never rounded past the physical cache."""
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=100)
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(9), (97,), 0, 64), np.int32)
    req = eng.submit(Request(prompt_ids=prompt, max_new=3))
    eng.run()
    want = np.asarray(generate(model, params, prompt[None], 3,
                               max_len=eng.max_len))[0]
    np.testing.assert_array_equal(np.asarray(req.tokens, np.int32), want)


# -- obs / report ------------------------------------------------------------


def test_serve_obs_histograms_and_report(tmp_path):
    """A served run under an obs session must emit non-empty TTFT and
    per-token histograms, serve counters/gauges, a ledger provenance
    record, and an `obs report` rendering with the serve section."""
    from torchpruner_tpu.obs.report import format_report, load_run

    obs_dir = str(tmp_path / "obs")
    session = obs.configure(obs_dir)
    try:
        model = llama_tiny()
        params, _ = init_model(model, seed=0)
        eng = ServeEngine(model, params, n_slots=2, max_len=64,
                          checkpoint_meta={"digest": "feedbeef"})
        reqs = synthetic_requests(5, vocab=64, prompt_lens=[4, 6],
                                  max_new=[4, 6], seed=4)
        traffic = OpenLoopTraffic(reqs,
                                  staggered_arrivals(5, every_steps=2),
                                  by_step=True)
        with obs.span("serve"):
            eng.run(traffic)
        ttft = session.metrics.get("serve_ttft_seconds")
        gaps = session.metrics.get("serve_token_seconds")
        assert ttft is not None and ttft.count == 5
        assert gaps is not None and gaps.count > 0
        assert obs.counter_value("serve_completed_total") == 5
        assert obs.counter_value("serve_admits_total") == 5
        assert obs.counter_value("serve_evictions_total") == 5
        assert obs.counter_value("serve_decode_steps_total") > 0
    finally:
        obs.shutdown()
    report = load_run(obs_dir)
    serve_records = report.get("serve") or []
    assert any(r.get("kind") == "summary"
               and r.get("checkpoint_digest") == "feedbeef"
               for r in serve_records)
    md = format_report(report)
    assert "serve:" in md and "TTFT p50/p99" in md
    m = report["metrics"]
    assert m.get("serve_ttft_seconds_p50") is not None
    assert m.get("serve_token_seconds_p99") is not None


def test_serve_scalars_diff_and_gates():
    """serve_* scalars participate in `obs diff` and gate checking —
    what wires the serve CI smoke into `obs diff --gate`."""
    from torchpruner_tpu.obs.report import check_gates, diff_runs

    def rep(ttft, tok, completed):
        return {"metrics": {
            "serve_ttft_seconds_p50": ttft,
            "serve_ttft_seconds_p99": ttft * 2,
            "serve_token_seconds_p50": tok,
            "serve_token_seconds_p99": tok * 3,
            "serve_gen_tokens_per_s": 100.0,
            "serve_completed_total": completed,
        }}

    d = diff_runs(rep(0.01, 0.001, 16), rep(0.05, 0.001, 14))
    assert d["scalars"]["serve_ttft_p50_s"]["pct"] == pytest.approx(400.0)
    gates = {"serve_ttft_p50_s": {"max_increase_pct": 300},
             "serve_completed": {"max_decrease": 0}}
    violations = check_gates(d, gates)
    assert {v["gate"] for v in violations} == {"serve_ttft_p50_s",
                                              "serve_completed"}
    assert not check_gates(diff_runs(rep(0.01, 0.001, 16),
                                     rep(0.01, 0.001, 16)), gates)


# -- front ends --------------------------------------------------------------


def test_http_endpoint_roundtrip():
    """POST /v1/generate through the threaded HTTP front end returns the
    engine's tokens; /healthz and /stats respond."""
    import urllib.request

    from torchpruner_tpu.serve.frontend import _http_server

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=64)
    server = _http_server(eng, 0, request_timeout_s=120.0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    stop = threading.Event()
    loop = threading.Thread(
        target=lambda: eng.run(stop_event=stop), daemon=True)
    loop.start()
    try:
        body = json.dumps({"prompt_ids": [5, 9, 2, 14],
                           "max_new": 6}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate", data=body,
            headers={"Content-Type": "application/json"})
        out = json.load(urllib.request.urlopen(req, timeout=120))
        assert out["state"] == "done" and len(out["tokens"]) == 6
        want = np.asarray(generate(
            model, params, np.asarray([[5, 9, 2, 14]], np.int32), 6,
            max_len=eng.max_len))[0]
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)
        health = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10))
        assert health["ok"]
        stats = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10))
        assert stats["gen_tokens"] >= 6
        # occupancy/utilization gauges ride /stats (idle engine -> 0)
        assert 0.0 <= stats["kv_page_occupancy"] <= 1.0
        assert 0.0 <= stats["slot_utilization"] <= 1.0
        assert stats["kv_page_budget"] > 0
        # no obs session in this test: /metrics degrades to 503, and
        # POST /profile reports it cannot arm a window
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10)
            raise AssertionError("expected 503 without a session")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/profile", data=b"",
                method="POST"), timeout=10)
            raise AssertionError("expected 409 without a profiler")
        except urllib.error.HTTPError as e:
            assert e.code == 409
            assert json.load(e)["armed"] is False
    finally:
        stop.set()
        server.shutdown()
        loop.join(timeout=30)


def test_http_metrics_endpoint_with_session(tmp_path):
    """GET /metrics serves the live Prometheus exposition (same format
    as metrics.prom) and POST /profile arms an on-demand capture window
    when a session with a profiler is active."""
    import urllib.request

    from torchpruner_tpu.serve.frontend import _http_server
    from torchpruner_tpu.serve.slo import SLOMonitor

    session = obs.configure(str(tmp_path / "obs"))
    try:
        model = llama_tiny()
        params, _ = init_model(model, seed=0)
        eng = ServeEngine(model, params, n_slots=2, max_len=64)
        eng.slo = SLOMonitor(ttft_p99_s=1.0, window=32,
                             check_every_steps=1)
        server = _http_server(eng, 0, request_timeout_s=120.0)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        stop = threading.Event()
        loop = threading.Thread(
            target=lambda: eng.run(stop_event=stop), daemon=True)
        loop.start()
        try:
            body = json.dumps({"prompt_ids": [3, 1, 4],
                               "max_new": 4}).encode()
            out = json.load(urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=120))
            assert out["state"] == "done"
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10).read()
            text = text.decode()
            assert "# TYPE serve_ttft_seconds histogram" in text
            assert "serve_slot_utilization" in text
            assert "serve_kv_page_occupancy" in text
            assert "serve_ttft_p99_rolling_s" in text  # SLO gauge live
            armed = json.load(urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/profile", data=b"",
                    method="POST"), timeout=10))
            assert armed["armed"] is True
            # the engine thread opens the window; start_trace's first
            # call can take seconds (profiler session init) — poll
            # until it becomes observable (armed -> opening -> open)
            prof = session.profiler
            deadline = time.time() + 60
            while time.time() < deadline:
                if prof.active or prof.windows or prof._failed:
                    break
                time.sleep(0.05)
            assert not prof._failed
            assert prof.active or prof.windows
            stats = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=10))
            assert stats["slo"]["breaches_total"] == 0
            assert stats["slo"]["thresholds_ms"]["ttft"] == 1000.0
        finally:
            stop.set()
            server.shutdown()
            loop.join(timeout=30)
    finally:
        obs.shutdown()


def test_healthz_readiness_states(tmp_path):
    """/healthz splits liveness from readiness: ready answers 200;
    draining / staging_swap / slo_breach answer 503 with the state
    named, so a probe (or the fleet router) stops dispatching BEFORE a
    drain completes."""
    import urllib.error
    import urllib.request

    from torchpruner_tpu.serve.frontend import _http_server
    from torchpruner_tpu.serve.slo import SLOMonitor

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=64)
    server = _http_server(eng, 0, request_timeout_s=10.0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    def probe():
        try:
            out = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10))
            return 200, out
        except urllib.error.HTTPError as e:
            return e.code, json.load(e)

    try:
        code, out = probe()
        # "ts" is the replica's wall clock — the fleet router's
        # clock-offset estimate (distributed tracing) rides this probe
        assert abs(out.pop("ts") - time.time()) < 60
        assert code == 200 and out == {"ok": True, "live": True,
                                       "state": "ready"}
        # staging a swap degrades readiness (router rotates away)
        eng._pending_swap = "/fake/ckpt"
        code, out = probe()
        assert code == 503 and out["state"] == "staging_swap"
        assert out["live"] and not out["ok"]
        eng._pending_swap = None
        # an SLO breach episode degrades readiness
        eng.slo = SLOMonitor(ttft_p99_s=0.001, window=8,
                             check_every_steps=1, min_samples=1)
        eng.slo.on_ttft(1.0)
        eng.slo.check(0)
        assert eng.slo.in_breach_any()
        code, out = probe()
        assert code == 503 and out["state"] == "slo_breach"
        eng.slo = None
        # a drain (scheduler closed) wins over everything
        eng.scheduler.closed = True
        code, out = probe()
        assert code == 503 and out["state"] == "draining"
        # /stats carries the same state + the swap counter the rolling
        # fleet upgrade polls
        stats = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10))
        assert stats["state"] == "draining" and stats["swaps"] == 0
    finally:
        server.shutdown()


def test_http_backpressure_sheds_with_retry_after():
    """Over-capacity POSTs get 503 + Retry-After (bounded queue), never
    an unboundedly growing queue: with queue_bound=1 and no engine loop
    draining it, the second submission is shed immediately while the
    first stays queued."""
    import urllib.error
    import urllib.request

    from torchpruner_tpu import obs as obs_mod
    from torchpruner_tpu.serve.frontend import _http_server
    from torchpruner_tpu.serve.request import SHED

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=64,
                      queue_bound=1)
    server = _http_server(eng, 0, request_timeout_s=60.0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    body = json.dumps({"prompt_ids": [5, 9, 2], "max_new": 4}).encode()

    def post():
        return urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate", data=body,
            headers={"Content-Type": "application/json"}), timeout=60)

    first_result = {}
    t = threading.Thread(
        target=lambda: first_result.update(json.load(post())),
        daemon=True)
    t.start()
    deadline = time.time() + 30
    while eng.scheduler.queue_depth < 1 and time.time() < deadline:
        time.sleep(0.01)
    assert eng.scheduler.queue_depth == 1
    try:
        post()
        raise AssertionError("expected 503 over capacity")
    except urllib.error.HTTPError as e:
        assert e.code == 503
        assert int(e.headers["Retry-After"]) >= 1
        assert json.load(e)["state"] == SHED
    assert eng.scheduler.shed_total == 1
    try:
        # the engine drains the queued request; the shed one is gone
        eng.run()
        t.join(timeout=60)
        assert first_result.get("state") == "done"
        assert len(first_result["tokens"]) == 4
    finally:
        server.shutdown()


def test_http_swap_endpoint_stages_hot_swap(tmp_path):
    """POST /swap stages a checkpoint hot-swap on the live endpoint
    (202; 409 while one is already staging) — the per-replica step of
    the fleet's rolling upgrade."""
    import urllib.error
    import urllib.request

    from torchpruner_tpu.checkpoint import save_checkpoint
    from torchpruner_tpu.serve.frontend import _http_server

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    r = prune(model, params, "block1_ffn/gate", [1, 2])
    ck = os.path.join(tmp_path, "ckpt-pruned")
    save_checkpoint(ck, r.model, r.params)

    eng = ServeEngine(model, params, n_slots=2, max_len=64)
    server = _http_server(eng, 0, request_timeout_s=60.0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    stop = threading.Event()
    loop = threading.Thread(target=lambda: eng.run(stop_event=stop),
                            daemon=True)
    loop.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/swap",
            data=json.dumps({"checkpoint": ck}).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=30)
        assert resp.status == 202 and json.load(resp)["staging"]
        # a second staging request while one is in flight: 409 (unless
        # the first already landed, which is also a pass)
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/swap",
                data=json.dumps({"checkpoint": ck}).encode(),
                headers={"Content-Type": "application/json"}),
                timeout=30)
            assert eng.swaps_total >= 1
        except urllib.error.HTTPError as e:
            assert e.code == 409
        deadline = time.time() + 120
        while eng.swaps_total < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert eng.swaps_total == 1
        stats = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10))
        assert stats["swaps"] == 1
        assert eng.model.widths() == r.model.widths()
    finally:
        stop.set()
        server.shutdown()
        loop.join(timeout=30)


def test_queue_snapshot_resubmission_roundtrip(tmp_path):
    """The PR 6 drain snapshot actually ROUND-TRIPS: requests drained
    by a SIGTERM-style preemption are resubmitted from
    serve_queue_snapshot.json into a fresh engine and decode
    BIT-IDENTICALLY to what an uninterrupted engine (and solo
    generate()) produces — the redrive path the fleet router rides."""
    from torchpruner_tpu.resilience.guards import PreemptionHandler
    from torchpruner_tpu.serve.engine import SNAPSHOT_FILENAME

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    eng = ServeEngine(model, params, n_slots=2, max_len=96,
                      run_dir=str(tmp_path))
    reqs = synthetic_requests(6, vocab=64, prompt_lens=[4, 7],
                              max_new=[16, 12], seed=21,
                              temperature=0.8)
    traffic = OpenLoopTraffic(reqs, staggered_arrivals(6, every_steps=1),
                              by_step=True)
    pre = PreemptionHandler()

    class FireAt:
        def __init__(self, inner):
            self.inner = inner

        @property
        def exhausted(self):
            return self.inner.exhausted

        def drain(self):
            return self.inner.drain()

        def pump(self, engine):
            n = self.inner.pump(engine)
            if engine.steps == 4:
                pre.request()
            return n

    eng.run(FireAt(traffic), preemption=pre)
    drained = [r for r in reqs if r.state == DRAINED]
    assert drained, "drill needs at least one drained request"
    snap = json.load(open(tmp_path / SNAPSHOT_FILENAME))
    assert len(snap["requests"]) == len(drained)

    # resubmit the snapshot into a FRESH engine (the restart path)
    eng2 = ServeEngine(model, params, n_slots=2, max_len=96)
    revived = [eng2.submit(Request.from_snapshot(d))
               for d in snap["requests"]]
    eng2.run()
    from torchpruner_tpu.generate import generate as _generate

    for r in revived:
        assert r.state == DONE and len(r.tokens) == r.max_new
        s = r.sampling
        want = np.asarray(_generate(
            model, params, r.prompt_ids[None], r.max_new,
            temperature=s.temperature, top_k=s.top_k, top_p=s.top_p,
            rng=jax.random.PRNGKey(s.seed), max_len=eng2.max_len))[0]
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      want)


def test_poisson_arrivals_seeded_and_monotone():
    a = poisson_arrivals(50, rate_per_s=10.0, seed=3)
    b = poisson_arrivals(50, rate_per_s=10.0, seed=3)
    assert a == b and all(x < y for x, y in zip(a, a[1:]))
    mean_gap = a[-1] / 50
    assert 0.03 < mean_gap < 0.3  # ~1/rate


def test_example_06_imports():
    """The serving example stays import-smoke-tested (its heavy work is
    inside main(), so import is cheap)."""
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "06_serve_8b_on_one_chip.py")
    spec = importlib.util.spec_from_file_location("example_06_serve",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert callable(mod.main)

"""Composite-block tests: Residual / attention / GLU specs, nested-path
instrumentation, recursive pruning-graph inference, and structural pruning
correctness via prune-vs-mask equivalence (the composite-model analog of the
reference's NaN-cascade tests, reference tests/test_pruner.py:72-121)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.graph import (
    find_best_evaluation_layer,
    group_for,
    pruning_graph,
)
from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.core.segment import SegmentedModel, init_model


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


def resnet_blocklet():
    """Stem conv -> projection-shortcut residual -> identity residual ->
    head.  Covers: stem cascade into body+shortcut, inner conv groups,
    body-final conv exclusion."""
    return SegmentedModel(
        layers=(
            L.Conv("stem", 8, (3, 3), use_bias=False),
            L.BatchNorm("stem_bn"),
            L.Activation("stem_relu", "relu"),
            L.Residual(
                "block1",
                body=(
                    L.Conv("conv1", 8, (3, 3), use_bias=False),
                    L.BatchNorm("bn1"),
                    L.Activation("relu1", "relu"),
                    L.Conv("conv2", 16, (3, 3), use_bias=False),
                    L.BatchNorm("bn2"),
                ),
                shortcut=(
                    L.Conv("sc", 16, (1, 1), use_bias=False),
                    L.BatchNorm("sc_bn"),
                ),
            ),
            L.Residual(
                "block2",
                body=(
                    L.Conv("conv1", 12, (3, 3), use_bias=False),
                    L.BatchNorm("bn1"),
                    L.Activation("relu1", "relu"),
                    L.Conv("conv2", 16, (3, 3), use_bias=False),
                    L.BatchNorm("bn2"),
                ),
            ),
            L.GlobalPool("pool", "avg"),
            L.Dense("head", 10),
        ),
        input_shape=(8, 8, 3),
    )


def tiny_transformer(causal=False, gated=False, heads=4, kv_heads=None):
    """Embedding -> pre-LN attention block -> pre-LN FFN block -> head."""
    d, dh = 16, 4
    ffn_body = (
        (
            L.RMSNorm("norm"),
            L.GatedDense("wi", 32, fn="silu"),
            L.Dense("wo", d, use_bias=False),
        )
        if gated
        else (
            L.LayerNorm("norm"),
            L.Dense("wi", 32),
            L.Activation("act", "gelu"),
            L.Dense("wo", d),
        )
    )
    norm = L.RMSNorm if gated else L.LayerNorm
    return SegmentedModel(
        layers=(
            L.Embedding("emb", 11, d),
            L.PosEmbed("pos", 12),
            L.Residual(
                "attn_block",
                body=(
                    norm("norm"),
                    L.MultiHeadAttention(
                        "attn", heads, dh, num_kv_heads=kv_heads,
                        causal=causal, rope=gated, use_bias=not gated,
                        impl="xla",
                    ),
                ),
            ),
            L.Residual("ffn_block", body=ffn_body),
            norm("final_norm"),
            L.GlobalPool("pool", "seq_mean"),
            L.Dense("head", 7),
        ),
        input_shape=(12,),
        input_dtype="int32",
    )


def tokens(model, batch=4, seed=0):
    return model.example_input(batch, seed)


# ---------------------------------------------------------------------------
# spec / apply basics
# ---------------------------------------------------------------------------


def test_residual_forward_shapes():
    model = resnet_blocklet()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 3))
    y, _ = model.apply(params, x, state=state)
    assert y.shape == (2, 10)
    assert model.out_shape("block1") == (8, 8, 16)
    assert model.out_shape("block1/conv1") == (8, 8, 8)
    assert model.in_shape("block1/sc") == (8, 8, 3 * 0 + 8)  # block input: 8ch


def test_transformer_forward_shapes():
    for gated in (False, True):
        model = tiny_transformer(gated=gated, causal=gated,
                                 kv_heads=2 if gated else None)
        params, state = init_model(model, seed=0)
        y, _ = model.apply(params, tokens(model), state=state)
        assert y.shape == (4, 7)


def test_identity_residual_shape_mismatch_raises():
    with pytest.raises(ValueError):
        model = SegmentedModel(
            layers=(
                L.Dense("fc", 8),
                L.Residual("r", body=(L.Dense("inner", 9),)),
            ),
            input_shape=(8,),
        )
        init_model(model)


def test_nested_layer_resolution():
    model = resnet_blocklet()
    assert model.layer("block1/conv2").features == 16
    assert model.layer("block1/sc").kernel_size == (1, 1)
    with pytest.raises(KeyError):
        model.layer("block1/nope")
    assert model.site_shape("block1/conv1") == (8, 8, 8)


def test_mha_site_shape_is_head_context():
    model = tiny_transformer()
    # (S, Dh, H): head axis last
    assert model.site_shape("attn_block/attn") == (12, 4, 4)


def test_widths_recurse():
    w = resnet_blocklet().widths()
    assert w["stem"] == 8 and w["block1/conv1"] == 8 and w["head"] == 10
    w = tiny_transformer().widths()
    assert w["attn_block/attn"] == 4 and w["ffn_block/wi"] == 32


# ---------------------------------------------------------------------------
# taps at nested sites
# ---------------------------------------------------------------------------


def test_nested_capture_and_mask():
    model = resnet_blocklet()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 3))
    y, _, z = model.apply(params, x, state=state, capture="block1/conv1")
    assert z.shape == (2, 8, 8, 8)
    mask = jnp.zeros((8,)).at[:4].set(1.0)
    y2, _, z2 = model.apply(
        params, x, state=state, unit_mask=("block1/conv1", mask),
        capture="block1/conv1",
    )
    assert np.allclose(np.asarray(z2[..., 4:]), 0.0)
    assert not np.allclose(np.asarray(y), np.asarray(y2))


def test_head_mask_zeroes_head_contribution():
    model = tiny_transformer()
    params, state = init_model(model, seed=0)
    x = tokens(model)
    z = model.apply(params, x, state=state, capture="attn_block/attn")[2]
    assert z.shape == (4, 12, 4, 4)  # (B, S, Dh, H)
    # masking ALL heads == zero attention output == residual passthrough
    y_masked, _ = model.apply(
        params, x, state=state,
        unit_mask=("attn_block/attn", jnp.zeros((4,))),
    )
    # manually compute: remove the attention block entirely except bo
    bo = params["attn_block"]["attn"].get("bo")
    stripped = SegmentedModel(
        layers=tuple(
            l for l in model.layers if l.name != "attn_block"
        ),
        input_shape=model.input_shape,
        input_dtype=model.input_dtype,
    )
    sp = {k: v for k, v in params.items() if k != "attn_block"}
    h, _ = stripped.apply(sp, x, state=state)
    # not exactly equal (bo still added); equal when bo is zero at init
    assert np.allclose(np.asarray(y_masked), np.asarray(h), atol=1e-5)


def test_perturb_matches_mask_gradient():
    """grad wrt an additive perturbation at a site == activation gradient."""
    model = tiny_transformer(gated=True, kv_heads=2)
    params, state = init_model(model, seed=0)
    x = tokens(model)
    site = "ffn_block/wi"
    zshape = model.site_shape(site)

    def loss_via_perturb(delta):
        y, _ = model.apply(params, x, state=state, perturb=(site, delta))
        return jnp.sum(y**2)

    g = jax.grad(loss_via_perturb)(jnp.zeros((4,) + zshape))
    assert g.shape == (4,) + zshape
    assert float(jnp.abs(g).sum()) > 0


# ---------------------------------------------------------------------------
# pruning-graph inference
# ---------------------------------------------------------------------------


def test_resnet_pruning_graph():
    model = resnet_blocklet()
    graph = pruning_graph(model)
    targets = {g.target: g for g in graph}
    # stem cascades into both block1 chains (projection shortcut present)
    assert "stem" in targets
    stem = targets["stem"]
    assert {c.layer for c in stem.consumers} == {"block1/conv1", "block1/sc"}
    assert {b.layer for b in stem.attached_bn} == {"stem_bn"}
    # inner conv1 groups prunable; body-final conv2 and shortcut sc excluded
    assert "block1/conv1" in targets and "block2/conv1" in targets
    assert "block1/conv2" not in targets
    assert "block1/sc" not in targets
    assert "block2/conv2" not in targets
    # block2 has an identity skip: nothing cascades into it from outside
    inner = targets["block1/conv1"]
    assert {c.layer for c in inner.consumers} == {"block1/conv2"}
    assert {b.layer for b in inner.attached_bn} == {"block1/bn1"}
    # head (model output) excluded by default
    assert "head" not in targets
    assert "head" in {g.target for g in pruning_graph(model, include_output=True)}


def test_transformer_pruning_graph():
    for gated in (False, True):
        model = tiny_transformer(gated=gated)
        targets = {g.target: g for g in pruning_graph(model)}
        # head group: self-contained
        assert targets["attn_block/attn"].consumers == ()
        # FFN hidden: consumer is wo inside the block
        ffn = targets["ffn_block/wi"]
        assert {c.layer for c in ffn.consumers} == {"ffn_block/wo"}
        # wo (body-final) and the residual stream are not prunable
        assert "ffn_block/wo" not in targets
        assert "emb" not in targets


def test_find_best_evaluation_layer_nested():
    model = resnet_blocklet()
    assert find_best_evaluation_layer(model, "block1/conv1") == "block1/relu1"
    assert find_best_evaluation_layer(model, "stem") == "stem_relu"
    t = tiny_transformer()
    assert find_best_evaluation_layer(t, "attn_block/attn") == "attn_block/attn"
    assert find_best_evaluation_layer(t, "ffn_block/wi") == "ffn_block/wi"


# ---------------------------------------------------------------------------
# structural pruning correctness: prune ≡ mask
# ---------------------------------------------------------------------------


def assert_prune_equals_mask(model, target, drop, mask_site, x, atol=1e-5):
    """Pruning units ``drop`` of ``target`` must produce the same model output
    as zero-masking those units at ``mask_site`` (the site just before the
    consumer — after attached norms).  Eval mode."""
    params, state = init_model(model, seed=0)
    n = L.n_units(model.layer(target))
    mask = jnp.ones((n,)).at[jnp.asarray(drop)].set(0.0)
    y_masked, _ = model.apply(
        params, x, state=state, unit_mask=(mask_site, mask)
    )
    res = prune(model, params, target, drop, state=state)
    y_pruned, _ = res.model.apply(res.params, x, state=res.state)
    np.testing.assert_allclose(
        np.asarray(y_masked), np.asarray(y_pruned), atol=atol
    )
    return res


def test_prune_resnet_inner_conv():
    model = resnet_blocklet()
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, 8, 3))
    res = assert_prune_equals_mask(
        model, "block1/conv1", [1, 5, 6], "block1/relu1", x
    )
    assert res.model.layer("block1/conv1").features == 5
    assert res.params["block1"]["conv1"]["w"].shape == (3, 3, 8, 5)
    assert res.params["block1"]["conv2"]["w"].shape == (3, 3, 5, 16)
    assert res.params["block1"]["bn1"]["scale"].shape == (5,)
    assert res.state["block1"]["bn1"]["mean"].shape == (5,)


def test_prune_resnet_stem_cascades_into_block():
    model = resnet_blocklet()
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, 8, 3))
    res = assert_prune_equals_mask(model, "stem", [0, 7], "stem_relu", x)
    assert res.params["stem"]["w"].shape == (3, 3, 3, 6)
    assert res.params["block1"]["conv1"]["w"].shape == (3, 3, 6, 8)
    assert res.params["block1"]["sc"]["w"].shape == (1, 1, 6, 16)
    assert res.params["stem_bn"]["scale"].shape == (6,)


def test_prune_ffn_hidden_dense():
    model = tiny_transformer(gated=False)
    x = tokens(model)
    # mask site: hidden activations after gelu (== after what pruning cuts)
    res = assert_prune_equals_mask(
        model, "ffn_block/wi", [0, 3, 31], "ffn_block/act", x
    )
    assert res.params["ffn_block"]["wi"]["w"].shape == (16, 29)
    assert res.params["ffn_block"]["wo"]["w"].shape == (29, 16)


def test_prune_ffn_hidden_gated():
    model = tiny_transformer(gated=True, kv_heads=2)
    x = tokens(model)
    res = assert_prune_equals_mask(
        model, "ffn_block/wi", [2, 17], "ffn_block/wi", x
    )
    assert res.params["ffn_block"]["wi"]["wg"].shape == (16, 30)
    assert res.params["ffn_block"]["wi"]["wu"].shape == (16, 30)
    assert res.params["ffn_block"]["wo"]["w"].shape == (30, 16)


def test_prune_attention_heads_mha():
    model = tiny_transformer(gated=False)
    x = tokens(model)
    res = assert_prune_equals_mask(
        model, "attn_block/attn", [1, 2], "attn_block/attn", x
    )
    attn = res.model.layer("attn_block/attn")
    assert attn.num_heads == 2 and attn.kv_heads == 2
    p = res.params["attn_block"]["attn"]
    assert p["wq"].shape == (16, 2, 4)
    assert p["wk"].shape == (16, 2, 4)
    assert p["wo"].shape == (2, 4, 16)
    assert p["bq"].shape == (2, 4)


def test_prune_attention_heads_gqa():
    """GQA: query heads prunable, shared KV heads untouched."""
    model = tiny_transformer(gated=True, causal=True, kv_heads=2)
    x = tokens(model)
    res = assert_prune_equals_mask(
        model, "attn_block/attn", [3], "attn_block/attn", x
    )
    attn = res.model.layer("attn_block/attn")
    assert attn.num_heads == 3 and attn.kv_heads == 2
    p = res.params["attn_block"]["attn"]
    assert p["wq"].shape == (16, 3, 4)
    assert p["wk"].shape == (16, 2, 4)  # shared KV: not sliced
    assert p["wo"].shape == (3, 4, 16)


def test_prune_gqa_head_forward_still_runs():
    """After pruning a GQA query head, H is no longer divisible by KV —
    the grouped repeat must still map groups correctly."""
    model = tiny_transformer(gated=True, causal=True, kv_heads=2)
    params, state = init_model(model, seed=0)
    x = tokens(model)
    res = prune(model, params, "attn_block/attn", [0], state=state)
    y, _ = res.model.apply(res.params, x, state=res.state)
    assert y.shape == (4, 7)
    assert np.all(np.isfinite(np.asarray(y)))


def test_prune_with_optimizer_state():
    model = tiny_transformer(gated=True, kv_heads=2)
    params, state = init_model(model, seed=0)
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    res = prune(
        model, params, "ffn_block/wi", [0, 1], state=state,
        opt_state=opt_state,
    )
    # Adam mu/nu sliced alongside params
    flat = jax.tree_util.tree_leaves_with_path(res.opt_state)
    mus = [
        leaf
        for path, leaf in flat
        if any("wg" == getattr(k, "key", None) for k in path)
        and hasattr(leaf, "shape")
    ]
    assert mus and all(m.shape == (16, 30) for m in mus)
    # pruned training step still runs
    def loss(p):
        y, _ = res.model.apply(p, tokens(model), state=res.state)
        return jnp.mean(y**2)

    g = jax.grad(loss)(res.params)
    updates, _ = tx.update(g, res.opt_state, res.params)
    optax.apply_updates(res.params, updates)


def test_spec_roundtrip_composite(tmp_path):
    """Composite / transformer specs survive spec_to_dict/spec_from_dict,
    including a pruned GQA layer's irregular kv_group and input_dtype."""
    from torchpruner_tpu.checkpoint import spec_from_dict, spec_to_dict

    model = tiny_transformer(gated=True, causal=True, kv_heads=2)
    params, state = init_model(model, seed=0)
    res = prune(model, params, "attn_block/attn", [0], state=state)
    restored = spec_from_dict(spec_to_dict(res.model))
    assert restored == res.model
    restored2 = spec_from_dict(spec_to_dict(resnet_blocklet()))
    assert restored2 == resnet_blocklet()


def test_with_features_rejects_irregular_kv_group():
    spec = L.MultiHeadAttention("a", 4, 8, num_kv_heads=2)
    irregular = L.pruned_spec(spec, [0, 2, 3])
    assert irregular.kv_group == (0, 1, 1)
    with pytest.raises(ValueError):
        L.with_features(irregular, 2)


def test_same_avg_pool_excludes_padding():
    model = SegmentedModel(
        layers=(L.Pool("p", "avg", (2, 2), padding="SAME"),),
        input_shape=(3, 3, 1),
    )
    x = jnp.ones((1, 3, 3, 1))
    y, _ = model.apply({}, x)
    # all-ones input must stay all-ones when padding is excluded
    np.testing.assert_allclose(np.asarray(y), 1.0)


def test_group_for_nested():
    model = resnet_blocklet()
    g = group_for(model, "block1/conv1")
    assert g.target == "block1/conv1"
    with pytest.raises(KeyError):
        group_for(model, "block1/bn1")

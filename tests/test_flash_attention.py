"""Flash-attention kernel tests: Pallas (interpret mode on CPU) vs the XLA
einsum reference, forward and gradients, causal and bidirectional, plus the
fallback path for non-blocking sequence lengths."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from torchpruner_tpu.ops.flash_attention import (
    _pick_blocks,
    _xla_attention,
    flash_attention,
)


def qkv(B=2, S=64, H=3, Dh=8, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, S, H, Dh)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_xla(causal):
    q, k, v = qkv()
    out = flash_attention(q, k, v, causal=causal)
    ref = _xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_xla(causal):
    q, k, v = qkv(S=32)
    g = jax.random.normal(jax.random.PRNGKey(9), q.shape)

    def loss(fn):
        def f(q_, k_, v_):
            return jnp.sum(fn(q_, k_, v_) * g)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    got = loss(lambda a, b, c: flash_attention(a, b, c, causal=causal))
    want = loss(lambda a, b, c: _xla_attention(a, b, c, causal=causal))
    for ga, gw in zip(got, want):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gw), atol=1e-4)


def test_blocking_selection():
    assert _pick_blocks(256) == (128, 128)
    assert _pick_blocks(64) == (64, 64)
    assert _pick_blocks(96) == (96, 96)  # < 128: single block
    assert _pick_blocks(200) == (8, 8)  # 200 = 8 * 25: halve down to 8
    # awkward lengths must take the XLA fallback, not a (1, 1)-tile kernel
    assert _pick_blocks(2047) is None  # odd > 128: halves all the way to 1
    assert _pick_blocks(132) is None  # 132 = 4 * 33: stops below MIN_BLOCK
    assert _pick_blocks(4) is None  # shorter than the minimum block


def test_block_size_override_matches():
    """Caller-tuned tile sizes (forward and backward) must not change
    numerics — only scheduling."""
    assert _pick_blocks(512, 256, 64) == (256, 64)
    q, k, v = qkv(S=64)

    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=32)
    ref = _xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def loss(fn):
        return jax.grad(lambda a: jnp.sum(fn(a, k, v)))(q)

    got = loss(lambda a, b=k, c=v: flash_attention(
        a, b, c, causal=True, block_q=16, block_k=32))
    want = loss(lambda a, b=k, c=v: _xla_attention(a, b, c, causal=True))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


def test_odd_length_still_matches():
    q, k, v = qkv(S=17)  # prime-ish length: single (17, 17) block
    out = flash_attention(q, k, v, causal=True)
    ref = _xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_causal_first_row_attends_self_only():
    q, k, v = qkv(S=16)
    out = flash_attention(q, k, v, causal=True)
    # position 0 can only attend to itself: output == v[:, 0]
    np.testing.assert_allclose(
        np.asarray(out[:, 0]), np.asarray(v[:, 0]), atol=1e-5
    )


def test_bf16_runs_and_is_close():
    q, k, v = qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=False)
    ref = _xla_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=False,
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref), atol=3e-2
    )


@pytest.mark.parametrize("causal", [False, True])
def test_multiblock_gradients_match_xla(causal):
    """S=256 -> 2x2 blocks of 128: exercises KV streaming and the causal
    block-skipping in both backward kernels."""
    q, k, v = qkv(B=1, S=256, H=2, Dh=8, seed=3)
    g = jax.random.normal(jax.random.PRNGKey(4), q.shape)

    def grads(fn):
        def f(q_, k_, v_):
            return jnp.sum(fn(q_, k_, v_) * g)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    got = grads(lambda a, b, c: flash_attention(a, b, c, causal=causal))
    want = grads(lambda a, b, c: _xla_attention(a, b, c, causal=causal))
    for ga, gw in zip(got, want):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gw),
                                   atol=2e-4, rtol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_unequal_block_sizes_backward(causal):
    """Directly drive the backward kernels with block_q != block_k (the
    dkv kernel's i_start rounding is only exercised this way)."""
    from torchpruner_tpu.ops.flash_attention import _flash_bwd, _flash_fwd

    q, k, v = qkv(B=1, S=64, H=2, Dh=8, seed=5)
    g = jax.random.normal(jax.random.PRNGKey(6), q.shape)
    qt, kt, vt, gt = (jnp.moveaxis(t, 2, 1) for t in (q, k, v, g))
    o, lse = _flash_fwd(qt, kt, vt, causal, 16, 32, True)
    dq, dk, dv = _flash_bwd(qt, kt, vt, o, lse, gt, causal, 16, 32, True)

    def f(q_, k_, v_):
        return jnp.sum(_xla_attention(q_, k_, v_, causal=causal) * g)

    wq, wk, wv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    for got, want in ((dq, wq), (dk, wk), (dv, wv)):
        np.testing.assert_allclose(
            np.asarray(jnp.moveaxis(got, 1, 2)), np.asarray(want),
            atol=2e-4, rtol=1e-3,
        )


def test_bf16_gradients_run():
    q, k, v = qkv(S=32, dtype=jnp.bfloat16)

    def f(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=True))

    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    assert dq.dtype == jnp.bfloat16
    assert all(bool(jnp.all(jnp.isfinite(t.astype(jnp.float32))))
               for t in (dq, dk, dv))


def test_auto_dispatch_is_seq_length_aware(monkeypatch):
    """impl="auto" keeps the XLA path on CPU always, and on TPU below
    FLASH_AUTO_MIN_S (the measured S=2048 point has XLA faster with
    affordable memory); flash engages only where its linear-in-S backward
    memory matters."""
    from torchpruner_tpu.core import layers as L

    calls = []
    monkeypatch.setattr(
        "torchpruner_tpu.ops.flash_attention.flash_attention",
        lambda q, k, v, causal: calls.append(q.shape) or _xla_attention(
            q, k, v, causal=causal),
    )
    q, k, v = qkv(B=1, S=16, H=2, Dh=8)
    L.attention_core(q, k, v, causal=True, impl="auto")
    assert calls == []  # cpu backend -> xla
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    L.attention_core(q, k, v, causal=True, impl="auto")
    assert calls == []  # tpu but S=16 < FLASH_AUTO_MIN_S -> xla
    monkeypatch.setattr(L, "FLASH_AUTO_MIN_S", 16)
    L.attention_core(q, k, v, causal=True, impl="auto")
    assert len(calls) == 1  # tpu and S >= threshold -> flash kernel


def test_cross_attention_lengths_route_to_xla_path():
    """Differing q/k lengths (cross attention) are outside the kernel's
    grid (built from q's length); they must compute through the XLA
    path — full key coverage — not silently truncate K/V."""
    q, _, _ = qkv(S=32)
    _, k, v = qkv(S=128, seed=1)
    got = flash_attention(q, k, v, causal=False)
    want = _xla_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    # the long-S block_k bump keys on k's length and must not force the
    # kernel for these shapes either
    q2, _, _ = qkv(S=64, seed=2)
    _, k2, v2 = qkv(S=128, seed=3)
    got2 = flash_attention(q2, k2, v2, causal=False)
    want2 = _xla_attention(q2, k2, v2, causal=False)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2),
                               rtol=1e-6, atol=1e-6)


def test_causal_cross_attention_bottom_right_aligned():
    """Causal with a query chunk shorter than the KV prefix (chunked
    prefill): query i sees keys j <= i + (Sk - Sq).  The last query of
    the chunk sees every key; the mask equals tril when Sq == Sk."""
    q, _, _ = qkv(S=4, seed=4)
    _, k, v = qkv(S=8, seed=5)
    out = flash_attention(q, k, v, causal=True)
    # row i must equal self-attention over the first (Sk - Sq) + i + 1
    # keys, computed independently per row
    for i in range(4):
        n_vis = 8 - 4 + i + 1
        want = _xla_attention(q[:, i:i + 1], k[:, :n_vis], v[:, :n_vis],
                              causal=False)
        np.testing.assert_allclose(np.asarray(out[:, i:i + 1]),
                                   np.asarray(want), rtol=1e-5, atol=1e-5)


def test_pallas_kernels_on_cpu_via_force_flag(monkeypatch):
    """Tier-1's guarantee that the real Pallas kernels (interpret mode)
    still run on CPU now that the production non-TPU path is the
    blocked lax formulation: FORCE_PALLAS routes dispatch through the
    kernels, and fwd+bwd must match XLA."""
    from torchpruner_tpu.ops import flash_attention as F

    monkeypatch.setattr(F, "FORCE_PALLAS", True)
    q, k, v = qkv(S=256, dtype=jnp.float32)
    g = jax.random.normal(jax.random.PRNGKey(11), q.shape)

    def grads(fn):
        def f(q_, k_, v_):
            return jnp.sum(fn(q_, k_, v_, causal=True) * g)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    np.testing.assert_allclose(
        np.asarray(F.flash_attention(q, k, v, causal=True)),
        np.asarray(_xla_attention(q, k, v, causal=True)), atol=1e-5)
    for ga, gw in zip(grads(F.flash_attention),
                      grads(lambda a, b, c, causal: _xla_attention(
                          a, b, c, causal=causal))):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gw),
                                   atol=2e-4, rtol=1e-3)


def test_bwd_xla_fallback_when_env_armed(monkeypatch):
    """The RETIRED 32k fallback stays env-armable: with
    FLASH_BWD_XLA_MIN_S set, the vjp recomputes gradients through the
    XLA path while the forward stays flash; both must match the
    pure-XLA computation."""
    from torchpruner_tpu.ops import flash_attention as F

    assert F.FLASH_BWD_XLA_MIN_S is None  # retired by default
    monkeypatch.setattr(F, "FORCE_PALLAS", True)
    monkeypatch.setattr(F, "FLASH_BWD_XLA_MIN_S", 32)
    q, k, v = qkv(S=64)

    def loss_flash(q_, k_, v_):
        return jnp.sum(F.flash_attention(q_, k_, v_, causal=True) ** 2)

    def loss_xla(q_, k_, v_):
        return jnp.sum(F._xla_attention(q_, k_, v_, causal=True) ** 2)

    val_f, grads_f = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(
        q, k, v)
    val_x, grads_x = jax.value_and_grad(loss_xla, argnums=(0, 1, 2))(
        q, k, v)
    np.testing.assert_allclose(float(val_f), float(val_x), rtol=1e-5)
    for gf, gx in zip(grads_f, grads_x):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gx),
                                   rtol=1e-4, atol=1e-5)

"""Test configuration: force an 8-device virtual CPU platform BEFORE any
backend initializes, so mesh/pjit code paths are exercised without TPU
hardware (SURVEY.md §4 "Transfer to the build").

Note: the environment's TPU plugin selects itself via a
``jax.config.update("jax_platforms", ...)`` at interpreter startup, which
overrides the ``JAX_PLATFORMS`` env var — so the config update below is the
one that actually takes effect; the env vars are set too for any
subprocesses tests may spawn.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

#: Tests measured >=9 s each on the 1-core CI box (suite run 2026-07-30;
#: the top-80 durations account for ~85% of the 23-min wall).  Auto-marked
#: ``slow`` here — one list instead of decorators scattered over 20 files —
#: so ``pytest -m "not slow"`` is a <5-min quick lane and CI runs both.
SLOW_TESTS = {
    "test_attributions.py::test_bf16_scoring_preserves_ranking",
    "test_attributions.py::test_conv_metrics_smoke",
    "test_blocks.py::test_prune_with_optimizer_state",
    "test_blocks.py::test_residual_forward_shapes",
    "test_blocks.py::test_transformer_forward_shapes",
    "test_checkpoint.py::test_checkpoint_roundtrip_after_prune",
    "test_core.py::test_shape_inference_matches_eval_shape",
    "test_experiments.py::test_prune_retrain_over_configured_mesh",
    "test_flash_attention.py::test_block_size_override_matches",
    "test_flash_attention.py::test_flash_gradients_match_xla",
    "test_flash_attention.py::test_multiblock_gradients_match_xla",
    "test_flash_attention.py::test_odd_length_still_matches",
    "test_generate.py::test_decode_matches_after_pruning",
    "test_generate.py::test_decode_matches_full_forward_dense",
    "test_generate.py::test_decode_with_longer_buffer_matches",
    "test_generate.py::test_truncated_sampling_respects_top_k_and_top_p",
    "test_graph.py::test_static_graph_matches_nan_oracle",
    "test_masking.py::test_masked_forward_equals_pruned_forward_conv_bn_flatten",
    "test_masking.py::test_masked_forward_equals_pruned_forward_fc",
    "test_masking.py::test_simulated_prune_retrain_matches_structural_accuracy",
    "test_models.py::test_attributions_on_nested_sites",
    "test_models.py::test_bert_tiny_fc1_prune_vs_mask_equivalence",
    "test_models.py::test_resnet20_forward_and_graph",
    "test_models.py::test_vit_tiny_forward_and_prune_groups",
    "test_moe.py::test_expert_parallel_sharding_and_step",
    "test_multiprocess.py::test_two_process_dp_matches_single_process",
    "test_bench_harness.py::test_robustness_leg_resumes_across_kills",
    "test_moe.py::test_moe_aux_weight_in_training_loss",
    "test_moe.py::test_moe_forward_and_gate_sparsity",
    "test_moe.py::test_sparse_dispatch_matches_dense_when_nothing_dropped",
    "test_moe.py::test_sparse_moe_trains_under_expert_parallel_sharding",
    "test_pipeline.py::test_pipelined_lm_training_runs_and_learns",
    "test_presets.py::test_prune_retrain_on_llama_tiny_ffn",
    "test_pruner.py::test_optimizer_state_sliced_and_training_continues",
    "test_ring_attention.py::test_chunk_streaming_matches_single_block",
    "test_ring_attention.py::test_ring_bf16_output_dtype",
    "test_ring_attention.py::test_ring_gradients_match_single_device",
    "test_ring_attention.py::test_ring_matches_single_device",
    "test_sharding_aot.py::test_llama3_8b_sp_step_lowers_at_128k_context",
    "test_sharding_aot.py::test_llama3_8b_train_step_lowers_on_abstract_pod_mesh",
    "test_sharding_aot.py::test_llama3_8b_training_memory_budget_fits_v5p",
    "test_sp_trainer.py::test_sp_trainer_matches_single_device",
    "test_sp_trainer.py::test_sp_trainer_prune_rebuild_recompile",
    "test_sp_trainer.py::test_sp_trainer_remat_and_bf16",
    "test_tp.py::test_attribution_scoring_with_tp_sharded_params",
    "test_tp.py::test_tp_prune_rebuild_step",
    "test_tp.py::test_tp_step_matches_fsdp_step",
    "test_train.py::test_remat_training_matches_exact",
    "test_train.py::test_sharded_trainer_bf16_remat_step",
    "test_ulysses.py::test_auto_dispatch_matches_reference",
    "test_ulysses.py::test_ulysses_gradients_match_single_device",
    "test_flash_attention.py::test_causal_first_row_attends_self_only",
    "test_generate.py::test_decode_matches_full_forward_moe",
    "test_generate.py::test_generate_with_tensor_parallel_params",
    "test_models.py::test_bert_tiny_forward_and_linear_pruning",
    "test_models.py::test_llama_tiny_forward_loss_and_causality",
    "test_moe.py::test_sparse_dispatch_cuts_flops_by_expert_ratio",
    "test_pipeline.py::test_pipelined_bn_model_threads_state_through_microbatches",
    "test_torch_import.py::test_hf_llama_import_matches_transformers_forward",
    "test_train.py::test_mixed_precision_training_keeps_f32_master_state",
    "test_pp_spmd.py::test_pp_spmd_forward_matches_sequential",
    "test_pp_spmd.py::test_pp_spmd_grads_match_sequential",
    "test_pp_spmd.py::test_pp_spmd_train_step_matches_single_device",
    "test_pp_spmd.py::test_pp_spmd_remat_matches",
    "test_pp_spmd.py::test_pp_spmd_composes_with_data_axis",
    "test_pp_spmd.py::test_pp_spmd_vit_forward_matches",
    "test_pp_spmd.py::test_pp_spmd_dropout_trains_with_rng",
    "test_pp_spmd.py::test_pp_spmd_train_step_dropout_with_per_step_rng",
    "test_sharding_aot.py::test_llama3_8b_pp_spmd_step_lowers_on_abstract_pod_mesh",
    "test_pp_spmd.py::test_pp_spmd_composes_with_uniform_prune",
    "test_multiprocess.py::test_two_process_spmd_pipeline_matches_single_process",
    "test_pp_spmd.py::test_pp_spmd_interleaved_forward_matches_sequential",
    "test_pp_spmd.py::test_pp_spmd_interleaved_train_step_matches_gpipe",
    "test_pp_spmd.py::test_pp_spmd_interleaved_ragged_wave_still_matches",
    "test_quant.py::test_quantized_random_params_build_and_serve",
    "test_train.py::test_multi_step_matches_sequential_steps",
    "test_torch_import.py::test_vgg16_bn_import_from_saved_checkpoint_file",
    "test_int4_matmul.py::test_int4_matmul_tiles_prefill_row_counts",
    "test_analysis.py::test_lint_sweep_all_presets_full",
}


def pytest_collection_modifyitems(items):
    seen = set()
    for item in items:
        key = f"{item.path.name}::{item.originalname or item.name}"
        if key in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
            seen.add(key)
    stale = SLOW_TESTS - seen
    # a renamed/removed test must not silently fall back into the quick
    # lane while its dead entry lingers here (full-suite runs only —
    # partial collections legitimately miss entries)
    if stale and len(items) > len(SLOW_TESTS):
        import warnings

        warnings.warn(
            f"conftest.SLOW_TESTS entries matched no collected test "
            f"(renamed/removed?): {sorted(stale)}"
        )

"""Test configuration: force an 8-device virtual CPU platform BEFORE any
backend initializes, so mesh/pjit code paths are exercised without TPU
hardware (SURVEY.md §4 "Transfer to the build").

Note: the environment's TPU plugin selects itself via a
``jax.config.update("jax_platforms", ...)`` at interpreter startup, which
overrides the ``JAX_PLATFORMS`` env var — so the config update below is the
one that actually takes effect; the env vars are set too for any
subprocesses tests may spawn.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

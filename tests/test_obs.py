"""Unified runtime telemetry (torchpruner_tpu.obs): span nesting and the
JSONL event stream, metrics math (MFU/tokens-s from known inputs),
exporter formats, multi-host gating, compile-counter attribution across a
forced retrace, the CSVLogger satellites, and the end-to-end CLI smoke
run with ``--obs-dir`` (the quick-lane acceptance check)."""

import csv
import json
import math
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.obs.exporters import prometheus_text, write_prometheus
from torchpruner_tpu.obs.metrics import (
    MetricsRegistry,
    StepTelemetry,
    train_flops_per_step,
)
from torchpruner_tpu.obs.spans import SpanTracer


@pytest.fixture(autouse=True)
def _clean_session():
    """Every test starts and ends without a global obs session."""
    obs.shutdown()
    yield
    obs.shutdown()


def _read_events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- span tracer ------------------------------------------------------------


def test_span_nesting_and_event_ordering(tmp_path):
    events = []
    tracer = SpanTracer(sink=events.append, annotate=False)
    with tracer.span("outer", run=1) as outer:
        assert tracer.current_id() == outer.id
        with tracer.span("inner") as inner:
            assert inner.parent == outer.id
            assert inner.depth == 1
            assert tracer.current_id() == inner.id
        with tracer.span("inner") as inner2:
            assert inner2.parent == outer.id
    assert tracer.current_id() is None

    kinds = [(e["event"], e["name"]) for e in events]
    assert kinds == [
        ("span_begin", "outer"), ("span_begin", "inner"),
        ("span_end", "inner"), ("span_begin", "inner"),
        ("span_end", "inner"), ("span_end", "outer"),
    ]
    # ids are unique, meta rides on both begin and end
    assert len({e["span"] for e in events}) == 3
    assert events[0]["run"] == 1 and events[-1]["run"] == 1
    # aggregates: inner called twice, durations accumulate under one name
    agg = tracer.phase_summary()
    assert agg["inner"]["calls"] == 2
    assert agg["outer"]["calls"] == 1
    assert agg["outer"]["total_s"] >= agg["inner"]["total_s"] >= 0.0


def test_span_exception_still_closes():
    tracer = SpanTracer(annotate=False)
    with pytest.raises(RuntimeError):
        with tracer.span("boom"):
            raise RuntimeError("x")
    assert tracer.current_id() is None
    assert tracer.phase_summary()["boom"]["calls"] == 1


# -- metrics math -----------------------------------------------------------


def test_mfu_from_known_flops_and_step_time():
    reg = MetricsRegistry()
    st = StepTelemetry(reg)
    st.configure(flops_per_step=1e9, peak_flops=1e12)
    for _ in range(10):
        st.on_step(0.001, examples=32, tokens=64)
    d = st.derive()
    # 10 steps × 1e9 FLOPs over 0.01 s = 1e12 FLOP/s achieved = peak
    assert d["steps"] == 10
    assert d["mfu"] == pytest.approx(1.0)
    assert d["step_time_mean_s"] == pytest.approx(0.001)
    assert d["examples_per_s"] == pytest.approx(32 / 0.001)
    assert d["tokens_per_s"] == pytest.approx(64 / 0.001)
    # derived gauges land in the registry for the exporters
    assert reg.get("mfu").value == pytest.approx(1.0)
    assert reg.get("tokens_per_s").value == pytest.approx(64000.0)


def test_multi_step_dispatch_counts_k_steps():
    st = StepTelemetry(MetricsRegistry())
    st.on_step(0.08, examples=8 * 4, tokens=None, steps=8)
    d = st.derive()
    assert d["steps"] == 8
    assert d["step_time_mean_s"] == pytest.approx(0.01)
    assert d["examples_per_s"] == pytest.approx(32 / 0.08)


def test_train_flops_per_step_is_3x_forward():
    assert train_flops_per_step(7.0) == 21.0


def test_mfu_unknown_denominators_reported_as_none_and_nan_gauge():
    reg = MetricsRegistry()
    st = StepTelemetry(reg)
    st.on_step(0.001, examples=4)
    d = st.derive()
    assert d["mfu"] is None
    assert math.isnan(reg.get("mfu").value)  # stable textfile schema
    assert reg.get("tokens_per_s").value == 0.0


# -- exporter formats -------------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+$"
)


def test_prometheus_textfile_format(tmp_path):
    reg = MetricsRegistry()
    reg.counter("compile_count_total", "compilations").inc(3)
    reg.gauge("mfu").set(0.42)
    h = reg.histogram("step_time_seconds", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.005, 0.05, 5.0):
        h.observe(v)
    text = prometheus_text(reg)
    lines = [l for l in text.splitlines() if l]
    for line in lines:
        assert line.startswith("#") or _PROM_LINE.match(line), line
    assert "# TYPE compile_count_total counter" in lines
    assert "compile_count_total 3" in lines
    assert "mfu 0.42" in lines
    # histogram buckets are CUMULATIVE and end at +Inf == count
    assert 'step_time_seconds_bucket{le="0.001"} 1' in lines
    assert 'step_time_seconds_bucket{le="0.01"} 2' in lines
    assert 'step_time_seconds_bucket{le="0.1"} 3' in lines
    assert 'step_time_seconds_bucket{le="+Inf"} 4' in lines
    assert "step_time_seconds_count 4" in lines

    path = tmp_path / "m.prom"
    write_prometheus(reg, str(path))
    assert path.read_text() == text


# -- multi-host gating ------------------------------------------------------


def test_non_zero_process_index_emits_no_files(tmp_path):
    session = obs.configure(str(tmp_path / "obs"), process_index=1,
                            annotate=False, watch_compiles=False)
    assert not session.is_emitter
    with obs.span("phase"):
        assert obs.current_span_id() is not None  # local tracking stays on
    obs.shutdown()
    assert not os.path.exists(tmp_path / "obs" / "events.jsonl")
    assert not os.path.exists(tmp_path / "obs" / "metrics.prom")


def test_process_zero_emits_files(tmp_path):
    obs.configure(str(tmp_path / "obs"), process_index=0, annotate=False)
    with obs.span("phase"):
        pass
    obs.shutdown()
    events = _read_events(tmp_path / "obs" / "events.jsonl")
    assert [e["event"] for e in events] == [
        "obs_init", "span_begin", "span_end", "run_summary"]
    assert os.path.exists(tmp_path / "obs" / "metrics.prom")


# -- compile accounting -----------------------------------------------------


def test_compile_counter_increments_across_forced_retrace(tmp_path):
    session = obs.configure(str(tmp_path), process_index=0, annotate=False)

    def f(x):
        return jnp.tanh(x) * 2.0

    jf = jax.jit(f)
    with obs.span("compile_phase") as rec:
        jf(jnp.ones(5)).block_until_ready()
        c1, t1 = rec.compile_count, rec.trace_count
        # a new shape forces a retrace AND a fresh backend compile
        jf(jnp.ones(7)).block_until_ready()
        assert rec.compile_count > c1
        assert rec.trace_count > t1
    assert c1 >= 1 and t1 >= 1
    counts = session.compiles.counts()
    assert counts["compile_count"] >= 2
    assert counts["compile_s"] > 0
    # the span_end event carries the attribution
    obs.shutdown()
    end = [e for e in _read_events(tmp_path / "events.jsonl")
           if e["event"] == "span_end"][0]
    assert end["compile_count"] >= 2
    assert end["compile_s"] > 0


def test_compile_listener_unregisters_on_shutdown():
    session = obs.configure(process_index=0, annotate=False)
    jax.jit(lambda x: x - 3)(jnp.ones(3))
    before = session.compiles.counts()["compile_count"]
    assert before >= 1
    obs.shutdown()
    jax.jit(lambda x: x - 4)(jnp.ones(3))  # after shutdown: not counted
    assert session.compiles.counts()["compile_count"] == before


# -- overhead guard ---------------------------------------------------------


def test_step_instrumentation_overhead_under_budget():
    """The per-step hot path must stay under 2% of even a FAST (5 ms)
    compiled step — i.e. <=100 µs per call; measured it is ~1-2 µs."""
    obs.configure(process_index=0, annotate=False, watch_compiles=False)
    n = 2000
    obs.record_step(0.001, 32, 64)  # warm the path
    t0 = time.perf_counter()
    for _ in range(n):
        obs.record_step(0.001, 32, 64)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 100e-6, f"record_step cost {per_call * 1e6:.1f} µs"

    # disabled path (no session) is pure no-op territory
    obs.shutdown()
    t0 = time.perf_counter()
    for _ in range(n):
        obs.record_step(0.001, 32, 64)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 20e-6


# -- trainer integration ----------------------------------------------------


def _tiny_trainer(**kw):
    from torchpruner_tpu.core import layers as L
    from torchpruner_tpu.core.segment import SegmentedModel
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    model = SegmentedModel(
        (L.Dense("fc1", 8), L.Activation("r", "relu"), L.Dense("out", 3)),
        (6,),
    )
    return Trainer.create(model, optax.sgd(0.01), cross_entropy_loss, **kw)


def test_trainer_steps_feed_step_telemetry():
    session = obs.configure(process_index=0, annotate=False,
                            watch_compiles=False)
    trainer = _tiny_trainer()
    x = jnp.ones((16, 6), jnp.float32)
    y = jnp.zeros((16,), jnp.int32)
    for _ in range(3):
        trainer.step(x, y)
    # a streak's FIRST step is unrecorded (async backends would log
    # dispatch-only µs for it), so 3 calls -> 2 recorded intervals
    d = session.step.derive()
    assert d["steps"] == 2
    assert session.metrics.counter("examples_total").value == 32
    # evaluate() breaks the streak: the next step is a first step again
    trainer.evaluate([(x, y)])
    trainer.step(x, y)
    assert session.step.derive()["steps"] == 2
    trainer.step(x, y)
    assert session.step.derive()["steps"] == 3


def test_trainer_grad_norm_opt_in_records_gauge():
    session = obs.configure(process_index=0, annotate=False,
                            watch_compiles=False)
    trainer = _tiny_trainer(grad_norm=True)
    x = jnp.ones((8, 6), jnp.float32)
    y = jnp.zeros((8,), jnp.int32)
    l = trainer.step(x, y)
    assert np.isfinite(float(l))  # loss unwraps from the (loss, gnorm) pair
    g = session.metrics.get("grad_norm")
    assert g is not None and g.value > 0


# -- CSVLogger satellites ---------------------------------------------------


def test_csvlogger_resume_continues_step_ids(tmp_path):
    from torchpruner_tpu.train.logger import CSVLogger

    path = str(tmp_path / "log.csv")
    with CSVLogger(path, experiment="e") as lg:
        for _ in range(2):
            lg.log_prune_step(
                layer="fc1", method="m", test_loss=1.0, test_acc=0.5,
                test_loss_pp=1.1, test_acc_pp=0.4, n_params=10,
            )
    # resume: step ids continue instead of restarting at 0
    with CSVLogger(path, experiment="e") as lg:
        assert lg._step == 2
        lg.log_epoch(epoch=0, train_loss=0.9, test_loss=1.0, test_acc=0.5)
    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert [r["step"] for r in rows] == ["0", "1", "2"]
    # exactly one header line
    with open(path) as f:
        assert sum(l.startswith("timestamp,") for l in f) == 1


def test_csvlogger_jsonl_mirror_keeps_header_order(tmp_path):
    from torchpruner_tpu.train.logger import CSV_FIELDS, CSVLogger

    path = str(tmp_path / "log.csv")
    with CSVLogger(path, experiment="e") as lg:
        lg.log_prune_step(
            layer="fc1", method="m", test_loss=1.0, test_acc=0.5,
            test_loss_pp=1.1, test_acc_pp=0.4, n_params=10,
        )
        lg.log_epoch(epoch=0, train_loss=0.9, test_loss=1.0, test_acc=0.5)
    for rec in _read_events(path + ".jsonl"):
        assert list(rec.keys()) == CSV_FIELDS


def test_csvlogger_rows_carry_active_span_id(tmp_path):
    from torchpruner_tpu.train.logger import CSVLogger

    obs.configure(process_index=0, annotate=False, watch_compiles=False)
    path = str(tmp_path / "log.csv")
    with CSVLogger(path, experiment="e") as lg:
        with obs.span("retrain") as rec:
            lg.log_epoch(epoch=0, train_loss=1.0, test_loss=1.0,
                         test_acc=0.1)
        lg.log_epoch(epoch=1, train_loss=1.0, test_loss=1.0, test_acc=0.1)
    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert rows[0]["span_id"] == rec.id
    assert rows[1]["span_id"] == ""


def test_csvlogger_resumes_pre_span_id_schema(tmp_path):
    """A CSV written before the span_id column keeps its own (narrower)
    header on resume — no ragged rows, no rewritten history."""
    from torchpruner_tpu.train.logger import CSVLogger

    path = str(tmp_path / "old.csv")
    old_fields = ["timestamp", "experiment", "step", "layer", "method",
                  "test_loss", "test_acc", "test_loss_pp", "test_acc_pp",
                  "n_params", "flops", "widths", "prune_time",
                  "prune_ratio", "train_loss"]
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, old_fields)
        w.writeheader()
        w.writerow({k: ("7" if k == "step" else "x") for k in old_fields})
    with CSVLogger(path, experiment="e") as lg:
        assert lg._step == 8
        lg.log_epoch(epoch=0, train_loss=1.0, test_loss=1.0, test_acc=0.1)
    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert rows[-1]["step"] == "8"
    assert "span_id" not in rows[-1]


def test_configure_failure_keeps_previous_session(tmp_path):
    """A failing constructor (unwritable obs_dir) must leave the existing
    session installed and usable; close() is idempotent either way."""
    session = obs.configure(str(tmp_path / "ok"), process_index=0,
                            annotate=False, watch_compiles=False)
    blocked = tmp_path / "blocked"
    blocked.write_text("")  # a FILE where a directory is needed
    with pytest.raises(OSError):
        obs.configure(str(blocked / "obs"), process_index=0, annotate=False)
    assert obs.get() is session
    with obs.span("still_alive"):
        pass
    obs.shutdown()
    session.close()  # second close: no I/O on the closed event file
    events = _read_events(tmp_path / "ok" / "events.jsonl")
    assert sum(e["event"] == "run_summary" for e in events) == 1
    assert any(e.get("name") == "still_alive" for e in events)


def test_reused_obs_dir_summarizes_latest_run_only(tmp_path):
    from torchpruner_tpu.utils.profiling import span_phase_summary

    obs_dir = str(tmp_path / "obs")
    for _ in range(2):  # same dir twice: events.jsonl appends
        obs.configure(obs_dir, process_index=0, annotate=False,
                      watch_compiles=False)
        with obs.span("phase"):
            pass
        obs.shutdown()
    phases = span_phase_summary(os.path.join(obs_dir, "events.jsonl"))
    assert phases["phase"]["calls"] == 1  # not 2: latest session only


# -- span JSONL joins (profiling / trace_analysis) --------------------------


def _write_span_stream(path):
    events = [
        {"event": "obs_init", "ts": 0},
        {"event": "span_begin", "span": "s1", "name": "retrain",
         "parent": None, "depth": 0, "ts": 1.0},
        {"event": "span_end", "span": "s1", "name": "retrain",
         "parent": None, "depth": 0, "ts": 3.0, "dur_s": 2.0,
         "compile_count": 2, "compile_s": 0.5, "trace_count": 3},
        {"event": "span_end", "span": "s2", "name": "eval",
         "parent": None, "depth": 0, "ts": 4.0, "dur_s": 1.0,
         "compile_count": 0, "compile_s": 0.0, "trace_count": 0},
    ]
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
        f.write("{torn-line")  # killed-run tail must be tolerated


def test_steptimer_from_span_jsonl(tmp_path):
    from torchpruner_tpu.utils.profiling import StepTimer, span_phase_summary

    path = str(tmp_path / "events.jsonl")
    _write_span_stream(path)
    timer = StepTimer.from_span_jsonl(path)
    assert timer.summary()["retrain"] == {
        "total_s": 2.0, "calls": 1, "mean_s": 2.0}
    phases = span_phase_summary(path)
    assert phases["retrain"]["compile_count"] == 2
    assert phases["eval"]["total_s"] == 1.0


def test_trace_summary_joins_span_phases(tmp_path):
    from torchpruner_tpu.utils.profiling import trace
    from torchpruner_tpu.utils.trace_analysis import (
        markdown_summary,
        summarize_trace,
    )

    f = jax.jit(lambda a: (a @ a).sum())
    a = jnp.ones((64, 64))
    f(a).block_until_ready()
    with trace(str(tmp_path / "tr")):
        f(a).block_until_ready()
    spans = str(tmp_path / "events.jsonl")
    _write_span_stream(spans)
    s = summarize_trace(str(tmp_path / "tr"), spans_jsonl=spans)
    assert s["phases"]["retrain"]["total_s"] == 2.0
    assert s["phases"]["retrain"]["compile_count"] == 2
    md = markdown_summary(s)
    assert "phase (runtime spans)" in md and "| retrain |" in md


# -- end-to-end CLI smoke (quick lane) --------------------------------------


def test_cli_obs_dir_end_to_end(tmp_path, monkeypatch, capsys):
    """The acceptance check at smoke scale: the MLP prune→retrain preset
    under ``--obs-dir`` produces a parseable span stream covering all
    pipeline phases, a Prometheus textfile with the step/compile series,
    and phase wall times that sum to within 10% of the run's total."""
    from torchpruner_tpu.__main__ import main

    monkeypatch.chdir(tmp_path)  # default log_path lands in tmp
    obs_dir = str(tmp_path / "obs")
    rc = main(["--preset", "mnist_mlp_shapley", "--smoke",
               "--obs-dir", obs_dir, "--no-compilation-cache"])
    assert rc == 0
    out = capsys.readouterr()
    assert json.loads(out.out.strip().splitlines()[-1])["steps"] == 2
    assert "observability summary" in out.err

    events = _read_events(os.path.join(obs_dir, "events.jsonl"))
    names = {e["name"] for e in events if e["event"] == "span_end"}
    for phase in ("run", "prune_retrain", "setup", "attribution", "plan",
                  "apply_plan", "retrain", "eval", "flops"):
        assert phase in names, f"missing phase span {phase!r}"
    # begin/end pair up per span id
    begins = {e["span"] for e in events if e["event"] == "span_begin"}
    ends = {e["span"] for e in events if e["event"] == "span_end"}
    assert begins == ends

    # phase coverage: direct children of prune_retrain account for >=90%
    # of its wall time (the ISSUE's 10% accounting criterion)
    by_id = {e["span"]: e for e in events if e["event"] == "span_end"}
    root = next(e for e in by_id.values() if e["name"] == "prune_retrain")
    child_s = sum(e["dur_s"] for e in by_id.values()
                  if e["parent"] == root["span"])
    assert child_s >= 0.9 * root["dur_s"]
    assert child_s <= 1.01 * root["dur_s"]

    # run_summary event carries derived metrics + compile accounting
    summary = [e for e in events if e["event"] == "run_summary"][-1]
    assert summary["derived"]["steps"] > 0
    assert summary["compiles"]["compile_count"] > 0

    # Prometheus textfile: the promised series exist
    prom = open(os.path.join(obs_dir, "metrics.prom")).read()
    for series in ("step_time_seconds_sum", "step_time_seconds_count",
                   "steps_total", "examples_per_s", "tokens_per_s", "mfu",
                   "compile_count_total", "compile_seconds_total"):
        assert re.search(rf"^{series}", prom, re.M), f"missing {series}"

    # CSV rows cross-reference emitted span ids
    with open(tmp_path / "logs" / "experiment.csv") as f:
        rows = list(csv.DictReader(f))
    assert rows and all(r["span_id"] in ends for r in rows)


@pytest.mark.slow
def test_cli_obs_full_size_mlp_sweep(tmp_path, monkeypatch, capsys):
    """The same pipeline at the mid-size digits MLP (512-wide hiddens,
    taylor scoring) — the closest CI gets to a full obs sweep."""
    import dataclasses

    from torchpruner_tpu.__main__ import main
    from torchpruner_tpu.experiments.presets import mnist_mlp_shapley

    cfg = dataclasses.replace(
        mnist_mlp_shapley(smoke=True), model="digits_fc",
        method="taylor", method_kwargs={}, name="obs_full",
        log_path=str(tmp_path / "logs" / "log.csv"),
    )
    cfg_path = str(tmp_path / "cfg.json")
    cfg.to_json(cfg_path)
    monkeypatch.chdir(tmp_path)
    obs_dir = str(tmp_path / "obs")
    rc = main(["--config", cfg_path, "--obs-dir", obs_dir,
               "--no-compilation-cache"])
    assert rc == 0
    events = _read_events(os.path.join(obs_dir, "events.jsonl"))
    summary = [e for e in events if e["event"] == "run_summary"][-1]
    assert summary["phases"]["retrain"]["calls"] == 2
    assert summary["compiles"]["compile_count"] > 0


def test_cli_flushes_telemetry_when_the_run_crashes(tmp_path, monkeypatch):
    """A crashed run is when telemetry matters most: the exporters must
    flush (and the compile listener unregister) on the error path too."""
    from torchpruner_tpu.__main__ import main

    monkeypatch.chdir(tmp_path)
    cfg_path = tmp_path / "bad.json"
    cfg_path.write_text(json.dumps({
        "name": "crash", "model": "no_such_model",
        "dataset": "digits_flat",
    }))
    obs_dir = str(tmp_path / "obs")
    with pytest.raises(KeyError):
        main(["--config", str(cfg_path), "--obs-dir", obs_dir,
              "--no-compilation-cache"])
    assert obs.get() is None  # session torn down
    events = _read_events(os.path.join(obs_dir, "events.jsonl"))
    assert events[-1]["event"] == "run_summary"
    assert os.path.exists(os.path.join(obs_dir, "metrics.prom"))


def test_cli_no_obs_disables_everything(tmp_path, monkeypatch, capsys):
    from torchpruner_tpu.__main__ import main

    monkeypatch.chdir(tmp_path)
    rc = main(["--preset", "mnist_mlp_shapley", "--smoke", "--no-obs",
               "--no-compilation-cache"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "observability summary" not in err
    assert obs.get() is None

"""AOT sharding validation at BASELINE scale — no device memory needed.

The BASELINE.json north-star config ("Llama-3-8B FFN channel pruning,
pjit FSDP on v5p-64") can't be *run* in CI, but its shardings can be
*proven*: ``jax.eval_shape`` gives the full 8.03B-parameter shape tree
without allocating, an ``AbstractMesh({"data": 8, "model": 8})`` stands in
for the 64-chip pod, and the FSDP / TP rules are pure functions of shapes —
so a test can assert every parameter's PartitionSpec and fail on any large
tensor left unsharded (an 8B-param model with one replicated 4096x128256
embedding would OOM a real v5p chip; this is the test that catches it
before the pod does).  The train step is additionally traced and lowered
(``jax.jit(...).lower``) against the abstract mesh to prove the sharded
program is constructible end to end.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from torchpruner_tpu.analysis import abstract_mesh

from torchpruner_tpu.core.segment import init_model
from torchpruner_tpu.models import llama3_8b
from torchpruner_tpu.parallel.sharding import (
    fsdp_sharding,
    tp_sharding,
    tp_specs,
)
from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

MESH = abstract_mesh({"data": 8, "model": 8})
#: any tensor at least this big left fully replicated is a sharding bug
LARGE = 2**22  # 4M elements = 16 MB f32 per chip if replicated


def _abstract_lowering_supported() -> bool:
    """Whether this jax can AOT-lower a program whose sharded inputs
    live on an AbstractMesh (0.4.x raises on ``_device_assignment``
    whenever the lowering needs a device order, e.g. any reduction over
    a sharded operand)."""
    try:
        m = abstract_mesh({"x": 2})
        s = jax.ShapeDtypeStruct(
            (4,), jnp.float32, sharding=NamedSharding(m, P("x"))
        )
        jax.jit(jnp.sum).trace(s).lower(lowering_platforms=("tpu",))
        return True
    except (ValueError, TypeError):  # pragma: no cover - older jax
        return False


needs_abstract_lowering = pytest.mark.skipif(
    not _abstract_lowering_supported(),
    reason="AbstractMesh AOT lowering unsupported by this jax",
)


def _shapes():
    model = llama3_8b(seq_len=2048)
    params, state = jax.eval_shape(
        lambda k: init_model(model, seed=0), jax.random.PRNGKey(0)
    )
    return model, params, state


def _named_leaves(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        keys = tuple(getattr(k, "key", k) for k in path)
        yield "/".join(str(k) for k in keys), leaf


def _assert_no_large_replicated(params, shardings):
    """Every >= LARGE-element parameter must shard at least one axis, and
    every sharded axis must divide the mesh axis size."""
    sh_flat = dict(_named_leaves(shardings))
    checked = 0
    for name, leaf in _named_leaves(params):
        n = int(np.prod(leaf.shape))
        spec = sh_flat[name].spec
        for d, axis in enumerate(spec):
            if axis is not None:
                assert leaf.shape[d] % MESH.shape[axis] == 0, (name, spec)
        if n >= LARGE:
            assert any(a is not None for a in spec), (
                f"{name} {leaf.shape} ({n/1e6:.1f}M params) is replicated"
            )
            checked += 1
    assert checked >= 64  # 32 blocks x (attention + FFN) at minimum


def test_llama3_8b_fsdp_shards_every_large_tensor():
    model, params, _ = _shapes()
    shardings = fsdp_sharding(params, MESH)
    _assert_no_large_replicated(params, shardings)
    # the embedding + lm_head (the two 525M-param tensors) in particular
    emb = dict(_named_leaves(shardings))["tok_emb/emb"]
    assert emb.spec != P(None, None) and emb.spec != P()


def test_llama3_8b_tp_specs_are_megatron_shaped():
    """The pruning-graph-derived TP assignment must give column-parallel
    FFN up/gate, row-parallel down-proj, head-sharded attention."""
    model, _, _ = _shapes()
    specs = tp_specs(model, MESH)
    assert specs[("block1_ffn/gate", "wg")] == P(None, "model")
    assert specs[("block1_ffn/gate", "wu")] == P(None, "model")
    assert specs[("block1_ffn/down", "w")] == P("model", None)
    assert specs[("block7_attn/attn", "wq")] == P(None, "model", None)
    assert specs[("block7_attn/attn", "wk")] == P(None, "model", None)
    assert specs[("block7_attn/attn", "wo")] == P("model", None, None)
    # all 32 blocks claimed
    ffn_claims = [k for k in specs if k[0].endswith("_ffn/gate")]
    assert len(ffn_claims) == 4 * 32  # wg + wu + bg + bu per block


def test_llama3_8b_tp_sharding_covers_all_large_tensors():
    model, params, _ = _shapes()
    shardings = tp_sharding(model, params, MESH)
    _assert_no_large_replicated(params, shardings)


def test_llama3_8b_would_catch_an_unsharded_tensor():
    """Negative control: replicating one FFN tensor must fail the check."""
    model, params, _ = _shapes()
    shardings = fsdp_sharding(params, MESH)
    shardings["block1_ffn"]["gate"]["wg"] = NamedSharding(MESH, P())
    with pytest.raises(AssertionError):
        _assert_no_large_replicated(params, shardings)


def _abstract_sharded_inputs(params, opt_shapes, p_sh, mesh):
    """(p_s, o_s): ShapeDtypeStruct trees carrying the given param
    shardings and FSDP-over-data adam-state shardings (scalar counts
    replicate) — the shared recipe for every AOT lowering test."""
    opt_sh = jax.tree_util.tree_map(
        # adam m/v mirror the param tree; scalar counts replicate
        lambda leaf: (
            NamedSharding(mesh, P())
            if np.ndim(leaf) == 0
            else fsdp_sharding(leaf, mesh, axis="data")
        ),
        opt_shapes,
        is_leaf=lambda x: hasattr(x, "shape"),
    )
    p_s = jax.tree_util.tree_map(
        lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh),
        params, p_sh)
    o_s = jax.tree_util.tree_map(
        lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh),
        opt_shapes, opt_sh,
        is_leaf=lambda x: hasattr(x, "shape"))
    return p_s, o_s


@pytest.mark.parametrize("partition", ["fsdp", "tp"])
@needs_abstract_lowering
def test_llama3_8b_train_step_lowers_on_abstract_pod_mesh(partition):
    """Trace + lower the full sharded train step (fwd, bwd, adam update)
    at 8B scale on the abstract {data: 8, model: 8} mesh — proves the
    sharded program constructs without 64 chips or 8B params in memory."""
    model, params, state = _shapes()
    tx = optax.adam(1e-4)
    opt_shapes = jax.eval_shape(tx.init, params)
    if partition == "fsdp":
        p_sh = fsdp_sharding(params, MESH)
    else:
        p_sh = tp_sharding(model, params, MESH)
    batch_sh = NamedSharding(MESH, P("data"))
    B, S = 16, 2048
    x_s = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=batch_sh)

    from torchpruner_tpu.utils.dtypes import cast_floats

    def step(params, opt_state, x):
        # the honest 8B training config: bf16 compute (f32 masters) with
        # recompute-in-backward blocks — what a real v5p run would compile
        def loss_fn(p):
            out, _ = model.apply(
                cast_floats(p, jnp.bfloat16), x, state=state, train=True,
                remat=True,
            )
            return jnp.mean(lm_cross_entropy_loss(out, x))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    p_s, o_s = _abstract_sharded_inputs(params, opt_shapes, p_sh, MESH)
    lowered = jax.jit(step).trace(p_s, o_s, x_s).lower(
        lowering_platforms=("tpu",)
    )
    hlo = lowered.as_text()
    assert "sdy.sharding" in hlo or "mhlo.sharding" in hlo or "sharding" in hlo


@needs_abstract_lowering
def test_llama3_8b_sp_step_lowers_at_128k_context():
    """Long-context north star: the sequence-parallel train step (ring
    attention, RoPE at global offsets, psum'd masked loss/grads) traces
    and lowers for TPU at 8B scale and S = 131072 over an abstract
    {data: 4, seq: 16} pod mesh — each shard holds 8192 positions, and no
    (S, S) score tensor exists anywhere in the program."""
    from jax import lax

    from torchpruner_tpu.parallel.mesh import relaxed_shard_map

    from torchpruner_tpu.parallel.sp import sp_model
    from torchpruner_tpu.utils.dtypes import cast_floats

    mesh = abstract_mesh({"data": 4, "seq": 16})
    S = 131072
    model = sp_model(llama3_8b(seq_len=S), "ring")
    params, state = jax.eval_shape(
        lambda k: init_model(model, seed=0), jax.random.PRNGKey(0)
    )

    def local_step(params, x, tgt, mask):
        def loss_fn(p):
            logits, _ = model.apply(
                cast_floats(p, jnp.bfloat16), x, state=state, train=True,
                remat=True,
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
            total = lax.psum(jnp.sum(nll * mask), ("data", "seq"))
            count = lax.psum(jnp.sum(mask), ("data", "seq"))
            return total / count

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return lax.psum(grads, ("data", "seq")), loss

    repl = P()
    bseq = P("data", "seq")
    mapped = relaxed_shard_map(
        local_step, mesh,
        in_specs=(repl, bseq, bseq, bseq),
        out_specs=(repl, repl),
    )
    B = 4
    x_s = jax.ShapeDtypeStruct(
        (B, S), jnp.int32, sharding=NamedSharding(mesh, bseq)
    )
    m_s = jax.ShapeDtypeStruct(
        (B, S), jnp.float32, sharding=NamedSharding(mesh, bseq)
    )
    p_s = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(
            l.shape, l.dtype, sharding=NamedSharding(mesh, P())
        ),
        params,
    )
    lowered = jax.jit(mapped).trace(p_s, x_s, x_s, m_s).lower(
        lowering_platforms=("tpu",)
    )
    assert "sharding" in lowered.as_text()


def test_llama3_8b_training_memory_budget_fits_v5p():
    """The scaling-methodology planning step: the 8B adam FSDP config on
    the {data: 8, model: 8} pod must budget within a v5p chip's HBM —
    computed exactly from shapes and shardings, no arrays."""
    import optax

    from torchpruner_tpu.parallel import HBM_BYTES, training_memory

    model, params, _ = _shapes()
    # ZeRO-style FSDP over the FULL 64-chip mesh (both axes)
    shardings = fsdp_sharding(params, MESH, axis=("data", "model"))
    budget = training_memory(
        model, shardings, dict(MESH.shape), tx=optax.adam(1e-4),
        batch_per_chip=2, compute_dtype=jnp.bfloat16, remat=True,
    )
    # 8.03B f32 params over 64 chips ~ 0.47 GiB; x4 for grads+adam m/v
    gib = 2.0**30
    assert 0.3 * gib < budget.params_bytes < 0.7 * gib
    assert budget.opt_bytes > 1.5 * budget.params_bytes  # m + v + counts
    assert budget.fits(HBM_BYTES["TPU v5p"]), budget.report()
    # sharding over the model axis alone costs ~8x the parameter bytes
    b_model_only = training_memory(
        model, fsdp_sharding(params, MESH), dict(MESH.shape),
    )
    assert b_model_only.params_bytes > 7 * budget.params_bytes
    # and the same model replicated on one chip must NOT fit a v5e
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = jax.tree_util.tree_map(
        lambda _: NamedSharding(MESH, P()), params,
    )
    b1 = training_memory(model, rep, dict(MESH.shape), tx=optax.adam(1e-4))
    assert not b1.fits(HBM_BYTES["TPU v5e"])
    assert b1.largest_replicated[1] > 1 * gib  # the embedding


@needs_abstract_lowering
def test_llama3_8b_pp_spmd_step_lowers_on_abstract_pod_mesh():
    """The collective-based pipeline step (parallel/pp_spmd.py) traces
    and lowers for TPU at 8B scale on an abstract {pp: 8, data: 8}
    64-chip mesh — 4 blocks per stage, batch sharded over data, remat
    per block — proving the cross-host PP program constructs without a
    pod."""
    from torchpruner_tpu.parallel.pp_spmd import pp_spmd_train_step

    mesh = abstract_mesh({"pp": 8, "data": 8})
    model, params, _ = _shapes()
    tx = optax.adam(1e-4)
    opt_shapes = jax.eval_shape(tx.init, params)
    # params/opt enter in the model's ordinary layout, FSDP-sharded over
    # the data axis; the step stacks blocks and reshards them over pp
    # internally (GSPMD inserts the collectives)
    p_sh = fsdp_sharding(params, mesh, axis="data")
    p_s, o_s = _abstract_sharded_inputs(params, opt_shapes, p_sh, mesh)
    B, S = 64, 2048  # microbatch 16 divides data=8
    x_s = jax.ShapeDtypeStruct(
        (B, S), jnp.int32, sharding=NamedSharding(mesh, P("data")))

    step = pp_spmd_train_step(
        model, tx, lm_cross_entropy_loss, mesh=mesh, n_microbatches=4,
        data_axis="data", remat=True, compute_dtype=jnp.bfloat16)
    lowered = step.trace(p_s, o_s, x_s).lower(lowering_platforms=("tpu",))
    assert "sharding" in lowered.as_text()


@needs_abstract_lowering
def test_llama3_8b_distributed_taylor_scoring_lowers():
    """The scoring third of the north-star loop (attribution -> prune ->
    retrain on pods): Taylor per-example rows at the BASELINE FFN prune
    site, batch sharded over data, params TP-sharded over model, reduced
    as distributed moments (sum / sum-of-squares psum'd by XLA) — traced
    and lowered at 8B scale on the abstract {data: 8, model: 8} mesh.
    This is exactly what DistributedScorer dispatches per batch
    (parallel/scoring.py run(): run_rows + jnp.sum moments)."""
    from torchpruner_tpu.attributions.activation import grad_rows_fn
    from torchpruner_tpu.utils.dtypes import cast_floats

    model, params, state = _shapes()
    assert not jax.tree_util.tree_leaves(state)
    row_fn = grad_rows_fn(model, "block1_ffn/gate",
                          lm_cross_entropy_loss, "taylor")

    def moments(p, x, y):
        rows = row_fn(cast_floats(p, jnp.bfloat16), {}, x, y)
        rows = rows.astype(jnp.float32)
        return jnp.sum(rows, axis=0), jnp.sum(rows * rows, axis=0)

    p_sh = tp_sharding(model, params, MESH)
    p_s = jax.tree_util.tree_map(
        lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh),
        params, p_sh)
    B, S = 16, 2048
    x_s = jax.ShapeDtypeStruct(
        (B, S), jnp.int32, sharding=NamedSharding(MESH, P("data")))
    lowered = jax.jit(moments).trace(p_s, x_s, x_s).lower(
        lowering_platforms=("tpu",))
    assert "sharding" in lowered.as_text()


@needs_abstract_lowering
def test_llama3_8b_distributed_shapley_rows_lower():
    """Shapley rows (the scan-over-units marginal chain x vmap over
    permutations) trace and lower at 8B on the abstract pod mesh with
    TP-sharded params and data-sharded batch — the most expensive
    attribution in the loop proven constructible at BASELINE scale."""
    from torchpruner_tpu.attributions.shapley import shapley_rows_fn
    from torchpruner_tpu.utils.dtypes import cast_floats

    model, params, _ = _shapes()
    n_units = model.site_shape("block1_ffn/gate")[-1]
    assert n_units == 14336
    row_fn = shapley_rows_fn(model, "block1_ffn/gate",
                             lm_cross_entropy_loss, False)

    def rows(p, x, y, perms):
        return row_fn(cast_floats(p, jnp.bfloat16), {}, x, y, perms)

    p_sh = tp_sharding(model, params, MESH)
    p_s = jax.tree_util.tree_map(
        lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh),
        params, p_sh)
    B, S = 16, 2048
    x_s = jax.ShapeDtypeStruct(
        (B, S), jnp.int32, sharding=NamedSharding(MESH, P("data")))
    perm_s = jax.ShapeDtypeStruct((1, n_units), jnp.int32,
                                  sharding=NamedSharding(MESH, P()))
    lowered = jax.jit(rows).trace(p_s, x_s, x_s, perm_s).lower(
        lowering_platforms=("tpu",))
    assert "sharding" in lowered.as_text()


def test_llama3_8b_int4_decode_program_lowers():
    """The flagship serving program — Llama-3-8B, int4 QTensor weights,
    bf16 KV cache, prefill + 16-token scan — traces and lowers at full
    scale with no chip and no arrays (eval_shape builds the quantized
    tree abstractly).  Proves the one-chip 8B decode config composes
    end-to-end before the on-chip capture runs it."""
    from torchpruner_tpu.experiments.llama8b_decode import (
        quantized_random_params,
    )
    from torchpruner_tpu.generate import _generate_fn, init_cache
    from torchpruner_tpu.models import llama

    model = llama(seq_len=256)
    params_s, _ = jax.eval_shape(
        lambda: quantized_random_params(model, bits=4))
    B, S, n_new = 8, 64, 16
    cache_s = jax.eval_shape(
        lambda: init_cache(model, B, S + n_new, jnp.bfloat16))
    prompt_s = jax.ShapeDtypeStruct((B, S), jnp.int32)
    rng_s = jax.ShapeDtypeStruct((2,), jnp.uint32)
    run = _generate_fn(model, S, n_new, 0.0)
    lowered = run.trace(params_s, cache_s, prompt_s, rng_s).lower(
        lowering_platforms=("tpu",))
    hlo = lowered.as_text()
    assert "xi8>" in hlo  # the packed int4 payloads ride as int8

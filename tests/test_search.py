"""search/: campaign grids, pre-pricing gates, Pareto dominance, the
frontier artifact, ledger trial stamping, and (slow lane) the live
campaign driver with its kill -9 → resume → identical-frontier drill."""

import dataclasses
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from torchpruner_tpu.search.driver import (
    CampaignManifest,
    run_campaign,
)
from torchpruner_tpu.search.frontier import (
    build_frontier,
    bucket_scalars,
    curve_dominated,
    dominates,
    frontier_digest,
    pareto_flags,
)
from torchpruner_tpu.search.grid import CampaignSpec, digits_smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# grid
# ---------------------------------------------------------------------------


def test_enumeration_is_deterministic_and_digest_stable():
    spec = digits_smoke()
    a = spec.enumerate_trials()
    b = digits_smoke().enumerate_trials()
    assert [t.trial_id for t in a] == [t.trial_id for t in b]
    assert len({t.trial_id for t in a}) == len(a) >= 8
    # execution knobs are not search identity: a resume may run wider
    assert dataclasses.replace(spec, jobs=7).digest() == spec.digest()
    # the search space IS identity
    assert dataclasses.replace(spec, axes={}).digest() != spec.digest()


def test_unknown_trial_field_is_loud():
    spec = CampaignSpec(name="x", base="mnist_mlp_shapley", smoke=True,
                        axes={"not_a_field": [1, 2]})
    with pytest.raises(ValueError, match="not_a_field"):
        spec.enumerate_trials()


def test_trial_config_materializes_overrides(tmp_path):
    spec = digits_smoke()
    trial = next(t for t in spec.enumerate_trials()
                 if t.trial_id.endswith("layerwise"))
    cfg = spec.trial_config(trial, str(tmp_path / "t"))
    assert cfg.experiment == "prune_retrain"
    assert cfg.layer_fractions == {"fc1": 0.25, "fc2": 0.625}
    assert cfg.run_dir == str(tmp_path / "t")
    assert cfg.name.startswith("digits_smoke:")


def test_campaign_from_json_file_roundtrip(tmp_path):
    spec = digits_smoke()
    path = tmp_path / "campaign.json"
    path.write_text(json.dumps(spec.to_dict()))
    loaded = CampaignSpec.from_any(str(path))
    assert loaded.digest() == spec.digest()
    assert [t.trial_id for t in loaded.enumerate_trials()] == \
        [t.trial_id for t in spec.enumerate_trials()]


def test_unknown_campaign_name_is_loud():
    with pytest.raises(KeyError, match="digits_smoke"):
        CampaignSpec.from_any("no_such_campaign")


# ---------------------------------------------------------------------------
# dominance (satellite: isolation/property tests)
# ---------------------------------------------------------------------------


def test_dominates_margin_semantics():
    # classic Pareto at margin 0: strictly better in one, no worse in
    # the other
    assert dominates((10, 0.9), (10, 0.8))
    assert dominates((5, 0.8), (10, 0.8))
    assert not dominates((10, 0.8), (10, 0.8))       # exact tie
    assert not dominates((11, 0.95), (10, 0.8))      # more flops
    # near-tie margin: within-margin accuracy gaps don't dominate at
    # equal flops; beyond-margin gaps do
    assert not dominates((10, 0.81), (10, 0.80), margin=0.02)
    assert dominates((10, 0.83), (10, 0.80), margin=0.02)
    # fewer flops at no worse accuracy still dominates under a margin
    assert dominates((5, 0.80), (10, 0.80), margin=0.02)


def test_pareto_flags_order_independent():
    rng = np.random.default_rng(0)
    pts = [(float(f), float(a)) for f, a in
           rng.uniform(0, 1, size=(40, 2))]
    base = dict(zip(pts, pareto_flags(pts, margin=0.03)))
    for seed in range(5):
        perm = list(pts)
        np.random.default_rng(seed).shuffle(perm)
        flags = pareto_flags(perm, margin=0.03)
        assert all(base[p] == fl for p, fl in zip(perm, flags))


def test_pareto_near_ties_survive():
    pts = [(10.0, 0.90), (10.0, 0.89), (10.0, 0.80)]
    flags = pareto_flags(pts, margin=0.02)
    # the 0.89 point is within the near-tie margin of 0.90 — a
    # legitimate run-to-run coin flip stays on the frontier; 0.80 is
    # beaten beyond the margin and is flagged dominated
    assert flags == [True, True, False]


def test_curve_dominated_is_rung_matched():
    # the completed trial's FINAL point (5, 0.9) crushes the partial
    # round-1 point (20, 0.4) — but at the MATCHED rung (round 1) the
    # completed trial was also at 0.45: within the margin, so a later
    # round could catch up, and the trial must NOT stop
    completed = [[(20.0, 0.45), (5.0, 0.9)]]
    assert not curve_dominated([(20.0, 0.4)], completed, margin=0.1)
    # a genuinely collapsed trial (chance-level at the same rung) stops
    assert curve_dominated([(20.0, 0.1)], completed, margin=0.1)


def test_curve_dominated_requires_every_rung_beaten():
    completed = [[(20.0, 0.8), (5.0, 0.9)]]
    # rung 0 beaten, rung 1 within margin -> no stop
    assert not curve_dominated([(20.0, 0.2), (5.0, 0.85)], completed,
                               margin=0.1)
    # both rungs beaten past the margin -> stop
    assert curve_dominated([(20.0, 0.2), (5.0, 0.5)], completed,
                           margin=0.1)


def test_curve_dominated_margin_is_strict():
    completed = [[(10.0, 0.5)]]
    # beaten by EXACTLY the margin = within confidence -> never stop
    assert not curve_dominated([(10.0, 0.4)], completed, margin=0.1)
    assert curve_dominated([(10.0, 0.39)], completed, margin=0.1)


def test_curve_dominated_guards():
    completed = [[(10.0, 0.9)]]
    assert not curve_dominated([], completed, margin=0.1)
    assert not curve_dominated([(10.0, 0.1)], [], margin=0.1)
    assert not curve_dominated([(10.0, 0.1)], completed, margin=0.1,
                               min_points=2)
    # a partial curve LONGER than every completed curve has rungs
    # nobody can judge -> no stop
    assert not curve_dominated([(10.0, 0.1), (5.0, 0.1)], completed,
                               margin=0.1)
    # fewer flops at the matched rung is new Pareto territory -> no stop
    assert not curve_dominated([(8.0, 0.1)], completed, margin=0.1)


# ---------------------------------------------------------------------------
# pre-pricing gates
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def smoke_pricing(tmp_path_factory):
    from torchpruner_tpu.search.pricing import price_campaign

    spec = digits_smoke()
    trials = spec.enumerate_trials()
    pricing = price_campaign(
        spec, trials, str(tmp_path_factory.mktemp("camp")))
    return spec, trials, pricing


def test_pricing_excludes_over_budget_by_name(smoke_pricing):
    from torchpruner_tpu.search.pricing import format_exclusions

    _, _, pricing = smoke_pricing
    victim = next(tid for tid in pricing if tid.endswith("over_budget"))
    p = pricing[victim]
    assert p["excluded_by"] == "cost" and not p["feasible"]
    assert any("median" in r for r in p["reasons"])
    # the loud exclusion list names the victim
    assert f"- `{victim}` [cost]:" in format_exclusions(pricing)


def test_pricing_shares_compiles_and_prices_survivors(smoke_pricing):
    _, _, pricing = smoke_pricing
    ok = {tid: p for tid, p in pricing.items() if not p["excluded_by"]}
    assert len(ok) >= 7
    steps = {p["predicted_step_ms"] for p in ok.values()}
    # every survivor shares the one train-step program shape -> one
    # compile, one prediction
    assert len(steps) == 1
    for p in ok.values():
        assert p["predicted_trial_s"] > 0
        assert p["predicted_hbm_bytes_per_chip"] > 0
        assert p["n_rounds"] == 2


def test_pricing_hbm_gate_via_env(tmp_path, monkeypatch):
    from torchpruner_tpu.search.pricing import price_campaign

    monkeypatch.setenv("TORCHPRUNER_PLAN_HBM_BYTES", "1024")
    spec = digits_smoke()
    trials = spec.enumerate_trials()[:2]
    pricing = price_campaign(spec, trials, str(tmp_path))
    for tid, p in pricing.items():
        assert p["excluded_by"] == "hbm", (tid, p)
        assert any("watermark" in r for r in p["reasons"])


def test_pricing_config_gate_dead_layer_fraction(tmp_path):
    from torchpruner_tpu.search.grid import TrialSpec
    from torchpruner_tpu.search.pricing import price_campaign

    spec = digits_smoke()
    trials = [
        TrialSpec("t00_dead", {"policy": "fraction",
                               "layer_fractions": {"conv9": 0.5}}),
        TrialSpec("t01_bad_frac", {"policy": "fraction",
                                   "fraction": 1.5}),
    ]
    pricing = price_campaign(spec, trials, str(tmp_path))
    assert pricing["t00_dead"]["excluded_by"] == "config"
    assert any("conv9" in r for r in pricing["t00_dead"]["reasons"])
    assert pricing["t01_bad_frac"]["excluded_by"] == "config"


def test_pricing_config_gate_non_numeric_fraction(tmp_path):
    """A null/non-numeric fraction override must exclude THAT candidate
    loudly — never crash the whole campaign's pricing pass."""
    from torchpruner_tpu.search.grid import TrialSpec
    from torchpruner_tpu.search.pricing import price_campaign

    trials = [
        TrialSpec("t00_null_frac", {"policy": "fraction",
                                    "fraction": None}),
        TrialSpec("t01_ok", {"policy": "fraction", "fraction": 0.5}),
    ]
    pricing = price_campaign(digits_smoke(), trials, str(tmp_path))
    assert pricing["t00_null_frac"]["excluded_by"] == "config"
    assert any("non-numeric" in r
               for r in pricing["t00_null_frac"]["reasons"])
    assert pricing["t01_ok"]["feasible"]


# ---------------------------------------------------------------------------
# per-layer fractions (config + prune loop)
# ---------------------------------------------------------------------------


def test_policy_for_target_first_match_wins():
    from torchpruner_tpu.experiments.prune_retrain import policy_for_target
    from torchpruner_tpu.utils.config import ExperimentConfig

    cfg = ExperimentConfig(policy="negative", fraction=0.5,
                           layer_fractions={"fc": 0.25, "fc2": 0.75})
    assert policy_for_target(cfg, "fc1") == ("fraction", 0.25)
    # insertion order: "fc" matches fc2 first
    assert policy_for_target(cfg, "fc2") == ("fraction", 0.25)
    assert policy_for_target(cfg, "out") == ("negative", 0.5)


def test_layer_fractions_validation():
    from torchpruner_tpu.utils.config import ExperimentConfig

    with pytest.raises(ValueError, match="layer_fractions"):
        ExperimentConfig(layer_fractions={"fc1": 1.0})


def test_prune_retrain_honors_layer_fractions():
    from torchpruner_tpu.experiments.presets import get_preset
    from torchpruner_tpu.experiments.prune_retrain import run_prune_retrain

    cfg = dataclasses.replace(
        get_preset("mnist_mlp_shapley", smoke=True),
        name="layerfrac_smoke", method="weight_norm", method_kwargs={},
        policy="fraction", fraction=0.5,
        layer_fractions={"fc1": 0.25}, finetune_epochs=0,
    )
    history = run_prune_retrain(cfg, verbose=False)
    widths = history[-1].widths
    # fc1 pruned at its per-layer 0.25, fc2 at the global 0.5
    assert widths["fc1"] == 48 and widths["fc2"] == 32, widths


# ---------------------------------------------------------------------------
# frontier artifact
# ---------------------------------------------------------------------------


def _fake_manifest_and_results():
    spec = digits_smoke()
    manifest = CampaignManifest(
        name=spec.name, campaign_id=spec.campaign_id,
        spec_digest=spec.digest(),
        trials={
            "t0": {"overrides": {"fraction": 0.25}, "status": "done",
                   "pricing": {"predicted_step_ms": 0.03,
                               "predicted_trial_s": 1.0}},
            "t1": {"overrides": {"fraction": 0.5}, "status": "done"},
            "t2": {"overrides": {"fraction": 0.5, "lr": 3.0},
                   "status": "early_stopped"},
            "t3": {"overrides": {"finetune_epochs": 512},
                   "status": "excluded",
                   "pricing": {"excluded_by": "cost",
                               "reasons": ["512x the median"]}},
        })
    results = {
        "t0": {"final_acc": 0.9, "final_loss": 0.3, "params": 5962,
               "flops": 24000.0, "rounds": 2, "checkpoint": "ckpt-000002",
               "checkpoint_digest": "abc123", "ledger_run_id": "c:t0",
               "curve": [[30000.0, 0.5], [24000.0, 0.9]],
               "step_time_mean_s": 0.001, "wall_s": 5.0},
        "t1": {"final_acc": 0.6, "final_loss": 0.9, "params": 3466,
               "flops": 14000.0, "rounds": 2, "checkpoint": "ckpt-000002",
               "checkpoint_digest": "def456", "ledger_run_id": "c:t1",
               "curve": [[20000.0, 0.4], [14000.0, 0.6]],
               "step_time_mean_s": 0.001, "wall_s": 5.0},
    }
    return spec, manifest, results


def test_build_frontier_points_counts_and_provenance():
    spec, manifest, results = _fake_manifest_and_results()
    fr = build_frontier(spec=spec, manifest=manifest, results=results,
                        dense_flops=32000.0, margin=0.02)
    assert fr["counts"] == {"trials": 4, "completed": 2,
                            "non_dominated": 2, "dominated": 0,
                            "early_stopped": 1, "excluded": 1,
                            "failed": 0}
    by = {p["trial_id"]: p for p in fr["points"]}
    assert by["t0"]["checkpoint_digest"] == "abc123"
    assert by["t0"]["ledger_run_id"] == "c:t0"
    assert by["t0"]["config"] == {"fraction": 0.25}
    assert fr["early_stopped"] == ["t2"]
    assert fr["excluded"][0]["trial_id"] == "t3"
    assert fr["buckets"]["frontier_best_acc_flops_le_50pct"] == 0.6
    assert fr["buckets"]["frontier_best_acc_flops_le_100pct"] == 0.9


def test_frontier_digest_ignores_volatile_fields():
    spec, manifest, results = _fake_manifest_and_results()
    fr1 = build_frontier(spec=spec, manifest=manifest, results=results,
                         dense_flops=32000.0, margin=0.02)
    # volatile: wall-clock measurements and the commit-counter-shaped
    # checkpoint NAME (an interrupted trial commits more often)
    results["t0"] = dict(results["t0"], wall_s=99.0,
                         step_time_mean_s=0.5, checkpoint="ckpt-000007")
    fr2 = build_frontier(spec=spec, manifest=manifest, results=results,
                         dense_flops=32000.0, margin=0.02)
    assert fr1["frontier_digest"] == fr2["frontier_digest"]
    # deterministic content: any accuracy change must change the digest
    results["t0"] = dict(results["t0"], final_acc=0.91)
    fr3 = build_frontier(spec=spec, manifest=manifest, results=results,
                         dense_flops=32000.0, margin=0.02)
    assert fr3["frontier_digest"] != fr1["frontier_digest"]
    assert frontier_digest(fr3) == fr3["frontier_digest"]


def test_bucket_scalars_names_and_values():
    pts = [{"accuracy": 0.9, "flops": 80.0},
           {"accuracy": 0.7, "flops": 40.0},
           {"accuracy": 0.5, "flops": 20.0}]
    s = bucket_scalars(pts, 100.0, [0.25, 0.5, 1.0])
    assert s == {"frontier_best_acc_flops_le_25pct": 0.5,
                 "frontier_best_acc_flops_le_50pct": 0.7,
                 "frontier_best_acc_flops_le_100pct": 0.9}


def test_frontier_gauges_ledger_and_report_section(tmp_path):
    from torchpruner_tpu import obs
    from torchpruner_tpu.obs.report import format_report, load_run
    from torchpruner_tpu.search.frontier import record_obs

    spec, manifest, results = _fake_manifest_and_results()
    fr = build_frontier(spec=spec, manifest=manifest, results=results,
                        dense_flops=32000.0, margin=0.02)
    obs.configure(str(tmp_path))
    try:
        record_obs(fr)
        assert obs.counter_value("frontier_points_total") == 2
        assert obs.counter_value("frontier_early_stopped_total") == 1
        assert obs.counter_value(
            "frontier_best_acc_flops_le_50pct") == 0.6
    finally:
        obs.shutdown()
    rep = load_run(str(tmp_path))
    assert rep["frontier"], "frontier ledger record missing"
    assert rep["metrics"]["frontier_best_acc"] == 0.9
    md = format_report(rep)
    assert "frontier: 2 point(s), 2 non-dominated" in md
    assert "`t0`" in md and "abc123"[:12] in md
    assert "<=50pct=0.6" in md


def test_obs_diff_carries_frontier_scalars_and_gates(tmp_path):
    from torchpruner_tpu.obs.ledger import build_report
    from torchpruner_tpu.obs.report import check_gates, diff_runs

    def rep(best):
        return build_report(metrics={
            "frontier_best_acc": best,
            "frontier_best_acc_flops_le_50pct": best - 0.2,
            "search_trials_early_stopped_total": 1,
        })

    d = diff_runs(rep(0.9), rep(0.7))
    assert d["scalars"]["frontier_best_acc"]["delta"] == pytest.approx(
        -0.2)
    gates = {"frontier_best_acc": {"max_decrease": 0.1},
             "search_trials_early_stopped_total": {"max_decrease": 0}}
    v = check_gates(d, gates)
    assert [x["gate"] for x in v] == ["frontier_best_acc"]
    assert not check_gates(diff_runs(rep(0.9), rep(0.9)), gates)


# ---------------------------------------------------------------------------
# ledger trial stamping (satellite 1)
# ---------------------------------------------------------------------------


def test_ledger_stamps_and_dedups_per_trial(tmp_path):
    from torchpruner_tpu.obs.ledger import ProvenanceRecorder

    rec = ProvenanceRecorder(str(tmp_path))
    rec.set_context(trial_id="tA", campaign_id="c1")
    assert rec.record_round(target="fc1", round=0, post={"acc": 0.5})
    # same identity within the trial dedups...
    assert not rec.record_round(target="fc1", round=0,
                                post={"acc": 0.6})
    # ...but ANOTHER trial's same-named round coexists
    rec.set_context(trial_id="tB", campaign_id="c1")
    assert rec.record_round(target="fc1", round=0, post={"acc": 0.7})
    rec.close()
    from torchpruner_tpu.obs.ledger import load_ledger

    rounds = [r for r in load_ledger(str(tmp_path / "ledger.jsonl"))
              if r.get("event") == "round"]
    assert [(r["trial_id"], r["campaign_id"]) for r in rounds] == \
        [("tA", "c1"), ("tB", "c1")]


def test_report_groups_rounds_per_trial(tmp_path):
    from torchpruner_tpu.obs.ledger import build_report
    from torchpruner_tpu.obs.report import (
        _rounds_by_label,
        diff_runs,
        format_report,
    )

    rounds = [
        {"event": "round", "trial_id": "tB", "target": "fc1", "round": 0,
         "post": {"acc": 0.7}, "pre": {"acc": 0.2}},
        {"event": "round", "trial_id": "tA", "target": "fc1", "round": 0,
         "post": {"acc": 0.5}, "pre": {"acc": 0.2}},
    ]
    rep = build_report(records=rounds)
    labels = set(_rounds_by_label(rep))
    assert labels == {"tA/fc1", "tB/fc1"}
    md = format_report(rep)
    assert "| trial " in md and "`tA`" in md and "`tB`" in md
    # per-trial matching: a diff of the same report has zero missing
    d = diff_runs(rep, rep)
    assert set(d["rounds"]) == labels and not d["missing_rounds"]
    # un-stamped reports keep the pre-campaign rendering (no column)
    plain = build_report(records=[dict(rounds[0], trial_id=None)])
    assert "| trial " not in format_report(plain)


def test_set_trial_module_hook(tmp_path):
    from torchpruner_tpu import obs
    from torchpruner_tpu.obs.report import load_run

    obs.configure(str(tmp_path))
    try:
        obs.set_trial("t42", campaign_id="camp-1")
        obs.record_round(target="fc1", round=0, post={"acc": 0.5})
        obs.record_trial(trial_id="t42", status="done", accuracy=0.5)
    finally:
        obs.shutdown()
    rep = load_run(str(tmp_path))
    assert rep["rounds"][0]["trial_id"] == "t42"
    assert rep["rounds"][0]["campaign_id"] == "camp-1"
    assert rep["trials"][0]["status"] == "done"


# ---------------------------------------------------------------------------
# campaign manifest
# ---------------------------------------------------------------------------


def test_campaign_manifest_roundtrip_and_kind_check(tmp_path):
    m = CampaignManifest(name="x", campaign_id="x-1", spec_digest="d",
                         trials={"t0": {"status": "pending"}})
    m.save(str(tmp_path))
    loaded = CampaignManifest.load(str(tmp_path))
    assert loaded.trials == m.trials and loaded.campaign_id == "x-1"
    bad = dataclasses.replace(m, kind="serve")
    bad.save(str(tmp_path))
    with pytest.raises(ValueError, match="search"):
        CampaignManifest.load(str(tmp_path))


def test_run_campaign_refuses_grid_mismatch(tmp_path):
    spec = digits_smoke()
    CampaignManifest(name=spec.name, campaign_id=spec.campaign_id,
                     spec_digest="somethingelse").save(str(tmp_path))
    with pytest.raises(ValueError, match="different grid"):
        run_campaign(spec, str(tmp_path), jobs=1)


# ---------------------------------------------------------------------------
# the live driver (slow lane: subprocess workers, real prune-retrain)
# ---------------------------------------------------------------------------


def _tiny_spec() -> CampaignSpec:
    """A reduced digits campaign for the in-test driver runs: 3 healthy
    trials, one doomed (diverging LR, slow enough to be judged), one
    over-budget — the full gate/early-stop/frontier shape at ~third of
    the smoke preset's wall."""
    return CampaignSpec(
        name="tiny_ci",
        base="mnist_mlp_shapley",
        smoke=True,
        common={"policy": "fraction", "finetune_epochs": 1, "lr": 0.05,
                "method_kwargs": {}},
        axes={"method": ["weight_norm"], "fraction": [0.25, 0.5, 0.75]},
        trials=[
            {"id": "doomed_lr", "method": "random", "fraction": 0.5,
             "finetune_epochs": 4, "lr": 3.0},
            {"id": "over_budget", "method": "weight_norm",
             "fraction": 0.5, "finetune_epochs": 512},
        ],
        jobs=2,
        early_stop={"margin": 0.15, "min_rounds": 1},
        max_trial_cost_ratio=16.0,
    )


@pytest.mark.slow
def test_campaign_end_to_end(tmp_path):
    from torchpruner_tpu import obs
    from torchpruner_tpu.obs.report import load_run

    spec = _tiny_spec()
    obs.configure(str(tmp_path / "obs"))
    try:
        fr = run_campaign(spec, str(tmp_path), cpu=True, poll_s=0.2)
    finally:
        obs.shutdown()
    assert fr["counts"]["completed"] == 3
    assert fr["early_stopped"] == ["t03_doomed_lr"]
    assert [e["trial_id"] for e in fr["excluded"]] == ["t04_over_budget"]
    by = {p["trial_id"]: p for p in fr["points"]}
    for p in by.values():
        # every point carries config + checkpoint digest + ledger
        # provenance (the acceptance criterion)
        assert p["config"].get("fraction") in (0.25, 0.5, 0.75)
        assert p["checkpoint_digest"] and p["ledger_run_id"]
        assert p["accuracy"] is not None and p["flops"] > 0
        assert len(p["curve"]) if "curve" in p else True
    # the artifact is on disk, digest-consistent, and re-renderable
    disk = json.load(open(tmp_path / "frontier.json"))
    assert disk["frontier_digest"] == fr["frontier_digest"]
    assert frontier_digest(disk) == disk["frontier_digest"]
    # campaign-level report: frontier section + counters
    rep = load_run(str(tmp_path / "obs"))
    assert rep["metrics"]["search_trials_early_stopped_total"] == 1
    assert rep["metrics"]["search_trials_completed_total"] == 3
    assert rep["metrics"]["frontier_points_total"] == 3
    # each trial's own obs dir carries its stamped rounds
    done = [tid for tid, st in CampaignManifest.load(
        str(tmp_path)).trials.items() if st["status"] == "done"]
    one = load_run(os.path.join(str(tmp_path), "trials", done[0], "obs"))
    assert all(r["trial_id"] == done[0] for r in one["rounds"])
    assert one["run"]["campaign_id"] == spec.campaign_id
    # worker output is preserved per trial (failed-trial diagnosis)
    assert os.path.exists(
        os.path.join(str(tmp_path), "trials", done[0], "worker.log"))


@pytest.mark.slow
def test_campaign_kill9_resume_reproduces_identical_frontier(tmp_path):
    """The chaos drill: SIGKILL the driver (and its workers) mid-
    campaign and mid-early-stop; resuming must reproduce the IDENTICAL
    frontier an uninterrupted campaign produces."""
    spec = _tiny_spec()
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec.to_dict()))

    def cli(dir_, *extra, check=True):
        r = subprocess.run(
            [sys.executable, "-m", "torchpruner_tpu", "search",
             str(spec_path), "--cpu", "--campaign-dir", str(dir_),
             "--poll-s", "0.2", *extra],
            capture_output=True, text=True, timeout=900, cwd=REPO)
        if check:
            assert r.returncode == 0, r.stderr[-2000:]
        return r

    # uninterrupted reference
    cli(tmp_path / "ref")
    ref = json.load(open(tmp_path / "ref" / "frontier.json"))
    assert ref["counts"]["early_stopped"] == 1

    # drill 1: kill -9 mid-campaign (after the 2nd completion, queue
    # still full), then resume
    killed = cli(tmp_path / "drill", "--chaos",
                 '{"kill_after_trials": 2}', check=False)
    assert killed.returncode == -signal.SIGKILL, (
        killed.returncode, killed.stderr[-1000:])
    m = CampaignManifest.load(str(tmp_path / "drill"))
    assert sum(1 for s in m.trials.values()
               if s["status"] == "done") >= 2
    assert any(s["status"] in ("pending", "running")
               for s in m.trials.values())
    cli(tmp_path / "drill")
    got = json.load(open(tmp_path / "drill" / "frontier.json"))
    assert got["frontier_digest"] == ref["frontier_digest"], (
        got["counts"], ref["counts"])

    # drill 2: kill -9 mid-early-stop (the decision is recorded, the
    # worker still lives), then resume — the durable decision holds
    killed = cli(tmp_path / "drill2", "--chaos",
                 '{"kill_on_early_stop": true}', check=False)
    assert killed.returncode == -signal.SIGKILL
    m = CampaignManifest.load(str(tmp_path / "drill2"))
    assert any(s["status"] == "early_stop_requested"
               for s in m.trials.values()), \
        {t: s["status"] for t, s in m.trials.items()}
    cli(tmp_path / "drill2")
    got2 = json.load(open(tmp_path / "drill2" / "frontier.json"))
    assert got2["frontier_digest"] == ref["frontier_digest"]
    m = CampaignManifest.load(str(tmp_path / "drill2"))
    assert m.trials["t03_doomed_lr"]["status"] == "early_stopped"

"""Fleet serving-plane tests: durable request-plane journal semantics
(accept ⇒ completed-or-redrivable by construction), health-checked
router dispatch with retry/backoff/deadline budgets, failover +
journaled redrive on replica death, degraded-mode admission shedding,
rolling hot-swap, the fleet-wide obs shard merge, and (slow lane) the
real kill -9 subprocess drill."""

import json
import os
import threading
import time

import numpy as np
import pytest

from torchpruner_tpu.fleet import (
    ACCEPTED,
    COMPLETED,
    DISPATCHED,
    FAILED,
    FleetRouter,
    PlaneRecord,
    ReplicaBusy,
    ReplicaDown,
    RequestPlane,
    RouterPolicy,
)
from torchpruner_tpu.fleet.frontend import FleetChaos
from torchpruner_tpu.fleet.report import merge_replica_shards

PAYLOAD = {"prompt_ids": [1, 2, 3], "max_new": 4, "eos_id": None,
           "temperature": 0.0, "top_k": None, "top_p": None, "seed": 7}


# -- request plane -----------------------------------------------------------


def test_plane_accept_is_durable_before_ack(tmp_path):
    journal = str(tmp_path / "journal.json")
    plane = RequestPlane(journal)
    rec = plane.accept(PAYLOAD, deadline_s=60.0)
    # the journal already holds the record when accept() returns — the
    # "accepted ⇒ durable" half of the zero-loss contract
    raw = json.load(open(journal))
    assert [r["rid"] for r in raw["records"]] == [rec.rid]
    assert raw["records"][0]["state"] == ACCEPTED
    assert raw["records"][0]["payload"]["prompt_ids"] == [1, 2, 3]
    assert rec.remaining_s() > 50


def test_plane_lifecycle_and_idempotent_completion(tmp_path):
    plane = RequestPlane(str(tmp_path / "j.json"))
    rec = plane.accept(PAYLOAD, deadline_s=60.0)
    got = plane.checkout()
    assert got is rec and rec.state == DISPATCHED
    assert plane.checkout() is None
    plane.assign(rec.rid, "replica0")
    assert rec.replica == "replica0" and rec.attempts == 1
    assert plane.assigned_to("replica0") == [rec.rid]
    # release → pending again (front), redrive counted
    assert plane.release(rec.rid, redrive=True)
    assert rec.state == ACCEPTED and rec.redrives == 1
    assert plane.pending_depth == 1
    plane.checkout()
    assert plane.complete(rec.rid, [9, 8, 7, 6], "replica1")
    assert rec.state == COMPLETED and rec.completed_by == "replica1"
    assert rec._event.is_set()
    # a hedged duplicate finishing second is dropped, not double-counted
    assert not plane.complete(rec.rid, [0, 0, 0, 0], "replica0")
    assert rec.tokens == [9, 8, 7, 6]
    assert plane.duplicate_results_total == 1
    # terminal records cannot be released or failed
    assert not plane.release(rec.rid)
    assert not plane.fail(rec.rid, "late")
    assert plane.all_terminal()


def test_plane_load_redrives_non_terminal(tmp_path):
    """Router death: reloading the journal turns accepted AND
    dispatched records back into pending work (redrive), keeps
    completed ones terminal, and never reuses an rid."""
    journal = str(tmp_path / "j.json")
    plane = RequestPlane(journal)
    a = plane.accept(PAYLOAD, deadline_s=60.0)
    b = plane.accept(PAYLOAD, deadline_s=60.0)
    c = plane.accept(PAYLOAD, deadline_s=60.0)
    plane.checkout()
    plane.assign(a.rid, "replica0")
    plane.checkout()
    plane.complete(b.rid, [1], "replica1")
    del plane

    revived = RequestPlane.load(journal)
    assert revived.get(b.rid).state == COMPLETED
    assert revived.get(b.rid)._event.is_set()
    assert revived.get(a.rid).state == ACCEPTED
    assert revived.get(a.rid).redrives == 1  # was dispatched
    assert revived.get(c.rid).redrives == 0  # was merely accepted
    assert revived.pending_depth == 2
    fresh = revived.accept(PAYLOAD, deadline_s=1.0)
    assert fresh.rid not in {a.rid, b.rid, c.rid}


def test_plane_compaction_bounds_journal(tmp_path):
    """The long-running endpoint's journal stays bounded: only the
    newest ``retain_terminal`` terminal records are kept (waiters hold
    their own record reference; non-terminal records are never
    touched)."""
    journal = str(tmp_path / "j.json")
    plane = RequestPlane(journal, retain_terminal=2)
    recs = [plane.accept(PAYLOAD, deadline_s=60.0) for _ in range(5)]
    keep = plane.accept(PAYLOAD, deadline_s=60.0)  # stays accepted
    for r in recs:
        plane.checkout()
        plane.complete(r.rid, [1], "replica0")
    assert plane.compacted_total == 3
    raw = json.load(open(journal))
    states = [r["state"] for r in raw["records"]]
    assert states.count("completed") == 2
    assert plane.get(keep.rid) is not None
    assert recs[0]._event.is_set()  # the waiter's copy is unaffected


# -- fake replicas for router unit tests -------------------------------------


class FakeReplica:
    """Scripted stand-in for ReplicaClient: serves greedy 'tokens'
    derived from the payload, can die after K requests, report a
    health state, or shed with 503."""

    def __init__(self, name, *, die_after=None, state="ready",
                 busy=False, latency_s=0.0):
        self.name = name
        self.die_after = die_after
        self.state = state
        self.busy = busy
        self.latency_s = latency_s
        self.served = 0
        self.dead = False
        self.swapped = 0

    def healthz(self, timeout=None):
        if self.dead:
            return {"live": False, "ready": False, "state": "dead"}
        return {"live": True, "ready": self.state == "ready",
                "state": self.state}

    def stats(self, timeout=None):
        return {"kv_page_occupancy": 0.1 * self.served,
                "slot_utilization": 0.0, "queue_depth": 0,
                "swaps": self.swapped, "state": self.state}

    def generate(self, payload, timeout=None):
        if self.dead:
            raise ReplicaDown(f"{self.name}: connection refused")
        if self.busy:
            raise ReplicaBusy(f"{self.name}: 503", retry_after_s=0.01)
        if self.latency_s:
            time.sleep(self.latency_s)
        if self.die_after is not None and self.served >= self.die_after:
            self.dead = True
            raise ReplicaDown(f"{self.name}: connection reset mid-request")
        self.served += 1
        return {"state": "done",
                "tokens": [x + 1 for x in payload["prompt_ids"]]}

    def swap(self, checkpoint, timeout=None):
        self.swapped += 1
        return {"staging": True}


def _fast_policy(**kw):
    base = dict(queue_bound=32, max_attempts=6, attempt_timeout_s=5.0,
                default_deadline_s=30.0, base_backoff_s=0.001,
                max_backoff_s=0.01, health_every_s=0.01,
                max_inflight_per_replica=4)
    base.update(kw)
    return RouterPolicy(**base)


def _run_router(router, timeout_s=30.0):
    router.run_until_drained(poll_s=0.002, timeout_s=timeout_s)
    router.close()


# -- router ------------------------------------------------------------------


def test_router_dispatches_least_loaded_and_completes(tmp_path):
    plane = RequestPlane(str(tmp_path / "j.json"))
    reps = [FakeReplica("replica0"), FakeReplica("replica1")]
    router = FleetRouter(plane, reps, policy=_fast_policy())
    recs = [router.submit({**PAYLOAD, "prompt_ids": [i, i + 1]})
            for i in range(8)]
    assert all(r is not None for r in recs)
    _run_router(router)
    for i, rec in enumerate(recs):
        assert rec.state == COMPLETED
        assert rec.tokens == [i + 1, i + 2]
    # least-loaded routing spread the work over both replicas
    assert reps[0].served > 0 and reps[1].served > 0
    assert router.failovers_total == 0


def test_router_failover_redrives_dead_replicas_requests(tmp_path):
    """A replica dying mid-request loses nothing: its journaled
    records re-enter the pending queue (redrive) and complete on the
    survivor; the death is counted exactly once."""
    plane = RequestPlane(str(tmp_path / "j.json"))
    reps = [FakeReplica("replica0", die_after=2),
            FakeReplica("replica1")]
    router = FleetRouter(plane, reps, policy=_fast_policy())
    recs = [router.submit({**PAYLOAD, "prompt_ids": [i]})
            for i in range(10)]
    _run_router(router)
    assert all(r.state == COMPLETED for r in recs)
    assert all(r.tokens == [i + 1] for i, r in enumerate(recs))
    assert router.failovers_total == 1
    assert reps[0].served == 2
    assert reps[1].served >= 8
    # the records replica0 killed carry their redrive/attempt history
    assert sum(r.redrives for r in recs) >= 1 \
        or sum(r.attempts for r in recs) > len(recs)


def test_router_all_dead_fails_records_not_silently(tmp_path):
    """Nothing usable: records fail LOUDLY (attempts/deadline
    exhausted, fleet_failed counters) — never hang, never vanish."""
    plane = RequestPlane(str(tmp_path / "j.json"))
    router = FleetRouter(
        plane, [FakeReplica("replica0", state="draining")],
        policy=_fast_policy(max_attempts=3, default_deadline_s=0.5))
    rec = router.submit(PAYLOAD)
    assert rec is not None
    router.run_until_drained(poll_s=0.002, timeout_s=30.0)
    router.close()
    assert rec.state == FAILED
    assert rec.error


def test_router_admission_sheds_on_bound_and_degraded(tmp_path):
    plane = RequestPlane(str(tmp_path / "j.json"))
    reps = [FakeReplica("replica0"), FakeReplica("replica1")]
    router = FleetRouter(plane, reps,
                         policy=_fast_policy(queue_bound=4,
                                             degraded_queue_factor=0.5))
    router.check_health(force=True)
    # fill the pending queue to the bound without dispatching
    for i in range(4):
        assert router.submit(PAYLOAD) is not None
    verdict = router.admission()
    assert not verdict["accepting"] and verdict["reason"] == "backpressure"
    assert verdict["code"] == 429 and verdict["retry_after_s"] >= 1
    assert router.submit(PAYLOAD) is None
    assert router.shed_total == 1 and plane.counts()["shed"] == 1
    # SLO-breach majority tightens the bound (degraded admission):
    # depth 2 < bound 4 would accept, but 2 >= 4*0.5 sheds
    _run_router(router)
    for r in reps:
        r.state = "slo_breach"
    router2 = FleetRouter(RequestPlane(), reps,
                          policy=_fast_policy(queue_bound=4,
                                              degraded_queue_factor=0.5))
    router2.check_health(force=True)
    assert router2.degraded()
    assert router2.effective_queue_bound() == 2
    assert router2.submit(PAYLOAD) is not None
    assert router2.submit(PAYLOAD) is not None
    assert router2.submit(PAYLOAD) is None  # shed at the tightened bound
    assert router2.admission()["reason"] == "degraded"
    router2.close()


def test_router_prefers_ready_but_degrades_gracefully(tmp_path):
    """slo_breach replicas are avoided while a ready one exists, but a
    fully-degraded fleet still serves (only draining/dead are never
    picked)."""
    plane = RequestPlane()
    breached = FakeReplica("replica0", state="slo_breach")
    ready = FakeReplica("replica1")
    router = FleetRouter(plane, [breached, ready],
                         policy=_fast_policy())
    recs = [router.submit({**PAYLOAD, "prompt_ids": [i]})
            for i in range(6)]
    _run_router(router)
    assert all(r.state == COMPLETED for r in recs)
    assert breached.served == 0 and ready.served == 6
    # now nothing is ready: the breached replica still gets the work
    breached2 = FakeReplica("replica0", state="slo_breach")
    router2 = FleetRouter(RequestPlane(), [breached2],
                          policy=_fast_policy())
    rec = router2.submit(PAYLOAD)
    _run_router(router2)
    assert rec.state == COMPLETED and breached2.served == 1


def test_router_rolling_swap_walks_replicas(tmp_path):
    reps = [FakeReplica("replica0"), FakeReplica("replica1"),
            FakeReplica("replica2")]
    # FakeReplica.swap bumps its own counter, which stats() reports —
    # the router's wait-for-landing loop sees it immediately
    router = FleetRouter(RequestPlane(), reps, policy=_fast_policy())
    router.check_health(force=True)
    assert router.rolling_swap("/fake/ckpt", wait_s=5.0) == 3
    assert [r.swapped for r in reps] == [1, 1, 1]
    router.close()


def test_router_policy_loop_knobs(tmp_path):
    """The hardcoded swap/drain sleeps are RouterPolicy fields now —
    the defaults match the old constants, and run_until_drained uses
    the policy cadence when no explicit poll_s is passed."""
    assert RouterPolicy().swap_poll_s == 0.25
    assert RouterPolicy().drain_poll_s == 0.02
    reps = [FakeReplica("replica0")]
    router = FleetRouter(RequestPlane(), reps,
                         policy=_fast_policy(swap_poll_s=0.001,
                                             drain_poll_s=0.001))
    rec = router.submit(PAYLOAD)
    router.run_until_drained(timeout_s=30.0)  # policy drain_poll_s
    router.close()
    assert rec.state == COMPLETED
    assert router.policy.swap_poll_s == 0.001


def test_health_scrape_records_rtt_and_replica_gauges(tmp_path):
    """Each health scrape lands its RTT in ``fleet_scrape_seconds``
    and the scraped view in per-replica gauges — the history the
    router's own time-series recorder snapshots every window."""
    from torchpruner_tpu import obs

    obs.shutdown()
    obs.configure(process_index=0, annotate=False, watch_compiles=False)
    try:
        reps = [FakeReplica("replica0"), FakeReplica("replica1")]
        reps[0].served = 3  # occupancy = 0.3 via FakeReplica.stats
        router = FleetRouter(RequestPlane(), reps,
                             policy=_fast_policy())
        router.check_health(force=True)
        snap = obs.get().metrics.snapshot()
        assert snap["fleet_scrape_seconds_count"] == 2
        assert snap["fleet_replica_replica0_occupancy"] \
            == pytest.approx(0.3)
        assert snap["fleet_replica_replica0_queue_depth"] == 0
        assert snap["fleet_replica_replica0_state_code"] == 0  # ready
        assert snap["fleet_replica_replica0_scrape_rtt_s"] >= 0.0
        # a dead replica keeps reporting: state code -1, RTT still
        # sampled (the probe round trip is what timed out/failed)
        reps[1].dead = True
        router.check_health(force=True)
        snap = obs.get().metrics.snapshot()
        assert snap["fleet_replica_replica1_state_code"] == -1
        assert snap["fleet_scrape_seconds_count"] == 4
        router.close()
    finally:
        obs.shutdown()


def test_fleet_chaos_validates_keys():
    c = FleetChaos.from_any('{"kill_replica_at_step": 3, '
                            '"replica_index": 1}')
    assert c.kill_replica_at_step == 3 and c.replica_index == 1
    assert FleetChaos.from_any(None).kill_replica_at_step == -1
    with pytest.raises(ValueError, match="unknown fleet chaos"):
        FleetChaos.from_any('{"kill_at_step": 3}')


# -- fleet-wide obs shard merge ----------------------------------------------


def test_merge_replica_shards_rehomes_and_skips_missing(tmp_path):
    from torchpruner_tpu.obs.aggregate import (
        load_shards,
        merge_shards,
        shard_path,
    )

    fleet_obs = str(tmp_path / "obs")
    os.makedirs(fleet_obs)
    rep_dirs = [str(tmp_path / f"obs/replica{i}") for i in range(3)]
    for i, d in enumerate(rep_dirs[:2]):  # replica2 was kill -9'd
        os.makedirs(d)
        json.dump({"process_index": 0,
                   "counters": {"serve_completed_total":
                                {"value": 5 + i, "help": "x"}},
                   "gauges": {}, "histograms": {}},
                  open(shard_path(d, 0), "w"))
    present = merge_replica_shards(fleet_obs, rep_dirs)
    assert [present[d] for d in rep_dirs] == [True, True, False]
    shards = load_shards(fleet_obs)
    assert [s["process_index"] for s in shards] == [1, 2]
    merged = merge_shards(shards)
    # counters SUM across replicas — the fleet-wide view
    assert merged.get("serve_completed_total").value == 11


# -- integration: router over real engines (in-process HTTP) -----------------


@pytest.fixture
def live_replicas():
    """Two REAL ServeEngine replicas behind the real HTTP front end,
    in-process (threads) — identical weights/geometry, ephemeral
    ports."""
    import jax.numpy as jnp  # noqa: F401 - ensures jax configured

    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.fleet.replica import ReplicaClient
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.serve import ServeEngine
    from torchpruner_tpu.serve.frontend import _http_server

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    engines, servers, stops, threads, clients = [], [], [], [], []
    for i in range(2):
        eng = ServeEngine(model, params, n_slots=2, max_len=64,
                          queue_bound=8, retain_results=False)
        server = _http_server(eng, 0, request_timeout_s=120.0)
        port = server.server_address[1]
        stop = threading.Event()
        threads.append(threading.Thread(target=server.serve_forever,
                                        daemon=True))
        threads.append(threading.Thread(
            target=lambda e=eng, s=stop: e.run(stop_event=s),
            daemon=True))
        engines.append(eng)
        servers.append(server)
        stops.append(stop)
        clients.append(ReplicaClient(f"replica{i}", port))
    for t in threads:
        t.start()
    try:
        yield model, params, engines, servers, stops, clients
    finally:
        for stop in stops:
            stop.set()
        for server in servers:
            server.shutdown()


def test_router_over_real_replicas_bit_identical(live_replicas,
                                                 tmp_path):
    """End to end over the REAL serve HTTP front end: the router
    completes every request and each result is bit-identical to its
    solo generate() decode — then one replica 'dies' (server torn
    down) and the remainder still completes on the survivor with a
    counted failover."""
    from torchpruner_tpu.generate import generate

    model, params, engines, servers, stops, clients = live_replicas
    plane = RequestPlane(str(tmp_path / "j.json"))
    router = FleetRouter(
        plane, clients,
        policy=_fast_policy(attempt_timeout_s=120.0,
                            default_deadline_s=240.0,
                            base_backoff_s=0.01, max_backoff_s=0.1,
                            health_every_s=0.05))
    rng = np.random.default_rng(0)
    payloads = [{"prompt_ids": rng.integers(0, 64, size=4 + (i % 3)
                                            ).tolist(),
                 "max_new": 3 + (i % 2), "seed": i,
                 "temperature": 0.0}
                for i in range(6)]
    recs = [router.submit(p) for p in payloads]
    router.run_until_drained(poll_s=0.01, timeout_s=240.0)
    assert all(r.state == COMPLETED for r in recs)

    # replica0 dies; the rest of the traffic survives on replica1
    stops[0].set()
    servers[0].shutdown()
    recs2 = [router.submit(p) for p in payloads[:3]]
    router.run_until_drained(poll_s=0.01, timeout_s=240.0)
    router.close()
    assert all(r.state == COMPLETED for r in recs2)
    assert all(r.completed_by == "replica1" for r in recs2)
    assert router.failovers_total >= 1

    import jax

    for p, rec in zip(payloads, recs):
        want = np.asarray(generate(
            model, params,
            np.asarray(p["prompt_ids"], np.int32)[None], p["max_new"],
            rng=jax.random.PRNGKey(p["seed"]), max_len=64))[0]
        np.testing.assert_array_equal(
            np.asarray(rec.tokens, np.int32), want)


# -- the real thing: subprocess kill -9 drill (slow lane) --------------------


@pytest.mark.slow
def test_fleet_kill9_drill_zero_loss(tmp_path):
    """3 subprocess replicas, open-loop Poisson load, kill -9 one
    mid-stream: zero accepted-request loss, journaled redrive to the
    survivors, bit-identical --verify, and the survivors' obs shards
    merged into one fleet report."""
    import subprocess
    import sys

    fleet_dir = str(tmp_path / "fleet")
    r = subprocess.run(
        [sys.executable, "-m", "torchpruner_tpu", "fleet", "llama_tiny",
         "--cpu", "--replicas", "3", "--slots", "2", "--max-len", "96",
         "--synthetic", "18", "--rate", "3.0", "--verify",
         "--max-new", "8,12", "--prompt-lens", "4,8",
         "--fleet-dir", fleet_dir,
         "--chaos", '{"kill_replica_at_step": 5}'],
        capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    s = json.loads([l for l in r.stdout.splitlines()
                    if l.startswith("{")][-1])
    assert s["killed"] == ["replica0"]
    assert s["accepted"] == 18 and s["completed"] == 18
    assert s["lost"] == 0 and s["verify_mismatches"] == 0
    assert s["failovers"] >= 1 and s["redrives"] >= 1
    assert s["shards_merged"] == 2  # the kill -9'd replica ships none
    # the journal is the durable account of the whole drill
    j = json.load(open(os.path.join(fleet_dir, "fleet_journal.json")))
    assert len(j["records"]) == 18
    assert all(rec["state"] == "completed" for rec in j["records"])
    assert any(rec["redrives"] > 0 for rec in j["records"])
    # the merged fleet report carries the failover counters + the
    # summed serve histograms from the surviving replicas
    from torchpruner_tpu.obs.report import load_run

    rep = load_run(os.path.join(fleet_dir, "obs"))
    m = rep["metrics"]
    assert m.get("fleet_failover_total", 0) >= 1
    assert m.get("fleet_redrive_total", 0) >= 1
    assert m.get("fleet_completed_total") == 18
    assert m.get("serve_ttft_seconds_count", 0) > 0
    # distributed tracing: EVERY completed request's waterfall is
    # contiguous across router + replica pids, the redriven one shows
    # both attempts, and the TTFT stage budget reconciles within 10%
    assert s["traces_assembled"] == 18 and s["traces_cross_process"] == 18
    assert s["traces_redriven_cross_process"] >= 1
    assert abs(s["ttft_recon_pct"]) <= 10
    assert m.get("serve_queue_wait_seconds_count", 0) >= 18
    assert m.get("fleet_dispatch_wait_seconds_count", 0) >= 18
    trace = json.load(open(os.path.join(fleet_dir, "obs", "trace.json")))
    req = [e for e in trace["traceEvents"]
           if e.get("cat") == "reqtrace" and e.get("ph") in ("X", "i")]
    pids = {e["pid"] for e in req}
    assert 0 in pids and len(pids) >= 2  # router + >=1 replica
    # the budget + exemplar waterfalls render in `obs report`
    from torchpruner_tpu.obs.report import format_report

    md = format_report(rep)
    assert "latency budget:" in md and "exemplar waterfalls" in md

"""Distributed request tracing (obs/reqtrace + trace_export assembly):
stage recording and exemplar policy, cross-process B/E pairing with
duplicate span names, synthetic closing of a SIGKILLed replica's torn
spans, monotonic per-tid timestamps after clock-offset alignment,
per-request waterfall assembly, the TTFT/E2E latency budget, and the
fleet wiring (journaled trace ids, dispatch-wait/queue-wait
histograms)."""

import json
import os

import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.obs import reqtrace
from torchpruner_tpu.obs import trace_export as te


@pytest.fixture
def session(tmp_path):
    s = obs.configure(str(tmp_path / "obs"), process_index=0,
                      annotate=False, watch_compiles=False)
    yield s
    obs.shutdown()
    reqtrace.reset()


def _events(session):
    path = os.path.join(session.obs_dir, "events.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- recorder ----------------------------------------------------------------


def test_eager_mode_emits_every_stage(session):
    reqtrace.reset(sample_every=1)
    tid = reqtrace.mint_trace_id("r00000")
    reqtrace.stage(tid, "accept", rid="r00000")
    reqtrace.stage(tid, "prefill", dur_s=0.01)
    reqtrace.finish(tid, outcome="complete", e2e_s=0.02)
    evs = _events(session)
    stages = [e for e in evs if e.get("event") == "req_stage"]
    assert [e["stage"] for e in stages] == ["accept", "prefill"]
    assert all(e["trace"] == tid for e in stages)
    summaries = [e for e in evs if e.get("event") == "req_trace"]
    assert summaries[0]["outcome"] == "complete"
    # aggregates recorded regardless of exemplar policy
    m = session.metrics.get("reqtrace_stage_prefill_seconds")
    assert m.count == 1 and m.sum == pytest.approx(0.01)
    assert obs.counter_value("reqtrace_exemplars_total") == 1


def test_sampled_mode_keeps_slowest_k_plus_hash_sample(session):
    reqtrace.reset(sample_every=10**9, slowest_k=2, window=6)
    e2es = [0.01, 0.5, 0.02, 0.9, 0.03, 0.04]
    tids = []
    for i, e2e in enumerate(e2es):
        tid = f"t{i:02d}"
        tids.append(tid)
        reqtrace.stage(tid, "prefill", dur_s=0.001)
        reqtrace.finish(tid, outcome="complete", e2e_s=e2e)
    # window of 6 closed: exactly the 2 slowest flushed full detail
    flushed = {e["trace"] for e in _events(session)
               if e.get("event") == "req_trace"}
    assert flushed == {"t01", "t03"}
    assert obs.counter_value("reqtrace_agg_only_total") == 4
    # every request still contributed to the aggregate histogram
    assert session.metrics.get(
        "reqtrace_stage_prefill_seconds").count == 6


def test_hash_sampling_is_deterministic_across_processes():
    # the 1-in-N decision depends only on the trace id, so a replica
    # and the router flush the SAME subset without coordination
    ids = [f"tr-r{i:05d}-abc" for i in range(200)]
    a = [reqtrace.is_sampled(t, 8) for t in ids]
    b = [reqtrace.is_sampled(t, 8) for t in ids]
    assert a == b
    assert 0 < sum(a) < len(ids)
    assert all(reqtrace.is_sampled(t, 1) for t in ids[:5])


def test_session_close_flushes_partial_window(tmp_path):
    s = obs.configure(str(tmp_path / "obs"), process_index=0,
                      annotate=False, watch_compiles=False)
    reqtrace.reset(sample_every=10**9, slowest_k=8, window=1000)
    reqtrace.stage("tx", "prefill", dur_s=0.01)
    reqtrace.finish("tx", outcome="complete", e2e_s=0.3)
    obs.shutdown()  # close flushes the partial slowest-K window
    with open(tmp_path / "obs" / "events.jsonl") as f:
        evs = [json.loads(line) for line in f if line.strip()]
    assert any(e.get("event") == "req_trace" and e["trace"] == "tx"
               and e.get("exemplar") == "slow" for e in evs)
    reqtrace.reset()


# -- latency budget ----------------------------------------------------------


def _metrics_with_stages(session):
    reqtrace.reset(sample_every=1)
    for _ in range(4):
        reqtrace.stage(None, "replica_queue", dur_s=0.003)
        reqtrace.stage(None, "admission", dur_s=0.001)
        reqtrace.stage(None, "prefill", dur_s=0.006)
        reqtrace.stage(None, "decode", dur_s=0.05)
        reqtrace.stage(None, "journal_flush", dur_s=0.002)
        reqtrace.stage(None, "dispatch_wait", dur_s=0.004)
        obs.observe("serve_ttft_seconds", 0.010)
        obs.observe("reqtrace_e2e_seconds", 0.080)
    return session.metrics.snapshot()


def test_latency_budget_reconciles_and_attributes(session):
    b = reqtrace.latency_budget(_metrics_with_stages(session))
    ttft = b["ttft"]
    # budget = 3+1+6 = 10 ms vs measured 10 ms -> recon ~0
    assert ttft["measured_mean_ms"] == pytest.approx(10.0)
    assert ttft["recon_pct"] == pytest.approx(0.0, abs=1e-6)
    pct = {r["stage"]: r["pct"] for r in ttft["stages"]}
    assert pct["prefill"] == pytest.approx(60.0)
    assert pct["replica_queue"] == pytest.approx(30.0)
    e2e = b["e2e"]
    # stage sum 66 ms vs e2e 80 ms -> 17.5% unattributed (transport)
    assert e2e["unattributed_pct"] == pytest.approx(17.5)
    reqtrace.install_budget_gauges(b)
    snap = session.metrics.snapshot()
    assert snap["ttft_stage_prefill_pct"] == pytest.approx(60.0)
    assert abs(snap["reqtrace_ttft_recon_pct"]) < 1e-6


def test_latency_budget_none_without_stage_data():
    assert reqtrace.latency_budget({"steps_total": 5}) is None


# -- trace_export: cross-process span assembly -------------------------------


def _span_stream(pid_os, spans):
    """Events for one process: obs_init + the given (name, tid, t0, t1)
    spans (t1 None = torn: SIGKILL before span_end)."""
    evs = [{"event": "obs_init", "ts": 0.0, "pid": pid_os,
            "process_index": 0}]
    for i, (name, sid_tid, t0, t1) in enumerate(spans):
        sid = f"s{pid_os}{i:05d}"
        evs.append({"event": "span_begin", "span": sid, "name": name,
                    "parent": None, "depth": 0, "ts": t0,
                    "tid": sid_tid})
        if t1 is not None:
            evs.append({"event": "span_end", "span": sid, "name": name,
                        "parent": None, "depth": 0, "ts": t1,
                        "tid": sid_tid, "dur_s": t1 - t0})
    return evs


def test_merged_streams_pair_duplicate_names_within_pid():
    # BOTH processes run a span named "serve_prefill" — pairing must
    # stay within each pid (span ids never cross processes)
    streams = [
        {"name": "router", "pid": 0, "shift_s": 0.0,
         "events": _span_stream(100, [("serve_prefill", 7, 10.0, 11.0)])},
        {"name": "replica0", "pid": 1, "shift_s": 0.0,
         "events": _span_stream(200, [("serve_prefill", 9, 10.5, 12.0)])},
    ]
    out = te.merged_trace_events(streams)
    be = [(e["ph"], e["pid"]) for e in out
          if e.get("name") == "serve_prefill"]
    assert be.count(("B", 0)) == 1 and be.count(("E", 0)) == 1
    assert be.count(("B", 1)) == 1 and be.count(("E", 1)) == 1
    # process rows are named
    meta = [e for e in out if e.get("ph") == "M"
            and e["name"] == "process_name"]
    assert {m["args"]["name"].split(" (")[0] for m in meta} \
        >= {"router", "replica0"}


def test_torn_replica_spans_closed_synthetically():
    # the kill -9'd replica's stream ends mid-span: the B still gets a
    # synthetic E so the trace opens in Perfetto
    streams = [{"name": "replica0", "pid": 3, "shift_s": 0.0,
                "events": _span_stream(
                    300, [("decode", 5, 10.0, None)])}]
    out = te.merged_trace_events(streams)
    es = [e for e in out if e["ph"] == "E" and e["name"] == "decode"]
    assert len(es) == 1 and es[0]["args"].get("torn") is True
    bs = [e for e in out if e["ph"] == "B"]
    assert es[0]["ts"] >= bs[0]["ts"]


def test_clock_shift_applied_and_timestamps_monotonic_per_tid():
    # replica clock runs 2 s AHEAD; shift -2 aligns it.  Feed spans
    # whose RAW order would go backwards after alignment and assert the
    # per-(pid, tid) clamp keeps each track monotonic.
    streams = [
        {"name": "replica0", "pid": 1, "shift_s": -2.0,
         "events": _span_stream(200, [
             ("a", 4, 12.0, 12.5),     # aligned: 10.0..10.5
             ("b", 4, 11.9, 12.1),     # aligned: 9.9..10.1 (regresses)
         ])},
    ]
    out = te.merged_trace_events(streams)
    slices = [e for e in out if e["ph"] in ("B", "E")]
    assert slices[0]["ts"] == pytest.approx(10.0 * 1e6)
    per_tid = {}
    for e in slices:
        key = (e["pid"], e["tid"])
        assert e["ts"] >= per_tid.get(key, 0.0)
        per_tid[key] = e["ts"]


# -- trace_export: per-request waterfall assembly ----------------------------


def _req_streams():
    router = [
        {"event": "obs_init", "ts": 0.0, "pid": 1, "process_index": 0},
        {"event": "req_stage", "trace": "trA", "stage": "accept",
         "ts": 10.0, "dur_s": 0.0, "rid": "r00000"},
        {"event": "req_stage", "trace": "trA", "stage": "journal_flush",
         "ts": 10.0, "dur_s": 0.002},
        {"event": "req_stage", "trace": "trA", "stage": "dispatch_wait",
         "ts": 10.01, "dur_s": 0.004, "attempt": 1},
        {"event": "req_stage", "trace": "trA", "stage": "redrive",
         "ts": 10.5, "dur_s": 0.0},
        {"event": "req_stage", "trace": "trA", "stage": "dispatch_wait",
         "ts": 10.51, "dur_s": 0.001, "attempt": 2},
        {"event": "req_trace", "trace": "trA", "outcome": "complete",
         "ts": 11.2, "e2e_s": 1.2},
        # a second request that died with its replica: no terminal
        # summary anywhere
        {"event": "req_stage", "trace": "trB", "stage": "accept",
         "ts": 10.2, "dur_s": 0.0},
    ]
    # the replica clock is 0.25 s ahead (shift -0.25 aligns)
    replica = [
        {"event": "obs_init", "ts": 0.0, "pid": 2, "process_index": 0},
        {"event": "req_stage", "trace": "trA", "stage": "replica_queue",
         "ts": 10.30, "dur_s": 0.01},
        {"event": "req_stage", "trace": "trA", "stage": "prefill",
         "ts": 10.31, "dur_s": 0.02},
        {"event": "req_trace", "trace": "trA", "outcome": "complete",
         "ts": 10.9, "ttft_s": 0.05},
    ]
    return [
        {"name": "router", "pid": 0, "events": router, "shift_s": 0.0},
        {"name": "replica0", "pid": 1, "events": replica,
         "shift_s": -0.25},
    ]


def test_assemble_request_traces_cross_process():
    traces = te.assemble_request_traces(_req_streams())
    a = traces["trA"]
    assert a["outcome"] == "complete"
    assert a["e2e_s"] == pytest.approx(1.2)    # router summary wins
    assert a["ttft_s"] == pytest.approx(0.05)  # replica detail kept
    assert a["pids"] == [0, 1]
    assert a["attempts"] == 2 and a["redrive"] and not a["torn"]
    # stages sorted on the ALIGNED clock: the replica's prefill
    # (raw 10.31 -> aligned 10.06) lands between the dispatch attempts
    names = [s["stage"] for s in a["stages"]]
    assert names == ["accept", "journal_flush", "dispatch_wait",
                     "replica_queue", "prefill", "redrive",
                     "dispatch_wait"]
    assert traces["trB"]["torn"] and traces["trB"]["outcome"] is None


def test_waterfall_events_span_both_pids_on_one_tid():
    traces = te.assemble_request_traces(_req_streams())
    out = te.reqtrace_trace_events(traces)
    slices = [e for e in out if e["ph"] in ("X", "i")]
    tids = {e["args"]["trace"]: e["tid"] for e in slices}
    assert tids["trA"] >= te.REQTRACE_TID_BASE
    a_rows = [e for e in slices if e["args"]["trace"] == "trA"]
    assert {e["pid"] for e in a_rows} == {0, 1}  # the waterfall hops
    assert len({e["tid"] for e in a_rows}) == 1  # ...on ONE row
    # instant stages are markers, timed ones are slices
    phs = {e["name"]: e["ph"] for e in a_rows}
    assert phs["accept"] == "i" and phs["prefill"] == "X"


def test_fleet_report_collect_and_write(tmp_path):
    """fleet.report end to end on a synthetic layout: clock_offset
    events drive the replica shift; write_fleet_trace produces ONE
    trace.json holding spans + waterfalls from both processes."""
    from torchpruner_tpu.fleet import report as fr

    obs_dir = tmp_path / "obs"
    rep_dir = obs_dir / "replica0"
    rep_dir.mkdir(parents=True)
    streams = _req_streams()
    router_events = list(streams[0]["events"])
    router_events.insert(1, {"event": "clock_offset", "ts": 9.0,
                             "replica": "replica0", "offset_s": 0.25,
                             "rtt_s": 0.001})
    with open(obs_dir / "events.jsonl", "w") as f:
        for ev in router_events:
            f.write(json.dumps(ev) + "\n")
    with open(rep_dir / "events.jsonl", "w") as f:
        for ev in streams[1]["events"]:
            f.write(json.dumps(ev) + "\n")

    got = fr.collect_streams(str(obs_dir))
    assert [s["name"] for s in got] == ["router", "replica0"]
    assert got[1]["shift_s"] == pytest.approx(-0.25)

    traces = fr.assemble_fleet_traces(str(obs_dir))
    tsum = fr.trace_summary(traces)
    assert tsum["assembled"] == 2 and tsum["completed"] == 1
    assert tsum["cross_process"] == 1
    assert tsum["redriven_cross_process"] == 1 and tsum["torn"] == 1
    ex = fr.slowest_exemplars(traces, k=3)
    assert ex[0]["trace"] == "trA" and ex[0]["redrive"]
    assert ex[0]["stages"][0]["at_ms"] == 0.0

    path = fr.write_fleet_trace(str(obs_dir))
    trace = json.load(open(path))
    req = [e for e in trace["traceEvents"]
           if e.get("cat") == "reqtrace" and e["ph"] in ("X", "i")]
    assert {e["pid"] for e in req} == {0, 1}


# -- fleet wiring ------------------------------------------------------------


def test_plane_mints_and_journals_trace_ids(tmp_path, session):
    from torchpruner_tpu.fleet import RequestPlane

    reqtrace.reset(sample_every=1)
    journal = str(tmp_path / "j.json")
    plane = RequestPlane(journal)
    rec = plane.accept({"prompt_ids": [1], "max_new": 2},
                       deadline_s=30.0)
    assert rec.trace_id and rec.trace_id.startswith("tr-r00000")
    raw = json.load(open(journal))
    assert raw["records"][0]["trace_id"] == rec.trace_id
    # accept + journal_flush stages landed in the event stream
    stages = [e["stage"] for e in _events(session)
              if e.get("event") == "req_stage"
              and e.get("trace") == rec.trace_id]
    assert stages == ["accept", "journal_flush"]
    assert session.metrics.get(
        "reqtrace_stage_journal_flush_seconds").count == 1
    # a reloaded journal keeps the SAME trace id (one waterfall across
    # a router restart)
    revived = RequestPlane.load(journal)
    assert revived.get(rec.rid).trace_id == rec.trace_id
    # completion observes router-side e2e + emits the summary
    plane.checkout()
    plane.complete(rec.rid, [5, 6], "replica1")
    assert session.metrics.get("reqtrace_e2e_seconds").count == 1
    summaries = [e for e in _events(session)
                 if e.get("event") == "req_trace"]
    assert summaries and summaries[-1]["outcome"] == "complete"
    assert summaries[-1]["replica"] == "replica1"


def test_router_records_dispatch_wait_and_propagates_trace(session):
    from torchpruner_tpu.fleet import FleetRouter, RequestPlane
    from torchpruner_tpu.fleet.router import RouterPolicy

    reqtrace.reset(sample_every=1)
    seen_payloads = []

    class Rep:
        name = "replica0"

        def healthz(self, timeout=None):
            return {"live": True, "ready": True, "state": "ready",
                    "clock_offset_s": 0.002, "rtt_s": 0.0005}

        def stats(self, timeout=None):
            return {}

        def generate(self, payload, timeout=None):
            seen_payloads.append(payload)
            return {"state": "done", "tokens": [1, 2]}

    plane = RequestPlane()
    router = FleetRouter(plane, [Rep()], policy=RouterPolicy(
        max_attempts=3, attempt_timeout_s=5.0, default_deadline_s=10.0,
        health_every_s=0.01))
    rec = router.submit({"prompt_ids": [3], "max_new": 2})
    router.run_until_drained(poll_s=0.002, timeout_s=20.0)
    router.close()
    assert rec.state == "completed"
    # the dispatch payload carried the trace id; the JOURNALED payload
    # did not (redrive/verify replay the original)
    assert seen_payloads[0]["trace_id"] == rec.trace_id
    assert "trace_id" not in rec.payload
    assert session.metrics.get(
        "fleet_dispatch_wait_seconds").count >= 1
    # the health probe's offset sample landed as a clock_offset event
    offs = [e for e in _events(session)
            if e.get("event") == "clock_offset"]
    assert offs and offs[0]["replica"] == "replica0"
    assert offs[0]["offset_s"] == pytest.approx(0.002)


def test_router_shed_records_shed_stage(session):
    from torchpruner_tpu.fleet import FleetRouter, RequestPlane
    from torchpruner_tpu.fleet.router import RouterPolicy

    reqtrace.reset(sample_every=1)
    plane = RequestPlane()
    router = FleetRouter(plane, [], policy=RouterPolicy())
    assert router.submit({"prompt_ids": [1], "max_new": 1}) is None
    router.close()
    evs = _events(session)
    sheds = [e for e in evs if e.get("event") == "req_stage"
             and e.get("stage") == "shed"]
    assert sheds and sheds[0]["reason"] == "no_live_replica"


# -- serve wiring ------------------------------------------------------------


def test_scheduler_records_queue_age_at_admission(session):
    import time

    from torchpruner_tpu.serve.allocator import KVCacheAllocator
    from torchpruner_tpu.serve.request import Request
    from torchpruner_tpu.serve.scheduler import Scheduler

    reqtrace.reset(sample_every=1)
    sched = Scheduler(KVCacheAllocator(2, 64))
    req = Request(prompt_ids=[1, 2], max_new=4, trace_id="tr-x")
    # backdate the arrival 50 ms: the queue age must be visible AT
    # ADMISSION, before any token was produced
    sched.submit(req, arrival_s=time.perf_counter() - 0.05)
    admitted = sched.admit()
    assert admitted == [req] and req.admitted_s is not None
    h = session.metrics.get("serve_queue_wait_seconds")
    assert h.count == 1 and h.sum >= 0.05
    live = sched.queue_wait_ms()
    assert live["p50"] >= 50.0 and live["p99"] >= live["p50"]
    # the traced request got its replica_queue stage
    stages = [e for e in _events(session)
              if e.get("event") == "req_stage"]
    assert stages and stages[0]["stage"] == "replica_queue"
    assert stages[0]["trace"] == "tr-x"


def test_request_from_dict_parses_trace_id():
    from torchpruner_tpu.serve.request import request_from_dict

    req = request_from_dict({"prompt_ids": [1, 2], "max_new": 3,
                             "trace_id": "tr-abc"})
    assert req.trace_id == "tr-abc"
    assert request_from_dict(
        {"prompt_ids": [1], "max_new": 1}).trace_id is None

"""Distribution layer tests on the 8-device virtual CPU mesh: distributed
scoring must equal single-device scoring; DP×FSDP training must run, match
single-device training, and survive prune→reshard→recompile."""

import jax
import numpy as np
import jax.numpy as jnp
import optax
import pytest

from torchpruner_tpu.attributions import (
    ShapleyAttributionMetric,
    TaylorAttributionMetric,
    WeightNormAttributionMetric,
)
from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.data import synthetic_dataset
from torchpruner_tpu.parallel import (
    DistributedScorer,
    ShardedTrainer,
    make_mesh,
    mesh_axes,
    shard_params,
)
from torchpruner_tpu.parallel.sharding import fsdp_spec
from torchpruner_tpu.train import Trainer, train_epoch
from torchpruner_tpu.utils.losses import cross_entropy_loss
from torchpruner_tpu.utils.reductions import mean_plus_2std


def model_8():
    return SegmentedModel(
        (L.Dense("fc1", 64), L.Activation("r1", "relu"),
         L.Dense("fc2", 32), L.Activation("r2", "relu"),
         L.Dense("out", 4)),
        (16,),
    )


def batches_8(n=128, bs=32, seed=0):
    return synthetic_dataset((16,), 4, n, seed=seed).batches(bs)


def test_make_mesh_shapes():
    assert jax.device_count() == 8
    m = make_mesh()
    assert mesh_axes(m) == {"data": 8}
    m2 = make_mesh({"data": 2, "model": 4})
    assert mesh_axes(m2) == {"data": 2, "model": 4}
    m3 = make_mesh({"data": -1, "model": 2})
    assert mesh_axes(m3) == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh({"data": 3})


def test_hybrid_mesh_single_slice_fallback():
    """Without multi-slice topology (CPU fake devices), make_hybrid_mesh
    must degrade to a plain mesh with the same named axes, so hybrid-mesh
    code runs unchanged on one slice."""
    from torchpruner_tpu.parallel import make_hybrid_mesh

    mesh = make_hybrid_mesh({"model": 4}, {"data": 2})
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 2, "model": 4,
    }
    single = make_hybrid_mesh({"model": 8}, {"data": 1})
    assert dict(zip(single.axis_names, single.devices.shape)) == {
        "data": 1, "model": 8,
    }
    with pytest.raises(ValueError):  # device count must still match
        make_hybrid_mesh({"model": 4}, {"data": 4})
    # a ShardedTrainer runs over the hybrid-constructed mesh unchanged
    t = ShardedTrainer.create(model_8(), optax.sgd(0.05),
                              cross_entropy_loss, mesh,
                              seed=0, min_shard_size=0)
    x, y = next(iter(batches_8(n=16, bs=16)))
    assert np.isfinite(float(t.step(x, y)))


def test_initialize_distributed_noop_without_config():
    from torchpruner_tpu.parallel import initialize_distributed

    assert initialize_distributed() is False  # no coordinator configured


def test_fsdp_spec_rules():
    mesh = make_mesh({"data": 2, "model": 4})
    assert fsdp_spec((128, 64), mesh, min_size=0) == jax.sharding.PartitionSpec("model", None)
    assert fsdp_spec((63, 61), mesh, min_size=0) == jax.sharding.PartitionSpec()  # indivisible
    assert fsdp_spec((8, 8), mesh, min_size=2**14) == jax.sharding.PartitionSpec()  # too small


@pytest.mark.parametrize("reduction", ["mean", "sum", "none", "mean+2std"])
def test_distributed_taylor_matches_single_device(reduction):
    model = model_8()
    params, state = init_model(model, seed=0)
    data = batches_8()
    red = mean_plus_2std if reduction == "mean+2std" else reduction
    single = TaylorAttributionMetric(model, params, data,
                                     cross_entropy_loss, reduction=red)
    expected = single.run("fc1", find_best_evaluation_layer=True)
    mesh = make_mesh({"data": 8})
    dist = DistributedScorer(
        TaylorAttributionMetric(model, params, data, cross_entropy_loss,
                                reduction=red),
        mesh,
    )
    got = dist.run("fc1", find_best_evaluation_layer=True)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=1e-6)


def test_distributed_shapley_matches_single_device():
    model = model_8()
    params, state = init_model(model, seed=0)
    data = batches_8()
    kw = dict(sv_samples=3, seed=11)
    expected = ShapleyAttributionMetric(
        model, params, data, cross_entropy_loss, **kw
    ).run("fc1")
    mesh = make_mesh({"data": 4, "model": 2})
    got = DistributedScorer(
        ShapleyAttributionMetric(model, params, data, cross_entropy_loss,
                                 **kw),
        mesh,
    ).run("fc1")
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=1e-6)


def test_distributed_weight_only_falls_back():
    model = model_8()
    params, _ = init_model(model, seed=0)
    mesh = make_mesh()
    m = WeightNormAttributionMetric(model, params, batches_8(),
                                    cross_entropy_loss)
    got = DistributedScorer(m, mesh).run("fc1")
    np.testing.assert_allclose(got, m.run("fc1"))


def test_indivisible_batch_rejected():
    model = model_8()
    params, _ = init_model(model, seed=0)
    mesh = make_mesh({"data": 8})
    data = synthetic_dataset((16,), 4, 30, seed=0).batches(30)  # 30 % 8 != 0
    m = TaylorAttributionMetric(model, params, data, cross_entropy_loss)
    with pytest.raises(ValueError, match="not divisible"):
        DistributedScorer(m, mesh).run("fc1")


def test_sharded_trainer_matches_single_device():
    """DP×FSDP SPMD training must track the single-device trajectory."""
    mesh = make_mesh({"data": 2, "model": 4})
    tx = optax.sgd(0.05)
    t_single = Trainer.create(model_8(), tx, cross_entropy_loss, seed=0)
    t_shard = ShardedTrainer.create(model_8(), tx, cross_entropy_loss, mesh,
                                    seed=0, min_shard_size=0)
    data = batches_8(n=64, bs=32)
    for x, y in data:
        l1 = t_single.step(x, y)
        l2 = t_shard.step(x, y)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-4)
    w1 = np.asarray(t_single.params["fc1"]["w"])
    w2 = np.asarray(t_shard.params["fc1"]["w"])
    np.testing.assert_allclose(w1, w2, rtol=1e-3, atol=1e-5)


def test_sharded_trainer_evaluate_matches_single_device():
    """Data-sharded evaluation — including batches that do NOT divide the
    data axis, which are padded to the next multiple and masked — must
    equal the single-device evaluation exactly."""
    mesh = make_mesh({"data": 2, "model": 4})
    tx = optax.sgd(0.05)
    t1 = Trainer.create(model_8(), tx, cross_entropy_loss, seed=0)
    t8 = ShardedTrainer.create(model_8(), tx, cross_entropy_loss, mesh,
                               seed=0, min_shard_size=0)
    # 15 % 2 != 0: every batch is ragged wrt the 2-way data axis, and the
    # final batch (5) is ragged wrt the batch size too
    data = synthetic_dataset((16,), 4, 50, seed=3).batches(15)
    l1, a1 = t1.evaluate(data)
    l8, a8 = t8.evaluate(data)
    np.testing.assert_allclose(l1, l8, rtol=1e-5)
    assert a1 == a8
    # dividing batches agree with ragged batches over the same examples
    l8b, a8b = t8.evaluate(synthetic_dataset((16,), 4, 50, seed=3).batches(25))
    np.testing.assert_allclose(l8, l8b, rtol=1e-5)
    assert a8 == a8b


def test_sharded_trainer_evaluate_pads_with_zeros_not_poison():
    """Regression (chaos runs): the ragged-batch pad rows must come from
    ZEROS, not a repeat of the final example — the validity mask cannot
    scrub a non-finite padded row (``inf * 0 = nan``), so a poisoned
    final example must count exactly once, like on a single device.

    An identity model (empty layer tuple) makes the poison exact: a
    ``-inf`` logit at the true class yields a deterministic ``+inf``
    loss for that one real row.  Zero padding keeps the masked total at
    ``inf`` (matching the single-device sum); the old repeat-padding
    replicated the row into the masked pad slots and degraded the total
    to ``nan`` via ``inf * 0``."""
    model = SegmentedModel((), (4,))
    mesh = make_mesh({"data": 2, "model": 4})
    tx = optax.sgd(0.05)
    t1 = Trainer.create(model, tx, cross_entropy_loss, seed=0)
    t8 = ShardedTrainer.create(model, tx, cross_entropy_loss, mesh,
                               seed=0, min_shard_size=0)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(45, 4)).astype(np.float32)
    y = (np.arange(45) % 4).astype(np.int32)
    # poisoned FINAL example of a ragged final batch (15 % 2 != 0: every
    # batch pads, and the last example is the old pad source)
    x[-1] = 0.0
    x[-1, 1] = -np.inf
    y[-1] = 1
    batches = [(x[i:i + 15], y[i:i + 15]) for i in range(0, 45, 15)]
    l1, a1 = t1.evaluate(batches)
    l8, a8 = t8.evaluate(batches)
    assert np.isinf(l1) and np.isinf(l8), (l1, l8)
    assert a1 == a8


def test_sharded_trainer_gradient_accumulation_matches():
    """SPMD gradient accumulation (scanned microbatches, each still
    sharded over the data axis) must match the unaccumulated SPMD step."""
    mesh = make_mesh({"data": 2, "model": 4})
    tx = optax.sgd(0.05, momentum=0.9)
    t1 = ShardedTrainer.create(model_8(), tx, cross_entropy_loss, mesh,
                               seed=0, min_shard_size=0)
    t4 = ShardedTrainer.create(model_8(), tx, cross_entropy_loss, mesh,
                               seed=0, min_shard_size=0, accum_steps=4)
    for x, y in batches_8(n=64, bs=32):
        l1 = t1.step(x, y)
        l4 = t4.step(x, y)
        np.testing.assert_allclose(float(l1), float(l4), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(t1.params["fc1"]["w"]), np.asarray(t4.params["fc1"]["w"]),
        rtol=1e-3, atol=1e-5,
    )


def test_sharded_trainer_prune_reshard_recompile():
    mesh = make_mesh({"data": 2, "model": 4})
    t = ShardedTrainer.create(model_8(), optax.adam(1e-3),
                              cross_entropy_loss, mesh, seed=0,
                              min_shard_size=0)
    data = batches_8(n=64, bs=32)
    for x, y in data:
        t.step(x, y)
    res = prune(t.model, t.params, "fc1", list(range(0, 64, 2)),
                state=t.state, opt_state=t.opt_state)
    t2 = t.rebuild(res.model, res.params, res.state, res.opt_state)
    assert t2.model.layer("fc1").features == 32
    for x, y in data:
        l = t2.step(x, y)
    assert np.isfinite(float(l))
    loss, acc = t2.evaluate(data)
    assert np.isfinite(loss)


def test_shard_params_layouts():
    mesh = make_mesh({"data": 2, "model": 4})
    model = model_8()
    params, _ = init_model(model, seed=0)
    placed, shardings = shard_params(params, mesh, min_size=0)
    # fc1 w (16,64): 64 divisible by 4 -> sharded on model axis
    s = placed["fc1"]["w"].sharding
    assert s.spec == jax.sharding.PartitionSpec(None, "model")


def test_distributed_scoring_honours_compute_dtype():
    """DistributedScorer must produce the same rows as metric.run() under
    bf16 scoring — the cast happens on both paths."""
    import jax.numpy as jnp

    from torchpruner_tpu.attributions import TaylorAttributionMetric
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.data import load_dataset
    from torchpruner_tpu.models import digits_fc
    from torchpruner_tpu.parallel import DistributedScorer, make_mesh
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    model = digits_fc()
    params, state = init_model(model, seed=0)
    data = load_dataset("digits_flat", "val").batches(
        40, drop_remainder=True
    )
    metric = TaylorAttributionMetric(
        model, params, data, cross_entropy_loss, state=state,
        compute_dtype=jnp.bfloat16,
    )
    local = metric.run("fc2")
    dist = DistributedScorer(metric, make_mesh({"data": 8})).run("fc2")
    np.testing.assert_allclose(local, dist, rtol=2e-5, atol=1e-7)


def test_zero_style_fsdp_over_full_mesh_trains():
    """model_axis as a tuple shards params over BOTH mesh axes (ZeRO-3
    style): per-chip param bytes drop by the full device count while
    training still converges."""
    import jax.numpy as jnp
    import optax

    from torchpruner_tpu.models.mlp import fc_net
    from torchpruner_tpu.parallel import ShardedTrainer, make_mesh
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    mesh = make_mesh({"data": 2, "model": 4})
    t = ShardedTrainer.create(
        fc_net(16, hidden=(64, 64), n_classes=4), optax.adam(1e-2),
        cross_entropy_loss, mesh, seed=0, min_shard_size=0,
        model_axis=("data", "model"),
    )
    # the big weights shard over 8 devices, not 4
    from jax.sharding import PartitionSpec as P

    w = t.params["fc1"]["w"]
    assert w.sharding.spec in (P(("data", "model"), None),
                               P(None, ("data", "model"))), w.sharding.spec
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(0), (16, 16)))
    y = np.asarray(np.arange(16) % 4, np.int32)
    l0 = float(t.step(x, y))
    l1 = float(t.step(x, y))
    assert np.isfinite(l0) and l1 < l0


def test_tuple_axis_rejected_for_tensor_parallelism():
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.parallel.sharding import tp_sharding

    mesh = make_mesh({"data": 2, "model": 4})
    model = llama_tiny(depth=1)
    params, _ = init_model(model, seed=0)
    with pytest.raises(ValueError, match="single mesh axis"):
        tp_sharding(model, params, mesh, axis=("data", "model"))


def test_memory_budget_rounds_shards_up():
    from jax.sharding import PartitionSpec as P

    from torchpruner_tpu.parallel.memory import _sharded_bytes

    # dim 10 over 8 chips: ceil(10/8)=2 rows per chip, never 1
    assert _sharded_bytes((10, 4), np.float32, P("model", None),
                          {"model": 8}) == 2 * 4 * 4
    assert _sharded_bytes((16, 4), np.float32, P(("data", "model"), None),
                          {"data": 2, "model": 4}) == 2 * 4 * 4

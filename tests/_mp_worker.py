"""Worker for the two-process distributed test (not collected by pytest).

Run as ``python _mp_worker.py <process_id> <num_processes> <port>``.
Joins the multi-host runtime through the framework's own
``initialize_distributed``, builds a global data mesh, feeds this host's
``Dataset.host_shard`` slice through ``ShardedTrainer`` (whose
``shard_batch`` assembles global batches from per-host locals), and
prints one JSON line with the loss trajectory and a parameter checksum.
"""

import json
import sys

import jax

# in-process platform selection: with the experimental TPU plugin
# installed the JAX_PLATFORMS env var alone does not defeat plugin
# discovery (see tests/conftest.py)
jax.config.update("jax_platforms", "cpu")

from torchpruner_tpu.parallel.mesh import initialize_distributed, make_mesh


def main() -> None:
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    assert initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=n,
        process_id=pid,
    ), "initialize_distributed must report distributed mode"

    import numpy as np
    import optax

    from torchpruner_tpu.data import synthetic_dataset
    from torchpruner_tpu.models.mlp import fc_net
    from torchpruner_tpu.parallel.train import ShardedTrainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    mesh = make_mesh({"data": jax.device_count()})
    trainer = ShardedTrainer.create(
        fc_net(16, hidden=(32, 32)), optax.sgd(0.05), cross_entropy_loss,
        mesh, seed=0, min_shard_size=0,
    )
    local = synthetic_dataset((16,), 4, 64, seed=0).host_shard()
    losses = [
        float(trainer.step(x, y))
        for x, y in local.iter_batches(16, drop_remainder=True)
    ]
    # ragged local batches (15,15,2): the padded+masked multiprocess
    # evaluation path must count exactly the real examples
    eval_loss, eval_acc = trainer.evaluate(local.batches(15))
    w = np.asarray(jax.device_get(trainer.params["fc1"]["w"]))
    print(json.dumps({
        "pid": pid,
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "losses": losses,
        "eval_loss": eval_loss,
        "eval_acc": eval_acc,
        "w_abs_sum": float(np.abs(w).sum()),
    }), flush=True)


if __name__ == "__main__":
    main()

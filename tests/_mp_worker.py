"""Worker for the two-process distributed test (not collected by pytest).

Run as ``python _mp_worker.py <process_id> <num_processes> <port>``.
Joins the multi-host runtime through the framework's own
``initialize_distributed``, builds a global data mesh, feeds this host's
``Dataset.host_shard`` slice through ``ShardedTrainer`` (whose
``shard_batch`` assembles global batches from per-host locals), and
prints one JSON line with the loss trajectory and a parameter checksum.
"""

import json
import sys

import jax

# in-process platform selection: with the experimental TPU plugin
# installed the JAX_PLATFORMS env var alone does not defeat plugin
# discovery (see tests/conftest.py)
jax.config.update("jax_platforms", "cpu")

from torchpruner_tpu.parallel.mesh import initialize_distributed, make_mesh


def main() -> None:
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "dp"
    assert initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=n,
        process_id=pid,
    ), "initialize_distributed must report distributed mode"
    if mode == "pp":
        run_pp(pid)
        return
    if mode == "obs":
        run_obs(pid, sys.argv[5])
        return

    import numpy as np
    import optax

    from torchpruner_tpu.data import synthetic_dataset
    from torchpruner_tpu.models.mlp import fc_net
    from torchpruner_tpu.parallel.train import ShardedTrainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    mesh = make_mesh({"data": jax.device_count()})
    trainer = ShardedTrainer.create(
        fc_net(16, hidden=(32, 32)), optax.sgd(0.05), cross_entropy_loss,
        mesh, seed=0, min_shard_size=0,
    )
    local = synthetic_dataset((16,), 4, 64, seed=0).host_shard()
    losses = [
        float(trainer.step(x, y))
        for x, y in local.iter_batches(16, drop_remainder=True)
    ]
    # ragged local batches (15,15,2): the padded+masked multiprocess
    # evaluation path must count exactly the real examples
    eval_loss, eval_acc = trainer.evaluate(local.batches(15))
    w = np.asarray(jax.device_get(trainer.params["fc1"]["w"]))
    print(json.dumps({
        "pid": pid,
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "losses": losses,
        "eval_loss": eval_loss,
        "eval_acc": eval_acc,
        "w_abs_sum": float(np.abs(w).sum()),
    }), flush=True)


def run_obs(pid: int, obs_dir: str) -> None:
    """Cross-host metric aggregation under a REAL multi-process runtime:
    each process runs an obs session over a shared obs_dir, records
    process-distinct counters/gauges/steps, and closes.  Only process 0
    may emit events.jsonl/metrics.prom/report.json, but EVERY process
    must leave a metrics.shard<i>.json, and process 0's merged export
    must carry the sum/max across hosts (asserted by the parent test)."""
    from torchpruner_tpu import obs

    session = obs.configure(obs_dir, annotate=False)
    assert session.process_index == jax.process_index()
    # barrier: the emitter's session INIT clears stale shards — no
    # process may reach close() (which writes its shard) until every
    # session is open, or a fast worker's shard could be swept.
    # Filesystem-based: the CPU gloo backend has no jit collectives
    # (multihost_utils.sync_global_devices raises INVALID_ARGUMENT)
    import os
    import time

    os.makedirs(obs_dir, exist_ok=True)
    open(os.path.join(obs_dir, f".ready.{pid}"), "w").close()
    deadline = time.time() + 60
    while time.time() < deadline and not all(
            os.path.exists(os.path.join(obs_dir, f".ready.{i}"))
            for i in range(jax.process_count())):
        time.sleep(0.05)
    with obs.span("work", host=pid):
        # distinct per-process totals so the merge is distinguishable
        # from any single shard: counter sums, gauge max/min
        obs.inc("mp_examples_total", 10 * (pid + 1))
        obs.gauge_set("mp_hbm_gauge", 100.0 * (pid + 1))
        for _ in range(pid + 1):
            obs.record_step(0.01, examples=8)
    # no explicit pre-close wait: the emitter's close() itself blocks
    # (bounded, aggregate.wait_for_peer_shards) until the peers' shard
    # writes land — the production path the parent test asserts on
    obs.shutdown()
    print(json.dumps({
        "pid": pid,
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "is_emitter": session.is_emitter,
    }), flush=True)


def run_pp(pid: int) -> None:
    """SPMD pipeline parallelism across processes: the pp mesh axis spans
    both hosts' devices, so the stage-to-stage ``ppermute`` crosses the
    process boundary — the collective-based PP path a pod runs (the
    device-pinned ``parallel.pipeline`` cannot do this)."""
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.parallel.pp_spmd import pp_spmd_train_step
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    mesh = Mesh(np.asarray(jax.devices()), ("pp",))
    rep = NamedSharding(mesh, P())

    def glob(a):
        return jax.make_array_from_process_local_data(rep, np.asarray(a))

    model = llama_tiny(depth=4)
    params, _ = init_model(model, seed=0)
    tokens = np.asarray(model.example_input(8, seed=0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(jax.tree_util.tree_map(np.asarray, params))
    params = jax.tree_util.tree_map(glob, params)
    opt_state = jax.tree_util.tree_map(glob, opt_state)
    toks = glob(tokens)

    step = pp_spmd_train_step(model, opt, lm_cross_entropy_loss,
                              mesh=mesh, n_microbatches=4)
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, toks)
        losses.append(float(loss))

    # the interleaved schedule (V=2 chunks/device) across the same
    # process boundary: the wrap-around ppermute edge S-1 -> 0 crosses
    # hosts in BOTH directions (depth 8 so depth % (4 stages * 2) == 0)
    model8 = llama_tiny(depth=8)
    params8, _ = init_model(model8, seed=0)
    opt_state8 = opt.init(jax.tree_util.tree_map(np.asarray, params8))
    params8 = jax.tree_util.tree_map(glob, params8)
    opt_state8 = jax.tree_util.tree_map(glob, opt_state8)
    step_i = pp_spmd_train_step(model8, opt, lm_cross_entropy_loss,
                                mesh=mesh, n_microbatches=4, interleave=2)
    losses_i = []
    for _ in range(2):
        params8, opt_state8, loss = step_i(params8, opt_state8, toks)
        losses_i.append(float(loss))
    print(json.dumps({
        "pid": pid,
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "losses": losses,
        "losses_interleaved": losses_i,
    }), flush=True)


if __name__ == "__main__":
    main()

"""Functional pruner tests — ports the reference's property-style pruner
suite (reference tests/test_pruner.py) to the functional API: shapes after
slicing, cascades through Flatten/Pool/BN, end-to-end forward after pruning,
dropout rescaling, and optimizer-state slicing (generalized to optax)."""

import jax
import numpy as np
import jax.numpy as jnp
import optax
import pytest

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.pruner import Pruner, prune, prune_by_scores
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.models import fmnist_convnet
from torchpruner_tpu.utils.losses import cross_entropy_loss


def small_mlp():
    return SegmentedModel(
        (L.Dense("fc1", 8), L.Activation("r1", "relu"), L.Dense("fc2", 4)),
        (6,),
    )


def test_out_prune_shapes():
    m = small_mlp()
    p, _ = init_model(m)
    res = prune(m, p, "fc1", [0, 3, 7])
    assert res.model.layer("fc1").features == 5
    assert res.params["fc1"]["w"].shape == (6, 5)
    assert res.params["fc1"]["b"].shape == (5,)
    assert res.params["fc2"]["w"].shape == (5, 4)  # consumer in-pruned
    # kept rows are the untouched ones
    np.testing.assert_array_equal(
        np.asarray(res.params["fc1"]["w"]),
        np.asarray(p["fc1"]["w"][:, [1, 2, 4, 5, 6]]),
    )


def test_duplicate_drop_indices_are_deduped():
    m = small_mlp()
    p, _ = init_model(m)
    res = prune(m, p, "fc1", [2, 2, 2])
    assert res.model.layer("fc1").features == 7
    assert res.params["fc1"]["w"].shape == (6, 7)


def test_pruned_forward_equals_submatrix_forward():
    """Pruning must be exactly equivalent to removing the units: the pruned
    model's output equals the original with those units forced to zero
    (ReLU net, so zeroing the unit kills its contribution)."""
    m = small_mlp()
    p, _ = init_model(m, seed=3)
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 6))
    drop = [1, 4]
    mask = jnp.ones(8).at[jnp.asarray(drop)].set(0.0)
    expected, _ = m.apply(p, x, unit_mask=("fc1", mask))
    res = prune(m, p, "fc1", drop)
    got, _ = res.model.apply(res.params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_conv_flatten_linear_cascade_forward():
    # reference test_pruner.py:83-92 (fan-out through flatten), with the
    # equivalence check instead of shape-only assertions
    m = SegmentedModel(
        (L.Conv("c", 3, (3, 3), padding="SAME"), L.Activation("r", "relu"),
         L.Flatten("f"), L.Dense("d", 5)),
        (4, 4, 2),
    )
    p, _ = init_model(m, seed=1)
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 4, 4, 2))
    mask = jnp.ones(3).at[1].set(0.0)
    expected, _ = m.apply(p, x, unit_mask=("c", mask))
    res = prune(m, p, "c", [1])
    assert res.params["c"]["w"].shape == (3, 3, 2, 2)
    assert res.params["d"]["w"].shape == (32, 5)  # (4*4*2 flattened)
    got, _ = res.model.apply(res.params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_conv_pool_flatten_cascade_forward():
    # reference test_pruner.py:94-107
    m = SegmentedModel(
        (L.Conv("c", 4, (3, 3), padding="SAME"), L.Activation("r", "relu"),
         L.Pool("p", "max", (2, 2)), L.Flatten("f"), L.Dense("d", 5)),
        (4, 4, 1),
    )
    p, _ = init_model(m, seed=1)
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 4, 4, 1))
    mask = jnp.ones(4).at[jnp.asarray([0, 2])].set(0.0)
    expected, _ = m.apply(p, x, unit_mask=("c", mask))
    res = prune(m, p, "c", [0, 2])
    got, _ = res.model.apply(res.params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_linear_bn_linear_cascade():
    # reference test_pruner.py:109-121 + BN-buffer resize (:153-158)
    m = SegmentedModel(
        (L.Dense("a", 8), L.BatchNorm("bn"), L.Activation("r", "relu"),
         L.Dense("b", 4)),
        (6,),
    )
    p, s = init_model(m, seed=0)
    res = prune(m, p, "a", [0, 7], state=s)
    assert res.params["bn"]["scale"].shape == (6,)
    assert res.state["bn"]["mean"].shape == (6,)
    assert res.state["bn"]["var"].shape == (6,)
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 6))
    out, _ = res.model.apply(res.params, x, state=res.state)
    assert out.shape == (3, 4)


def test_dropout_rescaled():
    # 0.5 -> 0.4 when 20% of units are pruned (reference test_pruner.py:162-176)
    m = SegmentedModel(
        (L.Dense("a", 10), L.Activation("r", "relu"), L.Dropout("dr", 0.5),
         L.Dense("b", 4)),
        (6,),
    )
    p, _ = init_model(m)
    res = prune(m, p, "a", [0, 1])
    assert res.model.layer("dr").rate == pytest.approx(0.4)


def test_fmnist_convnet_end_to_end_prune():
    m = fmnist_convnet()
    p, s = init_model(m, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 28, 28, 1))
    res = prune(m, p, "conv2", list(range(0, 64, 2)), state=s)
    out, _ = res.model.apply(res.params, x, state=res.state)
    assert out.shape == (2, 10)
    assert res.model.layer("conv2").features == 32
    assert res.params["fc1"]["w"].shape[0] == 7 * 7 * 32


@pytest.mark.parametrize("tx_name", ["sgd_momentum", "adam", "sgd_plain"])
def test_optimizer_state_sliced_and_training_continues(tx_name):
    """Train step -> prune -> train step must work, with momentum/Adam
    moments sliced consistently (reference test_pruner.py:180-228 is
    SGD-momentum only; optax generality per SURVEY.md §7)."""
    tx = {
        "sgd_momentum": optax.sgd(1e-2, momentum=0.9),
        "adam": optax.adam(1e-3),
        "sgd_plain": optax.sgd(1e-2),
    }[tx_name]
    m = small_mlp()
    p, _ = init_model(m, seed=0)
    opt_state = tx.init(p)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 6))
    y = jnp.zeros((16,), dtype=jnp.int32)

    def loss(p_):
        out, _ = m.apply(p_, x)
        return jnp.mean(cross_entropy_loss(out, y))

    g = jax.grad(loss)(p)
    up, opt_state = tx.update(g, opt_state, p)
    p = optax.apply_updates(p, up)

    res = prune(m, p, "fc1", [0, 5], opt_state=opt_state)
    m2, p2, opt_state2 = res.model, res.params, res.opt_state

    # every params-shaped leaf of the optimizer state must match new shapes
    flat_p = jax.tree_util.tree_leaves(p2)
    for leaf in jax.tree_util.tree_leaves(opt_state2):
        if hasattr(leaf, "shape") and leaf.ndim >= 1:
            assert any(leaf.shape == q.shape for q in flat_p), leaf.shape

    def loss2(p_):
        out, _ = m2.apply(p_, x)
        return jnp.mean(cross_entropy_loss(out, y))

    g2 = jax.grad(loss2)(p2)
    up2, _ = tx.update(g2, opt_state2, p2)
    p3 = optax.apply_updates(p2, up2)
    assert jax.tree_util.tree_structure(p3) == jax.tree_util.tree_structure(p2)


def test_momentum_values_sliced_not_reset():
    tx = optax.sgd(1e-2, momentum=0.9)
    m = small_mlp()
    p, _ = init_model(m, seed=0)
    opt_state = tx.init(p)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 6))
    y = jnp.zeros((4,), dtype=jnp.int32)
    g = jax.grad(
        lambda p_: jnp.mean(cross_entropy_loss(m.apply(p_, x)[0], y))
    )(p)
    _, opt_state = tx.update(g, opt_state, p)
    trace_before = opt_state[0].trace["fc1"]["w"]
    res = prune(m, p, "fc1", [2], opt_state=opt_state)
    trace_after = res.opt_state[0].trace["fc1"]["w"]
    keep = [0, 1, 3, 4, 5, 6, 7]
    np.testing.assert_array_equal(
        np.asarray(trace_after), np.asarray(trace_before[:, keep])
    )


def test_prune_by_scores_policies():
    m = small_mlp()
    p, _ = init_model(m)
    scores = np.array([-1.0, 2.0, -0.5, 3.0, 1.0, 0.5, -2.0, 4.0])
    res = prune_by_scores(m, p, "fc1", scores, policy="negative")
    assert res.model.layer("fc1").features == 5
    res2 = prune_by_scores(m, p, "fc1", scores, policy="fraction", fraction=0.25)
    assert res2.model.layer("fc1").features == 6
    # custom callable policy
    res3 = prune_by_scores(m, p, "fc1", scores, policy=lambda s: np.array([0]))
    assert res3.model.layer("fc1").features == 7


def test_callable_policy_duplicates_deduped_before_bucketing():
    from torchpruner_tpu.core.pruner import score_drop_indices

    scores = np.array([-1.0, 2.0, -0.5, 3.0, 1.0, 0.5, -2.0, 4.0])
    dup = lambda s: np.array([0, 0, 2, 2, 6])  # 3 distinct units
    np.testing.assert_array_equal(
        score_drop_indices(scores, policy=dup),
        np.array([0, 2, 6]),
    )
    # bucket math must count 3 dropped (keep 5 -> bucket=4 keeps 8), not 5
    assert len(score_drop_indices(scores, policy=dup, bucket=4)) == 0


def test_bucketed_pruning_rounds_kept_width_up():
    from torchpruner_tpu.core.pruner import bucket_drop

    m = small_mlp()
    p, _ = init_model(m)
    scores = np.array([-1.0, 2.0, -0.5, 3.0, 1.0, 0.5, -2.0, 4.0])
    # negative policy alone keeps 5; bucket=4 rounds up to 8 -> un-drops
    # the 3 highest-scoring dropped units (here: all of them)
    res = prune_by_scores(m, p, "fc1", scores, policy="negative", bucket=4)
    assert res.model.layer("fc1").features == 8
    # fraction=0.75 drops 6, keeps 2; bucket=4 keeps 4 — the extra kept
    # units must be the HIGHEST-scoring of the dropped set
    drop = np.argsort(scores)[:6]
    adjusted = bucket_drop(scores, drop, 4)
    assert len(scores) - len(adjusted) == 4
    kept = sorted(set(range(8)) - set(adjusted.tolist()))
    assert kept == sorted(np.argsort(scores)[-4:].tolist())
    # bucket=1 is the identity
    np.testing.assert_array_equal(bucket_drop(scores, drop, 1), drop)
    # already-aligned kept counts are untouched
    np.testing.assert_array_equal(bucket_drop(scores, drop, 2), drop)


def test_all_negative_never_empties_layer():
    m = small_mlp()
    p, _ = init_model(m)
    res = prune_by_scores(m, p, "fc1", -np.ones(8), policy="negative")
    assert res.model.layer("fc1").features >= 1


def test_pruner_class_wrapper():
    m = small_mlp()
    p, _ = init_model(m)
    pr = Pruner(m, p)
    pr.prune_model("fc1", [0])
    pr.prune_model("fc1", [0])
    assert pr.model.layer("fc1").features == 6
    x = jnp.ones((2, 6))
    out, _ = pr.model.apply(pr.params, x)
    assert out.shape == (2, 4)


def test_bad_plan_path_raises():
    from torchpruner_tpu.core.plan import Consumer, PlanError, PruneGroup

    m = small_mlp()
    p, _ = init_model(m)
    bad = PruneGroup(target="fc1", consumers=(Consumer(layer="nope"),))
    # the analyzer pre-flight names the offending path instead of letting
    # an anonymous KeyError surface from the slicing loop
    with pytest.raises(PlanError, match="nope/w"):
        prune(m, p, bad, [0])

"""SPMD (collective-based) pipeline parallelism — parallel/pp_spmd.py.

The cross-host-capable PP formulation: stacked block params sharded over
a ``pp`` mesh axis, microbatches streamed via ``lax.ppermute`` inside
one ``shard_map``-ed program.  Correctness bar: the pipelined forward
and the pipelined train step must match the plain single-device
``model.apply`` / gradient step on the same params — the schedule is an
execution reordering, not a numerical change (exact for the forward
modulo reduction order; tight rtol for grads).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchpruner_tpu.models import llama_tiny
from torchpruner_tpu.core.segment import init_model
from torchpruner_tpu.parallel.pp_spmd import (
    pp_spmd_apply,
    pp_spmd_train_step,
    split_pipeline,
)
from torchpruner_tpu.utils.losses import lm_cross_entropy_loss


def _mesh(n_stages):
    # a pp-only submesh (make_mesh insists on consuming every device)
    return jax.sharding.Mesh(
        np.asarray(jax.devices()[:n_stages]), ("pp",))


def _model_and_data(depth=4, batch=8, seed=0):
    model = llama_tiny(depth=depth)
    params, state = init_model(model, seed=seed)
    assert not state, "llama blocks are stateless"
    tokens = np.asarray(model.example_input(batch, seed=seed))
    return model, params, jnp.asarray(tokens)


def test_split_pipeline_structure():
    model, _, _ = _model_and_data(depth=4)
    pre, pairs, post = split_pipeline(model)
    assert [s.name for s in pre] == ["tok_emb"]
    assert len(pairs) == 4
    assert [s.name for s in post] == ["final_norm", "lm_head"]


def test_split_pipeline_rejects_nonuniform():
    from torchpruner_tpu.core.pruner import prune_by_scores

    model, params, _ = _model_and_data(depth=4)
    # prune one block's FFN: its shapes now differ from the others

    res = prune_by_scores(model, params, "block2_ffn/gate",
                          np.arange(64.0), policy="fraction", fraction=0.25)
    with pytest.raises(ValueError, match="non-uniform"):
        split_pipeline(res.model)


@pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (2, 8)])
def test_pp_spmd_forward_matches_sequential(n_stages, n_micro):
    model, params, tokens = _model_and_data(depth=4)
    mesh = _mesh(n_stages)
    want, _ = model.apply(params, tokens)
    got = pp_spmd_apply(model, params, tokens, mesh=mesh,
                        n_microbatches=n_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pp_spmd_grads_match_sequential():
    model, params, tokens = _model_and_data(depth=4)
    mesh = _mesh(4)

    def seq_loss(p):
        logits, _ = model.apply(p, tokens)
        return lm_cross_entropy_loss(logits, tokens).mean()

    def pp_loss(p):
        logits = pp_spmd_apply(model, p, tokens, mesh=mesh,
                               n_microbatches=4)
        return lm_cross_entropy_loss(logits, tokens).mean()

    g_seq = jax.grad(seq_loss)(params)
    g_pp = jax.grad(pp_loss)(params)
    flat_seq = jax.tree_util.tree_leaves_with_path(g_seq)
    flat_pp = {jax.tree_util.keystr(k): v
               for k, v in jax.tree_util.tree_leaves_with_path(g_pp)}
    assert len(flat_seq) == len(flat_pp)
    for k, v in flat_seq:
        np.testing.assert_allclose(
            np.asarray(flat_pp[jax.tree_util.keystr(k)]), np.asarray(v),
            rtol=2e-4, atol=2e-5, err_msg=jax.tree_util.keystr(k))


def test_pp_spmd_train_step_matches_single_device():
    model, params, tokens = _model_and_data(depth=4)
    mesh = _mesh(4)
    opt = optax.adam(1e-3)

    step = pp_spmd_train_step(model, opt, lm_cross_entropy_loss,
                              mesh=mesh, n_microbatches=4)

    def seq_step(p, s, toks):
        def loss(p_):
            logits, _ = model.apply(p_, toks)
            return lm_cross_entropy_loss(logits, toks).mean()

        l, g = jax.value_and_grad(loss)(p)
        updates, s = opt.update(g, s, p)
        return jax.tree_util.tree_map(lambda a, u: a + u, p, updates), s, l

    p_pp, s_pp = params, opt.init(params)
    p_sq, s_sq = params, opt.init(params)
    for _ in range(3):
        p_pp, s_pp, l_pp = step(p_pp, s_pp, tokens)
        p_sq, s_sq, l_sq = seq_step(p_sq, s_sq, tokens)
        np.testing.assert_allclose(float(l_pp), float(l_sq),
                                   rtol=1e-4, atol=1e-6)


def test_pp_spmd_remat_matches():
    model, params, tokens = _model_and_data(depth=2)
    mesh = _mesh(2)
    want = pp_spmd_apply(model, params, tokens, mesh=mesh,
                         n_microbatches=2)
    got = pp_spmd_apply(model, params, tokens, mesh=mesh,
                        n_microbatches=2, remat=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pp_spmd_composes_with_uniform_prune():
    """Pruning every block's FFN to the SAME width keeps the stack
    uniform (per-block indices may differ — only shapes must match), so
    structured pruning composes with SPMD pipelining: the pipelined
    forward of the pruned model equals its sequential forward."""
    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.core.pruner import prune_by_scores

    model, params, tokens = _model_and_data(depth=2)
    rng = np.random.default_rng(0)
    pm, pp_, ps = model, params, None
    for g in pruning_graph(model):
        if not g.target.endswith("/gate"):
            continue
        scores = rng.normal(size=pm.layer(g.target).features)
        res = prune_by_scores(pm, pp_, g.target, scores,
                              policy="fraction", fraction=0.25, state=ps)
        pm, pp_, ps = res.model, res.params, res.state
    assert pm is not model, "prune must have fired"
    mesh = _mesh(2)
    want, _ = pm.apply(pp_, tokens)
    got = pp_spmd_apply(pm, pp_, tokens, mesh=mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pp_spmd_composes_with_data_axis():
    """PP x DP on a 2-D mesh: batch sharded over `data` while the block
    stack pipelines over `pp` — the pod layout.  Output must equal the
    single-device forward."""
    model, params, tokens = _model_and_data(depth=2)
    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()[:4]).reshape(2, 2), ("pp", "data"))
    want, _ = model.apply(params, tokens)
    got = pp_spmd_apply(model, params, tokens, mesh=mesh,
                        n_microbatches=2, data_axis="data")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pp_spmd_vit_forward_matches():
    """ViT's `_attn`/`_mlp` Residual pairs pipeline exactly like llama's
    `_attn`/`_ffn` — vision transformer forward parity over 2 stages."""
    from torchpruner_tpu.models import vit_tiny

    model = vit_tiny(depth=2)
    params, state = init_model(model, seed=0)
    assert not state
    x = jnp.asarray(np.asarray(model.example_input(4, seed=0)))
    mesh = _mesh(2)
    want, _ = model.apply(params, x)
    got = pp_spmd_apply(model, params, x, mesh=mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pp_spmd_bert_forward_matches():
    """BERT's repeating unit is (attn Residual, post-LN, mlp Residual,
    post-LN) — the block-index grouping stacks the whole 4-spec unit, so
    the encoder pipelines too.  Forward parity over 2 stages."""
    from torchpruner_tpu.models import bert_tiny

    model = bert_tiny()
    pre, groups, post = split_pipeline(model)
    assert len(groups[0]) >= 3  # the interleaved-LN unit, not a pair
    params, state = init_model(model, seed=0)
    assert not state
    x = jnp.asarray(np.asarray(model.example_input(4, seed=0)))
    mesh = _mesh(2)
    want, _ = model.apply(params, x)
    got = pp_spmd_apply(model, params, x, mesh=mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pp_spmd_dropout_trains_with_rng():
    """Dropout-bearing ViT pipelines in train mode when an rng is
    provided: deterministic under the same key, actually stochastic
    (train != eval), and eval mode still equals the sequential apply."""
    from torchpruner_tpu.models import vit

    model = vit(image_size=16, patch_size=4, dim=32, depth=2,
                num_heads=4, mlp_dim=64, n_classes=10, dropout=0.2)
    params, state = init_model(model, seed=0)
    assert not state
    x = jnp.asarray(np.asarray(model.example_input(4, seed=0)))
    mesh = _mesh(2)
    key = jax.random.PRNGKey(7)

    with pytest.raises(ValueError, match="needs an rng"):
        pp_spmd_apply(model, params, x, mesh=mesh, n_microbatches=2,
                      train=True)

    t1 = pp_spmd_apply(model, params, x, mesh=mesh, n_microbatches=2,
                       train=True, rng=key)
    t2 = pp_spmd_apply(model, params, x, mesh=mesh, n_microbatches=2,
                       train=True, rng=key)
    np.testing.assert_allclose(np.asarray(t1), np.asarray(t2))

    ev = pp_spmd_apply(model, params, x, mesh=mesh, n_microbatches=2)
    assert np.abs(np.asarray(t1) - np.asarray(ev)).max() > 1e-4
    want, _ = model.apply(params, x)
    np.testing.assert_allclose(np.asarray(ev), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pp_spmd_train_step_dropout_with_per_step_rng():
    """The training-step API trains a dropout-bearing ViT when given a
    per-step rng, and raises the Dropout layer's own error without."""
    from torchpruner_tpu.models import vit

    model = vit(image_size=16, patch_size=4, dim=32, depth=2,
                num_heads=4, mlp_dim=64, n_classes=10, dropout=0.2)
    params, _ = init_model(model, seed=0)
    x = jnp.asarray(np.asarray(model.example_input(4, seed=0)))
    mesh = _mesh(2)
    opt = optax.adam(1e-3)

    # classification loss shaped like loss_fn(logits, y): reuse tokens
    # slot for labels via a closure
    y = jnp.zeros((4,), jnp.int32)

    def loss_fn(logits, _tokens):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -logp[jnp.arange(4), y]

    step = pp_spmd_train_step(model, opt, loss_fn, mesh=mesh,
                              n_microbatches=2)
    s = opt.init(params)
    with pytest.raises(ValueError, match="needs an rng"):
        step(params, s, x)
    p2, s2, l1 = step(params, s, x, jax.random.PRNGKey(0))
    _, _, l2 = step(p2, s2, x, jax.random.PRNGKey(1))
    assert np.isfinite(float(l1)) and np.isfinite(float(l2))


def test_pp_spmd_moe_rejected():
    """MoE blocks emit a load-balancing aux loss the SPMD schedule does
    not collect — silently dropping it would let experts collapse, so
    the split refuses (EP via ShardedTrainer handles MoE)."""
    from torchpruner_tpu.models import llama_moe_tiny

    with pytest.raises(ValueError, match="aux loss"):
        split_pipeline(llama_moe_tiny())


@pytest.mark.parametrize("n_stages,V,n_micro", [(2, 2, 4), (4, 2, 8),
                                                (2, 4, 4)])
def test_pp_spmd_interleaved_forward_matches_sequential(n_stages, V,
                                                        n_micro):
    """The Megatron interleaved schedule (V virtual chunks per device,
    wrap-around ppermute) is an execution reordering: forward equals the
    plain single-device apply.  depth=8 covers cb>1 (2,2), cb=1 with
    V=S (4,2) and V>S (2,4)."""
    model, params, tokens = _model_and_data(depth=8)
    mesh = _mesh(n_stages)
    want, _ = model.apply(params, tokens)
    got = pp_spmd_apply(model, params, tokens, mesh=mesh,
                        n_microbatches=n_micro, interleave=V)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pp_spmd_interleaved_train_step_matches_gpipe():
    """interleave=2 train steps track both the GPipe (V=1) pipelined
    steps and the single-device steps — same losses, same params."""
    model, params, tokens = _model_and_data(depth=4)
    mesh = _mesh(2)
    opt = optax.adam(1e-3)
    step_v2 = pp_spmd_train_step(model, opt, lm_cross_entropy_loss,
                                 mesh=mesh, n_microbatches=4, interleave=2)
    step_v1 = pp_spmd_train_step(model, opt, lm_cross_entropy_loss,
                                 mesh=mesh, n_microbatches=4)
    p2, s2 = params, opt.init(params)
    p1, s1 = params, opt.init(params)
    for _ in range(2):
        p2, s2, l2 = step_v2(p2, s2, tokens)
        p1, s1, l1 = step_v1(p1, s1, tokens)
        np.testing.assert_allclose(float(l2), float(l1), rtol=1e-4,
                                   atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_pp_spmd_interleave_rejects_bad_depth():
    model, params, tokens = _model_and_data(depth=4)
    with pytest.raises(ValueError, match="virtual chunks"):
        pp_spmd_apply(model, params, tokens, mesh=_mesh(2),
                      n_microbatches=4, interleave=3)


def test_pp_spmd_interleaved_ragged_wave_still_matches():
    """M not a multiple of S: the last wave is partial — injection and
    banking masks keep the schedule correct (garbage lanes never bank)."""
    model, params, tokens = _model_and_data(depth=8, batch=6)
    mesh = _mesh(2)
    want, _ = model.apply(params, tokens)
    got = pp_spmd_apply(model, params, tokens, mesh=mesh,
                        n_microbatches=3, interleave=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

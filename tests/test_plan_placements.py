"""`parallel.train.plan_placements` edge cases in isolation.

The placement planner is load-bearing three ways — ShardedTrainer places
real state with it, the collective lint compiles contract programs over
it, and the auto-parallelism planner enumerates candidates through it —
but until now it was only tested through those consumers.  These tests
pin its rules directly on abstract trees (no parameter materialized):
non-divisible largest dims, already-model-sharded params under zero,
the replicated fallback, and re-derivation on pruned (smaller) trees,
plus the planted-hazard knob and the mesh-factorization enumeration.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from torchpruner_tpu.parallel.train import (
    mesh_factorizations,
    plan_placements,
)


def _mesh(data=2, model=2):
    n = data * model
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(
        np.array(jax.devices()[:n]).reshape(data, model),
        ("data", "model"),
    )


def _abstract(shapes):
    return {k: jax.ShapeDtypeStruct(s, jnp.float32)
            for k, s in shapes.items()}


def _plan(params, mesh, *, tx=None, zero=False, partition="fsdp",
          state=None, plant=None):
    tx = tx or optax.adam(1e-3)
    opt = jax.eval_shape(tx.init, params)
    return plan_placements(
        None, params, state if state is not None else {}, opt, tx, mesh,
        partition=partition, zero=zero, plant=plant,
    )


def test_fsdp_shards_largest_divisible_dim():
    mesh = _mesh()
    params = {"w": jax.ShapeDtypeStruct((2 ** 14, 6), jnp.float32)}
    ps, ss, os_, zs = _plan(params, mesh)
    assert ps["w"].spec == P("model", None)
    assert zs is None


def test_nondivisible_largest_dim_falls_to_next_or_replicates():
    mesh = _mesh()
    # largest dim 3*2**13 odd multiple — 24576 % 2 == 0 so it shards;
    # force TRUE non-divisibility with odd dims on every axis
    params = _abstract({
        "odd": (2 ** 14 + 1, 5),        # no dim divides model=2
        "second": (2 ** 13 * 3, 7),     # largest divides -> sharded
    })
    ps, *_ = _plan(params, mesh)
    assert ps["odd"].spec == P(), "no divisible dim must replicate"
    assert ps["second"].spec == P("model", None)


def test_small_arrays_replicate_under_min_shard_size():
    mesh = _mesh()
    params = _abstract({"tiny": (64, 64)})  # 4096 < 2**14 default
    ps, *_ = _plan(params, mesh)
    assert ps["tiny"].spec == P()


def test_zero_adds_data_axis_on_unsharded_dim():
    mesh = _mesh()
    params = _abstract({"w": (2 ** 14, 8)})
    ps, _, os_, zs = _plan(params, mesh, zero=True)
    assert ps["w"].spec == P("model", None)
    # zero spec: data axis lands on the largest dim the param placement
    # left unsharded — here dim 1 (8 % data=2 == 0)
    assert zs["w"].spec == P("model", "data")


def test_zero_extends_already_model_sharded_dim_to_tuple():
    mesh = _mesh()
    # dim 1 (=3) does not divide data; dim 0 is model-sharded but
    # divides model*data -> the spec extends to the compound tuple
    params = _abstract({"w": (2 ** 14, 3)})
    ps, _, os_, zs = _plan(params, mesh, zero=True)
    assert ps["w"].spec == P("model", None)
    assert zs["w"].spec == P(("model", "data"), None)


def test_zero_replicated_fallback_keeps_param_spec():
    mesh = _mesh()
    # nothing divides data=2: the update domain degrades to the param
    # placement (replicated update — exactly pre-ZeRO behavior)
    params = _abstract({"w": (3, 5)})
    ps, _, os_, zs = _plan(params, mesh, zero=True)
    assert ps["w"].spec == P()
    assert zs["w"].spec == P()


def test_opt_state_takes_zero_placement_and_counts_replicate():
    mesh = _mesh()
    params = _abstract({"w": (2 ** 14, 8)})
    tx = optax.adam(1e-3)
    ps, _, os_, zs = _plan(params, mesh, tx=tx, zero=True)
    # adam: ScaleByAdamState(count, mu, nu) — param-shaped slots carry
    # the ZERO spec, the scalar count replicates
    flat = jax.tree_util.tree_leaves(
        os_, is_leaf=lambda x: hasattr(x, "spec"))
    specs = {tuple(s.spec) for s in flat}
    assert tuple(zs["w"].spec) in specs
    assert () in specs  # the replicated count


def test_zero_skipped_without_data_axis_gt_one():
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2),
                ("data", "model"))
    params = _abstract({"w": (2 ** 14, 8)})
    *_, zs = _plan(params, mesh, zero=True)
    assert zs is None


def test_plant_knocks_out_zero_tree():
    mesh = _mesh()
    params = _abstract({"w": (2 ** 14, 8)})
    *_, zs = _plan(params, mesh, zero=True, plant="replicated_allreduce")
    assert zs is None


def test_state_replicates():
    mesh = _mesh()
    params = _abstract({"w": (2 ** 14, 8)})
    state = _abstract({"bn_mean": (2 ** 14,)})
    _, ss, *_ = _plan(params, mesh, state=state)
    assert ss["bn_mean"].spec == P()


def test_pruned_tree_rederivation_falls_back():
    """The rebuild() path in isolation: the SAME planner call over the
    pruned (smaller) trees — a dim that stopped dividing loses its
    shard, and the zero domain re-derives under the new shapes."""
    mesh = _mesh()
    full = _abstract({"w": (2 ** 14, 8), "v": (2 ** 14, 4)})
    ps_full, _, _, zs_full = _plan(full, mesh, zero=True)
    assert ps_full["w"].spec == P("model", None)
    assert zs_full["w"].spec == P("model", "data")

    # prune w's rows to an odd width: no dim of w divides model OR data
    pruned = _abstract({"w": (2 ** 14 - 1, 3), "v": (2 ** 14, 4)})
    ps_p, _, os_p, zs_p = _plan(pruned, mesh, zero=True)
    assert ps_p["w"].spec == P()       # replicated fallback
    assert zs_p["w"].spec == P()       # update domain degrades with it
    assert ps_p["v"].spec == P("model", None)  # untouched leaf keeps its shard
    assert zs_p["v"].spec == P("model", "data")


def test_unknown_partition_raises():
    mesh = _mesh()
    params = _abstract({"w": (2 ** 14, 8)})
    with pytest.raises(ValueError, match="partition"):
        _plan(params, mesh, partition="3d")


# ---------------------------------------------------------------------------
# mesh_factorizations — the planner's candidate-mesh enumeration
# ---------------------------------------------------------------------------


def test_mesh_factorizations_covers_all_divisors():
    got = mesh_factorizations(8)
    assert got == [
        {"data": 8},
        {"data": 4, "model": 2},
        {"data": 2, "model": 4},
        {"data": 1, "model": 8},
    ]


def test_mesh_factorizations_single_device_and_bounds():
    assert mesh_factorizations(1) == [{"data": 1}]
    assert mesh_factorizations(12, max_model=3) == [
        {"data": 12},
        {"data": 6, "model": 2},
        {"data": 4, "model": 3},
    ]
    # every entry is a valid mesh over exactly n devices
    for axes in mesh_factorizations(16):
        assert int(np.prod(list(axes.values()))) == 16

"""KV-cache decoding tests: per-position logits from the cached decode must
equal the full causal forward's, for dense, GQA-head/FFN-pruned, and MoE
models; generation is deterministic (greedy) / seeded (temperature)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.core.segment import init_model
from torchpruner_tpu.generate import (
    generate,
    init_cache,
    make_decode_step,
    make_slot_decode_step,
)
from torchpruner_tpu.models import llama_moe_tiny, llama_tiny


def decode_all_positions(model, params, toks, max_len=None):
    """Feed toks one at a time through the jitted decode step; stack the
    per-position logits."""
    B, S = toks.shape
    step = make_decode_step(model)
    cache = init_cache(model, B, max_len or S)
    outs = []
    for pos in range(S):
        logits, cache = step(params, cache, toks[:, pos:pos + 1], pos)
        outs.append(logits)
    return jnp.stack(outs, axis=1)  # (B, S, V)


def parity_case(model, atol=2e-4):
    params, state = init_model(model, seed=0)
    toks = np.asarray(
        jax.random.randint(jax.random.PRNGKey(3), (2, 12), 0, 64), np.int32
    )
    full, _ = model.apply(params, toks, state=state, train=False)
    dec = decode_all_positions(model, params, toks)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full), atol=atol)
    return params, state, toks


def test_decode_matches_full_forward_dense():
    parity_case(llama_tiny())


def test_decode_matches_full_forward_moe():
    parity_case(llama_moe_tiny())


def test_decode_matches_after_pruning():
    """Head + FFN pruning changes shapes and GQA grouping; decode must
    track the pruned spec exactly."""
    model = llama_tiny()
    params, state, toks = (None, None, None)
    params, state = init_model(model, seed=0)
    r = prune(model, params, "block1_ffn/gate", [0, 3, 17], state=state)
    r = prune(r.model, r.params, "block2_attn/attn", [1], state=r.state)
    model, params, state = r.model, r.params, r.state
    toks = np.asarray(
        jax.random.randint(jax.random.PRNGKey(5), (2, 10), 0, 64), np.int32
    )
    full, _ = model.apply(params, toks, state=state, train=False)
    dec = decode_all_positions(model, params, toks)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full), atol=2e-4)


def test_decode_with_longer_buffer_matches():
    """A max_len buffer longer than the sequence (the serving case) must
    not change the numerics — future positions are masked, not read."""
    model = llama_tiny()
    params, state = init_model(model, seed=0)
    toks = np.asarray(
        jax.random.randint(jax.random.PRNGKey(7), (1, 8), 0, 64), np.int32
    )
    full, _ = model.apply(params, toks, state=state, train=False)
    dec = decode_all_positions(model, params, toks, max_len=32)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full), atol=2e-4)


def ragged_parity_case(model, params):
    """The continuous-batching correctness contract: a slot array whose
    sequences START and FINISH at different engine steps must produce
    per-position logits BIT-IDENTICAL to each sequence decoded alone.
    The slot caches are poisoned up front — recycled-slot stale K/V must
    be masked into irrelevance, not merely approximately small."""
    B, T = 3, 24
    toks = np.asarray(
        jax.random.randint(jax.random.PRNGKey(3), (B, 16), 0, 64),
        np.int32)
    starts, lens = [0, 3, 6], [10, 8, 6]
    slot_step = make_slot_decode_step(model)
    cache = init_cache(model, B, T)
    cache = jax.tree_util.tree_map(lambda a: a + 7.25, cache)  # poison
    pos = np.zeros(B, np.int32)
    fed = [0] * B
    ragged = [[] for _ in range(B)]
    for step_i in range(20):
        tok = np.zeros((B, 1), np.int32)
        active = [b for b in range(B)
                  if step_i >= starts[b] and fed[b] < lens[b]]
        if not active:
            break
        for b in active:
            tok[b, 0] = toks[b, fed[b]]
        logits, cache = slot_step(params, cache, jnp.asarray(tok),
                                  jnp.asarray(pos))
        logits = np.asarray(logits)
        for b in active:
            ragged[b].append(logits[b])
            fed[b] += 1
            pos[b] += 1
    assert fed == lens
    step1 = make_decode_step(model)
    for b in range(B):
        c1 = init_cache(model, 1, T)
        for p_ in range(lens[b]):
            solo, c1 = step1(params, c1, jnp.asarray(toks[b:b + 1,
                                                          p_:p_ + 1]), p_)
            np.testing.assert_array_equal(
                np.asarray(solo)[0], ragged[b][p_],
                err_msg=f"row {b} pos {p_}: ragged batched decode "
                        "diverged from solo decode")


def test_ragged_slot_decode_bit_identical_dense():
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    ragged_parity_case(model, params)


def test_ragged_slot_decode_bit_identical_pruned():
    """Head + FFN pruning changes shapes and GQA grouping; the slot
    decode must track the pruned spec exactly (pruned serving is the
    whole point of the engine)."""
    model = llama_tiny()
    params, state = init_model(model, seed=0)
    r = prune(model, params, "block1_ffn/gate", [0, 3, 17], state=state)
    r = prune(r.model, r.params, "block2_attn/attn", [1], state=r.state)
    ragged_parity_case(r.model, r.params)


def test_ragged_slot_decode_bit_identical_moe():
    model = llama_moe_tiny()
    params, _ = init_model(model, seed=0)
    ragged_parity_case(model, params)


def test_generate_greedy_matches_stepwise_argmax():
    """generate() (scanned prefill + scanned sampling) must reproduce the
    token-by-token greedy rollout."""
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    prompt = np.asarray([[5, 9, 2, 14]], np.int32)
    n_new = 6
    got = np.asarray(generate(model, params, prompt, n_new))

    # manual rollout with the single-step API
    step = make_decode_step(model)
    cache = init_cache(model, 1, prompt.shape[1] + n_new)
    logits = None
    for pos in range(prompt.shape[1]):
        logits, cache = step(params, cache, prompt[:, pos:pos + 1], pos)
    want = []
    pos = prompt.shape[1]
    for _ in range(n_new):
        tok = np.asarray(jnp.argmax(logits, axis=-1), np.int32)
        want.append(tok)
        logits, cache = step(params, cache, tok[:, None], pos)
        pos += 1
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_generate_with_tensor_parallel_params():
    """Distributed serving: generate() with TP-sharded parameters (the
    pruning-graph column/row placement) must emit the same tokens as the
    single-device run — GSPMD partitions the cached decode without any
    decode-specific sharding code."""
    from torchpruner_tpu.parallel import make_mesh
    from torchpruner_tpu.parallel.sharding import tp_sharding

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    prompt = np.asarray([[5, 9, 2, 14]], np.int32)
    want = np.asarray(generate(model, params, prompt, 6))

    mesh = make_mesh({"model": 4}, devices=jax.devices()[:4])
    params_tp = jax.device_put(
        params, tp_sharding(model, params, mesh, "model", 0)
    )
    got = np.asarray(generate(model, params_tp, prompt, 6))
    np.testing.assert_array_equal(got, want)


def test_generate_temperature_seeded_and_validated():
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    prompt = np.asarray([[1, 2, 3]], np.int32)
    a = generate(model, params, prompt, 5, temperature=0.8,
                 rng=jax.random.PRNGKey(0))
    b = generate(model, params, prompt, 5, temperature=0.8,
                 rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt, 2, temperature=0.8)
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, prompt, 5, max_len=4)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, 2, temperature=0.8, top_k=0,
                 rng=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, prompt, 2, temperature=0.8, top_p=1.5,
                 rng=jax.random.PRNGKey(0))


def test_truncated_sampling_respects_top_k_and_top_p():
    """top_k=1 must equal greedy regardless of temperature; top_p mass-
    truncation keeps exactly the smallest prefix reaching the mass."""
    from torchpruner_tpu.generate import _truncate_logits

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    prompt = np.asarray([[1, 2, 3]], np.int32)
    greedy = np.asarray(generate(model, params, prompt, 5))
    k1 = np.asarray(generate(model, params, prompt, 5, temperature=2.0,
                             top_k=1, rng=jax.random.PRNGKey(4)))
    np.testing.assert_array_equal(k1, greedy)

    def kept(arr):
        return set(np.where(np.asarray(arr)[0] > -1e30)[0])

    # analytic nucleus: probs = [0.6, 0.22, 0.08, 0.03, 0.07]
    logits = jnp.log(jnp.asarray([[0.6, 0.22, 0.08, 0.03, 0.07]]))
    assert kept(_truncate_logits(logits, None, 0.6)) == {0}  # 0.6 covers
    # 0.8 needs the top two (0.6 + 0.22)
    assert kept(_truncate_logits(logits, None, 0.8)) == {0, 1}
    # top_k=3 keeps exactly the three largest (0.6, 0.22, 0.08)
    assert kept(_truncate_logits(logits, 3, None)) == {0, 1, 2}


def test_generate_with_bf16_cache_first_token_and_shape():
    """bf16 KV cache (the serving config: half the cache bytes): the
    FIRST greedy token must match the f32 cache — a single-step argmax
    flip needs a logit margin below cache rounding error.  Later tokens
    can legitimately diverge (one flip re-conditions the whole suffix),
    so only shape/dtype is asserted for the rest."""
    import jax.numpy as jnp

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    toks = np.asarray(
        jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, 64), np.int32
    )
    f32 = np.asarray(generate(model, params, toks, 12))
    b16 = np.asarray(
        generate(model, params, toks, 12, cache_dtype=jnp.bfloat16))
    np.testing.assert_array_equal(f32[:, 0], b16[:, 0])
    assert b16.shape == f32.shape and b16.dtype == f32.dtype

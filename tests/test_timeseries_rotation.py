"""Reader coverage across rotated time-series streams (satellite of the
incident-correlation PR): ``load_series`` / ``segment_percentiles`` /
``aggregate_windows`` must behave identically whether a run's windows
live in one ``metrics_ts.jsonl`` or straddle rotated backups — including
the interaction of a torn tail (kill -9 mid-write) with histogram bucket
bounds that shipped once in a window now living in an older backup."""

import json
import os

import pytest

from torchpruner_tpu.obs.metrics import MetricsRegistry
from torchpruner_tpu.obs.timeseries import (
    TS_FILENAME,
    TimeseriesRecorder,
    aggregate_windows,
    load_series,
    segment_percentiles,
    series_paths,
    split_warmup,
    window_quantile,
)


def _record_run(tmp_path, n_windows=24, per_window=3, value=0.010,
                **kw):
    """A run with a histogram observed in EVERY window, forced through
    rotation with a tiny byte budget."""
    reg = MetricsRegistry()
    # enough backups to keep EVERY window: these tests exercise the
    # read seam between files, not the pruning policy
    rec = TimeseriesRecorder(reg, str(tmp_path), interval_s=0.01,
                             rotate_bytes=kw.pop("rotate_bytes", 1000),
                             backups=kw.pop("backups", 8), **kw)
    h = reg.histogram("lat_seconds")
    c = reg.counter("reqs_total")
    for i in range(n_windows):
        for _ in range(per_window):
            h.observe(value)
            c.inc()
        rec.tick()
    rec.close()
    return os.path.join(str(tmp_path), TS_FILENAME)


def test_bounds_carry_forward_across_rotation_boundary(tmp_path):
    """The ``le`` bounds ship once (first window, oldest backup after
    rotation); every later window — including those in a different
    file — must still reconstruct per-window quantiles."""
    path = _record_run(tmp_path)
    assert len(series_paths(path)) > 1, "rotation never happened"
    _, windows = load_series(str(tmp_path))
    with_hist = [w for w in windows if "lat_seconds" in
                 (w.get("hist") or {})]
    assert len(with_hist) >= 20
    # raw on-disk: only the FIRST occurrence carries bounds...
    raw = [json.loads(line) for p in series_paths(path)
           for line in open(p) if line.strip()]
    raw_hists = [r["hist"]["lat_seconds"] for r in raw
                 if r.get("kind") == "ts_window"
                 and "lat_seconds" in (r.get("hist") or {})]
    assert "le" in raw_hists[0]
    assert all("le" not in h for h in raw_hists[1:])
    # ...but the reader re-attaches them to every window, so quantile
    # reconstruction works on windows from the NEWEST file too
    for w in with_hist:
        assert window_quantile(w, "lat_seconds", 0.99) is not None


def test_aggregate_and_segment_span_rotation_boundary(tmp_path):
    _record_run(tmp_path, n_windows=24, per_window=3)
    _, windows = load_series(str(tmp_path))
    agg = aggregate_windows(windows, "lat_seconds")
    assert agg is not None
    assert agg["n"] == 24 * 3  # no window lost at the boundary
    assert agg["sum"] == pytest.approx(24 * 3 * 0.010, rel=1e-6)
    seg = segment_percentiles(windows, "lat_seconds")
    assert seg["n"] == 72
    assert seg["mean"] == pytest.approx(0.010, rel=1e-6)
    assert seg["p50"] is not None and seg["p99"] is not None
    # a segment drawn ONLY from late windows (all in the newest file,
    # none of which shipped bounds on disk) still reconstructs
    _, steady = split_warmup(windows, 0.5)
    late = segment_percentiles(steady, "lat_seconds")
    assert late is not None and late["n"] == sum(
        w["hist"]["lat_seconds"]["n"] for w in steady
        if "lat_seconds" in (w.get("hist") or {}))


def test_torn_tail_on_newest_file_keeps_rotated_history(tmp_path):
    """kill -9 mid-append: the torn final line is dropped, every intact
    window in the live file AND the backups survives, and bucket bounds
    carried from the rotated prefix still apply to the kept windows."""
    path = _record_run(tmp_path, n_windows=24)
    _, before = load_series(str(tmp_path))
    with open(path, "a") as f:
        f.write('{"kind": "ts_window", "seq": 999, "hist": {"lat')
    _, after = load_series(str(tmp_path))
    assert [w["seq"] for w in after] == [w["seq"] for w in before]
    # aggregation unchanged by the torn tail
    assert aggregate_windows(after, "lat_seconds")["n"] == \
        aggregate_windows(before, "lat_seconds")["n"]
    last = [w for w in after
            if "lat_seconds" in (w.get("hist") or {})][-1]
    assert window_quantile(last, "lat_seconds", 0.5) is not None


def test_torn_tail_in_rotated_backup_is_skipped_too(tmp_path):
    """Rotation can race a kill: a torn line at the end of a BACKUP
    (not just the live file) must be skipped without losing the rest
    of that backup or the files after it."""
    path = _record_run(tmp_path, n_windows=24)
    backups = [p for p in series_paths(path) if p != path]
    assert backups
    with open(backups[0], "a") as f:
        f.write('{"kind": "ts_window", "seq": 998, "coun')
    _, windows = load_series(str(tmp_path))
    seqs = [w["seq"] for w in windows]
    assert seqs == sorted(seqs)
    # windows after the torn backup (later backups + live file) kept
    assert seqs[-1] == 25  # 24 ticks + forced close window
    assert aggregate_windows(windows, "lat_seconds")["n"] == 72


def test_value_shift_across_boundary_is_visible_in_segments(tmp_path):
    """Percentile reconstruction must see a latency shift that happens
    to coincide with a file rotation — the reader seam can't smooth or
    drop it (this is the signal the anomaly detector scores)."""
    reg = MetricsRegistry()
    rec = TimeseriesRecorder(reg, str(tmp_path), interval_s=0.01,
                             rotate_bytes=1000, backups=8)
    h = reg.histogram("lat_seconds")
    for i in range(30):
        for _ in range(3):
            h.observe(0.010 if i < 20 else 0.500)
        rec.tick()
    rec.close()
    path = os.path.join(str(tmp_path), TS_FILENAME)
    assert len(series_paths(path)) > 1
    _, windows = load_series(str(tmp_path))
    hist_windows = [w for w in windows
                    if "lat_seconds" in (w.get("hist") or {})]
    early = segment_percentiles(hist_windows[:20], "lat_seconds")
    late = segment_percentiles(hist_windows[20:], "lat_seconds")
    assert early["p99"] < 0.1 < late["p50"]
    full = segment_percentiles(hist_windows, "lat_seconds")
    assert full["n"] == 90
    assert full["mean"] == pytest.approx(
        (20 * 3 * 0.010 + 10 * 3 * 0.500) / 90, rel=1e-6)

"""tpu-lint pass 6 (host-side concurrency & durability) tests: a
synthetic violation corpus — one minimal module per check id, asserted
by name AND path — a clean fixture that must produce zero findings,
waiver match / stale-waiver / bad-waiver semantics, the planted-
violation drill, the standalone CLI exit codes, and the whole-package
scan smoke (zero error-severity findings on the committed tree, under
the PERF.md <10 s wall bound)."""

import json
import subprocess
import sys
import time

import pytest

from torchpruner_tpu.analysis import host_lint_default_paths, scan_source
from torchpruner_tpu.analysis.host_lint import (
    Waiver,
    apply_waivers,
    default_waivers_path,
    host_lint_main,
    lint_host,
    load_waivers,
)


def checks(findings, severity=None):
    return [f.check for f in findings
            if severity is None or f.severity == severity]


# -- synthetic violation corpus: one minimal module per check id -------------


UNLOCKED_WRITE = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self.n += 1

    def racy(self):
        self.n = 5
"""


def test_unlocked_shared_write_fires():
    fs = scan_source(UNLOCKED_WRITE, "synthetic/unlocked.py")
    hits = [f for f in fs if f.check == "host/unlocked-shared-write"]
    assert len(hits) == 1
    assert hits[0].severity == "error"
    assert hits[0].path.startswith("synthetic/unlocked.py:")
    assert "Counter.racy" in hits[0].path
    assert "n" in hits[0].message


READ_GUARDED_WRITE = """
import threading

class Gate:
    def __init__(self):
        self._lock = threading.Lock()
        self.closed = False

    def submit(self):
        with self._lock:
            if self.closed:
                return False
        return True

    def shutdown(self):
        self.closed = True
"""


def test_read_under_lock_guards_the_attribute():
    # an attribute only READ under the lock is still lock-guarded: the
    # lock exists because someone consults it (the scheduler.closed
    # race this check was built from)
    fs = scan_source(READ_GUARDED_WRITE, "synthetic/readguard.py")
    hits = [f for f in fs if f.check == "host/unlocked-shared-write"]
    assert len(hits) == 1
    assert "Gate.shutdown" in hits[0].path


CROSS_OBJECT_WRITE = """
import threading

class Scheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self.closed = False

    def close(self):
        with self._lock:
            self.closed = True

class Engine:
    def __init__(self, scheduler):
        self.scheduler = scheduler

    def drain(self):
        self.scheduler.closed = True
"""


def test_cross_object_unlocked_write_fires():
    fs = scan_source(CROSS_OBJECT_WRITE, "synthetic/cross.py")
    hits = [f for f in fs if f.check == "host/unlocked-shared-write"]
    assert len(hits) == 1
    assert "Engine.drain" in hits[0].path
    assert "Scheduler" in hits[0].message


BLOCKING_UNDER_LOCK = """
import threading
import time

class Slow:
    def __init__(self):
        self._lock = threading.Lock()

    def pause(self):
        with self._lock:
            time.sleep(0.5)
"""


def test_blocking_under_lock_fires():
    fs = scan_source(BLOCKING_UNDER_LOCK, "synthetic/blocking.py")
    hits = [f for f in fs if f.check == "host/blocking-under-lock"]
    assert len(hits) == 1
    assert hits[0].severity == "warning"
    assert "Slow.pause" in hits[0].path


LOCK_ORDER = """
import threading

class Deadlocky:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._io_lock = threading.Lock()

    def forward(self):
        with self._state_lock:
            with self._io_lock:
                pass

    def backward(self):
        with self._io_lock:
            with self._state_lock:
                pass
"""


def test_lock_order_cycle_fires():
    fs = scan_source(LOCK_ORDER, "synthetic/order.py")
    hits = [f for f in fs if f.check == "host/lock-order"]
    assert len(hits) == 1
    assert hits[0].severity == "error"
    assert "synthetic/order.py" in hits[0].path


TORN_WRITE = """
import json

def flush(path, records):
    with open(path + "/journal.json", "w") as f:
        json.dump(records, f)
"""


def test_torn_write_fires():
    fs = scan_source(TORN_WRITE, "synthetic/torn.py")
    hits = [f for f in fs if f.check == "host/torn-write"]
    assert hits, checks(fs)
    assert hits[0].severity == "error"
    assert "atomic_write_json" in hits[0].message


DAEMON_LEAK = """
import threading

def start_pump():
    t = threading.Thread(target=print)
    t.start()
    return t
"""


def test_daemon_leak_fires():
    fs = scan_source(DAEMON_LEAK, "synthetic/daemon.py")
    hits = [f for f in fs if f.check == "host/daemon-leak"]
    assert len(hits) == 1
    assert hits[0].severity == "warning"
    assert "start_pump" in hits[0].path


def test_daemon_true_and_joined_threads_pass():
    ok = """
import threading

def start_daemon():
    t = threading.Thread(target=print, daemon=True)
    t.start()

def start_joined():
    t = threading.Thread(target=print)
    t.start()
    t.join()
"""
    fs = scan_source(ok, "synthetic/daemon_ok.py")
    assert "host/daemon-leak" not in checks(fs)


WALLCLOCK_DIGEST = """
import time

def make_trial_id(seq):
    return f"trial-{seq}-{time.time()}"
"""


def test_wallclock_in_digest_fires():
    fs = scan_source(WALLCLOCK_DIGEST, "synthetic/wallclock.py")
    hits = [f for f in fs if f.check == "host/wallclock-in-digest"]
    assert len(hits) == 1
    assert hits[0].severity == "error"
    assert "make_trial_id" in hits[0].path


# -- clean fixture ------------------------------------------------------------


CLEAN = """
import json
import threading
import time

from torchpruner_tpu.resilience.manifest import atomic_write_json

class Tidy:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self.n += 1

    def snapshot(self):
        with self._lock:
            n = self.n
        return n

def persist(path, data):
    atomic_write_json(path + "/manifest.json", data)

def wait_a_bit():
    time.sleep(0.01)
"""


def test_clean_fixture_zero_findings():
    assert scan_source(CLEAN, "synthetic/clean.py") == []


def test_locked_suffix_convention():
    # methods named *_locked run with the caller's lock held — their
    # writes are guarded, not racy (the SLOMonitor._check_locked idiom)
    src = """
import threading

class Mon:
    def __init__(self):
        self._lock = threading.Lock()
        self.rolling = 0

    def check(self):
        with self._lock:
            return self._check_locked()

    def _check_locked(self):
        self.rolling = 1
        return self.rolling
"""
    fs = scan_source(src, "synthetic/locked_suffix.py")
    assert "host/unlocked-shared-write" not in checks(fs)


def test_init_writes_are_exempt():
    src = """
import threading

class Boring:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = "new"

    def advance(self):
        with self._lock:
            self.state = "running"
"""
    assert scan_source(src, "synthetic/init_ok.py") == []


# -- waiver semantics ---------------------------------------------------------


def test_waiver_downgrades_to_info_with_reason(tmp_path):
    mod = tmp_path / "racy.py"
    mod.write_text(BLOCKING_UNDER_LOCK)
    wfile = tmp_path / "waivers.json"
    wfile.write_text(json.dumps({"waivers": [{
        "check": "host/blocking-under-lock",
        "file": "racy.py",
        "reason": "test fixture: sleep is intentional",
    }]}))
    fs = lint_host([str(mod)], waivers_path=str(wfile))
    assert checks(fs, "error") == []
    assert checks(fs, "warning") == []
    waived = [f for f in fs if f.check == "host/blocking-under-lock"]
    assert len(waived) == 1
    assert waived[0].severity == "info"
    assert "waived (test fixture: sleep is intentional)" \
        in waived[0].message


def test_stale_waiver_is_an_error(tmp_path):
    mod = tmp_path / "fine.py"
    mod.write_text(CLEAN)
    wfile = tmp_path / "waivers.json"
    wfile.write_text(json.dumps({"waivers": [{
        "check": "host/blocking-under-lock",
        "file": "fine.py",
        "reason": "excuses code that no longer exists",
    }]}))
    fs = lint_host([str(mod)], waivers_path=str(wfile))
    assert checks(fs, "error") == ["host/stale-waiver"]


def test_waiver_for_unscanned_file_is_not_stale(tmp_path):
    # the default scan covers the serving plane only; a waiver for a
    # file OUTSIDE the scanned paths must not be reported stale
    mod = tmp_path / "fine.py"
    mod.write_text(CLEAN)
    wfile = tmp_path / "waivers.json"
    wfile.write_text(json.dumps({"waivers": [{
        "check": "host/blocking-under-lock",
        "file": "somewhere/else.py",
        "reason": "scanned in the full-package CI lane only",
    }]}))
    fs = lint_host([str(mod)], waivers_path=str(wfile))
    assert checks(fs, "error") == []


def test_reasonless_waiver_is_an_error(tmp_path):
    mod = tmp_path / "fine.py"
    mod.write_text(CLEAN)
    wfile = tmp_path / "waivers.json"
    wfile.write_text(json.dumps({"waivers": [{
        "check": "host/blocking-under-lock",
        "file": "fine.py",
    }]}))
    fs = lint_host([str(mod)], waivers_path=str(wfile))
    assert checks(fs, "error") == ["host/bad-waiver"]


def test_apply_waivers_counts_hits():
    fs = scan_source(BLOCKING_UNDER_LOCK, "synthetic/blocking.py")
    w = Waiver("host/blocking-under-lock", "synthetic/blocking.py",
               "unit test")
    out = apply_waivers(fs, [w], ["synthetic/blocking.py"])
    assert w.hits == 1
    assert all(f.severity == "info" for f in out)


def test_committed_waiver_file_is_well_formed():
    waivers, findings = load_waivers(default_waivers_path())
    assert findings == []
    assert waivers, "committed waiver file should carry entries"
    assert all(w.reason for w in waivers)


# -- planted-violation drill --------------------------------------------------


def test_planted_unlocked_write_drill(tmp_path):
    mod = tmp_path / "fine.py"
    mod.write_text(CLEAN)
    fs = lint_host([str(mod)], waivers_path=str(tmp_path / "none.json"),
                   plant="unlocked_write")
    errs = [f for f in fs if f.severity == "error"]
    assert [f.check for f in errs] == ["host/unlocked-shared-write"]
    assert "<planted:unlocked_write>" in errs[0].path


def test_foreign_plant_is_ignored(tmp_path):
    # TORCHPRUNER_LINT_PLANT is shared with the collective drill —
    # pass 4's replicated_allreduce must not trip pass 6 (and vice
    # versa: the placement planner ignores unlocked_write)
    mod = tmp_path / "fine.py"
    mod.write_text(CLEAN)
    fs = lint_host([str(mod)], waivers_path=str(tmp_path / "none.json"),
                   plant="replicated_allreduce")
    assert checks(fs, "error") == []


# -- entry points -------------------------------------------------------------


def test_cli_clean_tree_exits_zero(tmp_path, capsys):
    artifact = tmp_path / "host_lint.json"
    rc = host_lint_main(["torchpruner_tpu", "--json", str(artifact)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "host" in out
    data = json.loads(artifact.read_text())
    assert data["errors"] == 0


def test_cli_planted_drill_exits_one(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("TORCHPRUNER_LINT_PLANT", "unlocked_write")
    rc = host_lint_main(["torchpruner_tpu"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "host/unlocked-shared-write" in out


def test_module_cli_dispatch(tmp_path):
    mod = tmp_path / "racy.py"
    mod.write_text(UNLOCKED_WRITE)
    proc = subprocess.run(
        [sys.executable, "-m", "torchpruner_tpu", "lint-host", str(mod),
         "--waivers", str(tmp_path / "none.json")],
        capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "host/unlocked-shared-write" in proc.stdout


def test_default_paths_are_the_serving_plane():
    paths = host_lint_default_paths()
    tails = [p.replace("\\", "/").rsplit("/", 1)[-1] for p in paths]
    assert tails == ["fleet", "serve", "search", "obs", "resilience"]


def test_record_gauges_lands_in_obs(tmp_path):
    from torchpruner_tpu import obs
    from torchpruner_tpu.analysis.host_lint import record_gauges

    obs.configure(str(tmp_path / "obs"), annotate=False)
    try:
        record_gauges(scan_source(UNLOCKED_WRITE, "synthetic/u.py"))
        assert obs.counter_value("host_lint_findings_total") == 1
        assert obs.counter_value("host_lint_errors_total") == 1
    finally:
        obs.shutdown()


# -- whole-package smoke ------------------------------------------------------


def test_whole_package_scan_is_clean_and_fast():
    t0 = time.perf_counter()
    fs = lint_host(["torchpruner_tpu"])
    wall = time.perf_counter() - t0
    errs = [f for f in fs if f.severity == "error"]
    assert errs == [], [f.format() for f in errs]
    # warnings must be fixed or waived too — zero silent exceptions
    warns = [f for f in fs if f.severity == "warning"]
    assert warns == [], [f.format() for f in warns]
    assert wall < 10.0, f"host lint took {wall:.1f}s (PERF.md bound: 10s)"

"""Weight-only int8 serving quantization (ops/quant.py).

The deploy pipeline the reference never had: prune -> fine-tune ->
quantize -> generate.  These tests pin (1) the quantization math
(symmetric per-output-channel, output-side rescaling exact), (2) logit
fidelity of a quantized model end to end (forward AND KV-cache decode),
(3) composition with structural pruning, and (4) the prune-after-
quantize refusal.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchpruner_tpu as tp
from torchpruner_tpu.core.segment import init_model
from torchpruner_tpu.models import llama_tiny
from torchpruner_tpu.ops.quant import (
    QTensor,
    quantize_tensor,
    wval,
    oscale,
)


def test_quantize_tensor_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 32)).astype(np.float32)
    qt = quantize_tensor(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 32)
    assert qt.out_scale().shape == (32,)
    # symmetric max-abs/127: per-channel error <= scale/2
    err = np.abs(qt.dequantize() - w)
    assert (err <= np.asarray(qt.scale) / 2 + 1e-7).all()
    # output-side rescaling == dequantized matmul, exactly
    x = rng.normal(size=(4, 64)).astype(np.float32)
    y_scaled = oscale(x @ wval(qt, jnp.float32), qt)
    y_dequant = x @ qt.dequantize()
    np.testing.assert_allclose(np.asarray(y_scaled),
                               np.asarray(y_dequant), rtol=1e-5, atol=1e-5)


def test_quantize_tensor_zero_channel_and_3d():
    w = np.zeros((8, 4), np.float32)
    qt = quantize_tensor(w)
    np.testing.assert_array_equal(np.asarray(qt.dequantize()), w)
    # attention-projection shape (d, h, k): one scale per (h, k) output
    rng = np.random.default_rng(1)
    w3 = rng.normal(size=(16, 2, 8)).astype(np.float32)
    q3 = quantize_tensor(w3, in_axes=1)
    assert q3.scale.shape == (1, 2, 8) and q3.out_scale().shape == (2, 8)
    # wo shape (h, k, d), two contracted input axes -> per-d scale
    wo = rng.normal(size=(2, 8, 16)).astype(np.float32)
    qo = quantize_tensor(wo, in_axes=2)
    assert qo.scale.shape == (1, 1, 16) and qo.out_scale().shape == (16,)
    # MoE expert planes contract the MIDDLE axis: per-(expert, out) scale
    we = rng.normal(size=(4, 16, 8)).astype(np.float32)  # (E, D, F)
    qe = quantize_tensor(we, in_axes=(1,))
    assert qe.scale.shape == (4, 1, 8) and qe.out_scale().shape == (4, 8)
    err = np.abs(np.asarray(qe.dequantize()) - we)
    assert (err <= np.asarray(qe.scale) / 2 + 1e-7).all()


def test_qtensor_is_a_pytree():
    qt = quantize_tensor(np.ones((4, 4), np.float32))
    leaves = jax.tree.leaves(qt)
    assert len(leaves) == 2  # q + scale flow through jit/device_put
    moved = jax.device_put(qt)
    assert isinstance(moved, QTensor)


def _logit_agreement(model, params, qparams, x):
    dense, _ = model.apply(params, x)
    quant, _ = model.apply(qparams, x)
    return np.asarray(dense), np.asarray(quant)


def test_quantized_llama_forward_close_and_int8_stored():
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    qparams = tp.quantize_params(model, params)
    # the FFN gate/up, attention projections and lm head are int8 now
    leaves = jax.tree.leaves(
        qparams, is_leaf=lambda t: isinstance(t, QTensor))
    n_q = sum(isinstance(t, QTensor) for t in leaves)
    assert n_q >= 2 * 4 + 2 * 2 + 1  # per block: 4 attn + 2 ffn; + head
    x = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 256),
        np.int32)
    dense, quant = _logit_agreement(model, params, qparams, x)
    # int8 weights: logits close, argmax token identical except at
    # near-ties.  On a tiny RANDOM net several positions have a dense
    # top-1/top-2 margin inside the int8 perturbation, and which side
    # they land on varies with jax-version init numerics — so instead of
    # a flat agreement threshold, require every flip to BE a near-tie
    # (margin < the measured quantization noise).
    assert np.abs(dense - quant).max() < 0.15 * np.abs(dense).max()
    agree = dense.argmax(-1) == quant.argmax(-1)
    top2 = np.sort(dense, axis=-1)
    margin = top2[..., -1] - top2[..., -2]
    noise = np.abs(dense - quant).max()
    assert (margin[~agree] < noise).all(), (
        f"argmax flipped outside quantization noise: margins "
        f"{margin[~agree]} vs noise {noise}")
    assert agree.mean() > 0.9, f"top-1 agreement {agree.mean()}"


def test_quantized_decode_matches_quantized_forward():
    """The KV-cache decode path applies the same quantized weights as the
    batch forward — generate() from int8 params equals greedy decode on
    the quantized logits."""
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    qparams = tp.quantize_params(model, params)
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 256),
        np.int32)
    out_q = np.asarray(tp.generate(model, qparams, prompt, 8))  # (B, 8)
    # reference: greedy argmax rollout on the quantized FORWARD path
    toks = prompt.copy()
    for _ in range(8):
        logits, _ = model.apply(qparams, jnp.asarray(toks))
        nxt = np.asarray(logits)[:, -1].argmax(-1).astype(np.int32)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out_q, toks[:, prompt.shape[1]:])


def test_prune_then_quantize_composes_and_reverse_refuses():
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    # prune 25% of one FFN's channels, then quantize the pruned model
    from torchpruner_tpu.attributions import WeightNormAttributionMetric
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    scores = WeightNormAttributionMetric(
        model, params, [], lm_cross_entropy_loss).run("block1_ffn/gate")
    res = tp.prune_by_scores(model, params, "block1_ffn/gate", scores,
                             policy="fraction", fraction=0.25)
    qparams = tp.quantize_params(res.model, res.params)
    prompt = np.asarray([[1, 2, 3, 4]], np.int32)
    out = tp.generate(res.model, qparams, prompt, 4)
    assert np.asarray(out).shape == (1, 4)  # (B, n_new)
    # pruning AFTER quantization must refuse loudly, not corrupt
    with pytest.raises(ValueError, match="prune BEFORE"):
        tp.prune_by_scores(model, tp.quantize_params(model, params),
                           "block1_ffn/gate", scores,
                           policy="fraction", fraction=0.25)


@pytest.mark.parametrize("dispatch", ["dense", "sparse"])
def test_quantized_moe_close_to_dequantized(dispatch):
    """Expert-plane int8: the output-side rescaling (trailing-broadcast
    in the dense formulation, positional keepdims in the sparse dispatch
    buffers) equals applying the dequantized weights, both dispatches."""
    from torchpruner_tpu.models import llama_moe_tiny

    model = llama_moe_tiny(dispatch=dispatch)
    params, _ = init_model(model, seed=0)
    qparams = tp.quantize_params(model, params)
    x = np.asarray(
        jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 256),
        np.int32)
    quant, _ = model.apply(qparams, x)
    deq, _ = model.apply(tp.dequantize_params(qparams), x)
    # same weights, two evaluation orders -> tight tolerance
    np.testing.assert_allclose(np.asarray(quant), np.asarray(deq),
                               rtol=2e-4, atol=2e-4)
    dense_out, _ = model.apply(params, x)
    dense_out = np.asarray(dense_out)
    # vs the float model, int8 error is bounded per matmul — but the
    # routers' top-k is DISCRETE: a token whose router logits sit at a
    # near-tie swaps its whole expert set under the (tiny) quantization
    # perturbation (measured: a 0.02 router-logit shift flips 1/16
    # tokens and turns a 4% max-logit error into 24%).  That is routing
    # chaos on a random net, not quantization infidelity (the tight
    # quant-vs-dequant check above pins the fidelity), so bound the
    # non-flipped majority tightly and the flipped tail loosely.
    tok_err = np.abs(dense_out - np.asarray(quant)).max(-1)  # (B, S)
    scale = np.abs(dense_out).max()
    assert (tok_err < 0.15 * scale).mean() >= 0.8, (
        f"per-token rel errs {np.sort(tok_err / scale)[::-1][:4]}")
    assert tok_err.max() < scale  # flips reroute tokens, never corrupt


def test_quantize_layers_subset_and_dequantize_roundtrip():
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    # typo'd layer names refuse instead of silently deploying unquantized
    with pytest.raises(KeyError, match="no quantizable layer"):
        tp.quantize_params(model, params, layers=["block1_ffn/gates"])
    qp = tp.quantize_params(model, params, layers=["block1_ffn/gate"])
    assert isinstance(qp["block1_ffn"]["gate"]["wg"], QTensor)
    assert not isinstance(qp["block2_ffn"]["gate"]["wg"], QTensor)
    back = tp.dequantize_params(qp)
    # dequantized pytree has the original structure and close values
    w0 = np.asarray(params["block1_ffn"]["gate"]["wg"])
    w1 = np.asarray(back["block1_ffn"]["gate"]["wg"])
    assert w1.dtype == np.float32 and w0.shape == w1.shape
    assert np.abs(w0 - w1).max() <= np.abs(w0).max() / 127 + 1e-7


def test_int4_storage_halves_and_serves():
    """bits=4: packed payloads store half the int8 bytes at rest; the
    model serves through the same wval/oscale sites (unpack producer),
    with error bounded by the coarser grid."""
    import numpy as np

    from torchpruner_tpu.generate import generate
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.ops.quant import QTensor, quantize_params

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    q8 = quantize_params(model, params)
    q4 = quantize_params(model, params, bits=4)

    n8 = sum(l.q.nbytes for l in jax.tree_util.tree_leaves(
        q8, is_leaf=lambda x: isinstance(x, QTensor))
        if isinstance(l, QTensor))
    n4 = sum(l.q.nbytes for l in jax.tree_util.tree_leaves(
        q4, is_leaf=lambda x: isinstance(x, QTensor))
        if isinstance(l, QTensor))
    assert n4 * 2 == n8, (n4, n8)

    toks = model.example_input(2, seed=0)
    ref, _ = model.apply(params, toks)
    y4, _ = model.apply(q4, toks)
    # the quantized SERVING path must be exact against its own
    # dequantized reference (the lossiness lives in the grid, not the
    # plumbing); vs the original, int4's error is bounded by ~the
    # int8 error x the grid ratio (measured: 0.22 -> 2.47 here)
    from torchpruner_tpu.ops.quant import dequantize_params

    yd, _ = model.apply(dequantize_params(q4), toks)
    assert float(jnp.max(jnp.abs(y4 - yd))) < 1e-4
    assert float(jnp.max(jnp.abs(y4 - ref))) < 8.0

    out = generate(model, q4, np.asarray(toks)[:, :4], 6)
    assert out.shape == (2, 6)


def test_int4_pytree_roundtrip_keeps_bits():
    from torchpruner_tpu.ops.quant import quantize_tensor

    t = quantize_tensor(jnp.ones((8, 6)), in_axes=(0,), bits=4)
    assert t.bits == 4 and t.q.shape == (4, 6) and t.shape == (8, 6)
    leaves, treedef = jax.tree_util.tree_flatten(t)
    t2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert t2.bits == 4 and t2.pack_axis == 0
    np.testing.assert_array_equal(np.asarray(t2.unpacked()),
                                  np.asarray(t.unpacked()))


def test_int4_packs_middle_axis_and_rejects_odd():
    from torchpruner_tpu.ops.quant import quantize_tensor

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(3, 10, 5)).astype(np.float32))
    t = quantize_tensor(w, in_axes=(1,), bits=4)  # MoE wg layout
    assert t.pack_axis == 1 and t.q.shape == (3, 5, 5)
    deq = np.asarray(t.dequantize())
    assert np.max(np.abs(deq - np.asarray(w))) <= np.asarray(
        t.scale).max() * 0.5 + 1e-6

    with pytest.raises(ValueError, match="even-length"):
        quantize_tensor(jnp.ones((5, 4)), in_axes=(0,), bits=4)


def test_int4_dense_kernel_path_matches_unpack_path():
    """bf16 activations route Dense/GatedDense int4 weights through the
    fused kernel; the result must match the XLA unpack formulation at
    the same (bf16 operand) precision."""
    from torchpruner_tpu.ops.quant import qdot, quantize_tensor, wval

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(512, 512)).astype(np.float32))
    t = quantize_tensor(w, in_axes=(0,), bits=4)
    x = jnp.asarray(rng.normal(size=(4, 512)).astype(np.float32))
    via_kernel = qdot(x.astype(jnp.bfloat16), t)
    via_unpack = (x.astype(jnp.bfloat16)
                  @ wval(t, jnp.bfloat16)).astype(jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(via_kernel, np.float32),
                               np.asarray(via_unpack, np.float32),
                               rtol=3e-2, atol=3e-1)
    # f32 activations take the exact unpack path
    np.testing.assert_allclose(
        np.asarray(qdot(x, t)), np.asarray(x @ wval(t, x.dtype)),
        rtol=1e-6, atol=1e-6)


def test_qtensor_unflattens_legacy_aux_format():
    """Treedefs serialized before bits/pack_axis existed carried the bare
    in_axes tuple as aux_data; they must still unflatten (bits=8)."""
    from torchpruner_tpu.ops.quant import QTensor

    q = jnp.zeros((4, 2), jnp.int8)
    scale = jnp.ones((1, 2), jnp.float32)
    t = QTensor.tree_unflatten((0,), (q, scale))
    assert t.in_axes == (0,) and t.bits == 8 and t.pack_axis == 0
    # and the current format still round-trips through flatten/unflatten
    t4 = QTensor(q, scale, (0,), 4, 0)
    children, aux = t4.tree_flatten()
    t4b = QTensor.tree_unflatten(aux, children)
    assert t4b.bits == 4 and t4b.pack_axis == 0 and t4b.in_axes == (0,)


def test_quantized_random_params_build_and_serve():
    """The 8B serving experiment's direct-at-quantized builder
    (experiments/llama8b_decode.py): QTensor leaves land exactly where
    quantize_params puts them, and the tree decodes through generate."""
    import jax

    from torchpruner_tpu.experiments.llama8b_decode import (
        logical_params,
        quantized_random_params,
        weight_bytes,
    )
    from torchpruner_tpu.generate import generate
    from torchpruner_tpu.models import llama

    model = llama(vocab_size=64, dim=16, depth=2, num_heads=2,
                  num_kv_heads=1, head_dim=8, ffn_dim=32, seq_len=32)
    params, state = quantized_random_params(model, bits=4, seed=1)
    assert state == {}

    from torchpruner_tpu.ops.quant import QTensor

    # every attention/FFN matmul weight is a QTensor; norms/embedding not
    blk = params["block1_attn"]
    assert all(isinstance(blk["attn"][k], QTensor)
               for k in ("wq", "wk", "wv", "wo"))
    assert not isinstance(blk["norm"]["scale"], QTensor)
    ffn = params["block1_ffn"]
    assert all(isinstance(ffn["gate"][k], QTensor) for k in ("wg", "wu"))
    assert isinstance(ffn["down"]["w"], QTensor)
    assert isinstance(params["lm_head"]["w"], QTensor)
    assert not isinstance(params["tok_emb"]["emb"], QTensor)

    # logical count equals the float model's count; bytes roughly halve
    # the int8 representation (packed axis) for the quantized majority
    ref_params, _ = model.init(jax.random.PRNGKey(0))
    from torchpruner_tpu.utils.flops import param_count

    assert logical_params(params) == param_count(ref_params)
    assert weight_bytes(params) < param_count(ref_params)  # < 1 B/param

    toks = generate(model, params, jnp.zeros((2, 4), jnp.int32), 4)
    assert toks.shape == (2, 4)


def test_qdot_3d_weight_kernel_path_matches_tensordot():
    """Attention-shaped (d, H, Dh) int4 weights flatten onto the fused
    kernel (packing pairs along axis 0 survive a trailing-axes flatten);
    the result must match the XLA tensordot formulation, and float 3-D
    weights must take the same contraction."""
    from torchpruner_tpu.ops.quant import qdot, quantize_tensor, wval

    rng = np.random.default_rng(5)
    d, H, Dh = 512, 4, 128
    w = jnp.asarray(rng.normal(size=(d, H, Dh)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(2, 3, d)).astype(np.float32))
    t = quantize_tensor(w, in_axes=(0,), bits=4)

    via_kernel = qdot(x.astype(jnp.bfloat16), t)
    assert via_kernel.shape == (2, 3, H, Dh)
    via_unpack = jnp.tensordot(x.astype(jnp.bfloat16),
                               wval(t, jnp.bfloat16), axes=(2, 0))
    np.testing.assert_allclose(np.asarray(via_kernel, np.float32),
                               np.asarray(via_unpack, np.float32),
                               rtol=3e-2, atol=3e-1)
    # float 3-D weight: plain tensordot
    np.testing.assert_allclose(
        np.asarray(qdot(x, w)),
        np.asarray(jnp.tensordot(x, w, axes=(2, 0))),
        rtol=1e-6, atol=1e-5)

"""Model-zoo tests for the BASELINE.json capability families (ResNet, ViT,
BERT, Llama): forward shapes, static pruning-graph structure, and structural
pruning correctness (prune-vs-mask equivalence — the composite-model analog
of the reference's NaN-cascade tests, reference tests/test_pruner.py:72-121).

Full-size specs (resnet50 / vit_b16 / bert_base / llama3_8b) are checked
*statically* — graph structure and parameter counts from the specs alone —
so no big array is ever materialized on the test CPU.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.graph import group_for, pruning_graph
from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.models import (
    bert_base,
    bert_tiny,
    llama3_8b,
    llama_tiny,
    resnet20_cifar,
    resnet50,
    vit_b16,
    vit_tiny,
)
from torchpruner_tpu.utils.losses import lm_cross_entropy_loss


def spec_param_count(model: SegmentedModel) -> int:
    """Parameter count from the static spec (no arrays materialized)."""

    def count(layers, in_shape):
        total = 0
        shape = tuple(in_shape)
        for spec in layers:
            if isinstance(spec, L.Residual):
                total += count(spec.body, shape)
                total += count(spec.shortcut, shape)
            else:
                total += _layer_params(spec, shape)
            shape = L.out_shape(spec, shape)
        return total

    return count(model.layers, model.input_shape)


def _layer_params(spec, in_shape):
    d = in_shape[-1] if in_shape else 0
    if isinstance(spec, L.Dense):
        return d * spec.features + (spec.features if spec.use_bias else 0)
    if isinstance(spec, L.Conv):
        kh, kw = spec.kernel_size
        return kh * kw * d * spec.features + (
            spec.features if spec.use_bias else 0
        )
    if isinstance(spec, L.BatchNorm):
        return 2 * d
    if isinstance(spec, L.LayerNorm):
        return d * (2 if spec.use_bias else 1)
    if isinstance(spec, L.RMSNorm):
        return d
    if isinstance(spec, L.Embedding):
        return spec.vocab_size * spec.features
    if isinstance(spec, L.PosEmbed):
        return spec.max_len * d
    if isinstance(spec, L.ClsToken):
        return d
    if isinstance(spec, L.MultiHeadAttention):
        H, KV, Dh = spec.num_heads, spec.kv_heads, spec.head_dim
        d_out = spec.out_features if spec.out_features is not None else d
        n = d * H * Dh + 2 * d * KV * Dh + H * Dh * d_out
        if spec.use_bias:
            n += H * Dh + 2 * KV * Dh + d_out
        return n
    if isinstance(spec, L.GatedDense):
        return 2 * d * spec.features + (
            2 * spec.features if spec.use_bias else 0
        )
    return 0


# ---------------------------------------------------------------------------
# ResNet
# ---------------------------------------------------------------------------


def test_resnet20_forward_and_graph():
    model = resnet20_cifar()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y, _ = model.apply(params, x, state=state)
    assert y.shape == (2, 10)
    graph = pruning_graph(model)
    targets = [g.target for g in graph]
    # stem feeds stage1_block1 through an *identity* skip (16 -> 16, stride
    # 1) so it is width-pinned; interior conv1s are prunable, conv2s (feeding
    # the residual sum) are not.
    assert "stem" not in targets
    assert "stage1_block1/conv1" in targets
    assert all(not t.endswith("/conv2") for t in targets)
    # 9 blocks, one prunable conv each
    assert len(targets) == 9


def test_resnet20_prune_block_conv_then_forward():
    model = resnet20_cifar()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    target = "stage2_block1/conv1"
    g = group_for(model, target)
    assert any(c.layer == "stage2_block1/conv2" for c in g.consumers)
    res = prune(model, params, target, [0, 3, 7], state=state)
    assert res.model.layer(target).features == 32 - 3
    y, _ = res.model.apply(res.params, x, state=res.state)
    assert y.shape == (2, 10)


def test_resnet20_prune_vs_mask_equivalence():
    """Zeroing units of an interior block conv == pruning them (eval mode):
    the consumer slice removes exactly the masked contributions."""
    model = resnet20_cifar()
    params, state = init_model(model, seed=1)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 32, 3))
    target = "stage1_block2/conv1"
    drop = [1, 5, 11]
    keep_mask = jnp.ones((16,)).at[jnp.asarray(drop)].set(0.0)
    y_masked, _ = model.apply(
        params, x, state=state, unit_mask=(target, keep_mask)
    )
    res = prune(model, params, target, drop, state=state)
    y_pruned, _ = res.model.apply(res.params, x, state=res.state)
    np.testing.assert_allclose(
        np.asarray(y_masked), np.asarray(y_pruned), atol=1e-4
    )


def test_digits_convnet_conv_flatten_cascade_and_mask_equivalence():
    """The conv+BN parity model (8x8 real-digits family): pruning conv2
    must cascade through pool2 -> flatten into fc1's input with the 2x2
    spatial fan-out, and equal masking the same channels (eval mode)."""
    from torchpruner_tpu.models import digits_convnet

    model = digits_convnet()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 1))
    g = group_for(model, "conv2")
    fc1 = [c for c in g.consumers if c.layer == "fc1"]
    assert fc1 and fc1[0].fan_out == 4  # 2x2 post-pool spatial positions

    drop = [0, 9, 31]
    keep_mask = jnp.ones((32,)).at[jnp.asarray(drop)].set(0.0)
    y_masked, _ = model.apply(
        params, x, state=state, unit_mask=("conv2", keep_mask)
    )
    res = prune(model, params, "conv2", drop, state=state)
    assert res.model.layer("conv2").features == 29
    assert res.params["fc1"]["w"].shape[0] == 29 * 4
    y_pruned, _ = res.model.apply(res.params, x, state=res.state)
    np.testing.assert_allclose(
        np.asarray(y_masked), np.asarray(y_pruned), atol=1e-4
    )


def test_resnet50_static_structure():
    model = resnet50()
    # 16 bottleneck blocks x 2 prunable convs each, + prunable stem (the
    # first block has a projection shortcut, so the stem cascades into it)
    graph = pruning_graph(model)
    targets = [g.target for g in graph]
    assert "stem" in targets
    assert len(targets) == 1 + 2 * 16
    stem = group_for(model, "stem")
    consumer_layers = {c.layer for c in stem.consumers}
    assert consumer_layers == {
        "stage1_block1/conv1", "stage1_block1/proj"
    }
    n = spec_param_count(model)
    assert abs(n - 25.56e6) / 25.56e6 < 0.01  # torchvision: 25,557,032


# ---------------------------------------------------------------------------
# ViT
# ---------------------------------------------------------------------------


def test_vit_tiny_forward_and_prune_groups():
    model = vit_tiny()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    y, _ = model.apply(params, x, state=state)
    assert y.shape == (2, 10)
    targets = [g.target for g in pruning_graph(model)]
    # per block: one head group + one MLP hidden group
    assert "block1_attn/attn" in targets
    assert "block1_mlp/fc1" in targets
    assert len(targets) == 2 * 2


def test_vit_tiny_prune_heads_and_mlp():
    model = vit_tiny()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    y0, _ = model.apply(params, x, state=state)
    res = prune(model, params, "block1_attn/attn", [2], state=state)
    res2 = prune(
        res.model, res.params, "block2_mlp/fc1", [0, 9, 33], state=res.state
    )
    assert res2.model.layer("block1_attn/attn").num_heads == 3
    assert res2.model.layer("block2_mlp/fc1").features == 61
    y, _ = res2.model.apply(res2.params, x, state=res2.state)
    assert y.shape == (2, 10)
    assert np.all(np.isfinite(np.asarray(y)))


def test_vit_tiny_head_prune_vs_mask_equivalence():
    model = vit_tiny()
    params, state = init_model(model, seed=3)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 16, 16, 3))
    site = "block2_attn/attn"
    mask = jnp.ones((4,)).at[1].set(0.0)
    y_masked, _ = model.apply(params, x, state=state, unit_mask=(site, mask))
    res = prune(model, params, site, [1], state=state)
    y_pruned, _ = res.model.apply(res.params, x, state=res.state)
    np.testing.assert_allclose(
        np.asarray(y_masked), np.asarray(y_pruned), atol=1e-5
    )


def test_vit_b16_static_structure():
    model = vit_b16()
    targets = [g.target for g in pruning_graph(model)]
    assert len(targets) == 2 * 12
    n = spec_param_count(model)
    assert abs(n - 86.6e6) / 86.6e6 < 0.01  # ViT-B/16: ~86.6M


# ---------------------------------------------------------------------------
# BERT
# ---------------------------------------------------------------------------


def test_bert_tiny_forward_and_linear_pruning():
    model = bert_tiny()
    params, state = init_model(model, seed=0)
    x = model.example_input(3)
    y, _ = model.apply(params, x, state=state)
    assert y.shape == (3, 2)
    # the BASELINE "Linear-layer pruning" target: fc1 with fc2 consumer
    g = group_for(model, "block1_mlp/fc1")
    assert any(c.layer == "block1_mlp/fc2" for c in g.consumers)
    res = prune(model, params, "block1_mlp/fc1", list(range(16)), state=state)
    assert res.model.layer("block1_mlp/fc1").features == 48
    y2, _ = res.model.apply(res.params, x, state=res.state)
    assert y2.shape == (3, 2)


def test_bert_tiny_fc1_prune_vs_mask_equivalence():
    model = bert_tiny()
    params, state = init_model(model, seed=1)
    x = model.example_input(2, seed=5)
    drop = [0, 7, 40]
    mask = jnp.ones((64,)).at[jnp.asarray(drop)].set(0.0)
    y_masked, _ = model.apply(
        params, x, state=state, unit_mask=("block2_mlp/fc1", mask)
    )
    res = prune(model, params, "block2_mlp/fc1", drop, state=state)
    y_pruned, _ = res.model.apply(res.params, x, state=res.state)
    np.testing.assert_allclose(
        np.asarray(y_masked), np.asarray(y_pruned), atol=1e-5
    )


def test_bert_base_static_structure():
    model = bert_base()
    targets = [g.target for g in pruning_graph(model)]
    # per block: head group + MLP hidden group; plus the prunable pooler
    # (the classification head itself is excluded as the output layer)
    assert len(targets) == 2 * 12 + 1 and "pooler" in targets
    n = spec_param_count(model)
    # BERT-base encoder + pooler (no token-type embs, no MLM head): ~109M
    assert abs(n - 109e6) / 109e6 < 0.02


# ---------------------------------------------------------------------------
# Llama
# ---------------------------------------------------------------------------


def test_llama_tiny_forward_loss_and_causality():
    model = llama_tiny()
    params, state = init_model(model, seed=0)
    x = model.example_input(2)
    y, _ = model.apply(params, x, state=state)
    assert y.shape == (2, 16, 256)
    loss = lm_cross_entropy_loss(y, x)
    assert loss.shape == (2,) and np.all(np.isfinite(np.asarray(loss)))
    # causality: changing the last token must not affect earlier logits
    x2 = np.asarray(x).copy()
    x2[:, -1] = (x2[:, -1] + 1) % 256
    y2, _ = model.apply(params, jnp.asarray(x2), state=state)
    np.testing.assert_allclose(
        np.asarray(y[:, :-1]), np.asarray(y2[:, :-1]), atol=1e-5
    )
    assert not np.allclose(np.asarray(y[:, -1]), np.asarray(y2[:, -1]))


def test_llama_tiny_ffn_channel_pruning():
    model = llama_tiny()
    params, state = init_model(model, seed=0)
    x = model.example_input(2)
    g = group_for(model, "block1_ffn/gate")
    assert any(c.layer == "block1_ffn/down" for c in g.consumers)
    drop = [0, 13, 50, 63]
    mask = jnp.ones((64,)).at[jnp.asarray(drop)].set(0.0)
    y_masked, _ = model.apply(
        params, x, state=state, unit_mask=("block1_ffn/gate", mask)
    )
    res = prune(model, params, "block1_ffn/gate", drop, state=state)
    assert res.model.layer("block1_ffn/gate").features == 60
    assert res.params["block1_ffn"]["down"]["w"].shape[0] == 60
    y_pruned, _ = res.model.apply(res.params, x, state=res.state)
    np.testing.assert_allclose(
        np.asarray(y_masked), np.asarray(y_pruned), atol=1e-5
    )


def test_llama_tiny_gqa_head_pruning():
    model = llama_tiny()  # 4 query heads, 2 KV heads
    params, state = init_model(model, seed=0)
    x = model.example_input(2)
    res = prune(model, params, "block2_attn/attn", [1], state=state)
    spec = res.model.layer("block2_attn/attn")
    assert spec.num_heads == 3
    # surviving heads keep their original KV assignments
    assert spec.head_kv_index() == (0, 1, 1)
    y, _ = res.model.apply(res.params, x, state=res.state)
    assert y.shape == (2, 16, 256)
    assert np.all(np.isfinite(np.asarray(y)))


def test_attributions_on_nested_sites():
    """Data-dependent metrics score nested (in-Residual) and attention-head
    sites via the tap path; weight-norm resolves nested params."""
    from torchpruner_tpu import (
        ShapleyAttributionMetric,
        TaylorAttributionMetric,
        WeightNormAttributionMetric,
    )
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    model = vit_tiny()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 16, 3))
    y = jnp.arange(4) % 10
    data = [(x, y)]
    t = TaylorAttributionMetric(
        model, params, data, cross_entropy_loss, state=state
    )
    assert t.run("block1_mlp/fc1").shape == (64,)
    assert t.run("block1_attn/attn").shape == (4,)
    sv = ShapleyAttributionMetric(
        model, params, data, cross_entropy_loss, state=state, sv_samples=2
    )
    assert sv.run("block2_attn/attn").shape == (4,)
    wn = WeightNormAttributionMetric(
        model, params, data, cross_entropy_loss, state=state
    )
    assert wn.run("block1_mlp/fc1").shape == (64,)
    assert wn.run("block1_attn/attn").shape == (4,)


def test_nested_taylor_matches_topLevel_equivalent():
    """The tap-based gradient path must agree with the segment-based path:
    score the same Dense both ways by building the same net flat vs wrapped
    in a size-1 'residual' (body-only, zero shortcut is not expressible, so
    compare tap path on a top-level layer instead: force taps via the
    attention-free nested check is impossible — use a flat model and compare
    grad_rows_fn tap mode against segment mode directly)."""
    from torchpruner_tpu.attributions.activation import grad_rows_fn
    from torchpruner_tpu.models import mnist_fc
    from torchpruner_tpu.utils.losses import cross_entropy_loss
    from torchpruner_tpu.models.mlp import fc_net

    model = fc_net(20, hidden=(8, 8), n_classes=4)
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (6, 20))
    y = jnp.arange(6) % 4
    seg = grad_rows_fn(model, "fc1", cross_entropy_loss, "taylor")
    # build the tap-mode function by hand (what nested sites use)
    import torchpruner_tpu.attributions.activation as act

    orig = act.needs_taps
    act.needs_taps = lambda m, l: True
    try:
        grad_rows_fn.cache_clear()
        tap = grad_rows_fn(model, "fc1", cross_entropy_loss, "taylor")
    finally:
        act.needs_taps = orig
        grad_rows_fn.cache_clear()
    np.testing.assert_allclose(
        np.asarray(seg(params, state, x, y)),
        np.asarray(tap(params, state, x, y)),
        atol=1e-5,
    )


def test_llama3_8b_static_structure():
    model = llama3_8b()
    targets = [g.target for g in pruning_graph(model)]
    # per block: head group + FFN group; lm_head excluded as output layer
    assert len(targets) == 2 * 32
    n = spec_param_count(model)
    assert abs(n - 8.03e6 * 1000) / 8.03e9 < 0.01  # Llama-3-8B: 8.03B

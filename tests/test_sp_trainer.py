"""Sequence-parallel training tests: the shard_map'd SP step (ring and
ulysses attention cores, RoPE at global offsets, psum'd loss/grads) must
track the single-device training trajectory, and compose with pruning."""

import numpy as np
import jax
import optax
import pytest

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.models import llama_tiny
from torchpruner_tpu.parallel import SPTrainer, make_mesh, sp_model
from torchpruner_tpu.train import Trainer
from torchpruner_tpu.utils.losses import lm_cross_entropy_loss


def toks(B=4, S=16, seed=0):
    return np.asarray(
        jax.random.randint(jax.random.PRNGKey(seed), (B, S), 0, 256),
        np.int32,
    )


@pytest.mark.parametrize("impl,seq", [("ring", 4), ("ulysses", 2)])
def test_sp_trainer_matches_single_device(impl, seq):
    mesh = make_mesh({"data": 2, "seq": seq},
                     devices=jax.devices()[:2 * seq])
    tx = optax.adam(1e-2)
    t_ref = Trainer.create(llama_tiny(), tx, lm_cross_entropy_loss, seed=0)
    t_sp = SPTrainer.create(llama_tiny(), tx, mesh, seed=0, impl=impl)

    for step_seed in range(3):
        batch = toks(seed=step_seed)
        l_ref = float(t_ref.step(batch, batch))
        l_sp = float(t_sp.step(batch))
        np.testing.assert_allclose(l_ref, l_sp, rtol=1e-4)

    w_ref = np.asarray(t_ref.params["block1_ffn"]["gate"]["wg"])
    w_sp = np.asarray(t_sp.params["block1_ffn"]["gate"]["wg"])
    np.testing.assert_allclose(w_ref, w_sp, rtol=1e-3, atol=1e-5)


def test_sp_trainer_prune_rebuild_recompile():
    """FFN pruning composes with SP: prune, rebuild, step again."""
    mesh = make_mesh({"data": 2, "seq": 4})
    t = SPTrainer.create(llama_tiny(), optax.adam(1e-3), mesh, seed=0)
    batch = toks()
    l0 = float(t.step(batch))
    r = prune(t.model, t.params, "block1_ffn/gate", [0, 7, 21],
              state=t.state, opt_state=t.opt_state)
    t = t.rebuild(r.model, r.params, r.state, r.opt_state)
    l1 = float(t.step(batch))
    assert np.isfinite(l0) and np.isfinite(l1)
    assert t.model.layer("block1_ffn/gate").features == 61


def test_sp_trainer_remat_and_bf16():
    """remat must not change the SP loss; bf16 mixed precision runs and
    stays close to f32 (bf16 noise level)."""
    import jax.numpy as jnp

    mesh = make_mesh({"data": 2, "seq": 4})
    batch = toks()
    base = float(SPTrainer.create(
        llama_tiny(), optax.adam(1e-3), mesh, seed=0).step(batch))
    rem = float(SPTrainer.create(
        llama_tiny(), optax.adam(1e-3), mesh, seed=0, remat=True
    ).step(batch))
    np.testing.assert_allclose(base, rem, rtol=1e-5)
    b16 = float(SPTrainer.create(
        llama_tiny(), optax.adam(1e-3), mesh, seed=0,
        compute_dtype=jnp.bfloat16,
    ).step(batch))
    assert np.isfinite(b16) and abs(b16 - base) < 0.1


def test_sp_trainer_evaluate_runs_single_device_core():
    """evaluate() reverts attention to the single-device core and must
    agree with the reference trainer's evaluation."""
    mesh = make_mesh({"data": 2, "seq": 4})
    tx = optax.adam(1e-2)
    t_sp = SPTrainer.create(llama_tiny(), tx, mesh, seed=0)
    t_ref = Trainer.create(llama_tiny(), tx, lm_cross_entropy_loss, seed=0)
    batch = toks()
    data = [(batch, batch)]
    l_sp, a_sp = t_sp.evaluate(data, lm_cross_entropy_loss)
    l_ref, a_ref = t_ref.evaluate(data)
    np.testing.assert_allclose(l_sp, l_ref, rtol=1e-5)
    assert a_sp == a_ref


def test_sp_model_converts_nested_attention():
    m = sp_model(llama_tiny(), "ring")
    assert m.layer("block1_attn/attn").impl == "ring"
    assert m.layer("block2_attn/attn").impl == "ring"
    with pytest.raises(ValueError, match="impl"):
        sp_model(llama_tiny(), "nope")


def test_sp_model_outside_shard_map_raises_clear_error():
    """Applying an SP-impl model outside shard_map must explain the fix
    (sp_model(model, 'auto')), not raise jax's unbound-axis NameError."""
    from torchpruner_tpu.core.segment import init_model

    m = sp_model(llama_tiny(), "ring")
    params, state = init_model(llama_tiny(), seed=0)
    with pytest.raises(RuntimeError, match="sp_model"):
        m.apply(params, toks(B=1, S=8), state=state)


def test_sp_trainer_requires_axes():
    mesh = make_mesh({"data": 8})
    with pytest.raises(ValueError, match="seq"):
        SPTrainer.create(llama_tiny(), optax.adam(1e-3), mesh)


def test_sp_trainer_rejects_batchnorm_models():
    """Per-shard-divergent running stats would silently come back as one
    shard's values under the replicated out_specs — must error instead."""
    from torchpruner_tpu.models import fmnist_convnet

    mesh = make_mesh({"data": 2, "seq": 4})
    with pytest.raises(NotImplementedError, match="BatchNorm"):
        SPTrainer.create(fmnist_convnet(), optax.adam(1e-3), mesh)


def test_sp_attention_rejects_taps():
    """Attribution taps under SP are unsupported — the error must be
    explicit, not silently-local scores."""
    model = sp_model(llama_tiny(), "ring")
    from torchpruner_tpu.core.segment import init_model

    params, state = init_model(llama_tiny(), seed=0)
    mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
    from torchpruner_tpu.parallel.mesh import relaxed_shard_map
    from jax.sharding import PartitionSpec as P

    def run(x):
        return model.apply(
            params, x, state=state,
            unit_mask=("block1_attn/attn", np.ones((4,), np.float32)),
        )[0]

    fn = relaxed_shard_map(run, mesh, in_specs=(P(None, "seq"),),
                           out_specs=P(None, "seq"))
    with pytest.raises(NotImplementedError, match="taps"):
        fn(toks())

"""Torch checkpoint import: a torchvision-layout VGG16-bn state_dict maps
onto the framework's (params, state) and the two frameworks' forwards
agree — the migration path for the reference's pretrained model."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from torchpruner_tpu.utils.torch_import import (
    _flatten_perm,
    import_torch_vgg16_bn,
)

VGG16_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
             512, 512, 512, "M", 512, 512, 512, "M"]


def build_torch_vgg16_bn(n_classes=10, width=512):
    """The reference checkpoint's architecture via public torch.nn only
    (torchvision vgg16_bn features + the reference's 512-wide classifier,
    reference cifar10.py:62-74)."""
    import torch.nn as nn

    feats, in_c = [], 3
    for v in VGG16_CFG:
        if v == "M":
            feats.append(nn.MaxPool2d(2, 2))
        else:
            feats += [nn.Conv2d(in_c, v, 3, padding=1),
                      nn.BatchNorm2d(v), nn.ReLU(True)]
            in_c = v
    return nn.Sequential(
        nn.Sequential(*feats),
        nn.Sequential(nn.Dropout(), nn.Linear(512, width), nn.ReLU(True),
                      nn.Dropout(), nn.Linear(width, width), nn.ReLU(True),
                      nn.Linear(width, n_classes)),
    )


def _rename(sd):
    """nn.Sequential(0=features, 1=classifier) keys -> torchvision names."""
    out = {}
    for k, v in sd.items():
        k = k.replace("0.", "features.", 1) if k.startswith("0.") else \
            k.replace("1.", "classifier.", 1)
        out[k] = v
    return out


def test_vgg16_bn_import_matches_torch_forward():
    torch.manual_seed(0)
    tm = build_torch_vgg16_bn().eval()
    # exercise non-trivial BN statistics
    with torch.no_grad():
        for bn in [m for m in tm.modules()
                   if isinstance(m, torch.nn.BatchNorm2d)]:
            bn.running_mean.normal_(0, 0.1)
            bn.running_var.uniform_(0.5, 1.5)

    model, params, state = import_torch_vgg16_bn(_rename(tm.state_dict()))
    assert model.layer("conv13").features == 512
    assert model.layer("out").features == 10

    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        # torch runs NCHW; flatten happens inside Sequential boundary
        feats = tm[0](torch.from_numpy(x.transpose(0, 3, 1, 2)))
        want = tm[1](torch.flatten(feats, 1)).numpy()
    got, _ = model.apply(params, x, state=state, train=False)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-3, atol=1e-3)


def test_flatten_perm_round_trips():
    """torch C-major flatten vs our HWC flatten: permuting the Linear's
    input rows must make both paths equal for spatial maps > 1x1."""
    H, W, C = 2, 3, 4
    x = np.arange(H * W * C).reshape(H, W, C)
    torch_flat = x.transpose(2, 0, 1).reshape(-1)  # what torch sees
    ours_flat = x.reshape(-1)
    perm = _flatten_perm((H, W, C))
    np.testing.assert_array_equal(torch_flat[perm], ours_flat)


def test_import_rejects_wrong_layout():
    sd = {"features.0.weight": np.zeros((64, 3, 3, 3)),
          "features.0.bias": np.zeros((64,))}
    with pytest.raises(ValueError, match="13 conv"):
        import_torch_vgg16_bn(sd)


def test_hf_llama_import_matches_transformers_forward():
    """A HuggingFace LlamaForCausalLM state_dict (random init, tiny
    config, built locally — no network) imports onto our llama() and the
    two frameworks' logits agree."""
    transformers = pytest.importorskip("transformers")
    from transformers import LlamaConfig, LlamaForCausalLM

    from torchpruner_tpu.utils.torch_import import import_hf_llama

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(cfg).eval()

    model, params, state = import_hf_llama(
        hf.state_dict(), vocab_size=128, dim=32, depth=2, num_heads=4,
        num_kv_heads=2, ffn_dim=48, rope_theta=10000.0, seq_len=16,
    )
    x = np.random.default_rng(0).integers(0, 128, size=(2, 16))
    with torch.no_grad():
        want = hf(torch.from_numpy(x)).logits.numpy()
    got, _ = model.apply(params, x.astype(np.int32), state=state)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-3)


def test_hf_llama_import_then_prune_and_train():
    """The migration composes with the framework's defining operation:
    import -> FFN prune -> train step."""
    transformers = pytest.importorskip("transformers")
    import optax
    from transformers import LlamaConfig, LlamaForCausalLM

    from torchpruner_tpu.core.pruner import prune
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss
    from torchpruner_tpu.utils.torch_import import import_hf_llama

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=24,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        tie_word_embeddings=True, attention_bias=False, mlp_bias=False,
    )
    hf = LlamaForCausalLM(cfg)
    model, params, state = import_hf_llama(
        hf.state_dict(), vocab_size=64, dim=16, depth=1, num_heads=2,
        num_kv_heads=2, ffn_dim=24, seq_len=8,
    )
    res = prune(model, params, "block1_ffn/gate", [0, 5], state=state)
    t = Trainer.create(res.model, optax.adam(1e-3), lm_cross_entropy_loss,
                       params=res.params, state=res.state)
    x = np.random.default_rng(0).integers(0, 64, size=(4, 8)).astype(np.int32)
    l0 = float(t.step(x, x))
    l1 = float(t.step(x, x))
    assert np.isfinite(l0) and l1 < l0


def test_import_handles_bf16_checkpoints():
    """Real llama3 checkpoints ship torch bfloat16 — the importer must
    widen, not crash."""
    transformers = pytest.importorskip("transformers")
    from transformers import LlamaConfig, LlamaForCausalLM

    from torchpruner_tpu.utils.torch_import import import_hf_llama

    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=24,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        tie_word_embeddings=True, attention_bias=False, mlp_bias=False,
    )).to(torch.bfloat16)
    model, params, _ = import_hf_llama(
        hf.state_dict(), vocab_size=64, dim=16, depth=1, num_heads=2,
        num_kv_heads=2, ffn_dim=24, seq_len=8,
    )
    x = np.zeros((1, 8), np.int32)
    out, _ = model.apply(params, x)
    assert np.isfinite(np.asarray(out)).all()


def test_vgg16_bn_import_from_saved_checkpoint_file(tmp_path):
    """End-to-end through a genuine ``.pth`` file: ``torch.save`` the
    state_dict, ``torch.load`` it back (the reference's pretrained-VGG
    flow, reference VGG notebook cell 4), import, and check forward
    parity — the file round trip is what a migrating user actually does."""
    torch.manual_seed(1)
    tm = build_torch_vgg16_bn().eval()
    with torch.no_grad():
        for bn in [m for m in tm.modules()
                   if isinstance(m, torch.nn.BatchNorm2d)]:
            bn.running_mean.normal_(0, 0.1)
            bn.running_var.uniform_(0.5, 1.5)

    ckpt = tmp_path / "cifar10_vgg16_bn.pth"
    torch.save(_rename(tm.state_dict()), ckpt)
    loaded = torch.load(ckpt, map_location="cpu")
    model, params, state = import_torch_vgg16_bn(loaded)

    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        feats = tm[0](torch.from_numpy(x.transpose(0, 3, 1, 2)))
        want = tm[1](torch.flatten(feats, 1)).numpy()
    got, _ = model.apply(params, x, state=state, train=False)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-3, atol=1e-3)

"""Resilience layer: manifests, chaos injection, guards, retry, and the
resumable pipelines (crash-resume equality is the headline: a SIGKILLed
retrain resumed from its manifest reaches the same final eval loss as an
uninterrupted run)."""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.resilience import (
    ChaosConfig,
    NonFiniteStreakError,
    PreemptionHandler,
    RetryPolicy,
    RunManifest,
    StepGuard,
    atomic_write_json,
    chaos,
    is_oom_error,
    retry_call,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    yield
    chaos.disable()  # never leak an injection into the next test


def _train_cfg(run_dir, **kw):
    from torchpruner_tpu.utils.config import ExperimentConfig

    base = dict(
        name="res_test", model="digits_fc_tiny", dataset="digits_flat",
        experiment="train", epochs=1, batch_size=32, eval_batch_size=64,
        lr=0.05, run_dir=str(run_dir), checkpoint_every_steps=10,
        log_path=os.path.join(str(run_dir), "log.csv"),
    )
    base.update(kw)
    return ExperimentConfig(**base)


# -- manifest ----------------------------------------------------------------


def test_manifest_roundtrip_and_kind_guard(tmp_path):
    m = RunManifest(kind="train", experiment="e", epoch=3, batch_cursor=7,
                    completed=["fc1"], lr_scale=0.25)
    m.save(str(tmp_path))
    m2 = RunManifest.load(str(tmp_path))
    assert (m2.epoch, m2.batch_cursor, m2.completed, m2.lr_scale) == \
        (3, 7, ["fc1"], 0.25)
    # same dir, different driver kind: refused
    with pytest.raises(ValueError, match="refusing to resume"):
        RunManifest.load_or_new(str(tmp_path), kind="robustness",
                                experiment="e")


def test_atomic_write_json_never_leaves_partials(tmp_path):
    p = tmp_path / "x.json"
    atomic_write_json(str(p), {"a": 1})
    atomic_write_json(str(p), {"a": 2})
    assert json.load(open(p)) == {"a": 2}
    # no tmp litter
    assert [f for f in os.listdir(tmp_path) if f.startswith(".tmp.")] == []


# -- retry -------------------------------------------------------------------


def test_retry_recovers_transient_and_reraises_persistent():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    slept = []
    assert retry_call(flaky, policy=RetryPolicy(tries=4, base_delay_s=0.01),
                      sleep=slept.append) == "ok"
    assert calls["n"] == 3 and len(slept) == 2
    # deterministic jitter: same policy, same schedule
    assert slept == [RetryPolicy(tries=4, base_delay_s=0.01).delay(1),
                     RetryPolicy(tries=4, base_delay_s=0.01).delay(2)]

    with pytest.raises(OSError):
        retry_call(lambda: (_ for _ in ()).throw(OSError("always")),
                   policy=RetryPolicy(tries=2, base_delay_s=0.0),
                   sleep=lambda _s: None)
    # non-transient types pass straight through on the first call
    with pytest.raises(KeyError):
        retry_call(lambda: {}["x"], policy=RetryPolicy(tries=5),
                   sleep=lambda _s: None)


def test_with_retries_deadline_and_exhaustion_ordering():
    """The shared Deadline/with_retries helper (data-stream retries AND
    fleet router dispatch) pins its error ordering: the LAST allowed
    attempt's failure re-raises unchanged (exhaustion wins), while a
    mid-budget deadline cut raises DeadlineExceeded chained from the
    last real failure."""
    from torchpruner_tpu.resilience.retry import (
        Deadline,
        DeadlineExceeded,
        with_retries,
    )

    # exhaustion wins when the deadline expires DURING the last
    # allowed attempt: the caller sees the real failure, not a wrapper
    boom = OSError("real failure")

    def slow_fail(_t):
        time.sleep(0.6)
        raise boom

    with pytest.raises(OSError) as ei:
        with_retries(slow_fail,
                     policy=RetryPolicy(tries=2, base_delay_s=0.0,
                                        jitter=0.0),
                     deadline=Deadline.after(1.0),
                     sleep=lambda _s: None)
    assert ei.value is boom

    # an expired deadline BEFORE any attempt: DeadlineExceeded, zero
    # attempts burned
    calls = {"n": 0}

    def count(_t):
        calls["n"] += 1
        raise OSError("x")

    with pytest.raises(DeadlineExceeded):
        with_retries(count, policy=RetryPolicy(tries=5),
                     deadline=Deadline(t_end=0.0, budget_s=0.0),
                     sleep=lambda _s: None)
    assert calls["n"] == 0

    # a backoff sleep that would cross the deadline is never taken:
    # DeadlineExceeded chained from the failure that spent the budget
    with pytest.raises(DeadlineExceeded) as ei:
        with_retries(count,
                     policy=RetryPolicy(tries=5, base_delay_s=10.0,
                                        jitter=0.0),
                     deadline=Deadline.after(0.5),
                     sleep=lambda _s: None)
    assert calls["n"] == 1
    assert isinstance(ei.value.__cause__, OSError)

    # success path: fn receives the per-attempt timeout clamped to the
    # remaining budget
    seen = []

    def ok(timeout_s):
        seen.append(timeout_s)
        return "ok"

    assert with_retries(ok, deadline=Deadline.after(100.0),
                        attempt_timeout_s=5.0) == "ok"
    assert seen[0] == pytest.approx(5.0)
    assert with_retries(ok, attempt_timeout_s=3.0) == "ok"
    assert seen[1] == 3.0
    # Deadline.clamp: remaining budget caps a larger attempt timeout
    d = Deadline.after(1.0)
    assert d.clamp(100.0) <= 1.0
    assert 0.0 < d.remaining() <= 1.0 and not d.expired


# -- chaos -------------------------------------------------------------------


def test_chaos_config_parsing_and_validation():
    assert ChaosConfig.from_any('{"nan_at_step": 3}').nan_at_step == 3
    assert ChaosConfig.from_any(None).any_active() is False
    with pytest.raises(ValueError, match="unknown chaos keys"):
        ChaosConfig.from_any({"nan_at_stepp": 3})
    # a defaults-only config installs nothing
    assert chaos.configure({"nan_at_step": -1}) is None
    assert chaos.configure({"nan_at_step": 4}) is not None
    assert chaos.active()
    # the fleet "slow replica" fault is an active injection and fires
    # on EVERY step (latency degradation, not a one-shot)
    assert chaos.configure({"slow_steps_ms": 1.0}) is not None
    t0 = time.perf_counter()
    chaos.maybe_slow_step()
    chaos.maybe_slow_step()
    assert time.perf_counter() - t0 >= 0.002
    chaos.disable()


def test_chaos_fires_once_at_exact_step():
    chaos.configure({"nan_at_step": 2})
    x = np.ones((4, 3), np.float32)
    assert np.isfinite(chaos.poison_batch(1, x)).all()
    assert np.isnan(chaos.poison_batch(2, x)).all()
    # once-per-process: step 2 again (post-resume replay) does NOT re-fire
    assert np.isfinite(chaos.poison_batch(2, x)).all()

    chaos.configure({"oom_at_step": 0})
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED") as ei:
        chaos.maybe_oom(0)
    assert is_oom_error(ei.value)


# -- guards ------------------------------------------------------------------


def test_step_guard_streak_semantics():
    g = StepGuard(max_bad_steps=3)
    assert g.observe(False) is False
    g.observe(True)
    g.observe(True)
    g.observe(False)  # streak broken
    g.observe(True)
    g.observe(True)
    with pytest.raises(NonFiniteStreakError) as ei:
        g.observe(True)
    assert ei.value.streak == 3 and g.total_skips == 5


def test_is_oom_error_classification():
    assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: ..."))
    assert is_oom_error(MemoryError())
    assert is_oom_error(Exception("Out of memory allocating 2.1G"))
    assert not is_oom_error(ValueError("shape mismatch"))


def test_preemption_handler_sigterm_sets_flag():
    with PreemptionHandler() as pre:
        assert not pre.requested
        os.kill(os.getpid(), signal.SIGTERM)
        # synchronous delivery on the main thread by the next bytecode
        assert pre.requested
        assert pre.should_snapshot()
    # restored: a SIGTERM now would kill the process, so don't send one


def test_guarded_step_skips_nan_and_holds_params():
    """Compiled guard: a NaN-poisoned batch leaves params/opt-state
    bit-identical, counts one skip, and training continues."""
    import optax

    from torchpruner_tpu.data import synthetic_dataset
    from torchpruner_tpu.models.mlp import fc_net
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    session = obs.configure(None, watch_compiles=False)
    try:
        ds = synthetic_dataset((8,), 3, 64, seed=0)
        guard = StepGuard(max_bad_steps=5)
        tr = Trainer.create(fc_net(8, hidden=(16,), n_classes=3),
                            optax.adam(1e-2), cross_entropy_loss,
                            seed=0, guard=guard)
        batches = ds.batches(16)
        tr.step(*batches[0])
        w_before = np.asarray(jax.device_get(tr.params["fc1"]["w"]))
        opt_before = np.asarray(
            jax.device_get(jax.tree_util.tree_leaves(tr.opt_state)[0]))
        bad = (np.full_like(np.asarray(batches[1][0]), np.nan),
               batches[1][1])
        tr.step(*bad)  # skipped inside the program
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(tr.params["fc1"]["w"])), w_before)
        np.testing.assert_array_equal(
            np.asarray(jax.tree_util.tree_leaves(tr.opt_state)[0]),
            np.asarray(opt_before))
        assert guard.total_skips == 1
        assert obs.counter_value("resilience_nan_skips_total") == 1
        l = tr.step(*batches[2])  # healthy step proceeds
        assert np.isfinite(float(l))
        assert guard.consecutive == 0
    finally:
        obs.shutdown()
        assert session is not None


# -- resilient train loop ----------------------------------------------------


def test_resilient_train_nan_chaos_recovers(tmp_path):
    """cfg.chaos nan_at_step + guard: the injected step is skipped, the
    run completes, and the recovery counters are visible."""
    from torchpruner_tpu.experiments.train_model import run_train

    obs.configure(None, watch_compiles=False)
    try:
        cfg = _train_cfg(tmp_path / "run", guard_nonfinite=True,
                         chaos={"nan_at_step": 5})
        trainer, history = run_train(cfg, verbose=False)
        assert len(history) == 1
        assert np.isfinite(history[-1]["test_loss"])
        assert obs.counter_value("resilience_nan_skips_total") >= 1
        assert obs.counter_value("chaos_injections_total") >= 1
        m = RunManifest.load(str(tmp_path / "run"))
        assert m.status == "done"
    finally:
        obs.shutdown()


def test_resilient_train_oom_degrades_accum(tmp_path):
    """Synthetic RESOURCE_EXHAUSTED at a step: rollback + accum_steps
    doubled (halved microbatch), run completes."""
    from torchpruner_tpu.experiments.train_model import run_train

    obs.configure(None, watch_compiles=False)
    try:
        cfg = _train_cfg(tmp_path / "run", chaos={"oom_at_step": 12},
                         checkpoint_every_steps=5)
        trainer, history = run_train(cfg, verbose=False)
        assert len(history) == 1
        assert trainer.accum_steps == 2
        m = RunManifest.load(str(tmp_path / "run"))
        assert m.accum_steps == 2 and m.status == "done"
        assert obs.counter_value("resilience_oom_retries_total") == 1
        assert obs.counter_value("resilience_rollbacks_total") == 1
    finally:
        obs.shutdown()


def test_resilient_train_streak_rolls_back_with_lr_backoff(tmp_path,
                                                           monkeypatch):
    """A persistent NaN source trips the streak guard; the runner rolls
    back to the last checkpoint and halves the LR (scale stage), and the
    rolled-back trainer's params come from the committed checkpoint."""
    from torchpruner_tpu.experiments.train_model import run_train

    # poison every batch from step 8 until the first rollback happens by
    # monkeypatching the chaos hook (cfg chaos only fires once)
    import torchpruner_tpu.resilience.chaos as chaos_mod

    state = {"rolled": False}
    real_poison = chaos_mod.poison_batch

    def poison(step, x):
        if not state["rolled"] and step >= 8:
            return np.full_like(np.asarray(x), np.nan)
        return real_poison(step, x)

    monkeypatch.setattr(chaos_mod, "poison_batch", poison)
    chaos.configure({"delay_callback_s": 1e-9})  # keep chaos.active() True

    from torchpruner_tpu.resilience import runner as runner_mod

    real_restore = runner_mod.run_resilient_train

    obs.configure(None, watch_compiles=False)
    try:
        cfg = _train_cfg(tmp_path / "run", guard_nonfinite=True,
                         max_bad_steps=2, lr_backoff=0.5,
                         checkpoint_every_steps=4, max_rollbacks=2)

        # stop poisoning once a rollback registered, so the run recovers
        orig_inc = obs.inc

        def inc(name, n=1, help=""):
            if name == "resilience_rollbacks_total":
                state["rolled"] = True
            return orig_inc(name, n, help)

        monkeypatch.setattr(obs, "inc", inc)
        trainer, history = run_train(cfg, verbose=False)
        assert state["rolled"], "streak never triggered a rollback"
        m = RunManifest.load(str(tmp_path / "run"))
        assert m.status == "done"
        assert m.rollbacks == 1
        assert m.lr_scale == pytest.approx(0.5)
        assert real_restore is runner_mod.run_resilient_train
    finally:
        obs.shutdown()


@pytest.mark.slow
def test_crash_resume_matches_uninterrupted(tmp_path):
    """Acceptance: SIGKILL mid-retrain (deterministic chaos kill), resume
    from the manifest, final eval loss equals the uninterrupted run's
    (rtol 1e-4 — in practice bit-identical: same rng, same shuffle, same
    batches after the cursor fast-forward)."""
    worker = os.path.join(REPO, "tests", "_resilience_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))

    def run(run_dir, chaos_spec=None):
        cmd = [sys.executable, worker, str(run_dir)]
        if chaos_spec:
            cmd.append(json.dumps(chaos_spec))
        return subprocess.run(cmd, capture_output=True, text=True,
                              env=env, cwd=REPO, timeout=420)

    ref = run(tmp_path / "uninterrupted")
    assert ref.returncode == 0, ref.stderr[-2000:]
    ja = json.loads([l for l in ref.stdout.splitlines()
                     if l.startswith("{")][-1])

    killed = run(tmp_path / "killed", {"kill_at_step": 20})
    assert killed.returncode == -signal.SIGKILL, (
        killed.returncode, killed.stderr[-2000:])
    # the manifest points at a complete checkpoint despite the SIGKILL
    m = RunManifest.load(str(tmp_path / "killed"))
    assert m.checkpoint and m.status == "running"

    resumed = run(tmp_path / "killed")
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    jb = json.loads([l for l in resumed.stdout.splitlines()
                     if l.startswith("{")][-1])

    np.testing.assert_allclose(jb["final_test_loss"],
                               ja["final_test_loss"], rtol=1e-4)
    np.testing.assert_allclose(jb["w_abs_sum"], ja["w_abs_sum"],
                               rtol=1e-4)
    m = RunManifest.load(str(tmp_path / "killed"))
    assert m.status == "done" and m.resumes == 1


# -- prune-retrain resume ----------------------------------------------------


def _prune_cfg(run_dir, **kw):
    from torchpruner_tpu.utils.config import ExperimentConfig

    base = dict(
        name="res_prune", model="digits_fc_tiny", dataset="digits_flat",
        method="weight_norm", policy="fraction", fraction=0.25,
        finetune_epochs=1, score_examples=32, batch_size=32,
        eval_batch_size=64, lr=0.05, run_dir=str(run_dir),
        log_path=os.path.join(str(run_dir), "log.csv"),
    )
    base.update(kw)
    return ExperimentConfig(**base)


@pytest.mark.slow
def test_prune_retrain_resumes_completed_rounds(tmp_path):
    """A finished resilient prune-retrain re-entered with the same
    run_dir replays NOTHING (all targets in the manifest) and returns
    the identical full history from the records."""
    from torchpruner_tpu.experiments.prune_retrain import run_prune_retrain

    cfg = _prune_cfg(tmp_path / "run")
    h1 = run_prune_retrain(cfg, verbose=False)
    assert len(h1) == 2  # fc1, fc2
    m = RunManifest.load(str(tmp_path / "run"))
    assert m.status == "done" and len(m.completed) == 2

    import time

    t0 = time.perf_counter()
    h2 = run_prune_retrain(_prune_cfg(tmp_path / "run"), verbose=False)
    resume_s = time.perf_counter() - t0
    assert [r.layer for r in h2] == [r.layer for r in h1]
    np.testing.assert_allclose(
        [r.post_loss for r in h2], [r.post_loss for r in h1], rtol=1e-6)
    # no scoring / retraining happened: the "resume" is setup-only
    assert resume_s < 60


@pytest.mark.slow
def test_prune_retrain_mid_round_resume_after_kill(tmp_path):
    """CLI end-to-end: chaos SIGKILL during the first target's retrain;
    the resumed run finishes BOTH targets without re-scoring the first
    (its stage says phase=retrain) and the manifest completes."""
    run_dir = str(tmp_path / "run")
    cfg_path = str(tmp_path / "cfg.json")
    _prune_cfg(run_dir, checkpoint_every_steps=10).to_json(cfg_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))

    def cli(*extra):
        return subprocess.run(
            [sys.executable, "-m", "torchpruner_tpu", "--config", cfg_path,
             "--cpu", "--resume", run_dir, "--checkpoint-every", "10",
             *extra],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=420)

    killed = cli("--chaos", json.dumps({"kill_at_step": 15}),
                 "--no-obs")
    assert killed.returncode == -signal.SIGKILL, (
        killed.returncode, killed.stderr[-2000:])
    m = RunManifest.load(run_dir)
    assert m.checkpoint, "no checkpoint committed before the kill"
    assert m.stage.get("phase") == "retrain"

    resumed = cli("--no-obs")
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    m = RunManifest.load(run_dir)
    assert m.status == "done"
    assert len(m.completed) == 2 and len(m.records) == 2
    assert m.resumes == 1
    out = json.loads([l for l in resumed.stdout.splitlines()
                      if l.startswith("{")][-1])
    assert out["steps"] == 2


# -- robustness sweep resume -------------------------------------------------


def test_sweep_journal_resume_and_preempt(tmp_path):
    """Sweep: full run persists per-layer results; a re-entered run
    skips every completed layer; a preemption at a layer boundary
    commits and unwinds."""
    from torchpruner_tpu.experiments.robustness import run_robustness_config
    from torchpruner_tpu.resilience.guards import Preempted
    from torchpruner_tpu.resilience.runner import SweepJournal
    from torchpruner_tpu.utils.config import ExperimentConfig

    def cfg():
        return ExperimentConfig(
            name="res_sweep", model="digits_fc_tiny",
            dataset="digits_flat", experiment="robustness",
            method="weight_norm", score_examples=48, eval_batch_size=48,
            run_dir=str(tmp_path / "run"),
            log_path=os.path.join(str(tmp_path), "log.csv"),
        )

    aucs1 = run_robustness_config(cfg(), verbose=False)
    assert "weight_norm" in aucs1
    m = RunManifest.load(str(tmp_path / "run"))
    assert m.status == "done" and len(m.completed) == 2
    assert os.path.exists(tmp_path / "run" / "sweep_results.json")

    aucs2 = run_robustness_config(cfg(), verbose=False)
    assert aucs2["weight_norm"] == pytest.approx(aucs1["weight_norm"])
    m = RunManifest.load(str(tmp_path / "run"))
    assert m.resumes >= 1

    # preemption at the layer boundary: commit + Preempted
    c2 = cfg()
    c2.run_dir = str(tmp_path / "run2")
    j = SweepJournal(c2)
    j.pre.request()
    with pytest.raises(Preempted):
        j.on_layer("fc1", {"weight_norm": [{"auc": 1.0}]})
    m2 = RunManifest.load(c2.run_dir)
    assert m2.completed == ["fc1"] and m2.status == "preempted"
    j.pre.__exit__(None, None, None)


# -- empty-iterator satellite ------------------------------------------------


def test_empty_eval_warns_and_counts(caplog):
    import logging

    import optax

    from torchpruner_tpu.data import synthetic_dataset
    from torchpruner_tpu.models.mlp import fc_net
    from torchpruner_tpu.train.loop import Trainer, evaluate, train_epoch
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    obs.configure(None, watch_compiles=False)
    try:
        model = fc_net(8, hidden=(8,), n_classes=3)
        tr = Trainer.create(model, optax.sgd(0.1), cross_entropy_loss,
                            seed=0)
        with caplog.at_level(logging.WARNING, logger="torchpruner_tpu"):
            with pytest.raises(ValueError, match="empty dataset"):
                evaluate(model, tr.params, tr.state, [],
                         cross_entropy_loss)
            # exhausted one-shot generator: the classic silent-nan case
            gen = iter(synthetic_dataset((8,), 3, 16, seed=0).batches(8))
            list(gen)
            assert np.isnan(train_epoch(tr, gen, verbose=False))
        assert obs.counter_value("eval_empty_total") == 2
        warnings = [r for r in caplog.records
                    if "empty or exhausted" in r.getMessage()]
        assert len(warnings) == 2
    finally:
        obs.shutdown()


def test_resilient_train_retries_transient_data_failure(tmp_path):
    """An injected transient OSError out of the data stream is absorbed
    by re-opening the stream at the cursor — the run completes, the
    retry counters tick, and no batch is silently skipped."""
    from torchpruner_tpu.experiments.train_model import run_train

    obs.configure(None, watch_compiles=False)
    try:
        cfg = _train_cfg(tmp_path / "run",
                         chaos={"fail_data_at_step": 3})
        trainer, history = run_train(cfg, verbose=False)
        assert len(history) == 1
        # every batch of the train split was stepped despite the fault
        from torchpruner_tpu.data import load_dataset

        n = len(load_dataset("digits_flat", "train", seed=cfg.seed))
        assert trainer.step_count == -(-n // cfg.batch_size)
        assert obs.counter_value("resilience_retries_total") >= 1
        assert obs.counter_value(
            "resilience_retries_data_fetch_total") >= 1
        m = RunManifest.load(str(tmp_path / "run"))
        assert m.status == "done"
    finally:
        obs.shutdown()

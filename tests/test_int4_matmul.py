"""Fused int4 weight-only matmul — ops/int4_matmul.py.

CPU runs the Pallas kernel in interpreter mode (like the flash tests),
so correctness is exercised everywhere; the bandwidth claim is measured
on chip (PERF.md serving section).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchpruner_tpu.ops.int4_matmul import (
    int4_matmul,
    pack_int4,
    quantize_int4,
    unpack_int4,
)


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.integers(-8, 8, size=(64, 16)).astype(np.int8))
    p = pack_int4(q)
    assert p.shape == (32, 16) and p.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(unpack_int4(p)), np.asarray(q))


def test_pack_rejects_odd_rows():
    with pytest.raises(ValueError, match="even"):
        pack_int4(jnp.zeros((3, 4), jnp.int8))


@pytest.mark.parametrize("D,F,blocks", [
    (1024, 512, {}),                                # kernel, default tiles
    (1024, 512, {"block_d": 256, "block_f": 256}),  # kernel, small tiles
    (96, 48, {}),                                   # XLA fallback path
])
def test_int4_matmul_matches_unpacked_reference(D, F, blocks):
    """Kernel path (tiling shapes) and XLA fallback (non-tiling) both
    equal the explicit unpack-then-matmul in f32."""
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.integers(-8, 8, size=(D, F)).astype(np.int8))
    x = jnp.asarray(rng.normal(size=(8, D)).astype(np.float32))
    p = pack_int4(q)
    # the kernel computes in bf16 operands / f32 accumulation — compare
    # against the same-precision XLA matmul, where agreement is tight
    want = jnp.dot(x.astype(jnp.bfloat16), q.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)
    got = int4_matmul(x, p, **blocks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)


def test_int4_matmul_tiles_prefill_row_counts():
    """Row counts above MAX_UNTILED_ROWS get their own grid dimension
    (a prefill through a bits=4 model, e.g. B8 × S2048 = 16384 rows,
    must not hold the whole row block in VMEM); numerics match."""
    rng = np.random.default_rng(4)
    B, D, F = 2048, 512, 512
    q = jnp.asarray(rng.integers(-8, 8, size=(D, F)).astype(np.int8))
    x = jnp.asarray(rng.normal(size=(B, D)).astype(np.float32))
    want = jnp.dot(x.astype(jnp.bfloat16), q.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)
    got = int4_matmul(x, pack_int4(q))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)


def test_quantize_int4_bounds_error_and_applies_scale():
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(256, 128)).astype(np.float32))
    packed, scale = quantize_int4(w)
    deq = np.asarray(unpack_int4(packed), np.float32) * np.asarray(scale)
    # int4 grid: |err| <= scale/2 per element
    assert np.max(np.abs(deq - np.asarray(w)) / np.asarray(scale)) <= 0.5 + 1e-6

    x = jnp.asarray(rng.normal(size=(4, 256)).astype(np.float32))
    got = int4_matmul(x, packed, scale)
    # same arithmetic as the kernel: bf16 int matmul, f32 post-scale
    want = (jnp.dot(x.astype(jnp.bfloat16),
                    unpack_int4(packed).astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32)
            * scale[None, :])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)


def test_zero_channel_roundtrips_exactly():
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    w = w.at[:, 3].set(0.0)  # one dead channel among live ones
    packed, scale = quantize_int4(w)
    assert float(scale[3]) == 1.0  # the zero-channel fallback scale
    x = jnp.ones((2, 64), jnp.float32)
    y = np.asarray(int4_matmul(x, packed, scale))
    np.testing.assert_array_equal(y[:, 3], np.zeros(2))
    assert np.abs(y[:, :3]).max() > 0  # live channels stay live


def test_pick_row_block_divisor_search():
    """Row blocks: whole for decode-sized B; the largest divisor
    <= MAX_UNTILED_ROWS for prefill-sized B (2000 rows -> 1000, not an
    XLA fallback); degenerate primes route to the fallback (0)."""
    from torchpruner_tpu.ops.int4_matmul import (
        MAX_UNTILED_ROWS,
        _pick_row_block,
    )

    assert _pick_row_block(8) == 8
    assert _pick_row_block(MAX_UNTILED_ROWS) == MAX_UNTILED_ROWS
    assert _pick_row_block(16384) == 1024
    assert _pick_row_block(2000) == 1000   # B8 x S250 prefill
    assert _pick_row_block(2048) == 1024
    assert _pick_row_block(1297 * 2) == 0  # 2x prime: no block in [8, 1024]
    assert _pick_row_block(104729) == 0    # prime: degenerate, fallback


def test_block_sizes_adapt_to_nondefault_axes():
    """Axes the 512 defaults don't divide shrink to a fitting
    lane-aligned block instead of losing the kernel: F=768 and the
    Llama-3 lm_head's F=128256 -> 384; truly unfittable axes (no
    128-multiple divisor) still fall back."""
    from torchpruner_tpu.ops.int4_matmul import _fit_block

    assert _fit_block(768, 512) == 384
    assert _fit_block(128256, 512) == 384  # 384 * 334; 512 doesn't divide
    assert _fit_block(4096, 512) == 512
    assert _fit_block(1002, 512) == 0   # 2*3*167: no 128-multiple divides
    assert _fit_block(128, 512) == 128

    # end-to-end: F=768 takes the kernel path and matches numerics
    rng = np.random.default_rng(6)
    D, F = 512, 768
    q = jnp.asarray(rng.integers(-8, 8, size=(D, F)).astype(np.int8))
    x = jnp.asarray(rng.normal(size=(4, D)).astype(np.float32))
    want = jnp.dot(x.astype(jnp.bfloat16), q.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)
    got = int4_matmul(x, pack_int4(q))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)

"""Run ledger & reports (torchpruner_tpu.obs.{ledger,aggregate,
trace_export,report}): score-distribution math, recorder dedup/resume/
backfill, histogram percentiles, Prometheus text lint, Perfetto trace
schema round-tripped through ``load_span_events``, event-stream
rotation, cross-host shard merging, the ``obs report`` / ``obs diff``
CLI with gates, the planted-regression catch, and kill-9 ledger
continuity through a CLI resume."""

import json
import os
import re
import signal
import subprocess
import sys

import numpy as np
import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.obs.aggregate import (
    load_shards,
    merge_shards,
    registry_to_shard,
    write_shard,
)
from torchpruner_tpu.obs.ledger import (
    ProvenanceRecorder,
    load_ledger,
    score_distribution,
)
from torchpruner_tpu.obs.metrics import Histogram, MetricsRegistry
from torchpruner_tpu.obs.report import (
    check_gates,
    diff_runs,
    load_run,
    obs_main,
)
from torchpruner_tpu.obs.trace_export import (
    trace_events_from_spans,
    write_trace,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_session():
    obs.shutdown()
    yield
    obs.shutdown()


# -- score distributions -----------------------------------------------------


def test_score_distribution_margins_and_near_ties():
    scores = np.arange(10.0)  # 0..9
    d = score_distribution(scores, drop=[0, 1, 2])
    assert d["n"] == 10 and d["n_pruned"] == 3 and d["n_kept"] == 7
    assert d["kept_min"] == 3.0 and d["pruned_max"] == 2.0
    assert d["margin"] == pytest.approx(1.0)
    # boundary 2.5, span p99-p1 ≈ 8.8, eps ≈ 0.44: no unit within eps
    assert d["near_ties"] == 0
    assert d["p50"] == pytest.approx(4.5)

    # a near-tie cluster right at the decision boundary is counted
    tied = np.array([0.0, 0.999, 1.0, 1.001, 10.0, 20.0, 30.0, 40.0])
    d2 = score_distribution(tied, drop=[0, 1, 2])
    assert d2["margin"] == pytest.approx(0.001, rel=1e-6)
    assert d2["near_ties"] >= 3

    # negative margin: the policy removed a unit scoring above a kept one
    d3 = score_distribution(np.array([5.0, 1.0, 2.0, 3.0]), drop=[0])
    assert d3["margin"] < 0

    assert score_distribution(np.array([]))["n"] == 0
    assert "margin" not in score_distribution(np.arange(4.0), drop=[])


# -- recorder ----------------------------------------------------------------


def test_recorder_dedupes_in_session_and_scopes_view_per_run(tmp_path):
    d = str(tmp_path)
    rec = ProvenanceRecorder(d)
    assert rec.record_round(target="fc1", round=0, n_dropped=3)
    assert not rec.record_round(target="fc1", round=0)  # dup in-session
    assert rec.record_round(target="fc2", round=1, n_dropped=1)
    assert [r["target"] for r in rec.rounds()] == ["fc1", "fc2"]
    rec.close()

    # a NEW session reusing the dir starts its OWN view: a fresh run's
    # report must never carry a predecessor's rounds...
    rec2 = ProvenanceRecorder(d)
    assert rec2.rounds() == []
    assert rec2.record_round(target="fc1", round=0, n_dropped=9)
    assert [r["n_dropped"] for r in rec2.rounds()] == [9]
    # ...but can ADOPT a prior record explicitly (the resume bridge;
    # keys carry the trial_id slot — None outside campaigns)
    assert rec2.adopt(("round", None, "fc2", 1))
    assert not rec2.adopt(("round", None, "fc2", 1))      # once
    assert not rec2.adopt(("round", None, "nothere", 0))  # unknown key
    assert [r["target"] for r in rec2.rounds()] == ["fc1", "fc2"]
    assert rec2.rounds()[1]["n_dropped"] == 1  # prior payload intact
    rec2.close()


def test_iterative_schedule_ledgers_every_round_of_a_layer(tmp_path):
    """Pruning the SAME layer in successive rounds must ledger each
    round (dedup keys include the round index), and diffs must pair
    them round-for-round."""
    rec = ProvenanceRecorder(str(tmp_path))
    assert rec.record_round(target="fc1", round=0, n_dropped=10)
    assert rec.record_round(target="fc1", round=1, n_dropped=5)
    assert rec.record_round(target="fc1", round=2, n_dropped=2)
    assert not rec.record_round(target="fc1", round=1)  # true dup
    assert len(rec.rounds()) == 3
    rec.close()

    from torchpruner_tpu.obs.ledger import build_report

    rep = build_report(records=rec.rounds())
    d = diff_runs(rep, rep)
    assert set(d["rounds"]) == {"fc1", "fc1#1", "fc1#2"}
    assert d["missing_rounds"] == []


def test_recorder_backfill_fills_only_missing_rounds(tmp_path):
    rec = ProvenanceRecorder(str(tmp_path))
    rec.record_round(target="fc2", round=0, n_dropped=5)
    manifest_records = [
        {"layer": "fc2", "pre_acc": 0.5, "post_acc": 0.6, "n_dropped": 5,
         "n_params": 100, "pre_loss": 1.0, "post_loss": 0.9,
         "prune_time": 0.1, "widths": {"fc2": 59}},
        {"layer": "fc1", "pre_acc": 0.6, "post_acc": 0.7, "n_dropped": 3,
         "n_params": 80, "pre_loss": 0.9, "post_loss": 0.8,
         "prune_time": 0.1, "widths": {"fc1": 61}},
    ]
    assert rec.backfill_rounds(manifest_records) == 1  # fc2 already there
    rounds = rec.rounds()
    assert [r["target"] for r in rounds] == ["fc2", "fc1"]
    assert rounds[1]["backfilled"] is True
    assert rounds[1]["post"]["acc"] == 0.7
    rec.close()


def test_ledger_tolerates_torn_tail(tmp_path):
    path = tmp_path / "ledger.jsonl"
    path.write_text('{"event": "round", "target": "a"}\n{"torn')
    rec = ProvenanceRecorder(str(tmp_path))  # opens despite the tear
    # the intact record is adoptable (no round field -> None in key)
    assert rec.adopt(("round", None, "a", None))
    rec.close()


def test_report_json_is_strict_json_even_with_nan_metrics(tmp_path):
    """CPU runs gauge mfu as NaN — report.json (and its ledger lines)
    must still parse under STRICT JSON (null, not the NaN extension)."""
    d = str(tmp_path / "obs")
    obs.configure(d, process_index=0, annotate=False, watch_compiles=False)
    obs.record_step(0.01, 32)
    obs.gauge_set("weird", float("nan"))
    obs.record_round(target="fc1", round=0,
                     score_dist=score_distribution(
                         np.array([0.0, np.nan, 1.0]), [0]))
    obs.shutdown()
    raw = open(os.path.join(d, "report.json")).read()
    assert "NaN" not in raw and "Infinity" not in raw
    rep = json.loads(raw)  # strict enough; the string check above is
    assert rep["rounds"][0]["target"] == "fc1"  # the real assertion
    for line in open(os.path.join(d, "ledger.jsonl")):
        assert "NaN" not in line


# -- histogram percentiles ---------------------------------------------------


def test_histogram_quantiles_from_buckets():
    h = Histogram("t", buckets=(0.001, 0.01, 0.1, 1.0))
    for v in [0.005] * 90 + [0.05] * 9 + [0.5]:
        h.observe(v)
    assert 0.001 <= h.quantile(0.5) <= 0.01
    assert 0.01 <= h.quantile(0.95) <= 0.1
    assert h.quantile(0.99) <= 0.5  # clamped to observed max
    p = h.percentiles()
    assert p["p50"] <= p["p95"] <= p["p99"]
    assert Histogram("e").quantile(0.5) is None

    reg = MetricsRegistry()
    hh = reg.histogram("step_time_seconds")
    hh.observe(0.01)
    snap = reg.snapshot()
    assert snap["step_time_seconds_p50"] == pytest.approx(0.01)
    assert "step_time_seconds_p99" in snap


# -- Prometheus text lint ----------------------------------------------------

_SERIES = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)$")


def _prom_lint(text):
    """Minimal textfile lint: every line is a comment or a series sample;
    every sampled family has a TYPE; cumulative buckets are monotone and
    end at +Inf == count."""
    typed = {}
    series = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            typed[name] = kind
            continue
        if line.startswith("#"):
            assert line.startswith("# HELP "), line
            continue
        m = _SERIES.match(line)
        assert m, f"unparseable series line: {line!r}"
        series.append(m.groups())
    hist_buckets = {}
    for name, labels, value in series:
        family = re.sub(r"_(bucket|sum|count)$", "", name) \
            if re.search(r"_(bucket|sum|count)$", name) and \
            re.sub(r"_(bucket|sum|count)$", "", name) in typed else name
        assert family in typed, f"series {name} has no TYPE"
        if name.endswith("_bucket"):
            le = re.search(r'le="([^"]+)"', labels or "").group(1)
            hist_buckets.setdefault(family, []).append(
                (float("inf") if le == "+Inf" else float(le),
                 float(value)))
    for family, buckets in hist_buckets.items():
        bounds = [b for b, _ in buckets]
        counts = [c for _, c in buckets]
        assert bounds == sorted(bounds)
        assert counts == sorted(counts), f"{family} buckets not cumulative"
        assert bounds[-1] == float("inf"), f"{family} missing +Inf"
        count = [float(v) for n, _, v in series
                 if n == f"{family}_count"][0]
        assert counts[-1] == count, f"{family} +Inf bucket != count"
    return typed


def test_prometheus_text_lints_and_carries_percentiles():
    from torchpruner_tpu.obs.exporters import prometheus_text

    reg = MetricsRegistry()
    reg.counter("examples_total", "ex").inc(32)
    reg.gauge("mfu", "model flops util").set(0.5)
    h = reg.histogram("step_time_seconds", "steps")
    for v in (0.001, 0.002, 0.004, 2.0):
        h.observe(v)
    text = prometheus_text(reg)
    typed = _prom_lint(text)
    assert typed["examples_total"] == "counter"
    assert typed["step_time_seconds"] == "histogram"
    # percentile companion gauges ship in the same textfile
    assert typed["step_time_seconds_p50"] == "gauge"
    for q in ("p50", "p95", "p99"):
        assert re.search(rf"^step_time_seconds_{q} \S+$", text, re.M)


# -- event-stream rotation ---------------------------------------------------


def test_event_rotation_and_rotated_load(tmp_path):
    from torchpruner_tpu.utils.profiling import (
        load_span_events,
        span_phase_summary,
    )

    obs_dir = str(tmp_path / "obs")
    # cap sized so the ~12 KB stream rotates 2-3 times but stays within
    # the default 3 retained backups (beyond that the oldest falls off —
    # the bound is the point)
    obs.configure(obs_dir, process_index=0, annotate=False,
                  watch_compiles=False, rotate_bytes=4000)
    for i in range(40):
        with obs.span("phase", i=i):
            pass
    obs.shutdown()
    events_path = os.path.join(obs_dir, "events.jsonl")
    assert os.path.exists(events_path + ".1")  # rotated at least once
    # the rotated set reads back as ONE stream: every span still there
    events = load_span_events(events_path)
    phases = span_phase_summary(events_path)
    assert phases["phase"]["calls"] == 40
    begins = {e["span"] for e in events if e["event"] == "span_begin"}
    assert len(begins) == 40

    # rotation off (default): a long stream stays one file
    obs_dir2 = str(tmp_path / "obs2")
    obs.configure(obs_dir2, process_index=0, annotate=False,
                  watch_compiles=False)
    for i in range(40):
        with obs.span("phase", i=i):
            pass
    obs.shutdown()
    assert not os.path.exists(
        os.path.join(obs_dir2, "events.jsonl.1"))


# -- Perfetto trace export ---------------------------------------------------


def test_trace_export_schema_roundtrip(tmp_path):
    """The exported trace.json satisfies the Trace Event Format schema:
    B/E pairing balances per track, ts monotonic per tid, pid from the
    process index — round-tripped through load_span_events."""
    from torchpruner_tpu.utils.profiling import load_span_events

    obs_dir = str(tmp_path / "obs")
    obs.configure(obs_dir, process_index=0, annotate=False,
                  watch_compiles=False)
    with obs.span("run"):
        with obs.span("retrain", target="fc1"):
            pass
        with obs.span("eval"):
            pass
    obs.shutdown()
    trace_path = os.path.join(obs_dir, "trace.json")
    assert os.path.exists(trace_path)
    trace = json.load(open(trace_path))
    evs = trace["traceEvents"]
    assert trace["displayTimeUnit"] == "ms"

    stacks = {}
    last_ts = {}
    for e in evs:
        assert {"ph", "pid", "tid"} <= set(e)
        if e["ph"] == "M":
            continue
        assert e["ph"] in ("B", "E")
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last_ts.get(key, 0), "ts not monotonic"
        last_ts[key] = e["ts"]
        if e["ph"] == "B":
            stacks.setdefault(key, []).append(e["name"])
        else:
            assert stacks[key].pop() == e["name"], "B/E mis-paired"
    assert all(not s for s in stacks.values()), "unbalanced B/E"
    names = {e["name"] for e in evs if e["ph"] == "B"}
    assert {"run", "retrain", "eval"} <= names
    # args carry span meta
    retrain_b = next(e for e in evs
                     if e["ph"] == "B" and e["name"] == "retrain")
    assert retrain_b["args"]["target"] == "fc1"

    # the same converter over the parsed stream gives identical events
    again = trace_events_from_spans(load_span_events(
        os.path.join(obs_dir, "events.jsonl")))
    assert [e["ph"] for e in again] == [e["ph"] for e in evs]


def test_trace_export_closes_torn_spans(tmp_path):
    """A SIGKILLed run leaves span_begin without span_end — the exporter
    synthesizes the E so the trace still opens balanced."""
    path = str(tmp_path / "events.jsonl")
    with open(path, "w") as f:
        for ev in [
            {"event": "obs_init", "ts": 0, "process_index": 3},
            {"event": "span_begin", "span": "s1", "name": "run",
             "ts": 1.0, "tid": 7},
            {"event": "span_begin", "span": "s2", "name": "retrain",
             "ts": 2.0, "tid": 7},
        ]:
            f.write(json.dumps(ev) + "\n")
    out = write_trace(path)
    evs = json.load(open(out))["traceEvents"]
    bs = [e for e in evs if e["ph"] == "B"]
    es = [e for e in evs if e["ph"] == "E"]
    assert len(bs) == len(es) == 2
    assert all(e["args"].get("torn") for e in es)
    assert all(e["pid"] == 3 and e["tid"] == 7 for e in bs + es)
    # innermost closes first
    assert es[0]["name"] == "retrain" and es[1]["name"] == "run"


# -- shard merge (single-process unit; the real 2-process path is in
#    test_multiprocess.py) --------------------------------------------------


def test_shard_merge_rules(tmp_path):
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("examples_total").inc(10)
    b.counter("examples_total").inc(20)
    a.gauge("hbm").set(100)
    b.gauge("hbm").set(300)
    ha = a.histogram("step_time_seconds", buckets=(0.01, 0.1))
    hb = b.histogram("step_time_seconds", buckets=(0.01, 0.1))
    ha.observe(0.005)
    hb.observe(0.05)
    hb.observe(5.0)
    merged = merge_shards([registry_to_shard(a, 0),
                           registry_to_shard(b, 1)])
    snap = merged.snapshot()
    assert snap["examples_total"] == 30
    assert snap["hbm"] == 300          # max wins
    assert snap["hbm_min"] == 100      # spread companion
    h = merged.get("step_time_seconds")
    assert h.count == 3 and h.counts == [1, 1, 1]
    assert h.min == 0.005 and h.max == 5.0


def test_nonzero_process_writes_shard_and_emitter_merges(tmp_path):
    from torchpruner_tpu.obs import ObsSession

    obs_dir = str(tmp_path / "obs")
    # a pod's real ordering: every process OPENS its session up front
    # (emitter first clears any dead run's shards), closes write shards
    s0 = ObsSession(obs_dir, process_index=0, annotate=False,
                    watch_compiles=False)
    s1 = ObsSession(obs_dir, process_index=1, annotate=False,
                    watch_compiles=False)
    s1.metrics.counter("mp_total").inc(5)
    s0.metrics.counter("mp_total").inc(7)
    s1.close()  # worker host drains first
    assert os.path.exists(os.path.join(obs_dir, "metrics.shard1.json"))
    assert not os.path.exists(os.path.join(obs_dir, "metrics.prom"))
    s0.close()  # emitter merges whatever shards are present
    prom = open(os.path.join(obs_dir, "metrics.prom")).read()
    assert re.search(r"^mp_total 12$", prom, re.M)
    assert len(load_shards(obs_dir)) == 2


def test_new_session_clears_stale_shards_and_scopes_report(tmp_path):
    """A FRESH run reusing an obs dir must not inherit its predecessor:
    stale shards are cleared at init (no double-counted counters) and
    report.json carries only the new run's rounds."""
    obs_dir = str(tmp_path / "obs")
    obs.configure(obs_dir, process_index=0, annotate=False,
                  watch_compiles=False)
    obs.inc("mp_total", 5)
    obs.record_round(target="old_round", round=0)
    obs.shutdown()
    # pretend a dead 2-process run also left a foreign shard behind
    import shutil

    shutil.copyfile(os.path.join(obs_dir, "metrics.shard0.json"),
                    os.path.join(obs_dir, "metrics.shard7.json"))

    obs.configure(obs_dir, process_index=0, annotate=False,
                  watch_compiles=False)
    obs.inc("mp_total", 2)
    obs.record_round(target="new_round", round=0)
    obs.shutdown()
    prom = open(os.path.join(obs_dir, "metrics.prom")).read()
    assert re.search(r"^mp_total 2$", prom, re.M)  # not 7, not 12
    rep = load_run(obs_dir)
    assert [r["target"] for r in rep["rounds"]] == ["new_round"]


# -- report / diff / gates ---------------------------------------------------


def _make_run(tmp_path, name, step_t, post_acc, p50=4.5, targets=("fc1",)):
    d = str(tmp_path / name)
    obs.configure(d, process_index=0, annotate=False, watch_compiles=False)
    obs.annotate_run(experiment=name)
    for _ in range(10):
        obs.record_step(step_t, 32)
    scores = np.arange(10.0) + (p50 - 4.5)
    for i, t in enumerate(targets):
        obs.record_round(
            target=t, round=i, method="taylor", n_dropped=3,
            score_dist=score_distribution(scores, [0, 1, 2]),
            pre={"loss": 1.0, "acc": 0.7},
            post={"loss": 0.9, "acc": post_acc}, params=100)
    obs.shutdown()
    return d


def test_report_load_render_and_json(tmp_path, capsys):
    d = _make_run(tmp_path, "runA", 0.01, 0.65)
    report = load_run(d)
    assert len(report["rounds"]) == 1
    assert report["run"]["experiment"] == "runA"
    assert report["derived"]["steps"] == 10
    rc = obs_main(["report", d])
    assert rc == 0
    out = capsys.readouterr().out
    assert "| fc1 |" in out and "obs report" in out
    rc = obs_main(["report", d, "--json"])
    assert rc == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["rounds"][0]["target"] == "fc1"
    assert obs_main(["report", str(tmp_path / "nope")]) == 2


def test_report_reconstructs_from_ledger_when_killed_before_close(tmp_path):
    """No report.json (killed run): load_run rebuilds from ledger.jsonl
    + events.jsonl + shards."""
    d = _make_run(tmp_path, "runA", 0.01, 0.65)
    os.unlink(os.path.join(d, "report.json"))
    report = load_run(d)
    assert report["run"].get("reconstructed")
    assert len(report["rounds"]) == 1
    assert report["derived"]["steps"] == 10  # from the metric shard


def test_diff_and_gates_catch_regressions(tmp_path):
    a = load_run(_make_run(tmp_path, "A", 0.01, 0.65,
                           targets=("fc1", "fc2")))
    b = load_run(_make_run(tmp_path, "B", 0.02, 0.40, p50=14.5,
                           targets=("fc1",)))
    d = diff_runs(a, b)
    assert d["scalars"]["step_time_mean_s"]["pct"] == pytest.approx(100.0)
    assert d["rounds"]["fc1"]["post_acc_delta"] == pytest.approx(-0.25)
    assert d["rounds"]["fc1"]["score_p50_drift"] > 1.0
    assert d["missing_rounds"] == ["fc2"]

    gates = {
        "step_time_mean_s": {"max_increase_pct": 50},
        "round_post_acc": {"max_decrease": 0.1},
        "score_p50_drift": {"max": 0.25},
        "missing_rounds": {"max": 0},
    }
    violated = {v["gate"] for v in check_gates(d, gates)}
    assert violated == set(gates)
    # self-diff is clean under the same gates
    assert check_gates(diff_runs(a, a), gates) == []
    # unknown gate names are violations, not silent no-ops
    assert check_gates(diff_runs(a, a), {"step_tme": {}})[0]["gate"] == \
        "step_tme"


def test_diff_cli_gate_exit_codes(tmp_path, capsys):
    a = _make_run(tmp_path, "A", 0.01, 0.65)
    b = _make_run(tmp_path, "B", 0.03, 0.65)
    gate_path = str(tmp_path / "gates.json")
    json.dump({"step_time_mean_s": {"max_increase_pct": 50}},
              open(gate_path, "w"))
    assert obs_main(["diff", a, b, "--gate", gate_path]) == 1
    err = capsys.readouterr().err
    assert "GATE VIOLATION [step_time_mean_s]" in err
    assert obs_main(["diff", a, a, "--gate", gate_path]) == 0
    assert obs_main(["diff", a, b]) == 0  # no --gate: report-only


# -- end-to-end: planted regression through the real pipeline ---------------


def test_cli_planted_regression_trips_the_gate(tmp_path, monkeypatch):
    """The acceptance check: the digits smoke preset twice — normal vs
    config-degraded (halved batch => ~2x the optimizer steps) — and
    ``obs diff --gate`` exits 1 naming the violated gate, while the
    normal-vs-normal diff passes the same gates."""
    import dataclasses

    from torchpruner_tpu.__main__ import main
    from torchpruner_tpu.experiments.presets import mnist_mlp_shapley

    monkeypatch.chdir(tmp_path)
    dir_a = str(tmp_path / "obs_a")
    dir_b = str(tmp_path / "obs_b")
    cfg = mnist_mlp_shapley(smoke=True)
    cfg_a = dataclasses.replace(
        cfg, log_path=str(tmp_path / "a.csv"))
    cfg_b = dataclasses.replace(
        cfg, batch_size=cfg.batch_size // 2, name="degraded",
        log_path=str(tmp_path / "b.csv"))
    cfg_a.to_json(str(tmp_path / "a.json"))
    cfg_b.to_json(str(tmp_path / "b.json"))
    assert main(["--config", str(tmp_path / "a.json"), "--obs-dir", dir_a,
                 "--no-compilation-cache"]) == 0
    assert main(["--config", str(tmp_path / "b.json"), "--obs-dir", dir_b,
                 "--no-compilation-cache"]) == 0

    report = load_run(dir_a)
    assert len(report["rounds"]) == 2  # fc1, fc2
    assert all(r["score_dist"]["n"] > 0 for r in report["rounds"])

    gate_path = str(tmp_path / "gates.json")
    json.dump({"steps": {"max_increase_pct": 50},
               "missing_rounds": {"max": 0},
               "round_post_acc": {"max_decrease": 0.3}},
              open(gate_path, "w"))
    rc = main(["obs", "diff", dir_a, dir_b, "--gate", gate_path])
    assert rc == 1  # halved batch doubled steps_total: gate named
    rc = main(["obs", "diff", dir_a, dir_a, "--gate", gate_path])
    assert rc == 0


# -- kill-9 ledger continuity ------------------------------------------------


@pytest.mark.slow
def test_killed_and_resumed_run_has_one_continuous_ledger(tmp_path):
    """SIGKILL mid second-round retrain, resume with the SAME obs dir:
    `obs report` shows exactly one record per target — the pre-kill
    round survives, the post-resume round lands, nothing duplicates."""
    from torchpruner_tpu.utils.config import ExperimentConfig

    run_dir = str(tmp_path / "run")
    obs_dir = str(tmp_path / "obs")
    cfg_path = str(tmp_path / "cfg.json")
    ExperimentConfig(
        name="ledger_kill", model="digits_fc_tiny", dataset="digits_flat",
        method="weight_norm", policy="fraction", fraction=0.25,
        finetune_epochs=1, score_examples=32, batch_size=32,
        eval_batch_size=64, lr=0.05, run_dir=run_dir,
        log_path=os.path.join(run_dir, "log.csv"),
    ).to_json(cfg_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))

    def cli(*extra):
        return subprocess.run(
            [sys.executable, "-m", "torchpruner_tpu", "--config", cfg_path,
             "--cpu", "--resume", run_dir, "--checkpoint-every", "10",
             "--obs-dir", obs_dir, *extra],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=420)

    # ~40 steps/retrain epoch: step 55 is mid the SECOND target's retrain
    killed = cli("--chaos", json.dumps({"kill_at_step": 55}))
    assert killed.returncode == -signal.SIGKILL, (
        killed.returncode, killed.stderr[-2000:])
    rounds = [r for r in load_ledger(os.path.join(obs_dir, "ledger.jsonl"))
              if r.get("event") == "round"]
    assert len(rounds) == 1  # first round committed before the kill

    resumed = cli()
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    report = load_run(obs_dir)
    targets = [r["target"] for r in report["rounds"]]
    assert sorted(targets) == ["fc1", "fc2"]
    assert len(targets) == len(set(targets)) == 2
    # the resumed round still carries its staged score distribution
    assert all((r.get("score_dist") or {}).get("n", 0) > 0
               for r in report["rounds"])

    # and the CLI renders it: one row per round, exit 0
    out = subprocess.run(
        [sys.executable, "-m", "torchpruner_tpu", "obs", "report",
         obs_dir, "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr[-1000:]
    rep = json.loads(out.stdout)
    assert len(rep["rounds"]) == 2

"""Kernel-subsystem tests: autotune cache round-trip, decode-shaped
attention parity (incl. the bit-stability contract the serve --verify
path hangs on), block-sparse matmul fwd/bwd + training equivalence, and
the fused int8/int4 dequant matmul — all through the real kernel code in
interpreter mode on CPU."""

import json
import os

import numpy as np
import jax
import numpy as onp
import jax.numpy as jnp
import pytest

from torchpruner_tpu.ops import autotune


@pytest.fixture
def tune_cache(tmp_path, monkeypatch):
    path = str(tmp_path / "tune.json")
    monkeypatch.setenv(autotune.ENV_VAR, path)
    autotune.reset()
    yield path
    autotune.reset()


# -- autotune ----------------------------------------------------------------


def test_autotune_record_persist_reload(tune_cache):
    key = autotune.record(autotune.KIND_FLASH, 64, 4096, jnp.bfloat16,
                          (128, 256), ms=1.5)
    assert os.path.exists(tune_cache)
    autotune.reset()  # drop memory: must reload from disk
    assert autotune.lookup(autotune.KIND_FLASH, 64, 4096,
                           jnp.bfloat16) == (128, 256)
    # same seq bucket -> same entry; different head dim -> miss
    assert autotune.lookup(autotune.KIND_FLASH, 64, 3000,
                           jnp.bfloat16) == (128, 256)
    assert autotune.lookup(autotune.KIND_FLASH, 32, 4096,
                           jnp.bfloat16) is None
    entries = json.load(open(tune_cache))
    assert key in entries and entries[key]["blocks"] == [128, 256]


def test_autotune_non_tpu_records_defaults(tune_cache):
    calls = []

    def run(blocks):
        calls.append(blocks)
        return lambda: None

    blocks = autotune.autotune(
        autotune.KIND_FLASH, 16, 256, jnp.float32, run=run,
        candidates=((8, 8), (16, 16)), defaults=(128, 128))
    assert blocks == (128, 128)
    assert calls == []  # interpreter timing is meaningless: no timing ran
    assert autotune.lookup(autotune.KIND_FLASH, 16, 256,
                           jnp.float32) == (128, 128)


def test_autotune_force_times_candidates_and_roundtrips(tune_cache):
    from torchpruner_tpu.ops import flash_attention as F

    S, Dh = 128, 8
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (1, S, 2, Dh)) for kk in ks)

    def run(blocks):
        fn = jax.jit(lambda a, b, c: F.flash_attention(
            a, b, c, causal=True, block_q=blocks[0], block_k=blocks[1]))
        return lambda: fn(q, k, v)

    best = autotune.autotune(
        autotune.KIND_FLASH, Dh, S, q.dtype, run=run,
        candidates=((32, 32), (64, 64)), defaults=(128, 128),
        force=True, iters=1, warmup=1)
    assert best in ((32, 32), (64, 64))
    autotune.reset()
    assert autotune.lookup(autotune.KIND_FLASH, Dh, S, q.dtype) == best


def test_flash_dispatch_consults_tuned_blocks(tune_cache, monkeypatch):
    from torchpruner_tpu.ops import flash_attention as F

    seen = {}
    orig = F._lax_flash

    def spy(q, k, v, causal, bq, bk):
        seen["blocks"] = (bq, bk)
        return orig(q, k, v, causal, bq, bk)

    monkeypatch.setattr(F, "_lax_flash", spy)
    S, Dh = 256, 16
    autotune.record(autotune.KIND_FLASH, Dh, S, jnp.float32, (64, 32))
    q, k, v = (jax.random.normal(kk, (1, S, 2, Dh))
               for kk in jax.random.split(jax.random.PRNGKey(1), 3))
    F.flash_attention(q, k, v, causal=True)
    assert seen["blocks"] == (64, 32)


# -- decode attention --------------------------------------------------------


def _decode_case(B=3, T=128, H=2, Dh=16, cache_dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (B, 1, H, Dh))
    kc = jax.random.normal(ks[1], (B, T, H, Dh), cache_dtype)
    vc = jax.random.normal(ks[2], (B, T, H, Dh), cache_dtype)
    pos = jnp.asarray([3, T // 2, T - 1][:B], jnp.int32)
    return q, kc, vc, pos


def test_decode_kernel_matches_einsum():
    from torchpruner_tpu.ops import decode_attention as DA

    q, kc, vc, pos = _decode_case()
    got = DA.decode_attention(q, kc, vc, pos)
    want = DA.xla_decode_attention(q, kc, vc, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_decode_kernel_masks_poisoned_future():
    """Garbage (huge values) past each row's pos — recycled-slot stale
    K/V — must not perturb the result at all."""
    from torchpruner_tpu.ops import decode_attention as DA

    q, kc, vc, pos = _decode_case()
    clean = DA.decode_attention(q, kc, vc, pos)
    kc_p, vc_p = onp.array(kc), onp.array(vc)
    for b, p in enumerate(np.asarray(pos)):
        kc_p[b, p + 1:] = 1e6
        vc_p[b, p + 1:] = -1e6
    poisoned = DA.decode_attention(q, jnp.asarray(kc_p), jnp.asarray(vc_p),
                                   pos)
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(poisoned))


def test_decode_scalar_pos_bit_identical_to_vector():
    """A scalar pos broadcast across the batch (generate's scan) and the
    per-slot vector form (the serve step) must agree BIT-identically —
    the --verify replay contract."""
    from torchpruner_tpu.ops import decode_attention as DA

    q, kc, vc, _ = _decode_case(B=2, T=64)
    p = 37
    vec = DA.decode_attention(q, kc, vc, jnp.asarray([p, p], jnp.int32))
    sca = DA.decode_attention(q, kc, vc, jnp.asarray(p, jnp.int32))
    np.testing.assert_array_equal(np.asarray(vec), np.asarray(sca))


def test_decode_row_independent_of_batch_neighbours():
    """Row b's output depends only on row b's q/cache/pos — solo decode
    (B=1) must reproduce the batched row bit-identically."""
    from torchpruner_tpu.ops import decode_attention as DA

    q, kc, vc, pos = _decode_case(B=3, T=128)
    batched = np.asarray(DA.decode_attention(q, kc, vc, pos))
    for b in range(3):
        solo = DA.decode_attention(q[b:b + 1], kc[b:b + 1], vc[b:b + 1],
                                   pos[b:b + 1])
        np.testing.assert_array_equal(np.asarray(solo)[0], batched[b])


def test_decode_block_is_deterministic_in_T_only():
    from torchpruner_tpu.ops.decode_attention import decode_block

    assert decode_block(64) == 64
    assert decode_block(96) == 32
    assert decode_block(24) == 8
    assert decode_block(512) == 128  # capped at the lane width
    assert decode_block(20) is None  # largest pow2 divisor (4) < 8
    assert decode_block(100) is None


def test_decode_non_blocking_T_falls_back_consistently():
    """T with no clean blocking routes BOTH the batched and the solo
    call to the einsum path — fallback choice is a function of T, so
    bit-identity survives."""
    from torchpruner_tpu.ops import decode_attention as DA

    q, kc, vc, pos = _decode_case(B=2, T=20)
    got = DA.decode_attention(q, kc, vc, pos[:2])
    want = DA.xla_decode_attention(q, kc, vc, pos[:2])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_slot_vs_solo_bit_identity_kernel_blocks():
    """End-to-end ragged parity at a cache length where the KERNEL (not
    the einsum fallback) serves decode: T=32 -> block 32."""
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.ops.decode_attention import decode_block
    from test_generate import ragged_parity_case

    assert decode_block(24) is not None  # ragged_parity_case uses T=24
    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    ragged_parity_case(model, params)


# -- block-sparse matmul -----------------------------------------------------


def _sparse_w(D, F, block, seed=3):
    w = onp.array(jax.random.normal(jax.random.PRNGKey(seed), (D, F)),
                  onp.float32)
    in_keep = tuple(range(0, D // block, 2))
    out_keep = tuple(b for b in range(F // block) if b % 3 != 1)
    for b in range(D // block):
        if b not in in_keep:
            w[b * block:(b + 1) * block] = 0
    for b in range(F // block):
        if b not in out_keep:
            w[:, b * block:(b + 1) * block] = 0
    return jnp.asarray(w), in_keep, out_keep


def test_blocksparse_forward_matches_masked_dense():
    from torchpruner_tpu.ops.blocksparse import blocksparse_matmul

    block = 32
    w, ik, ok = _sparse_w(128, 96, block)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 8, 128))
    got = blocksparse_matmul(x, w, in_keep=ik, out_keep=ok, block=block)
    want = x @ w
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4)
    # dropped output columns are EXACT zeros (mask semantics)
    dropped_cols = [c for b in range(96 // block) if b not in ok
                    for c in range(b * block, (b + 1) * block)]
    assert (np.asarray(got)[..., dropped_cols] == 0).all()


def test_blocksparse_gradients_match_dense_on_kept_blocks():
    from torchpruner_tpu.ops.blocksparse import blocksparse_matmul

    block = 32
    w, ik, ok = _sparse_w(64, 64, block)
    x = jax.random.normal(jax.random.PRNGKey(5), (16, 64))

    def f_sparse(x_, w_):
        return jnp.sum(blocksparse_matmul(
            x_, w_, in_keep=ik, out_keep=ok, block=block) ** 2)

    def f_dense(x_, w_):
        return jnp.sum((x_ @ w_) ** 2)

    gx, gw = jax.grad(f_sparse, argnums=(0, 1))(x, w)
    gx_d, gw_d = jax.grad(f_dense, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_d),
                               atol=1e-3, rtol=1e-4)
    mask = onp.zeros((64, 64), bool)
    for bi in ik:
        for bj in ok:
            mask[bi * block:(bi + 1) * block,
                 bj * block:(bj + 1) * block] = True
    np.testing.assert_allclose(np.asarray(gw)[mask],
                               np.asarray(gw_d)[mask],
                               atol=1e-3, rtol=1e-4)
    # dropped blocks receive EXACTLY zero gradient (they are pruned)
    assert (np.asarray(gw)[~mask] == 0).all()


def test_keep_block_helpers():
    from torchpruner_tpu.ops.blocksparse import (
        keep_blocks_from_drop,
        keep_blocks_from_mask,
    )

    assert keep_blocks_from_drop(128, range(32, 64), 32) == (0, 2, 3)
    assert keep_blocks_from_drop(128, [5], 32) is None  # partial block
    assert keep_blocks_from_drop(100, [], 32) is None   # doesn't tile
    m = onp.ones(96)
    m[64:] = 0
    assert keep_blocks_from_mask(m, 32) == (0, 1)


def test_score_drop_indices_granularity():
    from torchpruner_tpu.core.pruner import score_drop_indices

    scores = onp.arange(256, dtype=onp.float64)  # ascending: low first
    drop = score_drop_indices(scores, policy="fraction", fraction=0.5,
                              granularity=128)
    np.testing.assert_array_equal(drop, onp.arange(128))
    neg = -onp.ones(256)
    neg[128:] = 1.0
    drop2 = score_drop_indices(neg, policy="negative", granularity=64)
    np.testing.assert_array_equal(drop2, onp.arange(128))
    with pytest.raises(ValueError, match="granularity"):
        score_drop_indices(scores[:100], granularity=64)


def test_blocksparse_training_matches_masked_dense():
    """The full integration: drop 50% of a layer's units at 128-block
    granularity, train masked-dense vs block-sparse-dispatched
    (train.loop param_transform) — identical loss/param trajectories,
    masked units pinned at zero."""
    import optax

    from torchpruner_tpu.core import layers as L
    from torchpruner_tpu.core import masking
    from torchpruner_tpu.core.pruner import score_drop_indices
    from torchpruner_tpu.core.segment import SegmentedModel, init_model
    from torchpruner_tpu.train.loop import make_train_step
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    model = SegmentedModel([
        L.Dense("fc1", 32, 256), L.Activation("a1", "relu"),
        L.Dense("fc2", 256, 256), L.Activation("a2", "relu"),
        L.Dense("out", 256, 10),
    ], input_shape=(32,))
    params, state = init_model(model, seed=0)
    scores = onp.asarray(
        jax.random.normal(jax.random.PRNGKey(6), (256,)))
    drop = score_drop_indices(scores, policy="fraction", fraction=0.5,
                              granularity=128)
    drops = {"fc2": drop}
    masks, _ = masking.drop_masks(model, params, drops, state=state)
    mp = masking.apply_masks(params, masks)
    tx = optax.chain(optax.sgd(0.05), masking.masked_update(masks))
    x = jax.random.normal(jax.random.PRNGKey(7), (16, 32))
    y = onp.asarray(jax.random.randint(jax.random.PRNGKey(8), (16,), 0, 10))

    def run(param_transform):
        step = make_train_step(model, tx, cross_entropy_loss,
                               donate=False,
                               param_transform=param_transform)
        p, s, o = mp, state, tx.init(mp)
        for i in range(3):
            p, s, o, l = step(p, s, o, x, y, jax.random.PRNGKey(i))
        return p, float(l)

    p_dense, l_dense = run(None)
    p_sparse, l_sparse = run(lambda p: masking.blocksparse_params(
        model, p, drops, block=128))
    assert l_dense == pytest.approx(l_sparse, rel=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_dense),
                    jax.tree_util.tree_leaves(p_sparse)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=1e-3)
    assert (np.asarray(p_sparse["fc2"]["w"])[:, drop] == 0).all()


def test_qdot_dispatches_blocksparse_weight():
    from torchpruner_tpu.ops.blocksparse import BlockSparseWeight
    from torchpruner_tpu.ops.quant import qdot

    block = 32
    w, ik, ok = _sparse_w(64, 64, block)
    bsw = BlockSparseWeight(w, ik, ok, block)
    x = jax.random.normal(jax.random.PRNGKey(9), (8, 64))
    np.testing.assert_allclose(np.asarray(qdot(x, bsw)),
                               np.asarray(x @ w), atol=1e-4)
    # pytree: wrapping survives jit boundaries with static keep lists
    y = jax.jit(lambda x_, w_: qdot(x_, w_))(x, bsw)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w),
                               atol=1e-4)


# -- fused dequant matmul ----------------------------------------------------


def test_dequant_matmul_int8_fused_scale_parity():
    from torchpruner_tpu.ops.fused_matmul import dequant_matmul
    from torchpruner_tpu.ops.quant import quantize_tensor

    rng = onp.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(256, 384)).astype(onp.float32))
    x = jnp.asarray(rng.normal(size=(8, 256)).astype(onp.float32))
    qt = quantize_tensor(w, in_axes=1)
    got = dequant_matmul(x, qt.q, qt.out_scale(), bits=8)
    want = jnp.dot(x.astype(jnp.bfloat16), qt.q.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32) \
        * qt.out_scale()[None]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-3)


@pytest.mark.parametrize("shape", [(8, 256, 384), (8, 250, 100)])
def test_dequant_matmul_int4_matches_unpack_path(shape):
    """Tiled kernel and non-tiling XLA fallback agree with the
    reference unpack-then-matmul at fused scale."""
    from torchpruner_tpu.ops.fused_matmul import dequant_matmul
    from torchpruner_tpu.ops.int4_matmul import quantize_int4, unpack_int4

    B, D, F = shape
    rng = onp.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(D, F)).astype(onp.float32))
    x = jnp.asarray(rng.normal(size=(B, D)).astype(onp.float32))
    p4, s4 = quantize_int4(w)
    got = dequant_matmul(x, p4, s4, bits=4)
    want = jnp.dot(x.astype(jnp.bfloat16),
                   unpack_int4(p4).astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32) * s4[None]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-3)


def test_qdot_int8_kernel_routing_forced():
    """With INT8_KERNEL forced on, qdot serves int8 QTensors through the
    fused kernel — same result as the XLA convert path within bf16
    accumulation tolerance."""
    from torchpruner_tpu.ops import fused_matmul as FM
    from torchpruner_tpu.ops.quant import oscale, qdot, quantize_tensor

    rng = onp.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(256, 128)).astype(onp.float32))
    x = jnp.asarray(rng.normal(size=(4, 256)).astype(onp.float32)
                    ).astype(jnp.bfloat16)
    qt = quantize_tensor(w, in_axes=1)
    prev = FM.INT8_KERNEL
    try:
        FM.INT8_KERNEL = True
        got = oscale(qdot(x, qt), qt)
    finally:
        FM.INT8_KERNEL = prev
    want = oscale(x @ qt.q.astype(jnp.bfloat16), qt)
    np.testing.assert_allclose(np.asarray(got, onp.float32),
                               np.asarray(want, onp.float32),
                               rtol=2e-2, atol=2e-2)
    assert not FM.int8_kernel_active()  # auto: off on the CPU backend


# -- lint exemption ----------------------------------------------------------


def test_jaxpr_lint_exempts_kernel_internals():
    """A kernel-bearing bf16 program must not trip promoted-matmul or
    dtype-drift on the kernel's deliberate f32 MXU accumulation."""
    from torchpruner_tpu.analysis.jaxpr_lint import lint_jaxpr
    from torchpruner_tpu.ops import flash_attention as F

    prev = F.FORCE_PALLAS
    try:
        F.FORCE_PALLAS = True

        def f(q, k, v):
            return jnp.sum(F.flash_attention(q, k, v, causal=True))

        q = jnp.zeros((1, 64, 2, 16), jnp.bfloat16)
        closed = jax.make_jaxpr(jax.grad(f))(q, q, q)
    finally:
        F.FORCE_PALLAS = prev
    findings = lint_jaxpr(closed, compute_dtype=jnp.bfloat16)
    bad = [x for x in findings
           if "matmul" in x.check or "drift" in x.check]
    assert not bad, [x.message for x in bad]

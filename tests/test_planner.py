"""Auto-parallelism planner (analysis/planner.py, CLI ``--plan auto``).

The search tests run on the 8 virtual CPU devices conftest forces, over
the digits smoke preset — the same geometry the CI planner smoke uses.
One full ``plan_auto`` run is shared module-wide (it compiles ~25 real
candidate programs); the planted-infeasible and probe paths run on
narrowed search spaces to stay fast.
"""

import dataclasses
import json
import os

import pytest

import jax

from torchpruner_tpu.analysis import planner
from torchpruner_tpu.analysis.planner import (
    Candidate,
    enumerate_candidates,
    format_plan,
    plan_auto,
    probe_candidate,
)
from torchpruner_tpu.experiments.presets import mnist_mlp_shapley
from torchpruner_tpu.experiments.prune_retrain import MODEL_REGISTRY


def _cfg(**kw):
    return dataclasses.replace(
        mnist_mlp_shapley(smoke=True), name="planner_test", **kw)


@pytest.fixture(scope="module")
def model():
    return MODEL_REGISTRY[_cfg().model][0]()


@pytest.fixture(scope="module")
def plan(model):
    """One full search over the digits smoke preset on 8 devices."""
    return plan_auto(_cfg(), model=model, n_devices=8)


# ---------------------------------------------------------------------------
# enumeration
# ---------------------------------------------------------------------------


def test_enumerate_baseline_first_and_unique(model):
    cfg = _cfg()
    cands = enumerate_candidates(cfg, 8, model=model)
    assert cands[0].baseline
    assert cands[0].batch_size == cfg.batch_size
    assert cands[0].mesh == {}
    labels = [c.label for c in cands]
    assert len(labels) == len(set(labels))


def test_enumerate_respects_mode_validity(model):
    for c in enumerate_candidates(_cfg(), 8, model=model):
        data = c.mesh.get("data", 1)
        model_ax = c.mesh.get("model", 1)
        if c.zero:
            assert data > 1
        if c.partition == "tp" and not c.baseline:
            assert model_ax > 1
        if c.mesh:
            assert c.batch_size % (data * c.accum_steps) == 0
        # every candidate round-trips through config validation
        c.config(_cfg())


def test_repairs_reround_batch_to_new_accum_multiple():
    """The accum repair must re-round the batch like the enumerator
    does — otherwise the recommended config violates the
    batch % (data * accum) invariant its own search maintains."""
    from torchpruner_tpu.analysis.planner import _repairs

    cand = Candidate(mesh={"data": 4}, partition="fsdp", zero=False,
                     batch_size=12, accum_steps=1, remat=False)
    reps = {r.label: r for r in _repairs(cand)}
    accum_rep = next(r for r in reps.values() if r.accum_steps == 2)
    assert accum_rep.batch_size % (4 * 2) == 0
    assert accum_rep.batch_size == 16  # 12 rounded up to data*accum
    assert all(r.repair_of == cand.label for r in reps.values())


def test_candidate_labels_are_stable():
    c = Candidate(mesh={"data": 4, "model": 2}, partition="tp",
                  zero=True, batch_size=128, accum_steps=2, remat=True)
    assert c.label == "d4xm2/tp/zero/b128/a2/remat"
    c2 = Candidate(mesh={}, partition="fsdp", zero=False,
                   batch_size=32, accum_steps=1, remat=False)
    assert c2.label == "single/local/b32"


# ---------------------------------------------------------------------------
# the full search
# ---------------------------------------------------------------------------


def test_plan_ranks_three_plus_feasible_candidates(plan):
    """The acceptance bar: >= 3 feasible candidates ranked by predicted
    step time, each lint-clean and within its own HBM budget."""
    assert len(plan["ranked"]) >= 3
    by_label = {c["label"]: c for c in plan["candidates"]}
    for label in plan["ranked"]:
        c = by_label[label]
        assert c["feasible"]
        assert not c["lint"]["errors"], (label, c["lint"])
        assert c["hbm"]["fits"], label
        assert c["predicted"]["step_ms"] > 0
        assert c["predicted"]["bound"] in ("compute", "hbm", "ici")
    # ranked is genuinely ordered by predicted ms/example
    scores = [by_label[l]["predicted"]["step_ms_per_example"]
              for l in plan["ranked"]]
    assert scores == sorted(scores)


def test_winner_beats_hand_written_baseline(plan):
    by_label = {c["label"]: c for c in plan["candidates"]}
    winner = by_label[plan["winner"]]
    baseline = by_label[plan["baseline"]]
    assert baseline["baseline"]
    assert winner["predicted"]["step_ms_per_example"] <= \
        baseline["predicted"]["step_ms_per_example"]
    assert plan["margin_over_baseline_pct"] is not None


def test_plan_artifact_renders_and_roundtrips(plan, tmp_path):
    text = format_plan(plan)
    assert plan["winner"] in text
    assert "| bound |" in text.replace("| bound ", "| bound |")[:10**6]
    path = tmp_path / "plan.json"
    planner.write_plan(plan, str(path))
    again = json.loads(path.read_text())
    assert again["ranked"] == plan["ranked"]
    assert format_plan(again) == text


def test_planted_infeasible_excluded_loudly_by_name(plan, model):
    """The CI drill's logic: an HBM budget planted between two
    candidates' watermarks must exclude the over-budget candidate BY
    NAME (artifact reasons + planner/over-hbm finding), never
    silently."""
    ws = sorted({c["hbm"]["watermark_bytes_per_chip"]
                 for c in plan["candidates"]})
    assert ws[0] < ws[-1], "search space must spread watermarks"
    budget = (ws[0] + ws[-1]) / 2 / 0.85
    narrowed = plan_auto(_cfg(), model=model, n_devices=8,
                         batch_ladder=(1, 2), hbm_budget=budget)
    over = [c for c in narrowed["candidates"] if c["excluded_by"] == "hbm"]
    kept = [c for c in narrowed["candidates"] if c["feasible"]]
    assert over, "planted budget excluded nothing"
    assert kept, "planted budget excluded everything"
    finding_paths = {f["path"] for f in narrowed["findings"]
                     if f["check"] == "planner/over-hbm"}
    rendered = format_plan(narrowed)
    for c in over:
        assert c["label"] not in narrowed["ranked"]
        assert any("HBM watermark" in r for r in c["reasons"]), c
        assert c["label"] in finding_paths
        # the exact exclusion line — a ranked repair label like
        # `<victim>/a2` must not satisfy this by substring
        assert f"- `{c['label']}` [hbm]" in rendered


def test_no_feasible_candidate_is_an_error_finding(model):
    out = plan_auto(_cfg(), model=model, n_devices=8, hbm_budget=1.0)
    assert out["ranked"] == []
    assert out["winner"] is None
    assert any(f["check"] == "planner/no-feasible"
               and f["severity"] == "error" for f in out["findings"])


def test_compile_cap_truncates_loudly(model):
    out = plan_auto(_cfg(), model=model, n_devices=8, max_compile=3)
    capped = [c for c in out["candidates"] if c["excluded_by"] == "cap"]
    assert capped
    assert any(f["check"] == "planner/truncated" for f in out["findings"])
    assert len(out["ranked"]) <= 3


# ---------------------------------------------------------------------------
# probes
# ---------------------------------------------------------------------------


def test_probe_measures_and_gates(model):
    cfg = _cfg()
    cand = Candidate(mesh={"data": 2}, partition="fsdp", zero=False,
                     batch_size=cfg.batch_size, accum_steps=1,
                     remat=False)
    cand.predicted = {"step_ms": 1e-6, "flops": 1e6,
                      "step_ms_per_example": 1e-9}
    probe = probe_candidate(cand, cfg, model, steps=2, warmup=1)
    assert probe["measured_ms"] > 0
    assert probe["steps"] == 2
    # a 1 ns prediction can never be within 30% of a real measurement
    assert probe["gated"] and abs(probe["drift_pct"]) > 30
    assert probe["mfu"] > 0


def test_probe_demotes_gated_candidates(model):
    out = plan_auto(_cfg(), model=model, n_devices=8, probe_top=2,
                    probe_steps=2, batch_ladder=(1,), max_model=1,
                    drift_gate_pct=1e-9)  # everything probed gates
    probed = [c for c in out["candidates"]
              if (c.get("probe") or {}).get("gated")]
    assert probed, "top candidates must have been probed and gated"
    # gated candidates sank below every un-probed feasible one
    ranked = out["ranked"]
    gated_idx = [ranked.index(c["label"]) for c in probed
                 if c["label"] in ranked]
    clean_idx = [i for i, l in enumerate(ranked)
                 if l not in {c["label"] for c in probed}]
    if clean_idx and gated_idx:
        assert min(gated_idx) > max(clean_idx)
    assert any(f["check"] == "planner/probe-drift"
               for f in out["findings"])


# ---------------------------------------------------------------------------
# CLI + obs wiring
# ---------------------------------------------------------------------------


def test_cli_plan_auto_and_report(tmp_path, capsys):
    from torchpruner_tpu.__main__ import main

    out = str(tmp_path / "plan.json")
    rc = main(["mnist_mlp_shapley", "--smoke", "--cpu", "--plan", "auto",
               "--plan-out", out, "--no-compilation-cache"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "winner" in text and os.path.exists(out)
    rc = main(["mnist_mlp_shapley", "--smoke", "--cpu", "--plan",
               "report", "--plan-out", out, "--no-compilation-cache"])
    assert rc == 0
    assert "plan: mnist_mlp_shapley" in capsys.readouterr().out


def test_plan_gauges_and_ledger_record_land(tmp_path, model):
    from torchpruner_tpu import obs
    from torchpruner_tpu.obs.report import load_run

    obs_dir = str(tmp_path / "obs")
    obs.configure(obs_dir)
    try:
        plan_auto(_cfg(), model=model, n_devices=8, batch_ladder=(1,),
                  max_model=1)
    finally:
        obs.shutdown()
    rep = load_run(obs_dir)
    metrics = rep.get("metrics") or {}
    assert metrics.get("plan_candidates_total", 0) >= 2
    assert metrics.get("plan_feasible_total", 0) >= 1
    assert metrics.get("plan_winner_step_ms", 0) > 0
    recs = rep.get("plan") or []
    assert recs and recs[-1]["winner"]
    # the report renders a plan section
    from torchpruner_tpu.obs.report import format_report

    assert "plan: winner" in format_report(rep)

"""End-to-end experiment drivers on tiny models/data (the minimum
end-to-end slice of SURVEY.md §7, as a test)."""

import numpy as np
import optax

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.data import synthetic_dataset
from torchpruner_tpu.experiments import (
    ablation_curve,
    build_metric,
    layerwise_robustness,
    run_prune_retrain,
)
from torchpruner_tpu.experiments.robustness import auc_summary, loss_increase_auc
from torchpruner_tpu.utils.config import ExperimentConfig
from torchpruner_tpu.utils.losses import cross_entropy_loss


def tiny_model():
    return SegmentedModel(
        (L.Dense("fc1", 16), L.Activation("r1", "relu"),
         L.Dense("fc2", 16), L.Activation("r2", "relu"),
         L.Dense("out", 4)),
        (8,),
    )


def tiny_sets():
    train = synthetic_dataset((8,), 4, 256, seed=1)
    val = synthetic_dataset((8,), 4, 64, seed=2)
    test = synthetic_dataset((8,), 4, 64, seed=3)
    return train, val, test


def test_prune_retrain_shapley_end_to_end(tmp_path):
    """The full spine: dataset → Shapley scores → negative-index prune →
    recompiled fine-tune step → evaluation (reference 'Pruning Untrained
    Networks' recipe)."""
    cfg = ExperimentConfig(
        name="tiny", method="shapley",
        method_kwargs={"sv_samples": 3},
        policy="fraction", fraction=0.25,
        finetune_epochs=1, batch_size=32, eval_batch_size=32,
        lr=0.05, log_path=str(tmp_path / "log.csv"),
    )
    history = run_prune_retrain(
        cfg, model=tiny_model(), datasets=tiny_sets(), verbose=False
    )
    assert [h.layer for h in history] == ["fc2", "fc1"]  # outermost first
    assert all(h.n_dropped == 4 for h in history)
    assert history[-1].widths == {"fc1": 12, "fc2": 12, "out": 4}
    assert np.isfinite(history[-1].post_loss)
    assert (tmp_path / "log.csv").exists()


def test_prune_retrain_negative_policy(tmp_path):
    cfg = ExperimentConfig(
        name="neg", method="taylor", reduction="mean",
        policy="negative", finetune_epochs=0,
        eval_batch_size=32, log_path=str(tmp_path / "l.csv"),
    )
    history = run_prune_retrain(
        cfg, model=tiny_model(), datasets=tiny_sets(), verbose=False
    )
    assert len(history) == 2


def test_ablation_curve_monotonic_degradation():
    """Removing ALL units must end at a fully-ablated network; the curve's
    last point equals masking everything; base point equals no masking."""
    model = tiny_model()
    params, state = init_model(model, seed=0)
    _, _, test = tiny_sets()
    data = test.batches(32)
    n = 16
    ranking = np.arange(n)
    curve = ablation_curve(model, params, state, "fc1", ranking, data,
                           cross_entropy_loss)
    assert curve["loss"].shape == (n,)
    # removing nothing (base) should differ from removing everything
    assert curve["loss"][-1] != curve["base_loss"]
    auc = loss_increase_auc(curve)
    assert np.isfinite(auc)


def test_ablation_curve_sharded_matches_single_device():
    """The mesh-sharded ablation (batches split over the data axis, XLA
    all-reducing the loss/count sums) must reproduce the single-device
    curve exactly — the pod-scale path for the 6.5 h-baseline sweep."""
    from torchpruner_tpu.parallel import make_mesh

    model = tiny_model()
    params, state = init_model(model, seed=0)
    _, _, test = tiny_sets()
    ranking = np.arange(16)
    want = ablation_curve(model, params, state, "fc1", ranking,
                          test.batches(32), cross_entropy_loss)
    mesh = make_mesh({"data": 8})
    got = ablation_curve(model, params, state, "fc1", ranking,
                         test.batches(32, drop_remainder=True),
                         cross_entropy_loss, mesh=mesh)
    np.testing.assert_allclose(got["loss"], want["loss"], rtol=1e-5)
    np.testing.assert_allclose(got["acc"], want["acc"], rtol=1e-5)

    # non-dividing batches are rejected with the drop_remainder hint
    import pytest

    with pytest.raises(ValueError, match="drop_remainder"):
        ablation_curve(model, params, state, "fc1", ranking,
                       [(np.zeros((5, 16), np.float32),
                         np.zeros((5,), np.int32))],
                       cross_entropy_loss, mesh=mesh)


def test_batched_ablation_matches_per_curve():
    """ablation_curves_batch (one vmapped scan over all rankings) must
    reproduce each individual ablation_curve exactly."""
    from torchpruner_tpu.experiments.robustness import ablation_curves_batch

    model = tiny_model()
    params, state = init_model(model, seed=0)
    _, _, test = tiny_sets()
    rng = np.random.default_rng(3)
    rankings = np.stack([rng.permutation(16) for _ in range(5)])
    batched = ablation_curves_batch(
        model, params, state, "fc1", rankings, test.batches(32),
        cross_entropy_loss,
    )
    for r, curve in zip(rankings, batched):
        want = ablation_curve(model, params, state, "fc1", r,
                              test.batches(32), cross_entropy_loss)
        np.testing.assert_allclose(curve["loss"], want["loss"], rtol=1e-5)
        np.testing.assert_allclose(curve["acc"], want["acc"], rtol=1e-5)
        assert curve["base_loss"] == want["base_loss"]


def test_ablation_curve_bf16_close_to_f32():
    """bf16 ablation forwards (the TPU sweep configuration) must agree
    with f32 at bf16 noise level — same ranking quality, MXU-rate math."""
    import jax.numpy as jnp

    model = tiny_model()
    params, state = init_model(model, seed=0)
    _, _, test = tiny_sets()
    ranking = np.arange(16)
    f32 = ablation_curve(model, params, state, "fc1", ranking,
                         test.batches(32), cross_entropy_loss)
    b16 = ablation_curve(model, params, state, "fc1", ranking,
                         test.batches(32), cross_entropy_loss,
                         compute_dtype=jnp.bfloat16)
    assert b16["loss"].dtype == np.float64 or np.issubdtype(
        b16["loss"].dtype, np.floating)
    np.testing.assert_allclose(b16["loss"], f32["loss"], rtol=0.05,
                               atol=0.05)
    np.testing.assert_allclose(
        loss_increase_auc(b16), loss_increase_auc(f32), atol=0.05
    )


def test_robustness_config_over_mesh(tmp_path):
    """cfg.mesh shards the whole sweep: DistributedScorer for the metric
    rows, sharded ablation batches; AUCs must match the unsharded run."""
    from torchpruner_tpu.experiments.robustness import run_robustness_config

    kw = dict(
        name="spmd_sweep", model="digits_fc", dataset="digits_flat",
        experiment="robustness", method="taylor", score_examples=64,
        eval_batch_size=32, target_filter=("fc2",),
        log_path=str(tmp_path / "log.csv"),
    )
    plain = run_robustness_config(ExperimentConfig(**kw), verbose=False)
    spmd = run_robustness_config(
        ExperimentConfig(**kw, mesh={"data": 8}), verbose=False
    )
    assert abs(spmd["taylor"] - plain["taylor"]) < 1e-4


def test_layerwise_robustness_sweep_ranks_methods():
    """A trained model's Shapley/Taylor rankings should beat an adversarial
    (worst-first) ranking; smoke-checks the full sweep structure."""
    import optax
    from torchpruner_tpu.train import Trainer, train_epoch

    model = tiny_model()
    train, val, test = tiny_sets()
    trainer = Trainer.create(model, optax.adam(1e-2), cross_entropy_loss)
    for e in range(3):
        train_epoch(trainer, train.batches(32, shuffle=True, seed=e),
                    verbose=False)
    model, params, state = trainer.model, trainer.params, trainer.state
    val_b = val.batches(32)
    test_b = test.batches(32)

    methods = {
        "taylor": lambda: build_metric("taylor", model, params, val_b,
                                       cross_entropy_loss, state=state),
        "sv": lambda: build_metric("shapley", model, params, val_b,
                                   cross_entropy_loss, state=state,
                                   sv_samples=3),
        "random": lambda: build_metric("random", model, params, val_b,
                                       cross_entropy_loss, state=state),
    }
    results = layerwise_robustness(
        model, params, state, test_b, methods, cross_entropy_loss,
        runs_stochastic=2, verbose=False,
    )
    assert set(results.keys()) == {"fc1", "fc2"}
    assert len(results["fc1"]["sv"]) == 2   # stochastic repeats
    assert len(results["fc1"]["taylor"]) == 1
    summary = auc_summary(results)
    assert set(summary) == {"taylor", "sv", "random"}
    # informed rankings should not be worse than random on average
    assert summary["sv"] <= summary["random"] + 0.5


def test_mean_plus_2std_reduction_via_registry():
    model = tiny_model()
    params, state = init_model(model, 0)
    _, val, _ = tiny_sets()
    m = build_metric("shapley", model, params, val.batches(32),
                     cross_entropy_loss, state=state, reduction="mean+2std",
                     sv_samples=2)
    scores = m.run("fc1")
    assert scores.shape == (16,)


def test_run_train_end_to_end_with_resume(tmp_path):
    """From-scratch training driver: multistep schedule, augmentation off,
    per-epoch CSV rows, checkpoint at the end, resume continues at the
    saved epoch."""
    from torchpruner_tpu.experiments.train_model import run_train

    ckpt = str(tmp_path / "ckpt")
    cfg = ExperimentConfig(
        name="train_tiny", experiment="train", epochs=2, batch_size=32,
        eval_batch_size=32, lr=0.05, lr_schedule="multistep",
        lr_milestones=(1,), lr_gamma=0.5,
        checkpoint_path=ckpt, log_path=str(tmp_path / "t.csv"),
    )
    trainer, history = run_train(
        cfg, model=tiny_model(), datasets=tiny_sets(), verbose=False
    )
    assert [h["epoch"] for h in history] == [0, 1]
    assert history[-1]["train_loss"] < history[0]["train_loss"] * 1.5
    assert (tmp_path / "t.csv").exists()

    # resume: checkpoint says epoch 2, so 3-epoch run does exactly 1 more
    # (same optimizer/schedule — the checkpoint's opt-state layout check
    # rightly rejects a different one)
    cfg3 = ExperimentConfig(
        name="train_tiny", experiment="train", epochs=3, batch_size=32,
        eval_batch_size=32, lr=0.05, lr_schedule="multistep",
        lr_milestones=(1,), lr_gamma=0.5,
        checkpoint_path=ckpt, log_path=str(tmp_path / "t.csv"),
    )
    _, hist2 = run_train(
        cfg3, model=tiny_model(), datasets=tiny_sets(), verbose=False
    )
    assert [h["epoch"] for h in hist2] == [2]


def test_run_train_elastic_recovers_from_mid_run_failure(tmp_path,
                                                         monkeypatch):
    """An injected mid-training crash (a preemption stand-in) must restart
    from the last checkpoint and finish all epochs."""
    from torchpruner_tpu.experiments.train_model import run_train_elastic
    from torchpruner_tpu.train import Trainer

    calls = {"n": 0}
    orig = Trainer.step

    def flaky(self, x, y):
        calls["n"] += 1
        if calls["n"] == 10:  # inside epoch 1, after epoch 0's checkpoint
            raise RuntimeError("injected preemption")
        return orig(self, x, y)

    monkeypatch.setattr(Trainer, "step", flaky)
    cfg = ExperimentConfig(
        name="elastic", experiment="train", epochs=3, batch_size=32,
        eval_batch_size=32, lr=0.05,
        checkpoint_path=str(tmp_path / "ckpt"),
        checkpoint_every_epochs=1, log_path=str(tmp_path / "t.csv"),
    )
    trainer, history = run_train_elastic(
        cfg, model=tiny_model(), datasets=tiny_sets(), verbose=False
    )
    assert history[-1]["epoch"] == 2       # completed all epochs
    assert history[0]["epoch"] >= 1        # resumed, not from scratch
    assert calls["n"] > 10                 # training continued past the crash

    # refuses to run without a checkpoint path (restart-from-scratch trap)
    import pytest

    with pytest.raises(ValueError, match="checkpoint_path"):
        run_train_elastic(
            ExperimentConfig(name="x", experiment="train", epochs=1),
            verbose=False,
        )


def test_run_train_prefetch_matches_inmemory_bitwise(tmp_path):
    """The native prefetch path and the in-memory path draw the same
    splitmix64 shuffle — training through either must produce identical
    losses (the C++ pipeline is load-bearing, not ornamental)."""
    from torchpruner_tpu.experiments.train_model import run_train

    def cfg(prefetch):
        return ExperimentConfig(
            name=f"pf{prefetch}", experiment="train", epochs=2,
            batch_size=32, eval_batch_size=32, lr=0.05,
            prefetch=prefetch, log_path=str(tmp_path / f"{prefetch}.csv"),
        )

    _, h_pf = run_train(cfg(True), model=tiny_model(), datasets=tiny_sets(),
                        verbose=False)
    _, h_mem = run_train(cfg(False), model=tiny_model(), datasets=tiny_sets(),
                         verbose=False)
    assert [h["train_loss"] for h in h_pf] == [h["train_loss"] for h in h_mem]
    assert [h["test_loss"] for h in h_pf] == [h["test_loss"] for h in h_mem]


def test_run_train_device_prefetch_matches_unprefetched(tmp_path):
    """device_prefetch stages batches on device ahead of the step (async
    transfer overlap) — order, contents, and therefore the training
    trajectory must be unchanged vs the unprefetched path."""
    from torchpruner_tpu.experiments.train_model import run_train

    def cfg(dp):
        return ExperimentConfig(
            name=f"dp{dp}", experiment="train", epochs=2,
            batch_size=32, eval_batch_size=32, lr=0.05,
            device_prefetch=dp, log_path=str(tmp_path / f"dp{dp}.csv"),
        )

    _, h_dp = run_train(cfg(3), model=tiny_model(), datasets=tiny_sets(),
                        verbose=False)
    _, h_off = run_train(cfg(0), model=tiny_model(), datasets=tiny_sets(),
                         verbose=False)
    assert [h["train_loss"] for h in h_dp] == [h["train_loss"] for h in h_off]
    assert [h["test_acc"] for h in h_dp] == [h["test_acc"] for h in h_off]


def test_device_prefetch_preserves_short_streams():
    from torchpruner_tpu.data import device_prefetch

    batches = [(np.full((2, 2), i), np.full((2,), i)) for i in range(5)]
    out = list(device_prefetch(iter(batches), size=8))  # size > stream
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        np.testing.assert_array_equal(np.asarray(x), batches[i][0])
        np.testing.assert_array_equal(np.asarray(y), batches[i][1])
    assert list(device_prefetch(iter([]), size=2)) == []


def test_augmented_epoch_stream_is_deterministic():
    """epoch_batches with augment=True draws per-batch seeds from
    (cfg.seed, epoch) — the same config must reproduce the same augmented
    stream, different epochs must differ (native/fallback equality is
    covered in test_native_data.py)."""
    from torchpruner_tpu.experiments.train_model import epoch_batches

    ds = synthetic_dataset((8, 8, 3), 4, 96, seed=1)
    cfg = ExperimentConfig(name="aug", experiment="train", batch_size=32,
                           augment=True)
    a = [x for x, _ in epoch_batches(ds, cfg, epoch=0)]
    b = [x for x, _ in epoch_batches(ds, cfg, epoch=0)]
    c = [x for x, _ in epoch_batches(ds, cfg, epoch=1)]
    for xa, xb in zip(a, b):
        np.testing.assert_array_equal(xa, xb)
    assert not all(np.array_equal(xa, xc) for xa, xc in zip(a, c))
    assert a[0].shape == (32, 8, 8, 3)


def test_robustness_config_writes_figures(tmp_path):
    import os

    from torchpruner_tpu.experiments.robustness import run_robustness_config

    cfg = ExperimentConfig(
        name="plots", model="digits_fc", dataset="digits_flat",
        experiment="robustness", method="taylor", score_examples=64,
        eval_batch_size=64, target_filter=("fc2",),
        plot_dir=str(tmp_path / "figs"),
        log_path=str(tmp_path / "log.csv"),
    )
    aucs = run_robustness_config(cfg, verbose=False)
    assert "taylor" in aucs
    assert os.path.getsize(tmp_path / "figs" / "robustness_fc2.png") > 0
    assert os.path.getsize(tmp_path / "figs" / "auc_summary.png") > 0


def test_robustness_config_writes_results_json(tmp_path):
    """cfg.results_path dumps the full sweep (curves, scores, AUCs) as
    JSON — the durable artifact the reference keeps as a pickle."""
    import json

    from torchpruner_tpu.experiments.robustness import run_robustness_config

    cfg = ExperimentConfig(
        name="dump", model="digits_fc", dataset="digits_flat",
        experiment="robustness", method="weight_norm", score_examples=64,
        eval_batch_size=64, target_filter=("fc2",),
        results_path=str(tmp_path / "out" / "results.json"),
        log_path=str(tmp_path / "log.csv"),
    )
    aucs = run_robustness_config(cfg, verbose=False)
    blob = json.loads((tmp_path / "out" / "results.json").read_text())
    assert blob["auc_summary"] == aucs
    run = blob["results"]["fc2"]["weight_norm"][0]
    assert len(run["loss"]) == len(run["scores"]) > 0
    assert isinstance(run["auc"], float)


def test_prune_retrain_over_configured_mesh(tmp_path):
    """cfg.mesh drives the SPMD loop: ShardedTrainer training, data-
    parallel scoring, prune->reshard->step — the full distributed recipe
    from one config.  score_examples=30 leaves a remainder batch, which
    mesh mode must drop rather than crash on."""
    from torchpruner_tpu.experiments.prune_retrain import run_prune_retrain

    cfg = ExperimentConfig(
        name="mesh_prune", model="llama_tiny", dataset="lm_tiny",
        loss="lm_cross_entropy", method="taylor", policy="fraction",
        fraction=0.25, target_filter=("_ffn/",), finetune_epochs=1,
        score_examples=30, batch_size=8, eval_batch_size=16,
        mesh={"data": 2, "model": 4}, partition="tp",
        compute_dtype="bfloat16", remat=True,
        log_path=str(tmp_path / "mesh_prune.csv"),
    )
    records = run_prune_retrain(cfg, verbose=False)
    assert len(records) >= 1
    for r in records:
        assert np.isfinite(r.post_loss)
        assert r.n_dropped > 0


def test_head_to_head_smoke_runs_reference_library():
    """The same-box reference comparison drives the ACTUAL reference
    package (torch CPU) and ours through the untrained-prune recipe on
    shared weights; the protocol must agree (same prunable widths both
    sides).  Skips when torch or the reference tree is absent."""
    import os

    import pytest

    pytest.importorskip("torch")
    from torchpruner_tpu.experiments.head_to_head import REFERENCE, run

    if not os.path.isdir(os.path.join(REFERENCE, "torchpruner")):
        pytest.skip("reference tree not available")
    r = run(smoke=True)
    # both sides start identical and prune a comparable negative set
    # (exact membership is Monte-Carlo — the reference's permutations
    # draw from numpy's global state, so run-to-run sets differ)
    assert r["ours"]["params"][0] == r["reference"]["params"][0]
    for side in ("ours", "reference"):
        before, after = r[side]["params"]
        assert after < before
    ratio = r["ours"]["params"][1] / r["reference"]["params"][1]
    assert 0.7 < ratio < 1.4, r
    assert r["speedup_same_box_cpu"] > 0
    assert min(r["score_spearman"].values()) > 0.2  # same-weights signal

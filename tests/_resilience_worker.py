"""Worker for the crash-resume tests (not collected by pytest).

Run as ``python _resilience_worker.py <run_dir> [chaos_json] [mode]``:
trains the digits smoke preset (``digits_fc_tiny``) resiliently into
``run_dir``, optionally under a chaos config (e.g. a deterministic
SIGKILL at a step boundary).  ``mode="zero"`` trains the same preset as
an SPMD run over a ``{"data": 2, "model": 2}`` mesh with ZeRO
weight-update sharding (``cfg.mesh`` + ``cfg.zero`` — the parent sets
``XLA_FLAGS=--xla_force_host_platform_device_count=4``), exercising the
sharded-checkpoint → re-placed-restore path.  On a COMPLETED run prints
one JSON line with the final eval metrics; a chaos-killed run prints
nothing (SIGKILL allows no goodbye) — the parent detects death by exit
code and re-runs without chaos to exercise the resume path.
"""

import json
import os
import sys

import jax

# in-process platform selection: with the experimental TPU plugin
# installed the JAX_PLATFORMS env var alone does not defeat plugin
# discovery (see tests/conftest.py)
jax.config.update("jax_platforms", "cpu")


def smoke_config(run_dir: str, chaos: dict, mode: str = ""):
    from torchpruner_tpu.utils.config import ExperimentConfig

    kw = {}
    if mode == "zero":
        kw = {"mesh": {"data": 2, "model": 2}, "zero": True}
    return ExperimentConfig(
        name="resilience_smoke",
        model="digits_fc_tiny",
        dataset="digits_flat",
        experiment="train",
        epochs=2,
        batch_size=32,
        eval_batch_size=64,
        lr=0.05,
        run_dir=run_dir,
        checkpoint_every_steps=7,
        guard_nonfinite=True,
        chaos=chaos,
        log_path=os.path.join(run_dir, "log.csv"),
        **kw,
    )


def main() -> None:
    run_dir = sys.argv[1]
    chaos = json.loads(sys.argv[2]) if len(sys.argv) > 2 and sys.argv[2] \
        else {}
    mode = sys.argv[3] if len(sys.argv) > 3 else ""
    cfg = smoke_config(run_dir, chaos, mode)
    trainer, history = __import__(
        "torchpruner_tpu.experiments.train_model",
        fromlist=["run_train"],
    ).run_train(cfg, verbose=False)
    last = history[-1]
    import numpy as np

    w = np.asarray(jax.device_get(trainer.params["fc1"]["w"]))
    print(json.dumps({
        "epochs": len(history),
        "final_test_loss": last["test_loss"],
        "final_test_acc": last["test_acc"],
        "steps": int(trainer.step_count),
        "w_abs_sum": float(np.abs(w).sum()),
        "devices": jax.device_count(),
    }), flush=True)


if __name__ == "__main__":
    main()

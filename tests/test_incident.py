"""Incident correlation (torchpruner_tpu.obs.incident): deterministic
suspect scoring (proximity x prior x replica match), trigger-echo
exclusion, absorb-coalescing (exactly one incident per episode), the
online correlator through the session's ``record_serve`` hook, the
supervisor's ``correlation_id``, the SLO burn-episode histogram, offline
reconstruction from a run dir's artifacts, and the ``obs incident`` CLI
exit-code contract."""

import json
import os

import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.obs.incident import (
    IncidentCorrelator,
    assemble_incident,
    assemble_run_incidents,
    correlate,
    rank_suspects,
    replica_hint,
    score_candidate,
    sparkline,
    triggers_of,
)
from torchpruner_tpu.obs.ledger import LEDGER_FILENAME, load_ledger
from torchpruner_tpu.obs.metrics import MetricsRegistry
from torchpruner_tpu.obs.report import obs_main
from torchpruner_tpu.serve.slo import SLOMonitor


@pytest.fixture(autouse=True)
def _clean_session():
    obs.shutdown()
    yield
    obs.shutdown()


class _Ledger:
    def __init__(self, recs=None):
        self.recs = list(recs or [])

    def records(self, event=None):
        return [r for r in self.recs
                if event is None or r.get("event") == event]

    def record(self, rec):
        self.recs.append(dict(rec))


def _trigger(ts=1000.0, replica="replica0"):
    return {"kind": "slo_burn", "ts": ts, "metric": "token",
            "replica": replica, "burn_fast": 50.0, "burn_slow": 20.0}


# -- scoring -----------------------------------------------------------------


def test_score_candidate_horizon_and_factors():
    rec = {"event": "serve", "kind": "chaos_injection",
           "replica": "replica0", "ts": 990.0}
    score, f = score_candidate(rec, 1000.0, "replica0", 100.0)
    # proximity 0.9 x prior 1.0 x same-replica 1.0
    assert score == pytest.approx(0.9)
    assert f == {"proximity": 0.9, "prior": 1.0, "replica_match": 1.0,
                 "dt_s": -10.0}
    # outside the horizon: not a candidate at all
    assert score_candidate(rec, 2000.0, "replica0", 100.0) is None
    # replica mismatch quarters the score; unknown replica halves it
    s_mismatch, _ = score_candidate(rec, 1000.0, "replica1", 100.0)
    assert s_mismatch == pytest.approx(0.9 * 0.25)
    s_unknown, _ = score_candidate(
        {"event": "serve", "kind": "scale_decision", "ts": 990.0},
        1000.0, "replica0", 100.0)
    assert s_unknown == pytest.approx(0.9 * 0.8 * 0.5)


def test_rank_suspects_planted_cause_wins_and_echo_excluded():
    records = [
        # the trigger's own ledger record: must NOT rank
        {"event": "serve", "kind": "slo_burn", "replica": "replica0",
         "ts": 1000.2, "metric": "token"},
        {"event": "serve", "kind": "chaos_injection",
         "replica": "replica0", "ts": 978.0, "chaos": "slow_replica",
         "slow_steps_ms": 250},
        {"event": "serve", "kind": "scale_decision", "ts": 995.0,
         "action": "scale_up"},
        {"event": "serve", "kind": "hot_swap", "replica": "replica1",
         "ts": 999.0},
        # excluded event classes never rank
        {"event": "reqtrace", "ts": 999.5, "exemplars": []},
        {"event": "round", "ts": 999.6},
    ]
    got = rank_suspects(records, _trigger(), 120.0)
    assert [s["class"] for s in got] == [
        "chaos_injection", "scale_decision", "hot_swap"]
    assert [s["rank"] for s in got] == [1, 2, 3]
    top = got[0]
    assert top["replica"] == "replica0"
    assert "slow_steps_ms=250" in top["evidence"]
    # deterministic: same input, same order
    assert got == rank_suspects(records, _trigger(), 120.0)


def test_rank_ties_break_by_time_then_class():
    records = [
        {"event": "serve", "kind": "preemption", "ts": 990.0},
        {"event": "serve", "kind": "preemption", "ts": 980.0},
    ]
    got = rank_suspects(records, _trigger(), 120.0)
    # equal class/prior: nearer in time scores higher
    assert got[0]["ts"] == 990.0 and got[0]["rank"] == 1


def test_replica_hint_parses_router_scrape_gauges():
    assert replica_hint("fleet_replica_replica2_occupancy") == "replica2"
    assert replica_hint("fleet_replica_r0_queue_depth") == "r0"
    assert replica_hint("serve_token_seconds_p99") is None


def test_assemble_incident_shape():
    records = [{"event": "serve", "kind": "chaos_injection",
                "replica": "replica0", "ts": 990.0}]
    inc = assemble_incident(_trigger(), records, incident_id="inc-1",
                            lookback_s=100.0)
    assert inc["event"] == "incident" and inc["incident_id"] == "inc-1"
    assert inc["span"] == {"t0": 900.0, "t1": 1100.0}
    assert inc["top_suspect"]["class"] == "chaos_injection"
    assert inc["triggers_absorbed"] == 0
    # strict JSON round-trip (it is a ledger record)
    json.dumps(inc)


# -- online correlator -------------------------------------------------------


def test_correlator_absorbs_triggers_within_lookback():
    led = _Ledger([{"event": "serve", "kind": "chaos_injection",
                    "replica": "replica0", "ts": 990.0}])
    c = IncidentCorrelator(ledger=led, lookback_s=100.0)
    inc = c.trigger(kind="slo_burn", ts=1000.0, metric="token",
                    replica="replica0")
    assert inc is not None and inc["incident_id"] == "inc-1"
    # a second trigger in-window folds in instead of opening a new one
    assert c.trigger(kind="slo_burn", ts=1050.0, metric="ttft") is None
    assert c.trigger(kind="anomaly", ts=1080.0,
                     anomaly_id="anom-7") is None
    assert len(c.incidents) == 1
    assert c.incidents[0]["triggers_absorbed"] == 2
    assert "anom-7" in c.incidents[0]["anomalies"]
    # far outside the window: a fresh incident
    assert c.trigger(kind="slo_burn", ts=5000.0)["incident_id"] == "inc-2"
    # both ledgered exactly once each
    assert len(led.records(event="incident")) == 2


def test_correlator_active_id_window():
    c = IncidentCorrelator(lookback_s=100.0)
    assert c.active_id(now=1000.0) is None
    c.trigger(kind="slo_burn", ts=1000.0, replica="replica0")
    assert c.active_id(now=1050.0) == "inc-1"
    assert c.active_id(now=2000.0) is None


def test_correlator_finalize_sets_gauges_even_when_zero():
    reg = MetricsRegistry()
    IncidentCorrelator(lookback_s=10.0).finalize(reg)
    snap = reg.snapshot()
    assert snap["incident_count"] == 0.0
    assert snap["incident_top_suspect_score"] == 0.0
    c = IncidentCorrelator(ledger=_Ledger([
        {"event": "serve", "kind": "chaos_injection",
         "replica": "replica0", "ts": 995.0}]), lookback_s=100.0)
    c.trigger(kind="slo_burn", ts=1000.0, replica="replica0")
    c.trigger(kind="slo_burn", ts=1001.0)
    c.finalize(reg)
    snap = reg.snapshot()
    assert snap["incident_count"] == 1.0
    assert snap["incident_absorbed_triggers"] == 1.0
    assert snap["incident_top_suspect_score"] > 0.9


def test_record_serve_burn_hook_opens_incident(tmp_path):
    """The wiring serve AND fleet frontends get for free: any ledgered
    ``slo_burn`` through ``record_serve`` triggers the correlator,
    anchored at the carried ``burn_ts`` (not the re-record time)."""
    obs.configure(str(tmp_path), process_index=0, annotate=False,
                  watch_compiles=False, ts_interval_s=0)
    s = obs.get()
    obs.record_serve(kind="chaos_injection", replica="replica0",
                     chaos="slow_replica", slow_steps_ms=250,
                     ts=990.0)
    obs.record_serve(kind="slo_burn", metric="token",
                     replica="replica0", burn_fast=50.0,
                     burn_slow=20.0, ts=2000.0, burn_ts=1000.0)
    assert len(s.incidents.incidents) == 1
    inc = s.incidents.incidents[0]
    assert inc["ts"] == 1000.0  # anchored at burn_ts
    assert inc["top_suspect"]["class"] == "chaos_injection"
    assert obs.active_incident_id() is None  # wall clock far past 1000
    obs.shutdown()
    m = json.load(open(os.path.join(str(tmp_path),
                                    "report.json")))["metrics"]
    assert m["incident_count"] == 1.0
    recs = load_ledger(os.path.join(str(tmp_path), LEDGER_FILENAME))
    assert sum(1 for r in recs if r.get("event") == "incident") == 1


def test_active_incident_id_rides_scale_decisions(tmp_path):
    """Satellite: the supervisor stamps ``correlation_id`` from
    ``obs.active_incident_id()`` — live incident id inside the lookback
    window, null otherwise."""
    assert obs.active_incident_id() is None  # no session: never raises
    obs.configure(str(tmp_path), process_index=0, annotate=False,
                  watch_compiles=False, ts_interval_s=0)
    s = obs.get()
    import time
    s.incidents.trigger(kind="slo_burn", ts=time.time(),
                        metric="token", replica="replica0")
    assert obs.active_incident_id() == "inc-1"
    obs.record_serve(kind="scale_decision", action="scale_up",
                     correlation_id=obs.active_incident_id())
    obs.shutdown()
    recs = load_ledger(os.path.join(str(tmp_path), LEDGER_FILENAME))
    dec = [r for r in recs if r.get("kind") == "scale_decision"]
    assert dec and dec[0]["correlation_id"] == "inc-1"


# -- SLO burn episode metrics (satellite) ------------------------------------


def test_burn_episode_duration_histogram_and_active_gauge(tmp_path):
    obs.configure(str(tmp_path), process_index=0, annotate=False,
                  watch_compiles=False, ts_interval_s=0)
    m = SLOMonitor(token_p99_s=0.010, check_every_steps=1,
                   min_samples=8)
    t0 = 1000.0
    for i in range(20):  # sustained breach fires the episode
        t = t0 + i * 0.1
        m.on_token(0.050, ts=t)
        m.check(step=i, now=t)
    snap = obs.get().metrics.snapshot()
    assert snap["slo_burn_active"] == 1.0
    assert snap.get("slo_burn_episode_seconds_count", 0) == 0
    for i in range(200):  # recovery re-arms and observes the duration
        t = t0 + 4.0 + i * 0.1
        m.on_token(0.001, ts=t)
        m.check(step=100 + i, now=t)
    snap = obs.get().metrics.snapshot()
    assert snap["slo_burn_active"] == 0.0
    assert snap["slo_burn_episode_seconds_count"] == 1
    # fired at ~t0+1.9s, recovered within the sweep: a sane duration
    assert 0.0 < snap["slo_burn_episode_seconds_sum"] < 30.0


# -- offline -----------------------------------------------------------------


def test_triggers_of_prefers_original_burn_ts():
    records = [{"event": "serve", "kind": "slo_burn", "metric": "token",
                "replica": "replica0", "ts": 2000.0, "burn_ts": 1000.0}]
    anomalies = [{"anomaly_id": "anom-replica1-1", "opened_ts": 1500.0,
                  "metric": "serve_token_seconds_p99",
                  "proc": "replica1", "z": 12.0}]
    got = triggers_of(records, anomalies)
    assert got[0]["ts"] == 1000.0 and got[0]["kind"] == "slo_burn"
    assert got[1]["replica"] == "replica1"  # proc names the replica


def test_correlate_coalesces_like_online():
    triggers = [_trigger(ts=1000.0), _trigger(ts=1050.0),
                {"kind": "anomaly", "ts": 5000.0,
                 "anomaly_id": "anom-1", "metric": "x_p99"}]
    incidents = correlate(triggers, [], lookback_s=100.0)
    assert [i["incident_id"] for i in incidents] == ["inc-1", "inc-2"]
    assert incidents[0]["triggers_absorbed"] == 1
    assert incidents[1]["kind"] == "anomaly"
    assert incidents[1]["anomalies"] == ["anom-1"]


def test_assemble_run_incidents_from_artifacts(tmp_path):
    """Offline reconstruction from a dir holding only a ledger — the
    kill -9 path: no session close, no finalize, still a postmortem."""
    with open(os.path.join(str(tmp_path), LEDGER_FILENAME), "w") as f:
        for rec in (
            {"event": "serve", "kind": "chaos_injection",
             "replica": "replica0", "chaos": "slow_replica",
             "slow_steps_ms": 250, "ts": 990.0},
            {"event": "serve", "kind": "slo_burn", "metric": "token",
             "replica": "replica0", "burn_fast": 50.0,
             "burn_slow": 20.0, "ts": 1000.0, "burn_ts": 1000.0},
        ):
            f.write(json.dumps(rec) + "\n")
        f.write('{"event": "serve", "kind": "slo_burn", "tor')  # torn
    out = assemble_run_incidents(str(tmp_path), lookback_s=100.0)
    assert len(out["incidents"]) == 1
    inc = out["incidents"][0]
    assert inc["top_suspect"]["class"] == "chaos_injection"
    assert inc["top_suspect"]["replica"] == "replica0"
    assert len(out["burns"]) == 1


def test_incident_cli_renders_and_exit_codes(tmp_path, capsys):
    # covered burn: exit 0, postmortem names the planted cause
    with open(os.path.join(str(tmp_path), LEDGER_FILENAME), "w") as f:
        f.write(json.dumps(
            {"event": "serve", "kind": "chaos_injection",
             "replica": "replica0", "chaos": "slow_replica",
             "ts": 990.0}) + "\n")
        f.write(json.dumps(
            {"event": "serve", "kind": "slo_burn", "metric": "token",
             "replica": "replica0", "burn_fast": 50.0,
             "burn_slow": 20.0, "ts": 1000.0,
             "burn_ts": 1000.0}) + "\n")
    assert obs_main(["incident", str(tmp_path)]) == 0
    md = capsys.readouterr().out
    assert "chaos_injection" in md and "| rank |" in md
    assert "reconstructed offline" in md  # no ledgered incident record
    # --json emits machine-readable output
    assert obs_main(["incident", str(tmp_path), "--json"]) == 0
    j = json.loads(capsys.readouterr().out)
    assert j["reconstructed"] and len(j["incidents"]) == 1


def test_incident_cli_exit_1_on_unexplained_burn(tmp_path, capsys):
    """A ledgered incident that does NOT cover a ledgered burn means
    the postmortem is incomplete — the CLI must say so loudly."""
    inc = assemble_incident(_trigger(ts=1000.0), [],
                            incident_id="inc-1", lookback_s=100.0)
    with open(os.path.join(str(tmp_path), LEDGER_FILENAME), "w") as f:
        f.write(json.dumps(inc) + "\n")
        f.write(json.dumps(
            {"event": "serve", "kind": "slo_burn", "metric": "ttft",
             "replica": "replica1", "burn_fast": 30.0,
             "burn_slow": 15.0, "ts": 9000.0,
             "burn_ts": 9000.0}) + "\n")
    assert obs_main(["incident", str(tmp_path)]) == 1
    err = capsys.readouterr().err
    assert "UNEXPLAINED BURN" in err


def test_sparkline_renders_range():
    s = sparkline([0.0, 0.5, 1.0])
    assert len(s) == 3 and s[0] == "▁" and s[-1] == "█"

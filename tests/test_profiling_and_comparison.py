"""Profiling utilities + the max-model methods-comparison experiment
(reference notebook 1 parity: every metric reproduces its analytic value
through the public API)."""

import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.experiments.max_comparison import (
    GROUND_TRUTH,
    run_max_comparison,
)
from torchpruner_tpu.utils.profiling import StepTimer, time_fn


def test_max_comparison_matches_analytic_values():
    r = run_max_comparison(sv_samples=300, verbose=False)
    for k in ("weight_norm", "apoz", "sensitivity", "taylor"):
        np.testing.assert_allclose(r[k], GROUND_TRUTH[k], atol=1e-5)
    np.testing.assert_allclose(r["shapley"], GROUND_TRUTH["shapley"], atol=0.2)


def test_max_comparison_version2_nonzero_gradients():
    r = run_max_comparison(version=2, sv_samples=50, verbose=False)
    # unit D's negative outgoing weight makes gradient metrics nonzero
    # (reference test_attributions.py:139-162)
    assert np.all(r["sensitivity"] > 0)
    assert np.all(r["taylor"] > 0)


def test_time_fn_reports_steady_state():
    import jax

    f = jax.jit(lambda x: x * 2 + 1)
    stats = time_fn(f, jnp.ones((64, 64)), iters=3, warmup=1)
    assert 0 < stats["min_s"] <= stats["mean_s"]
    assert stats["compile_s"] > 0


def test_step_timer_phases():
    t = StepTimer()
    with t.phase("score"):
        pass
    with t.phase("score"):
        pass
    with t.phase("prune"):
        pass
    s = t.summary()
    assert s["score"]["calls"] == 2 and s["prune"]["calls"] == 1
    assert s["score"]["total_s"] >= 0

"""Profiling utilities + the max-model methods-comparison experiment
(reference notebook 1 parity: every metric reproduces its analytic value
through the public API)."""

import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.experiments.max_comparison import (
    GROUND_TRUTH,
    run_max_comparison,
)
from torchpruner_tpu.utils.profiling import (
    StepTimer,
    time_fn,
    time_train_step,
)


def test_max_comparison_matches_analytic_values():
    r = run_max_comparison(sv_samples=300, verbose=False)
    for k in ("weight_norm", "apoz", "sensitivity", "taylor"):
        np.testing.assert_allclose(r[k], GROUND_TRUTH[k], atol=1e-5)
    np.testing.assert_allclose(r["shapley"], GROUND_TRUTH["shapley"], atol=0.2)


def test_max_comparison_version2_nonzero_gradients():
    r = run_max_comparison(version=2, sv_samples=50, verbose=False)
    # unit D's negative outgoing weight makes gradient metrics nonzero
    # (reference test_attributions.py:139-162)
    assert np.all(r["sensitivity"] > 0)
    assert np.all(r["taylor"] > 0)


def test_time_fn_reports_steady_state():
    import jax

    f = jax.jit(lambda x: x * 2 + 1)
    stats = time_fn(f, jnp.ones((64, 64)), iters=3, warmup=1)
    assert 0 < stats["min_s"] <= stats["mean_s"]
    assert stats["compile_s"] > 0


def test_time_train_step_fences_updated_params():
    """The trainer-step stopwatch must advance real training (the fence
    covers the params update, not just the loss scalar)."""
    import jax
    import optax

    from torchpruner_tpu.models import digits_fc
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    model = digits_fc()
    trainer = Trainer.create(model, optax.sgd(0.1), cross_entropy_loss,
                             seed=0)
    # host copy: the step donates the param buffers
    before = np.asarray(jax.tree_util.tree_leaves(trainer.params)[0]).copy()
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(4,) + model.input_shape).astype("float32"))
    y = jnp.zeros((4,), jnp.int32)
    stats = time_train_step(trainer, x, y, iters=2, warmup=1)
    assert stats["min_s"] > 0
    assert trainer.step_count == 3  # warmup + iters all executed
    after = np.asarray(jax.tree_util.tree_leaves(trainer.params)[0])
    assert not np.allclose(before, after)


def test_step_timer_phases():
    t = StepTimer()
    with t.phase("score"):
        pass
    with t.phase("score"):
        pass
    with t.phase("prune"):
        pass
    s = t.summary()
    assert s["score"]["calls"] == 2 and s["prune"]["calls"] == 1
    assert s["score"]["total_s"] >= 0


def test_trace_analysis_summarizes_profiler_output(tmp_path):
    """profiling.trace -> trace_analysis: the Chrome-trace parser must
    find the dominant op (a 256x256 matmul here), bucket it as matmul,
    and exclude Python-frame / runtime events from the totals."""
    import jax

    from torchpruner_tpu.utils.profiling import trace
    from torchpruner_tpu.utils.trace_analysis import (
        markdown_summary,
        summarize_trace,
    )

    f = jax.jit(lambda a, b: jnp.tanh(a @ b).sum())
    a = jnp.ones((256, 256))
    f(a, a).block_until_ready()  # compile outside the trace
    with trace(str(tmp_path)):
        for _ in range(3):
            f(a, a).block_until_ready()
    s = summarize_trace(str(tmp_path))
    assert s["total_ms"] > 0
    names = [op["name"] for op in s["top_ops"]]
    # CPU runtimes have named this op "dot_general..." or "dot.N"
    # depending on version; both categorize as matmul
    assert any(n.startswith("dot") for n in names)
    dot = next(op for op in s["top_ops"] if op["name"].startswith("dot"))
    assert dot["category"] == "matmul" and dot["count"] >= 3
    assert not any(n.startswith("$") for n in names)
    md = markdown_summary(s, top=5)
    assert "| matmul |" in md
    assert not any(n.startswith("end: ") for n in names)
    # a second session into the same dir must not double-count: only the
    # newest plugins/profile/<run> is summarized
    with trace(str(tmp_path)):
        f(a, a).block_until_ready()
    s2 = summarize_trace(str(tmp_path))
    dot2 = next(op for op in s2["top_ops"]
                if op["name"].startswith("dot"))
    assert dot2["count"] < dot["count"]


def test_trace_analysis_missing_dir_raises(tmp_path):
    import pytest as _pytest

    from torchpruner_tpu.utils.trace_analysis import summarize_trace

    with _pytest.raises(FileNotFoundError):
        summarize_trace(str(tmp_path / "nope"))

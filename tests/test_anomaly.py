"""Changepoint detection over the delta-window time-series
(torchpruner_tpu.obs.anomaly): the rolling median/MAD robust z-score,
score-then-admit warmup, hysteresis open/close with the dead band,
warmup-excluded offline replay, the fleet per-process split, and the
online hook on the recorder's tick."""

import os

import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.obs.anomaly import (
    AnomalyDetector,
    RollingMAD,
    detect_anomalies,
    detect_series,
    window_signals,
)
from torchpruner_tpu.obs.metrics import MetricsRegistry
from torchpruner_tpu.obs.timeseries import TimeseriesRecorder


@pytest.fixture(autouse=True)
def _clean_session():
    obs.shutdown()
    yield
    obs.shutdown()


def _window(seq, ts, sig=None, counters=None, gauges=None, dur_s=1.0):
    w = {"kind": "ts_window", "seq": seq, "ts": ts, "dur_s": dur_s}
    g = dict(gauges or {})
    if sig is not None:
        g["sig_latency"] = sig
    if g:
        w["gauges"] = g
    if counters:
        w["counters"] = counters
    return w


def _detector(**kw):
    kw.setdefault("gauge_prefixes", ("sig_",))
    kw.setdefault("min_history", 4)
    kw.setdefault("k", 2)
    kw.setdefault("z_threshold", 8.0)
    return AnomalyDetector(**kw)


# -- RollingMAD --------------------------------------------------------------


def test_rolling_mad_warms_up_then_scores():
    tr = RollingMAD(min_history=4)
    assert [tr.push(v) for v in (10, 10, 11, 9)] == [None] * 4
    z = tr.push(10)  # in-family value: small z
    assert z is not None and abs(z) < 2
    z = tr.push(100)  # a 10x spike scored BEFORE admission
    assert z > 8


def test_rolling_mad_flat_baseline_uses_median_floor():
    """A perfectly flat history has MAD 0 — the 5%-of-median floor
    keeps epsilon jitter from scoring as infinite z."""
    tr = RollingMAD(min_history=4)
    for _ in range(8):
        tr.push(10.0)
    assert abs(tr.push(10.001)) < 1  # noise, not anomaly
    assert tr.push(20.0) > 8  # a genuine 2x step still trips


def test_spike_does_not_absorb_into_its_own_baseline():
    tr = RollingMAD(min_history=4)
    for _ in range(6):
        tr.push(1.0)
    first = tr.push(50.0)
    second = tr.push(50.0)  # the spike is IN history now, but median holds
    assert first > 8 and second > 8


# -- window_signals ----------------------------------------------------------


def test_window_signals_hist_p99_counters_and_gauges():
    w = {
        "kind": "ts_window", "seq": 1, "ts": 1.0, "dur_s": 2.0,
        "hist": {"lat_seconds": {"le": [0.1, 1.0], "c": [0, 4],
                                 "n": 4, "sum": 2.0}},
        "counters": {"fleet_shed_total": 6, "steps_total": 100},
        "gauges": {"sig_depth": 3.0, "other": 1.0},
    }
    sig = window_signals(w, gauge_prefixes=("sig_",))
    assert sig["lat_seconds_p99"] == pytest.approx(1.0, rel=0.2)
    # watchlist counters become rates; arbitrary counters do not
    assert sig["fleet_shed_total_rate"] == pytest.approx(3.0)
    assert "steps_total_rate" not in sig
    # gauges are opt-in by prefix
    assert sig["sig_depth"] == 3.0 and "other" not in sig


# -- hysteresis --------------------------------------------------------------


def test_anomaly_opens_after_k_deviant_windows_and_closes():
    det = _detector()
    t = 100.0
    for i in range(6):  # baseline
        det.observe_window(_window(i, t + i, sig=10.0))
    assert det.counts() == {"opened": 0, "open": 0}
    # first deviant window: streak 1 of K=2 — not yet open
    det.observe_window(_window(10, t + 10, sig=100.0))
    assert det.counts()["open"] == 0
    out = det.observe_window(_window(11, t + 11, sig=100.0))
    assert [a["state"] for a in out] == ["open"]
    a = det.open_anomalies()[0]
    assert a["metric"] == "sig_latency" and a["anomaly_id"] == "anom-1"
    assert a["z"] > 8 and a["windows_deviant"] == 2
    # recovery: K consecutive recovered windows close it
    det.observe_window(_window(12, t + 12, sig=10.0))
    assert det.counts()["open"] == 1
    out = det.observe_window(_window(13, t + 13, sig=10.0))
    assert [a["state"] for a in out] == ["closed"]
    assert det.counts() == {"opened": 1, "open": 0}
    assert det.anomalies[0]["closed_ts"] == pytest.approx(t + 13)


def test_single_window_blip_never_opens():
    det = _detector()
    for i in range(6):
        det.observe_window(_window(i, 100.0 + i, sig=10.0))
    det.observe_window(_window(10, 110.0, sig=100.0))  # one blip
    for i in range(11, 15):
        det.observe_window(_window(i, 100.0 + i, sig=10.0))
    assert det.counts() == {"opened": 0, "open": 0}


def test_dead_band_resets_both_streaks():
    """Values between the recover and open thresholds must neither
    extend the deviant streak nor count toward recovery — no flapping."""
    det = _detector()
    for i in range(8):
        det.observe_window(_window(i, 100.0 + i, sig=10.0))
    det.observe_window(_window(10, 110.0, sig=100.0))  # deviant 1/2
    det.observe_window(_window(11, 111.0, sig=13.0))   # dead band
    det.observe_window(_window(12, 112.0, sig=100.0))  # deviant 1/2 again
    assert det.counts()["open"] == 0


def test_open_callback_fires_outside_lock_and_once():
    seen = []
    det = _detector(on_open=lambda a: seen.append(a["anomaly_id"]))
    for i in range(6):
        det.observe_window(_window(i, 100.0 + i, sig=10.0))
    for i in range(6, 10):
        det.observe_window(_window(i, 100.0 + i, sig=100.0))
    assert seen == ["anom-1"]  # open once, not once per deviant window


def test_gauge_history_and_gauges_between():
    det = _detector()
    for i in range(5):
        det.observe_window(_window(i, 100.0 + i, sig=1.0,
                                   gauges={"fleet_replica_r0_occupancy":
                                           float(i)}))
    hist = det.gauges_between(101.0, 103.0)
    assert [ts for ts, _ in hist] == [101.0, 102.0, 103.0]
    assert hist[0][1]["fleet_replica_r0_occupancy"] == 1.0


# -- offline replay ----------------------------------------------------------


def test_detect_series_excludes_warmup():
    """A level shift inside the warmup quarter must not open; the same
    shift in steady state must."""
    warm = [_window(i, 100.0 + i, sig=50.0) for i in range(5)]
    steady = [_window(10 + i, 110.0 + i, sig=10.0) for i in range(8)]
    spike = [_window(30 + i, 130.0 + i, sig=100.0) for i in range(3)]
    got = detect_series(warm + steady + spike, min_history=4, k=2,
                        gauge_prefixes=("sig_",))
    assert len(got) == 1
    assert got[0]["metric"] == "sig_latency"
    assert got[0]["opened_ts"] >= 130.0


def test_detect_anomalies_reads_recorded_run(tmp_path):
    """End to end through a real recorder file: flat latency then a
    sustained 50x shift must be detected offline from the run dir."""
    reg = MetricsRegistry()
    rec = TimeseriesRecorder(reg, str(tmp_path), interval_s=0.01)
    h = reg.histogram("serve_token_seconds")
    for i in range(30):
        for _ in range(4):
            h.observe(0.010 if i < 22 else 0.500)
        rec.tick()
    rec.close()
    got = detect_anomalies(str(tmp_path), min_history=4, k=2)
    assert any(a["metric"] == "serve_token_seconds_p99" for a in got), got


def test_detector_ids_carry_proc_prefix():
    det = _detector(proc="replica1", min_history=2, k=1)
    for i in range(4):
        det.observe_window(_window(i, 100.0 + i, sig=10.0))
    det.observe_window(_window(9, 109.0, sig=500.0))
    a = det.anomalies[0]
    assert a["anomaly_id"] == "anom-replica1-1" and a["proc"] == "replica1"


# -- online hook -------------------------------------------------------------


def test_recorder_on_window_feeds_detector(tmp_path):
    reg = MetricsRegistry()
    rec = TimeseriesRecorder(reg, str(tmp_path), interval_s=0.01)
    det = _detector(min_history=2, k=1, gauge_prefixes=("serve_",))
    rec.on_window = det.observe_window
    g = reg.gauge("serve_depth")
    for i in range(6):
        g.set(1.0)
        rec.tick()
    g.set(500.0)
    rec.tick()
    rec.close()
    assert det.counts()["opened"] == 1
    assert det.anomalies[0]["metric"] == "serve_depth"


def test_hot_path_overhead_with_detector_hook_installed(tmp_path):
    """The PR 17 recorder budgets re-gated WITH the anomaly hook wired:
    a not-due ``maybe_tick`` stays a clock read + compare (<100 µs),
    and a due tick — registry walk + per-window scoring pass — stays
    under 1% of a 1 Hz window."""
    import time

    reg = MetricsRegistry()
    for i in range(8):
        reg.counter(f"c{i}").inc()
        reg.gauge(f"g{i}").set(i)
        reg.histogram(f"h{i}").observe(0.001 * (i + 1))
    rec = TimeseriesRecorder(reg, str(tmp_path), interval_s=3600.0)
    det = AnomalyDetector(gauge_prefixes=("g",), min_history=4, k=2)
    rec.on_window = det.observe_window
    n = 5000
    rec.maybe_tick()  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        rec.maybe_tick()
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 100e-6, f"maybe_tick cost {per_call * 1e6:.1f} µs"

    m = 50
    t0 = time.perf_counter()
    for _ in range(m):
        rec.tick()
    per_tick = (time.perf_counter() - t0) / m
    rec.close()
    assert per_tick < 0.01, f"tick+score cost {per_tick * 1e3:.2f} ms"


def test_session_wires_detector_and_ledgers_open(tmp_path):
    """The configured session hooks detector → recorder → ledger: an
    anomaly open lands in the ledger and assembles an incident."""
    os.environ["TORCHPRUNER_ANOMALY_MIN_HISTORY"] = "2"
    os.environ["TORCHPRUNER_ANOMALY_K"] = "1"
    os.environ["TORCHPRUNER_ANOMALY_GAUGES"] = "probe_"
    try:
        s = obs.configure(str(tmp_path), process_index=0, annotate=False,
                          watch_compiles=False, ts_interval_s=1000.0)
        assert s.anomaly is not None and s.incidents is not None
        g = s.metrics.gauge("probe_sig")
        for _ in range(5):
            g.set(1.0)
            s.timeseries.tick()
        g.set(400.0)
        s.timeseries.tick()
        assert s.anomaly.counts()["opened"] == 1
        assert len(s.incidents.incidents) == 1
        assert s.incidents.incidents[0]["kind"] == "anomaly"
        obs.shutdown()
        from torchpruner_tpu.obs.ledger import LEDGER_FILENAME, load_ledger
        recs = load_ledger(os.path.join(str(tmp_path), LEDGER_FILENAME))
        assert any(r.get("event") == "anomaly" for r in recs)
        assert any(r.get("event") == "incident" for r in recs)
    finally:
        for k in ("TORCHPRUNER_ANOMALY_MIN_HISTORY",
                  "TORCHPRUNER_ANOMALY_K",
                  "TORCHPRUNER_ANOMALY_GAUGES"):
            os.environ.pop(k, None)

"""Kernel-level continuous profiling (torchpruner_tpu.obs.profile):
capture-window cadence and on-demand arming, per-kernel attribution with
roofline positions, kernel gate scalars tripping `obs diff --gate` while
the total-step gate stays green, not-comparable degradation against a
pre-kernel-era report, the Perfetto merge of profiler op events with the
span stream, per-executable compile attribution, the HBM timeline, and
the serve SLO monitor."""

import gzip
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.obs.profile import (
    HbmSampler,
    base_kernel_name,
    build_profile,
    format_profile,
    kernel_scalar_name,
    load_profile,
    scan_windows,
)
from torchpruner_tpu.obs.report import (
    check_gates,
    diff_runs,
    format_report,
    load_run,
    obs_main,
)
from torchpruner_tpu.utils.flops import roofline_position

GOLDEN_DIGITS = os.path.join(
    os.path.dirname(__file__), "..", "results",
    "obs_report_golden_digits_smoke.json")


@pytest.fixture(autouse=True)
def _clean_session():
    obs.shutdown()
    yield
    obs.shutdown()


@jax.jit
def _matmul_step(a, b):
    return jnp.tanh(a @ b).sum()


def _run_profiled(obs_dir, *, every=3, window=2, steps=8, n=256,
                  flops=True):
    """A matmul-dominated step loop under a profiling session; returns
    the closed session's dir artifacts for assertions."""
    session = obs.configure(str(obs_dir), profile_every=every,
                            profile_steps=window)
    if flops:
        obs.configure_step_flops(flops_per_step=3 * 2 * n**3,
                                 param_bytes=4.0 * n * n)
    a = jnp.ones((n, n))
    b = jnp.ones((n, n))
    _matmul_step(a, b).block_until_ready()  # compile outside the loop
    with obs.span("run"):
        for _ in range(steps):
            t0 = time.perf_counter()
            _matmul_step(a, b).block_until_ready()
            obs.record_step(time.perf_counter() - t0, examples=n)
    obs.shutdown()
    return session


# -- capture + attribution ---------------------------------------------------


def test_cadence_windows_kernel_table_and_gauges(tmp_path):
    """The tentpole end to end: cadence windows open without pausing the
    step loop, the ranked kernel table attributes the step's ms to real
    op names, every ranked kernel carries a roofline position, and the
    kernel_* gate scalars land in report.json's metric snapshot."""
    d = tmp_path / "obs"
    _run_profiled(d, every=3, window=2, steps=8)

    prof = json.load(open(d / "profile.json"))
    assert len(prof["windows"]) >= 1
    assert prof["steps_profiled"] >= 2
    kernels = prof["kernels"]
    assert kernels, "empty kernel table"
    names = [k["kernel"] for k in kernels]
    assert "dot" in names, names
    for k in kernels:
        assert k["ms_per_step"] >= 0
        rf = k["roofline"]
        assert rf["bound"] in ("compute", "memory", "unknown")
    # the dominant matmul got the step-FLOPs attribution -> an intensity
    dot = next(k for k in kernels if k["kernel"] == "dot")
    assert dot["category"] == "matmul"
    assert dot["roofline"]["intensity_flops_per_byte"] is not None
    assert dot["roofline"]["flops_est"] > 0

    # summed op ms vs the telemetry-measured step span: the coverage
    # sanity the acceptance reads (matmul-dominated loop -> the trace
    # must explain a meaningful share of the step, and cross-thread
    # overlap must not inflate it absurdly)
    assert prof["coverage"] is not None
    assert 0.15 < prof["coverage"] < 3.0, prof["coverage"]

    rep = json.load(open(d / "report.json"))
    assert rep["metrics"][kernel_scalar_name("dot", "ms")] > 0
    assert rep["metrics"]["profile_windows_total"] >= 1
    assert rep["profile"]["kernels"], "profile block missing from report"
    assert "timeline" not in rep["profile"]["hbm"]  # bulky raw stays out
    md = format_report(load_run(str(d)))
    assert "profile:" in md and "`dot`" in md


def test_window_sidecars_and_offline_scan(tmp_path):
    d = tmp_path / "obs"
    _run_profiled(d, every=4, window=2, steps=8)
    windows = scan_windows(str(d / "profile"))
    assert windows and all(os.path.isdir(w["dir"]) for w in windows)
    assert any(w["steps"] > 0 for w in windows)
    # offline re-parse (SIGKILLed-run path): profile.json deleted, the
    # windows alone must still produce a table
    os.remove(d / "profile.json")
    os.remove(d / "report.json")
    prof = load_profile(str(d))
    assert prof and prof["kernels"]


def test_on_demand_window(tmp_path):
    d = tmp_path / "obs"
    session = obs.configure(str(d), profile_every=0, profile_steps=2)
    assert obs.request_profile_window()
    assert not obs.request_profile_window()  # already armed
    a = jnp.ones((64, 64))
    _matmul_step(a, a).block_until_ready()
    for _ in range(4):
        t0 = time.perf_counter()
        _matmul_step(a, a).block_until_ready()
        obs.record_step(time.perf_counter() - t0, examples=64)
    assert session.profiler.windows, "on-demand window never closed"
    assert session.profiler.windows[0]["on_demand"]
    obs.shutdown()
    assert json.load(open(d / "profile.json"))["windows"]


def test_profile_cli_renders(tmp_path, capsys):
    d = tmp_path / "obs"
    _run_profiled(d, every=3, window=2, steps=7)
    assert obs_main(["profile", str(d)]) == 0
    out = capsys.readouterr().out
    assert "kernel profile" in out and "| kernel |" in out
    assert "dot" in out
    assert obs_main(["profile", str(tmp_path / "nope")]) == 2


def test_base_kernel_name_normalization():
    assert base_kernel_name("dot.4") == "dot"
    assert base_kernel_name("dot.17.clone") == "dot"
    assert base_kernel_name("tanh.5.clone") == "tanh"
    assert base_kernel_name("fusion.1234") == "fusion"
    assert base_kernel_name("loop_convolution_fusion.2") == \
        "loop_convolution_fusion"
    assert base_kernel_name("all-reduce.1") == "all_reduce"


def test_roofline_position_bounds():
    # intensity 100 FLOP/B vs ridge 10 -> compute-bound
    r = roofline_position(1e9, 1e7, 1e-3, peak_flops=1e12, peak_bw=1e11)
    assert r["bound"] == "compute"
    assert r["achieved_flops_per_s"] == pytest.approx(1e12)
    assert r["pct_peak_flops"] == pytest.approx(100.0)
    # intensity 1 vs ridge 10 -> memory-bound
    r = roofline_position(1e7, 1e7, 1e-3, peak_flops=1e12, peak_bw=1e11)
    assert r["bound"] == "memory"
    # nothing known -> unknown, never a guess
    r = roofline_position(None, None, 1e-3)
    assert r["bound"] == "unknown" and r["pct_peak_flops"] is None


# -- gates -------------------------------------------------------------------


def _report_with_kernels(dot_ms, step_ms, steps=100):
    return {"metrics": {
        kernel_scalar_name("dot", "ms"): dot_ms,
        kernel_scalar_name("fusion", "ms"): 0.1,
        "profile_coverage": 0.8,
    }, "derived": {"step_time_mean_s": step_ms / 1e3, "steps": steps}}


def test_planted_kernel_slowdown_trips_gate_step_gate_green():
    """The acceptance scenario: a kernel triples (a forced f32 matmul
    under the bf16 policy) while its share of the total step is small
    enough that the step-time gate stays green — the per-kernel gate
    must fail, naming the kernel."""
    base = _report_with_kernels(dot_ms=0.30, step_ms=3.0)
    # dot 0.30 -> 0.95 ms (+217%); total step 3.0 -> 3.6 ms (+20%)
    slow = _report_with_kernels(dot_ms=0.95, step_ms=3.6)
    gates = {"step_time_mean_s": {"max_increase_pct": 25},
             "kernel_dot_ms": {"max_increase_pct": 60}}
    violations = check_gates(diff_runs(base, slow), gates)
    assert [v["gate"] for v in violations] == ["kernel_dot_ms"]
    assert "increased" in violations[0]["detail"]
    # and a healthy run passes both
    assert not check_gates(diff_runs(base, base), gates)


def test_typoed_kernel_gate_is_a_violation():
    """The unknown-gate invariant extends to dynamic names: a kernel
    gate naming a metric NEITHER run has (a typo) must fail loudly, not
    silently disable itself; \"optional\": true opts out."""
    a, b = _report_with_kernels(0.2, 3.0), _report_with_kernels(0.3, 3.0)
    d = diff_runs(a, b)
    bad = {"kernel_dto_ms": {"max_increase_pct": 60}}
    violations = check_gates(d, bad)
    assert [v["gate"] for v in violations] == ["kernel_dto_ms"]
    assert "absent from both" in violations[0]["detail"]
    assert not check_gates(d, {"kernel_dto_ms": {
        "max_increase_pct": 60, "optional": True}})
    # known static scalars keep the existing skip semantics (mfu is
    # legitimately absent on CPU runs)
    assert not check_gates(d, {"mfu": {"max_decrease_pct": 10}})


def test_request_window_refused_at_cap(tmp_path):
    from torchpruner_tpu.obs.profile import ContinuousProfiler

    prof = ContinuousProfiler(str(tmp_path / "p"), max_windows=1)
    prof.windows.append({"index": 0, "dir": "x", "on_demand": False})
    assert prof.request_window() is False  # a True must mean a capture


def test_new_session_clears_stale_windows(tmp_path):
    """A session reusing an obs dir must not merge a dead run's capture
    windows into its own trace/kernel table (same invalidation the
    metric shards get)."""
    d = tmp_path / "obs"
    _run_profiled(d, every=3, window=2, steps=7)
    assert scan_windows(str(d / "profile"))
    obs.configure(str(d), annotate=False, watch_compiles=False)
    assert not scan_windows(str(d / "profile"))
    assert not os.path.exists(d / "profile.json")
    obs.shutdown()


def test_kernel_scalars_diff_dynamically():
    d = diff_runs(_report_with_kernels(0.2, 3.0),
                  _report_with_kernels(0.4, 3.0))
    e = d["scalars"]["kernel_dot_ms"]
    assert e["pct"] == pytest.approx(100.0)
    assert d["scalars"]["profile_coverage"]["delta"] == 0


def test_pre_kernel_era_report_degrades_to_not_comparable():
    """Satellite: diffing against a committed baseline from before the
    kernel scalars existed must NOT error — kernel rows render as
    informational 'not comparable' and gates skip them unless required."""
    golden = load_run(GOLDEN_DIGITS)
    assert not any(k.startswith("kernel_") for k in golden["metrics"])
    fresh = _report_with_kernels(0.3, 3.0)
    d = diff_runs(golden, fresh)
    e = d["scalars"]["kernel_dot_ms"]
    assert "not comparable" in e["note"] and "delta" not in e
    from torchpruner_tpu.obs.report import format_diff

    assert "not comparable" in format_diff(d)
    gates = {"kernel_dot_ms": {"max_increase_pct": 60}}
    assert not check_gates(d, gates)  # absent baseline -> skip
    gates = {"kernel_dot_ms": {"max_increase_pct": 60, "require": True}}
    assert [v["gate"] for v in check_gates(d, gates)] == ["kernel_dot_ms"]
    # the reverse direction (fresh A, old B) is symmetric
    assert "note" in diff_runs(fresh, golden)["scalars"]["kernel_dot_ms"]


# -- Perfetto merge ----------------------------------------------------------


def test_trace_merges_profiler_ops_with_spans(tmp_path):
    """Satellite: trace.json holds the span B/E stream AND the capture
    windows' op events — stable dedicated tids, monotonic ts per track,
    balanced B/E (the Perfetto schema lint)."""
    from torchpruner_tpu.obs.trace_export import PROFILE_TID_BASE

    d = tmp_path / "obs"
    _run_profiled(d, every=3, window=2, steps=7)
    trace = json.load(open(d / "trace.json"))
    evs = trace["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert xs, "no profiler op events merged"
    assert all(e["tid"] >= PROFILE_TID_BASE for e in xs)
    assert all(e["cat"] == "xla_op" for e in xs)
    assert {"dot.4"} & {e["name"] for e in xs} or \
        any(e["name"].startswith("dot") for e in xs)
    # schema lint: B/E balanced per track, ts monotonic per track
    stacks, last_ts = {}, {}
    for e in evs:
        if e["ph"] == "M":
            continue
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last_ts.get(key, 0), "ts regression"
        last_ts[key] = e["ts"]
        if e["ph"] == "B":
            stacks.setdefault(key, []).append(e["name"])
        elif e["ph"] == "E":
            assert stacks[key].pop() == e["name"]
        else:
            assert e["ph"] == "X"
    assert all(not s for s in stacks.values()), "unbalanced B/E"
    # each profile track announces itself (thread_name metadata)
    tids = {e["tid"] for e in xs}
    named = {e["tid"] for e in evs if e["ph"] == "M"
             and e["name"] == "thread_name"
             and "profile window" in (e.get("args") or {}).get("name", "")}
    assert tids <= named


def test_trace_without_windows_unchanged(tmp_path):
    """No capture windows -> the exporter emits the span-only trace
    (and never invents X events)."""
    d = tmp_path / "obs"
    obs.configure(str(d), annotate=False, watch_compiles=False)
    with obs.span("run"):
        pass
    obs.shutdown()
    evs = json.load(open(d / "trace.json"))["traceEvents"]
    assert not [e for e in evs if e["ph"] == "X"]


# -- compile attribution -----------------------------------------------------


def test_compile_seconds_attributed_per_executable(tmp_path):
    """Satellite: the watcher names the executables that paid the
    compile bill, and `obs report` renders the top-compilers table."""
    d = tmp_path / "obs"
    session = obs.configure(str(d), annotate=False)

    @jax.jit
    def costly_train_step(x):
        return jnp.tanh(x @ x).sum()

    with obs.span("run"):
        costly_train_step(jnp.ones((128, 128))).block_until_ready()
    by_exe = dict(session.compiles.by_executable)
    counts = session.compiles.counts()
    obs.shutdown()
    assert any("costly_train_step" in name for name in by_exe), by_exe
    name = next(n for n in by_exe if "costly_train_step" in n)
    assert by_exe[name]["count"] >= 1 and by_exe[name]["seconds"] > 0
    top = counts["by_executable"]
    assert top and top[0]["seconds"] >= top[-1]["seconds"]
    md = format_report(load_run(str(d)))
    assert "top compilers" in md and "costly_train_step" in md


def test_compile_log_level_restored():
    import logging

    logger = logging.getLogger("jax._src.dispatch")
    prior_level, prior_prop = logger.level, logger.propagate
    obs.configure(None)
    obs.shutdown()
    assert logger.level == prior_level
    assert logger.propagate == prior_prop


# -- HBM timeline ------------------------------------------------------------


def test_hbm_sampler_timeline_and_phase_watermarks(tmp_path):
    """Span edges sample memory; off-accelerator the host-RSS fallback
    keeps the timeline non-empty so the same assertions run in CI."""
    sampler = HbmSampler()
    sampler.on_event({"event": "span_begin", "name": "retrain", "ts": 1.0})
    sampler._t_last = 0.0  # bypass throttle for the second edge
    sampler.on_event({"event": "span_end", "name": "retrain", "ts": 2.0})
    assert sampler.timeline, "no samples (host fallback failed)"
    s = sampler.summary()
    assert s["phases"]["retrain"]["peak_bytes"] > 0
    assert s["phases"]["retrain"]["samples"] >= 1
    assert s["source"] in ("device", "host_rss")
    assert s["peak_bytes"] and s["peak_bytes"] >= \
        s["phases"]["retrain"]["peak_bytes"] - 1


def test_hbm_lands_in_profile_json(tmp_path):
    d = tmp_path / "obs"
    _run_profiled(d, every=3, window=2, steps=7)
    hbm = json.load(open(d / "profile.json"))["hbm"]
    assert hbm["phases"], "no per-phase watermarks"
    assert hbm["peak_bytes"] > 0
    md = format_profile(json.load(open(d / "profile.json")))
    assert "HBM watermark" in md


def test_hbm_sampler_throttles():
    sampler = HbmSampler()
    for i in range(50):
        sampler.on_event({"event": "span_begin", "name": "x", "ts": i})
    assert len(sampler.timeline) <= 2  # min-interval throttle


# -- serve SLO monitor -------------------------------------------------------


def test_slo_monitor_counts_breach_episodes(tmp_path):
    from torchpruner_tpu.serve.slo import SLOMonitor

    d = tmp_path / "obs"
    obs.configure(str(d), annotate=False, watch_compiles=False)
    mon = SLOMonitor(ttft_p99_s=0.010, token_p99_s=None, window=64,
                     check_every_steps=1, min_samples=4)
    for _ in range(8):
        mon.on_ttft(0.002)
    mon.maybe_check(1)
    assert mon.breaches_total == 0
    for _ in range(8):
        mon.on_ttft(0.050)  # sustained breach
    mon.maybe_check(2)
    mon.maybe_check(3)  # still in breach: same episode, not a new count
    assert mon.breaches_total == 1
    assert obs.counter_value("serve_slo_breach_total") == 1
    assert obs.counter_value("serve_slo_breach_ttft_total") == 1
    assert mon.rolling["ttft"] > 0.010
    for _ in range(64):
        mon.on_ttft(0.001)  # recovery refills the window
    mon.maybe_check(4)
    assert not mon._in_breach["ttft"]
    for _ in range(64):
        mon.on_ttft(0.050)
    mon.maybe_check(5)
    assert mon.breaches_total == 2  # re-armed -> new episode
    snap = mon.snapshot()
    assert snap["breaches_total"] == 2
    assert snap["thresholds_ms"]["ttft"] == 10.0
    obs.shutdown()
    # the breach is ledgered as serve provenance
    rep = load_run(str(d))
    breaches = [r for r in rep.get("serve", [])
                if r.get("kind") == "slo_breach"]
    assert breaches and breaches[0]["metric"] == "ttft"
    assert breaches[0]["threshold_s"] == pytest.approx(0.010)


def test_slo_monitor_gauges_exported():
    from torchpruner_tpu.serve.slo import SLOMonitor

    session = obs.configure(None)
    mon = SLOMonitor(window=32, check_every_steps=1)
    for _ in range(4):
        mon.on_token(0.003)
    mon.check(1)
    g = session.metrics.get("serve_token_p99_rolling_s")
    assert g is not None and g.value == pytest.approx(0.003, rel=0.2)
    obs.shutdown()

"""Ulysses (all-to-all head-scatter) sequence parallelism on the 8-device
CPU mesh: numerics vs the single-device reference, causal masking, gradient
flow through both all-to-alls, and the ring/ulysses strategy dispatch."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from torchpruner_tpu.ops.flash_attention import _xla_attention
from torchpruner_tpu.parallel import (
    choose_sp_strategy,
    make_mesh,
    sequence_parallel_attention,
    ulysses_attention,
)


def qkv(B=2, S=32, H=8, Dh=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, Dh)) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_seq", [2, 8])
def test_ulysses_matches_single_device(causal, n_seq):
    mesh = make_mesh({"seq": n_seq}, devices=jax.devices()[:n_seq])
    q, k, v = qkv()
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    ref = _xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = make_mesh({"seq": 8})
    q, k, v = qkv(H=6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh)


def test_ulysses_rejects_indivisible_sequence():
    mesh = make_mesh({"seq": 8})
    q, k, v = qkv(S=30)
    with pytest.raises(ValueError, match="sequence"):
        ulysses_attention(q, k, v, mesh)


def test_ulysses_gradients_match_single_device():
    mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
    q, k, v = qkv(S=16, H=4)
    g = jax.random.normal(jax.random.PRNGKey(7), q.shape)

    def grads(fn):
        return jax.grad(
            lambda q_, k_, v_: jnp.sum(fn(q_, k_, v_) * g), argnums=(0, 1, 2)
        )(q, k, v)

    got = grads(lambda a, b, c: ulysses_attention(a, b, c, mesh, causal=True))
    want = grads(lambda a, b, c: _xla_attention(a, b, c, causal=True))
    for ga, gw in zip(got, want):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gw), atol=1e-4)


def test_ulysses_bf16_output_dtype():
    mesh = make_mesh({"seq": 2}, devices=jax.devices()[:2])
    q, k, v = (t.astype(jnp.bfloat16) for t in qkv(S=16))
    out = ulysses_attention(q, k, v, mesh, causal=True)
    assert out.dtype == jnp.bfloat16


def test_strategy_dispatch_follows_head_count():
    mesh = make_mesh({"seq": 8})
    # 8 heads divide the axis -> ulysses; pruned to 6 heads -> ring
    assert choose_sp_strategy(8, mesh) == "ulysses"
    assert choose_sp_strategy(6, mesh) == "ring"


@pytest.mark.parametrize("H,expected", [(8, "ulysses"), (6, "ring")])
def test_auto_dispatch_matches_reference(H, expected):
    """After pruning heads to a non-divisible count the auto dispatcher must
    fall back to ring and still match the single-device reference."""
    mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
    q, k, v = qkv(S=16, H=H)
    assert choose_sp_strategy(H, mesh) == expected
    out = sequence_parallel_attention(q, k, v, mesh, causal=True)
    ref = _xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_unknown_strategy_rejected():
    mesh = make_mesh({"seq": 2}, devices=jax.devices()[:2])
    q, k, v = qkv(S=16)
    with pytest.raises(ValueError, match="strategy"):
        sequence_parallel_attention(q, k, v, mesh, strategy="nope")

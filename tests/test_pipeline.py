"""Pipeline-parallelism tests (multi-device CPU): stage balancing, pipelined
forward == single-device forward, pipelined GPipe training == single-device
training (same updates), and state/params gathering."""

import numpy as np
import jax
import jax.numpy as jnp
import optax

from torchpruner_tpu.core.segment import init_model
from torchpruner_tpu.models import llama_tiny, mnist_fc
from torchpruner_tpu.models.mlp import fc_net
from torchpruner_tpu.parallel.pipeline import (
    PipelineParallel,
    balance_stages,
    _1f1b_schedule,
    _layer_param_count,
)
from torchpruner_tpu.train.loop import Trainer
from torchpruner_tpu.utils.losses import cross_entropy_loss, lm_cross_entropy_loss


def test_balance_stages_partitions_all_layers():
    model = llama_tiny(depth=4)
    for n in (1, 2, 4):
        spans = balance_stages(model, n)
        assert len(spans) == n
        assert spans[0][0] == 0 and spans[-1][1] == len(model.layers)
        for (s0, e0), (s1, e1) in zip(spans, spans[1:]):
            assert e0 == s1 and e0 > s0
        # balanced within 2x of ideal for the big middle stages
        counts = [
            sum(
                _layer_param_count(spec, shp[0])
                for spec, shp in zip(model.layers[s:e], model.shapes[s:e])
            )
            for s, e in spans
        ]
        assert sum(counts) == sum(
            _layer_param_count(spec, shp[0])
            for spec, shp in zip(model.layers, model.shapes)
        )


def test_pipelined_forward_matches_single_device():
    model = fc_net(20, hidden=(32, 32, 32), n_classes=5)
    params, state = init_model(model, seed=0)
    pp = PipelineParallel.create(
        model, 4, devices=jax.devices()[:4], params=params, state=state,
        n_microbatches=2,
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 20))
    y_pp = pp.forward(x)
    y_ref, _ = model.apply(params, x, state=state)
    np.testing.assert_allclose(np.asarray(y_pp), np.asarray(y_ref), atol=1e-5)


def test_pipelined_transformer_forward():
    model = llama_tiny(depth=4)
    params, state = init_model(model, seed=0)
    pp = PipelineParallel.create(
        model, 3, devices=jax.devices()[:3], params=params, state=state,
        n_microbatches=2,
    )
    x = model.example_input(4)
    y_pp = pp.forward(x)
    y_ref, _ = model.apply(params, x, state=state)
    np.testing.assert_allclose(
        np.asarray(y_pp), np.asarray(y_ref), atol=2e-5
    )


def test_pipelined_training_matches_single_device():
    """One GPipe step must produce the same parameters as one single-device
    step on the same full batch (mean loss decomposes over microbatches)."""
    model = fc_net(12, hidden=(16, 16), n_classes=3)
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 12))
    y = np.asarray(jnp.arange(8) % 3, np.int32)

    tx = optax.sgd(0.1)
    pp = PipelineParallel.create(
        model, 2, loss_fn=cross_entropy_loss, tx=tx,
        devices=jax.devices()[:2], params=params, state=state,
        n_microbatches=4,
    )
    loss_pp = pp.train_step(x, y)

    ref = Trainer.create(model, tx, cross_entropy_loss, params=params,
                         state=state)
    loss_ref = float(ref.step(x, y))
    assert abs(loss_pp - loss_ref) < 1e-5
    merged = pp.gather_params()
    for k in merged:
        for pk in merged[k]:
            np.testing.assert_allclose(
                np.asarray(merged[k][pk]),
                np.asarray(ref.params[k][pk]),
                atol=1e-5, err_msg=f"{k}/{pk}",
            )


def test_1f1b_schedule_shape_and_memory_bound():
    """Every stage issues M forwards and M backwards; outstanding
    (un-backwarded) forwards at stage s never exceed n_stages - s — the
    memory property that separates 1F1B from GPipe (where it is M)."""
    for S, M in [(2, 4), (4, 8), (3, 2), (4, 1)]:
        sched = _1f1b_schedule(S, M)
        assert len(sched) == S
        for s, seq in enumerate(sched):
            assert sorted(k for op, k in seq if op == "F") == list(range(M))
            assert sorted(k for op, k in seq if op == "B") == list(range(M))
            live = peak = 0
            backwarded = set()
            for op, k in seq:
                if op == "F":
                    live += 1
                    peak = max(peak, live)
                else:
                    assert k in {kk for o, kk in seq[: seq.index((op, k))]
                                 if o == "F"}, "B before its F"
                    assert k not in backwarded
                    backwarded.add(k)
                    live -= 1
            assert peak <= min(S - s, M), (S, M, s, peak)
            # backwards in microbatch order (flush semantics)
            border = [k for op, k in seq if op == "B"]
            assert border == sorted(border)


def test_train_step_runs_1f1b_with_bounded_residuals():
    """The executed schedule matches 1F1B: per-stage peak live residuals
    are bounded by n_stages - s (GPipe would hold all M), and the step
    performs a single host sync."""
    model = fc_net(12, hidden=(16, 16, 16), n_classes=3)
    pp = PipelineParallel.create(
        model, 2, loss_fn=cross_entropy_loss, tx=optax.sgd(0.1),
        devices=jax.devices()[:2], seed=0, n_microbatches=8,
    )
    x = jax.random.normal(jax.random.PRNGKey(2), (16, 12))
    y = np.asarray(jnp.arange(16) % 3, np.int32)
    pp.train_step(x, y)
    stats = pp.last_step_stats
    assert stats["schedule"] == "1f1b"
    assert stats["host_syncs"] == 1
    for s, peak in enumerate(stats["max_live_residuals"]):
        assert peak <= 2 - s + 1  # n_stages - s, +1 slack never needed
        assert peak < 8  # strictly better than GPipe's M
    # issued op sequences match the planned schedule exactly
    assert stats["issued"] == _1f1b_schedule(2, 8)


def test_pipelined_bn_model_threads_state_through_microbatches():
    """BatchNorm running stats after one PP step must equal sequential
    microbatch processing with pre-step params on one device (microbatch
    k+1 sees the state microbatch k produced)."""
    from torchpruner_tpu.core import layers as L
    from torchpruner_tpu.core.segment import SegmentedModel

    model = SegmentedModel(
        (
            L.Conv("conv1", 4, kernel_size=(3, 3), padding="SAME"),
            L.BatchNorm("bn1"),
            L.Activation("act1", "relu"),
            L.Flatten("flatten"),
            L.Dense("fc1", 16),
            L.BatchNorm("bn2"),
            L.Activation("act2", "relu"),
            L.Dense("out", 3),
        ),
        (8, 8, 2),
    )
    params, state = init_model(model, seed=0)
    x = np.asarray(
        jax.random.normal(jax.random.PRNGKey(3), (8, 8, 8, 2)), np.float32
    )
    y = np.asarray(jnp.arange(8) % 3, np.int32)
    pp = PipelineParallel.create(
        model, 2, loss_fn=cross_entropy_loss, tx=optax.sgd(0.05),
        devices=jax.devices()[:2], params=params, state=state,
        n_microbatches=4,
    )
    pp.train_step(x, y)

    # reference: sequential microbatches, state threaded, params fixed
    ref_state = state
    for k in range(4):
        _, ref_state = model.apply(
            params, x[k * 2 : (k + 1) * 2], state=ref_state, train=True
        )
    got = pp.gather_state()
    flat_got = jax.tree_util.tree_leaves(got)
    flat_ref = jax.tree_util.tree_leaves(ref_state)
    assert len(flat_got) == len(flat_ref) > 0
    for a, b in zip(flat_got, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipelined_lm_training_runs_and_learns():
    model = llama_tiny(depth=2)
    pp = PipelineParallel.create(
        model, 2, loss_fn=lm_cross_entropy_loss, tx=optax.adam(1e-2),
        devices=jax.devices()[:2], seed=0, n_microbatches=2,
    )
    x = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 256), np.int32
    )
    losses = [pp.train_step(x, x) for _ in range(5)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # memorizes the fixed batch

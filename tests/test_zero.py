"""ZeRO-style cross-replica weight-update sharding
(``ShardedTrainer(zero=True)``) on the 8-device virtual CPU mesh:
zero-vs-replicated trainers must walk the same trajectory (the
reduce-scatter → 1/N update → all-gather transform is a layout change,
not a math change), optimizer state must actually live data-sharded
(the HBM claim, checked against ``training_memory``), and the placement
must survive prune→rebuild and checkpoint→restore — including a real
kill -9 → resume.
"""

import json
import os
import signal
import subprocess
import sys

import jax
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.data import synthetic_dataset
from torchpruner_tpu.models.mlp import fc_net
from torchpruner_tpu.parallel import (
    ShardedTrainer,
    make_mesh,
    training_memory,
    zero_update_spec,
)
from torchpruner_tpu.utils.losses import cross_entropy_loss

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def model_z():
    return fc_net(16, hidden=(64, 64), n_classes=4)


def batches_z(n=320, bs=32, seed=0):
    return synthetic_dataset((16,), 4, n, seed=seed).batches(bs)


def _has_data_axis(spec) -> bool:
    return any(
        "data" in (e if isinstance(e, tuple) else (e,))
        for e in spec if e is not None
    )


def test_zero_update_spec_rules():
    ms = {"data": 4, "model": 2}
    # largest unsharded dim that divides takes the data axis
    assert zero_update_spec((16, 64), P(None, "model"), ms) == \
        P("data", "model")
    # nothing unsharded divides -> extend the sharded dim to a tuple
    assert zero_update_spec((3, 64), P(None, "model"), ms) == \
        P(None, ("model", "data"))
    # nothing divides at all -> unchanged (replicated update fallback)
    assert zero_update_spec((3, 6), P(), ms) == P()
    # scalars unchanged; data axis of 1 is a no-op
    assert zero_update_spec((), P(), ms) == P()
    assert zero_update_spec((16, 64), P(None, "model"),
                            {"data": 1, "model": 2}) == P(None, "model")
    # already data-sharded (full-mesh tuple FSDP) stays put
    assert zero_update_spec((16, 64), P(("data", "model"), None), ms) == \
        P(("data", "model"), None)


@pytest.mark.parametrize("partition,accum,guarded", [
    ("fsdp", 1, False),
    ("tp", 1, False),
    ("fsdp", 2, True),
    ("tp", 2, True),
])
def test_zero_matches_replicated(partition, accum, guarded):
    """zero=True must be bit-close (rtol 1e-5) to the replicated-update
    trainer over 10 steps, composing with both partitions, gradient
    accumulation, and the compiled non-finite guard."""
    from torchpruner_tpu.resilience import StepGuard

    mesh = make_mesh({"data": 4, "model": 2})
    tx = optax.adam(1e-2)

    def mk(zero):
        return ShardedTrainer.create(
            model_z(), tx, cross_entropy_loss, mesh, seed=0,
            min_shard_size=0, partition=partition, zero=zero,
            accum_steps=accum,
            guard=StepGuard(3) if guarded else None,
        )

    t_rep, t_zero = mk(False), mk(True)
    for x, y in batches_z():
        l1 = float(t_rep.step(x, y))
        l2 = float(t_zero.step(x, y))
        np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-7)
    for a, b in zip(jax.tree_util.tree_leaves(t_rep.params),
                    jax.tree_util.tree_leaves(t_zero.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_zero_multi_step_matches_step():
    """K scanned steps in one SPMD program (ShardedTrainer.multi_step)
    must equal K individual zero steps on the same data."""
    mesh = make_mesh({"data": 4, "model": 2})
    tx = optax.sgd(0.05, momentum=0.9)
    ta = ShardedTrainer.create(model_z(), tx, cross_entropy_loss, mesh,
                               seed=0, min_shard_size=0, zero=True)
    tb = ShardedTrainer.create(model_z(), tx, cross_entropy_loss, mesh,
                               seed=0, min_shard_size=0, zero=True)
    data = list(batches_z(n=128, bs=32))
    xs = np.stack([d[0] for d in data])
    ys = np.stack([d[1] for d in data])
    losses_multi = np.asarray(ta.multi_step(xs, ys))
    losses_seq = [float(tb.step(x, y)) for x, y in data]
    np.testing.assert_allclose(losses_multi, losses_seq, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ta.params["fc1"]["w"]), np.asarray(tb.params["fc1"]["w"]),
        rtol=1e-5, atol=1e-7,
    )


def test_zero_opt_placement_and_memory_budget():
    """The HBM claim: param-shaped Adam slots actually live sharded over
    the data axis, and the planned budget drops accordingly —
    ``zero_opt <= replicated_opt / data_axis + const`` (the acceptance
    invariant; const covers replicated step-count scalars)."""
    mesh = make_mesh({"data": 4, "model": 2})
    tx = optax.adam(1e-3)
    t_rep = ShardedTrainer.create(model_z(), tx, cross_entropy_loss, mesh,
                                  seed=0, min_shard_size=0)
    t_zero = ShardedTrainer.create(model_z(), tx, cross_entropy_loss, mesh,
                                   seed=0, min_shard_size=0, zero=True)
    for t, want in ((t_rep, False), (t_zero, True)):
        for tree in (t.opt_state[0].mu, t.opt_state[0].nu):
            spec = tree["fc1"]["w"].sharding.spec
            assert _has_data_axis(spec) == want, (spec, want)
    # params themselves stay at the partition placement (ZeRO-1: the
    # data axis lives in the update domain, not the forward)
    assert not _has_data_axis(t_zero.params["fc1"]["w"].sharding.spec)

    kw = dict(tx=tx, params=t_rep.params)
    rep = training_memory(t_rep.model, t_rep._placements[0],
                          dict(mesh.shape), **kw)
    zero = training_memory(t_zero.model, t_zero._placements[0],
                           dict(mesh.shape), zero=True, **kw)
    data_ax = dict(mesh.shape)["data"]
    assert zero.opt_bytes <= rep.opt_bytes / data_ax + (1 << 16), \
        (zero.opt_bytes, rep.opt_bytes)
    assert zero.opt_bytes < rep.opt_bytes / 2  # a real drop, not slack
    # params/grads budgets are placement-unchanged
    assert zero.params_bytes == rep.params_bytes


def test_zero_prune_rebuild_reshards_smaller_opt_state():
    """rebuild() after a prune must re-shard the SMALLER optimizer state
    over the data axis and keep training."""
    mesh = make_mesh({"data": 4, "model": 2})
    t = ShardedTrainer.create(model_z(), optax.adam(1e-3),
                              cross_entropy_loss, mesh, seed=0,
                              min_shard_size=0, zero=True)
    data = list(batches_z(n=64, bs=32))
    for x, y in data:
        t.step(x, y)
    res = prune(t.model, t.params, "fc1", list(range(0, 64, 2)),
                state=t.state, opt_state=t.opt_state)
    t2 = t.rebuild(res.model, res.params, res.state, res.opt_state)
    assert t2.model.layer("fc1").features == 32
    mu = t2.opt_state[0].mu["fc1"]["w"]
    assert mu.shape == (16, 32)
    assert _has_data_axis(mu.sharding.spec), mu.sharding.spec
    for x, y in data:
        l = t2.step(x, y)
    assert np.isfinite(float(l))


def test_zero_checkpoint_roundtrip_preserves_placement_and_trajectory(
        tmp_path):
    """save → restore → rebuild must land the optimizer state back at
    the ZeRO placement and continue the exact trajectory."""
    from torchpruner_tpu.checkpoint import restore_checkpoint, save_checkpoint

    mesh = make_mesh({"data": 4, "model": 2})
    tx = optax.adam(1e-3)
    data = list(batches_z(n=128, bs=32))
    t = ShardedTrainer.create(model_z(), tx, cross_entropy_loss, mesh,
                              seed=0, min_shard_size=0, zero=True)
    for x, y in data[:2]:
        t.step(x, y)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, t.model, t.params, t.state, t.opt_state,
                    step=t.step_count)
    m2, p2, s2, o2, _meta = restore_checkpoint(path, tx=tx)
    t2 = t.rebuild(m2, p2, s2 or {}, o2)
    t2.rng = t.rng
    assert _has_data_axis(t2.opt_state[0].mu["fc1"]["w"].sharding.spec)
    for x, y in data[2:]:
        l1 = float(t.step(x, y))
        l2 = float(t2.step(x, y))
        np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_zero_config_requires_data_axis():
    from torchpruner_tpu.utils.config import ExperimentConfig

    with pytest.raises(ValueError, match="data"):
        ExperimentConfig(zero=True)
    cfg = ExperimentConfig(mesh={"data": 4, "model": 2}, zero=True)
    assert cfg.zero


@pytest.mark.slow
def test_zero_kill9_resume_matches_uninterrupted(tmp_path):
    """Acceptance: SIGKILL mid-train on the digits preset under
    mesh + zero=True, resume from the manifest — final metrics equal the
    uninterrupted zero run's (same contract as the local-trainer
    crash-resume test; in practice bit-identical)."""
    worker = os.path.join(REPO, "tests", "_resilience_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))

    def run(run_dir, chaos_spec=None):
        cmd = [sys.executable, worker, str(run_dir),
               json.dumps(chaos_spec) if chaos_spec else "", "zero"]
        return subprocess.run(cmd, capture_output=True, text=True,
                              env=env, cwd=REPO, timeout=600)

    ref = run(tmp_path / "uninterrupted")
    assert ref.returncode == 0, ref.stderr[-2000:]
    ja = json.loads([l for l in ref.stdout.splitlines()
                     if l.startswith("{")][-1])
    assert ja["devices"] == 4, ja

    killed = run(tmp_path / "killed", {"kill_at_step": 20})
    assert killed.returncode == -signal.SIGKILL, (
        killed.returncode, killed.stderr[-2000:])

    resumed = run(tmp_path / "killed")
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    jb = json.loads([l for l in resumed.stdout.splitlines()
                     if l.startswith("{")][-1])
    np.testing.assert_allclose(jb["final_test_loss"],
                               ja["final_test_loss"], rtol=1e-4)
    np.testing.assert_allclose(jb["w_abs_sum"], ja["w_abs_sum"],
                               rtol=1e-4)
    assert jb["epochs"] == ja["epochs"] == 2

"""One-pass sweep capture engine (attributions.base.ActivationCache).

Pins the tentpole claims: (1) cached and uncached scoring/ablation are
the SAME computation — all 8 panel methods' scores and the ablation
curves agree with capture on/off, on both the single-device and the
8-virtual-device mesh paths; (2) the whole multi-layer sweep compiles
≤ 2 capture programs (one per batch shape) regardless of layer count —
CompileWatcher-verified inside the ``capture_fill`` span; (3) mismatched
or unsupported consumers fall back to the uncached path and are counted
as misses, never silently served someone else's activations.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from torchpruner_tpu import obs
from torchpruner_tpu.attributions.base import ActivationCache
from torchpruner_tpu.core.graph import pruning_graph
from torchpruner_tpu.core.segment import capture_fn, init_model
from torchpruner_tpu.data.datasets import synthetic_dataset
from torchpruner_tpu.experiments.robustness import (
    ablation_curves_batch,
    layerwise_robustness,
    method_panel,
)
from torchpruner_tpu.models.mlp import fc_net
from torchpruner_tpu.utils.losses import cross_entropy_loss


def small_setup(n=32, bs=16, seed=0):
    """A 3-hidden-layer MLP + synthetic batches: 3 prunable sites whose
    eval layers shift through the LeakyReLUs."""
    model = fc_net(16, hidden=(12, 10, 8))
    params, state = init_model(model, seed=seed)
    data = synthetic_dataset((16,), 10, n, seed=seed)
    batches = [(jnp.asarray(x), jnp.asarray(y))
               for x, y in data.batches(bs)]
    return model, params, state, batches


def run_sweep(model, params, state, batches, *, capture, mesh=None,
              sv_samples=2):
    methods = method_panel(model, params, batches, cross_entropy_loss,
                           state=state, sv_samples=sv_samples)
    if mesh is not None:
        from torchpruner_tpu.parallel import DistributedScorer

        base = methods

        def wrap(factory):
            def make(run=0):
                return DistributedScorer(factory(run), mesh)
            return make

        methods = {name: wrap(f) for name, f in base.items()}
    return layerwise_robustness(
        model, params, state, batches, methods, cross_entropy_loss,
        verbose=False, capture=capture, mesh=mesh,
    )


def assert_sweeps_equal(a, b, rtol=1e-5):
    assert a.keys() == b.keys()
    for layer in a:
        assert a[layer].keys() == b[layer].keys()
        for m in a[layer]:
            for ra, rb in zip(a[layer][m], b[layer][m]):
                np.testing.assert_allclose(
                    ra["scores"], rb["scores"], rtol=rtol, atol=1e-6,
                    err_msg=f"{layer}/{m} scores")
                for k in ("loss", "acc", "base_loss", "base_acc"):
                    np.testing.assert_allclose(
                        ra[k], rb[k], rtol=rtol, atol=1e-6,
                        err_msg=f"{layer}/{m} {k}")


def test_capture_fn_matches_per_site_prefix():
    """The ONE multi-site program emits exactly what L per-site prefix
    runs would."""
    model, params, state, batches = small_setup()
    sites = ("act1", "act2", "act3")
    fn = capture_fn(model, sites)
    x = batches[0][0]
    caps = fn(params, state, x)
    for s in sites:
        ref, _ = model.apply(params, x, state=state, to_layer=s)
        np.testing.assert_array_equal(np.asarray(caps[s]),
                                      np.asarray(ref))


def test_panel_cached_vs_uncached_single_device():
    """All 8 panel methods (incl. 3 stochastic repeats) and the ablation
    walks: identical results with the capture engine on and off."""
    model, params, state, batches = small_setup()
    on = run_sweep(model, params, state, batches, capture=True)
    off = run_sweep(model, params, state, batches, capture=False)
    assert_sweeps_equal(on, off)


def test_panel_cached_vs_uncached_mesh():
    """Same equality through DistributedScorer + the SPMD ablation walk
    on the 8-virtual-device mesh (cached activations are filled sharded
    over the data axis)."""
    from torchpruner_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 8})
    model, params, state, batches = small_setup(n=32, bs=16)
    on = run_sweep(model, params, state, batches, capture=True, mesh=mesh)
    off = run_sweep(model, params, state, batches, capture=False,
                    mesh=mesh)
    assert_sweeps_equal(on, off)
    # and the mesh run equals the single-device run (same examples)
    local = run_sweep(model, params, state, batches, capture=True)
    assert_sweeps_equal(on, local, rtol=2e-5)


def test_ablation_curves_batch_cached_matches():
    model, params, state, batches = small_setup()
    rankings = np.stack([np.argsort(np.arange(12)),
                         np.argsort(-np.arange(12))])
    cache = ActivationCache(model, params, batches, sites=("act1",),
                            state=state)
    kw = dict(eval_layer="act1")
    a = ablation_curves_batch(model, params, state, "fc1", rankings,
                              batches, cross_entropy_loss,
                              capture_cache=cache, **kw)
    b = ablation_curves_batch(model, params, state, "fc1", rankings,
                              batches, cross_entropy_loss, **kw)
    assert cache.hits > 0
    for ca, cb in zip(a, b):
        for k in ("loss", "acc", "base_loss", "base_acc"):
            np.testing.assert_allclose(ca[k], cb[k], rtol=1e-5,
                                       atol=1e-6, err_msg=k)


def test_sweep_compiles_at_most_two_capture_programs():
    """The CI invariant: prefix/capture compiles in the capture_fill span
    stay ≤ 2 (one per distinct batch shape) no matter how many layers the
    sweep walks — the O(L) compile bill collapses to O(1).  Uses a ragged
    tail batch to exercise the =2 case, and CompileWatcher (not our own
    counters) as the source of truth."""
    model, params, state, _ = small_setup()
    data = synthetic_dataset((16,), 10, 40, seed=0)
    batches = [(jnp.asarray(x), jnp.asarray(y))
               for x, y in data.batches(16)]  # 16, 16, 8: two shapes
    session = obs.configure(None, process_index=0, annotate=False)
    try:
        run_sweep(model, params, state, batches, capture=True)
        fill = session.tracer.phase_summary().get("capture_fill")
        assert fill is not None, "capture_fill span never opened"
        assert fill["calls"] == 1, "cache filled more than once"
        assert fill["compile_count"] <= 2, fill
        counts = obs.capture_counts()
        assert counts["capture_hits"] > 0
        assert counts["capture_misses"] == 0
        assert counts["prefix_flops_saved"] > 0
    finally:
        obs.shutdown()


def test_mismatched_metric_falls_back_and_counts_miss():
    """A metric scoring DIFFERENT data than the cache was built from must
    recompute its own prefix (correct scores), counted as a miss."""
    from torchpruner_tpu.attributions import TaylorAttributionMetric

    model, params, state, batches = small_setup()
    other = synthetic_dataset((16,), 10, 32, seed=9)
    other_batches = [(jnp.asarray(x), jnp.asarray(y))
                     for x, y in other.batches(16)]
    cache = ActivationCache(model, params, batches, sites=("act1",),
                            state=state)
    m = TaylorAttributionMetric(model, params, other_batches,
                                cross_entropy_loss, state=state)
    m.capture_cache = cache
    got = m.run("fc1", find_best_evaluation_layer=True)
    m2 = TaylorAttributionMetric(model, params, other_batches,
                                 cross_entropy_loss, state=state)
    ref = m2.run("fc1", find_best_evaluation_layer=True)
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    assert cache.misses > 0 and cache.hits == 0


def test_forced_masking_path_declines_cache():
    """Shapley with use_partial=False cannot resume from a captured
    activation — it must decline (miss) and still match the fast path."""
    from torchpruner_tpu.attributions import ShapleyAttributionMetric

    model, params, state, batches = small_setup()
    cache = ActivationCache(model, params, batches, sites=("act1",),
                            state=state)

    def scores(use_partial, with_cache):
        m = ShapleyAttributionMetric(
            model, params, batches, cross_entropy_loss, state=state,
            sv_samples=4, use_partial=use_partial, seed=3)
        if with_cache:
            m.capture_cache = cache
        return m.run("fc1", find_best_evaluation_layer=True)

    slow = scores(False, True)
    assert cache.misses > 0
    fast = scores(True, True)
    assert cache.hits > 0
    np.testing.assert_allclose(slow, fast, rtol=1e-4, atol=1e-5)


def test_mesh_sweep_with_bn_state_hits_and_matches():
    """Non-empty (BatchNorm) state on the mesh path: the sweep aliases
    the replicated state copy, so the guards keep serving (no spurious
    misses) and results equal the uncached run."""
    from torchpruner_tpu.models import vgg16_bn
    from torchpruner_tpu.parallel import DistributedScorer, make_mesh
    from torchpruner_tpu.data import load_dataset

    model = vgg16_bn(width_multiplier=0.125, classifier_width=64)
    params, state = init_model(model, seed=0)
    assert state  # BN running stats — the non-empty-state case
    test = load_dataset("digits32", "test", n=16, seed=0)
    batches = [(jnp.asarray(x), jnp.asarray(y))
               for x, y in test.batches(16)]
    mesh = make_mesh({"data": 8})
    session = obs.configure(None, process_index=0, annotate=False)
    try:
        def sweep(capture):
            base = method_panel(model, params, batches,
                                cross_entropy_loss, state=state,
                                sv_samples=2)
            methods = {
                n: (lambda f: (lambda run=0:
                               DistributedScorer(f(run), mesh)))(f)
                for n, f in base.items()
            }
            return layerwise_robustness(
                model, params, state, batches, methods,
                cross_entropy_loss, layers=["conv2"], verbose=False,
                capture=capture, mesh=mesh)

        on = sweep(True)
        counts = obs.capture_counts()
        assert counts["capture_misses"] == 0, counts
        assert counts["capture_hits"] > 0, counts
        off = sweep(False)
        assert_sweeps_equal(on, off)
    finally:
        obs.shutdown()


def test_drop_releases_site_and_sweep_drops_finished_layers():
    """drop() frees a site's activations/gradients; the sweep drops each
    layer's site once its panel is done (bounding the cache to live
    sites, not O(L × dataset))."""
    model, params, state, batches = small_setup()
    cache = ActivationCache(model, params, batches,
                            sites=("act1", "act2"), state=state)
    list(cache.batches_for("act1"))  # fill
    assert all("act2" in caps for caps, _ in cache._batches)
    cache.drop("act2")
    assert not cache.has("act2")
    assert all("act2" not in caps for caps, _ in cache._batches)
    assert cache.has("act1")  # untouched


def test_nested_sites_are_skipped_not_cached():
    """needs_taps sites (inside a Residual body) never enter the cache —
    they stay on the instrumented full-forward path."""
    from torchpruner_tpu.core import layers as L
    from torchpruner_tpu.core.segment import SegmentedModel

    model = SegmentedModel(
        (L.Dense("fc1", 8), L.Activation("a1", "relu"),
         L.Residual("blk", body=(L.Dense("inner", 8),
                                 L.Activation("ia", "relu"),
                                 L.Dense("proj", 8))),
         L.Dense("out", 4)),
        (16,),
    )
    params, state = init_model(model, seed=0)
    data = synthetic_dataset((16,), 4, 16, seed=0)
    batches = [(jnp.asarray(x), jnp.asarray(y))
               for x, y in data.batches(16)]
    cache = ActivationCache(model, params, batches,
                            sites=("a1", "blk/ia"), state=state)
    assert cache.sites == ("a1",)
    assert cache.skipped_sites == ("blk/ia",)
    assert not cache.has("blk/ia")

"""Real-data ingestion: the sklearn-digits loader (real data, always
available) and the MNIST-IDX / CIFAR-pickle preparation scripts (driven on
synthetic distribution files with the exact public formats)."""

import gzip
import os
import pickle
import struct

import numpy as np
import pytest

from torchpruner_tpu.data import load_dataset
from torchpruner_tpu.data.prepare import (
    prepare_cifar10,
    prepare_digits,
    prepare_mnist,
    read_idx,
)


def test_digits_is_real_deterministic_and_split():
    tr = load_dataset("digits_flat", "train")
    va = load_dataset("digits_flat", "val")
    te = load_dataset("digits", "test")
    assert (len(tr), len(va), len(te)) == (1297, 200, 300)
    assert tr.x.shape == (1297, 64) and te.x.shape == (300, 8, 8, 1)
    assert 0.0 <= tr.x.min() and tr.x.max() <= 1.0
    assert set(np.unique(tr.y)) == set(range(10))  # all classes present
    # splits are disjoint (pixel rows can repeat; rely on the permutation)
    tr2 = load_dataset("digits_flat", "train")
    np.testing.assert_array_equal(tr.x, tr2.x)  # deterministic
    # real data is learnable far beyond chance by a linear probe
    from sklearn.linear_model import LogisticRegression

    clf = LogisticRegression(max_iter=200).fit(tr.x[:500], tr.y[:500])
    assert clf.score(va.x, va.y) > 0.85


def test_digits32_upscales_real_digits_to_cifar_geometry():
    base = load_dataset("digits", "test")
    ds = load_dataset("digits32", "test")
    assert ds.x.shape == (300, 32, 32, 3)
    np.testing.assert_array_equal(ds.y, base.y)
    # nearest-neighbour 4x upsample, tiled over 3 identical channels
    np.testing.assert_array_equal(ds.x[:, ::4, ::4, 0], base.x[..., 0])
    np.testing.assert_array_equal(ds.x[..., 0], ds.x[..., 2])
    np.testing.assert_array_equal(ds.x[:, 1::4, 2::4, 1], base.x[..., 0])


def _write_idx(path, arr):
    ndim = arr.ndim
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">I", (0x08 << 8) | ndim))
        f.write(struct.pack(f">{ndim}I", *arr.shape))
        f.write(arr.astype(np.uint8).tobytes())


def test_prepare_mnist_from_idx_files(tmp_path, monkeypatch):
    src, out = tmp_path / "src", tmp_path / "out"
    src.mkdir()
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 256, size=(50, 28, 28), dtype=np.uint8)
    ys = rng.integers(0, 10, size=(50,), dtype=np.uint8)
    xt = rng.integers(0, 256, size=(20, 28, 28), dtype=np.uint8)
    yt = rng.integers(0, 10, size=(20,), dtype=np.uint8)
    _write_idx(src / "train-images-idx3-ubyte.gz", xs)
    _write_idx(src / "train-labels-idx1-ubyte.gz", ys)
    _write_idx(src / "t10k-images-idx3-ubyte.gz", xt)
    _write_idx(src / "t10k-labels-idx1-ubyte.gz", yt)
    # round-trip of the IDX parser itself
    np.testing.assert_array_equal(
        read_idx(str(src / "train-images-idx3-ubyte.gz")), xs
    )

    sizes = prepare_mnist(str(src), str(out), n_val=10)
    assert sizes == {"train": 40, "val": 10, "test": 20}
    monkeypatch.setenv("TORCHPRUNER_TPU_DATA_DIR", str(out))
    ds = load_dataset("mnist", "train")
    flat = load_dataset("mnist_flat", "test")
    assert ds.x.shape == (40, 28, 28, 1) and flat.x.shape == (20, 784)
    # normalization: reconstructing raw pixels must round-trip
    raw = (ds.x[..., 0] * 0.3081 + 0.1307) * 255.0
    assert np.abs(raw.round() - raw).max() < 1e-2
    assert ds.y.dtype == np.int32


def test_prepare_cifar10_from_pickles(tmp_path, monkeypatch):
    src, out = tmp_path / "src", tmp_path / "out"
    src.mkdir()
    rng = np.random.default_rng(1)

    def write_batch(name, n):
        with open(src / name, "wb") as f:
            pickle.dump({
                b"data": rng.integers(
                    0, 256, size=(n, 3072), dtype=np.uint8
                ),
                b"labels": rng.integers(0, 10, size=(n,)).tolist(),
            }, f)

    for i in range(1, 6):
        write_batch(f"data_batch_{i}", 10)
    write_batch("test_batch", 8)
    sizes = prepare_cifar10(str(src), str(out), n_val=10)
    assert sizes == {"train": 40, "val": 10, "test": 8}
    monkeypatch.setenv("TORCHPRUNER_TPU_DATA_DIR", str(out))
    ds = load_dataset("cifar10", "val")
    assert ds.x.shape == (10, 32, 32, 3)
    # ImageNet-normalized: channel means near the normalized midpoint
    assert np.isfinite(ds.x).all() and ds.x.std() > 0.5


def test_prepare_digits_materializes_loader_output(tmp_path):
    sizes = prepare_digits(str(tmp_path))
    assert sizes == {"train": 1297, "val": 200, "test": 300}
    x = np.load(tmp_path / "digits_flat_val_x.npy")
    np.testing.assert_array_equal(x, load_dataset("digits_flat", "val").x)


def test_prepare_mnist_missing_files_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        prepare_mnist(str(tmp_path), str(tmp_path / "out"))


def test_disk_datasets_are_memory_mapped(tmp_path, monkeypatch):
    """Real on-disk datasets load as memmaps (imagenet-scale arrays never
    fully materialize) and batch identically to an eager load."""
    x = np.random.default_rng(0).normal(size=(50, 8, 8, 3)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 10, size=(50,)).astype(np.int32)
    np.save(tmp_path / "imagenet64_val_x.npy",
            x.astype(np.float32))
    np.save(tmp_path / "imagenet64_val_y.npy", y)
    monkeypatch.setenv("TORCHPRUNER_TPU_DATA_DIR", str(tmp_path))
    ds = load_dataset("imagenet64", "val")
    assert isinstance(ds.x, np.memmap)
    for (bx, by), i in zip(ds.iter_batches(16), range(4)):
        np.testing.assert_array_equal(np.asarray(bx), x[i * 16:(i + 1) * 16])
    sub = ds.subset(10, seed=3)
    assert len(sub) == 10 and np.isfinite(np.asarray(sub.x)).all()


def test_resample_grows_split_with_replacement():
    """Dataset.resample draws n examples with replacement — the cost-curve
    vehicle that lets sweep_scaling measure n=1000 on a 300-example
    split (wall-clock depends on array sizes, not label novelty)."""
    import numpy as np

    from torchpruner_tpu.data import load_dataset

    ds = load_dataset("digits32", "test", seed=0)
    big = ds.resample(2 * len(ds.x) + 7, seed=0)
    assert len(big.x) == 2 * len(ds.x) + 7
    assert big.x.shape[1:] == ds.x.shape[1:]
    assert set(np.unique(big.y)) <= set(np.unique(ds.y))

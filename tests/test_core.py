"""Core layer / SegmentedModel behavior."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.models import fmnist_convnet, max_model, mnist_fc, vgg16_bn
from torchpruner_tpu.models.analytic import max_model_batches


def test_max_model_forward_is_max():
    model, params, x, y = max_model()
    out, _ = model.apply(params, x)
    np.testing.assert_array_almost_equal(np.asarray(out), np.asarray(y))


def test_shape_inference_matches_eval_shape():
    for model in [mnist_fc(), fmnist_convnet(), vgg16_bn()]:
        params, state = init_model(model, seed=0)
        x = jnp.zeros((2,) + tuple(model.input_shape))
        out = jax.eval_shape(
            lambda p, s, x: model.apply(p, x, state=s)[0], params, state, x
        )
        assert tuple(out.shape) == (2,) + model.out_shape()


def test_prefix_suffix_compose():
    model = fmnist_convnet()
    params, state = init_model(model, seed=1)
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 28, 28, 1))
    full, _ = model.apply(params, x, state=state)
    for cut in ["conv1", "pool1", "flatten", "fc1", "act3"]:
        z, _ = model.apply(params, x, state=state, to_layer=cut)
        rest, _ = model.apply(params, z, state=state, from_layer=cut)
        np.testing.assert_allclose(
            np.asarray(rest), np.asarray(full), rtol=1e-5, atol=1e-5
        )


def test_unit_mask_zeroes_units():
    model, params, x, _ = max_model()
    mask = jnp.array([1.0, 1.0, 0.0, 1.0])
    z, _ = model.apply(params, x, to_layer="fc1", unit_mask=("fc1", mask))
    assert np.all(np.asarray(z)[:, 2] == 0)
    # masking pre-activation == masking post-relu for these inputs
    full_masked, _ = model.apply(params, x, unit_mask=("fc1", mask))
    z2, _ = model.apply(params, x, to_layer="fc1")
    manual, _ = model.apply(params, z2 * mask, from_layer="fc1")
    np.testing.assert_allclose(np.asarray(full_masked), np.asarray(manual))


def test_batchnorm_train_updates_state_eval_uses_it():
    model = SegmentedModel(
        (L.Dense("fc", 4), L.BatchNorm("bn")), input_shape=(3,)
    )
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 3))
    _, new_state = model.apply(params, x, state=state, train=True)
    assert not np.allclose(
        np.asarray(new_state["bn"]["mean"]), np.asarray(state["bn"]["mean"])
    )
    # eval mode leaves state untouched
    _, state2 = model.apply(params, x, state=new_state, train=False)
    np.testing.assert_array_equal(
        np.asarray(state2["bn"]["mean"]), np.asarray(new_state["bn"]["mean"])
    )


def test_dropout_train_vs_eval():
    model = SegmentedModel(
        (L.Dense("fc", 50), L.Dropout("drop", 0.5)), input_shape=(10,)
    )
    params, _ = init_model(model, seed=0)
    x = jnp.ones((4, 10))
    y_eval, _ = model.apply(params, x)
    y_tr, _ = model.apply(params, x, train=True, rng=jax.random.PRNGKey(0))
    assert np.any(np.asarray(y_tr) == 0.0) or not np.allclose(
        np.asarray(y_tr), np.asarray(y_eval)
    )


def test_widths_and_replace_layer():
    model = mnist_fc()
    assert model.widths() == {"fc1": 2024, "fc2": 2024, "out": 10}
    m2 = model.replace_layer("fc1", L.with_features(model.layer("fc1"), 100))
    assert m2.widths()["fc1"] == 100
    assert model.widths()["fc1"] == 2024  # original untouched


def test_model_is_hashable_jit_key():
    m1, m2 = mnist_fc(), mnist_fc()
    assert hash(m1) == hash(m2) and m1 == m2
    assert m1 != m1.replace_layer("fc1", L.with_features(m1.layer("fc1"), 5))


def test_duplicate_names_rejected():
    with pytest.raises(ValueError):
        SegmentedModel((L.Dense("a", 3), L.Dense("a", 4)), (2,))

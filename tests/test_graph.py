"""Pruning-graph inference + evaluation-point shifting + NaN oracle.

Mirrors the reference's cascade-discovery tests (reference
tests/test_pruner.py:72-121) but validates the STATIC graph against the
NaN-propagation oracle instead of relying on the oracle for pruning.
"""

import numpy as np
import pytest

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.graph import (
    find_best_evaluation_layer,
    nan_cascade_oracle,
    pruning_graph,
    group_for,
)
from torchpruner_tpu.core.plan import expand_keep, keep_indices
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.models import fmnist_convnet, vgg16_bn


def test_linear_linear_graph():
    m = SegmentedModel(
        (L.Dense("a", 8), L.Activation("r", "relu"), L.Dense("b", 4)), (6,)
    )
    (g,) = pruning_graph(m)
    assert g.target == "a"
    assert [c.layer for c in g.consumers] == ["b"]
    assert g.consumers[0].axis == 0 and g.consumers[0].fan_out == 1


def test_linear_bn_linear_graph():
    m = SegmentedModel(
        (L.Dense("a", 8), L.BatchNorm("bn"), L.Activation("r", "relu"),
         L.Dense("b", 4)),
        (6,),
    )
    (g,) = pruning_graph(m)
    assert [b.layer for b in g.attached_bn] == ["bn"]
    assert g.attached_bn[0].fan_out == 1


def test_conv_flatten_linear_fanout():
    # one conv channel fans out into spatial-many inputs of the dense
    # consumer (reference tests/test_pruner.py:83-92)
    m = SegmentedModel(
        (L.Conv("c", 3, (3, 3), padding="SAME"), L.Flatten("f"),
         L.Dense("d", 5)),
        (4, 4, 1),
    )
    (g,) = pruning_graph(m)
    c = g.consumers[0]
    assert c.layer == "d" and c.fan_out == 16  # 4*4 spatial positions


def test_conv_pool_flatten_linear_fanout():
    # max-pool shrinks the spatial fan-out (reference test_pruner.py:94-107)
    m = SegmentedModel(
        (L.Conv("c", 3, (3, 3), padding="SAME"), L.Pool("p", "max", (2, 2)),
         L.Flatten("f"), L.Dense("d", 5)),
        (4, 4, 1),
    )
    (g,) = pruning_graph(m)
    assert g.consumers[0].fan_out == 4  # 2*2 after pooling


def test_bn_after_flatten_gets_fanout():
    m = SegmentedModel(
        (L.Conv("c", 4, (3, 3), padding="SAME"), L.Flatten("f"),
         L.BatchNorm("bn"), L.Dense("d", 5)),
        (4, 4, 1),
    )
    (g,) = pruning_graph(m)
    assert g.attached_bn[0].fan_out == 16
    assert g.consumers[0].fan_out == 16


def test_vgg_graph_has_15_groups():
    groups = pruning_graph(vgg16_bn())
    assert len(groups) == 15  # 13 convs + fc1 + fc2; 'out' excluded
    assert groups[-1].target == "fc2"
    # dropout after fc1 attaches to fc1's group
    fc1 = group_for(vgg16_bn(), "fc1")
    assert fc1.attached_dropout == ("drop1",)


def test_find_best_evaluation_layer():
    m = SegmentedModel(
        (L.Dense("a", 8), L.BatchNorm("bn"), L.Activation("r", "relu"),
         L.Dense("b", 4)),
        (6,),
    )
    # shift past BN + ReLU (reference tests/test_attributions.py:177-201)
    assert find_best_evaluation_layer(m, "a") == "r"
    # a pool stops the walk
    m2 = fmnist_convnet()
    assert find_best_evaluation_layer(m2, "conv1") == "act1"
    assert find_best_evaluation_layer(m2, "fc1") == "act3"


@pytest.mark.parametrize("model_fn,target,drop", [
    (fmnist_convnet, "conv1", [0, 5]),
    (fmnist_convnet, "conv2", [1, 2, 63]),
    (fmnist_convnet, "fc1", [0, 100, 4095]),
])
def test_static_graph_matches_nan_oracle(model_fn, target, drop):
    """The static fan-out maps must reproduce exactly the indices the NaN
    trick discovers (reference pruner.py:21-57 as ground truth)."""
    model = model_fn()
    params, state = init_model(model, seed=0)
    report = nan_cascade_oracle(model, params, state, target, drop)
    group = group_for(model, target)
    n = model.layer(target).features
    dropped = np.setdiff1d(np.arange(n), keep_indices(n, drop))

    for c in group.consumers:
        # expected tainted input positions under the static fan-out map
        expected = np.sort(
            (np.arange(c.fan_out)[:, None] * n + dropped[None, :]).ravel()
        )
        got, orig_len = report[c.layer]
        np.testing.assert_array_equal(np.sort(got), expected)
        assert orig_len == n * c.fan_out
    for bn in group.attached_bn:
        expected = np.sort(
            (np.arange(bn.fan_out)[:, None] * n + dropped[None, :]).ravel()
        )
        got, _ = report[bn.layer]
        np.testing.assert_array_equal(np.sort(got), expected)


def test_expand_keep_strided_map():
    keep = keep_indices(4, [1])
    np.testing.assert_array_equal(
        expand_keep(keep, 4, 3), [0, 2, 3, 4, 6, 7, 8, 10, 11]
    )

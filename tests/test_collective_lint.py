"""tpu-lint passes 4/5: collective-contract lint over the REAL compiled
step programs (analysis/collective_lint.py) and the static step-time
cost model (analysis/cost_model.py).

The contract tests compile actual SPMD programs over the 8 virtual CPU
devices conftest forces, so the collectives asserted on are the ones the
partitioner emitted — not a simulation.  The golden predicted-vs-measured
test runs the digits CPU smoke trainer and holds the cost model to
informational tolerances (CPU constants are order-of-magnitude by
design; the <30% assertion is staged for on-chip capture)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchpruner_tpu.analysis import collective_lint as cl
from torchpruner_tpu.analysis import cost_model as cm
from torchpruner_tpu.analysis.collective_lint import Collective
from torchpruner_tpu.analysis.runner import lint_config
from torchpruner_tpu.experiments.presets import mnist_mlp_shapley
from torchpruner_tpu.parallel.mesh import relaxed_shard_map


def _zero_cfg(**kw):
    return dataclasses.replace(
        mnist_mlp_shapley(smoke=True), name="zero_lint",
        mesh={"data": 4, "model": 2}, zero=True, **kw)


def _mesh(*axes):
    names, sizes = zip(*axes)
    n = int(np.prod(sizes))
    return Mesh(np.array(jax.devices()[:n]).reshape(sizes), names)


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------


def test_downscale_axes_preserves_structure():
    assert cl.downscale_axes({"data": 8, "model": 8}, 8) == \
        {"data": 4, "model": 2} or \
        cl.downscale_axes({"data": 8, "model": 8}, 8) == \
        {"data": 2, "model": 4}
    # >1 axes never collapse to 1; 1-axes stay 1
    got = cl.downscale_axes({"data": 64, "model": 1}, 8)
    assert got == {"data": 8, "model": 1}
    # a single-device host cannot preserve a 2-axis structure
    assert cl.downscale_axes({"data": 4, "model": 2}, 1) is None
    assert cl.downscale_axes({"data": 4}, 2) == {"data": 2}


def test_hlo_collective_bytes_pinned_on_data_mesh():
    """A data-sharded sum to a replicated result is exactly one
    all-reduce of the result's bytes over the data axis — the byte-count
    extraction the cost model's ICI term stands on."""
    mesh = _mesh(("data", 4))
    f = jax.jit(lambda x: x.sum(axis=0),
                in_shardings=NamedSharding(mesh, P("data")),
                out_shardings=NamedSharding(mesh, P()))
    compiled = f.lower(
        jax.ShapeDtypeStruct((8, 128), jnp.float32)).compile()
    colls = cl.hlo_collectives(compiled, mesh)
    ar = [c for c in colls if c.kind == "all-reduce"]
    assert ar, [c.kind for c in colls]
    assert sum(c.bytes for c in ar) == 128 * 4
    assert all(c.group_size == 4 and c.axes == ("data",) for c in ar)


def test_hlo_collective_axes_on_2d_mesh():
    """On a {data:4, model:2} mesh, a model-sharded matmul's partial-sum
    reduction attributes to the model axis and an all-gather back to
    replicated attributes to the axis it spans."""
    mesh = _mesh(("data", 4), ("model", 2))
    w_sh = NamedSharding(mesh, P("model", None))
    x_sh = NamedSharding(mesh, P("data", "model"))
    out_sh = NamedSharding(mesh, P("data", None))
    f = jax.jit(lambda x, w: x @ w, in_shardings=(x_sh, w_sh),
                out_shardings=out_sh)
    compiled = f.lower(
        jax.ShapeDtypeStruct((8, 64), jnp.float32),
        jax.ShapeDtypeStruct((64, 32), jnp.float32)).compile()
    colls = cl.hlo_collectives(compiled, mesh)
    assert colls, "contracting a model-sharded dim must communicate"
    assert all(c.axes == ("model",) for c in colls), \
        [(c.kind, c.axes) for c in colls]


def test_wire_bytes_ring_costs():
    assert Collective("all-reduce", 1000, 4, ("data",)).wire_bytes() == \
        pytest.approx(2 * 1000 * 3 / 4)
    assert Collective("all-gather", 1000, 4, ("data",)).wire_bytes() == \
        pytest.approx(1000 * 3 / 4)
    assert Collective("reduce-scatter", 250, 4, ("data",)).wire_bytes() \
        == pytest.approx(250 * 3)
    assert Collective("collective-permute", 1000, 2,
                      ("data",)).wire_bytes() == 1000.0


# ---------------------------------------------------------------------------
# mode contracts on real programs
# ---------------------------------------------------------------------------


def test_zero_contract_clean_on_real_program():
    """The shipped ZeRO step program carries its sharded-update evidence
    (param-scale all-gathers over the data axis; TPU emits a true
    reduce-scatter) — the full 5-pass lint reports zero errors."""
    report = lint_config(_zero_cfg())
    assert report.ok, report.format()
    records, _ = cl.build_programs(_zero_cfg())
    train = next(r for r in records if r.name == "train_step")
    gather = sum(c.bytes for c in train.collectives
                 if c.kind == "all-gather" and c.axes is not None
                 and "data" in c.axes)
    assert gather >= train.param_bytes // 10, \
        [(c.kind, c.bytes, c.axes) for c in train.collectives]


def test_multi_step_program_carries_the_zero_contract():
    """The scanned K-steps-per-dispatch twin compiles as its own record
    and its loop body's collectives satisfy the same ZeRO contract —
    a regression that drops the update sharding only inside the scan
    cannot hide behind the single-step program."""
    findings, records = cl.lint_collectives(_zero_cfg())
    names = {r.name for r in records}
    assert "multi_step" in names, names
    assert not [f for f in findings if f.severity == "error"], findings
    multi = next(r for r in records if r.name == "multi_step")
    # cost_analysis counts a scan body once regardless of trip count, so
    # the compiled program's numbers already describe ONE optimizer step
    # and no per-step normalization applies; K rides along in meta.
    assert multi.meta["k"] == 2
    assert multi.steps_per_call == 1
    gather = sum(c.bytes for c in multi.collectives
                 if c.kind == "all-gather" and c.axes is not None
                 and "data" in c.axes)
    assert gather > 0, [(c.kind, c.axes) for c in multi.collectives]


def test_cli_zero_flag_applies_before_lint(tmp_path, monkeypatch, capsys):
    """The PR 9 ordering-bug class: ``--zero`` given as a FLAG (config
    says zero=False) must reach the lint — with the plant armed, the
    flag-driven zero contract must still fail loudly (exit 1 naming the
    check), proving --zero applies before --lint evaluates."""
    from torchpruner_tpu.__main__ import main

    cfg = dataclasses.replace(mnist_mlp_shapley(smoke=True),
                              name="cli_zero",
                              mesh={"data": 4, "model": 2})
    assert not cfg.zero
    path = tmp_path / "cli_zero.json"
    cfg.to_json(str(path))
    monkeypatch.setenv("TORCHPRUNER_LINT_PLANT", "replicated_allreduce")
    assert main(["--lint", str(path), "--zero"]) == 1
    assert "collective/zero-replicated-allreduce" in \
        capsys.readouterr().out
    monkeypatch.delenv("TORCHPRUNER_LINT_PLANT")
    assert main(["--lint", str(path), "--zero"]) == 0


def test_planted_replicated_allreduce_exits_dirty(monkeypatch):
    """TORCHPRUNER_LINT_PLANT=replicated_allreduce knocks the ZeRO
    update transform out of the shared placement planner while the
    config still says zero=True — the regression every numeric test
    passes.  The collective pass must name the violated contract."""
    monkeypatch.setenv("TORCHPRUNER_LINT_PLANT", "replicated_allreduce")
    report = lint_config(_zero_cfg())
    assert not report.ok
    assert any(f.check == "collective/zero-replicated-allreduce"
               for f in report.errors), report.format()


def test_plant_env_confined_to_lint_drivers(monkeypatch):
    """The planted-hazard env must be consumed ONLY by the lint drivers
    — a stale shell export cannot reach the telemetry cost predictor's
    build (it would silently skew every run's predicted_* gauges while
    parallel/train.py documents the env as lint-confined)."""
    monkeypatch.setenv("TORCHPRUNER_LINT_PLANT", "replicated_allreduce")
    # telemetry-shaped call: no plant= argument -> the TRUE program,
    # with the zero placement intact despite the env
    records, _ = cl.build_programs(_zero_cfg())
    train = next(r for r in records if r.name == "train_step")
    assert train.meta["zero_placements"] is not None
    # the lint driver still drives the drill through env_plant()
    findings, _ = cl.lint_collectives(_zero_cfg())
    assert any(f.check == "collective/zero-replicated-allreduce"
               for f in findings), findings


def test_tp_decode_unsharded_heads_warned():
    """Heads that don't divide the model axis mean the TP decode program
    (and its KV-cache contract check) cannot be built — the configs MOST
    at risk of KV replication must get a warning, never a silent skip."""
    from torchpruner_tpu.models.llama import llama_tiny

    cfg = dataclasses.replace(
        mnist_mlp_shapley(smoke=True), name="tp_odd_heads",
        model="llama_tiny", loss="lm_cross_entropy",
        mesh={"data": 2, "model": 2}, partition="tp")
    model = llama_tiny(dim=48, num_heads=3, num_kv_heads=3)
    findings, records = cl.lint_collectives(cfg, model=model)
    assert "decode_tp" not in {r.name for r in records}
    warned = [f for f in findings
              if f.check == "collective/tp-decode-unsharded"]
    assert warned and warned[0].severity == "warning", findings


def test_undownscalable_mesh_degrades_not_crashes(monkeypatch):
    """A mesh that can't be structure-preserved on this host must
    degrade to collective/skipped — and the MESHLESS programs
    (decode/prefill) must still build so the telemetry gauges survive
    single-device hosts."""
    monkeypatch.setattr(cl, "downscale_axes", lambda axes, n: None)
    cfg = dataclasses.replace(
        mnist_mlp_shapley(smoke=True), name="no_downscale",
        model="llama_tiny", loss="lm_cross_entropy",
        mesh={"data": 4, "model": 2}, partition="tp")
    records, findings = cl.build_programs(cfg)
    assert any(f.check == "collective/skipped" for f in findings)
    assert {"decode", "prefill"} <= {r.name for r in records}, records


def test_fsdp_missing_gather_contract():
    colls = [Collective("all-gather", 4096, 2, ("model",))]
    assert cl.check_fsdp_contract(colls, sharded_paths=["fc1/w"]) == []
    found = cl.check_fsdp_contract([], sharded_paths=["fc1/w"])
    assert [f.check for f in found] == ["collective/fsdp-missing-gather"]
    assert found[0].severity == "error"
    # nothing planned sharded -> nothing to demand
    assert cl.check_fsdp_contract([], sharded_paths=[]) == []


def test_tp_decode_contract_unit():
    entry = 2 * 4 * 128 * 4 * 8 * 4
    ok = [Collective("all-reduce", 4096, 2, ("model",)),
          Collective("all-gather", 512, 2, ("model",))]  # sub-threshold
    assert cl.check_tp_decode_contract(ok, cache_entry_bytes=entry) == []
    bad = ok + [Collective("all-gather", entry, 2, ("model",))]
    found = cl.check_tp_decode_contract(bad, cache_entry_bytes=entry)
    assert [f.check for f in found] == ["collective/tp-kv-allgather"]


def test_tp_decode_program_built_and_checked():
    """A TP LM config gets its decode program compiled with the cache
    sharded on heads; on the current lowering the compiler reassembles
    the cache (full-entry all-gathers), which the contract check
    reports — the exact hazard a naive TP serve would ship."""
    cfg = dataclasses.replace(
        mnist_mlp_shapley(smoke=True), name="tp_lm", model="llama_tiny",
        loss="lm_cross_entropy", mesh={"data": 2, "model": 2},
        partition="tp")
    findings, records = cl.lint_collectives(cfg)
    names = {r.name for r in records}
    assert {"train_step", "decode", "prefill", "decode_tp"} <= names
    tp_dec = next(r for r in records if r.name == "decode_tp")
    gathers = [c for c in tp_dec.collectives
               if c.kind == "all-gather" and c.axes is not None
               and "model" in c.axes]
    has_cache_gather = any(
        c.bytes >= tp_dec.meta["cache_entry_bytes"] // 2 for c in gathers)
    flagged = any(f.check == "collective/tp-kv-allgather"
                  for f in findings)
    # the check must agree with the program it inspected — and on the
    # current XLA lowering the reassembly is real, so it fires
    assert flagged == has_cache_gather
    assert flagged, "head-sharded cache no longer reassembled — if the "\
        "decode path now streams local shards, retire this pin"


def test_replication_leak_reported():
    mesh = _mesh(("data", 4))
    rep = NamedSharding(mesh, P())
    big = jax.ShapeDtypeStruct((512, 1024), jnp.float32)  # 2 MiB
    combined = {"m": (big, rep)}
    found = cl.replication_leaks(combined, axis="data")
    assert [f.check for f in found] == ["collective/replication-leak"]
    sharded = {"m": (big, NamedSharding(mesh, P("data", None)))}
    assert cl.replication_leaks(sharded, axis="data") == []


# ---------------------------------------------------------------------------
# jaxpr half: deadlock hazards
# ---------------------------------------------------------------------------


def _cond_program(divergent: bool):
    mesh = _mesh(("data", 4))

    def inner(x):
        def yes(v):
            return jax.lax.psum(v, "data")

        def no(v):
            return jax.lax.psum(v, "data") if not divergent else v

        return jax.lax.cond(x.sum() > 0, yes, no, x)

    f = relaxed_shard_map(inner, mesh, P("data"), P("data"))
    return jax.make_jaxpr(f)(jnp.ones((4, 8), jnp.float32))


def test_branch_divergent_collectives_are_an_error():
    closed = _cond_program(divergent=True)
    found = cl.lint_collective_jaxpr(closed, {"data": 4})
    assert any(f.check == "collective/branch-divergence"
               and f.severity == "error" for f in found), found


def test_branch_agreeing_collectives_are_clean():
    closed = _cond_program(divergent=False)
    found = cl.lint_collective_jaxpr(closed, {"data": 4})
    assert not [f for f in found
                if f.check == "collective/branch-divergence"], found


def test_collective_over_unknown_axis_is_an_error():
    mesh = _mesh(("data", 4))
    f = relaxed_shard_map(lambda x: jax.lax.psum(x, "data"), mesh,
                          P("data"), P())
    closed = jax.make_jaxpr(f)(jnp.ones((4, 8), jnp.float32))
    # the CONFIG's mesh defines only "model": this program deadlocks
    found = cl.lint_collective_jaxpr(closed, {"model": 2})
    assert any(f.check == "collective/unknown-axis"
               and f.severity == "error" for f in found), found
    assert not cl.lint_collective_jaxpr(closed, {"data": 4})


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------


def test_cost_prediction_positive_and_deterministic():
    cfg = mnist_mlp_shapley(smoke=True)
    records, _ = cl.build_programs(cfg)
    preds = cm.predict_programs(records)
    assert preds and all(p.step_ms > 0 for p in preds)
    # meshless programs move zero wire bytes
    assert all(p.comm_ms == 0 for p in preds)
    again = cm.predict_programs(cl.build_programs(cfg)[0])
    assert [p.step_ms for p in again] == [p.step_ms for p in preds]


def test_zero_mesh_prediction_carries_comm_term():
    records, _ = cl.build_programs(_zero_cfg())
    train = next(r for r in records if r.name == "train_step")
    pred = cm.predict_record(train)
    assert pred.ici_bytes > 0 and pred.comm_ms > 0
    assert pred.step_ms >= pred.comm_ms


def test_cpu_cost_constants_env_override(monkeypatch):
    records, _ = cl.build_programs(mnist_mlp_shapley(smoke=True))
    base = cm.predict_record(records[0])
    monkeypatch.setenv("TORCHPRUNER_COST_CPU_FLOPS", "1e9")
    slow = cm.predict_record(records[0])
    assert slow.compute_ms == pytest.approx(
        base.compute_ms * cm.CPU_COST_DEFAULTS["flops"] / 1e9)


def test_comm_bound_config_is_flagged():
    p = cm.CostPrediction(
        program="train_step", device_kind="test", flops=1e6,
        hbm_bytes=1e6, ici_bytes=1e9, compute_ms=0.1, hbm_ms=0.2,
        ici_ms=5.0)
    assert p.bound == "ici" and p.step_ms == 5.0 and p.comm_ms == 5.0
    found = cm.cost_findings([p])
    assert [f.check for f in found] == \
        ["cost/predicted-step", "cost/comm-bound"]
    assert found[1].severity == "warning"


def test_golden_predicted_vs_measured_digits_smoke():
    """The golden predicted-vs-measured table on the digits CPU smoke
    step.  Tolerances are informational by design — the CPU constants
    are order-of-magnitude and a tiny model's measured step is mostly
    dispatch — so the pin is the BAND (prediction within 1000x of
    measurement, both finite and positive) plus determinism; the <30%
    assertion is staged for on-chip capture (scripts/capture_tpu.sh)."""
    import time

    import optax

    from torchpruner_tpu.experiments.prune_retrain import MODEL_REGISTRY
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    model = MODEL_REGISTRY["digits_fc_tiny"][0]()
    tx = optax.sgd(0.05)
    batch = 32
    pred = cm.predict_train_step(model, tx, cross_entropy_loss,
                                 batch=batch)
    assert pred is not None and pred.step_ms > 0

    trainer = Trainer.create(model, tx, cross_entropy_loss, seed=0)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 64)).astype("float32"))
    y = jnp.asarray(rng.integers(0, 10, size=(batch,)).astype("int32"))
    for _ in range(3):  # compile + warm
        trainer.step(x, y)
    t0 = time.perf_counter()
    n = 30
    for _ in range(n):
        trainer.step(x, y)
    jax.block_until_ready(trainer.params)
    measured_ms = 1e3 * (time.perf_counter() - t0) / n

    ratio = pred.step_ms / measured_ms
    rows = [("train_step", pred.step_ms, measured_ms, ratio)]
    print("\npredicted-vs-measured (digits CPU smoke):")
    for name, p_, m_, r_ in rows:
        print(f"  {name:12s} predicted {p_:8.3f} ms  "
              f"measured {m_:8.3f} ms  ratio {r_:.3f}")
    assert 1e-3 < ratio < 1e3, rows


def test_predictions_land_as_obs_gauges():
    from torchpruner_tpu import obs

    obs.shutdown()
    session = obs.configure(None)
    try:
        preds = cm.record_config_predictions(mnist_mlp_shapley(smoke=True))
        assert preds, "prediction recording returned nothing"
        snap = session.metrics.snapshot()
        assert snap.get("predicted_step_ms", 0) > 0, snap
        assert "predicted_comm_ms" in snap, snap
    finally:
        obs.shutdown()


def test_prediction_drift_scalar_in_reports():
    from torchpruner_tpu.obs.report import _scalars_of

    rep = {"derived": {"step_time_p50_s": 0.002},
           "metrics": {"predicted_step_ms": 1.0}}
    sc = _scalars_of(rep)
    assert sc["predicted_vs_measured_step_pct"] == pytest.approx(-50.0)
    rep = {"metrics": {"predicted_step_ms_decode": 3.0,
                       "serve_token_seconds_p50": 0.002}}
    sc = _scalars_of(rep)
    assert sc["predicted_vs_measured_decode_pct"] == pytest.approx(50.0)


# ---------------------------------------------------------------------------
# runner satellites (pass 2 surfacing)
# ---------------------------------------------------------------------------


def test_fraction_stand_in_surfaced_as_info():
    cfg = _zero_cfg()  # policy "negative"
    assert cfg.policy != "fraction"
    report = lint_config(cfg, jaxpr=False, collectives=False, cost=False)
    checks = [f.check for f in report.findings]
    assert "sharding/fraction-stand-in" in checks, checks
    frac = dataclasses.replace(cfg, policy="fraction")
    report = lint_config(frac, jaxpr=False, collectives=False, cost=False)
    assert "sharding/fraction-stand-in" not in \
        [f.check for f in report.findings]


def test_explicit_plans_linted_under_config_mesh():
    """Explicit plans no longer skip the sharding pass: the plan is
    matched back to its graph group and simulated under the config
    mesh (the hbm-delta info row proves the pass ran)."""
    from torchpruner_tpu.core.graph import group_for
    from torchpruner_tpu.core.pruner import plan_for_group
    from torchpruner_tpu.experiments.prune_retrain import MODEL_REGISTRY

    model = MODEL_REGISTRY["digits_fc_tiny"][0]()
    plan = plan_for_group(model, group_for(model, "fc1"))
    cfg = dataclasses.replace(mnist_mlp_shapley(smoke=True),
                              mesh={"data": 4, "model": 2})
    report = lint_config(cfg, model=model, plans=[plan], jaxpr=False,
                         collectives=False, cost=False)
    checks = [f.check for f in report.findings]
    assert "sharding/hbm-delta" in checks, checks

"""Shape-aware checkpoint/restore, including post-prune widths
(SURVEY.md §5.4: layer widths are the extra metadata pruning forces)."""

import os

import jax
import numpy as np
import optax

from torchpruner_tpu.checkpoint import (
    restore_checkpoint,
    save_checkpoint,
    spec_from_dict,
    spec_to_dict,
)
from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.models import fmnist_convnet, vgg16_bn
from torchpruner_tpu.utils.losses import cross_entropy_loss


def test_spec_roundtrip():
    for model in [fmnist_convnet(), vgg16_bn(), fmnist_convnet(linearize=True)]:
        d = spec_to_dict(model)
        m2 = spec_from_dict(d)
        assert m2 == model


def test_checkpoint_roundtrip_after_prune(tmp_path):
    model = fmnist_convnet()
    params, state = init_model(model, seed=0)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 28, 28, 1))
    y = np.zeros((4,), dtype=np.int32)
    g = jax.grad(
        lambda p: float(0)
        + cross_entropy_loss(model.apply(p, x, state=state)[0], y).mean()
    )(params)
    _, opt_state = tx.update(g, opt_state, params)

    res = prune(model, params, "conv1", [0, 1, 2, 3], state=state,
                opt_state=opt_state)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, res.model, res.params, res.state, res.opt_state,
                    step=7, prune_history=[{"layer": "conv1", "dropped": 4}])

    m2, p2, s2, o2, meta = restore_checkpoint(path, tx=tx)
    assert m2 == res.model
    assert meta["widths"]["conv1"] == 28
    assert meta["step"] == 7
    np.testing.assert_array_equal(
        np.asarray(p2["conv1"]["w"]), np.asarray(res.params["conv1"]["w"])
    )
    np.testing.assert_array_equal(
        np.asarray(s2["bn1"]["mean"]), np.asarray(res.state["bn1"]["mean"])
    )
    # restored optimizer state continues training at the pruned shapes
    out, _ = m2.apply(p2, x, state=s2)
    assert out.shape == (4, 10)
    g2 = jax.grad(
        lambda p: cross_entropy_loss(m2.apply(p, x, state=s2)[0], y).mean()
    )(p2)
    up, _ = tx.update(g2, o2, p2)
    p3 = optax.apply_updates(p2, up)
    assert jax.tree_util.tree_structure(p3) == jax.tree_util.tree_structure(p2)


def test_checkpoint_refuses_cross_optimizer_restore(tmp_path):
    """sgd(momentum) and rmsprop flatten to identical leaf counts AND
    shapes (one per-param slot each) — only the recorded treedef tells
    them apart.  Restoring under the wrong optimizer must raise, not
    silently wire momentum buffers into rms accumulators."""
    import pytest

    from torchpruner_tpu.models.mlp import fc_net

    model = fc_net(8, hidden=(8,), n_classes=3)
    params, state = init_model(model, seed=0)
    tx_save = optax.sgd(0.1, momentum=0.9)
    opt_state = tx_save.init(params)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, model, params, state, opt_state)

    # same optimizer: restores fine
    _, _, _, o2, _ = restore_checkpoint(path, tx=optax.sgd(0.1, momentum=0.9))
    assert o2 is not None

    with pytest.raises(ValueError, match="structure mismatch"):
        restore_checkpoint(path, tx=optax.rmsprop(0.1))


def test_quantized_params_checkpoint_roundtrip(tmp_path):
    """A quantized serving tree (QTensor leaves, int4 + int8) survives
    save/restore: payloads and scales as arrays, static quantization
    metadata via spec.json — restored decode equals the original."""
    import jax.numpy as jnp

    from torchpruner_tpu.checkpoint import restore_checkpoint, save_checkpoint
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.generate import generate
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.ops.quant import QTensor, quantize_params

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    qp = quantize_params(model, params, bits=4)
    qp["lm_head"] = {"w": quantize_params(
        model, params)["lm_head"]["w"]}  # mix int8 in too

    path = str(tmp_path / "ckpt")
    save_checkpoint(path, model, qp, step=7)
    model2, qp2, _, _, meta = restore_checkpoint(path)
    assert meta["step"] == 7 and meta["quantized"]

    leaf = qp2["block1_ffn"]["gate"]["wg"]
    assert isinstance(leaf, QTensor) and leaf.bits == 4
    assert isinstance(qp2["lm_head"]["w"], QTensor)
    assert qp2["lm_head"]["w"].bits == 8

    prompt = jnp.zeros((2, 4), jnp.int32)
    want = generate(model, qp, prompt, 4)
    got = generate(model2, qp2, prompt, 4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # and the original (unquantized-tree) path still round-trips with no
    # "quantized" key in the metadata
    save_checkpoint(str(tmp_path / "plain"), model, params)
    _, p2, _, _, meta2 = restore_checkpoint(str(tmp_path / "plain"))
    assert "quantized" not in meta2


def test_corrupted_checkpoint_raises_descriptive_error(tmp_path):
    """Digest seal (resilience satellite): flipped bytes in the array
    tree surface as CheckpointCorruptError naming the digest mismatch —
    not a pickle/msgpack traceback from deep inside orbax."""
    import pytest

    from torchpruner_tpu.checkpoint import CheckpointCorruptError
    from torchpruner_tpu.models.mlp import fc_net
    from torchpruner_tpu.resilience.chaos import corrupt_checkpoint_bytes

    model = fc_net(8, hidden=(8,), n_classes=3)
    params, state = init_model(model, seed=0)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, model, params, state)
    restore_checkpoint(path)  # intact: verifies clean

    assert corrupt_checkpoint_bytes(path, force=True)
    with pytest.raises(CheckpointCorruptError, match="digest"):
        restore_checkpoint(path)


def test_truncated_spec_raises_descriptive_error(tmp_path):
    import pytest

    from torchpruner_tpu.checkpoint import CheckpointCorruptError
    from torchpruner_tpu.models.mlp import fc_net

    model = fc_net(8, hidden=(8,), n_classes=3)
    params, state = init_model(model, seed=0)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, model, params, state)

    spec = os.path.join(path, "spec.json")
    with open(spec, "r+b") as f:
        f.truncate(os.path.getsize(spec) // 2)
    with pytest.raises(CheckpointCorruptError, match="unreadable|truncated"):
        restore_checkpoint(path)
    # a missing checkpoint is corrupt-by-definition too, same error class
    with pytest.raises(CheckpointCorruptError, match="no spec.json"):
        restore_checkpoint(str(tmp_path / "nope"))


def test_atomic_save_preserves_previous_on_overwrite(tmp_path):
    """Overwriting a checkpoint leaves no tmp litter and the final state
    restores cleanly (the swap path: old arrays displaced, new renamed
    in, spec.json replaced last)."""
    from torchpruner_tpu.models.mlp import fc_net

    model = fc_net(8, hidden=(8,), n_classes=3)
    params, state = init_model(model, seed=0)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, model, params, state, step=1)
    save_checkpoint(path, model, params, state, step=2)
    _, _, _, _, meta = restore_checkpoint(path)
    assert meta["step"] == 2
    litter = [e for e in os.listdir(path) if e.startswith(".arrays.")
              or e.endswith(".tmp")]
    assert litter == []


def test_qtensor_sharded_checkpoint_roundtrip_and_corruption(tmp_path):
    """Resilience satellite: a quantized tree whose q/scale leaves live
    SHARDED over an 8-virtual-device mesh round-trips through
    save/restore (pack → orbax → unpack), and corrupted bytes raise
    CheckpointCorruptError instead of deserializing garbage."""
    import jax.numpy as jnp
    import pytest
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchpruner_tpu.checkpoint import CheckpointCorruptError
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.generate import generate
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.ops.quant import QTensor, quantize_params
    from torchpruner_tpu.parallel import make_mesh
    from torchpruner_tpu.resilience.chaos import corrupt_checkpoint_bytes

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    qp = quantize_params(model, params, bits=4)

    mesh = make_mesh({"data": 8})
    rep = NamedSharding(mesh, P())

    def place(t):
        if isinstance(t, QTensor):
            # shard the packed payload's first axis where it divides the
            # mesh; replicate the rest — mixed placements in one tree
            spec = P("data") if t.q.shape[0] % 8 == 0 else P()
            return QTensor(
                jax.device_put(t.q, NamedSharding(mesh, spec)),
                jax.device_put(t.scale, rep), t.in_axes, t.bits,
                t.pack_axis,
            )
        return jax.device_put(t, rep)

    qp_sharded = jax.tree_util.tree_map(
        place, qp, is_leaf=lambda x: isinstance(x, QTensor))
    assert any(
        len(leaf.q.sharding.device_set) == 8
        for leaf in jax.tree_util.tree_leaves(
            qp_sharded, is_leaf=lambda x: isinstance(x, QTensor))
        if isinstance(leaf, QTensor)
    ), "no leaf actually sharded — test setup degenerate"

    path = str(tmp_path / "ckpt")
    save_checkpoint(path, model, qp_sharded, step=1)
    model2, qp2, _, _, meta = restore_checkpoint(path)
    assert meta["quantized"]

    leaf = qp2["block1_ffn"]["gate"]["wg"]
    assert isinstance(leaf, QTensor) and leaf.bits == 4
    prompt = jnp.zeros((2, 4), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(generate(model2, qp2, prompt, 4)),
        np.asarray(generate(model, qp, prompt, 4)),
    )

    assert corrupt_checkpoint_bytes(path, force=True)
    with pytest.raises(CheckpointCorruptError):
        restore_checkpoint(path)


def test_interrupted_resave_recovers_displaced_old_tree(tmp_path):
    """A kill inside the re-save swap window (old arrays renamed away,
    new not yet committed) must still restore: the displaced tree at
    .arrays.old.* matches the sealed digest and is swapped back."""
    from torchpruner_tpu.models.mlp import fc_net

    model = fc_net(8, hidden=(8,), n_classes=3)
    params, state = init_model(model, seed=0)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, model, params, state, step=1)

    # simulate the crash window: arrays displaced, spec.json still the
    # step-1 commit (its digest seals the displaced tree)
    os.rename(os.path.join(path, "arrays"),
              os.path.join(path, ".arrays.old.99999"))
    _, _, _, _, meta = restore_checkpoint(path)
    assert meta["step"] == 1
    assert os.path.isdir(os.path.join(path, "arrays"))
    # and a subsequent save sweeps any remaining litter
    save_checkpoint(path, model, params, state, step=2)
    assert [e for e in os.listdir(path)
            if e.startswith((".arrays.old.", ".arrays.tmp."))] == []
    _, _, _, _, meta = restore_checkpoint(path)
    assert meta["step"] == 2

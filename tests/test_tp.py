"""Tensor-parallel sharding tests (8-device CPU mesh): pruning-graph-derived
column/row-parallel assignments, TP vs FSDP numerical agreement of the full
train step, and prune→reshard→recompile under TP."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.models import llama_tiny, vit_tiny
from torchpruner_tpu.parallel import ShardedTrainer, make_mesh, tp_specs
from torchpruner_tpu.utils.losses import cross_entropy_loss, lm_cross_entropy_loss


def test_tp_specs_from_pruning_graph():
    mesh = make_mesh({"data": 4, "model": 2})
    specs = tp_specs(llama_tiny(), mesh)
    # FFN: GatedDense column-parallel, down-projection row-parallel
    assert specs[("block1_ffn/gate", "wg")] == P(None, "model")
    assert specs[("block1_ffn/gate", "wu")] == P(None, "model")
    assert specs[("block1_ffn/down", "w")] == P("model", None)
    # attention: heads column-parallel (4 Q / 2 KV heads, both divide 2)
    assert specs[("block1_attn/attn", "wq")] == P(None, "model", None)
    assert specs[("block1_attn/attn", "wk")] == P(None, "model", None)
    assert specs[("block1_attn/attn", "wo")] == P("model", None, None)
    # lm_head is the (included) output group: column-parallel, no consumer
    assert specs[("lm_head", "w")] == P(None, "model")


def test_tp_specs_skip_indivisible_kv_heads():
    mesh = make_mesh({"data": 2, "model": 4})
    # 4 query heads divide 4; 2 KV heads do not -> KV replicated
    specs = tp_specs(llama_tiny(), mesh)
    assert specs[("block1_attn/attn", "wq")] == P(None, "model", None)
    assert ("block1_attn/attn", "wk") not in specs


def test_tp_placement_is_applied():
    """The placed arrays really carry the TP specs (placement regressions
    are invisible to numeric tests — GSPMD keeps any placement correct)."""
    mesh = make_mesh({"data": 2, "model": 4})
    t = ShardedTrainer.create(
        llama_tiny(), optax.sgd(1e-2), lm_cross_entropy_loss, mesh,
        seed=0, min_shard_size=0, partition="tp",
    )
    assert t.params["block1_ffn"]["gate"]["wg"].sharding.spec == P(None, "model")
    assert t.params["block1_ffn"]["down"]["w"].sharding.spec == P("model", None)
    assert t.params["block1_attn"]["attn"]["wq"].sharding.spec == P(
        None, "model", None
    )


def test_unknown_partition_raises():
    import pytest

    mesh = make_mesh({"data": 2, "model": 4})
    with pytest.raises(ValueError, match="partition"):
        ShardedTrainer.create(
            llama_tiny(), optax.sgd(1e-2), lm_cross_entropy_loss, mesh,
            seed=0, partition="tensor",
        )


def test_tp_step_matches_fsdp_step():
    """The same train step under TP and FSDP placements must produce the
    same loss trajectory — placement is not semantics."""
    mesh = make_mesh({"data": 2, "model": 4})
    x = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 256), np.int32
    )

    def run(partition):
        t = ShardedTrainer.create(
            llama_tiny(), optax.adam(1e-3), lm_cross_entropy_loss, mesh,
            seed=0, min_shard_size=0, partition=partition,
        )
        return [float(t.step(x, x)) for _ in range(3)]

    np.testing.assert_allclose(run("tp"), run("fsdp"), rtol=2e-4)


def test_tp_prune_rebuild_step():
    """Prune FFN channels and attention heads, rebuild under TP, step again
    — the resharding falls back cleanly where new widths stop dividing."""
    mesh = make_mesh({"data": 2, "model": 4})
    t = ShardedTrainer.create(
        llama_tiny(), optax.sgd(1e-2, momentum=0.9), lm_cross_entropy_loss,
        mesh, seed=0, min_shard_size=0, partition="tp",
    )
    x = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 256), np.int32
    )
    l0 = t.step(x, x)
    r = prune(t.model, t.params, "block1_ffn/gate", [0, 5, 9, 13],
              state=t.state, opt_state=t.opt_state)
    r = prune(r.model, r.params, "block2_attn/attn", [3],
              state=r.state, opt_state=r.opt_state)
    t = t.rebuild(r.model, r.params, r.state, r.opt_state)
    l1 = t.step(x, x)
    assert np.isfinite(float(l0)) and np.isfinite(float(l1))
    assert t.model.layer("block1_ffn/gate").features == 60
    assert t.model.layer("block2_attn/attn").num_heads == 3


def test_tp_on_vision_model_conv_chain():
    """ViT: patchify conv feeds PosEmbed (unit identity lost) so it stays
    FSDP; block MLPs get column/row TP pairs."""
    mesh = make_mesh({"data": 4, "model": 2})
    specs = tp_specs(vit_tiny(), mesh)
    assert ("patchify", "w") not in specs
    assert specs[("block1_mlp/fc1", "w")] == P(None, "model")
    assert specs[("block1_mlp/fc2", "w")] == P("model", None)


def test_attribution_scoring_with_tp_sharded_params():
    """Models too large for one chip score with TP-sharded parameters
    unchanged: the metrics' jitted row computations partition via GSPMD
    (same scores as unsharded) — compose with DistributedScorer's data
    sharding for the full 8B-scale scoring story."""
    from torchpruner_tpu.attributions import (
        ShapleyAttributionMetric,
        TaylorAttributionMetric,
    )
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.models import llama_tiny
    from torchpruner_tpu.parallel.sharding import tp_sharding
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    model = llama_tiny()
    params, state = init_model(model, seed=0)
    toks = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 256),
        np.int32,
    )
    batches = [(toks, toks)]
    mesh = make_mesh({"model": 4}, devices=jax.devices()[:4])
    params_tp = jax.device_put(
        params, tp_sharding(model, params, mesh, "model", 0)
    )
    for cls, kw in ((TaylorAttributionMetric, {}),
                    (ShapleyAttributionMetric, {"sv_samples": 2,
                                                "seed": 0})):
        want = cls(model, params, batches, lm_cross_entropy_loss,
                   state=state, **kw).run("block1_ffn/gate")
        got = cls(model, params_tp, batches, lm_cross_entropy_loss,
                  state=state, **kw).run("block1_ffn/gate")
        np.testing.assert_allclose(got, want, atol=1e-4)

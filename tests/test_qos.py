"""Isolation tests for the multi-tenant QoS plane, the scenario
workload library and the autoscaling supervisor's decision core
(PR 19).  Everything here is host-side and clock-injected — no model,
no subprocesses — so the properties the fleet chaos drill asserts
end-to-end (strict step-boundary preemption, quota sheds, no-flap
hysteresis, digest-pinned replay) are each pinned in isolation first.
"""

import json

import numpy as np
import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.fleet.supervisor import RUNGS, ScalePolicy, Supervisor
from torchpruner_tpu.fleet.workload import (
    build_schedule,
    schedule_digest,
    validate_scenario,
    verify_schedule,
)
from torchpruner_tpu.serve.allocator import KVCacheAllocator
from torchpruner_tpu.serve.qos import (
    BATCH,
    INTERACTIVE,
    QoS,
    TenantPolicy,
    TokenBucket,
)
from torchpruner_tpu.serve.request import ACTIVE, QUEUED, SHED, Request
from torchpruner_tpu.serve.scheduler import Scheduler


def _req(tenant=None, prompt_len=8, max_new=8, rid=None):
    ids = np.arange(prompt_len, dtype=np.int32) % 7
    r = Request(prompt_ids=ids, max_new=max_new, tenant=tenant)
    if rid is not None:
        r.id = rid
    return r


# -- token bucket ------------------------------------------------------------

def test_token_bucket_burst_then_throttle():
    """A fresh bucket holds ``burst`` tokens; the burst+1'th take at the
    same instant is throttled."""
    b = TokenBucket(rate=2.0, burst=4.0, now=0.0)
    assert [b.take(now=0.0) for _ in range(5)] == [True] * 4 + [False]
    # one token costs 1/rate seconds from empty
    assert b.retry_after_s(now=0.0) == pytest.approx(0.5)


def test_token_bucket_refill_math():
    b = TokenBucket(rate=2.0, burst=4.0, now=0.0)
    for _ in range(4):
        assert b.take(now=0.0)
    # 1 s at 2 tokens/s refills exactly 2 tokens — and never beyond
    assert b.take(now=1.0) and b.take(now=1.0) and not b.take(now=1.0)
    assert b.level == pytest.approx(0.0)
    b2 = TokenBucket(rate=2.0, burst=4.0, now=0.0)
    b2.take(now=100.0)  # a long idle period can't overfill the bucket
    assert b2.level == pytest.approx(3.0)


def test_token_bucket_zero_rate_unlimited():
    b = TokenBucket(rate=0.0, burst=1.0, now=0.0)
    assert all(b.take(now=0.0) for _ in range(100))
    assert b.retry_after_s(now=0.0) == 0.0


# -- tenant policy parsing ---------------------------------------------------

def test_tenant_policy_from_dict():
    p = TenantPolicy.from_dict("bulk", {"priority": "batch", "rate": 5,
                                        "burst": 10, "page_quota": 8})
    assert p.priority == BATCH
    assert p.preemptible  # batch defaults preemptible
    q = TenantPolicy.from_dict("chat", {"priority": "interactive"})
    assert q.priority == INTERACTIVE and not q.preemptible
    # explicit preemptible overrides the class default
    r = TenantPolicy.from_dict("bulk", {"priority": "batch",
                                        "preemptible": False})
    assert not r.preemptible


def test_tenant_policy_rejects_junk():
    with pytest.raises(ValueError, match="unknown tenant policy key"):
        TenantPolicy.from_dict("chat", {"prio": 0})
    with pytest.raises(ValueError, match="unknown priority class"):
        TenantPolicy.from_dict("chat", {"priority": "platinum"})
    with pytest.raises(ValueError, match="must match"):
        TenantPolicy.from_dict("Bad-Name", {})


# -- scheduler: priority admission + preemption ------------------------------

def _qos():
    return QoS.from_dict({
        "chat": {"priority": "interactive"},
        "bulk": {"priority": "batch"},
    }, now=0.0)


def test_priority_class_admission_order():
    """With both classes queued, interactive is admitted first even
    though batch was submitted first."""
    alloc = KVCacheAllocator(n_slots=1, max_len=64, page_len=16)
    sched = Scheduler(alloc, qos=_qos())
    bulk = sched.submit(_req("bulk"))
    chat = sched.submit(_req("chat"))
    admitted = sched.admit()
    assert admitted == [chat]
    assert chat.state == ACTIVE and bulk.state == QUEUED


def test_preemption_youngest_lower_class_victim():
    """An interactive head blocked on capacity evicts the YOUNGEST
    active batch request — slot + pages released, progress fully reset,
    victim re-queued at the FRONT of its class."""
    alloc = KVCacheAllocator(n_slots=2, max_len=64, page_len=16)
    sched = Scheduler(alloc, qos=_qos())
    b1, b2 = sched.submit(_req("bulk")), sched.submit(_req("bulk"))
    assert sched.admit() == [b1, b2]
    b1.admitted_s, b2.admitted_s = 1.0, 2.0  # pin admission order
    b2.tokens.extend([3, 4])                 # simulate decode progress
    chat = sched.submit(_req("chat"))
    admitted = sched.admit()
    assert admitted == [chat] and chat.state == ACTIVE
    # the younger batch request was the victim; the older kept its slot
    assert b2.state == QUEUED and b2.slot is None
    assert b1.state == ACTIVE and b1.slot is not None
    assert b2.preemptions == 1 and sched.preempted_total == 1
    assert b2.tokens == [] and b2.first_token_s is None
    assert sched._queues[BATCH][0] is b2  # front of its class queue
    # capacity restored -> the victim re-admits and restarts cleanly
    chat.state = ACTIVE  # still holding its slot
    sched.evict(b1)
    assert sched.admit() == [b2] and b2.state == ACTIVE


def test_preempt_guard_vetoes_mid_prefill_slots():
    """The engine's guard (slot mid-chunked-prefill) vetoes preemption:
    admission waits rather than perturbing the compiled step."""
    alloc = KVCacheAllocator(n_slots=1, max_len=64, page_len=16)
    sched = Scheduler(alloc, qos=_qos())
    bulk = sched.submit(_req("bulk"))
    assert sched.admit() == [bulk]
    sched.preempt_guard = lambda slot: False
    chat = sched.submit(_req("chat"))
    assert sched.admit() == []
    assert chat.state == QUEUED and bulk.state == ACTIVE
    assert sched.preempted_total == 0
    sched.preempt_guard = None  # boundary reached: now it may evict
    assert sched.admit() == [chat] and bulk.state == QUEUED


def test_interactive_never_preempted_by_batch():
    """Preemption is strictly one-way: a batch head never evicts an
    active interactive request (equal/higher classes are immune)."""
    alloc = KVCacheAllocator(n_slots=1, max_len=64, page_len=16)
    sched = Scheduler(alloc, qos=_qos())
    chat = sched.submit(_req("chat"))
    assert sched.admit() == [chat]
    bulk = sched.submit(_req("bulk"))
    assert sched.admit() == []
    assert chat.state == ACTIVE and bulk.state == QUEUED
    chat2 = sched.submit(_req("chat"))  # same class: also immune
    assert sched.admit() == []
    assert chat.state == ACTIVE and chat2.state == QUEUED


def test_page_quota_shed(tmp_path):
    """A head whose footprint would push its tenant past page_quota is
    SHED with the quota reason (not left blocking the queue); other
    tenants are untouched."""
    obs.configure(str(tmp_path / "obs"))
    try:
        qos = QoS.from_dict({
            "chat": {"priority": "interactive"},
            "bulk": {"priority": "batch", "page_quota": 4},
        }, now=0.0)
        alloc = KVCacheAllocator(n_slots=4, max_len=64, page_len=16)
        sched = Scheduler(alloc, qos=qos)
        b1 = sched.submit(_req("bulk", prompt_len=32, max_new=32))  # 4 pg
        b2 = sched.submit(_req("bulk", prompt_len=32, max_new=32))  # over
        chat = sched.submit(_req("chat", prompt_len=32, max_new=32))
        admitted = sched.admit()
        assert admitted == [chat, b1]  # interactive class served first
        assert b2.state == SHED
        assert alloc.tenant_pages("bulk") == 4
        assert obs.counter_value("serve_rejected_quota_total") == 1
        assert obs.counter_value("tenant_bulk_shed_total") == 1
        assert obs.counter_value("tenant_bulk_shed_quota_total") == 1
        # release frees quota: the tenant can admit again afterwards
        sched.evict(b1)
        b3 = sched.submit(_req("bulk", prompt_len=32, max_new=32))
        assert sched.admit() == [b3]
    finally:
        obs.shutdown()


def test_token_bucket_throttle_shed(tmp_path):
    """Submissions over a tenant's token bucket are shed at submit time
    with the throttle reason; an untenanted request never throttles."""
    obs.configure(str(tmp_path / "obs"))
    try:
        qos = QoS.from_dict(
            {"bulk": {"priority": "batch", "rate": 1.0, "burst": 2}},
            now=0.0)
        alloc = KVCacheAllocator(n_slots=2, max_len=64, page_len=16)
        sched = Scheduler(alloc, qos=qos)
        outcomes = [sched.submit(_req("bulk")).state for _ in range(3)]
        assert outcomes == [QUEUED, QUEUED, SHED]
        assert sched.submit(_req(None)).state == QUEUED
        assert obs.counter_value("serve_rejected_throttle_total") == 1
        assert obs.counter_value("tenant_bulk_shed_throttle_total") == 1
    finally:
        obs.shutdown()


# -- supervisor hysteresis ---------------------------------------------------

def _sig(age=0.0, pending=0, replicas=1, breach=0.0, retiring=0,
         rung="none"):
    return {"queue_age_s": age, "pending": pending, "replicas": replicas,
            "live": replicas, "breach_frac": breach, "retiring": retiring,
            "rung": rung}


def _sup(**kw):
    knobs = dict(min_replicas=1, max_replicas=2, queue_age_up_s=1.0,
                 queue_age_down_s=0.1, up_ticks=3, down_ticks=4,
                 cooldown_s=10.0, degrade_ticks=4)
    knobs.update(kw)
    pol = ScalePolicy(**knobs)
    t = {"now": 0.0}
    sup = Supervisor(router=None, policy=pol, now=lambda: t["now"])
    return sup, t


def test_supervisor_flapping_signal_never_acts():
    """Alternating hot/quiet samples reset the consecutive-tick
    counters: a noisy signal yields NO action, ever."""
    sup, t = _sup()
    for i in range(40):
        t["now"] = float(i)
        sig = _sig(age=5.0) if i % 2 else _sig(age=0.0, pending=3)
        assert sup.evaluate(sig, now=t["now"]) is None


def test_supervisor_scale_up_after_consecutive_ticks_and_cooldown():
    sup, t = _sup()
    assert sup.evaluate(_sig(age=5.0), now=0.0) is None
    assert sup.evaluate(_sig(age=5.0), now=1.0) is None
    assert sup.evaluate(_sig(age=5.0), now=2.0) == "scale_up"
    # tick() would reset + stamp; emulate the actuation bookkeeping
    sup._last_action_t, sup._up = 2.0, 0
    # still hot, but inside the cooldown window: no second decision
    for now in (3.0, 5.0, 8.0, 11.0):
        assert sup.evaluate(_sig(age=5.0, replicas=2), now=now) is None


def test_supervisor_breach_fraction_also_scales_up():
    sup, _ = _sup()
    for now in (0.0, 1.0):
        assert sup.evaluate(_sig(breach=0.6), now=now) is None
    assert sup.evaluate(_sig(breach=0.6), now=2.0) == "scale_up"


def test_supervisor_degrade_only_at_max_replicas():
    """At max_replicas a sustained hot signal climbs the ladder instead
    of scaling; retiring replicas don't count toward capacity."""
    sup, _ = _sup()
    at_max = _sig(age=5.0, replicas=2)
    assert sup.evaluate(at_max, now=0.0) is None
    assert sup.evaluate(at_max, now=1.0) is None
    # up_ticks (3) satisfied but degrade_ticks (4) also needed at max
    assert sup.evaluate(at_max, now=2.0) is None
    assert sup.evaluate(at_max, now=3.0) == "degrade"
    # a retiring replica means NOT at max -> scale_up instead
    sup2, _ = _sup()
    not_max = _sig(age=5.0, replicas=2, retiring=1)
    for i in range(2):
        assert sup2.evaluate(not_max, now=float(i)) is None
    assert sup2.evaluate(not_max, now=2.0) == "scale_up"


def test_supervisor_recover_precedes_scale_down():
    """A quiet fleet first unwinds the degradation ladder, then (rung
    0, above min_replicas) releases a replica; at min it holds."""
    sup, _ = _sup()
    quiet = _sig(age=0.0, pending=0, replicas=2)
    sup.rung = 1
    for i in range(3):
        assert sup.evaluate(quiet, now=float(i)) is None
    assert sup.evaluate(quiet, now=3.0) == "recover"
    sup.rung = 0
    assert sup.evaluate(quiet, now=4.0) == "scale_down"  # counter held
    assert sup.evaluate(_sig(age=0.0, replicas=1), now=5.0) is None
    # pending work blocks the quiet path even with a young queue head
    sup2, _ = _sup()
    for i in range(20):
        assert sup2.evaluate(_sig(age=0.0, pending=1, replicas=2),
                             now=float(i)) is None


def test_supervisor_ladder_rungs_are_ordered():
    assert RUNGS == ("none", "shed_batch", "tighten_admission",
                     "pruned_swap")


# -- workload scenarios ------------------------------------------------------

def _spec(**over):
    spec = {
        "version": 1,
        "name": "unit",
        "seed": 7,
        "vocab": 64,
        "tenants": {
            "chat": {"priority": "interactive"},
            "bulk": {"priority": "batch", "rate": 4.0, "burst": 8,
                     "page_quota": 8},
        },
        "classes": {
            "short": {"tenant": "chat", "prompt_lens": [4, 6, 12],
                      "max_new": [4, 8], "sessions": 3},
            "long": {"tenant": "bulk", "prompt_lens": [24],
                     "max_new": [16]},
        },
        "phases": [
            {"name": "warm", "duration_s": 2.0, "rate": 3.0,
             "mix": {"short": 0.7, "long": 0.3}},
            {"name": "crowd", "duration_s": 1.0, "rate": [6.0, 30.0],
             "mix": {"short": 1.0}},
        ],
        "retry": {"max_attempts": 3, "base_delay_s": 0.01,
                  "max_delay_s": 0.1, "hedge_after_s": 0.5},
    }
    spec.update(over)
    return spec


def test_build_schedule_deterministic_and_digest_stable():
    s1, s2 = build_schedule(_spec()), build_schedule(_spec())
    assert [(r.t, r.cls, r.payload) for r in s1] \
        == [(r.t, r.cls, r.payload) for r in s2]
    assert schedule_digest(s1) == schedule_digest(s2)
    assert schedule_digest(build_schedule(_spec(seed=8))) \
        != schedule_digest(s1)
    # arrivals are sorted and stay inside the total scenario span
    ts = [r.t for r in s1]
    assert ts == sorted(ts) and 0.0 < ts[-1] < 3.0
    # payloads carry the class's tenant and round-robin session ids
    shorts = [r for r in s1 if r.cls == "short"]
    assert all(r.payload["tenant"] == "chat" for r in shorts)
    assert {r.payload["session_id"] for r in shorts} \
        <= {"short-s0", "short-s1", "short-s2"}
    longs = [r for r in s1 if r.cls == "long"]
    assert all(len(r.payload["prompt_ids"]) == 24 for r in longs)
    # seeds are unique per arrival (spec seed + planned index)
    seeds = [r.payload["seed"] for r in s1]
    assert len(set(seeds)) == len(seeds)


def test_verify_schedule_digest_mismatch_raises():
    spec = _spec()
    sched = build_schedule(spec)
    digest = verify_schedule(spec, sched)  # no committed digest: passes
    spec["digest"] = digest
    assert verify_schedule(spec, sched) == digest
    spec["digest"] = "0" * 64
    with pytest.raises(ValueError, match="digest"):
        verify_schedule(spec, sched)


def test_validate_scenario_rejects_junk():
    with pytest.raises(ValueError, match="unknown scenario key"):
        validate_scenario(_spec(extra=1))
    with pytest.raises(ValueError, match="unknown key"):
        validate_scenario(_spec(classes={
            "short": {"prompt_lens": [4], "max_new": [4], "burst": 2}}))
    with pytest.raises(ValueError, match="unknown tenant"):
        spec = _spec()
        spec["classes"]["short"]["tenant"] = "ghost"
        validate_scenario(spec)
    with pytest.raises(ValueError, match="unknown class"):
        spec = _spec()
        spec["phases"][0]["mix"] = {"ghost": 1.0}
        validate_scenario(spec)
    with pytest.raises(ValueError, match="version"):
        validate_scenario(_spec(version=2))


def test_committed_scenarios_replay_bit_equal():
    """Every committed scenario's schedule must rebuild to its pinned
    digest — the cross-PR apples-to-apples guarantee."""
    import glob
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(here, "results", "scenarios",
                                          "*.json")))
    assert paths, "no committed scenarios found"
    from torchpruner_tpu.fleet.workload import load_scenario
    for path in paths:
        spec = load_scenario(path)
        assert spec.get("digest"), f"{path}: digest not committed"
        verify_schedule(spec, build_schedule(spec))


# -- plane queue age (the scale-up signal) -----------------------------------

def test_plane_oldest_pending_age(tmp_path):
    from torchpruner_tpu.fleet.plane import RequestPlane
    plane = RequestPlane(str(tmp_path / "journal.jsonl"))
    assert plane.oldest_pending_age_s() == 0.0
    rec = plane.accept({"prompt_ids": [1, 2], "max_new": 2},
                       deadline_s=60.0)
    age = plane.oldest_pending_age_s()
    assert 0.0 <= age < 5.0
    # dispatching the only pending record zeroes the signal
    got = plane.checkout()
    assert got is not None and got.rid == rec.rid
    assert plane.oldest_pending_age_s() == 0.0


# -- open-loop selector (shared by serve --synthetic / bench / replay) -------

def test_open_loop_selector_modes():
    from torchpruner_tpu.serve.traffic import open_loop, synthetic_requests
    reqs = synthetic_requests(4, vocab=64, prompt_lens=[4], max_new=[4])
    det = open_loop(reqs, rate=0.0, stagger_steps=2)
    assert det.by_step
    assert [t for t, _ in det._pending] == [0.0, 2.0, 4.0, 6.0]
    poisson = open_loop(reqs, rate=100.0, seed=3)
    assert not poisson.by_step
    arrivals = [t for t, _ in poisson._pending]
    assert arrivals == sorted(arrivals)
    # wall-clock schedules are seeded-deterministic too
    again = open_loop(reqs, rate=100.0, seed=3)
    assert arrivals == [t for t, _ in again._pending]

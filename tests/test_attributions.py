"""Attribution metric tests on the analytic ``max_model`` fixture.

Ground-truth values are hand-derivable from the fixture's weights (see
torchpruner_tpu/models/analytic.py); they match the reference's expected
values (reference tests/test_attributions.py:93-175) because the math is
framework-independent.  Shapley is asserted statistically (sv_samples=1000),
as in the reference (:128-137).
"""

import numpy as np
import pytest

from torchpruner_tpu.attributions import (
    APoZAttributionMetric,
    RandomAttributionMetric,
    SensitivityAttributionMetric,
    ShapleyAttributionMetric,
    TaylorAttributionMetric,
    WeightNormAttributionMetric,
)
from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.models.analytic import max_model, max_model_batches
from torchpruner_tpu.utils.losses import mse_loss
from torchpruner_tpu.utils.reductions import mean_plus_2std

ALL_METRICS = [
    RandomAttributionMetric,
    WeightNormAttributionMetric,
    APoZAttributionMetric,
    SensitivityAttributionMetric,
    TaylorAttributionMetric,
    ShapleyAttributionMetric,
]


def make(metric_cls, version=1, **kw):
    model, params, _, _ = max_model(version)
    data = max_model_batches(batch_size=1)
    return metric_cls(model, params, data, mse_loss, **kw)


def test_random_shape():
    attr = make(RandomAttributionMetric).run("fc1")
    assert attr.shape == (4,)


def test_weight_norm():
    attr = make(WeightNormAttributionMetric).run("fc1")
    np.testing.assert_array_almost_equal(attr, [1, 2, 2, 2])


def test_apoz():
    attr = make(APoZAttributionMetric).run("fc1")
    np.testing.assert_array_almost_equal(attr, [0.5, 0.5, 1, 1])


def test_sensitivity_zero_at_perfect_solution():
    attr = make(SensitivityAttributionMetric).run("fc1")
    np.testing.assert_array_almost_equal(attr, [0, 0, 0, 0])


def test_taylor_zero_at_perfect_solution():
    attr = make(TaylorAttributionMetric).run("fc1")
    np.testing.assert_array_almost_equal(attr, [0, 0, 0, 0])


def test_sensitivity_version2():
    # A carries weight 1 active half the time; B weight .5 active half;
    # C weight .5 always active; D weight .1 always active -> [.2,.1,.2,.04]
    attr = make(SensitivityAttributionMetric, version=2).run("fc1")
    np.testing.assert_array_almost_equal(attr, [0.2, 0.1, 0.2, 0.04])


def test_taylor_version2():
    attr = make(TaylorAttributionMetric, version=2).run("fc1")
    np.testing.assert_array_almost_equal(attr, [0.1, 0.1, 0.5, 0.1])


def test_taylor_version2_signed():
    attr = make(TaylorAttributionMetric, version=2, signed=True).run("fc1")
    np.testing.assert_array_almost_equal(attr, [0.1, 0.1, 0.5, -0.1])


def test_shapley_statistical():
    # Monte-Carlo estimate converges to the analytic Shapley values
    # (reference tests/test_attributions.py:128-137: sv_samples=1000, 1dp)
    attr = make(ShapleyAttributionMetric, sv_samples=1000).run("fc1")
    np.testing.assert_array_almost_equal(attr, [0.37, 0.37, 1.7, 0.0], decimal=1)


def test_shapley_slow_path_matches_fast_path():
    m_fast = make(ShapleyAttributionMetric, sv_samples=20, seed=7)
    m_slow = make(ShapleyAttributionMetric, sv_samples=20, seed=7,
                  use_partial=False)
    a = m_fast.run("fc1")
    b = m_slow.run("fc1")
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_eval_layer_shifting_rules():
    # data-driven metrics shift past BN+activation; weight-based don't
    # (reference tests/test_attributions.py:177-201)
    model = SegmentedModel(
        (L.Dense("fc1", 4), L.BatchNorm("bn"), L.Activation("r", "relu"),
         L.Dense("fc2", 1)),
        (3,),
    )
    params, state = init_model(model)
    data = max_model_batches()
    for cls in [TaylorAttributionMetric, SensitivityAttributionMetric,
                ShapleyAttributionMetric, APoZAttributionMetric]:
        metric = cls(model, params, data, mse_loss, state=state)
        assert metric.find_evaluation_layer("fc1", True) == "r"
    for cls in [WeightNormAttributionMetric, RandomAttributionMetric]:
        metric = cls(model, params, data, mse_loss, state=state)
        assert metric.find_evaluation_layer("fc1", True) == "fc1"


def test_shift_invariance_through_relu():
    # attribution before/after a ReLU is identical for these metrics on the
    # fixture (reference tests/test_attributions.py:203-216)
    for cls in [TaylorAttributionMetric, SensitivityAttributionMetric,
                APoZAttributionMetric, WeightNormAttributionMetric]:
        metric = make(cls)
        a = metric.run("fc1", find_best_evaluation_layer=False)
        b = metric.run("fc1", find_best_evaluation_layer=True)
        np.testing.assert_array_almost_equal(a, b)


@pytest.mark.parametrize("cls", ALL_METRICS)
def test_all_metrics_run_with_shifting(cls):
    # smoke: every metric runs with find_best_evaluation_layer=True
    # (reference tests/test_attributions.py:218-229)
    attr = make(cls).run("fc1", find_best_evaluation_layer=True)
    assert attr.shape == (4,)


def test_reductions():
    metric = make(TaylorAttributionMetric, version=2, reduction="none")
    rows = metric.run("fc1")
    assert rows.shape == (4, 4)  # (examples, units)
    m_sum = make(TaylorAttributionMetric, version=2, reduction="sum")
    np.testing.assert_allclose(m_sum.run("fc1"), rows.sum(0), rtol=1e-5)
    m_custom = make(TaylorAttributionMetric, version=2,
                    reduction=mean_plus_2std)
    np.testing.assert_allclose(
        m_custom.run("fc1"), rows.mean(0) + 2 * rows.std(0), rtol=1e-5
    )


def test_non_prunable_layer_rejected():
    metric = make(TaylorAttributionMetric)
    with pytest.raises(TypeError):
        metric.run("act1")


def test_batch_size_invariance_apoz():
    # accumulating per-example rows must not depend on batching
    model, params, _, _ = max_model()
    a = APoZAttributionMetric(model, params, max_model_batches(1), mse_loss)
    b = APoZAttributionMetric(model, params, max_model_batches(2), mse_loss)
    np.testing.assert_array_almost_equal(a.run("fc1"), b.run("fc1"))


def test_conv_metrics_smoke():
    # metrics run on a conv layer with spatial reduction
    from torchpruner_tpu.models import fmnist_convnet
    import jax

    model = fmnist_convnet()
    params, state = init_model(model, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 28, 28, 1))
    y = np.zeros((4,), dtype=np.int32)
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    data = [(x, y)]
    for cls in [APoZAttributionMetric, SensitivityAttributionMetric,
                TaylorAttributionMetric]:
        metric = cls(model, params, data, cross_entropy_loss, state=state)
        attr = metric.run("conv1", find_best_evaluation_layer=True)
        assert attr.shape == (32,)
    sv = ShapleyAttributionMetric(model, params, data, cross_entropy_loss,
                                  state=state, sv_samples=2)
    attr = sv.run("conv1", find_best_evaluation_layer=True)
    assert attr.shape == (32,)


def test_bf16_scoring_preserves_ranking():
    """compute_dtype=bfloat16 runs the scoring forwards in bf16 (f32 loss
    accumulation); rankings must track the f32 scores closely."""
    import jax.numpy as jnp

    from torchpruner_tpu.data import load_dataset
    from torchpruner_tpu.models import digits_fc
    from torchpruner_tpu.core.segment import init_model

    model = digits_fc()
    params, state = init_model(model, seed=0)
    val = load_dataset("digits_flat", "val")
    data = val.batches(100)

    from torchpruner_tpu.attributions import (
        ShapleyAttributionMetric as SV,
        TaylorAttributionMetric as Taylor,
    )
    from torchpruner_tpu.utils.losses import cross_entropy_loss as ce

    for cls, kw in ((SV, {"sv_samples": 3}), (Taylor, {})):
        f32 = cls(model, params, data, ce, state=state,
                  seed=0, **kw).run("fc2")
        bf16 = cls(model, params, data, ce, state=state,
                   seed=0, compute_dtype=jnp.bfloat16, **kw).run("fc2")
        assert bf16.dtype == np.float32  # rows always land f32 on host
        # Spearman rank correlation
        r_f, r_b = np.argsort(np.argsort(f32)), np.argsort(np.argsort(bf16))
        n = len(f32)
        rho = 1 - 6 * np.sum((r_f - r_b) ** 2) / (n * (n**2 - 1))
        assert rho > 0.95, (cls.__name__, rho)

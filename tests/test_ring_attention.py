"""Ring-attention (context parallelism) tests on the 8-device CPU mesh:
numerics vs the single-device reference, causal masking across ring hops,
and gradient flow under shard_map."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from torchpruner_tpu.ops.flash_attention import _xla_attention
from torchpruner_tpu.parallel import make_mesh
from torchpruner_tpu.parallel.ring import ring_attention


def qkv(B=2, S=32, H=2, Dh=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, Dh)) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_seq", [2, 8])
def test_ring_matches_single_device(causal, n_seq):
    mesh = make_mesh({"seq": n_seq}, devices=jax.devices()[:n_seq])
    q, k, v = qkv()
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = _xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_rejects_indivisible_sequence():
    mesh = make_mesh({"seq": 8})
    q, k, v = qkv(S=30)
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh)


def test_ring_gradients_match_single_device():
    mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
    q, k, v = qkv(S=16)
    g = jax.random.normal(jax.random.PRNGKey(7), q.shape)

    def grads(fn):
        return jax.grad(
            lambda q_, k_, v_: jnp.sum(fn(q_, k_, v_) * g), argnums=(0, 1, 2)
        )(q, k, v)

    got = grads(lambda a, b, c: ring_attention(a, b, c, mesh, causal=True))
    want = grads(lambda a, b, c: _xla_attention(a, b, c, causal=True))
    for ga, gw in zip(got, want):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gw), atol=1e-4)


def test_ring_bf16_output_dtype():
    mesh = make_mesh({"seq": 2}, devices=jax.devices()[:2])
    q, k, v = (t.astype(jnp.bfloat16) for t in qkv(S=16))
    out = ring_attention(q, k, v, mesh, causal=True)
    assert out.dtype == jnp.bfloat16


@pytest.mark.parametrize("causal", [False, True])
def test_chunk_streaming_matches_single_block(causal):
    """The blocked (streamed) chunk path must match the one-shot einsum
    path exactly — values and gradients — so ring attention's peak score
    memory can shrink without changing numerics."""
    from torchpruner_tpu.parallel.ring import _block_stats, _chunk_stats

    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (2, 8, 2, 4), jnp.float32)
    k = jax.random.normal(ks[1], (2, 64, 2, 4), jnp.float32)
    v = jax.random.normal(ks[2], (2, 64, 2, 4), jnp.float32)

    # q_off INSIDE the chunk (queries at 32..39, keys at 0..63): causal
    # masking then differs per KV block, exercising the streamed offsets
    want = _block_stats(q, k, v, 32, 0, causal)
    got = _chunk_stats(q, k, v, 32, 0, causal, block_k=16)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=1e-5, rtol=1e-5)

    def loss(fn):
        def f(q_, k_, v_):
            m, l, acc = fn(q_, k_, v_)
            return jnp.sum(acc / l[..., None])
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    got_g = loss(lambda a, b, c: _chunk_stats(a, b, c, 32, 0, causal,
                                              block_k=16))
    want_g = loss(lambda a, b, c: _block_stats(a, b, c, 32, 0, causal))
    for g, w in zip(got_g, want_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=1e-5, rtol=1e-4)

"""Windowed metric time-series (torchpruner_tpu.obs.timeseries) and the
SLO burn-rate alerting built on it: delta-snapshot recording (counters /
gauges / histogram bucket deltas), rotation- and torn-line-tolerant
readers, per-window and steady-state percentile reconstruction, the
kill -9 readable-prefix contract, the fleet merge onto the router clock,
the ``obs watch`` view, the hot-path overhead guard, and the
multi-window burn-rate episode semantics of ``serve.slo.SLOMonitor``."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from torchpruner_tpu import obs
from torchpruner_tpu.obs.ledger import LEDGER_FILENAME, load_ledger
from torchpruner_tpu.obs.metrics import MetricsRegistry
from torchpruner_tpu.obs.timeseries import (
    TS_FILENAME,
    TimeseriesRecorder,
    aggregate_windows,
    format_watch,
    load_series,
    segment_percentiles,
    series_paths,
    series_summary,
    split_warmup,
    steady_state_percentiles,
    watch,
    window_quantile,
)
from torchpruner_tpu.serve.slo import SLOMonitor


@pytest.fixture(autouse=True)
def _clean_session():
    obs.shutdown()
    yield
    obs.shutdown()


def _mk_recorder(tmp_path, **kw):
    reg = MetricsRegistry()
    rec = TimeseriesRecorder(reg, str(tmp_path), interval_s=0.05, **kw)
    return reg, rec


# -- recorder ----------------------------------------------------------------


def test_recorder_emits_deltas_not_cumulatives(tmp_path):
    reg, rec = _mk_recorder(tmp_path)
    reg.counter("reqs_total").inc(3)
    reg.gauge("depth").set(7)
    reg.histogram("lat_seconds").observe(0.003)
    rec.tick()
    reg.counter("reqs_total").inc(2)
    reg.histogram("lat_seconds").observe(0.004)
    reg.histogram("lat_seconds").observe(0.005)
    rec.tick()
    rec.close()

    meta, windows = load_series(str(tmp_path))
    assert meta["kind"] == "ts_meta" and meta["pid"] == os.getpid()
    # close() forces a final (empty-delta) window
    assert [w["seq"] for w in windows] == [1, 2, 3]
    assert windows[0]["counters"]["reqs_total"] == 3
    assert windows[1]["counters"]["reqs_total"] == 2  # delta, not 5
    assert windows[0]["gauges"]["depth"] == 7
    h0, h1 = windows[0]["hist"]["lat_seconds"], \
        windows[1]["hist"]["lat_seconds"]
    assert h0["n"] == 1 and h1["n"] == 2
    assert h1["sum"] == pytest.approx(0.009)
    assert sum(h0["c"]) == 1 and sum(h1["c"]) == 2
    # an idle window records nothing for the counter (zero deltas are
    # omitted) and the recorder's close gauges landed in the registry
    assert "counters" not in windows[2] or \
        "reqs_total" not in windows[2].get("counters", {})
    assert reg.get("ts_windows_total").value == 3.0


def test_bucket_bounds_ship_once_but_readers_see_them_everywhere(
        tmp_path):
    reg, rec = _mk_recorder(tmp_path)
    for v in (0.001, 0.01):
        reg.histogram("lat_seconds").observe(v)
        rec.tick()
    rec.close()
    raw = [json.loads(line) for line in
           open(os.path.join(str(tmp_path), TS_FILENAME))]
    on_disk = [r for r in raw if r.get("kind") == "ts_window"
               and "lat_seconds" in (r.get("hist") or {})]
    assert "le" in on_disk[0]["hist"]["lat_seconds"]
    assert "le" not in on_disk[1]["hist"]["lat_seconds"]
    # ...but load_series re-attaches the carried-forward bounds
    _, windows = load_series(str(tmp_path))
    for w in windows:
        h = (w.get("hist") or {}).get("lat_seconds")
        if h:
            assert h["le"] == on_disk[0]["hist"]["lat_seconds"]["le"]


def test_maybe_tick_respects_cadence(tmp_path):
    reg, rec = _mk_recorder(tmp_path)
    reg.counter("c").inc()
    t0 = time.time()
    assert not rec.maybe_tick(now=t0)          # not due yet
    assert rec.maybe_tick(now=t0 + 0.06)       # past the interval
    assert not rec.maybe_tick(now=t0 + 0.07)   # window just emitted
    assert rec.maybe_tick(now=t0 + 0.12)
    assert rec.windows_total == 2


def test_rotation_keeps_series_readable_oldest_first(tmp_path):
    reg, rec = _mk_recorder(tmp_path, rotate_bytes=400, backups=3)
    for i in range(30):
        reg.counter("c").inc()
        rec.tick()
    rec.close()
    path = os.path.join(str(tmp_path), TS_FILENAME)
    assert len(series_paths(path)) > 1  # rotation actually happened
    _, windows = load_series(str(tmp_path))
    seqs = [w["seq"] for w in windows]
    assert seqs == sorted(seqs)
    assert seqs[-1] == 31  # newest window is the forced close


def test_torn_final_line_is_skipped(tmp_path):
    reg, rec = _mk_recorder(tmp_path)
    reg.counter("c").inc()
    rec.tick()
    rec.close()
    path = os.path.join(str(tmp_path), TS_FILENAME)
    with open(path, "a") as f:
        f.write('{"kind": "ts_window", "seq": 99, "tr')  # kill point
    _, windows = load_series(str(tmp_path))
    assert [w["seq"] for w in windows] == [1, 2]


def test_kill9_mid_recording_leaves_parseable_prefix(tmp_path):
    """The durability half of the contract, end to end: SIGKILL a
    process recording windows in a tight loop; the survivor file must
    parse (modulo at most the torn final line) and hold real windows."""
    script = (
        "import time\n"
        "from torchpruner_tpu.obs.metrics import MetricsRegistry\n"
        "from torchpruner_tpu.obs.timeseries import TimeseriesRecorder\n"
        "reg = MetricsRegistry()\n"
        f"rec = TimeseriesRecorder(reg, {str(tmp_path)!r}, "
        "interval_s=0.05)\n"
        "print('UP', flush=True)\n"
        "while True:\n"
        "    reg.counter('steps_total').inc()\n"
        "    reg.histogram('lat_seconds').observe(0.001)\n"
        "    rec.tick()\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, "-c", script], env=env,
                         stdout=subprocess.PIPE, text=True)
    try:
        assert p.stdout.readline().strip() == "UP"
        deadline = time.time() + 20
        path = os.path.join(str(tmp_path), TS_FILENAME)
        while time.time() < deadline:
            if os.path.exists(path) and os.path.getsize(path) > 2000:
                break
            time.sleep(0.01)
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=10)
    finally:
        if p.poll() is None:
            p.kill()
    meta, windows = load_series(str(tmp_path))
    assert meta.get("kind") == "ts_meta"
    assert len(windows) >= 2
    assert all(w["kind"] == "ts_window" for w in windows)
    agg = aggregate_windows(windows, "lat_seconds")
    assert agg is not None and agg["n"] >= 2


# -- percentile reconstruction ----------------------------------------------


def test_window_quantile_tracks_histogram_estimator(tmp_path):
    reg, rec = _mk_recorder(tmp_path)
    h = reg.histogram("lat_seconds")
    values = [0.0005, 0.002, 0.004, 0.009, 0.02, 0.05, 0.08, 0.3]
    for v in values:
        h.observe(v)
    rec.tick()
    rec.close()
    _, windows = load_series(str(tmp_path))
    for q in (0.5, 0.9, 0.99):
        got = window_quantile(windows[0], "lat_seconds", q)
        ref = h.quantile(q)
        # same bucket math; the window path lacks the min/max clamp so
        # compare loosely (same bucket => within one bucket's width)
        assert got == pytest.approx(ref, rel=2.5)


def test_aggregate_and_segment_percentiles(tmp_path):
    reg, rec = _mk_recorder(tmp_path)
    h = reg.histogram("lat_seconds")
    for i in range(4):
        for _ in range(10):
            h.observe(0.001 if i < 2 else 0.1)
        rec.tick()
    rec.close()
    _, windows = load_series(str(tmp_path))
    slow = aggregate_windows(windows[2:4], "lat_seconds")
    assert slow["n"] == 20
    seg = segment_percentiles(windows[2:4], "lat_seconds")
    assert seg["mean"] == pytest.approx(0.1)
    assert seg["p50"] > 0.03  # the slow segment, not the run mean
    warm, steady = split_warmup(windows, warmup_frac=0.25)
    assert len(warm) == 1 and len(steady) == len(windows) - 1
    summary = series_summary(windows)
    assert summary["windows"] == len(windows)
    names = [r["name"] for r in summary["hist"]]
    assert names == ["lat_seconds"]
    assert summary["warmup_windows"] + summary["steady_windows"] \
        == summary["windows"]


def test_steady_state_percentiles_needs_enough_windows(tmp_path):
    reg, rec = _mk_recorder(tmp_path)
    reg.histogram("lat_seconds").observe(0.01)
    rec.tick()
    reg.histogram("lat_seconds").observe(0.02)
    rec.close()  # 2 windows total: under the default min of 3
    assert steady_state_percentiles(str(tmp_path), "lat_seconds") is None
    assert steady_state_percentiles(
        str(tmp_path), "lat_seconds", min_windows=1)["n"] == 1


# -- overhead guard ----------------------------------------------------------


def test_recorder_hot_path_overhead_under_budget(tmp_path):
    """Same contract as the PR 2 <100 µs/step guard: the per-step
    ``maybe_tick`` (not due — the 99.9% case) must be a clock read and
    a compare, and a full registry walk must cost <1% of a 1 Hz window
    even with a realistically populated registry."""
    reg = MetricsRegistry()
    for i in range(8):
        reg.counter(f"c{i}").inc()
        reg.gauge(f"g{i}").set(i)
        reg.histogram(f"h{i}").observe(0.001 * (i + 1))
    rec = TimeseriesRecorder(reg, str(tmp_path), interval_s=3600.0)
    n = 5000
    rec.maybe_tick()  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        rec.maybe_tick()
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 100e-6, f"maybe_tick cost {per_call * 1e6:.1f} µs"

    m = 50
    t0 = time.perf_counter()
    for _ in range(m):
        rec.tick()
    per_tick = (time.perf_counter() - t0) / m
    rec.close()
    assert per_tick < 0.01, f"tick cost {per_tick * 1e3:.2f} ms"


# -- obs session integration -------------------------------------------------


def test_session_records_and_closes_series(tmp_path):
    obs.configure(str(tmp_path), process_index=0, annotate=False,
                  watch_compiles=False, ts_interval_s=0.05)
    s = obs.get()
    assert s.timeseries is not None
    for _ in range(3):
        obs.record_step(0.001, 32, 64)
        time.sleep(0.06)
        obs.record_step(0.001, 32, 64)
    obs.timeseries_tick()
    obs.shutdown()
    meta, windows = load_series(str(tmp_path))
    assert meta.get("interval_s") == 0.05
    assert len(windows) >= 2
    assert any("steps_total" in (w.get("counters") or {})
               for w in windows)


def test_ts_interval_zero_disables_recorder(tmp_path, monkeypatch):
    monkeypatch.setenv("TORCHPRUNER_TS_INTERVAL_S", "0")
    obs.configure(str(tmp_path), process_index=0, annotate=False,
                  watch_compiles=False)
    assert obs.get().timeseries is None
    obs.shutdown()
    assert not os.path.exists(os.path.join(str(tmp_path), TS_FILENAME))


# -- fleet merge -------------------------------------------------------------


def _write_series(run_dir, pid, ts_list, depth):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, TS_FILENAME), "w") as f:
        f.write(json.dumps({"kind": "ts_meta", "v": 1, "pid": pid,
                            "t0": ts_list[0], "interval_s": 1.0}) + "\n")
        for i, ts in enumerate(ts_list):
            f.write(json.dumps({
                "kind": "ts_window", "seq": i + 1, "ts": ts,
                "dur_s": 1.0, "gauges": {"queue_depth": depth}}) + "\n")


def test_merge_timeseries_aligns_on_router_clock(tmp_path):
    from torchpruner_tpu.fleet.report import merge_timeseries

    fleet_obs = str(tmp_path / "obs")
    _write_series(fleet_obs, 100, [10.0, 11.0, 12.0], 0)
    # replica0's clock runs 0.25 s AHEAD of the router's...
    _write_series(os.path.join(fleet_obs, "replica0"), 101,
                  [10.75, 11.75], 3)
    _write_series(os.path.join(fleet_obs, "replica1"), 102,
                  [10.6, 11.6], 5)
    # ...which the router's health monitor measured and emitted
    with open(os.path.join(fleet_obs, "events.jsonl"), "w") as f:
        f.write(json.dumps({"event": "clock_offset", "ts": 9.0,
                            "replica": "replica0", "offset_s": 0.1,
                            "rtt_s": 0.01}) + "\n")
        f.write(json.dumps({"event": "clock_offset", "ts": 9.5,
                            "replica": "replica0", "offset_s": 0.25,
                            "rtt_s": 0.001}) + "\n")  # LAST wins

    out = merge_timeseries(fleet_obs)
    assert out == {"streams": 3, "windows": 7}
    merged = [json.loads(line) for line in
              open(os.path.join(fleet_obs, "metrics_ts_fleet.jsonl"))]
    assert len(merged) == 7
    # every record stamped with its process and placed on pid i+1
    pids = {r["proc"]: r["pid"] for r in merged}
    assert pids == {"router": 0, "replica0": 1, "replica1": 2}
    # replica0's windows re-homed by -0.25 s onto the router timeline
    r0 = [r for r in merged if r["proc"] == "replica0"]
    assert [r["ts"] for r in r0] == [pytest.approx(10.5),
                                     pytest.approx(11.5)]
    assert r0[0]["shift_s"] == pytest.approx(-0.25)
    # no offset event for replica1 -> unshifted
    r1 = [r for r in merged if r["proc"] == "replica1"]
    assert [r["ts"] for r in r1] == [10.6, 11.6]
    # the merged stream reads as ONE timeline
    tss = [r["ts"] for r in merged]
    assert tss == sorted(tss)
    # each replica's gauge history is recoverable from the merge
    assert all(r["gauges"]["queue_depth"] == 3 for r in r0)


# -- obs watch ---------------------------------------------------------------


def test_format_watch_and_once_frame(tmp_path, capsys):
    reg, rec = _mk_recorder(tmp_path)
    reg.counter("reqs_total").inc(5)
    reg.gauge("fleet_replica_r0_queue_depth").set(2)
    reg.histogram("serve_ttft_seconds").observe(0.02)
    rec.tick(now=time.time() + 0.06)
    # formatted mid-run: the newest window carries this window's deltas
    frame = format_watch(str(tmp_path))
    rec.close()
    assert "serve_ttft_seconds" in frame
    assert "reqs_total" in frame
    assert "fleet_replica_r0_queue_depth" in frame
    # after close the newest window is the final flush: gauges persist
    assert watch(str(tmp_path), once=True) == 0
    assert "fleet_replica_r0_queue_depth" in capsys.readouterr().out
    # empty dir: still renders (the live view starts before windows do)
    assert "no metrics_ts.jsonl" in format_watch(str(tmp_path / "nope"))


# -- SLO burn rate -----------------------------------------------------------


def test_burn_alert_fires_once_per_episode_and_rearms():
    m = SLOMonitor(token_p99_s=0.010, check_every_steps=1,
                   min_samples=8)
    t0 = 1000.0
    for i in range(20):  # clean traffic: no burn
        m.on_token(0.002, ts=t0 + i * 0.1)
        m.check(step=i, now=t0 + i * 0.1)
    assert m.burn_alerts_total == 0
    for i in range(40):  # sustained breach: ONE episode
        t = t0 + 2.0 + i * 0.1
        m.on_token(0.050, ts=t)
        m.check(step=100 + i, now=t)
    assert m.burn_alerts_total == 1
    for i in range(200):  # recovery re-arms
        t = t0 + 6.0 + i * 0.1
        m.on_token(0.001, ts=t)
        m.check(step=200 + i, now=t)
    assert not m.snapshot()["in_burn"]["token"]
    for i in range(40):  # second incident: second alert
        t = t0 + 27.0 + i * 0.1
        m.on_token(0.050, ts=t)
        m.check(step=500 + i, now=t)
    assert m.burn_alerts_total == 2
    snap = m.snapshot()
    assert snap["burn_alerts_total"] == 2  # additive /stats field
    assert "ttft_p99_rolling_ms" in snap  # legacy shape kept


def test_burn_needs_both_windows_over_threshold():
    """A short blip saturates the fast window but not the slow one —
    the multi-window AND must reject it."""
    m = SLOMonitor(token_p99_s=0.010, check_every_steps=1,
                   min_samples=8)
    t0 = 1000.0
    # 110 s of clean traffic filling the slow window...
    for i in range(110):
        m.on_token(0.001, ts=t0 + i * 1.0)
    # ...then a 10-observation blip within a second
    for i in range(10):
        t = t0 + 110.0 + i * 0.1
        m.on_token(0.050, ts=t)
        m.check(now=t)
    assert m.burn_alerts_total == 0


def test_burn_alert_is_ledgered_and_counts(tmp_path):
    obs.configure(str(tmp_path), process_index=0, annotate=False,
                  watch_compiles=False, ts_interval_s=0)
    m = SLOMonitor(token_p99_s=0.010, check_every_steps=1,
                   min_samples=8)
    t0 = 1000.0
    for i in range(20):
        t = t0 + i * 0.1
        m.on_token(0.050, ts=t)
        m.check(step=i, now=t)
    snap = obs.get().metrics.snapshot()
    obs.shutdown()
    assert snap["slo_burn_alerts_total"] == 1.0
    assert snap["slo_burn_token_fast"] >= 10.0
    burns = [r for r in load_ledger(
        os.path.join(str(tmp_path), LEDGER_FILENAME))
        if r.get("event") == "serve" and r.get("kind") == "slo_burn"]
    assert len(burns) == 1
    b = burns[0]
    assert b["metric"] == "token"
    assert b["burn_fast"] >= 10.0 and b["burn_slow"] >= 10.0
    assert b["threshold_s"] == pytest.approx(0.010)


def test_queue_age_hook_feeds_monitor():
    m = SLOMonitor(queue_p99_s=0.5, check_every_steps=1, min_samples=2)
    m.on_queue(0.1, ts=1000.0)
    m.on_queue(0.9, ts=1000.5)
    rolling = m.check(now=1000.6)
    assert rolling["queue"] == pytest.approx(0.9, rel=0.01)
    assert m.breaches_total == 1  # p99 over the 0.5 s threshold

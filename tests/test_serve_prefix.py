"""Serve v2 tests: the prefix-sharing KV cache (radix trie over page
chunks, refcounts, copy-on-write materialization, LRU eviction of
unpinned prefixes), chunked prefill (lane-aligned chunks interleaved
with decode, per-step token cap), and the fleet router's session/prefix
affinity.

The load-bearing invariants:

- **Bit-parity**: decode with sharing ON equals decode with sharing
  OFF equals solo ``generate()`` — a poisoned shared page would break
  greedy argmax, so token equality IS the cache-correctness proof.
- **Refcounts never go negative** and an evictor can never reclaim a
  page a resident request still reads.
- **Zero hits on disjoint prompts** — the trie must never invent a
  match.
- **The prefill cap is a hard per-step budget** (floored at one
  chunk), observable via ``max_prefill_tokens_step``.
"""

import numpy as np
import pytest

from torchpruner_tpu.serve.allocator import KVCacheAllocator, PrefixTrie

# -- trie units --------------------------------------------------------------


def _ids(*xs):
    return np.asarray(xs, np.int32)


def seq(n, base=0):
    return np.arange(base, base + n, dtype=np.int32)


def test_trie_insert_match_roundtrip():
    t = PrefixTrie(page_len=4)
    pages = iter(range(100))
    plan = t.insert(seq(12), 12, lambda protect: next(pages))
    assert [p for _, p in plan] == [0, 1, 2]
    n_tok, got_pages, path = t.match(seq(12), max_tokens=12)
    assert n_tok == 12 and got_pages == [0, 1, 2]
    # a shorter probe matches only whole pages
    n_tok, got_pages, _ = t.match(seq(7), max_tokens=7)
    assert n_tok == 4 and got_pages == [0]
    # max_tokens caps the match at a page boundary
    n_tok, got_pages, _ = t.match(seq(12), max_tokens=11)
    assert n_tok == 8 and got_pages == [0, 1]


def test_trie_split_on_divergence_preserves_shared_prefix():
    t = PrefixTrie(page_len=4)
    pages = iter(range(100))
    t.insert(seq(12), 12, lambda protect: next(pages))
    # same first 2 pages, divergent third page
    other = np.concatenate([seq(8), seq(4, base=100)])
    plan = t.insert(other, 12, lambda protect: next(pages))
    assert [i for i, _ in plan] == [2]  # only the novel page acquired
    n_a, pages_a, _ = t.match(seq(12), max_tokens=12)
    n_b, pages_b, _ = t.match(other, max_tokens=12)
    assert n_a == n_b == 12
    assert pages_a[:2] == pages_b[:2]      # shared prefix shares pages
    assert pages_a[2] != pages_b[2]        # divergent tails don't


def test_trie_refcount_pin_unpin_and_underflow():
    t = PrefixTrie(page_len=4)
    pages = iter(range(100))
    t.insert(seq(8), 8, lambda protect: next(pages))
    _, _, path = t.match(seq(8), max_tokens=8)
    t.pin(path)
    t.pin(path)
    assert all(n.refcount == 2 for n in path)
    t.unpin(path)
    t.unpin(path)
    assert all(n.refcount == 0 for n in path)
    with pytest.raises(RuntimeError):
        t.unpin(path)  # refcounts must never go negative


def test_trie_evict_refuses_pinned_and_takes_lru_unpinned_leaf():
    t = PrefixTrie(page_len=4)
    pages = iter(range(100))
    t.insert(seq(4), 4, lambda protect: next(pages))
    t.insert(seq(4, base=50), 4, lambda protect: next(pages))
    _, pages_a, path_a = t.match(seq(4), max_tokens=4)
    t.pin(path_a)
    # the pinned leaf is untouchable: eviction takes the unpinned one
    freed = t.evict_lru(protect=[])
    assert freed and freed != pages_a
    # only the pinned leaf remains → eviction REFUSES (empty), it
    # never reclaims a page a resident request still reads
    assert t.evict_lru(protect=[]) == []
    t.unpin(path_a)
    assert t.evict_lru(protect=[]) == pages_a


def test_trie_split_inherits_refcount():
    """Splitting a PINNED edge must keep every chain node pinned (the
    resident request reads through the new mid node), and an ancestor-
    chain unpin — what the allocator's release does — must balance."""
    from torchpruner_tpu.serve.allocator import _ancestors

    t = PrefixTrie(page_len=4)
    pages = iter(range(100))
    t.insert(seq(12), 12, lambda protect: next(pages))
    _, _, path = t.match(seq(12), max_tokens=12)
    t.pin(path)
    deep = path[-1]
    # divergence after page 1 splits the pinned edge
    other = np.concatenate([seq(4), seq(8, base=100)])
    t.insert(other, 12, lambda protect: next(pages))
    mid = deep.parent
    assert mid is not t.root and mid.refcount == 1  # pin carried over
    assert deep.refcount == 1
    # the pinned chain refuses eviction; only the divergent tail frees
    assert sorted(t.evict_lru(protect=[])) == [3, 4]
    assert t.evict_lru(protect=[]) == []
    t.unpin(list(_ancestors(deep)))
    assert all(n.refcount == 0 for n in t.nodes())


def test_trie_reset_returns_every_page():
    t = PrefixTrie(page_len=4)
    pages = iter(range(100))
    t.insert(seq(8), 8, lambda protect: next(pages))
    t.insert(seq(8, base=50), 8, lambda protect: next(pages))
    freed = t.reset()
    assert sorted(freed) == [0, 1, 2, 3]
    assert t.match(seq(8), max_tokens=8)[0] == 0


# -- allocator ---------------------------------------------------------------


def _alloc(**kw):
    base = dict(n_slots=2, max_len=32, page_len=8, prefix_pages=4)
    base.update(kw)
    return KVCacheAllocator(**base)


def test_allocator_miss_publish_hit_release_cycle():
    a = _alloc()
    prompt = seq(20)
    assert a.match_prefix(prompt, max_tokens=19) is None
    assert a.prefix_misses == 1
    plan = a.publish_prefix(prompt, 20)  # 2 whole pages of 8
    assert [i for i, _ in plan] == [0, 1]
    m = a.match_prefix(prompt, max_tokens=19)
    assert m is not None and m.tokens == 16 and len(m.pages) == 2
    assert a.shared_pages == 2
    # pinned pages refuse eviction even under pool pressure
    for i in range(10):
        assert a._acquire_page(protect=[]) is not None \
            or a.prefix_pool_exhausted > 0
    a.release_prefix(m)
    a.release_prefix(m)  # idempotent
    assert a.shared_pages == 0


def test_allocator_refcounts_never_negative_under_random_ops():
    rng = np.random.default_rng(0)
    a = _alloc(prefix_pages=8)
    prompts = [seq(24, base=100 * i) for i in range(4)]
    live = []
    for step in range(200):
        op = rng.integers(0, 3)
        p = prompts[int(rng.integers(0, len(prompts)))]
        if op == 0:
            m = a.match_prefix(p, max_tokens=23)
            if m is not None:
                live.append(m)
        elif op == 1:
            a.publish_prefix(p, int(p.size))
        elif live:
            a.release_prefix(live.pop(int(rng.integers(0, len(live)))))
        for node in a._trie.nodes():
            assert node.refcount >= 0
    for m in live:
        a.release_prefix(m)
    assert all(n.refcount == 0 for n in a._trie.nodes())


def test_allocator_evict_while_shared_refused():
    a = _alloc(prefix_pages=2)
    prompt = seq(20)
    a.publish_prefix(prompt, 20)           # fills the 2-page pool
    m = a.match_prefix(prompt, max_tokens=19)
    assert m is not None
    # every pool page is pinned: acquisition must FAIL (None), never
    # steal a shared page out from under the resident request
    assert a._acquire_page(protect=[]) is None
    assert a.prefix_pool_exhausted >= 1
    a.release_prefix(m)
    assert a._acquire_page(protect=[]) is not None  # now evictable


def test_allocator_lru_eviction_order():
    a = _alloc(prefix_pages=2)
    a.publish_prefix(seq(8), 8)
    a.publish_prefix(seq(8, base=50), 8)
    # touch the first prefix so the SECOND is LRU
    m = a.match_prefix(seq(8), max_tokens=8)
    assert m is not None
    a.release_prefix(m)
    got = a._acquire_page(protect=[])
    assert got is not None
    assert a.prefix_evictions == 1
    # the surviving prefix is the recently-used one
    assert a._trie.match(seq(8), 8)[0] == 8
    assert a._trie.match(seq(8, base=50), 8)[0] == 0


def test_allocator_release_unpins_split_inserted_mid():
    """Regression: a pinned match whose edge is later split by a
    divergent publish must still release cleanly — the split's mid
    node inherited the pin, and release walks the CURRENT ancestor
    chain (not the stale match-time path).  A leaked pin here would
    make the mid's pages permanently unevictable."""
    a = _alloc(prefix_pages=8)
    a.publish_prefix(seq(24), 24)               # 3 pages of 8
    m = a.match_prefix(seq(24), max_tokens=23)  # pins 2 whole pages
    assert m is not None and m.tokens == 16
    divergent = np.concatenate([seq(8), seq(16, base=500)])
    a.publish_prefix(divergent, 24)             # splits the pinned edge
    a.release_prefix(m)
    assert all(n.refcount == 0 for n in a._trie.nodes())
    # every pool page is now reclaimable (free list + LRU eviction)
    got = set()
    while True:
        p = a._acquire_page(protect=[])
        if p is None:
            break
        got.add(p)
    assert len(got) == a.prefix_pages


def test_allocator_prefix_disabled_by_default():
    a = KVCacheAllocator(n_slots=2, max_len=32, page_len=8)
    assert not a.prefix_enabled
    assert a.match_prefix(seq(16), max_tokens=15) is None
    assert a.prefix_misses == 0  # disabled ≠ miss: no counters move


# -- engine: chunked prefill + sharing parity --------------------------------


@pytest.fixture(scope="module")
def tiny():
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.models import llama_tiny

    model = llama_tiny()
    params, _ = init_model(model, seed=0)
    return model, params


def _engine(model, params, **kw):
    from torchpruner_tpu.serve import ServeEngine

    base = dict(n_slots=2, max_len=64, page_len=8)
    base.update(kw)
    return ServeEngine(model, params, **base)


def _serve(eng, reqs, max_steps=500):
    from torchpruner_tpu.serve import OpenLoopTraffic, staggered_arrivals

    eng.run(OpenLoopTraffic(reqs, staggered_arrivals(len(reqs), 2),
                            by_step=True))
    assert all(r.state == "done" for r in reqs)
    return {r.id: list(r.tokens) for r in reqs}


def _solo(model, params, req, max_len=64):
    import jax

    from torchpruner_tpu.generate import generate

    s = req.sampling
    out = generate(model, params, req.prompt_ids[None], req.max_new,
                   max_len=max_len, temperature=s.temperature,
                   top_k=s.top_k, top_p=s.top_p,
                   rng=jax.random.PRNGKey(s.seed))
    return np.asarray(out)[0].tolist()


def _shared_reqs(vocab, n=4, temperature=0.0):
    from torchpruner_tpu.serve import shared_prefix_requests

    return shared_prefix_requests(
        n, vocab=vocab, n_prefixes=2, prefix_len=16,
        suffix_lens=[3, 5, 9], max_new=[6, 8], seed=11,
        temperature=temperature)


def test_chunked_prefill_parity_with_legacy_and_solo(tiny):
    """Ragged (non-page-aligned) prompts through the chunked path
    decode bit-identically to the legacy whole-bucket path AND to
    solo generate — padded final chunks and parked decode positions
    leak nothing."""
    from torchpruner_tpu.serve import vocab_of

    model, params = tiny
    vocab = vocab_of(model)
    reqs_c = _shared_reqs(vocab)
    reqs_l = _shared_reqs(vocab)
    toks_c = _serve(_engine(model, params, prefill_chunk=8), reqs_c)
    toks_l = _serve(_engine(model, params), reqs_l)
    for rc, rl in zip(reqs_c, reqs_l):
        assert toks_c[rc.id] == toks_l[rl.id]
        assert toks_c[rc.id] == _solo(model, params, rc)


def test_sharing_on_off_bit_identical_poisoned_cache_guard(tiny):
    """The poisoned-cache parity: identical traffic with sharing ON
    (hits + COW + publication) and OFF must produce bit-identical
    tokens — and ON must actually share (hits > 0), or the test
    proves nothing."""
    from torchpruner_tpu.serve import vocab_of

    model, params = tiny
    vocab = vocab_of(model)
    reqs_on = _shared_reqs(vocab, n=5)
    reqs_off = _shared_reqs(vocab, n=5)
    eng_on = _engine(model, params, prefix_pages=8, prefill_chunk=8)
    toks_on = _serve(eng_on, reqs_on)
    toks_off = _serve(_engine(model, params, prefill_chunk=8), reqs_off)
    alloc = eng_on.scheduler.allocator
    assert alloc.prefix_hits > 0 and alloc.prefix_hit_tokens >= 16
    for a, b in zip(reqs_on, reqs_off):
        assert toks_on[a.id] == toks_off[b.id]
        assert toks_on[a.id] == _solo(model, params, a)
    # per-request attribution: hit + computed == prompt_len
    for r in reqs_on:
        assert r.prefix_hit_tokens + r.prefilled_tokens \
            == r.prompt_ids.size


def test_sampled_requests_share_bit_identically(tiny):
    """Seeded SAMPLED decode (temperature > 0) over shared prefixes:
    the first-token sample must come off the same logits/rng stream
    whether the prefix was computed or mapped."""
    from torchpruner_tpu.serve import vocab_of

    model, params = tiny
    vocab = vocab_of(model)
    reqs = _shared_reqs(vocab, n=4, temperature=0.8)
    eng = _engine(model, params, prefix_pages=8, prefill_chunk=8)
    toks = _serve(eng, reqs)
    assert eng.scheduler.allocator.prefix_hits > 0
    for r in reqs:
        assert toks[r.id] == _solo(model, params, r)


def test_disjoint_prompts_zero_hits(tiny):
    """Fully random prompts: the radix cache must never invent a
    match (hits exactly zero), and decode stays solo-identical."""
    from torchpruner_tpu.serve import synthetic_requests, vocab_of

    model, params = tiny
    vocab = vocab_of(model)
    reqs = synthetic_requests(4, vocab=vocab, prompt_lens=[17, 21],
                              max_new=[6], seed=5)
    eng = _engine(model, params, prefix_pages=8, prefill_chunk=8)
    toks = _serve(eng, reqs)
    alloc = eng.scheduler.allocator
    assert alloc.prefix_hits == 0 and alloc.prefix_hit_tokens == 0
    for r in reqs:
        assert toks[r.id] == _solo(model, params, r)


def test_prefill_cap_is_hard_per_step_budget(tiny):
    """With a cap, no engine step prefills more than the budget; the
    floor is one chunk (a smaller cap would deadlock)."""
    model, params = tiny
    from torchpruner_tpu.serve import vocab_of

    vocab = vocab_of(model)
    reqs = _shared_reqs(vocab, n=4)
    eng = _engine(model, params, prefix_pages=8, prefill_chunk=8,
                  prefill_token_cap=8)
    _serve(eng, reqs)
    assert eng.max_prefill_tokens_step <= 8
    s = eng.summary()
    assert s["max_prefill_tokens_step"] <= s["prefill_token_cap"] == 8
    # cap below the chunk width floors AT the chunk width
    eng2 = _engine(model, params, prefill_chunk=8, prefill_token_cap=3)
    assert eng2.scheduler.prefill_budget(8) == 8


def test_chunk_must_divide_geometry(tiny):
    model, params = tiny
    with pytest.raises(ValueError):
        _engine(model, params, prefill_chunk=24)   # 24 ∤ page_len 8
    with pytest.raises(ValueError):
        _engine(model, params, prefill_chunk=7)    # 7 ∤ max_len 64


def test_decode_interleaves_with_chunked_prefill(tiny):
    """A resident decoding request keeps emitting tokens while a long
    prompt prefills in capped chunks — the cap's whole purpose."""
    from torchpruner_tpu.serve import Request, Sampling, vocab_of

    model, params = tiny
    vocab = vocab_of(model)
    rng = np.random.default_rng(3)
    eng = _engine(model, params, prefix_pages=0, prefill_chunk=8,
                  prefill_token_cap=8)
    short = Request(prompt_ids=rng.integers(0, vocab, 4).astype(np.int32),
                    max_new=12, sampling=Sampling(seed=1))
    long = Request(prompt_ids=rng.integers(0, vocab, 48).astype(np.int32),
                   max_new=4, sampling=Sampling(seed=2))
    eng.submit(short)
    for _ in range(50):
        eng.step()
        if len(short.tokens) >= 2:
            break
    eng.submit(long)
    tokens_before = len(short.tokens)
    # the long prompt needs 6 chunked steps; the short request must
    # keep decoding during them
    for _ in range(6):
        eng.step()
    assert len(short.tokens) > tokens_before
    for _ in range(200):
        if short.state == "done" and long.state == "done":
            break
        eng.step()
    assert short.state == "done" and long.state == "done"
    assert list(short.tokens) == _solo(model, params, short)
    assert list(long.tokens) == _solo(model, params, long)


def test_swap_resets_prefix_pool(tiny):
    """A checkpoint hot-swap invalidates every published prefix (the
    pool holds OLD-weights K/V): the trie must come back empty."""
    from torchpruner_tpu.serve import vocab_of

    model, params = tiny
    vocab = vocab_of(model)
    eng = _engine(model, params, prefix_pages=8, prefill_chunk=8)
    reqs = _shared_reqs(vocab, n=3)
    _serve(eng, reqs)
    alloc = eng.scheduler.allocator
    assert alloc.prefix_pool_used > 0
    alloc.reset_prefix()
    assert alloc.prefix_pool_used == 0 and alloc.shared_pages == 0
    # and the pool is re-usable after the reset
    reqs2 = _shared_reqs(vocab, n=3)
    toks2 = _serve(eng, reqs2)
    for r in reqs2:
        assert toks2[r.id] == _solo(model, params, r)


# -- fleet affinity ----------------------------------------------------------


def _affinity_policy(**kw):
    from torchpruner_tpu.fleet import RouterPolicy

    base = dict(queue_bound=32, max_attempts=6, attempt_timeout_s=5.0,
                default_deadline_s=30.0, base_backoff_s=0.001,
                max_backoff_s=0.01, health_every_s=0.01,
                max_inflight_per_replica=4, affinity_prefix_tokens=8)
    base.update(kw)
    return RouterPolicy(**base)


def _mk_router(tmp_path, reps, **kw):
    from torchpruner_tpu.fleet import FleetRouter, RequestPlane

    plane = RequestPlane(str(tmp_path / "j.json"))
    return FleetRouter(plane, reps, policy=_affinity_policy(**kw))


def _payload(i, session=None, prefix=None):
    ids = (list(prefix) if prefix is not None else []) + [i, i + 1]
    out = {"prompt_ids": ids, "max_new": 2, "eos_id": None,
           "temperature": 0.0, "top_k": None, "top_p": None, "seed": i}
    if session:
        out["session_id"] = session
    return out


def test_session_affinity_routes_repeats_to_same_replica(tmp_path):
    from tests.test_fleet import FakeReplica

    reps = [FakeReplica("replica0"), FakeReplica("replica1")]
    router = _mk_router(tmp_path, reps)
    # sequential same-session requests: after the first completes, all
    # later ones must land on its replica
    served_by = []
    for i in range(6):
        rec = router.submit(_payload(i, session="s1"))
        router.run_until_drained(poll_s=0.002, timeout_s=10.0)
        served_by.append(rec.completed_by)
    assert len(set(served_by[1:])) == 1  # sticky after first contact
    assert router.affinity_preferred_total == 5
    assert router.affinity_hits_total == 5
    assert router.snapshot()["affinity"]["hit_rate"] == 1.0
    router.close()


def test_prefix_affinity_without_session_ids(tmp_path):
    from tests.test_fleet import FakeReplica

    reps = [FakeReplica("replica0"), FakeReplica("replica1")]
    router = _mk_router(tmp_path, reps)
    prefix = list(range(100, 108))  # >= affinity_prefix_tokens
    served_by = []
    for i in range(4):
        rec = router.submit(_payload(i, prefix=prefix))
        router.run_until_drained(poll_s=0.002, timeout_s=10.0)
        served_by.append(rec.completed_by)
    assert len(set(served_by[1:])) == 1
    assert router.affinity_hits_total == 3
    # a DIFFERENT leading chunk carries no preference
    rec = router.submit(_payload(9, prefix=list(range(200, 208))))
    router.run_until_drained(poll_s=0.002, timeout_s=10.0)
    assert router.affinity_preferred_total == 3  # unchanged
    router.close()


def test_affinity_forgotten_on_failover(tmp_path):
    """Keys pointing at a dead replica are dropped: the session's next
    request routes by load (no preference), completes on the survivor,
    and re-registers there."""
    from tests.test_fleet import FakeReplica

    reps = [FakeReplica("replica0", die_after=2),
            FakeReplica("replica1", state="draining")]
    router = _mk_router(tmp_path, reps)
    for i in range(2):
        router.submit(_payload(i, session="s1"))
        router.run_until_drained(poll_s=0.002, timeout_s=10.0)
    assert router.affinity_hits_total == 1
    preferred_before = router.affinity_preferred_total
    reps[1].state = "ready"   # survivor becomes routable
    rec = router.submit(_payload(7, session="s1"))  # kills replica0
    router.run_until_drained(poll_s=0.002, timeout_s=10.0)
    assert rec.state == "completed"
    assert rec.completed_by == "replica1"
    assert len(router.affinity) >= 1
    with router._lock:
        assert router.affinity.preferred(
            _payload(8, session="s1")) == "replica1"
    assert router.failovers_total == 1
    assert preferred_before < router.affinity_preferred_total
    router.close()


def test_affinity_is_hint_not_constraint(tmp_path):
    """An unusable preferred replica (draining) falls back to least-
    loaded — affinity must never stall dispatch."""
    from tests.test_fleet import FakeReplica

    reps = [FakeReplica("replica0"), FakeReplica("replica1")]
    router = _mk_router(tmp_path, reps)
    rec0 = router.submit(_payload(0, session="s1"))
    router.run_until_drained(poll_s=0.002, timeout_s=10.0)
    home = rec0.completed_by
    other = {"replica0": reps[1], "replica1": reps[0]}[home]
    dict(replica0=reps[0], replica1=reps[1])[home].state = "draining"
    router.check_health(force=True)
    rec = router.submit(_payload(1, session="s1"))
    router.run_until_drained(poll_s=0.002, timeout_s=10.0)
    assert rec.state == "completed"
    assert rec.completed_by == other.name  # fell back, didn't stall
    # the MISS is counted (preferred yes, hit no)
    snap = router.snapshot()["affinity"]
    assert snap["preferred"] == 1 and snap["hits"] == 0
    router.close()


def test_affinity_registry_lru_bounded(tmp_path):
    from torchpruner_tpu.fleet.router import PrefixAffinity

    aff = PrefixAffinity(prefix_tokens=4, max_keys=3)
    for i in range(5):
        aff.note({"session_id": f"s{i}", "prompt_ids": []}, "replica0")
    assert len(aff) == 3
    assert aff.preferred({"session_id": "s0", "prompt_ids": []}) is None
    assert aff.preferred({"session_id": "s4",
                          "prompt_ids": []}) == "replica0"
    # prefix_tokens=0 disables ALL affinity keys
    off = PrefixAffinity(prefix_tokens=0)
    off.note({"session_id": "s", "prompt_ids": list(range(9))}, "r0")
    assert len(off) == 0

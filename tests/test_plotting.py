"""Figure-machinery smoke tests (the reference ships plot helpers,
reference experiments/utils/utils.py:77-113): render each figure to a
file and check structure, not pixels."""

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")

from torchpruner_tpu.experiments.prune_retrain import PruneStepRecord
from torchpruner_tpu.utils.plotting import (
    METHOD_STYLE,
    method_style,
    plot_auc_summary,
    plot_prune_history,
    plot_robustness_curves,
)


def _fake_results(n_units=6):
    def run(seed):
        rng = np.random.default_rng(seed)
        return {
            "loss": np.cumsum(rng.random(n_units) * 0.1) + 0.5,
            "acc": np.linspace(0.9, 0.3, n_units),
            "base_loss": 0.5,
            "base_acc": 0.9,
            "auc": float(rng.random()),
            "scores": rng.random(n_units),
            "seconds": 0.1,
        }

    return {
        "conv1": {
            "sv": [run(0), run(1)],      # stochastic: band
            "taylor": [run(2)],
            "unknown_method": [run(3)],  # falls back to neutral style
        }
    }


def test_method_style_fixed_assignment():
    # color follows the method — the full 8-method panel is covered and
    # assignments are unique
    colors = [c for _, c in METHOD_STYLE.values()]
    assert len(set(colors)) == len(colors) == 8
    assert method_style("sv")[1] == METHOD_STYLE["sv"][1]
    assert method_style("nope")[0] == "nope"


def test_plot_robustness_curves(tmp_path):
    out = tmp_path / "curves.png"
    fig = plot_robustness_curves(_fake_results(), "conv1",
                                 save_path=str(out))
    assert out.stat().st_size > 0
    ax = fig.axes[0]
    # 3 method lines + baseline dashed line
    assert len(ax.lines) == 4
    assert ax.get_legend() is not None


def test_plot_auc_summary(tmp_path):
    out = tmp_path / "auc.png"
    aucs = {"sv": 0.35, "taylor": 0.47, "apoz": 0.56}
    fig = plot_auc_summary(aucs, reference={"sv": 0.31},
                           save_path=str(out))
    assert out.stat().st_size > 0
    assert len(fig.axes[0].patches) == 3  # one bar per method


def test_plot_prune_history(tmp_path):
    recs = [
        PruneStepRecord(layer=f"fc{i}", pre_loss=1.0, pre_acc=0.1 * i,
                        post_loss=0.9, post_acc=0.1 * i + 0.05,
                        n_params=1000 - 100 * i, n_dropped=10,
                        prune_time=1.0, widths={})
        for i in range(3)
    ]
    out = tmp_path / "hist.png"
    fig = plot_prune_history(recs, save_path=str(out))
    assert out.stat().st_size > 0
    assert len(fig.axes) == 2  # two single-axis panels, no dual axis

"""Bench harness mechanics (no real measurement): the per-leg partial
record that makes a killed child salvageable, and the shared null-result
skeleton."""

import json
import os
import sys

import pytest


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench as mod

    monkeypatch.setattr(mod, "PARTIAL_PATH",
                        str(tmp_path / "partial.json"))
    return mod


def test_partial_record_written_after_every_leg(bench, monkeypatch):
    """main() must persist finished legs as it goes (atomic replace), so
    a child killed mid-run leaves the completed measurements on disk."""
    calls, disk_at_call = [], []

    def stub(name, value):
        def leg(smoke):
            # snapshot what the salvage file held when this leg STARTED
            # (assertions must happen outside: run_leg catches exceptions)
            disk_at_call.append(
                list(json.load(open(bench.PARTIAL_PATH))["legs"])
                if os.path.exists(bench.PARTIAL_PATH) else None
            )
            calls.append(name)
            return {"value": value, "unit": "s", "vs_baseline": 1.0}
        return leg

    monkeypatch.setattr(bench, "_leg_mnist", stub("mnist_prune", 1.0))
    monkeypatch.setattr(bench, "_leg_llama_decode",
                        stub("llama_decode", 2.0))
    monkeypatch.setattr(sys, "argv", ["bench.py", "--run", "--cpu", "--no-cache"])
    out = bench.main()
    assert calls == ["mnist_prune", "llama_decode"]
    # the second leg saw the first leg's record already persisted
    assert disk_at_call == [None, ["mnist_prune"]]
    part = json.load(open(bench.PARTIAL_PATH))
    assert list(part["legs"]) == calls
    assert part["platform"] == "cpu"
    assert out["legs"]["mnist_prune"]["value"] == 1.0
    assert not os.path.exists(bench.PARTIAL_PATH + ".tmp")


def test_partial_record_skipped_in_smoke_mode(bench, monkeypatch):
    leg = lambda smoke: {"value": 1, "unit": "s", "vs_baseline": 1.0,
                         "mfu": 0.1, "img_per_s_per_chip": 1.0}
    monkeypatch.setattr(bench, "_leg_mnist", leg)
    for name in ("_leg_vgg_robustness", "_leg_vgg_train",
                 "_leg_flash_attention", "_leg_llama_decode",
                 "_leg_mfu_llama"):
        monkeypatch.setattr(bench, name, leg)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--run", "--cpu",
                                      "--smoke", "--no-cache"])
    bench.main()
    assert not os.path.exists(bench.PARTIAL_PATH)


def test_null_result_skeleton(bench):
    r = bench._null_result(error="x", attempts=[1])
    assert r["metric"] == "mnist_fc_shapley_prune_wall_clock"
    assert r["value"] is None and r["vs_baseline"] is None
    assert r["error"] == "x" and r["attempts"] == [1]

"""Bench harness mechanics (no real measurement): the per-leg partial
record and streamed snapshots that make a killed child salvageable, the
budget guard, the TPU-cache merge, and the shared null-result skeleton."""

import json
import os
import subprocess
import sys
import time

import pytest


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench as mod

    monkeypatch.setattr(mod, "PARTIAL_PATH",
                        str(tmp_path / "partial.json"))
    # the chaos-drill leg runs on every platform; stub it so harness-
    # mechanics tests don't spend ~15 s per test actually killing and
    # resuming subprocesses (tests/test_resilience.py owns the real leg)
    monkeypatch.setattr(mod, "_leg_resilience",
                        lambda smoke: {"value": 0.1, "unit": "s"})
    # likewise the serving leg (tests/test_serve.py owns the real engine)
    monkeypatch.setattr(mod, "_leg_serve",
                        lambda smoke, progress=None:
                        {"value": 0.1, "unit": "s"})
    # and the planner search leg (tests/test_planner.py owns the real
    # search — in-process it compiles ~25 candidate programs)
    monkeypatch.setattr(mod, "_leg_plan",
                        lambda smoke: {"value": 0.1, "unit": "s"})
    # and the sparsity-search campaign leg (tests/test_search.py owns
    # the real driver — it spawns worker subprocesses)
    monkeypatch.setattr(mod, "_leg_search",
                        lambda smoke: {"value": 0.1, "unit": "s"})
    # and the fleet failover drill (tests/test_fleet.py owns the real
    # kill -9 drill — it spawns 3 replica subprocesses)
    monkeypatch.setattr(mod, "_leg_fleet",
                        lambda smoke: {"value": 0.1, "unit": "s"})
    return mod


def test_partial_record_written_after_every_leg(bench, monkeypatch):
    """main() must persist finished legs as it goes (atomic replace), so
    a child killed mid-run leaves the completed measurements on disk."""
    calls, disk_at_call = [], []

    def stub(name, value):
        def leg(smoke):
            # snapshot what the salvage file held when this leg STARTED
            # (assertions must happen outside: run_leg catches exceptions)
            disk_at_call.append(
                list(json.load(open(bench.PARTIAL_PATH))["legs"])
                if os.path.exists(bench.PARTIAL_PATH) else None
            )
            calls.append(name)
            return {"value": value, "unit": "s", "vs_baseline": 1.0}
        return leg

    monkeypatch.setattr(bench, "_leg_mnist", stub("mnist_prune", 1.0))
    monkeypatch.setattr(bench, "_leg_resilience", stub("resilience", 0.5))
    monkeypatch.setattr(bench, "_leg_plan", stub("plan", 0.7))
    monkeypatch.setattr(bench, "_leg_llama_decode",
                        stub("llama_decode", 2.0))
    monkeypatch.setattr(bench, "_leg_serve", stub("serve", 3.0))
    monkeypatch.setattr(bench, "_leg_search", stub("search", 0.9))
    monkeypatch.setattr(bench, "_leg_fleet", stub("fleet", 0.8))
    monkeypatch.setattr(sys, "argv", ["bench.py", "--run", "--cpu", "--no-cache"])
    out = bench.main()
    assert calls == ["mnist_prune", "resilience", "plan", "search",
                     "llama_decode", "serve", "fleet"]
    # each later leg saw the earlier legs' records already persisted
    assert disk_at_call == [None, ["mnist_prune"],
                            ["mnist_prune", "resilience"],
                            ["mnist_prune", "resilience", "plan"],
                            ["mnist_prune", "resilience", "plan",
                             "search"],
                            ["mnist_prune", "resilience", "plan",
                             "search", "llama_decode"],
                            ["mnist_prune", "resilience", "plan",
                             "search", "llama_decode", "serve"]]
    part = json.load(open(bench.PARTIAL_PATH))
    assert list(part["legs"]) == calls
    assert part["platform"] == "cpu"
    assert out["legs"]["mnist_prune"]["value"] == 1.0
    assert not os.path.exists(bench.PARTIAL_PATH + ".tmp")


def test_partial_record_skipped_in_smoke_mode(bench, monkeypatch):
    leg = lambda smoke: {"value": 1, "unit": "s", "vs_baseline": 1.0,
                         "mfu": 0.1, "img_per_s_per_chip": 1.0}
    monkeypatch.setattr(bench, "_leg_mnist", leg)
    for name in ("_leg_vgg_robustness", "_leg_vgg_train",
                 "_leg_flash_attention", "_leg_llama_decode",
                 "_leg_mfu_llama", "_leg_serve"):
        monkeypatch.setattr(bench, name, leg)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--run", "--cpu",
                                      "--smoke", "--no-cache"])
    bench.main()
    assert not os.path.exists(bench.PARTIAL_PATH)


def test_null_result_skeleton(bench):
    r = bench._null_result(error="x", attempts=[1])
    assert r["metric"] == "mnist_fc_shapley_prune_wall_clock"
    assert r["value"] is None and r["vs_baseline"] is None
    assert r["error"] == "x" and r["attempts"] == [1]


def test_snapshot_streamed_after_every_leg(bench, monkeypatch, capsys):
    """Round-3 fix: main() must PRINT a complete, driver-parseable result
    snapshot after each leg (the orchestrator forwards them live, so a
    driver kill keeps everything already finished)."""
    leg = lambda smoke: {"value": 1.5, "unit": "s", "vs_baseline": 2.0}
    monkeypatch.setattr(bench, "_leg_mnist", leg)
    monkeypatch.setattr(bench, "_leg_llama_decode", leg)
    monkeypatch.setattr(bench, "_leg_serve", leg)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--run", "--cpu",
                                      "--no-cache"])
    monkeypatch.delenv("BENCH_DEADLINE_TS", raising=False)
    out = bench.main()
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    snaps = [json.loads(ln) for ln in lines]
    # one per leg (mnist, resilience, plan, search, decode, serve,
    # fleet)
    assert len(snaps) == 7
    for snap in snaps:
        assert snap["stream"] == "in_progress"
        assert {"metric", "value", "unit", "vs_baseline", "legs"} <= set(snap)
    # the first snapshot already carries the finished headline leg
    assert snaps[0]["metric"] == "mnist_fc_shapley_prune_wall_clock"
    assert snaps[0]["value"] == 1.5
    assert list(snaps[-1]["legs"]) == ["mnist_prune", "resilience",
                                       "plan", "search", "llama_decode",
                                       "serve", "fleet"]
    assert out["value"] == 1.5 and "stream" not in out


def test_budget_guard_skips_unfinishable_legs(bench, monkeypatch, capsys):
    """With an orchestrator deadline too close, legs are SKIPPED with a
    reason instead of being started and killed mid-measurement."""
    ran = []
    leg = lambda smoke: ran.append(1) or {"value": 1, "unit": "s"}
    monkeypatch.setattr(bench, "_leg_mnist", leg)
    monkeypatch.setattr(bench, "_leg_llama_decode", leg)
    monkeypatch.setattr(bench, "_leg_serve", leg)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--run", "--cpu",
                                      "--no-cache"])
    monkeypatch.setenv("BENCH_DEADLINE_TS", str(time.time() + 5.0))
    out = bench.main()
    assert ran == []
    assert "budget" in out["legs"]["mnist_prune"]["skipped"]
    assert "budget" in out["legs"]["resilience"]["skipped"]
    assert "budget" in out["legs"]["plan"]["skipped"]
    assert "budget" in out["legs"]["search"]["skipped"]
    assert "budget" in out["legs"]["llama_decode"]["skipped"]
    assert "budget" in out["legs"]["serve"]["skipped"]
    assert "budget" in out["legs"]["fleet"]["skipped"]
    assert out["value"] is None  # skipped legs never fake a headline
    # ...but the skip decisions themselves were streamed
    snaps = [json.loads(ln)
             for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(snaps) == 7


def test_leg_progress_checkpoints_are_streamed(bench, monkeypatch, capsys):
    """A leg that accepts ``progress`` (the multi-hour sweep) checkpoints
    itself: each call streams an in_progress snapshot, the in_progress
    entry never becomes the headline, and the final return replaces it."""

    def sweep_leg(smoke, progress=None):
        progress({"value": None, "unit": "s", "layers_done": 1})
        progress({"value": None, "unit": "s", "layers_done": 2})
        return {"value": 1.5, "unit": "s", "vs_baseline": 18.7}

    monkeypatch.setattr(bench, "_leg_mnist", sweep_leg)
    monkeypatch.setattr(bench, "_leg_llama_decode",
                        lambda smoke: {"value": 2.0, "unit": "s"})
    monkeypatch.setattr(sys, "argv", ["bench.py", "--run", "--cpu",
                                      "--no-cache"])
    monkeypatch.delenv("BENCH_DEADLINE_TS", raising=False)
    out = bench.main()
    snaps = [json.loads(ln)
             for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    prog = [s for s in snaps
            if s["legs"].get("mnist_prune", {}).get("in_progress")]
    assert [p["legs"]["mnist_prune"]["layers_done"] for p in prog] == [1, 2]
    # an unfinished headline leg must not fake a headline measurement
    for p in prog:
        assert p["value"] is None
    assert out["value"] == 1.5
    assert "in_progress" not in out["legs"]["mnist_prune"]


def test_leg_crash_keeps_checkpointed_progress(bench, monkeypatch, capsys):
    """A crash late in a checkpointing leg merges the error INTO the
    in_progress partial instead of discarding the finished layers."""

    def crashing_sweep(smoke, progress=None):
        progress({"value": None, "unit": "s", "layers_done": 12,
                  "auc_so_far": {"sv": 0.3}})
        raise RuntimeError("oom at layer 13")

    monkeypatch.setattr(bench, "_leg_mnist", crashing_sweep)
    monkeypatch.setattr(bench, "_leg_llama_decode",
                        lambda smoke: {"value": 2.0, "unit": "s"})
    monkeypatch.setattr(sys, "argv", ["bench.py", "--run", "--cpu",
                                      "--no-cache"])
    monkeypatch.delenv("BENCH_DEADLINE_TS", raising=False)
    out = bench.main()
    leg = out["legs"]["mnist_prune"]
    assert "oom at layer 13" in leg["error"]
    assert leg["layers_done"] == 12 and leg["auc_so_far"] == {"sv": 0.3}
    assert "in_progress" not in leg  # the entry is final, not running


def test_assemble_headline_prefers_sweep_and_names_dataset(bench):
    """The sweep headline metric carries the digits32 caveat in its NAME
    (advisor round-3: cross-dataset vs_baseline must not be quotable
    without the caveat)."""
    legs = {
        "mnist_prune": {"value": 10.0, "unit": "s", "vs_baseline": 2.8},
        "vgg16_robustness": {"value": 900.0, "unit": "s",
                             "vs_baseline": 12.0},
    }
    out = bench._assemble(legs, "tpu", "TPU v5 lite", None, smoke=False)
    assert out["metric"] == "vgg16_layerwise_sweep_digits32_wall_clock"
    assert out["value"] == 900.0
    # an errored sweep leg falls back to the MNIST headline
    legs["vgg16_robustness"] = {"error": "boom"}
    out = bench._assemble(legs, "tpu", "TPU v5 lite", None, smoke=False)
    assert out["metric"] == "mnist_fc_shapley_prune_wall_clock"


def test_stream_child_forwards_snapshots_live(bench, capsys):
    """_stream_child re-prints each child JSON line as it appears and
    returns the last one; non-JSON noise lines are passed over."""
    prog = ("import json,sys\n"
            "print('noise')\n"
            "print(json.dumps({'metric':'m','value':1}))\n"
            "print(json.dumps({'metric':'m','value':2}))\n")
    seen = []

    def enrich(c):
        seen.append(c["value"])
        c["enriched"] = True
        return c

    rc, last, _err = bench._stream_child([sys.executable, "-c", prog], 60.0,
                                         enrich)
    assert rc == 0 and last["value"] == 2 and last["enriched"]
    assert seen == [1, 2]
    lines = [json.loads(ln)
             for ln in capsys.readouterr().out.splitlines() if ln.strip()
             and ln.startswith("{")]
    assert [ln["value"] for ln in lines] == [1, 2]


def test_stream_child_kills_on_timeout(bench):
    prog = ("import json,sys,time\n"
            "print('progress line', file=sys.stderr, flush=True)\n"
            "print(json.dumps({'metric':'m','value':1}), flush=True)\n"
            "time.sleep(60)\n")
    t0 = time.time()
    # 8 s pre-kill budget: interpreter startup on the loaded 1-core box
    # can exceed 2 s, and the snapshot must get out before the kill
    rc, last, err = bench._stream_child([sys.executable, "-c", prog], 8.0,
                                        lambda c: c)
    assert time.time() - t0 < 30
    assert rc == -1
    assert last["value"] == 1  # the pre-kill snapshot survives
    assert "progress line" in err  # stderr tail captured for attempts[]


def test_write_tpu_cache_carries_forward_missing_legs(bench, monkeypatch,
                                                      tmp_path):
    """A budget-capped TPU run that skipped the expensive sweep must not
    erase a previously-cached sweep measurement — it is carried forward
    with the commit/timestamp it was measured at."""
    cache = tmp_path / "tpu_cache.json"
    monkeypatch.setattr(bench, "TPU_CACHE", str(cache))
    old = {"measured_at": "2026-07-29T00:00:00Z", "git_commit": "oldc",
           "result": {"legs": {
               "vgg16_robustness": {"value": 1558.1, "unit": "s"},
               "mnist_prune": {"value": 15.2, "unit": "s"},
           }}}
    cache.write_text(json.dumps(old))
    new = {"metric": "mnist_fc_shapley_prune_wall_clock", "value": 12.0,
           "unit": "s", "platform": "tpu",
           "legs": {"mnist_prune": {"value": 12.0, "unit": "s"},
                    "vgg16_robustness": {"skipped": "budget"}}}
    bench._write_tpu_cache(new)
    written = json.loads(cache.read_text())
    legs = written["result"]["legs"]
    # fresh leg wins; skipped leg replaced by the carried measurement
    assert legs["mnist_prune"]["value"] == 12.0
    assert "carried_from" not in legs["mnist_prune"]
    assert legs["vgg16_robustness"]["value"] == 1558.1
    assert legs["vgg16_robustness"]["carried_from"]["git_commit"] == "oldc"


def test_merge_keeps_current_errors_on_the_print_path(bench, monkeypatch,
                                                      tmp_path):
    """replace_errors=False (the PRINTED-result path) must keep a leg
    that errored THIS run visible instead of papering over the
    regression with a stale cached success; the default (cache-file)
    path stays last-known-good."""
    cache = tmp_path / "tpu_cache.json"
    monkeypatch.setattr(bench, "TPU_CACHE", str(cache))
    cache.write_text(json.dumps({
        "measured_at": "2026-07-29T00:00:00Z", "git_commit": "oldc",
        "result": {"legs": {
            "flash_attention": {"flash_ms": 73.7, "xla_ms": 72.1},
        }}}))
    current = {"flash_attention": {"error": "Pallas lowering failed"},
               "mnist_prune": {"value": 3.3, "unit": "s"}}
    printed = bench._merge_cached_legs(dict(current), replace_errors=False)
    assert printed["flash_attention"] == {"error": "Pallas lowering failed"}
    cached = bench._merge_cached_legs(dict(current))
    assert cached["flash_attention"]["flash_ms"] == 73.7
    assert cached["flash_attention"]["carried_from"]["git_commit"] == "oldc"


def test_orchestrate_prints_boot_line_first(bench, monkeypatch, capsys):
    """The orchestrator's FIRST act is printing a parseable skeleton, so
    a driver kill during preflight still leaves `parsed != null`."""
    monkeypatch.setattr(sys, "argv", ["bench.py", "--cpu", "--no-cache"])
    monkeypatch.delenv("BENCH_DEADLINE_TS", raising=False)
    final = {"metric": "mnist_fc_shapley_prune_wall_clock", "value": 3.0,
             "unit": "s", "vs_baseline": 9.3, "platform": "cpu", "legs": {}}

    def fake_stream(cmd, timeout_s, enrich):
        print(json.dumps(enrich(dict(final, stream="in_progress"))),
              flush=True)
        return 0, dict(final), ""

    monkeypatch.setattr(bench, "_stream_child", fake_stream)
    out = bench.orchestrate()
    lines = [json.loads(ln)
             for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert lines[0]["stream"] == "starting"
    assert lines[0]["metric"] == "mnist_fc_shapley_prune_wall_clock"
    assert lines[0]["value"] is None
    assert out["value"] == 3.0 and "stream" not in out


def test_robustness_leg_resumes_across_kills(bench, monkeypatch, tmp_path):
    """The multi-hour sweep leg must survive tunnel windows shorter than
    itself: a kill after layer 1 leaves trained weights + that layer on
    disk, and the rerun continues from layer 2 instead of starting over,
    deleting the scratch once the sweep completes."""
    import torchpruner_tpu.core.graph as G
    import torchpruner_tpu.models as M

    real_vgg, real_graph = M.vgg16_bn, G.pruning_graph
    monkeypatch.setattr(
        M, "vgg16_bn",
        lambda **kw: real_vgg(width_multiplier=0.125, classifier_width=64))
    # 3 layers keep the test's sweep minutes-scale, exercising the same
    # resume arithmetic as the 15-layer run
    monkeypatch.setattr(G, "pruning_graph", lambda m: real_graph(m)[:3])
    monkeypatch.setenv("BENCH_ROBUSTNESS_EXAMPLES", "16")
    resume = tmp_path / "resume.pkl"
    monkeypatch.setattr(bench, "ROBUSTNESS_RESUME", str(resume))

    class Wedge(Exception):
        pass

    seen = []

    def killer(partial):
        seen.append(partial)
        raise Wedge()  # simulate the tunnel dying right after layer 1

    with pytest.raises(Wedge):
        bench._leg_vgg_robustness(False, progress=killer)
    assert resume.exists()  # trained weights + layer 1 checkpointed
    assert seen[0]["layers_done"] == 1

    r = bench._leg_vgg_robustness(False, progress=lambda p: None)
    assert r["resumed_layers"] == 1
    assert r["n_layers"] == 3
    assert r["projection"] is None
    assert not resume.exists()  # complete: scratch cleared

"""Mixture-of-experts tests: routing semantics, expert pruning (graph,
surgery, attribution), and expert parallelism on the 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import PartitionSpec as P

import torchpruner_tpu as tp
from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.graph import group_for, pruning_graph
from torchpruner_tpu.core.pruner import prune
from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.models import llama_moe_tiny
from torchpruner_tpu.parallel import ShardedTrainer, make_mesh, tp_specs
from torchpruner_tpu.utils.losses import lm_cross_entropy_loss


def moe_net(n_experts=4, top_k=2):
    """Flat Dense -> MoE -> head net for unit-level checks."""
    return SegmentedModel(
        layers=(
            L.Embedding("emb", 32, 16),
            L.MoE("moe", n_experts, 24, top_k=top_k),
            L.GlobalPool("pool", "seq_mean"),
            L.Dense("head", 5),
        ),
        input_shape=(8,),
        input_dtype="int32",
    )


def test_moe_forward_and_gate_sparsity():
    model = moe_net()
    params, state = init_model(model, seed=0)
    x = model.example_input(3)
    y, _, gates = model.apply(params, x, state=state, capture="moe")
    assert y.shape == (3, 5)
    assert gates.shape == (3, 8, 4)
    # top-2 of 4: exactly 2 nonzero gates per token, summing to 1
    nz = np.asarray((gates > 0).sum(axis=-1))
    np.testing.assert_array_equal(nz, np.full((3, 8), 2))
    np.testing.assert_allclose(np.asarray(gates.sum(-1)), 1.0, atol=1e-6)


def test_moe_aux_loss_collection_and_balance_floor():
    """collect_aux returns the Switch-style load-balancing loss per MoE
    layer: >= ~1 (1.0 = perfectly balanced dispatch), collected only
    during training forwards."""
    model = moe_net()
    params, state = init_model(model, seed=0)
    x = model.example_input(4)
    _, _, aux = model.apply(params, x, state=state, train=True,
                            collect_aux=True,
                            rng=jax.random.PRNGKey(0))
    assert set(aux) == {"moe"}
    val = float(aux["moe"])
    assert np.isfinite(val) and val >= 0.99
    # eval forwards collect nothing (no balancing term at test time)
    _, _, aux_eval = model.apply(params, x, state=state, train=False,
                                 collect_aux=True)
    assert aux_eval == {}


def test_moe_dense_routing_collects_no_aux():
    """With top_k == n_experts the balancing loss is a gradient-free
    constant 1.0 — collecting it would make moe_aux_weight>0 a silent
    no-op, so dense routing must skip aux collection entirely."""
    model = moe_net(n_experts=4, top_k=4)
    params, state = init_model(model, seed=0)
    x = model.example_input(4)
    _, _, aux = model.apply(params, x, state=state, train=True,
                            collect_aux=True,
                            rng=jax.random.PRNGKey(0))
    assert aux == {}


def test_moe_aux_weight_in_training_loss():
    """A Trainer with moe_aux_weight adds weight x aux to the step loss;
    the remat path must carry the aux through jax.checkpoint (same value
    as the unremat step)."""
    from torchpruner_tpu.train import Trainer

    toks = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 256), np.int32
    )

    def first_loss(**kw):
        t = Trainer.create(llama_moe_tiny(), optax.adam(1e-3),
                           lm_cross_entropy_loss, seed=0, **kw)
        return float(t.step(toks, toks))

    base = first_loss()
    with_aux = first_loss(moe_aux_weight=0.5)
    assert with_aux > base + 0.4  # aux >= ~1, so +0.5 x aux >= ~0.5
    remat_aux = first_loss(moe_aux_weight=0.5, remat=True)
    np.testing.assert_allclose(with_aux, remat_aux, rtol=1e-5)


def test_moe_top1_and_dense_routing():
    for k, n in ((1, 4), (4, 4)):
        model = moe_net(top_k=k)
        params, state = init_model(model, seed=1)
        _, _, gates = model.apply(
            params, model.example_input(2), state=state, capture="moe"
        )
        nz = np.asarray((gates > 1e-9).sum(axis=-1))
        assert nz.max() <= max(k, 1) or k == 4


def test_moe_prune_group_and_surgery():
    model = moe_net()
    params, state = init_model(model, seed=0)
    g = group_for(model, "moe")
    assert g.consumers == ()  # self-contained expert group
    res = prune(model, params, "moe", [1, 3], state=state)
    spec = res.model.layer("moe")
    assert spec.n_experts == 2 and spec.top_k == 2
    p = res.params["moe"]
    assert p["router"].shape == (16, 2)
    assert p["wg"].shape == (2, 16, 24)
    assert p["wo"].shape == (2, 24, 16)
    y, _ = res.model.apply(res.params, model.example_input(2), state=res.state)
    assert y.shape == (2, 5) and np.all(np.isfinite(np.asarray(y)))


def test_moe_expert_attribution():
    model = moe_net()
    params, state = init_model(model, seed=0)
    x = model.example_input(8)
    y = np.zeros((8,), np.int32)
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    data = [(x, jnp.asarray(y))]
    for cls in (tp.TaylorAttributionMetric, tp.APoZAttributionMetric,
                tp.WeightNormAttributionMetric):
        scores = cls(model, params, data, cross_entropy_loss,
                     state=state).run("moe")
        assert scores.shape == (4,)
    sv = tp.ShapleyAttributionMetric(
        model, params, data, cross_entropy_loss, state=state, sv_samples=2
    ).run("moe")
    assert sv.shape == (4,)


def test_moe_in_llama_blocks_pruning_graph():
    model = llama_moe_tiny()
    targets = [g.target for g in pruning_graph(model)]
    assert "block1_moe/experts" in targets
    assert "block1_attn/attn" in targets
    params, state = init_model(model, seed=0)
    res = prune(model, params, "block2_moe/experts", [0], state=state)
    assert res.model.layer("block2_moe/experts").n_experts == 3
    x = model.example_input(2)
    yv, _ = res.model.apply(res.params, x, state=res.state)
    assert np.all(np.isfinite(np.asarray(yv)))


def test_expert_parallel_sharding_and_step():
    mesh = make_mesh({"data": 2, "model": 4})
    specs = tp_specs(llama_moe_tiny(), mesh)
    assert specs[("block1_moe/experts", "wg")] == P("model", None, None)
    assert specs[("block1_moe/experts", "router")] == P(None, "model")
    t = ShardedTrainer.create(
        llama_moe_tiny(), optax.adam(1e-3), lm_cross_entropy_loss, mesh,
        seed=0, min_shard_size=0, partition="tp",
    )
    assert t.params["block1_moe"]["experts"]["wg"].sharding.spec == P(
        "model", None, None
    )
    x = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 256), np.int32
    )
    l0 = float(t.step(x, x))
    # prune an expert, reshard (3 experts no longer divide 4 -> fallback),
    # step again
    r = prune(t.model, t.params, "block1_moe/experts", [2],
              state=t.state, opt_state=t.opt_state)
    t = t.rebuild(r.model, r.params, r.state, r.opt_state)
    l1 = float(t.step(x, x))
    assert np.isfinite(l0) and np.isfinite(l1)


def test_moe_dead_expert_prune_leaves_output_unchanged():
    """Pruning an expert that never wins the top-k leaves every output
    bit-equal — the surgery-correctness invariant for expert pruning.  A
    dead expert is *forced* deterministically by pushing one router column
    to -1e9 (it can then never be selected, so its gate is exactly 0)."""
    model = moe_net(n_experts=4, top_k=2)
    params, state = init_model(model, seed=3)
    dead = 2
    # positive embeddings + a large negative router column ⇒ the dead
    # expert's logit is always far below every other (x @ col is sign-
    # definite only because every embedding entry is positive)
    params["emb"]["emb"] = jnp.abs(params["emb"]["emb"]) + 0.1
    params["moe"]["router"] = (
        params["moe"]["router"].at[:, dead].set(-1e3)
    )
    x = model.example_input(4, seed=7)
    _, _, gates = model.apply(params, x, state=state, capture="moe")
    assert float(np.asarray(gates[..., dead]).max()) == 0.0
    y0, _ = model.apply(params, x, state=state)
    res = prune(model, params, "moe", [dead], state=state)
    y1, _ = res.model.apply(res.params, x, state=res.state)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), atol=1e-6)


def test_producer_feeding_moe_or_untied_attention_is_pinned():
    """A producer whose consumer's output width follows its input width
    (MoE; attention with out_features=None) cannot cascade — its group is
    dropped, like producers feeding residual sums."""
    base = dict(input_shape=(8,), input_dtype="int32")
    pinned = SegmentedModel(layers=(
        L.Embedding("emb", 32, 16),
        L.Dense("fc", 16),
        L.MoE("moe", 4, 24),
        L.GlobalPool("pool", "seq_mean"),
        L.Dense("head", 5),
    ), **base)
    targets = [g.target for g in pruning_graph(pinned)]
    assert "fc" not in targets and "moe" in targets

    pinned2 = SegmentedModel(layers=(
        L.Embedding("emb", 32, 16),
        L.Dense("fc", 16),
        L.MultiHeadAttention("attn", 4, 4),  # out_features=None: tied
        L.GlobalPool("pool", "seq_mean"),
        L.Dense("head", 5),
    ), **base)
    assert "fc" not in [g.target for g in pruning_graph(pinned2)]

    free = SegmentedModel(layers=(
        L.Embedding("emb", 32, 16),
        L.Dense("fc", 16),
        L.MultiHeadAttention("attn", 4, 4, out_features=16),  # pinned out
        L.GlobalPool("pool", "seq_mean"),
        L.Dense("head", 5),
    ), **base)
    g = next(g for g in pruning_graph(free) if g.target == "fc")
    assert {c.param for c in g.consumers} == {"wq", "wk", "wv"}
    # and the surgery is consistent end to end
    params, state = init_model(free, seed=0)
    res = prune(free, params, "fc", [3, 9], state=state)
    y, _ = res.model.apply(res.params, free.example_input(2), state=res.state)
    assert y.shape == (2, 5)


def sparse_moe_net(n_experts=4, top_k=2, capacity_factor=1.25):
    return SegmentedModel(
        layers=(
            L.Embedding("emb", 32, 16),
            L.MoE("moe", n_experts, 24, top_k=top_k, dispatch="sparse",
                  capacity_factor=capacity_factor),
            L.GlobalPool("pool", "seq_mean"),
            L.Dense("head", 5),
        ),
        input_shape=(8,),
        input_dtype="int32",
    )


def test_sparse_dispatch_matches_dense_when_nothing_dropped():
    """With capacity_factor = E/top_k the capacity equals the token count,
    nothing can be dropped, and the sparse gather/scatter formulation must
    reproduce the dense one — outputs AND parameter gradients."""
    E, K = 4, 2
    dense = moe_net(E, K)
    sparse = sparse_moe_net(E, K, capacity_factor=E / K)
    params, state = init_model(dense, seed=0)
    x = dense.example_input(3)
    y_d, _ = dense.apply(params, x, state=state)
    y_s, _ = sparse.apply(params, x, state=state)
    np.testing.assert_allclose(
        np.asarray(y_d), np.asarray(y_s), atol=1e-5
    )

    from torchpruner_tpu.utils.losses import cross_entropy_loss

    yt = jnp.zeros((3,), jnp.int32)

    def loss(model):
        def f(p):
            out, _ = model.apply(p, x, state=state)
            return jnp.mean(cross_entropy_loss(out, yt))
        return f

    g_d = jax.grad(loss(dense))(params)
    g_s = jax.grad(loss(sparse))(params)
    for leaf_d, leaf_s in zip(
        jax.tree_util.tree_leaves(g_d), jax.tree_util.tree_leaves(g_s)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf_d), np.asarray(leaf_s), atol=1e-5
        )


def test_sparse_dispatch_cuts_flops_by_expert_ratio():
    """cost_analysis FLOPs of the MoE block must drop roughly E/top_k x
    (the dense formulation runs every expert on every token)."""
    E, K = 8, 1
    d, F, S = 64, 256, 32

    def net(dispatch):
        return SegmentedModel(
            layers=(
                L.MoE("moe", E, F, top_k=K, dispatch=dispatch,
                      capacity_factor=1.0),
            ),
            input_shape=(S, d),
        )

    dense, sparse = net("dense"), net("sparse")
    params, state = init_model(dense, seed=0)
    x = dense.example_input(4)

    def flops(model):
        from torchpruner_tpu.analysis.cost_model import cost_analysis_dict

        f = jax.jit(lambda p, x_: model.apply(p, x_, state=state)[0])
        # cost_analysis() returns a dict or a [dict] depending on the
        # jax release — the cost model's normalizer absorbs both
        return cost_analysis_dict(f.lower(params, x).compile())["flops"]

    fd, fs = flops(dense), flops(sparse)
    # sparse pays router+sort overhead; demand at least half the ideal 8x
    assert fd / fs > (E / K) / 2, (fd, fs)


def test_sparse_dispatch_ablation_matches_dense():
    """Unit-mask ablation (the attribution instrumentation) must behave
    identically in both formulations: routing comes from pre-tap gates, so
    zeroing one expert's gate can't pollute other experts' capacity."""
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    E, K = 4, 2
    dense = moe_net(E, K)
    sparse = sparse_moe_net(E, K, capacity_factor=E / K)
    params, state = init_model(dense, seed=0)
    x = dense.example_input(4)
    data = [(x, jnp.zeros((4,), jnp.int32))]
    sv_d = tp.ShapleyAttributionMetric(
        dense, params, data, cross_entropy_loss, state=state, sv_samples=3
    ).run("moe")
    sv_s = tp.ShapleyAttributionMetric(
        sparse, params, data, cross_entropy_loss, state=state, sv_samples=3
    ).run("moe")
    np.testing.assert_allclose(sv_d, sv_s, atol=1e-4)


def test_sparse_dispatch_drops_overflow_tokens():
    """With a tiny capacity and a router forced to send every token to one
    expert, over-capacity contributions are zero (GShard drop semantics) and
    the output stays finite."""
    model = sparse_moe_net(4, 1, capacity_factor=0.25)
    params, state = init_model(model, seed=0)
    # every token picks expert 0: its column dominates
    params["moe"]["router"] = (
        jnp.zeros_like(params["moe"]["router"]).at[:, 0].set(1e3)
    )
    params["emb"]["emb"] = jnp.abs(params["emb"]["emb"]) + 0.1
    x = model.example_input(2)
    y, _ = model.apply(params, x, state=state)
    assert np.all(np.isfinite(np.asarray(y)))
    # capacity C = ceil(16 tokens * 1/4 * 0.25) = 1 slot for expert 0; the
    # dense-equivalent (no-drop) output must differ because 15 pairs shed
    dense_equiv = moe_net(4, 1)
    y_d, _ = dense_equiv.apply(params, x, state=state)
    assert not np.allclose(np.asarray(y), np.asarray(y_d), atol=1e-6)


def test_sparse_moe_trains_under_expert_parallel_sharding():
    mesh = make_mesh({"data": 2, "model": 4})
    model = llama_moe_tiny(dispatch="sparse", capacity_factor=2.0)
    t = ShardedTrainer.create(
        model, optax.adam(1e-3), lm_cross_entropy_loss, mesh,
        seed=0, min_shard_size=0, partition="tp",
    )
    x = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 256), np.int32
    )
    l0 = float(t.step(x, x))
    l1 = float(t.step(x, x))
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0


def test_moe_spec_validation():
    with pytest.raises(ValueError):
        L.MoE("m", 4, 8, dispatch="magic")
    with pytest.raises(ValueError):
        L.MoE("m", 4, 8, capacity_factor=0.0)


def test_moe_checkpoint_roundtrip_spec():
    from torchpruner_tpu.checkpoint import spec_from_dict, spec_to_dict

    for m in (llama_moe_tiny(),
              llama_moe_tiny(dispatch="sparse", capacity_factor=2.0)):
        assert spec_from_dict(spec_to_dict(m)) == m

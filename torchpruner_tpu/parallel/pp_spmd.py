"""SPMD pipeline parallelism — collective-based, cross-host capable.

:mod:`~torchpruner_tpu.parallel.pipeline` pipelines *heterogeneous*
stages by pinning each stage's params to a local device and letting
async dispatch overlap them — which is single-process by construction
(a process cannot ``device_put`` onto another host's chips).  This
module is the pods formulation for uniform-block transformer stacks
(the llama, ViT, and BERT families): ONE program runs on every device of a ``pp`` mesh
axis under ``shard_map``; the depth axis of the *stacked* block params
is sharded over ``pp`` (each device holds ``depth // n_stages``
consecutive blocks), microbatches stream through the stages, and
``lax.ppermute`` shifts activations stage→stage.  The permute is an XLA
collective like any other — it rides ICI within a host and DCN across
hosts — so the same compiled step pipelines across processes
(SURVEY.md §2.11's pods north star), with no NCCL-analog code.

Schedule: ONE ``lax.scan`` over ticks implementing the Megatron
interleaved schedule (Narayanan et al., 2021) with ``V = interleave``
virtual stages per device; ``V = 1`` (the default) reduces exactly to
GPipe forward fill/drain (Huang et al., 2019).  Each device holds the
``V`` depth-chunks ``v*S + d`` (``cb = depth/(S·V)`` blocks each) and
the activation ring gains a wrap edge ``S-1 → 0`` so a microbatch
passes every device ``V`` times.  The schedule is diagonal: at tick
``t`` device ``d`` sits on lane ``tt = t - d`` and computes chunk
``v = (tt // S) mod V`` for microbatch ``m = (tt // (S·V))·S + tt % S``
— stage 0 injects when ``v = 0``, the last stage banks when
``v = V-1``.  Bubble fraction: ``(S-1)/(V·M + S-1)`` — interleaving
cuts the GPipe bubble by ``~V`` at the price of ``V×`` the ppermute
traffic.  Gradients need nothing special: the transpose of
``ppermute`` is the reverse permutation, so ``jax.grad`` of the whole
step is pipeline-parallel automatically — activation gradients hop
backwards over the same collective.

Composability: params enter in the model's ordinary pytree layout and
are stacked inside the traced function, so gradient pytrees, optax
states, checkpoints, and the pruner all keep the unstacked layout;
other mesh axes (data, tensor) compose through GSPMD exactly as in
``ShardedTrainer``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def split_pipeline(model: SegmentedModel):
    """``(pre, groups, post)``: the top-level layers before the first
    transformer block, one spec-tuple per block (the repeating unit),
    and the layers after the last block.

    Blocks are recognized by the zoo's ``block{i}_*`` naming: all
    consecutive top-level specs sharing a block index form one group, so
    the repeating unit can be any shape — llama's (attn, ffn) Residual
    pair, ViT's (attn, mlp), BERT's (attn, attn_ln, mlp, mlp_ln) with
    interleaved post-LayerNorms.  Raises if the groups are not uniform
    (stage stacking needs identical param shapes in every block — a
    per-block-pruned or MoE-uneven stack should pipeline with
    :mod:`~torchpruner_tpu.parallel.pipeline` instead), if block indices
    are not contiguous, or if non-block layers interleave the stack.
    """
    pat = re.compile(r"^block(\d+)_(.+)$")
    pre: List[L.LayerSpec] = []
    groups: List[List[L.LayerSpec]] = []
    post: List[L.LayerSpec] = []
    cur_idx = None
    for spec in model.layers:
        m = pat.match(spec.name)
        if m is None:
            if groups:
                post.append(spec)
            else:
                pre.append(spec)
            continue
        if post:
            raise ValueError(
                f"block layer {spec.name} appears after non-block layer "
                f"{post[0].name}: the block stack must be contiguous "
                "for SPMD pipelining")
        idx = int(m.group(1))
        if cur_idx is None or idx == cur_idx + 1:
            groups.append([spec])
            cur_idx = idx
        elif idx == cur_idx:
            groups[-1].append(spec)
        else:
            raise ValueError(
                f"block indices jump at {spec.name} (previous block "
                f"{cur_idx}): the stack must be contiguous")
    if not groups:
        raise ValueError(
            "no block{i}_* layers found — pp_spmd needs a uniform "
            "transformer block stack (llama / ViT / BERT families)")

    def _reject_unsupported(spec):
        if isinstance(spec, L.BatchNorm):
            raise ValueError(
                f"BatchNorm ({spec.name}) carries running state; "
                "cross-microbatch state threading belongs to "
                "parallel.pipeline, not the SPMD formulation")
        if isinstance(spec, L.MoE):
            raise ValueError(
                f"MoE ({spec.name}) emits a load-balancing aux loss this "
                "schedule does not collect — train MoE stacks with "
                "ShardedTrainer (EP) or parallel.pipeline instead")
        for child in (getattr(spec, "body", ()) or ()) + tuple(
                getattr(spec, "shortcut", ()) or ()):
            _reject_unsupported(child)

    for spec in list(pre) + [s for g in groups for s in g] + list(post):
        _reject_unsupported(spec)

    canon = canonical_group(groups[0])
    for g in groups[1:]:
        if canonical_group(g) != canon:
            raise ValueError(
                f"non-uniform blocks ({g[0].name}... differs from "
                f"{groups[0][0].name}...) — stage stacking requires "
                "identical block shapes")
    return tuple(pre), tuple(tuple(g) for g in groups), tuple(post)


def canonical_group(group) -> tuple:
    """The group's specs with block-index-free names (``pp{j}``) — the
    uniformity comparand and the spec set the pipelined stage applies."""
    return tuple(dataclasses.replace(s, name=f"pp{j}")
                 for j, s in enumerate(group))


def stack_block_params(params, groups):
    """Per-leaf ``jnp.stack`` of the blocks' param subtrees along a new
    leading depth axis, keyed by canonical position name (``pp{j}``);
    positions without params (e.g. Activation) are absent, like they are
    in ``params``.  Runs under jit (the stack fuses; under a sharded
    entry the result is resharded by GSPMD per the shard_map in_specs).
    """
    out = {}
    for j, spec in enumerate(groups[0]):
        present = [g[j].name in params for g in groups]
        if not any(present):
            continue
        if not all(present):
            raise ValueError(
                f"block position {j} ({spec.name}) has params in some "
                "blocks but not others")
        trees = [params[g[j].name] for g in groups]
        out[f"pp{j}"] = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *trees)
    return out


def pp_spmd_apply(
    model: SegmentedModel,
    params,
    tokens,
    *,
    mesh: Mesh,
    n_microbatches: int,
    axis: str = "pp",
    data_axis: str | None = None,
    remat: bool = False,
    compute_dtype=None,
    train: bool = False,
    rng=None,
    interleave: int = 1,
):
    """Forward pass with the block stack pipelined over ``mesh[axis]``.

    ``interleave = V > 1`` enables the Megatron interleaved schedule:
    each device holds V non-contiguous depth chunks and the bubble
    shrinks ~V× (module docstring).  Requires
    ``depth % (n_stages * V) == 0``.

    ``tokens``: ``(B, S)`` int32, ``B % n_microbatches == 0``.  Embedding
    and head (the ``pre``/``post`` layers) run replicated outside the
    pipelined region — they are a sliver of the FLOPs; sharding them
    belongs to the data/tensor axes.  Returns ``(B, S, vocab)`` logits.

    ``rng`` enables stochastic layers (Dropout) in ``train`` mode: keys
    are folded per (tick, stage, block) so every microbatch at every
    block draws an independent mask — the masks need not (and do not)
    match the single-device execution order.

    ``data_axis`` composes PP with DP on a 2-D mesh (e.g.
    ``{"pp": 4, "data": 2}``): each microbatch's batch dim is sharded
    over ``data_axis``, so every pp stage runs the pipeline schedule on
    its data shard — the standard pod layout.  Block params stay
    replicated over ``data_axis`` (shard them over an fsdp axis via the
    caller's param shardings if needed; GSPMD composes).

    State-carrying layers (BatchNorm) are rejected: the llama family is
    stateless, and cross-microbatch state threading belongs to
    :mod:`~torchpruner_tpu.parallel.pipeline`.
    """
    pre, groups, post = split_pipeline(model)
    n_stages = mesh.shape[axis]
    depth = len(groups)
    V = int(interleave)
    if V < 1:
        raise ValueError(f"interleave must be >= 1, got {interleave}")
    if depth % (n_stages * V) != 0:
        raise ValueError(
            f"depth {depth} not divisible by {n_stages} stages × "
            f"{V} virtual chunks")
    cb = depth // (n_stages * V)  # blocks per virtual chunk
    M = n_microbatches
    B = tokens.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    if data_axis is not None:
        if data_axis not in mesh.shape:
            raise ValueError(f"data_axis {data_axis!r} not in mesh axes "
                             f"{tuple(mesh.shape)}")
        if (B // M) % mesh.shape[data_axis] != 0:
            raise ValueError(
                f"microbatch size {B // M} not divisible by mesh axis "
                f"{data_axis}={mesh.shape[data_axis]}")
    canon_specs = canonical_group(groups[0])

    if compute_dtype is not None:
        params = jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, params)

    rng_pre = rng_blocks = rng_post = None
    if rng is not None:
        rng_pre, rng_blocks, rng_post = jax.random.split(rng, 3)
    h, _ = L.apply_seq(pre, params, {}, tokens, train=train, rng=rng_pre)
    x_micro = h.reshape((M, B // M) + h.shape[1:])
    stacked = stack_block_params(params, groups)
    if V > 1:
        # re-order the depth axis so the contiguous pp shard of device d
        # holds its V interleaved chunks v*S + d (each cb consecutive
        # blocks), chunk-major: local block j belongs to chunk j // cb
        order = jnp.asarray([
            (v * n_stages + d) * cb + b
            for d in range(n_stages) for v in range(V) for b in range(cb)
        ])
        stacked = jax.tree_util.tree_map(
            lambda arr: jnp.take(arr, order, axis=0), stacked)

    # one lax.scan over the diagonal-lane schedule (module docstring):
    # lane tt = t - device; chunk v = (tt // S) mod V; microbatch
    # m = (tt // (S*V)) * S + tt % S.  V = 1 reduces to GPipe exactly.
    # Ticks to the last bank of microbatch M-1 (lane algebra, static):
    T = (((M - 1) // n_stages * V + V - 1) * n_stages
         + (M - 1) % n_stages + n_stages)

    def stage_program(blocks_local, x_all, key):
        idx = jax.lax.axis_index(axis)

        def apply_chunk(act, v, key_t):
            def body(a, xs):
                p_one, bidx = xs
                sub = (None if key_t is None
                       else jax.random.fold_in(key_t, bidx))
                a2, _ = L.apply_seq(
                    canon_specs, p_one,
                    {}, a, train=train, remat=remat, rng=sub,
                )
                return a2, None
            chunk = jax.tree_util.tree_map(
                lambda arr: jax.lax.dynamic_slice_in_dim(
                    arr, v * cb, cb, axis=0), blocks_local)
            out, _ = jax.lax.scan(
                body, act, (chunk, v * cb + jnp.arange(cb)))
            return out

        def tick(carry, t):
            act_in, out_buf = carry
            tt = t - idx
            p = jnp.mod(tt, n_stages)
            rnd = jnp.floor_divide(tt, n_stages)
            v = jnp.mod(rnd, V)
            m = jnp.floor_divide(rnd, V) * n_stages + p
            inject = x_all[jnp.clip(m, 0, M - 1)]
            cur = jnp.where((idx == 0) & (v == 0), inject, act_in)
            # independent masks per (tick, stage, data-shard, block):
            # tick + stage + data coordinate fold here, block inside
            # apply_chunk — without the data fold, replicated keys give
            # every data shard identical masks
            if key is None:
                key_t = None
            else:
                key_t = jax.random.fold_in(jax.random.fold_in(key, t), idx)
                if data_axis is not None:
                    key_t = jax.random.fold_in(
                        key_t, jax.lax.axis_index(data_axis))
            y = apply_chunk(cur, v, key_t)
            banked = out_buf.at[jnp.clip(m, 0, M - 1)].set(y)
            write = ((idx == n_stages - 1) & (v == V - 1)
                     & (m >= 0) & (m < M))
            out_buf = jnp.where(write, banked, out_buf)
            perm = [(s, s + 1) for s in range(n_stages - 1)]
            if V > 1:
                # the wrap edge sends chunk-v outputs back to stage 0
                # for chunk v+1 (stage 0's v = 0 injection overwrites
                # the wrapped value after the final chunk)
                perm = perm + [(n_stages - 1, 0)]
            act_next = jax.lax.ppermute(y, axis, perm)
            return (act_next, out_buf), None

        # the tick carry is device-varying from the first ppermute on;
        # seed it as varying so the loop-invariant checker types the
        # scan consistently (new shard_map VMA semantics)
        carry0 = (jnp.zeros_like(x_all[0]), jnp.zeros_like(x_all))
        if hasattr(jax.lax, "pcast"):
            carry0 = jax.lax.pcast(carry0, axis, to="varying")
        elif hasattr(jax.lax, "pvary"):  # pragma: no cover - older jax
            carry0 = jax.lax.pvary(carry0, axis)
        # else: pre-VMA jax — no varying-axes typing to seed
        (_, out_buf), _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        # only the last stage ever banks outputs; the psum both collects
        # them and re-replicates the result for the post layers
        return jax.lax.psum(out_buf, axis)

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    spec_blocks = jax.tree_util.tree_map(lambda _: P(axis), stacked)
    # (M, mb, seq, d): microbatch dim stays whole on every stage; the
    # per-microbatch batch dim shards over the optional data axis
    spec_x = P(None, data_axis) if data_axis else P()
    if rng_blocks is None:
        def program(blocks_local, x_all):
            return stage_program(blocks_local, x_all, None)
        y_micro = shard_map(
            program, mesh=mesh,
            in_specs=(spec_blocks, spec_x), out_specs=spec_x,
        )(stacked, x_micro)
    else:
        y_micro = shard_map(
            stage_program, mesh=mesh,
            in_specs=(spec_blocks, spec_x, P()), out_specs=spec_x,
        )(stacked, x_micro, rng_blocks)
    y = y_micro.reshape((B,) + y_micro.shape[2:])
    logits, _ = L.apply_seq(post, params, {}, y, train=train,
                            rng=rng_post)
    return logits


def pp_spmd_train_step(model, optimizer, loss_fn, *, mesh, n_microbatches,
                       axis: str = "pp", data_axis: str | None = None,
                       remat: bool = False, compute_dtype=None,
                       interleave: int = 1):
    """A jitted ``(params, opt_state, tokens, rng=None) -> (params',
    opt_state', loss)`` whose forward/backward is pipelined over
    ``mesh[axis]``.  ``loss_fn(logits, tokens) -> (B,)`` per-example
    losses (e.g. :func:`~torchpruner_tpu.utils.losses.lm_cross_entropy_loss`).
    Dropout-bearing models pass a fresh ``rng`` per step (omitting it
    raises the Dropout layer's needs-an-rng error at trace time).
    ``interleave`` enables the interleaved schedule (see
    :func:`pp_spmd_apply`)."""

    def loss(params, tokens, rng):
        logits = pp_spmd_apply(
            model, params, tokens, mesh=mesh,
            n_microbatches=n_microbatches, axis=axis,
            data_axis=data_axis, remat=remat,
            compute_dtype=compute_dtype, train=True, rng=rng,
            interleave=interleave)
        return loss_fn(logits, tokens).mean()

    @jax.jit
    def step(params, opt_state, tokens, rng=None):
        l, grads = jax.value_and_grad(loss)(params, tokens, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, l

    return step

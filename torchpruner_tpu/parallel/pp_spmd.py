"""SPMD pipeline parallelism — collective-based, cross-host capable.

:mod:`~torchpruner_tpu.parallel.pipeline` pipelines *heterogeneous*
stages by pinning each stage's params to a local device and letting
async dispatch overlap them — which is single-process by construction
(a process cannot ``device_put`` onto another host's chips).  This
module is the pods formulation for uniform-block transformer stacks
(the llama family): ONE program runs on every device of a ``pp`` mesh
axis under ``shard_map``; the depth axis of the *stacked* block params
is sharded over ``pp`` (each device holds ``depth // n_stages``
consecutive blocks), microbatches stream through the stages, and
``lax.ppermute`` shifts activations stage→stage.  The permute is an XLA
collective like any other — it rides ICI within a host and DCN across
hosts — so the same compiled step pipelines across processes
(SURVEY.md §2.11's pods north star), with no NCCL-analog code.

Schedule: GPipe forward fill/drain (Huang et al., 2019) over
``T = n_micro + n_stages - 1`` ticks, expressed as ONE ``lax.scan``:
at tick ``t`` stage 0 injects microbatch ``t``, every stage applies its
blocks to whatever the permute delivered, the last stage banks outputs
for microbatch ``t - (n_stages - 1)``.  The bubble fraction is the
standard ``(S - 1) / (M + S - 1)``.  Gradients need nothing special:
the transpose of ``ppermute`` is the reverse permutation, so
``jax.grad`` of the whole step is pipeline-parallel automatically —
activation gradients hop backwards over the same collective.

Composability: params enter in the model's ordinary pytree layout and
are stacked inside the traced function, so gradient pytrees, optax
states, checkpoints, and the pruner all keep the unstacked layout;
other mesh axes (data, tensor) compose through GSPMD exactly as in
``ShardedTrainer``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def split_pipeline(model: SegmentedModel):
    """``(pre, pairs, post)``: the top-level layers before the first
    uniform block, the per-block ``(attn, ffn)`` :class:`Residual`
    pairs, and the layers after the last block.

    Raises if the blocks are not uniform (stage stacking needs every
    block's param shapes identical — true for the dense llama family;
    pruned-per-block or MoE models should pipeline with
    :mod:`~torchpruner_tpu.parallel.pipeline` instead).
    """
    # llama blocks pair `_attn` with `_ffn`; ViT pairs `_attn` with
    # `_mlp` — both are uniform adjacent Residual pairs and pipeline
    # identically.  BERT interleaves post-LayerNorms between the
    # residuals, so it correctly fails the pairing (use
    # parallel.pipeline for it).
    pre: List[L.LayerSpec] = []
    pairs: List[Tuple[L.LayerSpec, L.LayerSpec]] = []
    post: List[L.LayerSpec] = []
    specs = list(model.layers)
    i = 0
    while i < len(specs):
        a = specs[i]
        b = specs[i + 1] if i + 1 < len(specs) else None
        if (isinstance(a, L.Residual) and isinstance(b, L.Residual)
                and a.name.endswith("_attn")
                and b.name.endswith(("_ffn", "_mlp"))):
            if post:
                # a pair after non-block layers would be silently
                # reordered around them by the stage stacking — refuse
                raise ValueError(
                    f"block pair {a.name}/{b.name} appears after "
                    f"non-block layer {post[0].name}: the block stack "
                    "must be contiguous for SPMD pipelining")
            pairs.append((a, b))
            i += 2
        elif not pairs:
            pre.append(a)
            i += 1
        else:
            post.append(a)
            i += 1
    if not pairs:
        raise ValueError(
            "no uniform (attn, ffn/mlp) Residual pairs found — pp_spmd "
            "needs a llama- or ViT-style block stack")
    def _reject_unsupported(spec):
        if isinstance(spec, L.BatchNorm):
            raise ValueError(
                f"BatchNorm ({spec.name}) carries running state; "
                "cross-microbatch state threading belongs to "
                "parallel.pipeline, not the SPMD formulation")
        for child in (getattr(spec, "body", ()) or ()) + tuple(
                getattr(spec, "shortcut", ()) or ()):
            _reject_unsupported(child)

    for spec in list(pre) + [s for p in pairs for s in p] + list(post):
        _reject_unsupported(spec)
    canon = tuple(dataclasses.replace(s, name=n)
                  for s, n in zip(pairs[0], ("pp_attn", "pp_ffn")))
    for a, b in pairs[1:]:
        got = (dataclasses.replace(a, name="pp_attn"),
               dataclasses.replace(b, name="pp_ffn"))
        if got != canon:
            raise ValueError(
                f"non-uniform blocks ({a.name}/{b.name} differ from "
                f"{pairs[0][0].name}/{pairs[0][1].name}) — stage stacking "
                "requires identical block shapes")
    return tuple(pre), tuple(pairs), tuple(post)


def stack_block_params(params, pairs):
    """Per-leaf ``jnp.stack`` of the blocks' param subtrees along a new
    leading depth axis: ``{"attn": tree, "ffn": tree}`` with every leaf
    shaped ``(depth, ...)``.  Runs under jit (the stack fuses; under a
    sharded entry the result is resharded by GSPMD per the shard_map
    in_specs)."""
    attn = [params[a.name] for a, _ in pairs]
    ffn = [params[f.name] for _, f in pairs]
    return {
        "attn": jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *attn),
        "ffn": jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *ffn),
    }


def pp_spmd_apply(
    model: SegmentedModel,
    params,
    tokens,
    *,
    mesh: Mesh,
    n_microbatches: int,
    axis: str = "pp",
    data_axis: str | None = None,
    remat: bool = False,
    compute_dtype=None,
    train: bool = False,
    rng=None,
):
    """Forward pass with the block stack pipelined over ``mesh[axis]``.

    ``tokens``: ``(B, S)`` int32, ``B % n_microbatches == 0``.  Embedding
    and head (the ``pre``/``post`` layers) run replicated outside the
    pipelined region — they are a sliver of the FLOPs; sharding them
    belongs to the data/tensor axes.  Returns ``(B, S, vocab)`` logits.

    ``rng`` enables stochastic layers (Dropout) in ``train`` mode: keys
    are folded per (tick, stage, block) so every microbatch at every
    block draws an independent mask — the masks need not (and do not)
    match the single-device execution order.

    ``data_axis`` composes PP with DP on a 2-D mesh (e.g.
    ``{"pp": 4, "data": 2}``): each microbatch's batch dim is sharded
    over ``data_axis``, so every pp stage runs the pipeline schedule on
    its data shard — the standard pod layout.  Block params stay
    replicated over ``data_axis`` (shard them over an fsdp axis via the
    caller's param shardings if needed; GSPMD composes).

    State-carrying layers (BatchNorm) are rejected: the llama family is
    stateless, and cross-microbatch state threading belongs to
    :mod:`~torchpruner_tpu.parallel.pipeline`.
    """
    pre, pairs, post = split_pipeline(model)
    n_stages = mesh.shape[axis]
    depth = len(pairs)
    if depth % n_stages != 0:
        raise ValueError(f"depth {depth} not divisible by {n_stages} stages")
    M = n_microbatches
    B = tokens.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    if data_axis is not None:
        if data_axis not in mesh.shape:
            raise ValueError(f"data_axis {data_axis!r} not in mesh axes "
                             f"{tuple(mesh.shape)}")
        if (B // M) % mesh.shape[data_axis] != 0:
            raise ValueError(
                f"microbatch size {B // M} not divisible by mesh axis "
                f"{data_axis}={mesh.shape[data_axis]}")
    attn_spec, ffn_spec = (dataclasses.replace(s, name=n)
                           for s, n in zip(pairs[0], ("pp_attn", "pp_ffn")))


    if compute_dtype is not None:
        params = jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, params)

    rng_pre = rng_blocks = rng_post = None
    if rng is not None:
        rng_pre, rng_blocks, rng_post = jax.random.split(rng, 3)
    h, _ = L.apply_seq(pre, params, {}, tokens, train=train, rng=rng_pre)
    x_micro = h.reshape((M, B // M) + h.shape[1:])
    stacked = stack_block_params(params, pairs)

    def stage_program(blocks_local, x_all, key):
        idx = jax.lax.axis_index(axis)

        def apply_blocks(act, key_t):
            def body(a, xs):
                p_one, bidx = xs
                sub = (None if key_t is None
                       else jax.random.fold_in(key_t, bidx))
                a2, _ = L.apply_seq(
                    (attn_spec, ffn_spec),
                    {"pp_attn": p_one["attn"], "pp_ffn": p_one["ffn"]},
                    {}, a, train=train, remat=remat, rng=sub,
                )
                return a2, None
            bps = depth // n_stages
            out, _ = jax.lax.scan(
                body, act, (blocks_local, jnp.arange(bps)))
            return out

        def tick(carry, t):
            act_in, out_buf = carry
            inject = x_all[jnp.clip(t, 0, M - 1)]
            cur = jnp.where(idx == 0, inject, act_in)
            # independent masks per (tick, stage, data-shard, block):
            # tick + stage + data coordinate fold here, block inside
            # apply_blocks — without the data fold, replicated keys give
            # every data shard identical masks
            if key is None:
                key_t = None
            else:
                key_t = jax.random.fold_in(jax.random.fold_in(key, t), idx)
                if data_axis is not None:
                    key_t = jax.random.fold_in(
                        key_t, jax.lax.axis_index(data_axis))
            y = apply_blocks(cur, key_t)
            m = t - (n_stages - 1)
            banked = out_buf.at[jnp.clip(m, 0, M - 1)].set(y)
            write = (idx == n_stages - 1) & (m >= 0) & (m < M)
            out_buf = jnp.where(write, banked, out_buf)
            act_next = jax.lax.ppermute(
                y, axis, [(s, s + 1) for s in range(n_stages - 1)])
            return (act_next, out_buf), None

        # the tick carry is device-varying from the first ppermute on;
        # seed it as varying so the loop-invariant checker types the
        # scan consistently (new shard_map VMA semantics)
        carry0 = (jnp.zeros_like(x_all[0]), jnp.zeros_like(x_all))
        if hasattr(jax.lax, "pcast"):
            carry0 = jax.lax.pcast(carry0, axis, to="varying")
        else:  # pragma: no cover - older jax
            carry0 = jax.lax.pvary(carry0, axis)
        (_, out_buf), _ = jax.lax.scan(
            tick, carry0, jnp.arange(M + n_stages - 1))
        # only the last stage ever banks outputs; the psum both collects
        # them and re-replicates the result for the post layers
        return jax.lax.psum(out_buf, axis)

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    spec_blocks = jax.tree_util.tree_map(lambda _: P(axis), stacked)
    # (M, mb, seq, d): microbatch dim stays whole on every stage; the
    # per-microbatch batch dim shards over the optional data axis
    spec_x = P(None, data_axis) if data_axis else P()
    if rng_blocks is None:
        def program(blocks_local, x_all):
            return stage_program(blocks_local, x_all, None)
        y_micro = shard_map(
            program, mesh=mesh,
            in_specs=(spec_blocks, spec_x), out_specs=spec_x,
        )(stacked, x_micro)
    else:
        y_micro = shard_map(
            stage_program, mesh=mesh,
            in_specs=(spec_blocks, spec_x, P()), out_specs=spec_x,
        )(stacked, x_micro, rng_blocks)
    y = y_micro.reshape((B,) + y_micro.shape[2:])
    logits, _ = L.apply_seq(post, params, {}, y, train=train,
                            rng=rng_post)
    return logits


def pp_spmd_train_step(model, optimizer, loss_fn, *, mesh, n_microbatches,
                       axis: str = "pp", data_axis: str | None = None,
                       remat: bool = False, compute_dtype=None):
    """A jitted ``(params, opt_state, tokens, rng=None) -> (params',
    opt_state', loss)`` whose forward/backward is pipelined over
    ``mesh[axis]``.  ``loss_fn(logits, tokens) -> (B,)`` per-example
    losses (e.g. :func:`~torchpruner_tpu.utils.losses.lm_cross_entropy_loss`).
    Dropout-bearing models pass a fresh ``rng`` per step (omitting it
    raises the Dropout layer's needs-an-rng error at trace time)."""

    def loss(params, tokens, rng):
        logits = pp_spmd_apply(
            model, params, tokens, mesh=mesh,
            n_microbatches=n_microbatches, axis=axis,
            data_axis=data_axis, remat=remat,
            compute_dtype=compute_dtype, train=True, rng=rng)
        return loss_fn(logits, tokens).mean()

    @jax.jit
    def step(params, opt_state, tokens, rng=None):
        l, grads = jax.value_and_grad(loss)(params, tokens, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, l

    return step

"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context capability (absent from the reference, which has no attention at
all — SURVEY.md §5.7): the sequence axis is sharded over a ``seq`` mesh axis;
each device keeps its local query block and the KV shards rotate around the
ring with ``lax.ppermute`` (one hop per step, riding ICI), while a running
online-softmax state ``(max, sumexp, acc)`` merges each arriving chunk (Liu
et al., 2023).  Each chunk is itself streamed in KV blocks (``_chunk_stats``),
so peak live score memory per device is O(S_local × block) — not
O(S_local^2) — plus two KV shards, independent of the global sequence
length; compute overlaps with the next chunk's transfer inside one compiled
XLA program.

``ring_attention`` is the user-facing wrapper (global arrays in, shard_map
inside); ``ring_attention_local`` is the per-shard computation for callers
already running under ``shard_map``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchpruner_tpu.parallel.mesh import axis_size as mesh_axis_size

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

_NEG_INF = -1e30


#: KV sub-block length for streaming inside one ring chunk.  A chunk's
#: score tensor is only ever (B, H, Sq, _BLOCK_K) live at once.
_BLOCK_K = 512


def _block_stats(q, k, v, q_off, k_off, causal):
    """Online-softmax statistics of local queries against one KV block —
    the flash-attention core as an XLA computation (autodiff-exact, so the
    ring's backward comes from plain ``jax.grad``; the single-device Pallas
    kernels live in ops/flash_attention.py).

    ``q``: (B, Sq, H, Dh); ``k``/``v``: (B, Sk, H, Dh); offsets are global
    sequence positions (for causal masking across the ring).
    Returns ``m``: (B, H, Sq), ``l``: (B, H, Sq), ``acc``: (B, H, Sq, Dh).
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bshk,bthk->bhst", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        qpos = q_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        kpos = k_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        keep = qpos >= kpos
        s = jnp.where(keep[None, None], s, _NEG_INF)
        m = jnp.max(s, axis=-1)
        # re-apply the mask multiplicatively so a fully-masked row yields
        # l = 0 (not Sk) — its m is _NEG_INF and it merges away to nothing
        p = jnp.exp(s - m[..., None]) * keep[None, None]
    else:
        m = jnp.max(s, axis=-1)
        p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bhst,bthk->bhsk", p, v.astype(p.dtype),
        preferred_element_type=jnp.float32,
    )
    return m, l, acc


def _merge_stats(m, l, acc, cm, cl, cacc):
    """Numerically-stable merge of two online-softmax partial states."""
    m_new = jnp.maximum(m, cm)
    a_old = jnp.exp(m - m_new)
    a_new = jnp.exp(cm - m_new)
    return (
        m_new,
        l * a_old + cl * a_new,
        acc * a_old[..., None] + cacc * a_new[..., None],
    )


def _chunk_stats(q, k, v, q_off, k_off, causal, block_k: int = _BLOCK_K):
    """Statistics of local queries against one ring chunk, *streaming* the
    chunk in ``block_k``-length KV blocks: peak live score memory is
    (B, H, Sq, block_k) rather than the whole (B, H, Sq, Sk) chunk.  The
    per-block computation is rematerialized (``jax.checkpoint``) so the
    backward recomputes blocks instead of saving every block's scores.
    Non-dividing lengths halve the block until it divides (like
    flash_attention's ``_pick_blocks``) so streaming stays active for
    non-power-of-two shard lengths."""
    Sk = k.shape[1]
    while block_k > 64 and Sk % block_k:
        block_k //= 2
    if Sk <= block_k or Sk % block_k:
        return _block_stats(q, k, v, q_off, k_off, causal)

    B, Sq, H, Dh = q.shape
    n_blocks = Sk // block_k
    kb = k.reshape(B, n_blocks, block_k, H, Dh)
    vb = v.reshape(B, n_blocks, block_k, H, Dh)
    block = jax.checkpoint(
        lambda kv_j, off_j: _block_stats(q, kv_j[0], kv_j[1], q_off, off_j,
                                         causal),
        static_argnums=(),
    )

    def step(carry, inp):
        kv_j, off_j = inp
        cm, cl, cacc = block(kv_j, off_j)
        return _merge_stats(*carry, cm, cl, cacc), None

    init = (
        jnp.full((B, H, Sq), _NEG_INF, jnp.float32),
        jnp.zeros((B, H, Sq), jnp.float32),
        jnp.zeros((B, H, Sq, Dh), jnp.float32),
    )
    offs = k_off + block_k * jnp.arange(n_blocks, dtype=jnp.int32)
    (m, l, acc), _ = lax.scan(
        step, init,
        ((jnp.moveaxis(kb, 1, 0), jnp.moveaxis(vb, 1, 0)), offs),
    )
    return m, l, acc


def ring_attention_local(q, k, v, *, axis: str, causal: bool = False):
    """Per-shard ring attention; must run under ``shard_map`` with the
    sequence dim of q/k/v sharded over mesh axis ``axis``.

    ``q``/``k``/``v``: (B, S_local, H, Dh) local shards (KV already expanded
    to H heads).  Returns the local output shard (B, S_local, H, Dh).
    """
    if k.shape[1] != q.shape[1]:
        raise ValueError(
            f"ring attention is self-attention: K/V shard length "
            f"{k.shape[1]} must equal Q's {q.shape[1]}"
        )
    n = mesh_axis_size(axis)
    idx = lax.axis_index(axis)
    B, S_loc, H, Dh = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(t, m, l, acc, k_cur, v_cur):
        src = (idx - t) % n  # whose KV chunk this device holds at step t
        cm, cl, cacc = _chunk_stats(
            q, k_cur, v_cur, idx * S_loc, src * S_loc, causal
        )
        return _merge_stats(m, l, acc, cm, cl, cacc)

    def step(t, carry):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = merge(t, m, l, acc, k_cur, v_cur)
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return m, l, acc, k_nxt, v_nxt

    # initial state must be marked varying over the ring axis (the loop
    # carry mixes it with axis-varying values under shard_map; pre-VMA
    # jax has no such typing and needs no seed)
    m0, l0, acc0 = (
        jnp.full((B, H, S_loc), _NEG_INF, jnp.float32),
        jnp.zeros((B, H, S_loc), jnp.float32),
        jnp.zeros((B, H, S_loc, Dh), jnp.float32),
    )
    if hasattr(lax, "pcast"):
        m0, l0, acc0 = lax.pcast((m0, l0, acc0), (axis,), to="varying")
    # n-1 hops; the last chunk merges without a (discarded) final rotate
    m, l, acc, k_last, v_last = lax.fori_loop(
        0, n - 1, step, (m0, l0, acc0, k, v)
    )
    m, l, acc = merge(n - 1, m, l, acc, k_last, v_last)
    out = acc / l[..., None]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)  # (B, S_loc, H, Dh)


def ring_attention(
    q, k, v, mesh: Mesh, *, axis: str = "seq", causal: bool = False
):
    """Context-parallel attention on globally-shaped ``(B, S, H, Dh)``
    arrays: shards the sequence dim over mesh axis ``axis`` and runs the
    ring under ``shard_map`` (collectives ride ICI, inserted explicitly as
    ``ppermute`` hops)."""
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(
            f"sequence {q.shape[1]} not divisible by mesh axis "
            f"{axis}={n}"
        )
    if k.shape[1] != q.shape[1] or v.shape[1] != q.shape[1]:
        raise ValueError(
            f"ring attention is self-attention: K/V length "
            f"{k.shape[1]}/{v.shape[1]} must equal Q's {q.shape[1]}"
        )
    spec = P(None, axis, None, None)

    fn = shard_map(
        functools.partial(ring_attention_local, axis=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    return fn(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )

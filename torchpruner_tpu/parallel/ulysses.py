"""Ulysses-style sequence parallelism — all-to-all head scatter.

The second long-context strategy next to ring attention (`parallel/ring.py`),
after DeepSpeed-Ulysses (Jacobs et al., 2023).  Both start from the same
layout — the sequence dim sharded over a ``seq`` mesh axis — but exchange
differently:

- **Ring**: KV shards rotate with ``ppermute`` (n-1 hops), queries stay put;
  communication volume per device is O(S/n * H * Dh * (n-1)) and overlaps
  chunk compute.  Head count doesn't constrain the mesh.
- **Ulysses** (this module): one ``all_to_all`` re-shards *seq -> heads*, so
  each device holds the FULL sequence for ``H/n`` heads and runs an ordinary
  single-device attention — here the Pallas flash kernel
  (`ops/flash_attention.py`), keeping the O(S x Dh) memory property — then a
  second ``all_to_all`` re-shards back *heads -> seq*.  Communication is two
  all-to-alls (4 counting the backward), each moving O(S/n * H * Dh) per
  device, usually cheaper than the ring at moderate mesh sizes, but it
  requires ``H % n == 0``.

The reference has no attention at all (SURVEY.md §5.7); this subsystem
exists because long-context transformer configs (BASELINE.json's llama rows)
are first-class targets of the TPU build.  Head pruning composes: prune
attention heads first, then pick the strategy whose divisibility constraint
the pruned head count still satisfies (`choose_sp_strategy`).

``ulysses_attention`` is the user-facing wrapper (global arrays in,
``shard_map`` inside); ``ulysses_attention_local`` is the per-shard function
for callers already under ``shard_map``.  Gradients flow through both
all-to-alls and the flash kernel's custom VJP, so ``jax.grad`` works
unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from torchpruner_tpu.parallel.mesh import (
    axis_size as mesh_axis_size,
    relaxed_shard_map,
)

from torchpruner_tpu.ops.flash_attention import flash_attention


def ulysses_attention_local(q, k, v, *, axis: str, causal: bool = False,
                            attn_fn=None):
    """Per-shard Ulysses attention; must run under ``shard_map`` with the
    sequence dim of q/k/v sharded over mesh axis ``axis``.

    ``q``/``k``/``v``: (B, S_local, H, Dh) local shards (KV already expanded
    to H heads).  Returns the local output shard (B, S_local, H, Dh).
    ``attn_fn(q, k, v, causal=...)`` is the full-sequence attention run on
    each device's head subset; default is the Pallas flash kernel.
    """
    n = mesh_axis_size(axis)
    H = q.shape[2]
    if H % n:
        raise ValueError(
            f"Ulysses needs heads % seq-axis == 0, got H={H}, {axis}={n}; "
            f"use ring attention for this head count"
        )
    attn = attn_fn or flash_attention
    # seq-sharded -> head-sharded: split the head dim n ways, concatenate
    # the gathered sequence blocks; (B, S/n, H, Dh) -> (B, S, H/n, Dh)
    qh, kh, vh = (
        lax.all_to_all(t, axis, split_axis=2, concat_axis=1, tiled=True)
        for t in (q, k, v)
    )
    out = attn(qh, kh, vh, causal=causal)
    # head-sharded -> seq-sharded: the inverse exchange
    return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q, k, v, mesh: Mesh, *, axis: str = "seq", causal: bool = False,
    attn_fn=None,
):
    """Sequence-parallel attention on globally-shaped ``(B, S, H, Dh)``
    arrays via head-scatter all-to-alls (riding ICI), with the full-sequence
    flash kernel on each device's head subset."""
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(
            f"sequence {q.shape[1]} not divisible by mesh axis {axis}={n}"
        )
    if k.shape[1] != q.shape[1] or v.shape[1] != q.shape[1]:
        raise ValueError(
            f"self-attention: K/V length {k.shape[1]}/{v.shape[1]} must "
            f"equal Q's {q.shape[1]}"
        )
    if q.shape[2] % n:
        raise ValueError(
            f"Ulysses needs heads % mesh axis == 0, got H={q.shape[2]}, "
            f"{axis}={n}; use ring_attention instead"
        )
    spec = P(None, axis, None, None)
    # check_vma=False: the Pallas flash kernel's outputs carry no varying-
    # mesh-axes annotation, which the checker (newer jax) rejects inside
    # shard_map even though the computation is correctly per-shard
    fn = relaxed_shard_map(
        functools.partial(
            ulysses_attention_local, axis=axis, causal=causal,
            attn_fn=attn_fn,
        ),
        mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    return fn(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )


def choose_sp_strategy(n_heads: int, mesh: Mesh, *, axis: str = "seq") -> str:
    """``"ulysses"`` when the (possibly pruned) head count divides the
    sequence axis — two all-to-alls beat n-1 ring hops — else ``"ring"``,
    which has no head-count constraint."""
    return "ulysses" if n_heads % mesh.shape[axis] == 0 else "ring"


def sequence_parallel_attention(
    q, k, v, mesh: Mesh, *, axis: str = "seq", causal: bool = False,
    strategy: str = "auto",
):
    """Dispatch between the two SP strategies on global arrays.

    ``strategy``: ``"ring"`` | ``"ulysses"`` | ``"auto"`` (Ulysses when the
    head count allows it, ring otherwise — e.g. after pruning heads to a
    count not divisible by the mesh axis).
    """
    from torchpruner_tpu.parallel.ring import ring_attention

    if strategy == "auto":
        strategy = choose_sp_strategy(q.shape[2], mesh, axis=axis)
    if strategy == "ulysses":
        return ulysses_attention(q, k, v, mesh, axis=axis, causal=causal)
    if strategy == "ring":
        return ring_attention(q, k, v, mesh, axis=axis, causal=causal)
    raise ValueError(f"unknown SP strategy {strategy!r}")

"""Data-parallel attribution scoring.

The reference scores on one device, one batch at a time (SURVEY.md §2.11);
here the per-example score rows — the uniform currency of every metric
(``make_row_fn``) — are computed SPMD with the batch sharded over the
``data`` mesh axis.  Reductions happen as distributed moments (Σx, Σx², N
psum-reduced by XLA when the sharded rows are summed), so ``mean``, ``sum``
and ``mean+2std`` never gather the ``(examples, n_units)`` matrix; ``none``
or arbitrary callables gather rows to host (both forms exposed, SURVEY.md
§7 "Distributed scoring semantics").
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.attributions.base import AttributionMetric
from torchpruner_tpu.parallel.sharding import shard_batch
from torchpruner_tpu.utils.reductions import from_moments, mean_plus_2std

MOMENT_REDUCTIONS = ("mean", "sum", "mean+2std")


class DistributedScorer:
    """Wrap any attribution metric to score with batches sharded over the
    mesh's ``data`` axis.

    ``scorer = DistributedScorer(metric, mesh); scores = scorer.run(layer)``
    gives the same result as ``metric.run(layer)`` (same rows, same
    reduction), computed SPMD.
    """

    def __init__(self, metric: AttributionMetric, mesh, axis: str = "data"):
        self.metric = metric
        self.mesh = mesh
        self.axis = axis

    def run(self, layer: str, *, find_best_evaluation_layer: bool = False,
            **kw) -> np.ndarray:
        metric = self.metric
        try:
            metric.make_row_fn  # weight-only metrics have no rows to shard
        except AttributeError:  # pragma: no cover
            pass
        if type(metric).make_row_fn is AttributionMetric.make_row_fn:
            return metric.run(
                layer, find_best_evaluation_layer=find_best_evaluation_layer,
                **kw,
            )
        eval_layer = metric.find_evaluation_layer(
            layer, find_best_evaluation_layer
        )
        row_fn = metric.make_row_fn(eval_layer, **kw)
        reduction = metric.reduction
        momentish = (
            reduction in ("mean", "sum", "mean+2std")
            or reduction is mean_plus_2std
        )

        # the metric's own cast + f32-rows invariant (base.run_rows), so
        # local and SPMD rows agree bit-for-bit in policy
        params = metric.cast(metric.params)

        if momentish:
            red = (
                "mean+2std"
                if reduction is mean_plus_2std or reduction == "mean+2std"
                else reduction
            )
            s1 = s2 = None
            n = 0
            for batch in metric.batches():
                x, y = shard_batch(batch, self.mesh, self.axis)
                rows = metric.run_rows(row_fn, params, x, y)
                b1 = jnp.sum(rows, axis=0)   # cross-device psum via XLA
                b2 = jnp.sum(rows * rows, axis=0)
                s1 = b1 if s1 is None else s1 + b1
                s2 = b2 if s2 is None else s2 + b2
                n += int(np.shape(batch[0])[0])
            return np.asarray(
                from_moments(red, np.asarray(s1), np.asarray(s2), n)
            )

        # row-gathering path: 'none' or arbitrary callables
        out = []
        for batch in metric.batches():
            x, y = shard_batch(batch, self.mesh, self.axis)
            out.append(np.asarray(metric.run_rows(row_fn, params, x, y)))
        return metric.aggregate_over_samples(np.concatenate(out, axis=0))

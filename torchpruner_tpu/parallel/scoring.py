"""Data-parallel attribution scoring.

The reference scores on one device, one batch at a time (SURVEY.md §2.11);
here the per-example score rows — the uniform currency of every metric
(``make_row_fn``) — are computed SPMD with the batch sharded over the
``data`` mesh axis.  Reductions happen as distributed moments (Σx, Σx², N
psum-reduced by XLA when the sharded rows are summed), so ``mean``, ``sum``
and ``mean+2std`` never gather the ``(examples, n_units)`` matrix; ``none``
or arbitrary callables gather rows to host (both forms exposed, SURVEY.md
§7 "Distributed scoring semantics").
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.attributions.base import AttributionMetric
from torchpruner_tpu.parallel.sharding import shard_batch
from torchpruner_tpu.utils.reductions import from_moments, mean_plus_2std

MOMENT_REDUCTIONS = ("mean", "sum", "mean+2std")


class DistributedScorer:
    """Wrap any attribution metric to score with batches sharded over the
    mesh's ``data`` axis.

    ``scorer = DistributedScorer(metric, mesh); scores = scorer.run(layer)``
    gives the same result as ``metric.run(layer)`` (same rows, same
    reduction), computed SPMD.
    """

    def __init__(self, metric: AttributionMetric, mesh, axis: str = "data"):
        self.metric = metric
        self.mesh = mesh
        self.axis = axis

    # the sweep installs the capture cache on whatever ``run`` object its
    # factory returned — forward the attribute to the wrapped metric so a
    # DistributedScorer is a drop-in AttributionMetric for the engine
    @property
    def capture_cache(self):
        return self.metric.capture_cache

    @capture_cache.setter
    def capture_cache(self, cache):
        self.metric.capture_cache = cache

    def run(self, layer: str, *, find_best_evaluation_layer: bool = False,
            **kw) -> np.ndarray:
        metric = self.metric
        if (not metric.data_dependent
                or type(metric).make_row_fn is AttributionMetric.make_row_fn):
            # weight-only metrics (and any metric that overrides run()
            # without a row fn) have no rows to shard
            return metric.run(
                layer, find_best_evaluation_layer=find_best_evaluation_layer,
                **kw,
            )
        eval_layer = metric.find_evaluation_layer(
            layer, find_best_evaluation_layer
        )
        reduction = metric.reduction
        momentish = (
            reduction in ("mean", "sum", "mean+2std")
            or reduction is mean_plus_2std
        )

        # the metric's own cast + f32-rows invariant (base.run_rows), so
        # local and SPMD rows agree bit-for-bit in policy
        params = metric.cast(metric.params)
        # row_fn is built lazily inside _rows: when the capture cache
        # serves the site, the uncached row fn (and, for Shapley, its
        # permutation draw) is never constructed
        row_fn = None
        if metric.capture_cache is None:
            row_fn = metric.make_row_fn(eval_layer, **kw)

        if momentish:
            red = (
                "mean+2std"
                if reduction is mean_plus_2std or reduction == "mean+2std"
                else reduction
            )
            s1 = s2 = None
            n = 0
            for rows in self._rows(eval_layer, row_fn, params, **kw):
                b1 = jnp.sum(rows, axis=0)   # cross-device psum via XLA
                b2 = jnp.sum(rows * rows, axis=0)
                s1 = b1 if s1 is None else s1 + b1
                s2 = b2 if s2 is None else s2 + b2
                n += int(rows.shape[0])
            return np.asarray(
                from_moments(red, np.asarray(s1), np.asarray(s2), n)
            )

        # row-gathering path: 'none' or arbitrary callables — rows stay
        # device-resident until one final fetch (base._collect's policy)
        out = list(self._rows(eval_layer, row_fn, params, **kw))
        return metric.aggregate_over_samples(
            np.asarray(jnp.concatenate(out, axis=0)))

    def _rows(self, eval_layer, row_fn, params, **kw):
        cached = self.metric.cached_row_stream(eval_layer, **kw)
        if cached is not None:
            yield from cached
            return
        if row_fn is None:
            row_fn = self.metric.make_row_fn(eval_layer, **kw)
        for batch in self.metric.batches():
            x, y = shard_batch(batch, self.mesh, self.axis)
            yield self.metric.run_rows(row_fn, params, x, y)

"""Distribution layer — device meshes, sharding rules, data-parallel
attribution scoring and DP/FSDP training.

The reference has NO distributed components at all (single process, one
device — SURVEY.md §2.11); this subsystem is the TPU-native capability that
replaces the torch-DDP/NCCL layer the north-star workload would otherwise
need (BASELINE.json).  There is no hand-written communication backend: the
mesh + named shardings make XLA insert the collectives (all-reduce of
gradients for DP, all-gather/reduce-scatter for FSDP parameters, psum of
score moments for distributed attribution), riding ICI within a pod and DCN
across pods.
"""

from torchpruner_tpu.parallel.mesh import (
    initialize_distributed,
    make_hybrid_mesh,
    make_mesh,
    mesh_axes,
)
from torchpruner_tpu.parallel.sharding import (
    batch_sharding,
    fsdp_sharding,
    replicate,
    shard_batch,
    shard_params,
    tp_sharding,
    tp_specs,
    zero_update_sharding,
    zero_update_spec,
)
from torchpruner_tpu.parallel.memory import (
    HBM_BYTES,
    MemoryBudget,
    training_memory,
)
from torchpruner_tpu.parallel.scoring import DistributedScorer
from torchpruner_tpu.parallel.train import ShardedTrainer
from torchpruner_tpu.parallel.ring import ring_attention, ring_attention_local
from torchpruner_tpu.parallel.ulysses import (
    choose_sp_strategy,
    sequence_parallel_attention,
    ulysses_attention,
    ulysses_attention_local,
)
from torchpruner_tpu.parallel.pipeline import PipelineParallel, balance_stages
from torchpruner_tpu.parallel.pp_spmd import (
    pp_spmd_apply,
    pp_spmd_train_step,
    split_pipeline,
    stack_block_params,
)
from torchpruner_tpu.parallel.sp import SPTrainer, sp_model

__all__ = [
    "initialize_distributed",
    "make_hybrid_mesh",
    "make_mesh",
    "mesh_axes",
    "batch_sharding",
    "fsdp_sharding",
    "replicate",
    "shard_batch",
    "shard_params",
    "tp_sharding",
    "tp_specs",
    "zero_update_sharding",
    "zero_update_spec",
    "DistributedScorer",
    "HBM_BYTES",
    "MemoryBudget",
    "training_memory",
    "ShardedTrainer",
    "ring_attention",
    "ring_attention_local",
    "choose_sp_strategy",
    "sequence_parallel_attention",
    "ulysses_attention",
    "ulysses_attention_local",
    "PipelineParallel",
    "pp_spmd_apply",
    "pp_spmd_train_step",
    "split_pipeline",
    "stack_block_params",
    "balance_stages",
    "SPTrainer",
    "sp_model",
]

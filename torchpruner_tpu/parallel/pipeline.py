"""Pipeline parallelism — GPipe-style microbatched stage execution.

A :class:`SegmentedModel` is already a pipeline of pure segments, so stage
partitioning is native: split the top-level layers into ``n_stages``
contiguous spans (balanced by parameter count), pin each span's params to
its own device, and stream microbatches through.  Each stage function is an
independently-jitted computation whose placement follows its (committed)
operands, and JAX's async dispatch overlaps the per-device work: while
stage 1 runs microbatch k, stage 0 is already executing microbatch k+1 —
the GPipe schedule emerges from the dependency graph without an explicit
scheduler (Huang et al., 2019).

Training chains per-stage ``jax.vjp``s: forward saves residuals on each
stage's device, the backward walks stages in reverse (activation gradients
hop device-to-device like activations did), and parameter gradients
accumulate across microbatches — on-device, in the stage's own memory.

``train_step`` issues work in **1F1B order** (PipeDream-flush: Narayanan et
al., 2019): each stage runs ``n_stages − 1 − s`` warm-up forwards, then
alternates one forward with one backward, then drains.  Because each JAX
device executes its enqueued computations in issue order, the per-stage
issue sequence *is* the schedule — no explicit scheduler thread.  Compared
to plain GPipe (all forwards, then all backwards) this bounds the number of
live activation residuals per stage at ``n_stages − s`` instead of
``n_microbatches``, which is what lets microbatch counts scale without
activation memory scaling with them.  The microbatch loss is accumulated
into a single on-device scalar on the last stage and fetched **once** per
step — there are no per-microbatch host syncs to serialize the schedule.

Mutable state (BatchNorm running stats) is threaded *through* the
microbatch sequence at each stage — microbatch ``k+1``'s forward sees the
state microbatch ``k`` produced — so a PP step updates running statistics
from the full batch, matching sequential microbatch processing on one
device (parameter updates still use pre-step params for every microbatch,
as in GPipe).

This is the honest JAX formulation of pipeline parallelism for one process
with several local devices (a TPU host's chips) and HETEROGENEOUS stages
(conv stacks, pruned-per-block models).  For uniform-block transformer
stacks, :mod:`~torchpruner_tpu.parallel.pp_spmd` is the cross-host
formulation: the schedule fused into one ``shard_map``-ed XLA program,
activations shifting stage-to-stage over ``lax.ppermute`` — the
collective rides ICI/DCN, so it pipelines across processes where this
module's device pinning cannot.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp
import optax

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def _layer_param_count(spec, in_shape) -> int:
    """Static per-layer parameter count (no arrays)."""
    total = 0
    if isinstance(spec, L.Residual):
        shape = tuple(in_shape)
        for child in spec.body:
            total += _layer_param_count(child, shape)
            shape = L.out_shape(child, shape)
        shape = tuple(in_shape)
        for child in spec.shortcut:
            total += _layer_param_count(child, shape)
            shape = L.out_shape(child, shape)
        return total
    d = in_shape[-1] if in_shape else 0
    if isinstance(spec, L.Dense):
        return d * spec.features + (spec.features if spec.use_bias else 0)
    if isinstance(spec, L.Conv):
        kh, kw = spec.kernel_size
        return kh * kw * d * spec.features + (
            spec.features if spec.use_bias else 0
        )
    if isinstance(spec, (L.BatchNorm,)):
        return 2 * d
    if isinstance(spec, L.LayerNorm):
        return d * (2 if spec.use_bias else 1)
    if isinstance(spec, L.RMSNorm):
        return d
    if isinstance(spec, L.Embedding):
        return spec.vocab_size * spec.features
    if isinstance(spec, L.PosEmbed):
        return spec.max_len * d
    if isinstance(spec, L.ClsToken):
        return d
    if isinstance(spec, L.MultiHeadAttention):
        H, KV, Dh = spec.num_heads, spec.kv_heads, spec.head_dim
        d_out = spec.out_features if spec.out_features is not None else d
        n = d * H * Dh + 2 * d * KV * Dh + H * Dh * d_out
        if spec.use_bias:
            n += H * Dh + 2 * KV * Dh + d_out
        return n
    if isinstance(spec, L.GatedDense):
        return 2 * d * spec.features + (
            2 * spec.features if spec.use_bias else 0
        )
    if isinstance(spec, L.MoE):
        E, F = spec.n_experts, spec.ffn_dim
        return d * E + 3 * E * d * F
    return 0


def balance_stages(model: SegmentedModel, n_stages: int) -> List[Tuple[int, int]]:
    """Split top-level layer indices into ``n_stages`` contiguous spans
    ``[(start, stop), ...]`` with roughly equal parameter counts (greedy:
    cut when the running count passes the ideal per-stage share)."""
    if not (1 <= n_stages <= len(model.layers)):
        raise ValueError(
            f"n_stages {n_stages} out of range [1, {len(model.layers)}]"
        )
    counts = [
        _layer_param_count(spec, shp[0])
        for spec, shp in zip(model.layers, model.shapes)
    ]
    total = sum(counts)
    spans: List[Tuple[int, int]] = []
    start, acc = 0, 0
    remaining = n_stages
    for i, c in enumerate(counts):
        acc += c
        layers_left = len(counts) - i - 1
        stages_after = remaining - 1
        if (
            remaining > 1
            and acc >= total / n_stages
            and layers_left >= stages_after
        ):
            spans.append((start, i + 1))
            start, acc = i + 1, 0
            remaining -= 1
    spans.append((start, len(counts)))
    while len(spans) < n_stages:  # degenerate: pad with empty-param spans
        s, e = spans[-1]
        if e - s < 2:
            raise ValueError(f"cannot split {model.names} into {n_stages}")
        spans[-1] = (s, e - 1)
        spans.append((e - 1, e))
    return spans


def _split_tree(tree: Dict[str, Any], names: Sequence[str]) -> Dict[str, Any]:
    return {k: tree[k] for k in names if k in tree}


@dataclass
class PipelineParallel:
    """Microbatched pipeline executor over local devices.

    ``stage_params[i]`` / ``stage_state[i]`` live committed on
    ``devices[i]``; ``forward`` and ``train_step`` stream microbatches
    through the stages (async dispatch overlaps the devices).
    """

    model: SegmentedModel
    spans: List[Tuple[int, int]]
    devices: List[Any]
    stage_params: List[Dict[str, Any]]
    stage_state: List[Dict[str, Any]]
    loss_fn: Optional[Callable] = None
    tx: Any = None
    opt_state: Any = None
    n_microbatches: int = 4
    _fwd_fns: List[Any] = field(default_factory=list, repr=False)
    _loss_grad_fn: Any = field(default=None, repr=False)
    #: filled by ``train_step``: per-stage peak live vjp residuals and the
    #: issued op sequence — deterministic evidence of the 1F1B schedule
    #: (``max_live_residuals[s] <= n_stages - s``, vs ``n_microbatches``
    #: under GPipe) without relying on wall-clock timing.
    last_step_stats: Optional[Dict[str, Any]] = field(default=None, repr=False)

    @classmethod
    def create(
        cls,
        model: SegmentedModel,
        n_stages: int,
        *,
        loss_fn: Optional[Callable] = None,
        tx=None,
        devices: Optional[Sequence] = None,
        seed: int = 0,
        n_microbatches: int = 4,
        params=None,
        state=None,
    ) -> "PipelineParallel":
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < n_stages:
            raise ValueError(
                f"{n_stages} stages need {n_stages} devices, have "
                f"{len(devices)}"
            )
        devices = devices[:n_stages]
        if params is None:
            params, state = model.init(jax.random.PRNGKey(seed))
        state = state if state is not None else {}
        spans = balance_stages(model, n_stages)
        stage_params, stage_state = [], []
        for (s, e), dev in zip(spans, devices):
            names = [l.name for l in model.layers[s:e]]
            stage_params.append(
                jax.device_put(_split_tree(params, names), dev)
            )
            stage_state.append(jax.device_put(_split_tree(state, names), dev))
        tx = tx
        opt_state = None
        if tx is not None:
            opt_state = [
                jax.device_put(tx.init(p), dev)
                for p, dev in zip(stage_params, devices)
            ]
        pp = cls(
            model=model, spans=spans, devices=devices,
            stage_params=stage_params, stage_state=stage_state,
            loss_fn=loss_fn, tx=tx, opt_state=opt_state,
            n_microbatches=n_microbatches,
        )
        pp._build_fns()
        return pp

    def _build_fns(self):
        self._fwd_fns = []
        for s, e in self.spans:
            frm = None if s == 0 else self.model.layers[s - 1].name
            to = self.model.layers[e - 1].name
            model = self.model

            def fn(params, state, x, train, _frm=frm, _to=to):
                y, new_state = model.apply(
                    params, x, state=state, train=train,
                    from_layer=_frm, to_layer=_to,
                )
                return y, new_state

            self._fwd_fns.append(
                jax.jit(fn, static_argnames=("train",))
            )
        if self.loss_fn is not None:
            loss_fn = self.loss_fn

            def loss_and_grad(z, yb):
                def f(z_):
                    return jnp.mean(loss_fn(z_, yb))

                return jax.value_and_grad(f)(z)

            self._loss_grad_fn = jax.jit(loss_and_grad)

    # -- inference ----------------------------------------------------------

    def forward(self, x) -> jnp.ndarray:
        """Pipelined eval forward; microbatches stream through the stages."""
        outs = []
        for mb in _microbatches(x, self.n_microbatches):
            z = jax.device_put(mb, self.devices[0])
            for i, fn in enumerate(self._fwd_fns):
                z, _ = fn(self.stage_params[i], self.stage_state[i], z, False)
                if i + 1 < len(self._fwd_fns):
                    z = jax.device_put(z, self.devices[i + 1])
            outs.append(z)
        return jnp.concatenate([jax.device_put(o, self.devices[-1])
                                for o in outs], axis=0)

    # -- training -----------------------------------------------------------

    def train_step(self, x, y) -> float:
        """One 1F1B pipeline step.

        Issues per-stage forwards/backwards in PipeDream-flush order (see
        module docstring), accumulating per-stage parameter gradients and
        the scalar loss on-device; one optimizer update per stage and ONE
        device→host fetch (the loss) at the very end.
        """
        if self.tx is None or self.loss_fn is None:
            raise ValueError("train_step needs tx= and loss_fn= at create()")
        S = len(self.spans)
        M = self.n_microbatches
        mbs_x = _microbatches(x, M)
        mbs_y = _microbatches(y, M)
        sched = _1f1b_schedule(S, M)

        grads: List[Any] = [None] * S
        cur_state = list(self.stage_state)  # threaded through microbatches
        vjps: Dict[Tuple[int, int], Any] = {}  # (stage, mb) -> residuals
        outs: Dict[Tuple[int, int], Any] = {}  # (stage, mb) -> activation
        pending_g: Dict[Tuple[int, int], Any] = {}  # (stage, mb) -> act grad
        live = [0] * S
        max_live = [0] * S
        issued: List[List[Tuple[str, int]]] = [[] for _ in range(S)]
        loss_acc = None  # device scalar on the last stage

        ptr = [0] * S
        issued_f = [set() for _ in range(S)]
        while any(ptr[s] < len(sched[s]) for s in range(S)):
            progress = False
            for s in range(S):
                if ptr[s] >= len(sched[s]):
                    continue
                op, k = sched[s][ptr[s]]
                if op == "F":
                    if s > 0 and k not in issued_f[s - 1]:
                        continue  # upstream activation not issued yet
                    if s == 0:
                        z_in = jax.device_put(
                            jnp.asarray(mbs_x[k]), self.devices[0]
                        )
                    else:
                        z_in = jax.device_put(
                            outs.pop((s - 1, k)), self.devices[s]
                        )

                    def f(p, z_, _fn=self._fwd_fns[s], _st=cur_state[s]):
                        return _fn(p, _st, z_, True)

                    z_out, vjp, ns = jax.vjp(
                        f, self.stage_params[s], z_in, has_aux=True
                    )
                    cur_state[s] = ns
                    vjps[(s, k)] = vjp
                    outs[(s, k)] = z_out
                    live[s] += 1
                    max_live[s] = max(max_live[s], live[s])
                    issued_f[s].add(k)
                else:  # backward
                    if k not in issued_f[s]:
                        continue
                    if s == S - 1:
                        yb = jax.device_put(
                            jnp.asarray(mbs_y[k]), self.devices[-1]
                        )
                        lval, g = self._loss_grad_fn(outs.pop((S - 1, k)), yb)
                        loss_acc = lval if loss_acc is None else loss_acc + lval
                    else:
                        if (s, k) not in pending_g:
                            continue  # downstream backward not issued yet
                        g = jax.device_put(
                            pending_g.pop((s, k)), self.devices[s]
                        )
                    dp, dz = vjps.pop((s, k))(g)
                    live[s] -= 1
                    grads[s] = (
                        dp
                        if grads[s] is None
                        else jax.tree_util.tree_map(jnp.add, grads[s], dp)
                    )
                    if s > 0:
                        pending_g[(s - 1, k)] = dz
                issued[s].append((op, k))
                ptr[s] += 1
                progress = True
            if not progress:
                raise RuntimeError("1F1B schedule deadlocked (bug)")

        # update per stage
        inv = 1.0 / M
        for i in range(S):
            gi = jax.tree_util.tree_map(lambda a: a * inv, grads[i])
            updates, self.opt_state[i] = self.tx.update(
                gi, self.opt_state[i], self.stage_params[i]
            )
            self.stage_params[i] = optax.apply_updates(
                self.stage_params[i], updates
            )
        self.stage_state = cur_state
        self.last_step_stats = {
            "schedule": "1f1b",
            "max_live_residuals": max_live,
            "issued": issued,
            "host_syncs": 1,
        }
        return float(loss_acc) * inv  # the single device->host fetch

    # -- utilities ----------------------------------------------------------

    def gather_params(self) -> Dict[str, Any]:
        """Merge stage params back into one (host-local) tree."""
        out: Dict[str, Any] = {}
        for p in self.stage_params:
            out.update(jax.device_get(p))
        return out

    def gather_state(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for s in self.stage_state:
            out.update(jax.device_get(s))
        return out


def _1f1b_schedule(
    n_stages: int, n_microbatches: int
) -> List[List[Tuple[str, int]]]:
    """Per-stage op sequences for non-interleaved 1F1B (PipeDream-flush).

    Stage ``s`` runs ``min(n_stages − 1 − s, M)`` warm-up forwards, then
    alternates forward/backward until all ``M`` forwards are issued, then
    drains the remaining backwards.  Every stage issues exactly ``M``
    forwards and ``M`` backwards; at most ``n_stages − s`` forwards are
    outstanding (un-backwarded) at stage ``s`` at any point.
    """
    per_stage: List[List[Tuple[str, int]]] = []
    for s in range(n_stages):
        warmup = min(n_stages - 1 - s, n_microbatches)
        seq: List[Tuple[str, int]] = [("F", k) for k in range(warmup)]
        f_next, b_next = warmup, 0
        while b_next < n_microbatches:
            if f_next < n_microbatches:
                seq.append(("F", f_next))
                f_next += 1
            seq.append(("B", b_next))
            b_next += 1
        per_stage.append(seq)
    return per_stage


def _microbatches(x, n: int):
    x = np.asarray(x) if not isinstance(x, jnp.ndarray) else x
    b = x.shape[0]
    if b % n:
        raise ValueError(f"batch {b} not divisible by {n} microbatches")
    size = b // n
    return [x[i * size : (i + 1) * size] for i in range(n)]

"""Pipeline parallelism — GPipe-style microbatched stage execution.

A :class:`SegmentedModel` is already a pipeline of pure segments, so stage
partitioning is native: split the top-level layers into ``n_stages``
contiguous spans (balanced by parameter count), pin each span's params to
its own device, and stream microbatches through.  Each stage function is an
independently-jitted computation whose placement follows its (committed)
operands, and JAX's async dispatch overlaps the per-device work: while
stage 1 runs microbatch k, stage 0 is already executing microbatch k+1 —
the GPipe schedule emerges from the dependency graph without an explicit
scheduler (Huang et al., 2019).

Training chains per-stage ``jax.vjp``s: forward saves residuals on each
stage's device, the backward walks stages in reverse (activation gradients
hop device-to-device like activations did), and parameter gradients
accumulate across microbatches — on-device, in the stage's own memory.

This is the honest JAX formulation of pipeline parallelism for one process
with several local devices (a TPU host's chips).  Cross-host pipelining
composes with the mesh layers (DP/FSDP/TP shard *within* a stage via
``ShardedTrainer``); a fused 1F1B schedule inside one XLA program is the
later optimization.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp
import optax

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def _layer_param_count(spec, in_shape) -> int:
    """Static per-layer parameter count (no arrays)."""
    total = 0
    if isinstance(spec, L.Residual):
        shape = tuple(in_shape)
        for child in spec.body:
            total += _layer_param_count(child, shape)
            shape = L.out_shape(child, shape)
        shape = tuple(in_shape)
        for child in spec.shortcut:
            total += _layer_param_count(child, shape)
            shape = L.out_shape(child, shape)
        return total
    d = in_shape[-1] if in_shape else 0
    if isinstance(spec, L.Dense):
        return d * spec.features + (spec.features if spec.use_bias else 0)
    if isinstance(spec, L.Conv):
        kh, kw = spec.kernel_size
        return kh * kw * d * spec.features + (
            spec.features if spec.use_bias else 0
        )
    if isinstance(spec, (L.BatchNorm,)):
        return 2 * d
    if isinstance(spec, L.LayerNorm):
        return d * (2 if spec.use_bias else 1)
    if isinstance(spec, L.RMSNorm):
        return d
    if isinstance(spec, L.Embedding):
        return spec.vocab_size * spec.features
    if isinstance(spec, L.PosEmbed):
        return spec.max_len * d
    if isinstance(spec, L.ClsToken):
        return d
    if isinstance(spec, L.MultiHeadAttention):
        H, KV, Dh = spec.num_heads, spec.kv_heads, spec.head_dim
        d_out = spec.out_features if spec.out_features is not None else d
        n = d * H * Dh + 2 * d * KV * Dh + H * Dh * d_out
        if spec.use_bias:
            n += H * Dh + 2 * KV * Dh + d_out
        return n
    if isinstance(spec, L.GatedDense):
        return 2 * d * spec.features + (
            2 * spec.features if spec.use_bias else 0
        )
    if isinstance(spec, L.MoE):
        E, F = spec.n_experts, spec.ffn_dim
        return d * E + 3 * E * d * F
    return 0


def balance_stages(model: SegmentedModel, n_stages: int) -> List[Tuple[int, int]]:
    """Split top-level layer indices into ``n_stages`` contiguous spans
    ``[(start, stop), ...]`` with roughly equal parameter counts (greedy:
    cut when the running count passes the ideal per-stage share)."""
    if not (1 <= n_stages <= len(model.layers)):
        raise ValueError(
            f"n_stages {n_stages} out of range [1, {len(model.layers)}]"
        )
    counts = [
        _layer_param_count(spec, shp[0])
        for spec, shp in zip(model.layers, model.shapes)
    ]
    total = sum(counts)
    spans: List[Tuple[int, int]] = []
    start, acc = 0, 0
    remaining = n_stages
    for i, c in enumerate(counts):
        acc += c
        layers_left = len(counts) - i - 1
        stages_after = remaining - 1
        if (
            remaining > 1
            and acc >= total / n_stages
            and layers_left >= stages_after
        ):
            spans.append((start, i + 1))
            start, acc = i + 1, 0
            remaining -= 1
    spans.append((start, len(counts)))
    while len(spans) < n_stages:  # degenerate: pad with empty-param spans
        s, e = spans[-1]
        if e - s < 2:
            raise ValueError(f"cannot split {model.names} into {n_stages}")
        spans[-1] = (s, e - 1)
        spans.append((e - 1, e))
    return spans


def _split_tree(tree: Dict[str, Any], names: Sequence[str]) -> Dict[str, Any]:
    return {k: tree[k] for k in names if k in tree}


@dataclass
class PipelineParallel:
    """Microbatched pipeline executor over local devices.

    ``stage_params[i]`` / ``stage_state[i]`` live committed on
    ``devices[i]``; ``forward`` and ``train_step`` stream microbatches
    through the stages (async dispatch overlaps the devices).
    """

    model: SegmentedModel
    spans: List[Tuple[int, int]]
    devices: List[Any]
    stage_params: List[Dict[str, Any]]
    stage_state: List[Dict[str, Any]]
    loss_fn: Optional[Callable] = None
    tx: Any = None
    opt_state: Any = None
    n_microbatches: int = 4
    _fwd_fns: List[Any] = field(default_factory=list, repr=False)

    @classmethod
    def create(
        cls,
        model: SegmentedModel,
        n_stages: int,
        *,
        loss_fn: Optional[Callable] = None,
        tx=None,
        devices: Optional[Sequence] = None,
        seed: int = 0,
        n_microbatches: int = 4,
        params=None,
        state=None,
    ) -> "PipelineParallel":
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < n_stages:
            raise ValueError(
                f"{n_stages} stages need {n_stages} devices, have "
                f"{len(devices)}"
            )
        devices = devices[:n_stages]
        if params is None:
            params, state = model.init(jax.random.PRNGKey(seed))
        state = state if state is not None else {}
        spans = balance_stages(model, n_stages)
        stage_params, stage_state = [], []
        for (s, e), dev in zip(spans, devices):
            names = [l.name for l in model.layers[s:e]]
            stage_params.append(
                jax.device_put(_split_tree(params, names), dev)
            )
            stage_state.append(jax.device_put(_split_tree(state, names), dev))
        tx = tx
        opt_state = None
        if tx is not None:
            opt_state = [
                jax.device_put(tx.init(p), dev)
                for p, dev in zip(stage_params, devices)
            ]
        pp = cls(
            model=model, spans=spans, devices=devices,
            stage_params=stage_params, stage_state=stage_state,
            loss_fn=loss_fn, tx=tx, opt_state=opt_state,
            n_microbatches=n_microbatches,
        )
        pp._build_fns()
        return pp

    def _build_fns(self):
        self._fwd_fns = []
        for s, e in self.spans:
            frm = None if s == 0 else self.model.layers[s - 1].name
            to = self.model.layers[e - 1].name
            model = self.model

            def fn(params, state, x, train, _frm=frm, _to=to):
                y, new_state = model.apply(
                    params, x, state=state, train=train,
                    from_layer=_frm, to_layer=_to,
                )
                return y, new_state

            self._fwd_fns.append(
                jax.jit(fn, static_argnames=("train",))
            )

    # -- inference ----------------------------------------------------------

    def forward(self, x) -> jnp.ndarray:
        """Pipelined eval forward; microbatches stream through the stages."""
        outs = []
        for mb in _microbatches(x, self.n_microbatches):
            z = jax.device_put(mb, self.devices[0])
            for i, fn in enumerate(self._fwd_fns):
                z, _ = fn(self.stage_params[i], self.stage_state[i], z, False)
                if i + 1 < len(self._fwd_fns):
                    z = jax.device_put(z, self.devices[i + 1])
            outs.append(z)
        return jnp.concatenate([jax.device_put(o, self.devices[-1])
                                for o in outs], axis=0)

    # -- training -----------------------------------------------------------

    def train_step(self, x, y) -> float:
        """GPipe step: all microbatch forwards (saving per-stage vjps), then
        the backward chain in reverse, gradients accumulated per stage
        on-device; one optimizer update per stage."""
        if self.tx is None or self.loss_fn is None:
            raise ValueError("train_step needs tx= and loss_fn= at create()")
        n_stage = len(self.spans)
        grads = [None] * n_stage
        new_states = list(self.stage_state)
        total_loss = 0.0
        mbs_x = _microbatches(x, self.n_microbatches)
        mbs_y = _microbatches(y, self.n_microbatches)

        # forward phase: per microbatch, chain vjps
        saved = []  # per microbatch: list of vjp fns + final activation
        for mb_x in mbs_x:
            z = jax.device_put(jnp.asarray(mb_x), self.devices[0])
            vjps = []
            for i, (s, e) in enumerate(self.spans):
                frm = None if s == 0 else self.model.layers[s - 1].name
                to = self.model.layers[e - 1].name
                st = self.stage_state[i]
                model = self.model

                def fwd(p, z_, _frm=frm, _to=to, _st=st):
                    y_, ns = model.apply(
                        p, z_, state=_st, train=True, from_layer=_frm,
                        to_layer=_to,
                    )
                    return y_, ns

                (z, ns), vjp = _vjp_with_aux(fwd, self.stage_params[i], z)
                new_states[i] = ns
                vjps.append(vjp)
                if i + 1 < n_stage:
                    z = jax.device_put(z, self.devices[i + 1])
            saved.append((vjps, z))

        # backward phase (reverse microbatch order, GPipe)
        for (vjps, z_out), mb_y in zip(reversed(saved), reversed(mbs_y)):
            yb = jax.device_put(jnp.asarray(mb_y), self.devices[-1])

            def loss_f(z_):
                return jnp.mean(self.loss_fn(z_, yb))

            lval, g = jax.value_and_grad(loss_f)(z_out)
            total_loss += float(lval) / len(saved)
            for i in range(n_stage - 1, -1, -1):
                dp, g = vjps[i](g)
                grads[i] = dp if grads[i] is None else jax.tree_util.tree_map(
                    jnp.add, grads[i], dp
                )
                if i > 0:
                    g = jax.device_put(g, self.devices[i - 1])

        # update per stage
        inv = 1.0 / len(saved)
        for i in range(n_stage):
            gi = jax.tree_util.tree_map(lambda a: a * inv, grads[i])
            updates, self.opt_state[i] = self.tx.update(
                gi, self.opt_state[i], self.stage_params[i]
            )
            self.stage_params[i] = optax.apply_updates(
                self.stage_params[i], updates
            )
        self.stage_state = new_states
        return total_loss

    # -- utilities ----------------------------------------------------------

    def gather_params(self) -> Dict[str, Any]:
        """Merge stage params back into one (host-local) tree."""
        out: Dict[str, Any] = {}
        for p in self.stage_params:
            out.update(jax.device_get(p))
        return out

    def gather_state(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for s in self.stage_state:
            out.update(jax.device_get(s))
        return out


def _vjp_with_aux(fwd, params, z):
    """``jax.vjp`` of a ``(y, state)`` function w.r.t. (params, z), keeping
    the state as untouched aux output and a vjp over ``y`` only."""
    (y, ns), vjp = jax.vjp(fwd, params, z, has_aux=False)

    def vjp_y(g):
        dp, dz = vjp((g, jax.tree_util.tree_map(jnp.zeros_like, ns)))
        return dp, dz

    return (y, ns), vjp_y


def _microbatches(x, n: int):
    x = np.asarray(x) if not isinstance(x, jnp.ndarray) else x
    b = x.shape[0]
    if b % n:
        raise ValueError(f"batch {b} not divisible by {n} microbatches")
    size = b // n
    return [x[i * size : (i + 1) * size] for i in range(n)]

"""Sharding rules: how params and batches map onto the mesh.

FSDP here = shard each (large-enough) parameter's largest divisible axis
over the ``model`` mesh axis; XLA all-gathers parameters into the matmuls
and reduce-scatters gradients — no hand-written collectives.  After a prune
step changes parameter shapes, call :func:`shard_params` again: arrays whose
pruned axis no longer divides the mesh fall back to replication (resharding
smaller arrays over the same mesh, SURVEY.md §5.8c).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard axis 0 (batch) over the data axis; replicate the rest."""
    return NamedSharding(mesh, P(axis))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_spec(shape, mesh: Mesh, axis: str = "model", min_size: int = 2**14):
    """PartitionSpec for one array: shard the largest dim divisible by the
    mesh axis; replicate small or indivisible arrays."""
    if axis not in mesh.axis_names:
        return P()
    size = mesh.shape[axis]
    if size == 1 or int(np.prod(shape)) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % size == 0:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def fsdp_sharding(tree, mesh: Mesh, axis: str = "model",
                  min_size: int = 2**14):
    """Sharding pytree (same structure as ``tree``) under the FSDP rule."""
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(
            mesh, fsdp_spec(np.shape(leaf), mesh, axis, min_size)
        ),
        tree,
    )


def shard_params(tree, mesh: Mesh, axis: str = "model",
                 min_size: int = 2**14):
    """Place a params-like pytree on the mesh under the FSDP rule.
    Returns ``(sharded_tree, sharding_tree)``."""
    shardings = fsdp_sharding(tree, mesh, axis, min_size)
    placed = jax.device_put(tree, shardings)
    return placed, shardings


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Place ``(x, y)`` with batch dim sharded over the data axis.  The
    leading dim must divide the axis size (callers pad or drop the
    remainder — ``Dataset.iter_batches(drop_remainder=True)``)."""
    sh = batch_sharding(mesh, axis)

    def put(a):
        if a.shape[0] % mesh.shape[axis]:
            raise ValueError(
                f"batch dim {a.shape[0]} not divisible by mesh axis "
                f"{axis}={mesh.shape[axis]}"
            )
        return jax.device_put(a, sh)

    return jax.tree_util.tree_map(put, batch)

"""Sharding rules: how params and batches map onto the mesh.

FSDP here = shard each (large-enough) parameter's largest divisible axis
over the ``model`` mesh axis; XLA all-gathers parameters into the matmuls
and reduce-scatters gradients — no hand-written collectives.  After a prune
step changes parameter shapes, call :func:`shard_params` again: arrays whose
pruned axis no longer divides the mesh fall back to replication (resharding
smaller arrays over the same mesh, SURVEY.md §5.8c).

Tensor parallelism (:func:`tp_sharding`) is *derived from the pruning
graph*: a prune group's target is exactly a Megatron column-parallel layer
(its unit axis shards over ``model``) and its consumers are the matching
row-parallel layers (their input axis shards, XLA psums the partial
products) — the same producer/consumer structure that makes a group
prunable makes it tensor-parallelizable.  Attention-head groups shard the
head axis (GQA KV projections shard only when the KV-head count divides the
axis).  Anything the graph doesn't claim falls back to the FSDP rule, so
``partition="tp"`` is a TP+FSDP hybrid on one mesh axis.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchpruner_tpu.core import layers as L


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard axis 0 (batch) over the data axis; replicate the rest."""
    return NamedSharding(mesh, P(axis))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_spec(shape, mesh: Mesh, axis="model", min_size: int = 2**14):
    """PartitionSpec for one array: shard the largest dim divisible by the
    mesh axis; replicate small or indivisible arrays.

    ``axis`` may be a tuple of mesh axes (e.g. ``("data", "model")``) for
    ZeRO-style sharding over the FULL mesh — per-chip parameter bytes then
    divide by the product of the axis sizes, at the cost of gathers over
    the data axis too.  Falls back to the first axis alone when a dim
    divides it but not the product."""
    axes = axis if isinstance(axis, tuple) else (axis,)
    axes = tuple(a for a in axes if a in mesh.axis_names)
    if not axes:
        return P()
    size = int(np.prod([mesh.shape[a] for a in axes]))
    if size == 1 or int(np.prod(shape)) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % size == 0:
            spec = [None] * len(shape)
            spec[d] = axes if len(axes) > 1 else axes[0]
            return P(*spec)
    if len(axes) > 1:  # partial: shard over the first axis alone
        return fsdp_spec(shape, mesh, axes[0], min_size)
    return P()


def fsdp_sharding(tree, mesh: Mesh, axis="model",
                  min_size: int = 2**14):
    """Sharding pytree (same structure as ``tree``) under the FSDP rule.
    ``axis`` may be a tuple for ZeRO-style full-mesh sharding."""
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(
            mesh, fsdp_spec(np.shape(leaf), mesh, axis, min_size)
        ),
        tree,
    )


def zero_update_spec(shape, spec, mesh_shape: Dict[str, int],
                     data_axis: str = "data"):
    """PartitionSpec for one array's ZeRO weight-update shard: ``spec``
    (the param's model-axis placement) with ``data_axis`` added on a
    divisible dim — the domain in which gradients are reduce-scattered,
    the optimizer state lives, and the 1/N update applies ("Automatic
    Cross-Replica Sharding of Weight Update in Data-Parallel Training").

    Placement rule: prefer the largest dim the param placement left
    unsharded; otherwise extend an already-sharded dim to a
    ``(model_axes..., data)`` tuple when the compound size still
    divides.  Every param-shaped leaf is eligible regardless of size
    (ZeRO shards the whole update — opt-state HBM is the point, and the
    per-leaf collectives ride the step's existing reduce); a leaf none
    of whose dims divide keeps ``spec`` (replicated update, exactly
    today's behavior).  Scalars keep ``spec`` too."""
    n = int(mesh_shape.get(data_axis, 1))
    if n <= 1 or not shape:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used = set()
    for e in entries:
        for a in (e if isinstance(e, tuple) else (e,)):
            if a is not None:
                used.add(a)
    if data_axis in used:  # already data-sharded (full-mesh tuple FSDP)
        return spec
    order = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in order:
        if entries[d] is None and shape[d] % n == 0:
            entries[d] = data_axis
            return P(*entries)
    for d in order:
        e = entries[d]
        if e is None:
            continue
        ax = e if isinstance(e, tuple) else (e,)
        k = n
        for a in ax:
            k *= int(mesh_shape.get(a, 1))
        if shape[d] % k == 0:
            entries[d] = tuple(ax) + (data_axis,)
            return P(*entries)
    return spec


def zero_update_sharding(tree, shardings, mesh: Mesh,
                         data_axis: str = "data"):
    """Param-shaped ``NamedSharding`` tree for the ZeRO update domain:
    each leaf's param placement from ``shardings`` with the data axis
    added per :func:`zero_update_spec`.  Used three ways by the trainer:
    as the ``with_sharding_constraint`` target that turns the gradient
    all-reduce into a reduce-scatter, as the optimizer-state placement
    (via ``optax.tree_map_params``), and as the sharding the update's
    output holds before the param all-gather."""
    mesh_shape = dict(mesh.shape)

    def one(leaf, sh):
        return NamedSharding(
            mesh,
            zero_update_spec(np.shape(leaf), sh.spec, mesh_shape, data_axis),
        )

    return jax.tree_util.tree_map(one, tree, shardings)


def shard_params(tree, mesh: Mesh, axis: str = "model",
                 min_size: int = 2**14):
    """Place a params-like pytree on the mesh under the FSDP rule.
    Returns ``(sharded_tree, sharding_tree)``.

    Arrays large enough to shard whose every dim fails the divisibility
    check fall back to replication; that is no longer silent — one
    warning line lists the affected paths (downgrade or silence it via
    ``analysis.severity_config["sharding/replicated-fallback"]``)."""
    shardings = fsdp_sharding(tree, mesh, axis, min_size)
    fallbacks = _replication_fallbacks(tree, shardings, mesh, axis, min_size)
    if fallbacks:
        from torchpruner_tpu.train.logger import lint_warning

        lint_warning(
            "sharding/replicated-fallback",
            f"{len(fallbacks)} array(s) no longer divide mesh axis "
            f"{axis!r} and fall back to replication: "
            + ", ".join(fallbacks),
        )
    placed = jax.device_put(tree, shardings)
    return placed, shardings


def _replication_fallbacks(tree, shardings, mesh: Mesh, axis,
                           min_size: int):
    """Paths of arrays the FSDP rule WANTED to shard (big enough, axis
    size > 1) but left replicated because no dim divides the mesh axis —
    the post-prune hazard the static analyzer reports as
    ``sharding/replicated-fallback``."""
    axes = axis if isinstance(axis, tuple) else (axis,)
    axes = tuple(a for a in axes if a in mesh.axis_names)
    if not axes:
        return []
    size = int(np.prod([mesh.shape[a] for a in axes]))
    if size == 1:
        return []
    flat_t, _ = jax.tree_util.tree_flatten_with_path(tree)
    flat_s = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec")
    )
    from torchpruner_tpu.core.plan import key_path_str

    out = []
    for (path, leaf), sh in zip(flat_t, flat_s):
        shape = np.shape(leaf)
        if int(np.prod(shape)) < min_size:
            continue
        if all(a is None for a in sh.spec):
            out.append(f"{key_path_str(path)} {tuple(shape)}")
    return out


def _tp_target_specs(spec, size: int) -> Dict[str, P]:
    """Column-parallel specs for a prune-group target (unit axis sharded)."""
    if isinstance(spec, L.Dense) and spec.features % size == 0:
        return {"w": P(None, "model"), "b": P("model")}
    if isinstance(spec, L.Conv) and spec.features % size == 0:
        return {"w": P(None, None, None, "model"), "b": P("model")}
    if isinstance(spec, L.GatedDense) and spec.features % size == 0:
        return {
            "wg": P(None, "model"), "wu": P(None, "model"),
            "bg": P("model"), "bu": P("model"),
        }
    if isinstance(spec, L.MultiHeadAttention) and spec.num_heads % size == 0:
        out = {
            "wq": P(None, "model", None), "bq": P("model", None),
            "wo": P("model", None, None), "bo": P(),
        }
        if spec.kv_heads % size == 0:
            out.update({
                "wk": P(None, "model", None), "bk": P("model", None),
                "wv": P(None, "model", None), "bv": P("model", None),
            })
        return out
    if isinstance(spec, L.MoE) and spec.n_experts % size == 0:
        # expert parallelism: each device holds n_experts/size experts and
        # computes their partial contributions; XLA reduces (the dense-
        # formulation equivalent of all-to-all expert dispatch)
        return {
            "wg": P("model", None, None),
            "wu": P("model", None, None),
            "wo": P("model", None, None),
            "router": P(None, "model"),
        }
    return {}


def _tp_consumer_specs(spec, in_width: int, size: int) -> Dict[str, P]:
    """Row-parallel specs for a group consumer (input axis sharded; XLA
    inserts the partial-sum reduction).  Biases stay replicated (added once
    after the reduce)."""
    if in_width % size:
        return {}
    if isinstance(spec, L.Dense):
        return {"w": P("model", None)}
    if isinstance(spec, L.Conv):
        return {"w": P(None, None, "model", None)}
    if isinstance(spec, L.GatedDense):
        return {"wg": P("model", None), "wu": P("model", None)}
    if isinstance(spec, L.MultiHeadAttention):
        return {
            "wq": P("model", None, None),
            "wk": P("model", None, None),
            "wv": P("model", None, None),
        }
    if isinstance(spec, L.MoE):
        return {
            "router": P("model", None),
            "wg": P(None, "model", None),
            "wu": P(None, "model", None),
        }
    return {}


def tp_specs(model, mesh: Mesh, axis: str = "model") -> Dict[Tuple[str, str], P]:
    """``{(layer_path, param_name): PartitionSpec}`` tensor-parallel
    assignments derived from the pruning graph (column-parallel targets,
    row-parallel consumers; first claim wins where a layer appears in
    multiple groups, e.g. conv chains)."""
    from torchpruner_tpu.core.graph import pruning_graph

    size = mesh.shape[axis]
    if size == 1:
        return {}
    out: Dict[Tuple[str, str], P] = {}

    def rename(p: P) -> P:
        return P(*(axis if x == "model" else x for x in p))

    def claim(layer: str, specs: Dict[str, P]):
        for pname, pspec in specs.items():
            out.setdefault((layer, pname), rename(pspec))

    for g in pruning_graph(model, include_output=True):
        tgt = model.layer(g.target)
        specs = _tp_target_specs(tgt, size)
        if not specs:
            continue
        claim(g.target, specs)
        for c in g.consumers:
            cspec = model.layer(c.layer)
            in_w = L.n_units(tgt) * c.fan_out
            claim(c.layer, _tp_consumer_specs(cspec, in_w, size))
    return out


def tp_sharding(model, params, mesh: Mesh, axis: str = "model",
                min_size: int = 2**14):
    """Sharding pytree for ``params``: pruning-graph-derived TP specs where
    they apply, the FSDP rule everywhere else (embeddings, norms, the
    residual-pinned projections).

    ``axis`` must be a single mesh axis: TP's column/row-parallel pairs
    communicate over ONE axis by construction (ZeRO-style tuple axes are
    an FSDP concept — use ``partition="fsdp"`` for those)."""
    if isinstance(axis, tuple):
        raise ValueError(
            "tensor parallelism shards over a single mesh axis; tuple "
            f"axes {axis!r} are only meaningful for the FSDP rule "
            "(partition='fsdp')"
        )
    assigned = tp_specs(model, mesh, axis)

    def spec_for(path, leaf):
        keys = tuple(getattr(k, "key", k) for k in path)
        layer, pname = "/".join(keys[:-1]), keys[-1]
        p = assigned.get((layer, pname))
        shape = np.shape(leaf)
        if p is not None:
            # a pruned layer may have stopped dividing the axis — fall back
            ok = all(
                s is None or shape[d] % mesh.shape[s] == 0
                for d, s in enumerate(p)
            )
            if ok:
                return NamedSharding(mesh, p)
        return NamedSharding(
            mesh, fsdp_spec(shape, mesh, axis, min_size)
        )

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Place ``(x, y)`` with batch dim sharded over the data axis.

    Single-process: a plain sharded ``device_put``; the leading dim must
    divide the axis size (callers pad or drop the remainder —
    ``Dataset.iter_batches(drop_remainder=True)``).

    Multi-process (a mesh spanning hosts after
    ``initialize_distributed``): each host passes its LOCAL shard — the
    slice ``Dataset.host_shard()`` feeds it — and the global array
    assembles from every host's addressable pieces without any
    cross-host copy, the standard per-host input pipeline on pods.  The
    local leading dim must then divide the axis's addressable share.
    """
    sh = batch_sharding(mesh, axis)
    multiprocess = any(
        d.process_index != jax.process_index() for d in mesh.devices.flat
    )

    def put(a):
        if multiprocess:
            return jax.make_array_from_process_local_data(sh, np.asarray(a))
        if a.shape[0] % mesh.shape[axis]:
            raise ValueError(
                f"batch dim {a.shape[0]} not divisible by mesh axis "
                f"{axis}={mesh.shape[axis]}"
            )
        return jax.device_put(a, sh)

    return jax.tree_util.tree_map(put, batch)

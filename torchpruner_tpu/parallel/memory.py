"""Per-chip memory accounting for sharded training — plan before you pod.

Given a model's shape tree and a sharding assignment, compute exactly how
many bytes of parameters, gradients and optimizer slots land on each chip,
without materializing anything (``jax.eval_shape`` + the sharding rules are
pure functions of shapes).  This is the planning step the scaling
methodology prescribes — pick a mesh, annotate shardings, CHECK THE BYTES,
then compile — and what the reference never needed at single-GPU scale.

The activation estimate is deliberately coarse (per-layer output sizes for
one microbatch, halved by remat to block boundaries); exact activation
footprints come from ``jit(...).lower().compile().memory_analysis()`` on
real hardware.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

#: HBM per chip (bytes) by device kind prefix — public spec sheets
HBM_BYTES = {
    "TPU v3": 16 * 2**30,
    "TPU v4": 32 * 2**30,
    "TPU v5 lite": 16 * 2**30,
    "TPU v5e": 16 * 2**30,
    "TPU v5p": 95 * 2**30,
    "TPU v5": 95 * 2**30,
    "TPU v6 lite": 32 * 2**30,
    "TPU v6e": 32 * 2**30,
}


@dataclass
class MemoryBudget:
    """Per-chip byte accounting for one training configuration."""

    params_bytes: int
    grads_bytes: int
    opt_bytes: int
    activations_bytes: int  # coarse estimate, one microbatch
    largest_replicated: tuple  # (path, bytes) — the first thing to shard

    @property
    def total_bytes(self) -> int:
        return (self.params_bytes + self.grads_bytes + self.opt_bytes
                + self.activations_bytes)

    def fits(self, hbm_bytes: int, headroom: float = 0.85) -> bool:
        """True when the budget fits within ``headroom`` of the chip HBM
        (the rest goes to XLA temps, collectives buffers, programs)."""
        return self.total_bytes <= hbm_bytes * headroom

    def report(self) -> str:
        gib = 2.0**30
        path, rb = self.largest_replicated
        return (
            f"per-chip: params {self.params_bytes / gib:.2f} GiB, "
            f"grads {self.grads_bytes / gib:.2f} GiB, "
            f"opt {self.opt_bytes / gib:.2f} GiB, "
            f"activations ~{self.activations_bytes / gib:.2f} GiB "
            f"(total {self.total_bytes / gib:.2f} GiB); "
            f"largest replicated tensor: {path} ({rb / gib:.2f} GiB)"
        )


def _sharded_bytes(shape, dtype, spec, mesh_shape: Dict[str, int]) -> int:
    """Bytes of one array's shard on a single chip under ``spec``.

    Per-dim extents round UP (a dim of 10 sharded 8 ways puts ceil(10/8)=2
    rows on a chip, padded) — budgets must never undercount."""
    extents = list(shape)
    for d, axis in enumerate(spec):
        if axis is None or d >= len(extents):
            continue
        k = 1
        for a in (axis if isinstance(axis, tuple) else (axis,)):
            k *= mesh_shape[a]
        extents[d] = -(-extents[d] // k)  # ceil division
    n = int(np.prod(extents)) if extents else 1
    return n * jnp.dtype(dtype).itemsize


def training_memory(
    model,
    shardings,
    mesh_shape: Dict[str, int],
    *,
    tx=None,
    batch_per_chip: int = 1,
    param_dtype=jnp.float32,
    compute_dtype=None,
    remat: bool = False,
    seed: int = 0,
    params=None,
    zero: bool = False,
    data_axis: str = "data",
) -> MemoryBudget:
    """Per-chip byte budget for training ``model`` under ``shardings``.

    ``shardings`` is a pytree of ``NamedSharding``/``PartitionSpec``
    matching the param tree (build it with ``fsdp_sharding`` /
    ``tp_sharding`` over an ``AbstractMesh`` — no devices needed).
    Gradients mirror the parameter shardings; optimizer slots are counted
    from ``jax.eval_shape(tx.init, params)`` with param-shaped leaves
    sharded like their param.

    ``zero=True`` counts optimizer slots at their ZeRO weight-update
    placement instead (``ShardedTrainer(zero=True)``): each param-shaped
    slot's spec gains the ``data_axis`` per
    ``parallel.sharding.zero_update_spec`` — the same rule the trainer
    places real state with, so ``opt_bytes`` drops by ~the data-axis
    size.  Params/grads are unchanged: ZeRO-1 keeps params at their
    model-axis placement between steps (the gradient reduce-scatter and
    param all-gather are transient, inside the step).

    ``params`` (concrete or ShapeDtypeStruct tree) overrides the
    re-initialized tree — required for pruned models, whose surgered
    trees (e.g. an irregular GQA head set) cannot round-trip through
    ``model.init``.
    """
    from torchpruner_tpu.core.segment import init_model

    if params is None:
        params, _ = jax.eval_shape(
            lambda k: init_model(model, seed=seed), jax.random.PRNGKey(seed)
        )
    flat_p, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_s = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec") or _is_pspec(x)
    )
    if len(flat_p) != len(flat_s):
        raise ValueError(
            f"shardings tree has {len(flat_s)} leaves, params {len(flat_p)}"
        )

    p_bytes = 0
    largest_rep = ("", 0)
    specs = []
    for (path, leaf), sh in zip(flat_p, flat_s):
        spec = sh.spec if hasattr(sh, "spec") else sh
        specs.append(spec)
        b = _sharded_bytes(leaf.shape, param_dtype, spec, mesh_shape)
        p_bytes += b
        if all(a is None for a in spec):
            full = int(np.prod(leaf.shape)) * jnp.dtype(param_dtype).itemsize
            if full > largest_rep[1]:
                name = "/".join(str(getattr(k, "key", k)) for k in path)
                largest_rep = (name, full)
    # gradients arrive in the params' dtype/sharding (bf16 grads when the
    # whole backward is bf16 would halve this; masters stay f32 here)
    g_bytes = p_bytes

    opt_bytes = 0
    if tx is not None:
        import optax

        opt_shapes = jax.eval_shape(tx.init, params)
        # map specs onto the optimizer state STRUCTURALLY (each param-
        # shaped slot gets exactly its param's spec, same-shape params
        # with different specs included) — the same rule the trainer uses
        # for real placement (parallel/train.py _shardings)
        spec_tree = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), specs
        )
        opt_specs = optax.tree_map_params(
            tx,
            lambda _leaf, spec: spec,
            opt_shapes,
            spec_tree,
            transform_non_params=lambda _leaf: None,
        )
        for leaf, spec in zip(
            jax.tree_util.tree_leaves(opt_shapes),
            jax.tree_util.tree_leaves(
                opt_specs, is_leaf=lambda x: x is None or _is_pspec(x)
            ),
        ):
            if spec is None:
                opt_bytes += int(np.prod(leaf.shape) or 1) * jnp.dtype(
                    leaf.dtype
                ).itemsize
            else:
                if zero:
                    from torchpruner_tpu.parallel.sharding import (
                        zero_update_spec,
                    )

                    spec = zero_update_spec(leaf.shape, spec, mesh_shape,
                                            data_axis)
                opt_bytes += _sharded_bytes(
                    leaf.shape, leaf.dtype, spec, mesh_shape
                )

    act_dtype = compute_dtype if compute_dtype is not None else param_dtype
    act = 0
    for shp in getattr(model, "shapes", ()):
        out_shape = shp[1] if isinstance(shp, tuple) and len(shp) == 2 else shp
        act += int(np.prod(out_shape)) * batch_per_chip
    act_bytes = act * jnp.dtype(act_dtype).itemsize
    if remat:
        # saved activations shrink to block boundaries; the recompute
        # peak is roughly one block's internals
        act_bytes //= 2

    return MemoryBudget(
        params_bytes=int(p_bytes),
        grads_bytes=int(g_bytes),
        opt_bytes=int(opt_bytes),
        activations_bytes=int(act_bytes),
        largest_replicated=largest_rep,
    )


def _is_pspec(x) -> bool:
    from jax.sharding import PartitionSpec

    return isinstance(x, PartitionSpec)

"""Sequence-parallel training — long-context causal-LM steps over a
``data × seq`` mesh.

DP/FSDP/TP (parallel/train.py) shard batches and weights; this trainer
shards the SEQUENCE dim, the axis that grows in long-context training
(SURVEY.md §5.7, BASELINE.json llama configs).  The whole train step runs
under ``shard_map``: every position-independent layer (norms, dense, MoE)
computes on its local sequence shard, and the attention layers — built
with ``impl="ring"`` or ``"ulysses"`` (core/layers.py) — exchange KV
shards by ``ppermute`` rotation or heads by ``all_to_all``, with RoPE at
each shard's global offset.  Per-token losses and gradients are
``psum``-reduced over both mesh axes; parameters stay replicated (compose
with gradient accumulation for memory; FSDP×SP composition is a later
step).

The causal next-token shift crosses shard boundaries, so the trainer
aligns targets on the host once per batch (``y[:, t]``'s target is
``y[:, t+1]``): each shard then has a fully local masked loss — no halo
exchange inside the step.

``SPTrainer`` mirrors the ``Trainer``/``ShardedTrainer`` surface (step /
rebuild / evaluate) and is equality-tested against the single-device
trainer in tests/test_sp_trainer.py.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np
import jax.numpy as jnp
import optax
from jax import lax

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.parallel.mesh import relaxed_shard_map
from torchpruner_tpu.core.segment import SegmentedModel


def sp_model(model: SegmentedModel, impl: str = "ring") -> SegmentedModel:
    """``model`` with every attention layer switched to the ``impl``
    sequence-parallel core (``"ring"`` | ``"ulysses"``) — or back to a
    single-device core (``"auto"`` | ``"xla"`` | ``"flash"``), which is
    how :meth:`SPTrainer.evaluate` runs outside ``shard_map``."""
    if impl not in ("ring", "ulysses", "auto", "xla", "flash"):
        raise ValueError(f"unknown SP impl {impl!r}")

    def convert(spec):
        if isinstance(spec, L.MultiHeadAttention):
            return dataclasses.replace(spec, impl=impl)
        if isinstance(spec, L.Residual):
            return dataclasses.replace(
                spec,
                body=tuple(convert(c) for c in spec.body),
                shortcut=tuple(convert(c) for c in spec.shortcut),
            )
        return spec

    return dataclasses.replace(
        model, layers=tuple(convert(s) for s in model.layers)
    )


def _contains_batchnorm(layers) -> bool:
    for spec in layers:
        if isinstance(spec, L.BatchNorm):
            return True
        if isinstance(spec, L.Residual) and (
            _contains_batchnorm(spec.body)
            or _contains_batchnorm(spec.shortcut)
        ):
            return True
    return False


def aligned_targets(tokens) -> tuple:
    """``(targets, mask)`` with ``targets[:, t] = tokens[:, t + 1]`` and the
    final (targetless) position masked out — the host-side shift that makes
    the causal-LM loss local to each sequence shard."""
    tokens = np.asarray(tokens)
    tgt = np.concatenate(
        [tokens[:, 1:], np.zeros_like(tokens[:, :1])], axis=1
    )
    mask = np.ones(tokens.shape, np.float32)
    mask[:, -1] = 0.0
    return tgt, mask


@dataclass
class SPTrainer:
    """Causal-LM trainer with the sequence dim sharded over ``seq`` (and
    the batch over ``data``).  Parameters replicated; loss is the masked
    mean next-token cross-entropy over all predicted positions."""

    model: SegmentedModel
    params: Any
    state: Any
    tx: Any
    opt_state: Any
    rng: Any
    mesh: Mesh
    impl: str = "ring"
    #: None = f32; jnp.bfloat16 = mixed precision (f32 masters, same
    #: policy as train.loop.make_loss_closure)
    compute_dtype: Any = None
    #: checkpoint composite blocks (recompute-in-backward)
    remat: bool = False
    _step_fn: Any = field(default=None, repr=False)
    step_count: int = 0

    @classmethod
    def create(
        cls,
        model: SegmentedModel,
        tx,
        mesh: Mesh,
        seed: int = 0,
        impl: str = "ring",
        compute_dtype=None,
        remat: bool = False,
    ) -> "SPTrainer":
        for axis in ("data", "seq"):
            if axis not in mesh.axis_names:
                raise ValueError(
                    f"SPTrainer needs a '{axis}' mesh axis, got "
                    f"{mesh.axis_names}"
                )
        if _contains_batchnorm(model.layers):
            # The shard_map step returns replicated out_specs with
            # check_vma=False; per-shard-divergent running stats would
            # silently come back as one shard's values.  Same guard as
            # generate._decode_seq — LM families use LayerNorm/RMSNorm.
            raise NotImplementedError(
                "SPTrainer does not support BatchNorm (per-batch running "
                "stats diverge across sequence shards); use LayerNorm/"
                "RMSNorm"
            )
        model = sp_model(model, impl)
        key = jax.random.PRNGKey(seed)
        params, state = model.init(key)
        t = cls(
            model=model, params=params,
            state=state if state is not None else {}, tx=tx,
            opt_state=tx.init(params), rng=key, mesh=mesh, impl=impl,
            compute_dtype=compute_dtype, remat=remat,
        )
        t._compile()
        return t

    def _compile(self):
        from torchpruner_tpu.utils.dtypes import cast_floats

        model, tx, mesh = self.model, self.tx, self.mesh
        compute_dtype, remat = self.compute_dtype, self.remat
        repl = P()
        bseq = P("data", "seq")

        def local_step(params, state, opt_state, x, tgt, mask, rng):
            # distinct dropout streams per shard
            rng = jax.random.fold_in(
                rng,
                lax.axis_index("data") * 4096 + lax.axis_index("seq"),
            )

            def loss_fn(p):
                if compute_dtype is not None:
                    p = cast_floats(p, compute_dtype)
                logits, new_state = model.apply(
                    p, x, state=state, train=True, rng=rng, remat=remat
                )
                logp = jax.nn.log_softmax(
                    logits.astype(jnp.float32), axis=-1
                )
                nll = -jnp.take_along_axis(
                    logp, tgt[..., None], axis=-1
                )[..., 0]
                loc_sum = jnp.sum(nll * mask)
                loc_cnt = jnp.sum(mask)
                total = lax.psum(loc_sum, ("data", "seq"))
                count = lax.psum(loc_cnt, ("data", "seq"))
                return total / count, new_state

            (l, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            grads = lax.psum(grads, ("data", "seq"))
            updates, new_opt = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_state, new_opt, l

        mapped = relaxed_shard_map(
            local_step,
            mesh,
            in_specs=(repl, repl, repl, bseq, bseq, bseq, repl),
            out_specs=(repl, repl, repl, repl),
        )  # check disabled: the ulysses path runs a Pallas kernel
        self._step_fn = jax.jit(mapped, donate_argnums=(0, 2))
        self._bseq = NamedSharding(mesh, bseq)

    def step(self, tokens) -> float:
        """One SP train step on a ``(B, S)`` token batch (B divisible by
        the data axis, S by the seq axis)."""
        tgt, mask = aligned_targets(tokens)
        x = jax.device_put(jnp.asarray(tokens), self._bseq)
        tgt = jax.device_put(jnp.asarray(tgt), self._bseq)
        mask = jax.device_put(jnp.asarray(mask), self._bseq)
        self.rng, sub = jax.random.split(self.rng)
        self.params, self.state, self.opt_state, l = self._step_fn(
            self.params, self.state, self.opt_state, x, tgt, mask, sub
        )
        self.step_count += 1
        return l

    def evaluate(self, data, loss_fn):
        """Average loss/accuracy over ``data`` — runs the single-device
        attention core (params are replicated, so evaluation needs no
        sequence sharding; pass batches of ``(tokens, targets)``)."""
        from torchpruner_tpu.train.loop import evaluate

        return evaluate(
            sp_model(self.model, "auto"), self.params, self.state, data,
            loss_fn,
        )

    def rebuild(self, model, params, state, opt_state) -> "SPTrainer":
        """Adopt pruned pytrees (e.g. after FFN-channel or head pruning)
        and recompile at the new shapes."""
        t = SPTrainer(
            model=sp_model(model, self.impl), params=params,
            state=state if state is not None else {}, tx=self.tx,
            opt_state=opt_state, rng=self.rng, mesh=self.mesh,
            impl=self.impl, compute_dtype=self.compute_dtype,
            remat=self.remat, step_count=self.step_count,
        )
        t._compile()
        return t

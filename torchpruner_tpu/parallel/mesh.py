"""Device-mesh construction.

One mesh, named axes, everything else is sharding annotations — the
"pick a mesh, annotate shardings, let XLA insert collectives" recipe.
Default axes: ``data`` (DP / sharded scoring) × ``model`` (FSDP/TP).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

DEFAULT_AXES = ("data", "model")


def make_mesh(
    axes: Optional[Dict[str, int]] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Build a mesh from ``{axis_name: size}``.

    - ``axes=None``: all devices on a 1-D ``data`` axis (pure DP).
    - sizes may use ``-1`` once, meaning "whatever is left".
    - the product must equal the device count.

    On real TPU slices ``mesh_utils.create_device_mesh`` lays the axes out so
    the innermost axis maps to physically-adjacent chips (ICI neighbors);
    put the highest-bandwidth-demand axis (``model``) last.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    names = tuple(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {int(np.prod(sizes))} "
            f"devices, have {n}"
        )
    mesh_devices = mesh_utils.create_device_mesh(
        tuple(sizes), devices=devices
    )
    return Mesh(mesh_devices, names)


def mesh_axes(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))

"""Device-mesh construction — single-slice and multi-slice (ICI × DCN).

One mesh, named axes, everything else is sharding annotations — the
"pick a mesh, annotate shardings, let XLA insert collectives" recipe.
Default axes: ``data`` (DP / sharded scoring) × ``model`` (FSDP/TP).

Multi-host: each host runs the same SPMD program; call
:func:`initialize_distributed` once at startup (before any jax call) so
the hosts form one runtime, then build the mesh over ``jax.devices()``
(which then lists EVERY host's devices).  Across pod slices, use
:func:`make_hybrid_mesh`: DCN-parallel axes (data) span slices, ICI axes
(model/FSDP) stay inside a slice — collectives ride the fast fabric, only
gradient all-reduces cross the data-center network.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

DEFAULT_AXES = ("data", "model")


def initialize_distributed(**kw) -> bool:
    """Join this process into the multi-host JAX runtime (the
    communication-backend bring-up NCCL/MPI setups do by hand; here it is
    one call).  Pass ``coordinator_address``/``num_processes``/
    ``process_id`` explicitly, or export ``JAX_COORDINATOR_ADDRESS`` (on
    TPU pods the remaining fields auto-discover from the metadata
    service).

    Returns True when running distributed, False when the single-process
    fallback was kept (no ``coordinator_address`` passed and no
    ``JAX_COORDINATOR_ADDRESS`` in the environment — e.g. local tests).
    Safe to call unconditionally at entry-point startup.
    """
    configured = kw.get("coordinator_address") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if not configured:
        return False
    jax.distributed.initialize(**kw)
    return True


def make_hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Dict[str, int],
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Mesh spanning multiple pod slices: ``ici_axes`` partition within a
    slice (model/FSDP — the bandwidth-hungry collectives), ``dcn_axes``
    across slices (data parallelism — one gradient all-reduce per step).

    ``make_hybrid_mesh({"model": 4}, {"data": 2})`` on 2×4-chip slices
    gives the same named axes as ``make_mesh({"data": 2, "model": 4})``
    on one 8-chip slice — shardings and trainers are layout-agnostic, so
    code written against the hybrid mesh runs unchanged on a single slice
    (the fallback when the devices carry no slice topology, e.g. CPU
    tests or one pod slice).
    """
    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    sizes = tuple(dcn_axes.values()) + tuple(ici_axes.values())
    try:
        mesh_devices = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_axes.values()),
            dcn_mesh_shape=tuple(dcn_axes.values()),
            devices=devices,
        )
    except (ValueError, AssertionError):
        # no multi-slice topology available: same axis names/sizes as a
        # plain mesh (device count must still match — make_mesh checks)
        return make_mesh(dict(zip(names, sizes)), devices=devices)
    return Mesh(mesh_devices, names)


def make_mesh(
    axes: Optional[Dict[str, int]] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Build a mesh from ``{axis_name: size}``.

    - ``axes=None``: all devices on a 1-D ``data`` axis (pure DP).
    - sizes may use ``-1`` once, meaning "whatever is left".
    - the product must equal the device count.

    On real TPU slices ``mesh_utils.create_device_mesh`` lays the axes out so
    the innermost axis maps to physically-adjacent chips (ICI neighbors);
    put the highest-bandwidth-demand axis (``model``) last.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    names = tuple(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {int(np.prod(sizes))} "
            f"devices, have {n}"
        )
    mesh_devices = mesh_utils.create_device_mesh(
        tuple(sizes), devices=devices
    )
    return Mesh(mesh_devices, names)


def mesh_axes(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def axis_size(axis: str) -> int:
    """Static size of a named mesh axis from inside ``shard_map``,
    portable across jax versions (``lax.axis_size`` arrived in 0.8; the
    older spelling ``lax.psum(1, axis)`` constant-folds to the same
    Python int under tracing)."""
    from jax import lax

    try:
        return lax.axis_size(axis)  # jax >= 0.8
    except AttributeError:  # pragma: no cover - older jax
        return lax.psum(1, axis)


def relaxed_shard_map(fn, mesh, in_specs, out_specs):
    """``shard_map`` with the varying-mesh-axes/replication check
    disabled, portable across jax versions: the entry point moved from
    ``jax.experimental.shard_map`` to ``jax.shard_map`` (0.8) and the
    flag was renamed ``check_rep`` -> ``check_vma``.  Used by the SP /
    Ulysses paths, whose Pallas flash kernel produces outputs the checker
    cannot annotate even though the computation is correctly per-shard.
    """
    import inspect

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    flag = (
        "check_vma"
        if "check_vma" in inspect.signature(shard_map).parameters
        else "check_rep"
    )
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **{flag: False})

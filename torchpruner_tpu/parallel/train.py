"""Sharded training: DP over the ``data`` axis × FSDP over ``model``.

The train step is the same pure function as the single-device one
(torchpruner_tpu/train/loop.py); distribution is entirely in the placement:
params/opt-state live sharded under the FSDP rule, batches arrive sharded on
``data``, and jit compiles one SPMD program in which XLA has inserted the
gradient all-reduce (DP) and parameter all-gather / gradient reduce-scatter
(FSDP).  ``out_shardings`` pins results to the input layout so buffers are
donated cleanly step to step.

After a prune step, ``rebuild`` re-shards the smaller arrays over the same
mesh and recompiles at the new shapes — the distributed version of the
recompilation economics in SURVEY.md §7.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import numpy as np
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchpruner_tpu import obs
from torchpruner_tpu.core.segment import SegmentedModel
from torchpruner_tpu.train.loop import _batch_tokens
from torchpruner_tpu.parallel.sharding import (
    batch_sharding,
    fsdp_sharding,
    replicate,
    shard_batch,
    tp_sharding,
)


def make_sharded_train_step(
    model: SegmentedModel,
    tx,
    loss_fn,
    mesh: Mesh,
    param_shardings,
    state_shardings,
    opt_shardings,
    data_axis: str = "data",
    compute_dtype=None,
    remat: bool = False,
    accum_steps: int = 1,
    moe_aux_weight: float = 0.0,
    grad_norm: bool = False,
    guard: bool = False,
):
    """Compile the SPMD train step with explicit in/out shardings.
    Mixed precision / remat / gradient accumulation come from the shared
    ``train.loop`` step body — one forward-and-update policy for the local
    and the SPMD steps.  With ``accum_steps``, each scanned microbatch
    keeps its example dim sharded on ``data_axis``.  ``grad_norm`` makes
    the loss output a ``(loss, global grad norm)`` pair (XLA inserts the
    cross-shard reduction; the ``rep`` out-sharding prefix covers both).
    ``guard`` compiles the non-finite skip guard into the SPMD program
    (the ``ok`` decision is a replicated scalar, so every shard skips or
    applies the update identically — mesh-consistent by construction)."""
    from torchpruner_tpu.train.loop import make_loss_closure, make_step_body

    loss_c = make_loss_closure(model, loss_fn, compute_dtype, remat,
                               moe_aux_weight)
    bs = batch_sharding(mesh, data_axis)
    rep = replicate(mesh)

    return jax.jit(
        make_step_body(loss_c, tx, accum_steps, grad_norm, guard),
        in_shardings=(param_shardings, state_shardings, opt_shardings,
                      bs, bs, rep),
        out_shardings=(param_shardings, state_shardings, opt_shardings, rep),
        donate_argnums=(0, 2),
    )


@dataclass
class ShardedTrainer:
    """DP×FSDP trainer over a mesh; same surface as ``train.loop.Trainer``."""

    model: SegmentedModel
    params: Any
    state: Any
    tx: Any
    opt_state: Any
    loss_fn: Callable
    rng: Any
    mesh: Mesh
    data_axis: str = "data"
    model_axis: str = "model"
    min_shard_size: int = 2**14
    #: "fsdp" = shard each large param's largest axis; "tp" = pruning-graph
    #: tensor parallelism (column/row-parallel pairs) with FSDP fallback
    partition: str = "fsdp"
    #: None = f32; jnp.bfloat16 = mixed precision (f32 masters)
    compute_dtype: Any = None
    #: checkpoint composite blocks (recompute-in-backward)
    remat: bool = False
    #: >1 = gradient accumulation over scanned microbatches
    accum_steps: int = 1
    #: >0 adds that multiple of the MoE load-balancing loss
    moe_aux_weight: float = 0.0
    #: opt-in telemetry: step also returns the global grad norm
    grad_norm: bool = False
    #: optional ``resilience.StepGuard`` — non-finite skip guard compiled
    #: into the SPMD step; see ``train.loop.Trainer.guard``
    guard: Any = None
    _step_fn: Any = field(default=None, repr=False)
    #: previous step's end timestamp — see train.loop.Trainer._t_stream
    #: (telemetry records return-to-return intervals within a streak)
    _t_stream: Any = field(default=None, repr=False)
    step_count: int = 0

    @classmethod
    def create(
        cls,
        model: SegmentedModel,
        tx,
        loss_fn,
        mesh: Mesh,
        seed: int = 0,
        data_axis: str = "data",
        model_axis: str = "model",
        min_shard_size: int = 2**14,
        partition: str = "fsdp",
        compute_dtype=None,
        remat: bool = False,
        accum_steps: int = 1,
        moe_aux_weight: float = 0.0,
        grad_norm: bool = False,
        guard: Any = None,
    ) -> "ShardedTrainer":
        key = jax.random.PRNGKey(seed)
        params, state = model.init(key)
        opt_state = tx.init(params)
        t = cls(
            model=model, params=params, state=state, tx=tx,
            opt_state=opt_state, loss_fn=loss_fn, rng=key, mesh=mesh,
            data_axis=data_axis, model_axis=model_axis,
            min_shard_size=min_shard_size, partition=partition,
            compute_dtype=compute_dtype, remat=remat,
            accum_steps=accum_steps, moe_aux_weight=moe_aux_weight,
            grad_norm=grad_norm, guard=guard,
        )
        t._place()
        return t

    # -- placement ---------------------------------------------------------

    def _shardings(self):
        if self.partition not in ("fsdp", "tp"):
            raise ValueError(
                f"unknown partition {self.partition!r} (use 'fsdp' or 'tp')"
            )
        if self.partition == "tp":
            ps = tp_sharding(self.model, self.params, self.mesh,
                             self.model_axis, self.min_shard_size)
        else:
            ps = fsdp_sharding(self.params, self.mesh, self.model_axis,
                               self.min_shard_size)
        ss = jax.tree_util.tree_map(lambda _: replicate(self.mesh), self.state)
        # param-shaped optimizer-state leaves (momentum, Adam m/v) shard with
        # their param; non-param leaves (step counts) replicate
        os_ = optax.tree_map_params(
            self.tx,
            lambda _leaf, spec: spec,
            self.opt_state,
            ps,
            transform_non_params=lambda _leaf: replicate(self.mesh),
        )
        return ps, ss, os_

    def _place(self):
        with obs.span("shard", partition=self.partition):
            ps, ss, os_ = self._shardings()
            self.params = jax.device_put(self.params, ps)
            self.state = jax.device_put(self.state, ss)
            self.opt_state = jax.device_put(self.opt_state, os_)
            self._step_fn = make_sharded_train_step(
                self.model, self.tx, self.loss_fn, self.mesh, ps, ss, os_,
                self.data_axis, compute_dtype=self.compute_dtype,
                remat=self.remat, accum_steps=self.accum_steps,
                moe_aux_weight=self.moe_aux_weight,
                grad_norm=self.grad_norm, guard=self.guard is not None,
            )
            self._record_memory_budget(ps)

    def _record_memory_budget(self, param_shardings):
        """Planned per-chip bytes (parallel.memory.training_memory) as obs
        gauges, plus live device bytes where the runtime reports them —
        the HBM side of the step telemetry.  Best-effort: telemetry must
        never block placement."""
        session = obs.get()
        if session is None:
            return
        try:
            from torchpruner_tpu.obs.metrics import record_device_memory
            from torchpruner_tpu.parallel.memory import training_memory

            budget = training_memory(
                self.model, param_shardings, dict(self.mesh.shape),
                tx=self.tx, compute_dtype=self.compute_dtype,
                remat=self.remat, params=self.params,
            )
            g = session.metrics.gauge
            g("planned_params_bytes_per_chip").set(budget.params_bytes)
            g("planned_grads_bytes_per_chip").set(budget.grads_bytes)
            g("planned_opt_bytes_per_chip").set(budget.opt_bytes)
            g("planned_total_bytes_per_chip").set(budget.total_bytes)
            record_device_memory(session.metrics)
        except Exception:
            pass

    # -- training ----------------------------------------------------------

    def step(self, x, y) -> float:
        from torchpruner_tpu.resilience import chaos as _chaos

        if _chaos.active():
            # same deterministic fault-injection boundary as the local
            # Trainer (kill / synthetic OOM / NaN-poisoned batch)
            _chaos.maybe_kill(self.step_count)
            _chaos.maybe_oom(self.step_count)
            x = _chaos.poison_batch(self.step_count, x)
        x, y = shard_batch((jnp.asarray(x), jnp.asarray(y)), self.mesh,
                           self.data_axis)
        self.rng, sub = jax.random.split(self.rng)
        self.params, self.state, self.opt_state, l = self._step_fn(
            self.params, self.state, self.opt_state, x, y, sub
        )
        self.step_count += 1
        if self.grad_norm or self.guard is not None:
            parts = l if isinstance(l, tuple) else (l,)
            l = parts[0]
            if self.grad_norm:
                obs.record_grad_norm(parts[1])
            if self.guard is not None:
                self.guard.observe(bool(parts[-1]))
        now = time.perf_counter()
        if self._t_stream is not None:
            # first step of a streak: dispatch-only time, not recorded
            # (see train.loop.Trainer.step)
            obs.record_step(now - self._t_stream, x.shape[0],
                            _batch_tokens(x, y))
        self._t_stream = now
        return l

    def rebuild(self, model, params, state, opt_state) -> "ShardedTrainer":
        """Adopt pruned (smaller) pytrees: re-shard over the same mesh,
        recompile the step."""
        t = ShardedTrainer(
            model=model, params=params,
            state=state if state is not None else {},
            tx=self.tx, opt_state=opt_state, loss_fn=self.loss_fn,
            rng=self.rng, mesh=self.mesh, data_axis=self.data_axis,
            model_axis=self.model_axis, min_shard_size=self.min_shard_size,
            partition=self.partition, compute_dtype=self.compute_dtype,
            remat=self.remat, accum_steps=self.accum_steps,
            moe_aux_weight=self.moe_aux_weight, grad_norm=self.grad_norm,
            guard=self.guard, step_count=self.step_count,
        )
        t._place()
        return t

    def evaluate(self, data):
        """Evaluation with every batch sharded over the data axis (XLA
        all-reduces the loss/count sums).  A batch that doesn't divide the
        axis is PADDED to the next multiple (repeating its last example)
        and evaluated under a validity mask, so the ragged final batch of
        a dataset keeps all devices busy instead of silently replicating —
        while still counting exactly the real examples."""
        from torchpruner_tpu.train.loop import make_masked_eval_step

        self._t_stream = None  # eval wall time is not step time
        # multi-process mesh: each host feeds its LOCAL shard (the same
        # contract as step()/shard_batch), pads to its addressable share
        # of the data axis, and the mask keeps global counts exact
        multiprocess = any(d.process_index != jax.process_index()
                           for d in self.mesh.devices.flat)
        n = (sum(d.process_index == jax.process_index()
                 for d in self.mesh.devices.flat) if multiprocess
             else self.mesh.shape[self.data_axis])
        step = make_masked_eval_step(self.model, self.loss_fn)
        tot_l, tot_c, tot_n, tot_p = 0.0, 0, 0, 0
        for x, y in (data() if callable(data) else data):
            x, y = jnp.asarray(x), jnp.asarray(y)
            b = x.shape[0]
            pad = (-b) % n
            if pad:
                x = jnp.concatenate([x, jnp.repeat(x[-1:], pad, axis=0)])
                y = jnp.concatenate([y, jnp.repeat(y[-1:], pad, axis=0)])
            valid = jnp.arange(b + pad) < b
            x, y, valid = shard_batch((x, y, valid), self.mesh,
                                      self.data_axis)
            l, c, nn, n_pred = step(self.params, self.state, x, y, valid)
            tot_l += float(l)
            tot_c += int(c)
            tot_n += int(nn)
            tot_p += int(n_pred)
        if tot_n == 0:
            from torchpruner_tpu.train.loop import _warn_empty_eval

            _warn_empty_eval("ShardedTrainer.evaluate()")
            raise ValueError("evaluate() got an empty dataset")
        return tot_l / tot_n, tot_c / tot_p

"""Sharded training: DP over the ``data`` axis × FSDP over ``model``.

The train step is the same pure function as the single-device one
(torchpruner_tpu/train/loop.py); distribution is entirely in the placement:
params/opt-state live sharded under the FSDP rule, batches arrive sharded on
``data``, and jit compiles one SPMD program in which XLA has inserted the
gradient all-reduce (DP) and parameter all-gather / gradient reduce-scatter
(FSDP).  ``out_shardings`` pins results to the input layout so buffers are
donated cleanly step to step.

After a prune step, ``rebuild`` re-shards the smaller arrays over the same
mesh and recompiles at the new shapes — the distributed version of the
recompilation economics in SURVEY.md §7.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import numpy as np
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchpruner_tpu import obs
from torchpruner_tpu.core.segment import SegmentedModel
from torchpruner_tpu.train.loop import _batch_tokens
from torchpruner_tpu.parallel.sharding import (
    batch_sharding,
    replicate,
    shard_batch,
)


def plan_placements(model, params, state, opt_state, tx, mesh,
                    *, partition: str = "fsdp", zero: bool = False,
                    data_axis: str = "data", model_axis: str = "model",
                    min_shard_size: int = 2 ** 14, plant: str = None):
    """``(param, state, opt, zero)`` NamedSharding trees — the ONE
    placement planner shared by :class:`ShardedTrainer` and the static
    analyzer's collective-contract pass (analysis/collective_lint.py).
    Pure tree/shape work: ``params``/``state``/``opt_state`` may be
    concrete arrays or abstract ``ShapeDtypeStruct`` trees, so the lint
    plans the EXACT placement production will use without materializing
    a parameter.

    ``plant="replicated_allreduce"`` knocks the ZeRO update transform
    out (the zero tree comes back ``None`` while the caller still
    believes ``zero=True``) — the planted hazard the collective lint's
    CI drill drives (env ``TORCHPRUNER_LINT_PLANT``, read ONLY by the
    lint drivers via ``analysis/collective_lint.env_plant`` — never by
    the trainer or the telemetry cost predictor, so a stale shell
    export can neither degrade real training nor skew the run's
    ``predicted_*`` gauges),
    standing in for the refactor that regresses the reduce-scatter →
    sharded update → all-gather sequence to a replicated all-reduce
    while every numeric test still passes."""
    from torchpruner_tpu.parallel.sharding import (
        fsdp_sharding as _fsdp, tp_sharding as _tp,
        zero_update_sharding as _zero,
    )

    if partition not in ("fsdp", "tp"):
        raise ValueError(
            f"unknown partition {partition!r} (use 'fsdp' or 'tp')"
        )
    if partition == "tp":
        ps = _tp(model, params, mesh, model_axis, min_shard_size)
    else:
        ps = _fsdp(params, mesh, model_axis, min_shard_size)
    ss = jax.tree_util.tree_map(lambda _: replicate(mesh), state)
    zs = None
    if zero and mesh.shape.get(data_axis, 1) > 1:
        zs = _zero(params, ps, mesh, data_axis)
    if plant == "replicated_allreduce":
        zs = None  # the planted hazard: ZeRO silently knocked out
    # param-shaped optimizer-state leaves (momentum, Adam m/v) shard with
    # their param — or with the ZeRO update domain when zero=True; non-
    # param leaves (step counts) replicate
    os_ = optax.tree_map_params(
        tx,
        lambda _leaf, spec: spec,
        opt_state,
        zs if zs is not None else ps,
        transform_non_params=lambda _leaf: replicate(mesh),
    )
    return ps, ss, os_, zs


def mesh_factorizations(n_devices: int, *, data_axis: str = "data",
                        model_axis: str = "model",
                        max_model: int = None) -> list:
    """Every 2-axis factorization of ``n_devices`` into
    ``{data: d, model: m}`` with ``d*m == n_devices`` — the mesh half of
    the planner's candidate space (analysis/planner.py).  Ordered
    data-major (pure DP first, pure model-parallel last); the pure-DP
    entry omits the degenerate ``model: 1`` axis so the candidate config
    round-trips through the same validation the hand-written presets
    use.  ``max_model`` bounds the model axis (attention-head counts
    rarely divide very wide TP).  Every returned mesh is a valid input
    to :func:`plan_placements` — the enumeration and the placement
    planner share one config vocabulary by construction."""
    out = []
    n = max(1, int(n_devices))
    for m in range(1, n + 1):
        if n % m:
            continue
        if max_model is not None and m > max_model:
            break
        d = n // m
        if m == 1:
            out.append({data_axis: d})
        else:
            out.append({data_axis: d, model_axis: m})
    return out


def make_sharded_train_step(
    model: SegmentedModel,
    tx,
    loss_fn,
    mesh: Mesh,
    param_shardings,
    state_shardings,
    opt_shardings,
    data_axis: str = "data",
    compute_dtype=None,
    remat: bool = False,
    accum_steps: int = 1,
    moe_aux_weight: float = 0.0,
    grad_norm: bool = False,
    guard: bool = False,
    zero_shardings=None,
):
    """Compile the SPMD train step with explicit in/out shardings.
    Mixed precision / remat / gradient accumulation come from the shared
    ``train.loop`` step body — one forward-and-update policy for the local
    and the SPMD steps.  With ``accum_steps``, each scanned microbatch
    keeps its example dim sharded on ``data_axis``.  ``grad_norm`` makes
    the loss output a ``(loss, global grad norm)`` pair (XLA inserts the
    cross-shard reduction; the ``rep`` out-sharding prefix covers both).
    ``guard`` compiles the non-finite skip guard into the SPMD program
    (the ``ok`` decision is a replicated scalar, so every shard skips or
    applies the update identically — mesh-consistent by construction).

    ``zero_shardings`` (``ShardedTrainer(zero=True)``) is the param-shaped
    update-domain placement: the step body reduce-scatters gradients onto
    the data axis, updates the local 1/N shard, and all-gathers fresh
    params — with ``opt_shardings`` expected to already carry the same
    data-sharded placement so optimizer state persists at 1/N per chip."""
    from torchpruner_tpu.train.loop import make_loss_closure, make_step_body

    loss_c = make_loss_closure(model, loss_fn, compute_dtype, remat,
                               moe_aux_weight)
    bs = batch_sharding(mesh, data_axis)
    rep = replicate(mesh)

    return jax.jit(
        make_step_body(loss_c, tx, accum_steps, grad_norm, guard,
                       zero_shardings=zero_shardings,
                       gather_shardings=param_shardings),
        in_shardings=(param_shardings, state_shardings, opt_shardings,
                      bs, bs, rep),
        out_shardings=(param_shardings, state_shardings, opt_shardings, rep),
        donate_argnums=(0, 2),
    )


def make_sharded_multi_step(
    model: SegmentedModel,
    tx,
    loss_fn,
    mesh: Mesh,
    param_shardings,
    state_shardings,
    opt_shardings,
    data_axis: str = "data",
    compute_dtype=None,
    remat: bool = False,
    accum_steps: int = 1,
    moe_aux_weight: float = 0.0,
    zero_shardings=None,
):
    """``(params, state, opt_state, xs, ys, rng) -> (params, state,
    opt_state, rng', losses)`` — K full optimizer steps in ONE compiled
    SPMD program over stacked batches ``xs`` of shape ``(K, B, ...)``
    (each scanned batch keeps its example dim sharded on ``data_axis``).
    The SPMD twin of :func:`torchpruner_tpu.train.loop.make_multi_step`,
    with the same 1/K dispatch amortization; the inner body is the shared
    step body, so ZeRO update sharding (``zero_shardings``) composes —
    each scanned step carries its own reduce-scatter → sharded update →
    all-gather sequence."""
    from torchpruner_tpu.train.loop import make_loss_closure, make_step_body

    loss_c = make_loss_closure(model, loss_fn, compute_dtype, remat,
                               moe_aux_weight)
    step = make_step_body(loss_c, tx, accum_steps,
                          zero_shardings=zero_shardings,
                          gather_shardings=param_shardings)
    rep = replicate(mesh)
    bs2 = NamedSharding(mesh, P(None, data_axis))  # (K, B, ...) stacks

    def multi(params, state, opt_state, xs, ys, rng):
        def body(carry, inp):
            p, st, o, r = carry
            xb, yb = inp
            r, sub = jax.random.split(r)
            p, st, o, l = step(p, st, o, xb, yb, sub)
            return (p, st, o, r), l

        (params, state, opt_state, rng), losses = jax.lax.scan(
            body, (params, state, opt_state, rng), (xs, ys)
        )
        return params, state, opt_state, rng, losses

    return jax.jit(
        multi,
        in_shardings=(param_shardings, state_shardings, opt_shardings,
                      bs2, bs2, rep),
        out_shardings=(param_shardings, state_shardings, opt_shardings,
                       rep, rep),
        donate_argnums=(0, 2),
    )


@dataclass
class ShardedTrainer:
    """DP×FSDP trainer over a mesh; same surface as ``train.loop.Trainer``."""

    model: SegmentedModel
    params: Any
    state: Any
    tx: Any
    opt_state: Any
    loss_fn: Callable
    rng: Any
    mesh: Mesh
    data_axis: str = "data"
    model_axis: str = "model"
    min_shard_size: int = 2**14
    #: "fsdp" = shard each large param's largest axis; "tp" = pruning-graph
    #: tensor parallelism (column/row-parallel pairs) with FSDP fallback
    partition: str = "fsdp"
    #: ZeRO-style cross-replica weight-update sharding: optimizer state
    #: (every param-shaped slot whose shape divides) lives sharded over
    #: the DATA axis on top of the partition's model-axis spec, gradients
    #: reduce-scatter instead of all-reduce, the update applies to the
    #: local 1/N shard, and fresh params all-gather for the next forward.
    #: Composes with both partitions, accum_steps, guard, and multi_step;
    #: frees ~(1 - 1/data_axis) of optimizer HBM per chip.
    zero: bool = False
    #: None = f32; jnp.bfloat16 = mixed precision (f32 masters)
    compute_dtype: Any = None
    #: checkpoint composite blocks (recompute-in-backward)
    remat: bool = False
    #: >1 = gradient accumulation over scanned microbatches
    accum_steps: int = 1
    #: >0 adds that multiple of the MoE load-balancing loss
    moe_aux_weight: float = 0.0
    #: opt-in telemetry: step also returns the global grad norm
    grad_norm: bool = False
    #: optional ``resilience.StepGuard`` — non-finite skip guard compiled
    #: into the SPMD step; see ``train.loop.Trainer.guard``
    guard: Any = None
    _step_fn: Any = field(default=None, repr=False)
    _multi_fn: Any = field(default=None, repr=False)
    #: placement tuple from the last _place(), for multi_step compilation
    _placements: Any = field(default=None, repr=False)
    #: previous step's end timestamp — see train.loop.Trainer._t_stream
    #: (telemetry records return-to-return intervals within a streak)
    _t_stream: Any = field(default=None, repr=False)
    step_count: int = 0

    @classmethod
    def create(
        cls,
        model: SegmentedModel,
        tx,
        loss_fn,
        mesh: Mesh,
        seed: int = 0,
        data_axis: str = "data",
        model_axis: str = "model",
        min_shard_size: int = 2**14,
        partition: str = "fsdp",
        zero: bool = False,
        compute_dtype=None,
        remat: bool = False,
        accum_steps: int = 1,
        moe_aux_weight: float = 0.0,
        grad_norm: bool = False,
        guard: Any = None,
        params: Any = None,
        state: Any = None,
        opt_state: Any = None,
    ) -> "ShardedTrainer":
        """``params``/``state``/``opt_state`` adopt restored host trees
        directly (placed once, at their actual shapes) instead of
        re-initializing — required for pruned/surgered models, whose
        trees cannot round-trip through ``model.init``."""
        key = jax.random.PRNGKey(seed)
        if params is None:
            params, state = model.init(key)
        elif state is None:
            state = {}
        if opt_state is None:
            opt_state = tx.init(params)
        t = cls(
            model=model, params=params, state=state, tx=tx,
            opt_state=opt_state, loss_fn=loss_fn, rng=key, mesh=mesh,
            data_axis=data_axis, model_axis=model_axis,
            min_shard_size=min_shard_size, partition=partition, zero=zero,
            compute_dtype=compute_dtype, remat=remat,
            accum_steps=accum_steps, moe_aux_weight=moe_aux_weight,
            grad_norm=grad_norm, guard=guard,
        )
        t._place()
        return t

    # -- placement ---------------------------------------------------------

    def _shardings(self):
        """``(param, state, opt, zero)`` sharding trees via the shared
        :func:`plan_placements` planner (one placement policy for the
        trainer and the static analyzer).  ``zero`` is the param-shaped
        update-domain tree (param spec + data axis) or None; when set,
        param-shaped optimizer slots take IT as their placement — the
        persistent 1/N-per-chip opt state ZeRO is for."""
        return plan_placements(
            self.model, self.params, self.state, self.opt_state, self.tx,
            self.mesh, partition=self.partition, zero=self.zero,
            data_axis=self.data_axis, model_axis=self.model_axis,
            min_shard_size=self.min_shard_size,
        )

    def _place(self):
        with obs.span("shard", partition=self.partition, zero=self.zero):
            ps, ss, os_, zs = self._shardings()
            self.params = jax.device_put(self.params, ps)
            self.state = jax.device_put(self.state, ss)
            self.opt_state = jax.device_put(self.opt_state, os_)
            self._placements = (ps, ss, os_, zs)
            self._step_fn = make_sharded_train_step(
                self.model, self.tx, self.loss_fn, self.mesh, ps, ss, os_,
                self.data_axis, compute_dtype=self.compute_dtype,
                remat=self.remat, accum_steps=self.accum_steps,
                moe_aux_weight=self.moe_aux_weight,
                grad_norm=self.grad_norm, guard=self.guard is not None,
                zero_shardings=zs,
            )
            self._multi_fn = None  # compiled lazily at the stacked shape
            self._record_memory_budget(ps)

    def _record_memory_budget(self, param_shardings):
        """Planned per-chip bytes (parallel.memory.training_memory) as obs
        gauges, plus live device bytes where the runtime reports them —
        the HBM side of the step telemetry.  Best-effort: telemetry must
        never block placement."""
        session = obs.get()
        if session is None:
            return
        try:
            from torchpruner_tpu.obs.metrics import record_device_memory
            from torchpruner_tpu.parallel.memory import training_memory

            budget = training_memory(
                self.model, param_shardings, dict(self.mesh.shape),
                tx=self.tx, compute_dtype=self.compute_dtype,
                remat=self.remat, params=self.params,
                zero=self.zero, data_axis=self.data_axis,
            )
            g = session.metrics.gauge
            g("planned_params_bytes_per_chip").set(budget.params_bytes)
            g("planned_grads_bytes_per_chip").set(budget.grads_bytes)
            g("planned_opt_bytes_per_chip").set(budget.opt_bytes)
            g("planned_total_bytes_per_chip").set(budget.total_bytes)
            if self.zero:
                # the counterfactual replicated-update budget next to the
                # ZeRO one, so the freed opt HBM is a first-class gauge
                rep = training_memory(
                    self.model, param_shardings, dict(self.mesh.shape),
                    tx=self.tx, compute_dtype=self.compute_dtype,
                    remat=self.remat, params=self.params,
                )
                g("planned_opt_replicated_bytes_per_chip").set(rep.opt_bytes)
                g("zero_opt_bytes_freed_per_chip").set(
                    max(0, rep.opt_bytes - budget.opt_bytes))
            record_device_memory(session.metrics)
        except Exception:
            pass

    # -- training ----------------------------------------------------------

    def step(self, x, y) -> float:
        from torchpruner_tpu.resilience import chaos as _chaos

        if _chaos.active():
            # same deterministic fault-injection boundary as the local
            # Trainer (kill / synthetic OOM / NaN-poisoned batch)
            _chaos.maybe_kill(self.step_count)
            _chaos.maybe_oom(self.step_count)
            x = _chaos.poison_batch(self.step_count, x)
        x, y = shard_batch((jnp.asarray(x), jnp.asarray(y)), self.mesh,
                           self.data_axis)
        self.rng, sub = jax.random.split(self.rng)
        self.params, self.state, self.opt_state, l = self._step_fn(
            self.params, self.state, self.opt_state, x, y, sub
        )
        self.step_count += 1
        if self.grad_norm or self.guard is not None:
            parts = l if isinstance(l, tuple) else (l,)
            l = parts[0]
            if self.grad_norm:
                obs.record_grad_norm(parts[1])
            if self.guard is not None:
                self.guard.observe(bool(parts[-1]))
        now = time.perf_counter()
        if self._t_stream is not None:
            # first step of a streak: dispatch-only time, not recorded
            # (see train.loop.Trainer.step)
            obs.record_step(now - self._t_stream, x.shape[0],
                            _batch_tokens(x, y))
        self._t_stream = now
        return l

    def multi_step(self, xs, ys):
        """K full optimizer steps in ONE dispatched SPMD program over
        stacked batches ``xs`` of shape (K, B, ...) — the distributed
        twin of ``Trainer.multi_step`` (1/K dispatch amortization).
        Each scanned batch shards its example dim over the data axis;
        ZeRO update sharding rides along when ``zero=True``.  Returns the
        (K,) per-step losses; identical results to K :meth:`step` calls
        on the same data (modulo guard/grad_norm, which multi_step does
        not thread — use :meth:`step` for guarded runs)."""
        if self._multi_fn is None:
            ps, ss, os_, zs = self._placements
            self._multi_fn = make_sharded_multi_step(
                self.model, self.tx, self.loss_fn, self.mesh, ps, ss, os_,
                self.data_axis, compute_dtype=self.compute_dtype,
                remat=self.remat, accum_steps=self.accum_steps,
                moe_aux_weight=self.moe_aux_weight, zero_shardings=zs,
            )
        xs, ys = jnp.asarray(xs), jnp.asarray(ys)
        sh = NamedSharding(self.mesh, P(None, self.data_axis))
        xs, ys = jax.device_put(xs, sh), jax.device_put(ys, sh)
        (self.params, self.state, self.opt_state, self.rng,
         losses) = self._multi_fn(
            self.params, self.state, self.opt_state, xs, ys, self.rng
        )
        k = int(xs.shape[0])
        self.step_count += k
        now = time.perf_counter()
        if self._t_stream is not None:  # see step(): first of a streak
            yshape = getattr(ys, "shape", ())
            tok = int(yshape[0] * yshape[1] * yshape[2]) \
                if len(yshape) >= 3 else None
            obs.record_step(now - self._t_stream, int(xs.shape[1]) * k,
                            tok, steps=k)
        self._t_stream = now
        return losses

    def rebuild(self, model, params, state, opt_state) -> "ShardedTrainer":
        """Adopt pruned (smaller) pytrees: re-shard over the same mesh,
        recompile the step.  ``zero=True`` carries through: the SMALLER
        optimizer state re-shards over the data axis (leaves whose pruned
        dims stopped dividing fall back per ``zero_update_spec``)."""
        t = ShardedTrainer(
            model=model, params=params,
            state=state if state is not None else {},
            tx=self.tx, opt_state=opt_state, loss_fn=self.loss_fn,
            rng=self.rng, mesh=self.mesh, data_axis=self.data_axis,
            model_axis=self.model_axis, min_shard_size=self.min_shard_size,
            partition=self.partition, zero=self.zero,
            compute_dtype=self.compute_dtype,
            remat=self.remat, accum_steps=self.accum_steps,
            moe_aux_weight=self.moe_aux_weight, grad_norm=self.grad_norm,
            guard=self.guard, step_count=self.step_count,
        )
        t._place()
        return t

    def evaluate(self, data):
        """Evaluation with every batch sharded over the data axis (XLA
        all-reduces the loss/count sums).  A batch that doesn't divide the
        axis is PADDED to the next multiple with ZEROS and evaluated under
        a validity mask, so the ragged final batch of a dataset keeps all
        devices busy instead of silently replicating — while still
        counting exactly the real examples.  Zeros, not a repeat of the
        last example: the mask multiplication cannot scrub a non-finite
        padded row (``inf * 0 = nan``), so a NaN/Inf-poisoned final
        example (chaos runs) must never be replicated into the padding —
        it should count exactly once, like on a single device."""
        from torchpruner_tpu.train.loop import make_masked_eval_step

        self._t_stream = None  # eval wall time is not step time
        # multi-process mesh: each host feeds its LOCAL shard (the same
        # contract as step()/shard_batch), pads to its addressable share
        # of the data axis, and the mask keeps global counts exact
        multiprocess = any(d.process_index != jax.process_index()
                           for d in self.mesh.devices.flat)
        n = (sum(d.process_index == jax.process_index()
                 for d in self.mesh.devices.flat) if multiprocess
             else self.mesh.shape[self.data_axis])
        step = make_masked_eval_step(self.model, self.loss_fn)
        tot_l, tot_c, tot_n, tot_p = 0.0, 0, 0, 0
        for x, y in (data() if callable(data) else data):
            x, y = jnp.asarray(x), jnp.asarray(y)
            b = x.shape[0]
            pad = (-b) % n
            if pad:
                x = jnp.concatenate(
                    [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
                y = jnp.concatenate(
                    [y, jnp.zeros((pad,) + y.shape[1:], y.dtype)])
            valid = jnp.arange(b + pad) < b
            x, y, valid = shard_batch((x, y, valid), self.mesh,
                                      self.data_axis)
            l, c, nn, n_pred = step(self.params, self.state, x, y, valid)
            tot_l += float(l)
            tot_c += int(c)
            tot_n += int(nn)
            tot_p += int(n_pred)
        if tot_n == 0:
            from torchpruner_tpu.train.loop import _warn_empty_eval

            _warn_empty_eval("ShardedTrainer.evaluate()")
            raise ValueError("evaluate() got an empty dataset")
        return tot_l / tot_n, tot_c / tot_p

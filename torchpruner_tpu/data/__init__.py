"""Input pipeline.

The reference uses torch DataLoaders over torchvision datasets (reference
experiments/models/mnist.py:51-82, cifar10.py:102-161).  Here datasets are
in-memory numpy arrays batched by a lightweight, deterministic iterator that
knows how to shard per host/device for data-parallel scoring and training.

This environment has no network egress, so ``load_dataset`` serves
deterministic synthetic data with the real datasets' shapes unless arrays
are found on disk (``TORCHPRUNER_TPU_DATA_DIR`` pointing at ``{name}_{split}
_x.npy`` / ``_y.npy`` files) — the loader interface is identical either way.
"""

from torchpruner_tpu.data.datasets import (
    Dataset,
    load_dataset,
    norm_zero,
    synthetic_dataset,
    synthetic_token_dataset,
)
from torchpruner_tpu.data.native import (
    augment_batch,
    device_prefetch,
    native_available,
    prefetch_batches,
    shuffled_indices,
)

__all__ = [
    "Dataset",
    "load_dataset",
    "norm_zero",
    "synthetic_dataset",
    "synthetic_token_dataset",
    "native_available",
    "augment_batch",
    "device_prefetch",
    "prefetch_batches",
    "shuffled_indices",
]

"""Prepare real datasets into the ``TORCHPRUNER_TPU_DATA_DIR`` npy layout.

The framework's loaders (:func:`~torchpruner_tpu.data.load_dataset`) look
for ``{name}_{split}_{x,y}.npy`` under ``$TORCHPRUNER_TPU_DATA_DIR`` before
synthesizing (datasets.py).  This module converts the standard public
distribution files — which a user downloads once, offline — into that
layout, reproducing the reference's preprocessing exactly:

- **MNIST** from the four IDX files (``train-images-idx3-ubyte[.gz]`` ...),
  normalized with the canonical ``(0.1307, 0.3081)`` mean/std the reference
  uses (reference experiments/models/mnist.py:56-60), 54k/6k train/val
  split by fixed permutation plus the 10k test set; written both as
  ``mnist`` (28, 28, 1) and ``mnist_flat`` (784,) layouts.
- **CIFAR-10** from the ``cifar-10-batches-py`` python pickles, normalized
  with the ImageNet statistics the reference uses (reference
  experiments/models/cifar10.py:104-110: mean (0.485, 0.456, 0.406), std
  (0.229, 0.224, 0.225)), 45k/5k train/val split plus the 10k test set;
  written as ``cifar10`` NHWC and ``cifar10_flat``.  Train-time
  augmentation (random crop + flip, reference cifar10.py:112-117) is NOT
  baked in — ``data.native.augment_batch`` applies it per
  epoch, matching torchvision's on-the-fly transforms.
- **digits** needs no input files: scikit-learn bundles the real data, and
  ``load_dataset("digits", ...)`` serves it directly; ``prepare_digits``
  exists only to materialize the same arrays for inspection.

CLI::

    python -m torchpruner_tpu.data.prepare mnist   --src /path/to/idx_dir --out $TORCHPRUNER_TPU_DATA_DIR
    python -m torchpruner_tpu.data.prepare cifar10 --src /path/to/cifar-10-batches-py --out $TORCHPRUNER_TPU_DATA_DIR
    python -m torchpruner_tpu.data.prepare digits  --out $TORCHPRUNER_TPU_DATA_DIR
"""

from __future__ import annotations

import argparse
import gzip
import os
import pickle
import struct
from typing import Dict, Tuple

import numpy as np

MNIST_MEAN, MNIST_STD = 0.1307, 0.3081
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

_SPLIT_SEED = 0  # fixed permutation for the train/val split


def _open_maybe_gz(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def _find(src: str, *names: str) -> str:
    for n in names:
        for cand in (os.path.join(src, n), os.path.join(src, n + ".gz")):
            if os.path.exists(cand):
                return cand
    raise FileNotFoundError(f"none of {names} (or .gz) under {src}")


def read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (the MNIST distribution format)."""
    with _open_maybe_gz(path) as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        if (magic >> 8) != 0x08:  # 0x08 = unsigned byte data
            raise ValueError(f"{path}: unsupported IDX magic {magic:#x}")
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _split(
    x: np.ndarray, y: np.ndarray, n_val: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    idx = np.random.default_rng(_SPLIT_SEED).permutation(len(x))
    val, train = idx[:n_val], idx[n_val:]
    return x[train], y[train], x[val], y[val]


def _write(out: str, name: str, split: str, x: np.ndarray, y: np.ndarray):
    os.makedirs(out, exist_ok=True)
    np.save(os.path.join(out, f"{name}_{split}_x.npy"), x)
    np.save(os.path.join(out, f"{name}_{split}_y.npy"), y.astype(np.int32))


def _write_image_and_flat(out, name, split, x, y):
    _write(out, name, split, x, y)
    _write(out, f"{name}_flat", split, x.reshape(len(x), -1), y)


def prepare_mnist(src: str, out: str, n_val: int = 6000) -> Dict[str, int]:
    """IDX files -> mnist / mnist_flat npy layout.  Returns split sizes."""
    xs = read_idx(_find(src, "train-images-idx3-ubyte", "train-images.idx3-ubyte"))
    ys = read_idx(_find(src, "train-labels-idx1-ubyte", "train-labels.idx1-ubyte"))
    xt = read_idx(_find(src, "t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"))
    yt = read_idx(_find(src, "t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"))

    def norm(a):
        a = a.astype(np.float32) / 255.0
        return ((a - MNIST_MEAN) / MNIST_STD)[..., None]  # NHWC, C=1

    xs, xt = norm(xs), norm(xt)
    x_tr, y_tr, x_val, y_val = _split(xs, ys, n_val)
    for split, (x, y) in {
        "train": (x_tr, y_tr), "val": (x_val, y_val), "test": (xt, yt),
    }.items():
        _write_image_and_flat(out, "mnist", split, x, y)
    return {"train": len(x_tr), "val": len(x_val), "test": len(xt)}


def prepare_cifar10(src: str, out: str, n_val: int = 5000) -> Dict[str, int]:
    """``cifar-10-batches-py`` pickles -> cifar10 / cifar10_flat layout."""

    def read_batch(name):
        with open(_find(src, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # NHWC
        return x, np.asarray(d[b"labels"])

    parts = [read_batch(f"data_batch_{i}") for i in range(1, 6)]
    xs = np.concatenate([p[0] for p in parts])
    ys = np.concatenate([p[1] for p in parts])
    xt, yt = read_batch("test_batch")

    def norm(a):
        a = a.astype(np.float32) / 255.0
        return (a - IMAGENET_MEAN) / IMAGENET_STD

    xs, xt = norm(xs), norm(xt)
    x_tr, y_tr, x_val, y_val = _split(xs, ys, n_val)
    for split, (x, y) in {
        "train": (x_tr, y_tr), "val": (x_val, y_val), "test": (xt, yt),
    }.items():
        _write_image_and_flat(out, "cifar10", split, x, y)
    return {"train": len(x_tr), "val": len(x_val), "test": len(xt)}


def prepare_digits(out: str) -> Dict[str, int]:
    """Materialize the bundled sklearn digits under the npy layout (the
    loaders already serve it without this; see module docstring)."""
    from torchpruner_tpu.data.datasets import _load_digits

    sizes = {}
    for split in ("train", "val", "test"):
        for name in ("digits", "digits_flat"):
            ds = _load_digits(name, split)
            _write(out, name, split, ds.x, ds.y)
        sizes[split] = len(ds.x)
    return sizes


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dataset", choices=["mnist", "cifar10", "digits"])
    ap.add_argument("--src", default="", help="directory with the "
                    "downloaded distribution files (mnist/cifar10)")
    ap.add_argument("--out", default=os.environ.get(
        "TORCHPRUNER_TPU_DATA_DIR", "data"))
    args = ap.parse_args(argv)
    if args.dataset == "digits":
        sizes = prepare_digits(args.out)
    elif args.dataset == "mnist":
        sizes = prepare_mnist(args.src, args.out)
    else:
        sizes = prepare_cifar10(args.src, args.out)
    print(f"{args.dataset} -> {args.out}: {sizes}")


if __name__ == "__main__":
    main()

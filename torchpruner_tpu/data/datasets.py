"""In-memory datasets with deterministic batching and device sharding."""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

#: (input_shape channels-last, n_classes) of the reference's datasets, plus
#: the BASELINE.json image targets.
DATASET_SHAPES = {
    "mnist": ((28, 28, 1), 10),
    "fashion_mnist": ((28, 28, 1), 10),
    "cifar10": ((32, 32, 3), 10),
    "mnist_flat": ((784,), 10),
    "cifar10_flat": ((3072,), 10),
    "imagenet": ((224, 224, 3), 1000),
    "imagenet64": ((64, 64, 3), 1000),
    "tiny_images16": ((16, 16, 3), 10),
    # scikit-learn's bundled handwritten-digits set (1,797 REAL 8x8 scans,
    # no download): the in-CI real-data vehicle for the reference's
    # untrained-net-pruning and method-ranking experiments
    "digits": ((8, 8, 1), 10),
    "digits_flat": ((64,), 10),
    # digits upscaled 8x8 -> 32x32 (nearest-neighbour) and tiled to 3
    # channels: REAL image data at CIFAR-10 geometry, so VGG16-bn-scale
    # experiments (training + the layerwise-robustness sweep) can run on
    # a genuinely trained net in environments without the CIFAR files
    "digits32": ((32, 32, 3), 10),
    "digits32_flat": ((3072,), 10),
}

#: fixed deterministic split of the 1,797 digits examples
_DIGITS_SPLIT = {"train": (0, 1297), "val": (1297, 1497), "test": (1497, 1797)}

def norm_zero(name: str) -> Optional[np.ndarray]:
    """Where a raw-zero pixel lands after ``name``'s normalization:
    ``-mean/std`` per channel, or None when the dataset is not
    standardized (0 is already the raw-zero value).

    Stats come from the one place that defines the on-disk normalization
    (data/prepare.py — reference experiments/models/mnist.py:56-60,
    cifar10.py:104-110).  Only image datasets prepare.py standardizes
    appear; flat variants are omitted (augmentation passes non-4D data
    through untouched), and so are scaled-only sets like digits.

    This is the border fill that makes post-normalization augmentation
    (:func:`~torchpruner_tpu.data.native.augment_batch`) bit-match the
    reference's pad-raw-then-Normalize order (its cifar10.py:105-110
    RandomCrop runs before Normalize)."""
    from torchpruner_tpu.data import prepare

    stats = {
        "mnist": ((prepare.MNIST_MEAN,), (prepare.MNIST_STD,)),
        "cifar10": (prepare.IMAGENET_MEAN, prepare.IMAGENET_STD),
    }.get(name)
    if stats is None:
        return None
    mean, std = (np.asarray(v, np.float32) for v in stats)
    return -mean / std

#: (seq_len, vocab_size, n_classes) — token datasets; ``n_classes=None``
#: marks language-modeling data (targets = inputs, next-token loss).
TOKEN_DATASET_SHAPES = {
    "glue_sst2": (128, 30522, 2),
    "glue_tiny": (16, 128, 2),
    "lm_corpus": (2048, 128256, None),
    "lm_mfu": (1024, 32000, None),  # matches models.mfu_llama
    "lm_tiny": (16, 256, None),
}


@dataclass
class Dataset:
    """A pair of arrays + batching.  ``batches()`` returns a list (re-iterable,
    the contract attribution metrics expect); ``iter_batches`` streams."""

    x: np.ndarray
    y: np.ndarray
    name: str = "dataset"

    def __len__(self):
        return len(self.x)

    def subset(self, n: int, seed: int = 0) -> "Dataset":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(self.x))[:n]
        return Dataset(self.x[idx], self.y[idx], self.name)

    def resample(self, n: int, seed: int = 0) -> "Dataset":
        """``n`` examples drawn WITH replacement — grows a split past its
        real size for cost-curve measurements (wall-clock depends on
        array sizes, not label novelty; see experiments/sweep_scaling).
        Not for accuracy evaluation: repeated examples bias statistics."""
        rng = np.random.default_rng(seed)
        idx = rng.integers(0, len(self.x), size=n)
        return Dataset(self.x[idx], self.y[idx],
                       f"{self.name}[resampled {n}]")

    def host_shard(self, index: Optional[int] = None,
                   count: Optional[int] = None) -> "Dataset":
        """This host's slice for multi-host data parallelism: host ``i``
        of ``count`` takes examples ``i::count`` (a strided view — no
        copy for memmapped on-disk arrays), so every host feeds its local
        devices a disjoint shard and global batches assemble by sharded
        device_put.  Defaults to ``jax.process_index()/process_count()``
        (identity in single-process runs)."""
        import jax

        index = jax.process_index() if index is None else index
        count = jax.process_count() if count is None else count
        if not 0 <= index < count:
            raise ValueError(f"host index {index} not in [0, {count})")
        if count == 1:
            return self
        return Dataset(
            self.x[index::count], self.y[index::count],
            f"{self.name}[host {index}/{count}]",
        )

    def iter_batches(
        self,
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        drop_remainder: bool = False,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        n = len(self.x)
        idx = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(idx)
        stop = n - (n % batch_size) if drop_remainder else n
        for i in range(0, stop, batch_size):
            j = idx[i : i + batch_size]
            yield self.x[j], self.y[j]

    def batches(self, batch_size: int, **kw):
        return list(self.iter_batches(batch_size, **kw))


def synthetic_dataset(
    input_shape,
    n_classes: int,
    n: int,
    seed: int = 0,
    name: str = "synthetic",
    center_seed: int = 1234,
) -> Dataset:
    """Deterministic gaussian-blob classification data: class c is drawn
    around a class-specific random mean, so models can actually learn
    (loss decreases, pruning effects are measurable).

    Class centers depend only on ``center_seed`` — train/val/test splits
    generated with different ``seed`` values share the same class structure.
    """
    centers = np.random.default_rng(center_seed).normal(
        0.0, 1.0, size=(n_classes,) + tuple(input_shape)
    )
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, size=(n,))
    x = centers[y] + rng.normal(0.0, 1.0, size=(n,) + tuple(input_shape))
    return Dataset(x.astype(np.float32), y.astype(np.int32), name)


def _load_from_disk(name: str, split: str, dtype) -> Optional[Dataset]:
    """``$TORCHPRUNER_TPU_DATA_DIR/{name}_{split}_{x,y}.npy`` if present
    (real data drops in for any dataset name, image or token).

    ``x`` is memory-mapped: imagenet-scale arrays never fully
    materialize in host RAM — batching slices copy only the touched rows
    (labels are small and load eagerly).  The dtype conversion is skipped
    when the file already carries the requested dtype (what
    ``data/prepare.py`` writes), preserving the mapping; a mismatched
    dtype forces a one-time conversion in memory."""
    data_dir = os.environ.get("TORCHPRUNER_TPU_DATA_DIR", "")
    fx = os.path.join(data_dir, f"{name}_{split}_x.npy")
    fy = os.path.join(data_dir, f"{name}_{split}_y.npy")
    if data_dir and os.path.exists(fx) and os.path.exists(fy):
        x = np.load(fx, mmap_mode="r")
        if x.dtype != dtype:
            x = np.asarray(x).astype(dtype)
        # y maps too: for LM datasets the target file is corpus-sized
        y = np.load(fy, mmap_mode="r")
        if y.dtype != np.int32:
            y = np.asarray(y).astype(np.int32)
        return Dataset(x, y, name)
    return None


def synthetic_token_dataset(
    seq_len: int,
    vocab_size: int,
    n_classes: Optional[int],
    n: int,
    seed: int = 0,
    name: str = "tokens",
    center_seed: int = 1234,
) -> Dataset:
    """Deterministic synthetic token data.

    Classification (``n_classes`` set): each class has a preferred token
    subset (drawn from ``center_seed``); examples mix class tokens with
    uniform noise, so attention models can actually learn the labels.
    Language modeling (``n_classes=None``): first-order Markov sequences
    with a fixed random transition structure; targets are the inputs
    (next-token objective).
    """
    rng = np.random.default_rng(seed)
    cg = np.random.default_rng(center_seed)
    if n_classes is not None:
        pref = cg.integers(0, vocab_size, size=(n_classes, max(4, seq_len // 4)))
        y = rng.integers(0, n_classes, size=(n,))
        x = rng.integers(0, vocab_size, size=(n, seq_len))
        sig = rng.random((n, seq_len)) < 0.5  # half the positions carry signal
        choice = rng.integers(0, pref.shape[1], size=(n, seq_len))
        x = np.where(sig, pref[y[:, None], choice], x)
        return Dataset(x.astype(np.int32), y.astype(np.int32), name)
    # LM: sparse Markov chain — each token has a few likely successors
    succ = cg.integers(0, vocab_size, size=(vocab_size, 4))
    x = np.empty((n, seq_len), dtype=np.int64)
    x[:, 0] = rng.integers(0, vocab_size, size=(n,))
    for t in range(1, seq_len):
        pick = succ[x[:, t - 1], rng.integers(0, 4, size=(n,))]
        noise = rng.integers(0, vocab_size, size=(n,))
        x[:, t] = np.where(rng.random(n) < 0.8, pick, noise)
    x = x.astype(np.int32)
    return Dataset(x, x, name)


def _load_digits(name: str, split: str) -> Optional[Dataset]:
    """The real scikit-learn digits data (bundled with sklearn, no
    network).  Pixels scaled to [0, 1] (raw range 0..16); a fixed
    permutation (seed 0) makes the train/val/test split deterministic."""
    try:
        from sklearn.datasets import load_digits as _sk_load
    except ImportError:  # pragma: no cover - sklearn is in the base image
        return None
    if split not in _DIGITS_SPLIT:
        raise KeyError(
            f"unknown digits split {split!r} (use one of "
            f"{sorted(_DIGITS_SPLIT)})"
        )
    raw = _sk_load()
    x = (raw.data / 16.0).astype(np.float32)  # (1797, 64)
    y = raw.target.astype(np.int32)
    idx = np.random.default_rng(0).permutation(len(x))
    lo, hi = _DIGITS_SPLIT[split]
    sel = idx[lo:hi]
    x = x[sel]
    if name == "digits":
        x = x.reshape(-1, 8, 8, 1)
    return Dataset(x, y[sel], f"{name}:{split}")


def load_dataset(
    name: str, split: str = "train", n: Optional[int] = None, seed: int = 0
) -> Dataset:
    """Load ``name`` (see DATASET_SHAPES / TOKEN_DATASET_SHAPES) from disk
    if available, else synthesize with the right shapes.  ``n`` limits the
    example count."""
    if name == "synthetic":
        name = "mnist_flat"
    if name in TOKEN_DATASET_SHAPES:
        ds = _load_from_disk(name, split, dtype=np.int32)
        if ds is None:
            seq_len, vocab, n_classes = TOKEN_DATASET_SHAPES[name]
            defaults = {"train": 10000, "val": 1000, "test": 2000}
            count = n or defaults.get(split, 1000)
            split_seed = {"train": 1, "val": 2, "test": 3}.get(split, 9)
            ds = synthetic_token_dataset(
                seq_len, vocab, n_classes, count, seed=seed * 10 + split_seed,
                name=f"{name}:{split}:synthetic",
            )
        if n is not None and len(ds) > n:
            ds = ds.subset(n, seed=seed)
        return ds
    if name not in DATASET_SHAPES:
        raise KeyError(
            f"unknown dataset {name!r}; known: "
            f"{list(DATASET_SHAPES) + list(TOKEN_DATASET_SHAPES)}"
        )
    shape, n_classes = DATASET_SHAPES[name]
    ds = _load_from_disk(name, split, dtype=np.float32)
    if ds is None and name in ("digits", "digits_flat"):
        ds = _load_digits(name, split)
    if ds is None and name in ("digits32", "digits32_flat"):
        base = _load_digits("digits", split)
        if base is not None:
            x = np.kron(base.x, np.ones((1, 4, 4, 1), np.float32))
            x = np.repeat(x, 3, axis=3)
            if name == "digits32_flat":
                # CIFAR-10-FC geometry (3072 = 32*32*3,) on real scans —
                # the vehicle for the reference's untrained CIFAR10-FC row
                x = x.reshape(len(x), -1)
            ds = Dataset(x, base.y, f"{name}:{split}")
    if ds is None:
        defaults = {"train": 50000, "val": 1000, "test": 10000}
        count = n or defaults.get(split, 1000)
        # different splits draw from the same class centers (same seed for
        # centers via the generator chain) but different example noise
        split_seed = {"train": 1, "val": 2, "test": 3}.get(split, 9)
        ds = synthetic_dataset(shape, n_classes, count, seed=seed * 10 + split_seed,
                               name=f"{name}:{split}:synthetic")
    if n is not None and len(ds) > n:
        ds = ds.subset(n, seed=seed)
    return ds

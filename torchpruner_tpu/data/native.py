"""ctypes bindings for the native data-pipeline library (cpp/data_pipeline.cc),
with a bit-identical pure-Python fallback.

The native path exists for the host side of big-input pipelines (ImageNet-
sized batches): C++ releases the GIL during shuffle/gather, so the
:func:`prefetch_batches` background thread overlaps host batch assembly with
device compute — the role torch's multi-worker DataLoader plays for the
reference.  Both paths produce identical batches (splitmix64 Fisher-Yates),
so determinism does not depend on whether the library built.
"""

from __future__ import annotations

import ctypes
import os
import queue
import subprocess
import threading
from typing import Iterator, Optional, Tuple

import numpy as np

_CPP_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "build", "libtp_data.so")
_lib = None
_lib_tried = False


def _load_library(build: bool = True):
    """Load (building on first use) the native library; None if unavailable."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if build:
            # unconditional: make's dependency check makes this a no-op
            # when build/ is fresh, and REBUILDS a .so left behind by an
            # older source (a stale binary bound with current argtypes
            # would corrupt memory, not error)
            try:
                subprocess.run(
                    ["make", "-C", os.path.abspath(_CPP_DIR)],
                    check=not os.path.exists(_LIB_PATH),
                    capture_output=True, timeout=120,
                )
            except FileNotFoundError:
                # make-less environment: a prebuilt .so may still be
                # loadable — the ABI check below refuses a stale one
                if not os.path.exists(_LIB_PATH):
                    raise
        lib = ctypes.CDLL(_LIB_PATH)
        # belt and braces for make-less environments: refuse any binary
        # whose exported ABI version doesn't match these bindings
        try:
            lib.tp_abi_version.restype = ctypes.c_int32
            abi = int(lib.tp_abi_version())
        except AttributeError:
            abi = 1
        if abi != 2:
            _lib = None
            return None
        lib.tp_shuffle_indices.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_uint64,
        ]
        lib.tp_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.tp_augment_images.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int32,
        ]
        _lib = lib
    except (OSError, subprocess.SubprocessError):
        _lib = None
    return _lib


def native_available() -> bool:
    return _load_library() is not None


# -- splitmix64 Fisher-Yates: the shared determinism contract ---------------

_M = (1 << 64) - 1


def _splitmix64(s: int) -> Tuple[int, int]:
    s = (s + 0x9E3779B97F4A7C15) & _M
    z = s
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M
    return s, z ^ (z >> 31)


def _py_shuffle(n: int, seed: int) -> np.ndarray:
    idx = np.arange(n, dtype=np.int64)
    s = seed & _M
    for i in range(n - 1, 0, -1):
        bound = i + 1
        threshold = ((1 << 64) - bound) % bound  # 2^64 mod bound
        while True:
            s, r = _splitmix64(s)
            if r >= threshold:
                break
        j = r % bound
        idx[i], idx[j] = idx[j], idx[i]
    return idx


def shuffled_indices(n: int, seed: int) -> np.ndarray:
    """Seeded permutation of ``0..n-1`` — native when available, identical
    Python sequence otherwise."""
    lib = _load_library()
    if lib is None:
        return _py_shuffle(n, seed)
    idx = np.empty(n, dtype=np.int64)
    lib.tp_shuffle_indices(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n), ctypes.c_uint64(seed & _M),
    )
    return idx


def gather_rows(src: np.ndarray, idx: np.ndarray,
                n_threads: int = 4) -> np.ndarray:
    """``src[idx]`` into a fresh contiguous buffer; multithreaded memcpy in
    C++ (GIL released) when available."""
    lib = _load_library()
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    # validate up front so both paths agree: the C++ loop is a raw memcpy
    # (out-of-range would read out of bounds), and numpy would accept
    # negative indices the native path can't
    if idx.size and (idx.min() < 0 or idx.max() >= len(src)):
        bad = idx[(idx < 0) | (idx >= len(src))][0]
        raise IndexError(
            f"index {bad} out of range for gather over {len(src)} rows"
        )
    if lib is None:
        return src[idx]
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    lib.tp_gather_rows(
        ctypes.c_void_p(src.ctypes.data),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(len(idx)), ctypes.c_int64(row_bytes),
        ctypes.c_void_p(out.ctypes.data), ctypes.c_int32(n_threads),
    )
    return out


def _augment_draws(n: int, seed: int, pad: int):
    """The augmentation randomness contract, vectorized: per-example
    splitmix64 streams seeded ``seed ^ ((i+1) * 0xD1B54A32D192ED03)``,
    three draws each → (flip bool, dy, dx).  Bit-identical to the C++
    kernel's draws (cpp/data_pipeline.cc tp_augment_images)."""
    span = np.uint64(2 * pad + 1)
    s = (np.uint64(seed & _M)
         ^ (np.arange(1, n + 1, dtype=np.uint64)
            * np.uint64(0xD1B54A32D192ED03)))

    def draw(state):
        # uint64 arithmetic wraps mod 2^64 — exactly the C++ semantics
        state = state + np.uint64(0x9E3779B97F4A7C15)
        z = state.copy()
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return state, z ^ (z >> np.uint64(31))

    s, r1 = draw(s)
    s, r2 = draw(s)
    s, r3 = draw(s)
    return (
        (r1 & np.uint64(1)).astype(bool),
        (r2 % span).astype(np.int64),
        (r3 % span).astype(np.int64),
    )


def _augment_numpy(x: np.ndarray, seed: int, pad: int,
                   fill=None) -> np.ndarray:
    """The pure-numpy augmentation path — same draws, flip-then-pad-crop
    semantics as the native kernel (the bitwise-parity test compares the
    kernel against exactly this function)."""
    n, h, w, c = x.shape
    flip, dy, dx = _augment_draws(n, seed, pad)
    x = np.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
    if fill is None:
        padded = np.pad(
            x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="constant"
        )
    else:
        padded = np.empty((n, h + 2 * pad, w + 2 * pad, c), np.float32)
        padded[:] = np.asarray(fill, np.float32)
        padded[:, pad:pad + h, pad:pad + w, :] = x
    rows = dy[:, None] + np.arange(h)[None, :]
    cols = dx[:, None] + np.arange(w)[None, :]
    return padded[np.arange(n)[:, None, None], rows[:, :, None],
                  cols[:, None, :], :]


def augment_batch(x: np.ndarray, seed: int, pad: int = 4,
                  n_threads: int = 4, fill=None) -> np.ndarray:
    """Random horizontal flip + ``pad``-pixel shift-and-crop on a
    channels-last float32 image batch (after the reference's
    RandomHorizontalFlip + RandomCrop(32, padding=4), its
    cifar10.py:105-110).  Native kernel when built (fused, threaded, no
    padded intermediate), identical-output numpy fallback otherwise;
    non-image (non-4D) inputs pass through unchanged.

    ``fill`` sets the per-channel border value (length-``c`` vector, or
    None for 0).  This function runs AFTER normalization, whereas the
    reference pads the raw image with 0 BEFORE Normalize — so its border
    pixels sit at ``-mean/std``.  Pass ``fill=-mean/std``
    (:func:`~torchpruner_tpu.data.datasets.norm_zero` knows the standard
    datasets' values) to reproduce the reference's border statistics
    exactly; leave None for data that was scaled, not standardized
    (digits in [0, 1]), where 0 IS the raw-zero image value."""
    if x.ndim != 4:
        return x
    x = np.ascontiguousarray(x, dtype=np.float32)
    n, h, w, c = x.shape
    if fill is not None:
        fill = np.ascontiguousarray(fill, dtype=np.float32).reshape(-1)
        if fill.size == 1:
            fill = np.repeat(fill, c)
        if fill.size != c:
            raise ValueError(
                f"fill has {fill.size} channels, images have {c}"
            )
    lib = _load_library()
    if lib is not None:
        out = np.empty_like(x)
        lib.tp_augment_images(
            ctypes.c_void_p(x.ctypes.data), ctypes.c_int64(n),
            ctypes.c_int64(h), ctypes.c_int64(w), ctypes.c_int64(c),
            ctypes.c_int64(pad), ctypes.c_uint64(seed & _M),
            ctypes.c_void_p(0 if fill is None else fill.ctypes.data),
            ctypes.c_void_p(out.ctypes.data), ctypes.c_int32(n_threads),
        )
        return out
    return _augment_numpy(x, seed, pad, fill)


def prefetch_batches(
    dataset,
    batch_size: int,
    *,
    shuffle: bool = False,
    seed: int = 0,
    drop_remainder: bool = False,
    prefetch: int = 2,
    n_threads: int = 4,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Batches of ``dataset`` assembled in a background thread, ``prefetch``
    deep — host gather overlaps device compute.  Same batch contents as
    ``Dataset.iter_batches`` with native shuffling."""
    n = len(dataset)
    idx = shuffled_indices(n, seed) if shuffle else np.arange(n, dtype=np.int64)
    stop = n - (n % batch_size) if drop_remainder else n
    q: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
    _SENTINEL = object()
    _ERROR = object()

    def worker():
        try:
            for i in range(0, stop, batch_size):
                j = idx[i : i + batch_size]
                q.put((gather_rows(dataset.x, j, n_threads),
                       gather_rows(dataset.y, j, n_threads)))
        except BaseException as exc:  # propagate, never truncate silently
            q.put((_ERROR, exc))
        else:
            q.put(_SENTINEL)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _SENTINEL:
            break
        if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERROR:
            t.join()
            raise item[1]
        yield item
    t.join()


def device_prefetch(
    stream: Iterator[Tuple[np.ndarray, np.ndarray]],
    size: int = 2,
    device=None,
) -> Iterator[Tuple]:
    """Batches from ``stream`` already transferred to ``device``, kept
    ``size`` ahead of the consumer.

    ``jax.device_put`` is asynchronous, so issuing the NEXT batches'
    host→device copies before the current step is consumed overlaps PCIe
    transfer with device compute — the device-side half of the input
    pipeline (``prefetch_batches`` above is the host-side half; compose
    them).  Order and contents are unchanged."""
    import collections

    import jax

    def put(batch):
        return jax.tree.map(lambda a: jax.device_put(a, device), batch)

    buf: "collections.deque" = collections.deque()
    it = iter(stream)
    try:
        while len(buf) < max(1, size):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(put(next(it)))
        except StopIteration:
            pass
        yield out

"""Unified runtime telemetry: span tracing, step metrics, compile
accounting, exporters.

One :class:`ObsSession` per process, installed with :func:`configure` and
torn down with :func:`shutdown`.  Instrumented code talks to the module
functions — :func:`span`, :func:`record_step`, :func:`current_span_id` —
which are no-ops (one global load + ``None`` check) when no session is
active, so libraries can instrument unconditionally and pay nothing
unless a driver turned telemetry on.

Typical driver::

    from torchpruner_tpu import obs

    obs.configure(obs_dir="logs/obs")        # or obs_dir=None: summary only
    with obs.span("run", experiment=cfg.name):
        ...                                   # phases open nested spans
    print(obs.shutdown(), file=sys.stderr)    # summary table; writes
                                              # events.jsonl + metrics.prom

Multi-host: only ``process_index == 0`` emits files (every process still
tracks spans/metrics locally, so in-memory summaries work anywhere).
The index is read lazily from ``jax.process_index()`` on first emission
and can be overridden for tests via ``configure(process_index=...)``.

Design refs: JaxPruner's cheap per-step instrumentation argument
(arXiv:2304.14082) and the TPU structured-pruning study's MFU/step-time
reporting (arXiv:2107.04191) — see PAPERS.md.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, Optional

from torchpruner_tpu.obs.compile_watch import CompileWatcher
from torchpruner_tpu.obs.exporters import (
    JsonlWriter,
    prometheus_text,
    summary_table,
    write_prometheus,
)
from torchpruner_tpu.obs.metrics import (
    MetricsRegistry,
    StepTelemetry,
    record_device_memory,
    train_flops_per_step,
)
from torchpruner_tpu.obs.spans import SpanRecord, SpanTracer

__all__ = [
    "ObsSession", "configure", "get", "shutdown", "span",
    "current_span_id", "record_step", "record_grad_norm",
    "configure_step_flops", "record_capture", "capture_counts",
    "inc", "observe", "gauge_set", "counter_value",
    "MetricsRegistry", "StepTelemetry",
    "SpanTracer", "SpanRecord", "train_flops_per_step",
    "prometheus_text", "summary_table",
]

EVENTS_FILENAME = "events.jsonl"
PROM_FILENAME = "metrics.prom"

_session: Optional["ObsSession"] = None


class ObsSession:
    """The wiring: tracer + registry + step telemetry + compile watcher
    + (optional) file exporters rooted at ``obs_dir``."""

    def __init__(self, obs_dir: Optional[str] = None,
                 process_index: Optional[int] = None,
                 annotate: bool = True, watch_compiles: bool = True):
        self.obs_dir = obs_dir
        self._process_index = process_index
        self._closed = False
        self.t_start = time.perf_counter()
        self.metrics = MetricsRegistry()
        self.events: Optional[JsonlWriter] = None
        if obs_dir and self.is_emitter:
            self.events = JsonlWriter(os.path.join(obs_dir, EVENTS_FILENAME))
        self.tracer = SpanTracer(sink=self.events, annotate=annotate)
        self.step = StepTelemetry(self.metrics)
        self.compiles = CompileWatcher(self.metrics, self.tracer)
        if watch_compiles:
            self.compiles.start()
        if self.events is not None:
            self.events({
                "event": "obs_init", "ts": time.time(), "pid": os.getpid(),
                "process_index": self.process_index,
            })

    # -- multi-host gate ---------------------------------------------------

    @property
    def process_index(self) -> int:
        if self._process_index is None:
            try:
                import jax

                self._process_index = jax.process_index()
            except Exception:
                self._process_index = 0
        return self._process_index

    @property
    def is_emitter(self) -> bool:
        """True on the one process allowed to write files."""
        return self.process_index == 0

    # -- summaries / teardown ---------------------------------------------

    def derived(self) -> Dict[str, Optional[float]]:
        return self.step.derive()

    def summary(self) -> str:
        return summary_table(
            self.tracer.phase_summary(), self.derived(),
            self.compiles.counts(),
            total_wall_s=time.perf_counter() - self.t_start,
        )

    def close(self) -> str:
        """Stop listeners, flush files, return the terminal summary.
        Idempotent: a second close reports again but never re-touches the
        (already closed) event file."""
        self.compiles.stop()
        already_closed, self._closed = self._closed, True
        derived = self.derived()          # writes derived gauges
        record_device_memory(self.metrics)
        text = summary_table(
            self.tracer.phase_summary(), derived, self.compiles.counts(),
            total_wall_s=time.perf_counter() - self.t_start,
        )
        if self.events is not None and not already_closed:
            self.events({
                "event": "run_summary", "ts": time.time(),
                "wall_s": round(time.perf_counter() - self.t_start, 6),
                "phases": self.tracer.phase_summary(),
                "derived": derived,
                "compiles": self.compiles.counts(),
                "metrics": self.metrics.snapshot(),
            })
            self.events.close()
        if self.obs_dir and self.is_emitter:
            try:
                write_prometheus(
                    self.metrics, os.path.join(self.obs_dir, PROM_FILENAME))
            except Exception:
                pass
        return text


# -- module-level convenience (the instrumentation surface) -----------------


def configure(obs_dir: Optional[str] = None, *,
              process_index: Optional[int] = None, annotate: bool = True,
              watch_compiles: bool = True) -> ObsSession:
    """Install the process-wide session (replacing any previous one).
    The new session is constructed BEFORE the old one is torn down, so a
    failing constructor (e.g. unwritable ``obs_dir``) leaves the previous
    session installed and intact."""
    global _session
    new = ObsSession(obs_dir, process_index=process_index,
                     annotate=annotate, watch_compiles=watch_compiles)
    if _session is not None:
        _session.close()
    _session = new
    return new


def get() -> Optional[ObsSession]:
    return _session


def shutdown(print_to=None) -> str:
    """Tear down the active session; returns (and optionally prints) the
    end-of-run summary table.  No-op empty string without a session."""
    global _session
    if _session is None:
        return ""
    text = _session.close()
    _session = None
    if print_to is not None:
        print(text, file=print_to, flush=True)
    return text


def span(name: str, **meta):
    """Open a named phase span (no-op context manager when telemetry is
    off).  Usable as ``with obs.span("retrain", target=t):``."""
    s = _session
    if s is None:
        return contextlib.nullcontext()
    return s.tracer.span(name, **meta)


def current_span_id() -> Optional[str]:
    s = _session
    return s.tracer.current_id() if s is not None else None


def record_step(dt_s: float, examples: int, tokens: Optional[int] = None,
                steps: int = 1):
    """Per-train-step hot path — microseconds; see StepTelemetry."""
    s = _session
    if s is not None:
        s.step.on_step(dt_s, examples, tokens, steps)


def record_grad_norm(gnorm) -> None:
    s = _session
    if s is not None:
        s.step.on_grad_norm(float(gnorm))


def record_capture(hits: int = 0, misses: int = 0,
                   prefix_flops_saved: float = 0.0) -> None:
    """Attribution capture-cache accounting (one-pass sweep engine,
    attributions.base.ActivationCache).  ``hits``/``misses`` count
    SCORING PASSES (one metric run or ablation walk) whose prefix
    forward was read from / recomputed despite the cache;
    ``prefix_flops_saved`` adds to the monotone gauge of estimated
    prefix FLOPs the cache avoided (utils.flops.prefix_flops_estimate).
    No-op without a session."""
    s = _session
    if s is None:
        return
    if hits:
        s.metrics.counter(
            "attrib_capture_hits_total",
            "scoring passes whose eval-site activation came from the "
            "one-pass capture cache").inc(hits)
    if misses:
        s.metrics.counter(
            "attrib_capture_misses_total",
            "scoring passes that recomputed the prefix forward despite "
            "an installed capture cache").inc(misses)
    if prefix_flops_saved:
        g = s.metrics.gauge(
            "prefix_flops_saved",
            "estimated prefix forward FLOPs avoided by capture reuse "
            "(monotone within a session)")
        g.set((g.value or 0.0) + prefix_flops_saved)


def capture_counts() -> Dict[str, float]:
    """Current capture-cache totals (zeros without a session) — what the
    bench sweep leg surfaces next to its wall/compile accounting."""
    s = _session
    if s is None:
        return {"capture_hits": 0, "capture_misses": 0,
                "prefix_flops_saved": 0.0}

    def val(name):
        m = s.metrics.get(name)
        return m.value if m is not None and m.value is not None else 0

    return {
        "capture_hits": int(val("attrib_capture_hits_total")),
        "capture_misses": int(val("attrib_capture_misses_total")),
        "prefix_flops_saved": float(val("prefix_flops_saved")),
    }


def inc(name: str, n: float = 1, help: str = "") -> None:
    """Bump a named counter (no-op without a session) — the generic
    instrumentation hook subsystems like ``resilience`` use for their
    ``*_total`` counters without each holding a registry reference."""
    s = _session
    if s is not None:
        s.metrics.counter(name, help).inc(n)


def observe(name: str, value: float, help: str = "") -> None:
    """Record one observation into a named histogram (no-op without a
    session) — e.g. ``checkpoint_write_seconds``."""
    s = _session
    if s is not None:
        s.metrics.histogram(name, help).observe(value)


def gauge_set(name: str, value: float, help: str = "") -> None:
    s = _session
    if s is not None:
        s.metrics.gauge(name, help).set(value)


def counter_value(name: str) -> float:
    """Current value of a named counter/gauge (0 without a session or
    before the first bump) — lets tests and smoke scripts assert on
    recovery counters without walking the registry."""
    s = _session
    if s is None:
        return 0.0
    v = getattr(s.metrics.get(name), "value", None)
    return float(v) if v is not None else 0.0


def configure_step_flops(flops_per_step: Optional[float] = None,
                         peak_flops: Optional[float] = None):
    """Give the step telemetry its MFU denominators (training FLOPs per
    step and the chip's spec-sheet peak).  When ``peak_flops`` is omitted,
    the first local device's bf16 peak is looked up (None off-TPU —
    MFU then stays unreported rather than guessed)."""
    s = _session
    if s is None:
        return
    if peak_flops is None:
        try:
            import jax

            from torchpruner_tpu.utils.flops import peak_bf16_flops

            peak_flops = peak_bf16_flops(jax.local_devices()[0])
        except Exception:
            peak_flops = None
    s.step.configure(flops_per_step=flops_per_step, peak_flops=peak_flops)

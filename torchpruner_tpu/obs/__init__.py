"""Unified runtime telemetry: span tracing, step metrics, compile
accounting, exporters.

One :class:`ObsSession` per process, installed with :func:`configure` and
torn down with :func:`shutdown`.  Instrumented code talks to the module
functions — :func:`span`, :func:`record_step`, :func:`current_span_id` —
which are no-ops (one global load + ``None`` check) when no session is
active, so libraries can instrument unconditionally and pay nothing
unless a driver turned telemetry on.

Typical driver::

    from torchpruner_tpu import obs

    obs.configure(obs_dir="logs/obs")        # or obs_dir=None: summary only
    with obs.span("run", experiment=cfg.name):
        ...                                   # phases open nested spans
    print(obs.shutdown(), file=sys.stderr)    # summary table; writes
                                              # events.jsonl + metrics.prom

Multi-host: only ``process_index == 0`` emits the event stream, the
ledger, and the merged exports — but every process with an ``obs_dir``
writes its OWN metric shard (``metrics.shard<i>.json``) at close, and
process 0 merges the shards (sum counters, max/min gauges, merge
histograms — ``obs.aggregate``) before exporting ``metrics.prom`` and
``report.json``, so non-zero processes' counters no longer vanish.
The index is read lazily from ``jax.process_index()`` on first emission
and can be overridden for tests via ``configure(process_index=...)``.

On top of the runtime telemetry, the session keeps a **run ledger**
(``obs.ledger.ProvenanceRecorder`` → ``ledger.jsonl``): per-round prune
decisions with score distributions and eval/params/FLOPs trajectories,
written by ``core.pruner`` / ``prune_retrain`` / the robustness sweep
through the ``record_*`` module functions below.  At close the session
bundles ledger + derived metrics + phase summary into ``report.json``
and exports the span stream as a Perfetto/Chrome ``trace.json`` —
consumed by ``python -m torchpruner_tpu obs report/diff`` (obs.report).

Design refs: JaxPruner's cheap per-step instrumentation argument
(arXiv:2304.14082) and the TPU structured-pruning study's MFU/step-time
reporting (arXiv:2107.04191) — see PAPERS.md.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, Optional

from torchpruner_tpu.obs.compile_watch import CompileWatcher
from torchpruner_tpu.obs.exporters import (
    JsonlWriter,
    prometheus_text,
    summary_table,
    write_prometheus,
)
from torchpruner_tpu.obs.metrics import (
    MetricsRegistry,
    StepTelemetry,
    record_device_memory,
    train_flops_per_step,
)
from torchpruner_tpu.obs.ledger import ProvenanceRecorder, score_distribution
from torchpruner_tpu.obs.spans import SpanRecord, SpanTracer

__all__ = [
    "ObsSession", "configure", "get", "shutdown", "span",
    "current_span_id", "record_step", "record_grad_norm",
    "configure_step_flops", "record_capture", "capture_counts",
    "inc", "observe", "gauge_set", "counter_value", "emit_event",
    "request_profile_window", "profile_tick", "profile_step",
    "timeseries_tick",
    "record_scores", "record_prune", "record_round", "record_epoch",
    "record_sweep_layer", "record_serve", "record_reqtrace",
    "ledger_backfill", "active_incident_id",
    "annotate_run", "set_trial", "record_trial", "record_frontier",
    "MetricsRegistry", "StepTelemetry",
    "SpanTracer", "SpanRecord", "train_flops_per_step",
    "ProvenanceRecorder", "score_distribution",
    "prometheus_text", "summary_table",
]

EVENTS_FILENAME = "events.jsonl"
PROM_FILENAME = "metrics.prom"
PROFILE_DIRNAME = "profile"
PROFILE_FILENAME = "profile.json"

#: env override for event-stream rotation (bytes; 0 = off).  Kept as an
#: env rather than a config field so long-running drivers can cap the
#: stream without a code change.
ROTATE_ENV = "TORCHPRUNER_OBS_ROTATE_BYTES"

#: env defaults for the continuous profiler (capture a window every N
#: recorded steps / steps per window) — the knobs also exposed as
#: ``configure(profile_every=..., profile_steps=...)`` and the CLI's
#: ``--profile-every`` / ``--profile-steps``.
PROFILE_EVERY_ENV = "TORCHPRUNER_PROFILE_EVERY"
PROFILE_STEPS_ENV = "TORCHPRUNER_PROFILE_STEPS"

#: env defaults for the windowed time-series recorder (obs.timeseries):
#: window cadence in seconds (0 disables) and rotation cap in bytes —
#: also exposed as ``configure(ts_interval_s=...)``.
TS_INTERVAL_ENV = "TORCHPRUNER_TS_INTERVAL_S"

_session: Optional["ObsSession"] = None


def _env_int(name: str, default: int = 0) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float = 0.0) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class ObsSession:
    """The wiring: tracer + registry + step telemetry + compile watcher
    + (optional) file exporters rooted at ``obs_dir``."""

    def __init__(self, obs_dir: Optional[str] = None,
                 process_index: Optional[int] = None,
                 annotate: bool = True, watch_compiles: bool = True,
                 rotate_bytes: Optional[int] = None,
                 profile_every: Optional[int] = None,
                 profile_steps: Optional[int] = None,
                 ts_interval_s: Optional[float] = None):
        self.obs_dir = obs_dir
        self._process_index = process_index
        self._closed = False
        self.t_start = time.perf_counter()
        self.metrics = MetricsRegistry()
        self.run_meta: Dict[str, Any] = {}
        self.events: Optional[JsonlWriter] = None
        self.ledger: Optional[ProvenanceRecorder] = None
        self.timeseries = None
        self.anomaly = None
        self.incidents = None
        self.profiler = None
        self.hbm = None
        self.profile: Optional[Dict[str, Any]] = None
        self.param_bytes: Optional[float] = None
        if rotate_bytes is None:
            rotate_bytes = _env_int(ROTATE_ENV, 0)
        if profile_every is None:
            profile_every = _env_int(PROFILE_EVERY_ENV, 0)
        if profile_steps is None:
            profile_steps = _env_int(PROFILE_STEPS_ENV, 0) or 3
        if obs_dir and self.is_emitter:
            # a NEW session invalidates any previous session's metric
            # shards (they are written at close; anything on disk now is
            # a dead run's — merging it would double-count)
            from torchpruner_tpu.obs.aggregate import clear_stale_shards

            try:
                clear_stale_shards(obs_dir)
            except Exception:
                pass
            self.events = JsonlWriter(os.path.join(obs_dir, EVENTS_FILENAME),
                                      rotate_bytes=rotate_bytes)
            self.ledger = ProvenanceRecorder(obs_dir)
            # windowed time-series: delta snapshots of this registry on
            # an interval cadence (obs.timeseries; 0 disables)
            if ts_interval_s is None:
                ts_interval_s = _env_float(TS_INTERVAL_ENV, 1.0)
            if ts_interval_s and ts_interval_s > 0:
                from torchpruner_tpu.obs.timeseries import (
                    DEFAULT_ROTATE_BYTES,
                    TS_ROTATE_ENV,
                    TimeseriesRecorder,
                )

                try:
                    self.timeseries = TimeseriesRecorder(
                        self.metrics, obs_dir, interval_s=ts_interval_s,
                        rotate_bytes=_env_int(TS_ROTATE_ENV,
                                              DEFAULT_ROTATE_BYTES))
                except Exception:
                    self.timeseries = None
            # anomaly detection + incident correlation (obs.anomaly /
            # obs.incident): the detector rides the recorder's
            # per-window hook; any burn alert (record_serve) or anomaly
            # open routes to the correlator, which assembles a ledgered
            # incident from this session's evidence
            try:
                from torchpruner_tpu.obs.anomaly import AnomalyDetector
                from torchpruner_tpu.obs.incident import (
                    IncidentCorrelator,
                )

                self.incidents = IncidentCorrelator(
                    ledger=self.ledger, registry=self.metrics)
                if self.timeseries is not None:
                    self.anomaly = AnomalyDetector(
                        on_open=self._on_anomaly_open,
                        on_close=self._on_anomaly_close)
                    self.incidents.detector = self.anomaly
                    self.timeseries.on_window = \
                        self.anomaly.observe_window
            except Exception:
                self.anomaly = None
                self.incidents = None
        self.tracer = SpanTracer(sink=self.events, annotate=annotate)
        if obs_dir and self.is_emitter:
            # continuous profiling: the profiler exists whenever the
            # session has a dir (on-demand windows via
            # request_profile_window / the serve endpoint need it even
            # at cadence 0); the HBM sampler rides the span stream
            from torchpruner_tpu.obs.profile import (
                ContinuousProfiler,
                HbmSampler,
            )

            self.profiler = ContinuousProfiler(
                os.path.join(obs_dir, PROFILE_DIRNAME),
                every_steps=profile_every, window_steps=profile_steps,
                emit=self.events, tracer=self.tracer)
            # samples stay in memory (-> profile.json's hbm timeline);
            # the span stream keeps its span_begin/span_end-only schema
            self.hbm = HbmSampler(emit=None)
            self.tracer.extra_sink = self.hbm.on_event
        self.step = StepTelemetry(self.metrics)
        self.compiles = CompileWatcher(self.metrics, self.tracer)
        if watch_compiles:
            self.compiles.start()
        if self.events is not None:
            self.events({
                "event": "obs_init", "ts": time.time(), "pid": os.getpid(),
                "process_index": self.process_index,
            })

    def _on_anomaly_open(self, rec: Dict[str, Any]) -> None:
        """Detector callback (invoked outside its lock): ledger the
        anomaly and let it trigger an incident."""
        if self.ledger is not None:
            try:
                self.ledger.record(dict(rec))
            except Exception:
                pass
        if self.incidents is not None:
            try:
                self.incidents.trigger(
                    kind="anomaly", ts=rec.get("opened_ts"),
                    metric=rec.get("metric"),
                    anomaly_id=rec.get("anomaly_id"), z=rec.get("z"))
            except Exception:
                pass

    def _on_anomaly_close(self, rec: Dict[str, Any]) -> None:
        if self.ledger is not None:
            try:
                self.ledger.record(dict(rec))
            except Exception:
                pass

    def clear_stale_profile(self) -> None:
        """Invalidate a previous run's capture windows in a reused obs
        dir (the same new-session semantics the metric shards get) —
        called by :func:`configure` AFTER the old session closed, never
        from the constructor: windows live on disk DURING a run, so a
        wipe-before-close would destroy the outgoing session's evidence
        right before its ``_finalize_profile`` parses it."""
        if self.profiler is None or self.profiler.windows \
                or self.profiler.active:
            return
        import shutil

        try:
            shutil.rmtree(os.path.join(self.obs_dir, PROFILE_DIRNAME),
                          ignore_errors=True)
            path = os.path.join(self.obs_dir, PROFILE_FILENAME)
            if os.path.exists(path):
                os.remove(path)
        except OSError:
            pass

    # -- multi-host gate ---------------------------------------------------

    @property
    def process_index(self) -> int:
        if self._process_index is None:
            try:
                import jax

                self._process_index = jax.process_index()
            except Exception:
                self._process_index = 0
        return self._process_index

    @property
    def is_emitter(self) -> bool:
        """True on the one process allowed to write files."""
        return self.process_index == 0

    # -- summaries / teardown ---------------------------------------------

    def derived(self) -> Dict[str, Optional[float]]:
        return self.step.derive()

    def summary(self) -> str:
        return summary_table(
            self.tracer.phase_summary(), self.derived(),
            self.compiles.counts(),
            total_wall_s=time.perf_counter() - self.t_start,
        )

    def close(self) -> str:
        """Stop listeners, flush files, return the terminal summary.
        Idempotent: a second close reports again but never re-touches the
        (already closed) event file."""
        self.compiles.stop()
        already_closed, self._closed = self._closed, True
        if not already_closed:
            if self is _session:
                # pending slowest-K request-trace exemplars flush into
                # the event stream before it closes (obs.reqtrace)
                try:
                    from torchpruner_tpu.obs import reqtrace

                    reqtrace.session_flush()
                except Exception:
                    pass
            self._finalize_profile()      # kernel gauges BEFORE export
            if self.incidents is not None:
                # incident/anomaly count gauges BEFORE the final window
                # and shard ship — they must ride the merge into
                # report.json, `obs diff`, and the CI gates (set even
                # when 0, so the clean-run false-positive gate compares
                # a real number, not an absent metric)
                try:
                    self.incidents.finalize(self.metrics)
                except Exception:
                    pass
            if self.timeseries is not None:
                # final forced window + ts_* gauges, BEFORE the shard
                # ships (the gauges must ride the merge into report.json
                # and `obs diff`)
                try:
                    self.timeseries.close()
                except Exception:
                    pass
        derived = self.derived()          # writes derived gauges
        record_device_memory(self.metrics)
        text = summary_table(
            self.tracer.phase_summary(), derived, self.compiles.counts(),
            total_wall_s=time.perf_counter() - self.t_start,
        )
        if self.events is not None and not already_closed:
            self.events({
                "event": "run_summary", "ts": time.time(),
                "wall_s": round(time.perf_counter() - self.t_start, 6),
                "phases": self.tracer.phase_summary(),
                "derived": derived,
                "compiles": self.compiles.counts(),
                "metrics": self.metrics.snapshot(),
            })
            self.events.close()
        if self.obs_dir:
            # EVERY process ships its metric shard; process 0 then merges
            # whatever shards are present into the exported registry —
            # the cross-host aggregation path (obs.aggregate)
            from torchpruner_tpu.obs import aggregate

            try:
                aggregate.write_shard(self.metrics, self.obs_dir,
                                      self.process_index)
            except Exception:
                pass
        if self.obs_dir and self.is_emitter:
            try:
                # every process reaches shutdown at the same program
                # point, but their shard writes race the merge — give
                # the peers a bounded window to land theirs (no-op
                # single-host; tunable for slow shared filesystems)
                aggregate.wait_for_peer_shards(
                    self.obs_dir, self.process_index)
            except Exception:
                pass
            try:
                merged = aggregate.merged_registry(
                    self.obs_dir, local=self.metrics,
                    process_index=self.process_index)
            except Exception:
                merged = self.metrics
            try:
                write_prometheus(
                    merged, os.path.join(self.obs_dir, PROM_FILENAME))
            except Exception:
                pass
            if not already_closed:
                self._export_artifacts(merged, derived)
        if self.ledger is not None and not already_closed:
            self.ledger.close()
        return text

    def _finalize_profile(self) -> None:
        """Close any open capture window, parse the windows into the
        ranked kernel table, install the ``kernel_*`` gate gauges, and
        write ``profile.json`` — all best-effort, all BEFORE the metric
        shard ships (the gauges must ride the merge into report.json)."""
        if self.profiler is None:
            return
        try:
            from torchpruner_tpu.obs.profile import (
                build_profile,
                kernel_gauges,
            )

            windows = self.profiler.close()
            if not windows and self.hbm is not None \
                    and not self.hbm.timeline:
                return
            peak_flops = peak_bw = None
            try:
                import jax

                from torchpruner_tpu.utils import flops as _flops

                dev = jax.local_devices()[0]
                peak_flops = self.step.peak_flops \
                    or _flops.peak_bf16_flops(dev)
                peak_bw = _flops.peak_hbm_bw(dev)
            except Exception:
                peak_flops = self.step.peak_flops
            self.profile = build_profile(
                windows,
                flops_per_step=self.step.flops_per_step,
                param_bytes=self.param_bytes,
                peak_flops=peak_flops, peak_bw=peak_bw,
                hbm=(self.hbm.summary() if self.hbm is not None
                     else None),
                telemetry_step_s=self.step.derive().get(
                    "step_time_p50_s"))
            kernel_gauges(self.profile, self.metrics)
            from torchpruner_tpu.obs.ledger import sanitize
            from torchpruner_tpu.resilience.manifest import (
                atomic_write_json,
            )

            atomic_write_json(
                os.path.join(self.obs_dir, PROFILE_FILENAME),
                sanitize(self.profile), indent=1)
        except Exception:
            self.profile = self.profile or None

    def _export_artifacts(self, merged, derived) -> None:
        """trace.json (Perfetto) + report.json (ledger bundle) — each
        best-effort; a failing exporter must never fail the run."""
        from torchpruner_tpu.obs import ledger as ledger_mod
        from torchpruner_tpu.obs import trace_export

        try:
            trace_export.write_trace(
                os.path.join(self.obs_dir, EVENTS_FILENAME),
                profile_dir=os.path.join(self.obs_dir, PROFILE_DIRNAME))
        except Exception:
            pass
        try:
            report = ledger_mod.build_report(
                run_meta=self.run_meta,
                records=(self.ledger.records() if self.ledger else []),
                derived=derived,
                phases=self.tracer.phase_summary(),
                compiles=self.compiles.counts(),
                metrics=merged.snapshot(),
                wall_s=round(time.perf_counter() - self.t_start, 6),
                profile=self.profile,
            )
            ledger_mod.write_report(
                report,
                os.path.join(self.obs_dir, ledger_mod.REPORT_FILENAME))
        except Exception:
            pass


# -- module-level convenience (the instrumentation surface) -----------------


def configure(obs_dir: Optional[str] = None, *,
              process_index: Optional[int] = None, annotate: bool = True,
              watch_compiles: bool = True,
              rotate_bytes: Optional[int] = None,
              profile_every: Optional[int] = None,
              profile_steps: Optional[int] = None,
              ts_interval_s: Optional[float] = None) -> ObsSession:
    """Install the process-wide session (replacing any previous one).
    The new session is constructed BEFORE the old one is torn down, so a
    failing constructor (e.g. unwritable ``obs_dir``) leaves the previous
    session installed and intact.  ``rotate_bytes`` caps the event
    stream (size-based rotation to ``events.jsonl.1`` …; default off,
    env ``TORCHPRUNER_OBS_ROTATE_BYTES``).  ``profile_every`` opens a
    ``profile_steps``-step ``jax.profiler`` capture window every N
    recorded steps (0/None = on-demand only; envs
    ``TORCHPRUNER_PROFILE_EVERY`` / ``TORCHPRUNER_PROFILE_STEPS``) —
    see ``obs.profile``.  ``ts_interval_s`` sets the windowed
    time-series cadence (obs.timeseries; default 1 s, env
    ``TORCHPRUNER_TS_INTERVAL_S``, 0 disables)."""
    global _session
    new = ObsSession(obs_dir, process_index=process_index,
                     annotate=annotate, watch_compiles=watch_compiles,
                     rotate_bytes=rotate_bytes,
                     profile_every=profile_every,
                     profile_steps=profile_steps,
                     ts_interval_s=ts_interval_s)
    if _session is not None:
        _session.close()
    # only after the old session exported its own windows/profile.json
    new.clear_stale_profile()
    _session = new
    return new


def get() -> Optional[ObsSession]:
    return _session


def shutdown(print_to=None) -> str:
    """Tear down the active session; returns (and optionally prints) the
    end-of-run summary table.  No-op empty string without a session."""
    global _session
    if _session is None:
        return ""
    text = _session.close()
    _session = None
    if print_to is not None:
        print(text, file=print_to, flush=True)
    return text


def span(name: str, **meta):
    """Open a named phase span (no-op context manager when telemetry is
    off).  Usable as ``with obs.span("retrain", target=t):``."""
    s = _session
    if s is None:
        return contextlib.nullcontext()
    return s.tracer.span(name, **meta)


def current_span_id() -> Optional[str]:
    s = _session
    return s.tracer.current_id() if s is not None else None


def record_step(dt_s: float, examples: int, tokens: Optional[int] = None,
                steps: int = 1):
    """Per-train-step hot path — microseconds; see StepTelemetry."""
    s = _session
    if s is not None:
        s.step.on_step(dt_s, examples, tokens, steps)
        if s.profiler is not None:
            # capture-window state machine: one increment + compare when
            # no window is open or armed (obs.profile.capture)
            s.profiler.on_step(dt_s)
        if s.timeseries is not None:
            # one clock read + compare off-cadence (obs.timeseries)
            s.timeseries.maybe_tick()


def request_profile_window() -> bool:
    """Arm one on-demand profiler capture window (the serve frontend's
    ``POST /profile``, manual driver hooks); it opens at the next step
    boundary.  False without a session/profiler or when a window is
    already open/armed."""
    s = _session
    if s is None or s.profiler is None:
        return False
    return s.profiler.request_window()


def profile_tick() -> None:
    """A non-step loop boundary for the profiler (an idle serving
    engine's loop) — lets on-demand windows open and stale windows
    close when no training steps are flowing.  No-op otherwise."""
    s = _session
    if s is not None and s.profiler is not None:
        s.profiler.tick()


def profile_step(dt_s: float = 0.0) -> None:
    """Drive the profiler's capture cadence from a non-training step
    (a serving engine's decode step) WITHOUT recording it into the
    train step telemetry.  No-op without a session/profiler."""
    s = _session
    if s is not None and s.profiler is not None:
        s.profiler.on_step(dt_s)


def timeseries_tick() -> None:
    """A loop-boundary hook for the windowed time-series recorder —
    the serving engine's run loop and the fleet router's tick call it
    so windows keep flowing when no ``record_step`` is (obs.timeseries;
    one clock read + compare off-cadence).  No-op without a session."""
    s = _session
    if s is not None and s.timeseries is not None:
        s.timeseries.maybe_tick()


def record_grad_norm(gnorm) -> None:
    s = _session
    if s is not None:
        s.step.on_grad_norm(float(gnorm))


def record_capture(hits: int = 0, misses: int = 0,
                   prefix_flops_saved: float = 0.0) -> None:
    """Attribution capture-cache accounting (one-pass sweep engine,
    attributions.base.ActivationCache).  ``hits``/``misses`` count
    SCORING PASSES (one metric run or ablation walk) whose prefix
    forward was read from / recomputed despite the cache;
    ``prefix_flops_saved`` adds to the monotone gauge of estimated
    prefix FLOPs the cache avoided (utils.flops.prefix_flops_estimate).
    No-op without a session."""
    s = _session
    if s is None:
        return
    if hits:
        s.metrics.counter(
            "attrib_capture_hits_total",
            "scoring passes whose eval-site activation came from the "
            "one-pass capture cache").inc(hits)
    if misses:
        s.metrics.counter(
            "attrib_capture_misses_total",
            "scoring passes that recomputed the prefix forward despite "
            "an installed capture cache").inc(misses)
    if prefix_flops_saved:
        g = s.metrics.gauge(
            "prefix_flops_saved",
            "estimated prefix forward FLOPs avoided by capture reuse "
            "(monotone within a session)")
        g.set((g.value or 0.0) + prefix_flops_saved)


def capture_counts() -> Dict[str, float]:
    """Current capture-cache totals (zeros without a session) — what the
    bench sweep leg surfaces next to its wall/compile accounting."""
    s = _session
    if s is None:
        return {"capture_hits": 0, "capture_misses": 0,
                "prefix_flops_saved": 0.0}

    def val(name):
        m = s.metrics.get(name)
        return m.value if m is not None and m.value is not None else 0

    return {
        "capture_hits": int(val("attrib_capture_hits_total")),
        "capture_misses": int(val("attrib_capture_misses_total")),
        "prefix_flops_saved": float(val("prefix_flops_saved")),
    }


def inc(name: str, n: float = 1, help: str = "") -> None:
    """Bump a named counter (no-op without a session) — the generic
    instrumentation hook subsystems like ``resilience`` use for their
    ``*_total`` counters without each holding a registry reference."""
    s = _session
    if s is not None:
        s.metrics.counter(name, help).inc(n)


def observe(name: str, value: float, help: str = "") -> None:
    """Record one observation into a named histogram (no-op without a
    session) — e.g. ``checkpoint_write_seconds``."""
    s = _session
    if s is not None:
        s.metrics.histogram(name, help).observe(value)


def gauge_set(name: str, value: float, help: str = "") -> None:
    s = _session
    if s is not None:
        s.metrics.gauge(name, help).set(value)


def emit_event(event: dict) -> None:
    """Append one raw event to the session's ``events.jsonl`` stream
    (no-op without a session or a file-backed emitter) — the hook the
    request tracer (``obs.reqtrace``) and the fleet router's
    clock-offset probe use to ride the span stream's file without being
    spans."""
    s = _session
    if s is not None and s.events is not None:
        try:
            s.events(event)
        except Exception:
            pass


def counter_value(name: str) -> float:
    """Current value of a named counter/gauge (0 without a session or
    before the first bump) — lets tests and smoke scripts assert on
    recovery counters without walking the registry."""
    s = _session
    if s is None:
        return 0.0
    v = getattr(s.metrics.get(name), "value", None)
    return float(v) if v is not None else 0.0


# -- run ledger (provenance) -------------------------------------------------
# All no-ops without a session or without an obs_dir (the ledger lives on
# disk; in-memory-only sessions have no recorder).  Emitter-gated like the
# event stream: in SPMD every process reaches the same decisions, so one
# ledger per run is the truth, not a shard.


def annotate_run(**meta) -> None:
    """Attach run-level metadata (experiment name, preset, config hash)
    to the session — lands in ``report.json``'s ``run`` block."""
    s = _session
    if s is not None:
        s.run_meta.update(meta)


def record_scores(site: str, scores, *, method: str = "", run: int = 0,
                  layer: str = "") -> None:
    """Ledger a per-site attribution score distribution (compact
    percentiles, not raw scores).  Skipped for non-1-D score arrays
    (``reduction='none'`` row matrices have no single distribution)."""
    s = _session
    if s is None or s.ledger is None:
        return
    import numpy as _np

    if _np.ndim(scores) != 1:
        return
    s.ledger.record_scores(site, scores, method=method, run=run,
                           layer=layer)


def record_prune(target: str, drop, n_units: int, *,
                 simulate: bool = False) -> None:
    """Ledger the concrete prune decision (site + dropped rows)."""
    s = _session
    if s is not None and s.ledger is not None:
        s.ledger.record_prune(target, drop, n_units, simulate=simulate)


def record_round(*, target: str, **fields) -> None:
    """Ledger one prune round's headline record (decision + score
    distribution + pre/post eval + cost).  Resume-safe: deduped on
    ``target``."""
    s = _session
    if s is not None and s.ledger is not None:
        s.ledger.record_round(target=target, **fields)


def record_epoch(**fields) -> None:
    s = _session
    if s is not None and s.ledger is not None and "epoch" in fields:
        s.ledger.record_epoch(**fields)


def record_sweep_layer(*, layer: str, **fields) -> None:
    s = _session
    if s is not None and s.ledger is not None:
        s.ledger.record_sweep_layer(layer=layer, **fields)


def record_serve(*, kind: str, **fields) -> None:
    """Ledger one serving-engine event (``kind`` = "summary" |
    "hot_swap" | ...): ties served traffic back to the checkpoint's
    prune provenance (digests, widths) next to the run's latency
    metrics.  Informational records — never deduped."""
    s = _session
    if s is not None and s.ledger is not None:
        s.ledger.record({"event": "serve", "kind": kind, **fields})
        if kind == "slo_burn" and s.incidents is not None:
            # burn alerts open incidents wherever --obs-dir is set —
            # serve frontends ledger burns directly, the fleet's
            # _collect_burn_alerts re-records replica burns (carrying
            # the original timestamp), so both planes correlate through
            # this one hook (obs.incident)
            try:
                s.incidents.trigger(
                    kind="slo_burn",
                    ts=fields.get("burn_ts") or fields.get("ts"),
                    metric=fields.get("metric"),
                    replica=fields.get("replica"),
                    burn_fast=fields.get("burn_fast"),
                    burn_slow=fields.get("burn_slow"))
            except Exception:
                pass


def active_incident_id() -> Optional[str]:
    """The correlation id in effect right now — the incident still
    inside its lookback horizon, else the oldest open anomaly, else
    ``None``.  The supervisor stamps this onto every ``scale_decision``
    record so postmortems link decision→signal without timestamp
    guessing.  No-op ``None`` without a session/correlator."""
    s = _session
    if s is None or s.incidents is None:
        return None
    try:
        return s.incidents.active_id()
    except Exception:
        return None


def record_reqtrace(**fields) -> None:
    """Ledger one request-trace analysis record (the fleet drill's
    latency budget + slowest-K exemplar waterfalls + assembly counts)
    — rendered by ``obs report``'s latency-budget section.
    Informational — never deduped."""
    s = _session
    if s is not None and s.ledger is not None:
        s.ledger.record({"event": "reqtrace", **fields})


def set_trial(trial_id: Optional[str],
              campaign_id: Optional[str] = None) -> None:
    """Stamp every subsequent ledger record with a campaign trial
    identity (``trial_id``/``campaign_id`` — ``None`` clears).  The
    search driver's satellite: records from concurrent trials pointed
    at one shared obs dir stay dedup-keyed and groupable PER TRIAL
    (``obs report`` renders a trial column; ``obs diff`` matches rounds
    per trial).  No-op without a session/ledger."""
    s = _session
    if s is not None and s.ledger is not None:
        s.ledger.set_context(trial_id=trial_id, campaign_id=campaign_id)


def record_trial(*, trial_id: str, status: str, **fields) -> None:
    """Ledger one campaign-trial status transition (``status`` =
    "excluded" | "done" | "early_stopped" | "failed") — the per-trial
    provenance trail the search driver leaves next to its frontier
    record.  Deduped per (trial, status)."""
    s = _session
    if s is not None and s.ledger is not None:
        s.ledger.record({"event": "trial", "trial_id": trial_id,
                         "status": status, **fields})


def record_frontier(**fields) -> None:
    """Ledger one campaign frontier summary (search/frontier.py): the
    non-dominated point set with provenance digests, dominated/early-
    stopped/excluded counts, and the FLOPs-bucket best accuracies —
    rendered by ``obs report``'s frontier section.  Informational —
    never deduped."""
    s = _session
    if s is not None and s.ledger is not None:
        s.ledger.record({"event": "frontier", **fields})


def record_plan(**fields) -> None:
    """Ledger one auto-parallelism planner run (analysis/planner.py):
    the winner/baseline labels, candidate/feasible counts, predicted
    margins, and the winner's probe drift — the provenance behind a
    config the planner chose, rendered by ``obs report``'s plan
    section.  Informational records — never deduped."""
    s = _session
    if s is not None and s.ledger is not None:
        s.ledger.record({"event": "plan", **fields})


def ledger_backfill(records, kind: str = "round") -> int:
    """Rehydrate ledger records from a RunManifest history on resume
    (``kind`` = "round" | "epoch") — keeps the ledger continuous when a
    resumed run points at a fresh obs dir.  Returns records written."""
    s = _session
    if s is None or s.ledger is None:
        return 0
    if kind == "epoch":
        return s.ledger.backfill_epochs(records)
    return s.ledger.backfill_rounds(records)


def runtime_snapshot() -> Dict[str, Any]:
    """The cost snapshot a round record embeds: steps/step-time/MFU so
    far, compile totals, and the HBM high-water gauge — cheap reads of
    already-accumulated state (no device sync)."""
    s = _session
    if s is None:
        return {}
    record_device_memory(s.metrics)
    d = s.step.derive()
    c = s.compiles.counts()
    hbm = [m.value for m in s.metrics
           if getattr(m, "name", "").startswith("hbm_bytes_in_use")
           and getattr(m, "value", None) is not None]
    return {
        "steps": d.get("steps"),
        "step_time_mean_s": d.get("step_time_mean_s"),
        "mfu": d.get("mfu"),
        "compile_s": c.get("compile_s"),
        "compile_count": c.get("compile_count"),
        "hbm_bytes_max": (max(hbm) if hbm else None),
    }


def configure_step_flops(flops_per_step: Optional[float] = None,
                         peak_flops: Optional[float] = None,
                         param_bytes: Optional[float] = None):
    """Give the step telemetry its MFU denominators (training FLOPs per
    step and the chip's spec-sheet peak).  When ``peak_flops`` is omitted,
    the first local device's bf16 peak is looked up (None off-TPU —
    MFU then stays unreported rather than guessed).  ``param_bytes``
    (live parameter bytes) feeds the profile subsystem's per-kernel
    weight-traffic byte estimates (obs.profile.kernels)."""
    s = _session
    if s is None:
        return
    if param_bytes is not None:
        s.param_bytes = float(param_bytes)
    if peak_flops is None:
        try:
            import jax

            from torchpruner_tpu.utils.flops import peak_bf16_flops

            peak_flops = peak_bf16_flops(jax.local_devices()[0])
        except Exception:
            peak_flops = None
    s.step.configure(flops_per_step=flops_per_step, peak_flops=peak_flops)
